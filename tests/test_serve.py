"""Interactive inference server (bin/serve.py) — the webcam-demo analog.

Covers the reference's Pluto demo behaviors (bin/pluto.jl): serve the
capture page (:133-334), classify a posted frame, return top-k labels
with probabilities (:338-382).
"""

from __future__ import annotations

import io
import json
import pathlib
import sys
import threading
import urllib.request

import numpy as np
import pytest

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1] / "bin"))

import serve  # noqa: E402


@pytest.fixture(scope="module")
def server():
    args = serve.build_parser().parse_args(
        ["--model", "resnet18", "--num-classes", "10", "--topk", "3",
         "--port", "0"]
    )
    predict = serve.make_app(args)
    srv = serve.serve(args, predict)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        yield f"http://127.0.0.1:{srv.server_address[1]}"
    finally:
        srv.shutdown()
        t.join(timeout=5)


def _jpeg_bytes() -> bytes:
    from PIL import Image

    rng = np.random.default_rng(0)
    arr = rng.integers(0, 255, (240, 320, 3), dtype=np.uint8)
    buf = io.BytesIO()
    Image.fromarray(arr).save(buf, format="JPEG")
    return buf.getvalue()


def test_index_page(server):
    with urllib.request.urlopen(f"{server}/") as r:
        body = r.read().decode()
    assert "getUserMedia" in body and "/predict" in body


def test_predict_roundtrip(server):
    req = urllib.request.Request(f"{server}/predict", data=_jpeg_bytes(), method="POST")
    with urllib.request.urlopen(req) as r:
        data = json.loads(r.read())
    preds = data["predictions"]
    assert len(preds) == 3
    assert all(0.0 <= p["prob"] <= 1.0 for p in preds)
    assert data["ms"] > 0


def test_predict_bad_payload(server):
    req = urllib.request.Request(f"{server}/predict", data=b"not a jpeg", method="POST")
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req)
    assert ei.value.code == 400
    assert "error" in json.loads(ei.value.read())


def test_unknown_path_404(server):
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(f"{server}/nope")
    assert ei.value.code == 404


def test_predict_concurrent_load(server):
    """ThreadingHTTPServer + jitted forward under concurrent clients: all
    requests succeed, identical frames classify identically (the compiled
    call is thread-safe), and distinct frames interleaved across threads
    do not cross-contaminate responses."""
    import concurrent.futures

    frames = {}
    rng = np.random.default_rng(7)
    from PIL import Image

    for key in range(3):
        arr = rng.integers(0, 255, (240, 320, 3), dtype=np.uint8)
        buf = io.BytesIO()
        Image.fromarray(arr).save(buf, format="JPEG")
        frames[key] = buf.getvalue()

    def post(key):
        req = urllib.request.Request(
            f"{server}/predict", data=frames[key], method="POST"
        )
        with urllib.request.urlopen(req, timeout=60) as r:
            data = json.loads(r.read())
        return key, [(p["label"], round(p["prob"], 5)) for p in data["predictions"]]

    jobs = [k for k in frames for _ in range(8)]  # 24 requests, 3 frames
    with concurrent.futures.ThreadPoolExecutor(max_workers=6) as ex:
        results = list(ex.map(post, jobs))

    by_frame = {}
    for key, preds in results:
        by_frame.setdefault(key, []).append(preds)
    assert sum(len(v) for v in by_frame.values()) == len(jobs)
    for key, preds_list in by_frame.items():
        assert all(p == preds_list[0] for p in preds_list), (
            f"frame {key}: concurrent responses diverged"
        )
