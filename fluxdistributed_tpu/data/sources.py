"""Bytes sources — local, HTTP, and GCS dataset backends with caching.

The reference's ``Data.toml`` registers datasets on two storage drivers:
a local ``FileSystem`` tree and a remote S3-backed ``JuliaHubDataRepo``
(Data.toml:4-27); DataSets.jl hides the difference behind a BlobTree.
The TPU-native analog (pods read from GCS in practice): a *source*
object mapping dataset-relative paths to bytes, with remote sources
caching fetched files locally so the hot path (native JPEG decode, which
wants real file paths) is always a local read.

* ``FileSource``  — a plain directory tree.
* ``HTTPSource``  — ``http(s)://`` base URL + local cache.
* ``GCSSource``   — ``gs://bucket/prefix`` via the public GCS HTTP
  endpoint (``storage.googleapis.com``) — no cloud SDK dependency; for
  private buckets set ``GCS_OAUTH_TOKEN`` (sent as a Bearer header).

``make_source`` dispatches on the scheme, so every ``path`` in the
dataset registry (data/registry.py) may be a local dir or a remote URL.
"""

from __future__ import annotations

import os
import tempfile
import time
import urllib.error
import urllib.parse
import urllib.request

__all__ = [
    "FileSource", "HTTPSource", "GCSSource", "make_source",
    "fetch_artifact", "fetch_checkpoint",
]


class FileSource:
    """Local directory tree (the reference's FileSystem driver,
    Data.toml:4-12)."""

    is_local = True

    def __init__(self, root: str):
        self.root = root

    @property
    def location(self) -> str:
        """User-facing dataset location (directory or URL)."""
        return self.root

    def local_path(self, rel: str) -> str:
        """Path of ``rel`` on the local filesystem (no copy)."""
        return os.path.join(self.root, rel)

    def open_bytes(self, rel: str) -> bytes:
        with open(self.local_path(rel), "rb") as f:
            return f.read()

    def __repr__(self):
        return f"FileSource({self.root!r})"


class HTTPSource:
    """Remote tree behind a base URL, cached under ``cache_dir``.

    ``local_path`` fetches on first access (atomic rename, so concurrent
    decode threads never see partial files) and serves the cache
    afterwards — the local-cache semantics DataSets.jl gives the
    reference's S3 dataset.
    """

    is_local = False

    def __init__(self, base_url: str, cache_dir: str | None = None, headers=None):
        self.base_url = base_url.rstrip("/")
        # Always namespace the cache by base URL — two datasets sharing a
        # cache_dir must never serve each other's files (identical
        # relative paths like LOC_synset_mapping.txt would collide).
        key = urllib.parse.quote(self.base_url, safe="")
        if cache_dir is None:
            cache_dir = os.environ.get(
                "FDTPU_CACHE", os.path.expanduser("~/.cache/fdtpu")
            )
        self.cache_dir = os.path.join(cache_dir, key)
        self.headers = dict(headers or {})

    def _request_headers(self) -> dict:
        return self.headers

    @property
    def location(self) -> str:
        return self.base_url

    def _url(self, rel: str) -> str:
        return f"{self.base_url}/{urllib.parse.quote(rel)}"

    #: request timeout (s) and transient-status retry schedule — object
    #: storage at pod request rates throws occasional 429/5xx and expects
    #: exponential backoff; a stalled connection must not wedge a decode
    #: worker forever.
    timeout = 30.0
    retry_backoff = (1.0, 2.0, 4.0)

    def open_bytes(self, rel: str) -> bytes:
        last: Exception | None = None
        for i in range(len(self.retry_backoff) + 1):
            req = urllib.request.Request(
                self._url(rel), headers=self._request_headers()
            )
            try:
                with urllib.request.urlopen(req, timeout=self.timeout) as r:
                    return r.read()
            except urllib.error.HTTPError as e:
                if e.code not in (429, 500, 502, 503, 504):
                    raise
                last = e
            except (urllib.error.URLError, TimeoutError, OSError) as e:
                last = e
            if i < len(self.retry_backoff):
                time.sleep(self.retry_backoff[i])
        raise last  # type: ignore[misc]

    def local_path(self, rel: str) -> str:
        dest = os.path.join(self.cache_dir, rel)
        if os.path.exists(dest):
            return dest
        os.makedirs(os.path.dirname(dest) or ".", exist_ok=True)
        data = self.open_bytes(rel)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(dest), suffix=".part")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(data)
            os.replace(tmp, dest)  # atomic: concurrent fetchers race benignly
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise
        return dest

    def __repr__(self):
        return f"{type(self).__name__}({self.base_url!r}, cache={self.cache_dir!r})"


class GCSSource(HTTPSource):
    """``gs://bucket/prefix`` via the public GCS JSON/XML HTTP endpoint."""

    def __init__(self, gs_url: str, cache_dir: str | None = None):
        parsed = urllib.parse.urlparse(gs_url)
        if parsed.scheme != "gs" or not parsed.netloc:
            raise ValueError(f"not a gs:// URL: {gs_url!r}")
        base = f"https://storage.googleapis.com/{parsed.netloc}{parsed.path}"
        super().__init__(base, cache_dir=cache_dir)
        self.gs_url = gs_url

    @property
    def location(self) -> str:
        return self.gs_url

    def _request_headers(self) -> dict:
        # Re-read per request: OAuth tokens expire (~1h), and first-epoch
        # fetch phases on large datasets run far longer than that — a
        # refresher process can rotate GCS_OAUTH_TOKEN mid-run.
        headers = dict(self.headers)
        token = os.environ.get("GCS_OAUTH_TOKEN")
        if token:
            headers["Authorization"] = f"Bearer {token}"
        return headers


def make_source(path_or_url: str, cache_dir: str | None = None):
    """Dispatch a registry ``path`` to the right source by scheme."""
    scheme = urllib.parse.urlparse(str(path_or_url)).scheme
    if scheme == "gs":
        return GCSSource(path_or_url, cache_dir=cache_dir)
    if scheme in ("http", "https"):
        return HTTPSource(path_or_url, cache_dir=cache_dir)
    return FileSource(path_or_url)


def fetch_artifact(path_or_url: str, cache_dir: str | None = None) -> str:
    """Resolve a single-file artifact to a LOCAL path, fetching if remote.

    The inference CLIs' analog of the reference notebook's trained-model
    download (bin/pluto.jl:52-124 fetches a BSON from JuliaHub job
    results): ``--torch-weights``/``--gpt2-weights``/``--synset``/
    ``--checkpoint`` may name an ``http(s)://`` or ``gs://`` object and
    it is pulled through the SAME cached source machinery the dataset
    registry uses (retry/backoff, atomic rename, OAuth for private
    buckets).  Local paths pass through untouched.
    """
    url = str(path_or_url)
    scheme = urllib.parse.urlparse(url).scheme
    if scheme not in ("http", "https", "gs"):
        return url
    base, _, name = url.rstrip("/").rpartition("/")
    if not name:
        raise ValueError(f"cannot split a file name out of {url!r}")
    return make_source(base, cache_dir=cache_dir).local_path(name)


def fetch_checkpoint(path_or_url: str, cache_dir: str | None = None) -> str:
    """Resolve a checkpoint location to a LOCAL directory or file.

    Local paths pass through.  A remote ``.zip`` (the portable way to
    ship an orbax checkpoint DIRECTORY over plain HTTP/GCS) is fetched
    via :func:`fetch_artifact` and unpacked next to its cache entry —
    once; later calls reuse the extracted tree.  Any other remote file
    (e.g. a ``.pt``) is simply fetched.
    """
    url = str(path_or_url)
    if urllib.parse.urlparse(url).scheme not in ("http", "https", "gs"):
        return url
    local = fetch_artifact(url, cache_dir=cache_dir)
    if not local.endswith(".zip"):
        return local
    dest = local[: -len(".zip")] + ".extracted"
    marker = os.path.join(dest, ".complete")
    if not os.path.exists(marker):
        import shutil
        import zipfile

        # concurrency-safe: each fetcher extracts into its OWN temp dir
        # (a shared ".part" path would let one process rmtree another's
        # in-progress extraction), then renames into place; the loser of
        # the rename race discards its copy if the winner completed.
        tmp = tempfile.mkdtemp(
            dir=os.path.dirname(dest) or ".",
            prefix=os.path.basename(dest) + ".",
        )
        with zipfile.ZipFile(local) as zf:
            zf.extractall(tmp)
        open(os.path.join(tmp, ".complete"), "w").close()
        try:
            os.replace(tmp, dest)
        except OSError:
            if os.path.exists(marker):
                shutil.rmtree(tmp)  # another fetcher won; use theirs
            else:
                # dest is a dead partial from a crashed run: clear it
                # and retry once
                shutil.rmtree(dest, ignore_errors=True)
                os.replace(tmp, dest)
    # a zip that wraps everything in one top-level dir unwraps to it —
    # unless that dir looks like a STEP dir ("step_0"/"0"), i.e. the
    # zip holds a checkpoint ROOT with a single saved step, which must
    # stay the root for latest_step() discovery
    import re

    entries = [e for e in os.listdir(dest) if e != ".complete"]
    if (len(entries) == 1
            and not re.fullmatch(r"(step[_-]?)?\d+", entries[0])
            and os.path.isdir(os.path.join(dest, entries[0]))):
        return os.path.join(dest, entries[0])
    return dest
