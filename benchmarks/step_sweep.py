#!/usr/bin/env python
"""Train-step configuration sweep for the ResNet-50 bench.

Measures steady-state img/s for combinations of model/input dtype
variants and XLA flags.  XLA flags bind at backend init, so the parent
re-execs itself (``--one``) with each configuration's environment and
collects one JSON line per child.

Run on the real chip:  python benchmarks/step_sweep.py
Child mode (internal): python benchmarks/step_sweep.py --one
(configuration reaches the child via SWEEP_* environment variables)
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

# bench.py (the shared timing protocol) lives at the repo root
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Ordered by expected leverage: if chip time runs out mid-sweep, the
# rows most likely to move the headline number have already printed.
CONFIGS = [
    {"name": "baseline-bf16", "env": {}},
    # fused multi-step: K optimizer steps per dispatch.  The runtime sits
    # behind a network tunnel (axon) — if throughput jumps with fusion,
    # the gap is host dispatch latency, not on-chip time
    {"name": "fuse-8", "env": {"SWEEP_FUSE": "8"}},
    {"name": "fuse-32", "env": {"SWEEP_FUSE": "32"}},
    # MXU-shaped stem: space_to_depth input + equivalent 4x4/1 conv
    # replaces the 7x7/2-on-3-channels stem pathology (exact re-layout,
    # tests/test_resnet_s2d.py)
    {"name": "s2d-stem", "env": {"SWEEP_S2D": "1"}},
    # combined best-case candidates: stem fix x batch x fused dispatch
    {"name": "s2d-512", "env": {"SWEEP_S2D": "1", "SWEEP_BATCH": "512"}},
    {"name": "s2d-fuse-8", "env": {"SWEEP_S2D": "1", "SWEEP_FUSE": "8"}},
    {"name": "latency-hiding-sched", "env": {
        "SWEEP_XLA_FLAGS": "--xla_tpu_enable_latency_hiding_scheduler=true"}},
    # full lever stack: if individual levers help, their combination is
    # the real headline candidate
    {"name": "s2d-lhs-512", "env": {
        "SWEEP_S2D": "1", "SWEEP_BATCH": "512",
        "SWEEP_XLA_FLAGS": "--xla_tpu_enable_latency_hiding_scheduler=true"}},
    {"name": "s2d-lhs-fuse-8", "env": {
        "SWEEP_S2D": "1", "SWEEP_FUSE": "8",
        "SWEEP_XLA_FLAGS": "--xla_tpu_enable_latency_hiding_scheduler=true"}},
    # ZeRO-1 weight-update sharding: optimizer state + update 1/N over
    # the data axis (reduce-scatter grads, all-gather params).  The
    # momentum update is cheap vs ResNet-50 FLOPs, so this measures the
    # reduce-scatter+all-gather vs all-reduce trade at DP numerics
    {"name": "zero1", "env": {"SWEEP_ZERO1": "1"}},
    {"name": "zero1-512", "env": {"SWEEP_ZERO1": "1", "SWEEP_BATCH": "512"}},
    # rule-derived dp x fsdp layouts (parallel/layout.py): the SAME dp
    # step math under ZeRO-3-style placement from the declarative rule
    # tables — measures the all-gather/reduce-scatter trade the layout
    # picker's ledger models, on the real chip
    {"name": "layout-fsdp", "env": {"SWEEP_LAYOUT": "fsdp"}},
    {"name": "layout-dp-fsdp-512", "env": {
        "SWEEP_LAYOUT": "dp_fsdp", "SWEEP_BATCH": "512"}},
    {"name": "batch-512", "env": {"SWEEP_BATCH": "512"}},
    {"name": "lhs-batch-512", "env": {
        "SWEEP_BATCH": "512",
        "SWEEP_XLA_FLAGS": "--xla_tpu_enable_latency_hiding_scheduler=true"}},
    # remat trades ~1 extra forward for O(depth)x less activation memory;
    # worth it iff the bigger batch it unlocks beats the FLOPs cost
    {"name": "remat-1024", "env": {"SWEEP_REMAT": "1", "SWEEP_BATCH": "1024"}},
    {"name": "remat-512", "env": {"SWEEP_REMAT": "1", "SWEEP_BATCH": "512"}},
    {"name": "bn-f32", "env": {"SWEEP_BN_F32": "1"}},
    {"name": "input-f32", "env": {"SWEEP_INPUT_F32": "1"}},
    {"name": "no-donate", "env": {"SWEEP_NO_DONATE": "1"}},
    {"name": "grad-accum-2", "env": {"SWEEP_ACCUM": "2", "SWEEP_BATCH": "512"}},
]


def _env_flag(name: str) -> bool:
    """'1'/'true'/'yes' enable, ''/'0'/'false'/'no'/unset disable."""
    return os.environ.get(name, "").strip().lower() in ("1", "true", "yes", "on")


def measure_one() -> dict:
    import jax

    if os.environ.get("SWEEP_PLATFORM"):
        # env JAX_PLATFORMS is ignored when the image pre-imports jax
        # (sitecustomize); the config update is the reliable override
        jax.config.update("jax_platforms", os.environ["SWEEP_PLATFORM"])
    import jax.numpy as jnp

    import bench

    batch = int(os.environ.get("SWEEP_BATCH", "256"))
    fuse = int(os.environ.get("SWEEP_FUSE", "1"))
    step, state, b = bench.build_step(
        batch,
        size=int(os.environ.get("SWEEP_SIZE", "224")),
        donate=not _env_flag("SWEEP_NO_DONATE"),
        accum_steps=int(os.environ.get("SWEEP_ACCUM", "1")),
        norm_dtype=jnp.float32 if _env_flag("SWEEP_BN_F32") else None,
        input_f32=_env_flag("SWEEP_INPUT_F32"),
        remat=_env_flag("SWEEP_REMAT"),
        fuse=fuse,
        s2d=_env_flag("SWEEP_S2D"),
        zero1=_env_flag("SWEEP_ZERO1"),
        layout=os.environ.get("SWEEP_LAYOUT") or None,
    )
    dt, _ = bench.time_compiled_step(
        step, state, b, target_seconds=float(os.environ.get("SWEEP_SECONDS", "2.0"))
    )
    # one fused call covers `fuse` optimizer steps on the same batch
    return {
        "img_per_sec_per_chip": round(batch * fuse / dt / jax.device_count(), 1),
        "step_ms": round(dt * 1e3 / fuse, 2),
        "batch": batch,
        "fuse": fuse,
        "platform": jax.devices()[0].platform,
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--one", action="store_true",
                    help="child mode: measure the SWEEP_* env configuration")
    ap.add_argument("--platform", default=None,
                    help="force platform for every child (e.g. cpu for a "
                         "smoke run on the fake-device mesh)")
    args = ap.parse_args()
    if args.platform:
        os.environ["SWEEP_PLATFORM"] = args.platform
    if args.one:
        print(json.dumps(measure_one()))
        return

    # hw_session exports this: between children is the only kill-free
    # place to stop (a SIGKILLed TPU child can wedge the device grant),
    # so the parent checks the deadline here and skips what no longer
    # fits a child's 1800 s self-bound
    deadline = int(os.environ.get("SWEEP_DEADLINE_EPOCH", "0") or 0)
    results = []
    for cfg in CONFIGS:
        if deadline and time.time() + 1800 > deadline:
            print(json.dumps({"config": cfg["name"],
                              "error": "skipped: deadline"}), flush=True)
            continue
        env = {**os.environ, **cfg["env"]}
        # APPEND sweep flags to pre-existing XLA_FLAGS so the row stays
        # comparable to the others (which inherit the environment's flags)
        extra = env.pop("SWEEP_XLA_FLAGS", None)
        if extra:
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") + " " + extra).strip()
        try:
            # generous timeout — a timeout SIGKILL of a TPU child can
            # leave the device grant wedged for every later config, so
            # this is a last resort, not a scheduling tool
            p = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--one"],
                env=env, capture_output=True, text=True, timeout=1800,
            )
        except subprocess.TimeoutExpired as e:
            # TimeoutExpired.stderr is bytes even under text=True
            err = e.stderr or b""
            if isinstance(err, bytes):
                err = err.decode(errors="replace")
            results.append({"config": cfg["name"], "error": "timeout",
                            "stderr": err[-300:]})
            print(json.dumps(results[-1]), flush=True)
            continue
        lines = p.stdout.strip().splitlines()
        r = None
        if lines:
            try:
                r = json.loads(lines[-1])
            except json.JSONDecodeError:
                pass
        if r is None or p.returncode != 0:
            r = {"error": f"rc={p.returncode}",
                 "stderr": p.stderr.strip()[-300:], **(r or {})}
        results.append({"config": cfg["name"], **r})
        print(json.dumps(results[-1]), flush=True)
    print(json.dumps({"sweep": results}))


if __name__ == "__main__":
    main()
