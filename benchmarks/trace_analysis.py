#!/usr/bin/env python
"""Trace-backed breakdown of the ResNet-50 train step.

Captures a ``jax.profiler`` trace of a few steady-state steps and parses
the xplane protobuf in-process (``jax.profiler.ProfileData`` — no
TensorBoard needed), aggregating device-op durations by fusion name.
This is the "where do the milliseconds go" tool for docs/benchmarks.md.

Usage: python benchmarks/trace_analysis.py [--steps 5] [--batch 256]
       [--model resnet50] [--top 30] [--platform cpu]

``--analyze-only`` skips the synthetic capture and analyzes an EXISTING
trace — a production ``train(..., profile_dir=...)`` capture (pass the
``profile_dir``; the profiler's ``plugins/profile/<session>/`` nesting
is searched recursively), a dir from a previous ``--trace-dir`` run, or
a single ``.xplane.pb`` file.  One analyzer for bench traces and
trainer traces, so a production step breakdown and a benchmark step
breakdown are directly comparable (docs/benchmarks.md "Trace handoff").
"""

from __future__ import annotations

import argparse
import collections
import glob
import os
import re
import tempfile


def capture(args) -> str:
    import jax
    import jax.numpy as jnp
    import numpy as np

    import fluxdistributed_tpu as fd
    from fluxdistributed_tpu import optim, sharding
    from fluxdistributed_tpu import models as models_lib
    from fluxdistributed_tpu.parallel import TrainState, make_train_step
    from fluxdistributed_tpu.parallel.dp import flax_loss_fn

    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    mesh = fd.data_mesh()
    model = getattr(models_lib, args.model)(
        num_classes=1000, space_to_depth=args.s2d
    )
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (args.batch, args.size, args.size, 3)).astype(np.float32)
    if args.s2d:
        x = np.ascontiguousarray(models_lib.space_to_depth(x))
    y = rng.integers(0, 1000, args.batch)
    variables = model.init(jax.random.PRNGKey(0), x[:1], train=True)
    params = variables["params"]
    mstate = {k: v for k, v in variables.items() if k != "params"}
    loss_fn = flax_loss_fn(model, fd.logitcrossentropy)
    opt = optim.momentum(0.1, 0.9)
    step = make_train_step(loss_fn, opt, mesh, donate=False)
    state = TrainState.create(
        sharding.replicate(params, mesh), opt,
        model_state=sharding.replicate(mstate, mesh),
    )
    b = sharding.shard_batch(
        {"image": x.astype(jnp.bfloat16),
         "label": np.asarray(fd.onehot(y, 1000))}, mesh
    )
    # compile + warm
    for _ in range(2):
        state, m = step(state, b)
    jax.block_until_ready(m["loss"])

    trace_dir = args.trace_dir or tempfile.mkdtemp(prefix="fdtpu_trace_")
    jax.profiler.start_trace(trace_dir)
    for _ in range(args.steps):
        state, m = step(state, b)
    jax.block_until_ready(m["loss"])
    jax.profiler.stop_trace()
    return trace_dir


_CLASS_PATTERNS = [
    ("conv", re.compile(r"conv|%convolution", re.I)),
    ("matmul", re.compile(r"dot|matmul", re.I)),
    ("allreduce/collective", re.compile(r"all-reduce|all-gather|collective|reduce-scatter", re.I)),
    ("batchnorm/elementwise", re.compile(r"fusion|add|multiply|subtract|divide|rsqrt|select", re.I)),
    ("reduce", re.compile(r"reduce", re.I)),
    ("copy/transpose", re.compile(r"copy|transpose|bitcast|reshape", re.I)),
]


def classify(name: str) -> str:
    for label, pat in _CLASS_PATTERNS:
        if pat.search(name):
            return label
    return "other"


def resolve_xplane(path: str) -> str:
    """Map a user-supplied trace path to ONE ``.xplane.pb`` file.

    Accepts a trainer ``profile_dir``, a ``--trace-dir`` from a capture
    run, or a direct ``.xplane.pb`` path.  A dir holding several capture
    sessions (e.g. a long-running trainer profiled twice) resolves to
    the NEWEST by mtime — and says so, so nobody silently analyzes last
    week's run.
    """
    if os.path.isfile(path):
        if not path.endswith(".xplane.pb"):
            raise SystemExit(
                f"{path} is a file but not an .xplane.pb — pass the "
                "profiler's xplane protobuf (or its directory)")
        return path
    paths = glob.glob(os.path.join(path, "**", "*.xplane.pb"),
                      recursive=True)
    if not paths:
        raise SystemExit(
            f"no .xplane.pb under {path} — expected a jax.profiler "
            "capture dir (benchmarks --trace-dir, or a trainer "
            "profile_dir from train(..., profile_dir=...))")
    paths.sort(key=os.path.getmtime)
    if len(paths) > 1:
        print(f"note: {len(paths)} capture sessions under {path}; "
              f"analyzing the newest ({os.path.relpath(paths[-1], path)})\n")
    return paths[-1]


def _load_profile(xplane: str):
    """Parse an ``.xplane.pb`` into a planes/lines/events view.

    Newer jax ships ``jax.profiler.ProfileData``; older toolchains (this
    image's jax 0.4.x) don't — there the TSL xplane protobuf that
    tensorflow carries parses the same file.  Both are adapted to the
    ProfileData attribute shape (``planes[].lines[].events[]`` with
    ``name``/``duration_ns``) so ``analyze`` has ONE consumer path.
    """
    try:
        from jax.profiler import ProfileData

        return ProfileData.from_file(xplane)
    except ImportError:
        pass
    try:
        from tensorflow.tsl.profiler.protobuf import xplane_pb2
    except ImportError as e:
        raise SystemExit(
            "cannot parse the trace: this jax has no "
            "jax.profiler.ProfileData and the tensorflow xplane proto "
            f"fallback is unavailable ({e}); upgrade jax or install "
            "tensorflow to analyze traces"
        )

    class _Event:
        __slots__ = ("name", "duration_ns")

        def __init__(self, name, duration_ns):
            self.name = name
            self.duration_ns = duration_ns

    class _Line:
        __slots__ = ("name", "events")

        def __init__(self, name, events):
            self.name = name
            self.events = events

    class _Plane:
        __slots__ = ("name", "lines")

        def __init__(self, name, lines):
            self.name = name
            self.lines = lines

    class _Profile:
        __slots__ = ("planes",)

        def __init__(self, planes):
            self.planes = planes

    space = xplane_pb2.XSpace()
    with open(xplane, "rb") as f:
        space.ParseFromString(f.read())
    planes = []
    for plane in space.planes:
        meta = plane.event_metadata  # id -> XEventMetadata
        lines = []
        for line in plane.lines:
            events = []
            for ev in line.events:
                md = meta.get(ev.metadata_id)
                name = (md.name or md.display_name) if md is not None else ""
                # XEvent carries picoseconds; ProfileData exposes ns
                events.append(_Event(name, ev.duration_ps / 1e3))
            lines.append(_Line(line.name, events))
        planes.append(_Plane(plane.name, lines))
    return _Profile(planes)


def analyze(trace_path: str, top: int):
    xplane = resolve_xplane(trace_path)
    pd = _load_profile(xplane)

    # pick accelerator device planes; on CPU there is no device plane, so
    # fall back to the host plane and SAY SO — host traces mix Python
    # frames in with XLA thunks and are not a device-op breakdown
    best = []
    for plane in pd.planes:
        pname = plane.name or ""
        if any(s in pname.lower() for s in ("tpu", "gpu", "device", "/xla:")):
            best.append(plane)
    host_fallback = not best
    if host_fallback:
        planes = [p for p in pd.planes if "cpu" in (p.name or "").lower()]
        best = planes[:1]
        if not best:
            raise SystemExit(
                f"no device plane in trace; planes = {[p.name for p in pd.planes]}"
            )
        print(
            "WARNING: no accelerator plane found — analyzing the HOST plane "
            "(includes Python/runtime frames; op classes are approximate). "
            "Run on TPU for a real device breakdown.\n"
        )

    durs: dict[str, float] = collections.defaultdict(float)
    counts: dict[str, int] = collections.defaultdict(int)
    for plane in best:
        for line in plane.lines:
            for ev in line.events:
                d = ev.duration_ns
                if d is None:
                    continue
                durs[ev.name] += d / 1e6  # ms
                counts[ev.name] += 1

    total = sum(durs.values())
    print(f"trace: {xplane}")
    print(f"planes analyzed: {[p.name for p in best]}")
    print(f"total device-op time: {total:.1f} ms (all steps, incl. overlap)\n")

    by_class: dict[str, float] = collections.defaultdict(float)
    for name, ms in durs.items():
        by_class[classify(name)] += ms
    print("by op class:")
    for label, ms in sorted(by_class.items(), key=lambda kv: -kv[1]):
        print(f"  {label:26s} {ms:9.1f} ms  ({100 * ms / max(total, 1e-9):5.1f}%)")

    print(f"\ntop {top} ops by total time:")
    for name, ms in sorted(durs.items(), key=lambda kv: -kv[1])[:top]:
        print(f"  {ms:9.2f} ms  x{counts[name]:<4d} {name[:110]}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--size", type=int, default=224)
    ap.add_argument("--model", default="resnet50")
    ap.add_argument("--top", type=int, default=30)
    ap.add_argument("--platform", default=None)
    ap.add_argument("--s2d", action="store_true",
                    help="trace the space_to_depth-stem model instead")
    ap.add_argument("--trace-dir", default=None)
    ap.add_argument("--analyze-only", default=None, metavar="PATH",
                    help="skip capture; analyze an existing trace: a "
                         "trainer profile_dir, a --trace-dir, or a "
                         "single .xplane.pb file")
    args = ap.parse_args()
    trace_dir = args.analyze_only or capture(args)
    analyze(trace_dir, args.top)


if __name__ == "__main__":
    main()
