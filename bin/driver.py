#!/usr/bin/env python
"""Training driver CLI — the entry point for single- and multi-host runs.

TPU-native replacement for the reference's driver pair (bin/driver.jl +
bin/main.jl): where the reference `addprocs(4)`s worker processes, parses
the sample table on process 1, hand-builds two sets of capacity-1
RemoteChannels and calls `FluxDistributed.start` (bin/driver.jl:3-41),
here ONE command runs on every host of a pod slice (or alone on a dev
box):

    # single host (all local chips):
    python bin/driver.py --model resnet50 --dataset synthetic \
        --batch-size 256 --cycles 100

    # each host of a TPU pod slice (cluster auto-detected):
    python bin/driver.py --model resnet50 --dataset imagenet ...

    # manual bring-up (e.g. CPU fake cluster):
    python bin/driver.py --coordinator localhost:9999 \
        --num-processes 2 --process-id $I --platform cpu --local-devices 4 ...

The compiled SPMD step is identical in every mode — multi-host changes
only device enumeration, not the program (contrast with the reference's
two separate code paths, src/ddp_tasks.jl vs src/sync.jl).
"""

from __future__ import annotations

import argparse
import sys
import time


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--model", default="resnet50",
                   help="model factory name in fluxdistributed_tpu.models "
                        "(resnet18/34/50/101/152, ...)")
    p.add_argument("--num-classes", type=int, default=None,
                   help="override class count (default: dataset's)")
    p.add_argument("--dataset", default="synthetic",
                   help="registered dataset name (Data.toml analog), 'synthetic' "
                        "(images), or 'synthetic-text' (LM token stream)")
    p.add_argument("--vocab", type=int, default=256,
                   help="vocab size for lm_* models / synthetic-text")
    p.add_argument("--seqlen", type=int, default=128,
                   help="sequence length for synthetic-text")
    p.add_argument("--data-toml", default=None,
                   help="dataset registry TOML to load (Data.toml analog)")
    p.add_argument("--val-dataset", default=None, help="registered val dataset name")
    p.add_argument("--image-size", type=int, default=224,
                   help="synthetic image side (smoke/test runs use small sizes)")
    p.add_argument("--batch-size", type=int, default=256,
                   help="GLOBAL batch size (reference: 96/device x N, README.md:43)")
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--cycles", type=int, default=None,
                   help="explicit cycle count (overrides epochs)")
    p.add_argument("--opt", default="momentum", choices=["momentum", "nesterov", "adam", "adamw", "descent", "lars"],
                   help="optimizer (reference: Momentum(0.01,0.9) README.md:37; ADAM src/sync.jl:97)")
    p.add_argument("--lr", type=float, default=0.01)
    p.add_argument("--warmup-steps", type=int, default=0)
    p.add_argument("--total-steps", type=int, default=None,
                   help="enable warmup-cosine schedule to this horizon")
    p.add_argument("--checkpoint-dir", default=None)
    p.add_argument("--checkpoint-every", type=int, default=20,
                   help="cycles between checkpoints (reference: 20, src/sync.jl:156)")
    p.add_argument("--resume", action="store_true",
                   help="resume from latest checkpoint in --checkpoint-dir")
    p.add_argument("--print-every", type=int, default=10)
    p.add_argument("--eval-every", type=int, default=50)
    p.add_argument("--final-eval", action="store_true",
                   help="after training, aggregate loss/top-k over the FULL "
                        "--val-dataset with train.evaluate")
    p.add_argument("--spmd", default="jit",
                   choices=["jit", "dp", "shard_map", "fsdp", "tp", "fsdp_tp",
                            "pp", "pp_1f1b", "ep", "sp"])
    p.add_argument("--layout", default=None, metavar="NAME|auto",
                   help="declarative dp x fsdp x tp layout "
                        "(parallel/layout.py): a preset name (dp, fsdp, "
                        "tp, dp_fsdp, fsdp_tp, dp_fsdp_tp) shards the "
                        "model from its committed rule table + the fsdp "
                        "overlay — NO per-model spec code; 'auto' runs "
                        "the layout picker (prices every candidate's "
                        "real compiled step, ranks by HBM headroom via "
                        "the fit checker's ranking, breaks ties by the "
                        "collective ledger) and trains with the fastest "
                        "layout that fits.  Keep --spmd jit (default)")
    p.add_argument("--hbm-bytes", type=float, default=None,
                   help="per-device HBM budget in bytes for --layout "
                        "auto (default: the live device bytes_limit; "
                        "REQUIRED for fit verdicts on backends without "
                        "memory_stats, e.g. the CPU mesh)")
    p.add_argument("--layout-report", default=None, metavar="PATH",
                   help="write the layout picker's report (chosen "
                        "layout + per-candidate headroom/ledger "
                        "ranking) as JSON here (--layout auto)")
    p.add_argument("--zero1", action="store_true",
                   help="ZeRO-1 weight-update sharding for the DP paths "
                        "(--spmd jit/dp/shard_map): reduce-scatter grads, "
                        "shard the optimizer state and update 1/N over the "
                        "data axis, all-gather updated params — DP-identical "
                        "numerics, ~N x lower optimizer memory")
    p.add_argument("--steps-per-call", type=int, default=1,
                   help="optimizer steps per dispatch (device loop; spmd=jit). "
                        "Amortizes host dispatch when the runtime is tunneled")
    p.add_argument("--tp", type=int, default=None,
                   help="model-axis size for --spmd tp / fsdp_tp (mesh "
                        "becomes {data: N/tp, model: tp}; required for "
                        "fsdp_tp, defaults to all devices for tp)")
    p.add_argument("--pipe", type=int, default=None,
                   help="pipe-axis size for --spmd pp / pp_1f1b (mesh "
                        "becomes {data: N/pipe, pipe: pipe}; defaults to "
                        "all devices, i.e. data=1)")
    p.add_argument("--microbatches", type=int, default=None,
                   help="pipeline microbatches per step (default 2x pipe "
                        "size; the (S-1)/(M+S-1) bubble shrinks as M grows)")
    p.add_argument("--pp-interleave", action="store_true",
                   help="Megatron interleaved virtual stages for --spmd "
                        "pp_1f1b (depth/pipe chunks per device; ~V-fold "
                        "smaller fill/drain bubble)")
    p.add_argument("--pp-schedule", default="1f1b", choices=["1f1b", "zb"],
                   help="pipeline timetable for --spmd pp_1f1b: classic "
                        "1F1B, or 'zb' (zero-bubble ZB-H1: each backward "
                        "splits into input-grad + deferred weight-grad "
                        "ticks and the weight-grad work fills the drain "
                        "bubble; bit-identical gradients)")
    p.add_argument("--pp-plan", default=None, metavar="PATH|auto",
                   help="profile-guided stage placement for --spmd "
                        "pp/pp_1f1b: 'auto' stages the model out and "
                        "plans from fresh static costs; PATH loads a "
                        "cost-profile artifact (--profile-out output) or "
                        "a saved plan JSON — non-uniform stage boundaries "
                        "minimizing the modeled max-stage cost (also "
                        "lifts the depth %% pipe divisibility "
                        "requirement).  Cross-topology artifacts are "
                        "rejected via the fingerprint check")
    p.add_argument("--expert-parallel", type=int, default=None,
                   help="expert-axis size for --spmd ep (mesh becomes "
                        "{data: N/ep, expert: ep}; defaults to all devices)")
    p.add_argument("--experts", type=int, default=None,
                   help="number of MoE experts for --spmd ep (multiple of "
                        "the expert axis; defaults to the axis size)")
    p.add_argument("--moe-every", type=int, default=None,
                   help="route every K-th decoder block through the MoE "
                        "layer (--spmd ep; default 2)")
    p.add_argument("--attn", default="dense",
                   choices=["dense", "blockwise", "flash"],
                   help="attention core for lm_* models: XLA dense, XLA "
                        "blockwise (memory-bounded scan), or the Pallas "
                        "flash kernel (fused fwd+bwd). Not combinable with "
                        "--spmd sp, which picks its own context-parallel "
                        "attention")
    p.add_argument("--attn-block", type=int, default=None,
                   help="block size for --attn blockwise|flash (default 128)")
    p.add_argument("--kv-heads", type=int, default=None,
                   help="grouped-query attention for lm_* models: number "
                        "of KV heads (must divide the model's num_heads; "
                        "shrinks the KV cache by num_heads/kv_heads)")
    p.add_argument("--window", type=int, default=None,
                   help="sliding-window attention for lm_* models: each "
                        "query attends its WINDOW newest keys (O(T*W) "
                        "attention; with --attn flash, out-of-band KV "
                        "blocks are skipped entirely)")
    p.add_argument("--sinks", type=int, default=0,
                   help="StreamingLLM attention sinks for lm_* models: the "
                        "first SINKS keys stay attendable outside the "
                        "window (requires --window)")
    p.add_argument("--norm", default="layernorm",
                   choices=["layernorm", "rmsnorm"],
                   help="lm_* block norm (rmsnorm = Llama-style)")
    p.add_argument("--mlp", default="gelu", choices=["gelu", "swiglu"],
                   help="lm_* block MLP (swiglu = Llama-style gated)")
    p.add_argument("--sp-strategy", default="ring",
                   choices=["ring", "ulysses"],
                   help="context-parallel attention for --spmd sp: 'ring' "
                        "(ppermute KV rotation, O(T/P) memory, any head "
                        "count) or 'ulysses' (two all_to_alls re-shard "
                        "seq<->heads; needs num_heads %% seq-axis == 0)")
    p.add_argument("--seq-parallel", type=int, default=None,
                   help="seq-axis size for --spmd sp (mesh becomes "
                        "{data: N/sp, seq: sp}; the LM runs ring attention "
                        "with the sequence sharded across it; defaults to "
                        "all devices)")
    p.add_argument("--verbose", action="store_true")
    p.add_argument("--wandb", action="store_true", help="log to Weights & Biases")
    # observability (fluxdistributed_tpu.obs): live endpoints + traces
    p.add_argument("--metrics-port", type=int, default=None,
                   help="serve GET /metrics (Prometheus text: step counter, "
                        "per-phase histograms, compile counts, OOM skips, "
                        "prefetch depth) and GET /healthz on this port for "
                        "the duration of the run (coordinator host only — "
                        "the serve/server.py stdlib-HTTP pattern)")
    p.add_argument("--trace-events", default=None, metavar="PATH",
                   help="record nested step-phase spans (data_wait/h2d/"
                        "dispatch/device/eval/checkpoint) and write "
                        "Chrome/Perfetto trace-event JSON here at exit; "
                        "implies per-step device sync so device time is "
                        "honestly attributed")
    p.add_argument("--metrics-jsonl", default=None, metavar="PATH",
                   help="append registry snapshots (JSON lines) here at the "
                        "print cadence — offline run diffing without a "
                        "Prometheus server")
    p.add_argument("--profile-out", default=None, metavar="PATH",
                   help="write a versioned cost-profile artifact "
                        "(obs.profile schema: static per-layer/step "
                        "FLOPs+bytes from the staged-out program, the "
                        "run's measured phase histograms, topology "
                        "fingerprint) here when training ends — the "
                        "input the pipeline planner and "
                        "benchmarks/pp_bubble.py consume")
    p.add_argument("--steady-after", type=int, default=None, metavar="N",
                   help="declare XLA warmup over after N cycles: any later "
                        "compile is counted + warned as a steady-state "
                        "recompile (fdtpu_jax_steady_recompiles_total)")
    p.add_argument("--flight", default=None, metavar="PATH",
                   help="black-box flight recorder (obs.flight): append "
                        "per-step records (step, loss, guard verdict, "
                        "phase seconds, headroom, compiles) here, flushed "
                        "+ checkpointed every few records — a SIGKILL "
                        "loses at most one flush interval, and the dump "
                        "footer (or its absence) says how the run ended")
    p.add_argument("--runs-ledger", default=None, metavar="PATH",
                   help="append one cross-run ledger record (obs.runs "
                        "schema: status, topology fingerprint, steps, "
                        "compile seconds, flight-dump path) here on every "
                        "exit path — the history bin/trends.py gates "
                        "regressions against")
    # cold-start performance (fluxdistributed_tpu.compilation)
    p.add_argument("--compile-cache", default=None, metavar="DIR",
                   help="enable JAX's persistent compilation cache here "
                        "(topology-namespaced subdir): the next run on "
                        "the same topology reads its XLA compiles from "
                        "disk — attempt N+1 of a short TPU grant window "
                        "skips attempt N's cold start")
    p.add_argument("--aot", default=None, metavar="DIR",
                   help="serialized train-step executables: load the "
                        "compiled step from DIR when topology + argument "
                        "signature match, else compile at prepare time "
                        "and serialize for the next process (also skips "
                        "tracing/lowering, which the compile cache "
                        "cannot)")
    p.add_argument("--prewarm", action="store_true",
                   help="run one donated dummy train step (and eval, "
                        "when a val set exists) before the training loop "
                        "starts, so step-0 timing excludes compilation")
    p.add_argument("--strict-checks", action="store_true",
                   help="debug-grade first steps: call 1 runs under "
                        "jax_debug_nans (a NaN names its producing "
                        "primitive), call 2 under "
                        "jax.transfer_guard('disallow') (an implicit "
                        "host<->device transfer on the steady state "
                        "raises); failures name the offending phase")
    p.add_argument("--watchdog-factor", type=float, default=5.0,
                   help="stall watchdog threshold as a multiple of the "
                        "rolling-median step time (warns + flips /healthz "
                        "to 503 when no step lands inside it; eval and "
                        "checkpoint phases are exempt). 0 disables the "
                        "watchdog")
    p.add_argument("--watchdog-escalate", type=int, default=4, metavar="N",
                   help="after a stall persists N further threshold "
                        "windows with no step, count a watchdog "
                        "ESCALATION (fdtpu_watchdog_escalations_total) — "
                        "the wedged-collective signal bin/supervise.py "
                        "SIGKILLs on. 0 disables escalation")
    # self-healing guard (fluxdistributed_tpu/train/guard.py)
    p.add_argument("--guard", action="store_true",
                   help="self-healing training: compile the anomaly "
                        "sentinel into the train step (global isfinite "
                        "any-reduce over loss+grads and global grad-norm, "
                        "ONE extra scalar fetch per step) and arm the "
                        "policy ladder — quarantine-and-skip anomalous "
                        "batches, roll back to the last-good checkpoint "
                        "when anomalies persist, halt (exit rc 65, not "
                        "retryable) when rollbacks loop.  Decisions are "
                        "recorded in the RESUME manifest and visible as "
                        "fdtpu_guard_* metrics")
    p.add_argument("--guard-zmax", type=float, default=8.0,
                   help="robust z-score above which a finite loss counts "
                        "as a spike anomaly")
    p.add_argument("--guard-window", type=int, default=64,
                   help="rolling window (accepted losses) behind the "
                        "spike detector's median/MAD")
    p.add_argument("--guard-rollback-after", type=int, default=3,
                   help="anomalies within the guard's anomaly window "
                        "that escalate skip -> rollback")
    p.add_argument("--replay-step", type=int, default=None, metavar="K",
                   help="diagnosis harness: instead of training, "
                        "re-execute loader item K deterministically "
                        "(same (seed, process, item) batch derivation) "
                        "against the prepared — or, with --resume, the "
                        "restored — state under jax_debug_nans, print "
                        "one JSON report line and exit.  The postmortem "
                        "for a quarantined step")
    p.add_argument("--fault-plan", default=None, metavar="JSON",
                   help="install a deterministic fault-injection plan "
                        "(fluxdistributed_tpu.faults) before anything "
                        "else runs — chaos/testing harness.  JSON object "
                        "or @path/to/plan.json, e.g. "
                        "'{\"sigterm_at_step\": 50}' proves the "
                        "checkpoint-on-SIGTERM path, "
                        "'{\"params\": {\"local_devices\": 4}}' simulates "
                        "a device-count change on resume")
    # manual cluster bring-up (CPU fake cluster / debugging)
    p.add_argument("--coordinator", default=None, help="coordinator host:port")
    p.add_argument("--num-processes", type=int, default=None)
    p.add_argument("--process-id", type=int, default=None)
    p.add_argument("--platform", default=None, help="force platform (e.g. cpu)")
    p.add_argument("--local-devices", type=int, default=None,
                   help="virtual CPU devices per process (fake-cluster mode)")
    return p


def _resolve_pp_plan(args, model, mesh):
    """``--pp-plan``: 'auto' stages the model out for fresh static
    costs; a path loads a cost-profile artifact (planned here) or a
    saved plan JSON (planned elsewhere) — the shared
    ``parallel.pp_plan.resolve_plan`` implementation, which rejects
    cross-topology artifacts through the fingerprint check
    (``prepare_training`` re-checks at consume time too)."""
    from fluxdistributed_tpu import mesh as mesh_lib
    from fluxdistributed_tpu.obs.profile import ProfileMismatch
    from fluxdistributed_tpu.parallel.pp_plan import PlanError, resolve_plan

    S = mesh.shape[mesh_lib.PIPE_AXIS]
    n_data = mesh.shape[mesh_lib.DATA_AXIS]
    M = args.microbatches or 2 * S
    try:
        return resolve_plan(
            args.pp_plan, S, M,
            schedule=args.pp_schedule if args.spmd == "pp_1f1b" else "1f1b",
            model=model,
            batch_size=max(args.batch_size // max(n_data, 1), 1),
            seqlen=args.seqlen)
    except (PlanError, ProfileMismatch, ValueError, OSError) as e:
        raise SystemExit(f"--pp-plan {args.pp_plan}: {e}")


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    from fluxdistributed_tpu import faults

    if args.fault_plan:
        import json

        spec = args.fault_plan
        if spec.startswith("@"):
            with open(spec[1:]) as f:
                spec = f.read()
        faults.install_plan(faults.FaultPlan.from_spec(json.loads(spec)))
        # a plan can simulate a device-count change on resume: the next
        # grant window handing back a different slice is modeled by
        # overriding the virtual-device count before backend init
        override = faults.param("local_devices")
        if override is not None:
            args.local_devices = int(override)
            args.platform = args.platform or "cpu"

    # Distributed init MUST precede any backend use.
    from fluxdistributed_tpu.parallel import multihost

    multihost.initialize(
        coordinator_address=args.coordinator,
        num_processes=args.num_processes,
        process_id=args.process_id,
        platform=args.platform,
        local_devices=args.local_devices,
    )

    import jax

    import fluxdistributed_tpu as fd
    from fluxdistributed_tpu import models, optim
    from fluxdistributed_tpu.data import SyntheticDataset
    from fluxdistributed_tpu.train import prepare_training, train
    from fluxdistributed_tpu.train.logging import ConsoleLogger, NullLogger

    if args.data_toml:
        fd.load_registry(args.data_toml)

    if args.dataset == "synthetic":
        dataset = SyntheticDataset(nsamples=max(args.batch_size * 8, 1024),
                                   nclasses=args.num_classes or 1000,
                                   shape=(args.image_size, args.image_size, 3))
    elif args.dataset == "synthetic-text":
        from fluxdistributed_tpu.data import SyntheticTextDataset

        dataset = SyntheticTextDataset(vocab=args.vocab, seqlen=args.seqlen)
    elif args.dataset.startswith("text:"):
        # byte-level LM on any local file: --dataset text:/path/corpus.txt
        from fluxdistributed_tpu.data import ByteTextDataset

        dataset = ByteTextDataset(args.dataset[len("text:"):], seqlen=args.seqlen)
        args.vocab = dataset.vocab
    else:
        dataset = fd.open_dataset(args.dataset)
    val_dataset = fd.open_dataset(args.val_dataset) if args.val_dataset else None

    model_fn = getattr(models, args.model)
    is_lm = args.model.startswith("lm_") or args.model == "TransformerLM"
    if not is_lm and not hasattr(dataset, "nclasses"):
        raise SystemExit(
            f"--dataset {args.dataset} is a token stream; use an lm_* model"
        )
    if is_lm and hasattr(dataset, "nclasses"):
        raise SystemExit(
            f"--model {args.model} trains on tokens; use --dataset synthetic-text"
        )
    if args.final_eval and args.val_dataset is None:
        raise SystemExit("--final-eval needs --val-dataset")
    def data_x_mesh(axis: str, flag: str, requested, min_k: int = 2):
        """The shared {data: N/k, <axis>: k} mesh recipe behind --tp /
        --pipe / --expert-parallel / --seq-parallel: resolve the default
        (all devices), validate divisibility, build the mesh."""
        from fluxdistributed_tpu.mesh import make_mesh

        ndev = jax.device_count()
        k = requested if requested is not None else ndev
        if k < min_k or ndev % k:
            raise SystemExit(
                f"{flag} {k} must be >={min_k} and divide {ndev} devices")
        return make_mesh({"data": ndev // k, axis: k}), k

    # Sequence/context parallelism: the model's attn_fn closes over the
    # mesh, so the seq mesh is built BEFORE the model for this mode
    sp_mesh = None
    sp_kwargs = {}
    if args.spmd == "sp":
        from fluxdistributed_tpu.parallel import (
            make_ring_attention, make_ulysses_attention,
        )

        if not is_lm:
            raise SystemExit("--spmd sp needs an lm_* model (causal context-"
                             "parallel attention over the sequence)")
        sp_mesh, sp = data_x_mesh("seq", "--seq-parallel", args.seq_parallel)
        if args.seqlen % sp:
            raise SystemExit(f"--seqlen {args.seqlen} must be a multiple of "
                             f"the seq axis size {sp}")
        if args.sp_strategy == "ulysses":
            # Ulysses re-shards heads over the seq axis: the head count is
            # a model-constructor default, so probe it before committing.
            nheads = model_fn(vocab=args.vocab).num_heads
            if nheads % sp:
                raise SystemExit(
                    f"--sp-strategy ulysses needs num_heads ({nheads} for "
                    f"{args.model}) divisible by the seq axis size {sp}; "
                    f"use --seq-parallel accordingly or --sp-strategy ring")
            make_attn = make_ulysses_attention
        else:
            make_attn = make_ring_attention
        sp_kwargs = {"attn_fn": make_attn(
            sp_mesh, batch_axis="data", causal=True)}

    # Attention-core selection for the LM family (one flag, shared
    # wiring with benchmarks/lm_bench.py via ops.attention_core)
    attn_kwargs = {}
    if args.attn_block is not None and args.attn == "dense":
        raise SystemExit("--attn-block only applies with --attn "
                         "blockwise|flash")
    if args.attn_block is not None and args.attn_block <= 0:
        raise SystemExit(f"--attn-block must be > 0, got {args.attn_block}")
    if args.window is not None:
        if not is_lm:
            raise SystemExit("--window only applies to lm_* models")
        if args.window < 1:
            raise SystemExit(f"--window must be >= 1, got {args.window}")
        if args.spmd == "sp":
            raise SystemExit("--window is not supported with --spmd sp "
                             "(context-parallel attention is unwindowed)")
        # the model field windows the default dense core AND the decode
        # path; a non-dense attn_fn gets its own window below
        attn_kwargs["window"] = args.window
        if args.sinks:
            if args.sinks < 0:
                raise SystemExit(f"--sinks must be >= 0, got {args.sinks}")
            attn_kwargs["sinks"] = args.sinks
    if args.sinks and args.window is None:
        raise SystemExit("--sinks requires --window")
    if args.attn != "dense":
        from fluxdistributed_tpu.ops import attention_core

        if not is_lm:
            raise SystemExit("--attn only applies to lm_* models")
        if args.spmd == "sp":
            raise SystemExit("--attn conflicts with --spmd sp: sequence "
                             "parallelism picks its own attention core "
                             "(use --sp-strategy)")
        attn_kwargs["attn_fn"] = attention_core(
            args.attn, args.attn_block if args.attn_block else 128,
            window=args.window, sinks=args.sinks)
    if args.kv_heads is not None:
        if not is_lm:
            raise SystemExit("--kv-heads only applies to lm_* models")
        nheads = model_fn(vocab=args.vocab).num_heads
        if args.kv_heads <= 0 or nheads % args.kv_heads:
            raise SystemExit(
                f"--kv-heads {args.kv_heads} must be > 0 and divide the "
                f"model's num_heads ({nheads} for {args.model})")
        if args.spmd in ("tp", "fsdp_tp") and not (
                args.spmd == "fsdp_tp" and args.tp is None):
            # lm_tp_rules head-shards the kv projection: the model axis
            # must divide the KV head count or sharding fails cryptically.
            # (fsdp_tp without --tp is itself invalid — the dedicated
            # check below reports THAT, not a misleading kv-heads error.)
            model_k = args.tp if args.tp is not None else jax.device_count()
            if args.kv_heads % model_k:
                raise SystemExit(
                    f"--kv-heads {args.kv_heads} must be a multiple of the "
                    f"TP model-axis size ({model_k}) so the grouped kv "
                    f"projection can be head-sharded")
        attn_kwargs["num_kv_heads"] = args.kv_heads
    if args.norm != "layernorm" or args.mlp != "gelu":
        if not is_lm:
            raise SystemExit("--norm/--mlp only apply to lm_* models")
        attn_kwargs["norm"] = args.norm
        attn_kwargs["mlp"] = args.mlp

    # MoE expert parallelism: the model's moe_fn closes over the mesh,
    # so the expert mesh is built BEFORE the model for this mode
    ep_mesh = None
    moe_kwargs = {}
    if args.spmd == "ep":
        from fluxdistributed_tpu.parallel.ep import moe_apply

        if not is_lm:
            raise SystemExit("--spmd ep needs an lm_* model (MoE blocks)")
        ep_mesh, ep = data_x_mesh(
            "expert", "--expert-parallel", args.expert_parallel)
        nex = args.experts if args.experts is not None else ep
        if nex % ep:
            raise SystemExit(f"--experts {nex} must be a multiple of the "
                             f"expert axis size {ep}")
        moe_kwargs = {
            "moe_every": args.moe_every if args.moe_every is not None else 2,
            "num_experts": nex,
            "moe_fn": moe_apply(
                models.moe_expert_fn, ep_mesh, capacity_factor=2.0,
                batch_axis="data",
            ),
        }

    if is_lm:
        # LM protocol: vocab-sized model, next-token loss, no top-k image
        # metrics; cycles must be explicit (the text stream is unbounded).
        # Pipeline modes build their own per-microbatch loss — passing a
        # loss_fn there is an error by design (trainer raises).
        model = model_fn(vocab=args.vocab, **moe_kwargs, **sp_kwargs,
                         **attn_kwargs)
        if args.spmd in ("pp", "pp_1f1b"):
            lm_extra = {"topk": ()}
        else:
            lm_extra = {"loss_fn": models.lm_loss_fn(model), "topk": ()}
        if args.cycles is None and not hasattr(dataset, "__len__"):
            raise SystemExit("--cycles is required for unbounded token "
                             "streams (synthetic-text has no epoch length; "
                             "text: datasets derive cycles from --epochs)")
    else:
        model = model_fn(num_classes=args.num_classes or dataset.nclasses)
        lm_extra = {}

    lr = args.lr
    if args.total_steps:
        lr = optim.warmup_cosine(args.lr, args.warmup_steps, args.total_steps)
    opt_factory = getattr(optim, args.opt)
    opt = opt_factory(lr)

    if args.tp is not None and args.spmd not in ("tp", "fsdp_tp"):
        raise SystemExit("--tp only applies with --spmd tp or fsdp_tp")
    if args.pipe is not None and args.spmd not in ("pp", "pp_1f1b"):
        raise SystemExit("--pipe only applies with --spmd pp or pp_1f1b")
    if args.microbatches is not None and args.spmd not in ("pp", "pp_1f1b"):
        raise SystemExit("--microbatches only applies with --spmd pp or pp_1f1b")
    if args.pp_interleave and args.spmd != "pp_1f1b":
        raise SystemExit("--pp-interleave only applies with --spmd pp_1f1b")
    if args.pp_schedule != "1f1b" and args.spmd != "pp_1f1b":
        raise SystemExit("--pp-schedule zb only applies with --spmd pp_1f1b")
    if args.pp_plan is not None and args.spmd not in ("pp", "pp_1f1b"):
        raise SystemExit("--pp-plan only applies with --spmd pp or pp_1f1b")
    if args.pp_plan is not None and args.pp_interleave:
        raise SystemExit("--pp-plan cannot combine with --pp-interleave "
                         "(planner boundaries are contiguous block ranges)")
    if (args.expert_parallel is not None or args.experts is not None
            or args.moe_every is not None) and args.spmd != "ep":
        raise SystemExit(
            "--expert-parallel/--experts/--moe-every only apply with --spmd ep")
    if args.seq_parallel is not None and args.spmd != "sp":
        raise SystemExit("--seq-parallel only applies with --spmd sp")
    if args.zero1 and args.spmd not in ("jit", "dp", "shard_map"):
        raise SystemExit("--zero1 only applies with --spmd jit/dp/shard_map "
                         "(fsdp already shards the optimizer state)")
    if args.layout is not None:
        if args.spmd not in ("jit", "dp"):
            raise SystemExit("--layout builds the rule-derived 3-D step "
                             "and needs --spmd jit (the default)")
        if args.zero1:
            raise SystemExit("--layout cannot combine with --zero1 (a "
                             "layout's fsdp axis already shards the "
                             "optimizer state)")
    if (args.hbm_bytes is not None or args.layout_report) \
            and args.layout != "auto":
        raise SystemExit("--hbm-bytes/--layout-report only apply with "
                         "--layout auto")
    if args.sp_strategy != "ring" and args.spmd != "sp":
        raise SystemExit("--sp-strategy only applies with --spmd sp")
    if args.spmd in ("tp", "fsdp_tp"):
        if args.spmd == "fsdp_tp" and (
                args.tp is None or args.tp >= jax.device_count()):
            raise SystemExit(
                "--spmd fsdp_tp needs --tp < device count: with no data-axis "
                "extent there is nothing for FSDP to shard over"
            )
        mesh, _ = data_x_mesh("model", "--tp", args.tp, min_k=1)
    elif args.spmd in ("pp", "pp_1f1b"):
        mesh, _ = data_x_mesh("pipe", "--pipe", args.pipe)
        lm_extra["num_microbatches"] = args.microbatches
        lm_extra["pipeline_interleave"] = args.pp_interleave
        lm_extra["pipeline_schedule"] = args.pp_schedule
        if args.pp_plan:
            plan = _resolve_pp_plan(args, model, mesh)
            lm_extra["pp_plan"] = plan
            if multihost.is_coordinator():
                print(plan.describe())
    elif args.spmd == "ep":
        mesh = ep_mesh
    elif args.spmd == "sp":
        mesh = sp_mesh
    elif args.layout is not None:
        # declarative dp x fsdp x tp layout (rule-derived sharding);
        # 'auto' = the picker: price every candidate's real compiled
        # step, rank by headroom, tiebreak by the collective ledger
        import numpy as np

        from fluxdistributed_tpu.parallel import layout as layout_lib

        if args.layout == "auto":
            from fluxdistributed_tpu.data.loader import batch_to_dict

            draw = dataset.batch(np.random.default_rng(0), args.batch_size)
            bd = batch_to_dict(draw, getattr(dataset, "nclasses", None))
            batch_struct = {
                k: jax.ShapeDtypeStruct(np.shape(v), np.asarray(v).dtype)
                for k, v in bd.items()}
            try:
                pick_report = layout_lib.pick(
                    model, batch_struct, opt, hbm_bytes=args.hbm_bytes,
                    loss_fn=lm_extra.get("loss_fn"))
            except layout_lib.LayoutError as e:
                rep = getattr(e, "report", None)
                if rep is not None:
                    if multihost.is_coordinator():
                        print(rep.describe())
                    if args.layout_report:
                        rep.save(args.layout_report)
                raise SystemExit(f"--layout auto: {e}")
            chosen = pick_report.chosen
            if multihost.is_coordinator():
                print(pick_report.describe())
            if args.layout_report:
                pick_report.save(args.layout_report)
        else:
            try:
                chosen = layout_lib.resolve_layout(args.layout)
            except layout_lib.LayoutError as e:
                raise SystemExit(f"--layout {args.layout}: {e}")
        mesh = chosen.build_mesh()
        lm_extra["layout"] = chosen
    else:
        mesh = fd.data_mesh()
    if multihost.is_coordinator():
        print(
            f"devices: {jax.device_count()} ({jax.local_device_count()}/host x "
            f"{jax.process_count()} hosts), platform "
            f"{jax.devices()[0].platform}, mesh {dict(mesh.shape)}"
        )

    # the compiled grad sentinel rides dp.make_train_step; other modes
    # still get the guard POLICY loss-only (non-finite loss + spikes),
    # so --guard degrades instead of erroring there
    guard_sentinel = args.guard and args.spmd in ("jit", "dp", "sp",
                                                  "ep", "pp")
    if args.guard and not guard_sentinel and multihost.is_coordinator():
        print(f"guard: spmd={args.spmd} has no compiled grad sentinel — "
              "running loss-only (non-finite loss + spike detection; "
              "gradient blow-ups that keep the loss finite pass unseen)")

    task = prepare_training(
        model, dataset, opt,
        mesh=mesh,
        batch_size=args.batch_size,
        epochs=args.epochs,
        cycles=args.cycles,
        val_dataset=val_dataset,
        spmd=args.spmd,
        zero1=args.zero1,
        steps_per_call=args.steps_per_call,
        cache_dir=args.compile_cache,
        aot=args.aot,
        warmup=args.prewarm,
        strict_checks=args.strict_checks,
        guard=guard_sentinel,
        **lm_extra,
    )

    if args.resume and args.checkpoint_dir:
        from fluxdistributed_tpu.train import resume_training

        manifest = resume_training(task, args.checkpoint_dir)
        if multihost.is_coordinator() and (
                manifest is not None or int(task.state.step)):
            src = ("RESUME manifest" if manifest is not None
                   else "latest checkpoint (no manifest)")
            print(f"resumed from step {int(task.state.step)} at item "
                  f"{getattr(task.loader, 'start', 0)} via {src}")

    if args.replay_step is not None:
        import json as json_lib

        from fluxdistributed_tpu.train import replay_item

        # one quarantined step, re-executed from checkpoint + cursor
        # for diagnosis — never trains, never mutates the state
        report = replay_item(task, args.replay_step)
        print(json_lib.dumps(report))
        return 0

    if args.wandb:
        from fluxdistributed_tpu.train.logging import WandbLogger

        # push the full run configuration at init (reference
        # src/loggers/wandb.jl:1 passes config= to WandbLogger): every
        # arch/spmd/optimizer flag plus the resolved runtime facts —
        # runs become comparable by WHAT they trained, not just curves
        run_config = dict(sorted(vars(args).items()))
        run_config.update(
            devices=jax.device_count(),
            hosts=jax.process_count(),
            platform=jax.devices()[0].platform,
            mesh={k: int(v) for k, v in dict(mesh.shape).items()},
        )
        logger = WandbLogger(project="fluxdistributed_tpu", config=run_config)
    else:
        # per-host logs like the reference's per-worker @info records;
        # non-coordinators stay quiet unless --verbose
        logger = ConsoleLogger() if (multihost.is_coordinator() or args.verbose) else NullLogger()

    # Unified observability: phase metrics + compile counters always on;
    # spans/watchdog/endpoints per flags.  The metrics endpoint binds on
    # the coordinator only (a fake cluster runs many processes per host —
    # N processes racing for one port helps nobody).
    from fluxdistributed_tpu.obs import (
        Observation, SpanTracer, StepWatchdog, get_registry,
        start_metrics_server,
    )

    observation = Observation(
        tracer=SpanTracer() if args.trace_events else None,
        watchdog=(StepWatchdog(factor=args.watchdog_factor,
                               escalate_after=args.watchdog_escalate)
                  if args.watchdog_factor else None),
        trace_path=args.trace_events,
        device_sync=bool(args.trace_events),
        steady_after=args.steady_after,
        jsonl_path=args.metrics_jsonl,
        profile_path=args.profile_out,
        flight_path=args.flight,
    )
    metrics_srv = None
    if args.metrics_port is not None and multihost.is_coordinator():
        reg = get_registry()

        def _health():
            return {
                "ok": reg.value("fdtpu_watchdog_stalled") < 1,
                "steps": reg.value("fdtpu_train_steps_total"),
                "oom_skipped": reg.value("fdtpu_train_oom_skipped_total"),
                "compiles": reg.value("fdtpu_jax_compiles_total"),
                "steady_recompiles": reg.value(
                    "fdtpu_jax_steady_recompiles_total"),
                "escalations": reg.value(
                    "fdtpu_watchdog_escalations_total"),
                "quarantined": reg.value("fdtpu_guard_quarantine_size"),
            }

        metrics_srv = start_metrics_server(
            port=args.metrics_port, health_fn=_health)
        print(f"metrics: http://0.0.0.0:{metrics_srv.port}/metrics "
              f"(+ /healthz)")

    guard_cfg = None
    if args.guard:
        from fluxdistributed_tpu.train import GuardConfig

        guard_cfg = GuardConfig(
            zmax=args.guard_zmax,
            window=args.guard_window,
            rollback_after=args.guard_rollback_after,
        )

    from fluxdistributed_tpu.train import GuardHalt

    t_train = time.monotonic()

    def _ledger(status, error=None, retryable=None, live=False):
        """Append this run's row to the cross-run ledger.  Best-effort
        on every exit path — the ledger must never change an exit code.
        The topology fingerprint calls ``jax.devices()``, which can
        HANG on a wedged backend, so it is only computed when ``live``
        says the backend provably just answered (done/halt/preempt —
        never on the crash path)."""
        if not args.runs_ledger:
            return
        try:
            from fluxdistributed_tpu.compilation import (
                topology_fingerprint,
            )
            from fluxdistributed_tpu.obs import get_registry
            from fluxdistributed_tpu.obs import runs as runs_lib

            reg = get_registry()
            fp = None
            if live:
                try:
                    fp = topology_fingerprint(mesh)
                except Exception:  # noqa: BLE001
                    fp = None
            wall = max(time.monotonic() - t_train, 1e-9)
            steps = reg.value("fdtpu_train_steps_total")
            runs_lib.append_run(args.runs_ledger, runs_lib.run_record(
                "train",
                fingerprint=fp,
                phase="train",
                retryable=retryable,
                error=error,
                metrics={
                    "steps": steps,
                    "steps_per_sec": steps / wall,
                    "wall_seconds": wall,
                    "compile_seconds": reg.value(
                        "fdtpu_jax_compile_seconds_total"),
                    "oom_skipped": reg.value(
                        "fdtpu_train_oom_skipped_total"),
                },
                flight=args.flight,
                status=status,
            ))
        except Exception as e:  # noqa: BLE001
            print(f"runs ledger append failed: {type(e).__name__}: {e}",
                  file=sys.stderr)

    try:
        train(
            task,
            print_every=args.print_every,
            eval_every=args.eval_every,
            topk=() if is_lm else (1, 5, 10),
            logger=logger,
            checkpoint_dir=args.checkpoint_dir,
            checkpoint_every=args.checkpoint_every,
            verbose=args.verbose,
            observation=observation,
            handle_signals=True,
            guard=guard_cfg,
        )
    except GuardHalt as e:
        # recovery is looping: a DISTINCT, deliberately NON-retryable
        # exit code — a supervisor must page a human, not requeue
        _ledger("halted", error=str(e), retryable=False, live=True)
        if multihost.is_coordinator():
            print(f"guard halt: {e} (exit code {faults.HALTED_RC}, "
                  "retryable: false)")
        return faults.HALTED_RC
    except faults.Preempted as e:
        # checkpoint + RESUME manifest are already durably on disk;
        # the DISTINCT exit code tells a supervisor "requeue me with
        # --resume", unlike 0 (done) or 1 (crashed)
        _ledger("preempted", error=str(e), retryable=True, live=True)
        if multihost.is_coordinator():
            print(f"preempted: {e} — resume with --resume "
                  f"--checkpoint-dir {args.checkpoint_dir} "
                  f"(exit code {faults.PREEMPTED_RC})")
        return faults.PREEMPTED_RC
    except BaseException as e:
        # a crash record with NO fingerprint (the backend may be the
        # thing that died — fingerprinting it could hang the exit)
        _ledger("crashed", error=f"{type(e).__name__}: {e}",
                retryable=None, live=False)
        raise
    finally:
        if metrics_srv is not None:
            metrics_srv.stop()
    _ledger("done", live=True)
    multihost.sync_global_devices("train_done")
    if args.final_eval:
        from fluxdistributed_tpu.train import evaluate

        metrics = evaluate(
            task, val_dataset, batch_size=args.batch_size,
            topk=() if is_lm else (1, 5, 10),
        )
        if multihost.is_coordinator():
            parts = ", ".join(
                f"{k}={v:.4f}" if isinstance(v, float) else f"{k}={v}"
                for k, v in metrics.items()
            )
            print(f"final eval: {parts}")
    if multihost.is_coordinator():
        print(f"done: {int(task.state.step)} steps, {task.num_missed} missed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
