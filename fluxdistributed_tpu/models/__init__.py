from .convnext import (
    ConvNeXt,
    convnext_base,
    convnext_large,
    convnext_small,
    convnext_test,
    convnext_tiny,
    convnext_xlarge,
)
from .resnet import ResNet, resnet18, resnet34, resnet50, resnet101, resnet152
from .torch_import import (
    import_torch_convnext,
    import_torch_resnet,
    import_torch_vit,
    load_torch_file,
)
from .simple import SimpleCNN, MLP
from .transformer_lm import (
    TransformerLM,
    generate,
    lm_loss_fn,
    lm_medium,
    lm_pp,
    lm_small,
    lm_tiny,
    next_token_loss,
)
from .vit import ViT, vit_tiny, vit_b16, vit_l16, vit_h14

__all__ = [
    "ConvNeXt",
    "convnext_test",
    "convnext_tiny",
    "convnext_small",
    "convnext_base",
    "convnext_large",
    "convnext_xlarge",
    "ResNet",
    "resnet18",
    "resnet34",
    "resnet50",
    "resnet101",
    "resnet152",
    "import_torch_resnet",
    "import_torch_vit",
    "import_torch_convnext",
    "load_torch_file",
    "SimpleCNN",
    "MLP",
    "TransformerLM",
    "generate",
    "lm_loss_fn",
    "lm_pp",
    "lm_tiny",
    "lm_small",
    "lm_medium",
    "next_token_loss",
    "ViT",
    "vit_tiny",
    "vit_b16",
    "vit_l16",
    "vit_h14",
]
