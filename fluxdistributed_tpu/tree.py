"""Gradient/parameter pytree machinery.

TPU-native replacement for the reference's hand-rolled grad-tree layer
(reference: src/ddp_tasks.jl:4-26 ``destruct``/``mywalk``/``_zero``;
src/overloads.jl:43-54 ``_accum``/``_dodiv``; test/runtests.jl:6-41 the
recursive ``compare`` comparator and ``getfirst``).

In JAX, gradients already come back as pytrees matching the parameter
structure, so most of the reference machinery collapses into
``jax.tree_util``.  What remains useful — and what this module provides —
is:

* zero-like construction (``zeros_like`` — the ``destruct`` analog),
* ``None``-tolerant accumulation and scalar division (``accum``/``div`` —
  the ``_accum``/``_dodiv`` analogs; the reference treats stateless layers
  as ``nothing`` leaves, JAX uses ``None`` in grad trees the same way),
* a sequential mean over a list of grad trees (``mean`` — the
  ``sync_buffer`` hub-reduce analog, src/ddp_tasks.jl:93-109 — used for
  tests and host-side debugging; the production path is a compiled XLA
  all-reduce, see ``fluxdistributed_tpu.parallel``),
* a structural numeric comparator with path-aware error messages
  (``allclose``/``assert_close`` — the test comparator analog), and
* small conveniences (``getfirst``, ``count_params``, ``nbytes``, casts).

Every function is pure and jit-compatible unless documented otherwise.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np

Pytree = Any

__all__ = [
    "zeros_like",
    "accum",
    "div",
    "scale",
    "add_scaled",
    "mean",
    "allclose",
    "assert_close",
    "getfirst",
    "count_params",
    "nbytes",
    "cast",
    "to_host",
    "synchronize",
]


def _is_none(x: Any) -> bool:
    return x is None


def zeros_like(tree: Pytree) -> Pytree:
    """A zeroed gradient tree with the same structure as ``tree``.

    Analog of the reference's ``destruct`` (src/ddp_tasks.jl:22-26), which
    walks the model with Functors and replaces every array leaf with
    ``zero(x)`` and every non-differentiable leaf with ``nothing``.  JAX
    grad trees carry ``None`` for non-differentiable leaves already, so we
    simply map ``jnp.zeros_like`` over the non-``None`` leaves.
    """
    return jax.tree.map(
        lambda x: None if x is None else jnp.zeros_like(x),
        tree,
        is_leaf=_is_none,
    )


def accum(a: Pytree, b: Pytree) -> Pytree:
    """Leafwise ``a + b`` where ``None`` acts as an additive identity.

    Analog of ``_accum`` (src/overloads.jl:43-46), which forwards to
    ``Zygote.accum`` so that ``nothing`` gradients (stateless layers such
    as pooling/activation) absorb into the other side.
    """

    def f(x, y):
        if x is None:
            return y
        if y is None:
            return x
        return x + y

    return jax.tree.map(f, a, b, is_leaf=_is_none)


def div(tree: Pytree, denom) -> Pytree:
    """Leafwise division by a scalar, skipping ``None`` leaves.

    Analog of ``_dodiv`` (src/overloads.jl:48-54) — the "divide by the
    number of replicas" half of gradient averaging.
    """
    return jax.tree.map(
        lambda x: None if x is None else x / denom, tree, is_leaf=_is_none
    )


def scale(tree: Pytree, s) -> Pytree:
    """Leafwise multiplication by a scalar, skipping ``None`` leaves."""
    return jax.tree.map(
        lambda x: None if x is None else x * s, tree, is_leaf=_is_none
    )


def add_scaled(a: Pytree, b: Pytree, s) -> Pytree:
    """``a + s * b`` leafwise, ``None``-tolerant.  Used by optimizers."""

    def f(x, y):
        if y is None:
            return x
        if x is None:
            return y * s
        return x + y * s

    return jax.tree.map(f, a, b, is_leaf=_is_none)


def mean(trees: Sequence[Pytree]) -> Pytree:
    """Sequential pairwise accumulate + divide over a list of grad trees.

    This is the semantics (not the implementation) of the reference's hub
    all-reduce: ``sync_buffer`` folds the per-device buffers pairwise with
    ``_accum`` on the HOST GPU then divides by N (src/ddp_tasks.jl:93-109);
    the process-DDP hub does the same in ``syncgrads`` (src/sync.jl:58-69).
    On TPU the production path is a single compiled ``psum``/``pmean``; this
    host-side fold exists for tests, debugging, and CPU-only use.
    """
    trees = list(trees)
    if not trees:
        raise ValueError("mean() of an empty list of trees")
    acc = trees[0]
    for t in trees[1:]:
        acc = accum(acc, t)
    return div(acc, float(len(trees)))


# ---------------------------------------------------------------------------
# Structural comparison (test comparator analog, test/runtests.jl:6-41)
# ---------------------------------------------------------------------------


def _leaf_mismatches(a, b, rtol, atol, path, out):
    if a is None and b is None:
        return
    if a is None or b is None:
        out.append((path, "one side is None"))
        return
    x = np.asarray(a)
    y = np.asarray(b)
    if x.shape != y.shape:
        out.append((path, f"shape {x.shape} vs {y.shape}"))
        return
    if not np.allclose(x, y, rtol=rtol, atol=atol):
        err = float(np.max(np.abs(x - y))) if x.size else 0.0
        out.append((path, f"max abs err {err:.3e}"))


def mismatches(a: Pytree, b: Pytree, rtol: float = 1e-4, atol: float = 1e-4):
    """List of ``(path, reason)`` for leaves of ``a`` and ``b`` that differ.

    The reference's test comparator ``compare`` recurses over tuples,
    NamedTuples, arrays (``isapprox`` at rtol=atol=1f-4 — the defaults
    here), ``RefValue`` and arbitrary structs (test/runtests.jl:6-29).
    JAX pytrees subsume all of those container cases.
    """
    la = jax.tree.leaves_with_path(a, is_leaf=_is_none)
    lb = jax.tree.leaves_with_path(b, is_leaf=_is_none)
    out: list[tuple[str, str]] = []
    if len(la) != len(lb):
        return [("<tree>", f"leaf count {len(la)} vs {len(lb)}")]
    for (pa, xa), (pb, xb) in zip(la, lb):
        if pa != pb:
            out.append((jax.tree_util.keystr(pa), f"path mismatch vs {jax.tree_util.keystr(pb)}"))
            continue
        _leaf_mismatches(xa, xb, rtol, atol, jax.tree_util.keystr(pa), out)
    return out


def allclose(a: Pytree, b: Pytree, rtol: float = 1e-4, atol: float = 1e-4) -> bool:
    """True iff every leaf of ``a`` matches ``b`` within tolerance."""
    return not mismatches(a, b, rtol=rtol, atol=atol)


def assert_close(a: Pytree, b: Pytree, rtol: float = 1e-4, atol: float = 1e-4, msg: str = ""):
    """Assert trees match, raising with the offending paths."""
    bad = mismatches(a, b, rtol=rtol, atol=atol)
    if bad:
        lines = "\n".join(f"  {p}: {r}" for p, r in bad[:20])
        more = "" if len(bad) <= 20 else f"\n  ... and {len(bad) - 20} more"
        raise AssertionError(f"trees differ{': ' + msg if msg else ''}\n{lines}{more}")


def getfirst(tree: Pytree, name: str):
    """First leaf (or subtree) reached through a key named ``name``.

    Analog of the reference's test helper ``getfirst`` (test/runtests.jl:37-41)
    which plucks e.g. the first ``:weight`` out of a nested grad tree.
    Matches dict keys and dataclass/NamedTuple field names.
    """
    found: list[Any] = []

    def walk(node):
        if found:
            return
        if isinstance(node, dict):
            for k, v in node.items():
                if found:
                    return
                if k == name:
                    found.append(v)
                    return
                walk(v)
        elif isinstance(node, (list, tuple)):
            for v in node:
                walk(v)
        elif hasattr(node, "_fields"):  # NamedTuple
            for k in node._fields:
                v = getattr(node, k)
                if k == name:
                    found.append(v)
                    return
                walk(v)

    walk(tree)
    return found[0] if found else None


# ---------------------------------------------------------------------------
# Conveniences
# ---------------------------------------------------------------------------


def count_params(tree: Pytree) -> int:
    """Total number of scalar parameters in the tree."""
    return sum(int(np.prod(x.shape)) for x in jax.tree.leaves(tree))


def nbytes(tree: Pytree) -> int:
    """Total bytes across all array leaves."""
    return sum(x.size * x.dtype.itemsize for x in jax.tree.leaves(tree))


def cast(tree: Pytree, dtype) -> Pytree:
    """Cast every floating-point leaf to ``dtype`` (ints/bools untouched)."""

    def f(x):
        if x is None:
            return None
        if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating):
            return jnp.asarray(x, dtype)
        return x

    return jax.tree.map(f, tree, is_leaf=_is_none)


def to_host(tree: Pytree) -> Pytree:
    """Copy every leaf to host memory as numpy arrays.

    Analog of the reference returning ``cpu(m)`` replicas at the end of
    ``train`` (src/ddp_tasks.jl:241-246).
    """
    def f(x):
        if x is None:
            return None
        if isinstance(x, jax.Array) and not x.is_fully_addressable:
            # A cross-process-sharded leaf (multi-host FSDP/TP state):
            # device_get cannot fetch non-addressable shards, so gather
            # the global value collectively.  Every process must reach
            # this point (to_host is already documented as a host-side
            # export, called uniformly at the end of train()).
            from jax.experimental import multihost_utils

            return np.asarray(multihost_utils.process_allgather(x, tiled=True))
        return np.asarray(jax.device_get(x))

    return jax.tree.map(f, tree, is_leaf=_is_none)


def synchronize(tree: Pytree) -> Pytree:
    """Block until every leaf's computation has completed; returns the tree.

    Analog of the reference's ``synchronize()`` shim (src/utils.jl:1-5) —
    on TPU the per-device stream sync becomes ``block_until_ready`` on the
    relevant arrays.  No-op for non-array leaves.
    """
    for x in jax.tree.leaves(tree):
        if hasattr(x, "block_until_ready"):
            x.block_until_ready()
    return tree
