"""Continuous-batching LM inference (the serving half of the north
star): slot-based KV cache engine (dense fixed slots or a paged KV
block pool with chunked prefill and prefix reuse), prefill/decode
scheduler, and a streaming HTTP front end — all requests flow through
a fixed pool of compiled XLA programs."""

from .cache_layout import BlockPool, DenseLayout, PagedLayout
from .engine import DEFAULT_BUCKETS, DEFAULT_KV_BLOCK_SIZE, LMEngine
from .router import (NoReplicaAvailable, Replica, Router, RouterError,
                     SupervisedReplica)
from .scheduler import Draining, QueueFull, Request, Scheduler
from .server import LMServer, serve_lm

__all__ = [
    "BlockPool",
    "DEFAULT_BUCKETS",
    "DEFAULT_KV_BLOCK_SIZE",
    "DenseLayout",
    "Draining",
    "LMEngine",
    "LMServer",
    "NoReplicaAvailable",
    "PagedLayout",
    "QueueFull",
    "Replica",
    "Request",
    "Router",
    "RouterError",
    "Scheduler",
    "SupervisedReplica",
    "serve_lm",
]
