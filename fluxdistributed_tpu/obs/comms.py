"""Collective-traffic ledger: what a compiled step moves over the
interconnect, counted per step per mesh axis.

Every parallelism variant's scaling story is a claim about collectives
— plain DP all-reduces the gradients, ZeRO-1 (arXiv:2004.13336)
replaces that with reduce-scatter + all-gather so the update shards,
pipeline stages ``ppermute`` activations, Ulysses/MoE ``all_to_all``
tokens — but nothing in the repo ever MEASURED those claims.  This
ledger does, at two layers that together cover every variant:

* **jaxpr layer** (:func:`jaxpr_collectives`) — walk the traced
  program (recursing through pjit/scan/cond/while/shard_map/custom-vjp
  sub-jaxprs) counting the explicit collective primitives ``psum`` /
  ``psum_scatter`` / ``all_gather`` / ``all_to_all`` / ``ppermute``
  with their mesh axes straight off the equation params and buffer
  bytes off the avals.  This is the SEMANTIC truth of explicitly-
  written schedules (shard_map variants, the pipeline scan) — e.g. the
  ZeRO-1 shard_map step shows reduce-scatter + all-gather on the
  ``data`` axis where the DP step shows only all-reduce, the paper's
  signature, asserted exactly on the 8-virtual-device CPU mesh.
* **HLO layer** (:func:`hlo_collectives`) — parse the
  post-optimization HLO of the COMPILED executable, where GSPMD
  variants (``spmd="jit"`` DP, fsdp, tp) materialize the collectives
  XLA inserted for them (their jaxprs contain none).  Mesh axes are
  recovered by matching each op's ``replica_groups`` against the
  partitions each axis combination induces on the mesh.

Counting semantics (both layers report PER STEP): a ``scan`` body's
collectives multiply by the trip count; ``cond`` branches merge at the
per-entry MAX (an upper bound — one branch runs per invocation);
``while`` bodies count once (trip count unknowable statically — a
documented lower bound).  Bytes are the collective's buffer size (max
of operand/result bytes — all-gather outputs and reduce-scatter inputs
are the full buffer), not wire bytes: ring-algorithm wire traffic is
``(N-1)/N ×`` buffer per hop and depends on the backend's algorithm
choice, which a static ledger should not guess.

The ledger feeds the ``fdtpu-profile/v2`` artifact next to the memory
model (:mod:`.memstats` compiles each variant once and hands the same
executable to both) and ``bin/fit.py``'s report.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "collective_signature",
    "hlo_collectives",
    "jaxpr_collectives",
    "merge_entries",
    "total_bytes",
]

#: jaxpr primitive name → canonical collective kind (the HLO spelling
#: without dashes, so both layers key identically)
JAXPR_COLLECTIVES = {
    "psum": "all_reduce",
    "pmin": "all_reduce",
    "pmax": "all_reduce",
    "psum_scatter": "reduce_scatter",
    "reduce_scatter": "reduce_scatter",
    "all_gather": "all_gather",
    "all_gather_invariant": "all_gather",
    "all_to_all": "all_to_all",
    "ppermute": "ppermute",
    "pshuffle": "ppermute",
}

#: HLO opcode → canonical kind
HLO_COLLECTIVES = {
    "all-reduce": "all_reduce",
    "all-gather": "all_gather",
    "reduce-scatter": "reduce_scatter",
    "all-to-all": "all_to_all",
    "collective-permute": "ppermute",
}

_HLO_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "f8e4m3fn": 1, "f8e5m2": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}


def _aval_bytes(aval) -> int:
    shape = getattr(aval, "shape", None)
    dtype = getattr(aval, "dtype", None)
    if shape is None or dtype is None:
        return 0
    import jax.numpy as jnp

    n = 1
    for d in shape:
        try:
            n *= int(d)
        except TypeError:  # polymorphic dim — skip, bytes stay honest-0
            return 0
    return n * jnp.dtype(dtype).itemsize


def _eqn_axes(eqn) -> Optional[Tuple[str, ...]]:
    """The mesh axis names a collective equation runs over (None when
    the primitive carries none — e.g. a constant-folded psum)."""
    axes = eqn.params.get("axes", None)
    if axes is None:
        axes = eqn.params.get("axis_name", None)
    if axes is None:
        return None
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    named = tuple(str(a) for a in axes if isinstance(a, str))
    return named or None


def _sub_jaxprs(eqn):
    """Every sub-jaxpr in an equation's params (pjit jaxpr, scan body,
    cond branches, while cond/body, custom-vjp call_jaxpr, remat, ...),
    labeled so branch alternatives can merge at max instead of sum."""
    from jax.core import ClosedJaxpr, Jaxpr

    def _as_jaxpr(v):
        if isinstance(v, ClosedJaxpr):
            return v.jaxpr
        if isinstance(v, Jaxpr):
            return v
        return None

    branches, bodies = [], []
    for key, v in eqn.params.items():
        j = _as_jaxpr(v)
        if j is not None:
            bodies.append(j)
            continue
        if isinstance(v, (tuple, list)):
            subs = [s for s in (_as_jaxpr(b) for b in v) if s is not None]
            if not subs:
                continue
            if key == "branches":
                branches.extend(subs)
            else:
                bodies.extend(subs)
    return bodies, branches


_Key = Tuple[str, Optional[Tuple[str, ...]]]


def _merge_max(dst: Dict[_Key, dict], src: Dict[_Key, dict]) -> None:
    for k, v in src.items():
        cur = dst.get(k)
        if cur is None or (v["count"], v["bytes"]) > (cur["count"],
                                                      cur["bytes"]):
            dst[k] = v


def _walk(jaxpr, mult: int, acc: Dict[_Key, dict]) -> None:
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        kind = JAXPR_COLLECTIVES.get(name)
        if kind is not None:
            per_call = max(
                [_aval_bytes(v.aval) for v in
                 list(eqn.invars) + list(eqn.outvars)] or [0])
            key = (kind, _eqn_axes(eqn))
            cell = acc.setdefault(
                key, {"count": 0, "bytes": 0, "bytes_per_call": per_call})
            cell["count"] += mult
            cell["bytes"] += mult * per_call
            cell["bytes_per_call"] = max(cell["bytes_per_call"], per_call)
        bodies, branches = _sub_jaxprs(eqn)
        sub_mult = mult
        if name == "scan":
            sub_mult = mult * int(eqn.params.get("length", 1))
        for b in bodies:
            _walk(b, sub_mult, acc)
        if branches:
            # one branch executes per invocation: merge alternatives at
            # the per-entry max (upper bound), never the sum — the
            # cond-skipped pipeline chunks would otherwise double-count
            merged: Dict[_Key, dict] = {}
            for b in branches:
                one: Dict[_Key, dict] = {}
                _walk(b, sub_mult, one)
                _merge_max(merged, one)
            for k, v in merged.items():
                cell = acc.setdefault(
                    k, {"count": 0, "bytes": 0, "bytes_per_call": 0})
                cell["count"] += v["count"]
                cell["bytes"] += v["bytes"]
                cell["bytes_per_call"] = max(cell["bytes_per_call"],
                                             v["bytes_per_call"])


def _entries(acc: Dict[_Key, dict]) -> List[dict]:
    out = []
    for (kind, axes), cell in sorted(
            acc.items(), key=lambda kv: (kv[0][0], kv[0][1] or ())):
        out.append({
            "kind": kind,
            "axes": list(axes) if axes else None,
            "count": int(cell["count"]),
            "bytes": int(cell["bytes"]),
            "bytes_per_call": int(cell["bytes_per_call"]),
        })
    return out


def jaxpr_collectives(fn, args: Tuple[Any, ...]) -> List[dict]:
    """Static per-step collective ledger of ``fn`` at ``args`` from the
    traced jaxpr (see module doc for counting semantics).  Entries::

        {"kind": "all_reduce" | "all_gather" | "reduce_scatter" |
                 "all_to_all" | "ppermute",
         "axes": ["data"] | None,   # mesh axes, None = not recorded
         "count": N,                # calls per step
         "bytes": B,                # Σ buffer bytes over those calls
         "bytes_per_call": B1}      # largest single buffer

    GSPMD-partitioned programs (``spmd="jit"`` dp, fsdp, tp) trace to
    jaxprs with NO explicit collectives — XLA inserts them at compile
    time; use :func:`hlo_collectives` on the compiled executable for
    those.  Tracing is abstract: nothing executes, nothing compiles."""
    import jax

    closed = jax.make_jaxpr(lambda *a: fn(*a))(*args)
    acc: Dict[_Key, dict] = {}
    _walk(closed.jaxpr, 1, acc)
    return _entries(acc)


# -- HLO layer --------------------------------------------------------------

_HLO_OP_RE = re.compile(
    r"=\s*(?P<type>\([^)]*\)|\S+)\s+"
    r"(?P<op>all-reduce|all-gather|reduce-scatter|all-to-all|"
    r"collective-permute)(?P<suffix>-start|-done)?\(")
_HLO_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")
_HLO_GROUPS_RE = re.compile(r"replica_groups=\{(\{[^}]*\}(?:,\{[^}]*\})*)\}")
# iota form: [G,K]<=[d0,d1,...] optionally T(perm) — arange(prod(dims))
# reshaped to dims, transposed by perm, flattened, dealt into G rows of K
_HLO_IOTA_RE = re.compile(
    r"replica_groups=\[(\d+),(\d+)\]<=\[([\d,]+)\](?:T\(([\d,]+)\))?")


def _type_bytes(type_str: str) -> int:
    total = 0
    for dtype, dims in _HLO_SHAPE_RE.findall(type_str):
        size = _HLO_DTYPE_BYTES.get(dtype)
        if size is None:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * size
    return total


def _parse_groups(line: str,
                  nworld: int = 0) -> Optional[List[Tuple[int, ...]]]:
    m = _HLO_GROUPS_RE.search(line)
    if m:
        return [tuple(int(x) for x in grp.split(",") if x)
                for grp in re.findall(r"\{([^}]*)\}", m.group(1))]
    m = _HLO_IOTA_RE.search(line)
    if m:
        import numpy as np

        g, k = int(m.group(1)), int(m.group(2))
        dims = [int(x) for x in m.group(3).split(",")]
        ids = np.arange(int(np.prod(dims)))
        if g * k == ids.size:
            if m.group(4):
                perm = [int(x) for x in m.group(4).split(",")]
                ids = ids.reshape(dims).transpose(perm).reshape(-1)
            return [tuple(int(x) for x in row)
                    for row in ids.reshape(g, k)]
    if "replica_groups={}" in line and nworld:
        # the empty-group spelling means "all devices, one group"
        return [tuple(range(nworld))]
    return None


def _axis_groups(mesh) -> Dict[Tuple[str, ...], frozenset]:
    """For each non-empty axis combination of ``mesh``: the partition
    of LOGICAL device ids (positions in ``mesh.devices.flat`` — the
    executable's partition-id order) into groups that vary over those
    axes with the others held fixed."""
    import itertools

    import numpy as np

    names = tuple(mesh.axis_names)
    shape = tuple(int(mesh.shape[n]) for n in names)
    ids = np.arange(int(np.prod(shape))).reshape(shape)
    out: Dict[Tuple[str, ...], frozenset] = {}
    for r in range(1, len(names) + 1):
        for combo in itertools.combinations(range(len(names)), r):
            moved = np.moveaxis(ids, combo, range(len(shape) - r,
                                                  len(shape)))
            flat = moved.reshape(-1, int(np.prod(
                [shape[i] for i in combo])))
            out[tuple(names[i] for i in combo)] = frozenset(
                frozenset(int(x) for x in row) for row in flat)
    return out


def hlo_collectives(compiled, mesh=None) -> List[dict]:
    """Collective ledger off a COMPILED executable's post-optimization
    HLO — the layer that sees what GSPMD inserted.  Same entry layout
    as :func:`jaxpr_collectives`; ``axes`` is recovered by matching
    each op's ``replica_groups`` against the partitions every axis
    combination of ``mesh`` induces (None when no mesh was given, the
    groups match no axis combination, or the op carries no groups —
    ``collective-permute`` uses ``source_target_pairs``; the jaxpr
    layer attributes those).  Async pairs count at the ``-start`` op;
    ``-done`` ops are skipped.

    Counting caveat: this layer counts op SITES in the optimized
    program text — a collective inside an HLO ``while`` body counts
    once, however many iterations run.  For GSPMD variants (no loops)
    sites equal per-step executions; for scanned schedules (pipeline)
    the jaxpr layer's trip-count-multiplied numbers are the per-step
    truth."""
    text = compiled.as_text()
    if not isinstance(text, str):  # some builds return a list of modules
        text = "\n".join(str(t) for t in text)
    by_axes = _axis_groups(mesh) if mesh is not None else {}
    nworld = int(mesh.devices.size) if mesh is not None else 0
    acc: Dict[_Key, dict] = {}
    for line in text.splitlines():
        m = _HLO_OP_RE.search(line)
        if m is None or m.group("suffix") == "-done":
            continue
        kind = HLO_COLLECTIVES[m.group("op")]
        per_call = _type_bytes(m.group("type"))
        axes: Optional[Tuple[str, ...]] = None
        groups = _parse_groups(line, nworld)
        if groups is not None and by_axes:
            gset = frozenset(frozenset(g) for g in groups)
            for combo, expected in by_axes.items():
                if gset == expected:
                    axes = combo
                    break
        key = (kind, axes)
        cell = acc.setdefault(
            key, {"count": 0, "bytes": 0, "bytes_per_call": 0})
        cell["count"] += 1
        cell["bytes"] += per_call
        cell["bytes_per_call"] = max(cell["bytes_per_call"], per_call)
    return _entries(acc)


# -- rollups ---------------------------------------------------------------

def collective_signature(entries: Sequence[dict]) -> Dict[str, int]:
    """``{kind: total count}`` — the shape tests pin ("zero1 =
    reduce_scatter + all_gather where dp = all_reduce only")."""
    out: Dict[str, int] = {}
    for e in entries:
        out[e["kind"]] = out.get(e["kind"], 0) + int(e["count"])
    return out


def merge_entries(*entry_lists: Sequence[dict]) -> List[dict]:
    """Sum several ledgers (e.g. a serve engine's program pool) into
    one, keyed on (kind, axes)."""
    acc: Dict[_Key, dict] = {}
    for entries in entry_lists:
        for e in entries:
            key = (e["kind"], tuple(e["axes"]) if e.get("axes") else None)
            cell = acc.setdefault(
                key, {"count": 0, "bytes": 0, "bytes_per_call": 0})
            cell["count"] += int(e["count"])
            cell["bytes"] += int(e["bytes"])
            cell["bytes_per_call"] = max(cell["bytes_per_call"],
                                         int(e.get("bytes_per_call", 0)))
    return _entries(acc)


def total_bytes(entries: Sequence[dict]) -> int:
    """Σ buffer bytes a step moves through collectives."""
    return sum(int(e["bytes"]) for e in entries)
