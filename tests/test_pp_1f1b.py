"""Hand-scheduled 1F1B pipeline: schedule proofs + gradient parity.

Invariants: the static lockstep timetable hits the canonical
2(M+S-1) ticks with every action placed (the builder additionally
asserts latch/ring safety internally); the full 1F1B fwd+bwd program
reproduces ``jax.grad`` of the unpipelined composition — loss, stage
grads, and outer (embed/head) grads — for M < S, M = S, and M > S
(ring-slot reuse); the compiled train step trains; and the LM wiring
(``lm_pp_1f1b``) matches the plain ``TransformerLM`` loss/grads,
including chunked virtual stages (V > 1) and tied embeddings.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# tier-2 (slow): 27 pipeline-schedule compiles on the 8-device mesh — the tier-1 iteration loop must fit the
# 870s verify window (ROADMAP); CI's slow job still runs this file
pytestmark = pytest.mark.slow

from fluxdistributed_tpu import mesh as mesh_lib, optim
from fluxdistributed_tpu.parallel.dp import TrainState
from fluxdistributed_tpu.parallel.pp import stack_stage_params
from fluxdistributed_tpu.parallel.pp_1f1b import (
    build_schedule,
    make_train_step_1f1b,
    pipeline_grads_1f1b,
)

S = 4
D = 16
DIN = 8
NCLS = 6


@pytest.fixture(scope="module")
def mesh():
    return mesh_lib.make_mesh({"pipe": S})


# ---- schedule ----

@pytest.mark.parametrize("s,m", [(2, 1), (2, 4), (4, 2), (4, 4), (4, 16), (8, 8), (8, 32)])
def test_schedule_ticks_and_counts(s, m):
    sched = build_schedule(s, m)
    assert sched.ticks == 2 * (m + s - 1)
    # every device performs exactly M forwards and M backwards
    assert (sched.is_fwd.sum(axis=0) == m).all()
    assert (sched.is_bwd.sum(axis=0) == m).all()
    # one action per device per tick
    assert not (sched.is_fwd & sched.is_bwd).any()


def test_schedule_render_and_memory_fields():
    sched = build_schedule(4, 8)
    text = sched.render()
    # canonical facts visible in the rendering
    assert "S=4 M=8 V=1 T=22" in text
    assert text.count("\n") == 4  # header + one row per device
    assert "F7" in text and "B7" in text
    # 1F1B memory bound: in-flight never exceeds min(S, M)
    assert sched.max_in_flight <= 4
    # interleaved render uses chunk-qualified cells
    assert "f1:" in build_schedule(4, 4, 2).render()


@pytest.mark.parametrize("s,m,v", [(4, 4, 2), (4, 8, 2), (8, 8, 2), (4, 8, 4), (2, 4, 3)])
def test_interleaved_schedule_beats_blocked(s, m, v):
    """Interleaving exists to shrink the bubble: at these (moderate-M)
    shapes the chosen timetable must beat the blocked-placement
    utilization M/(M+S-1).  (At very large M blocked is already
    amortized and interleave stops paying — not asserted.)"""
    sched = build_schedule(s, m, v)
    assert (sched.is_fwd.sum(axis=0) == v * m).all()
    assert (sched.is_bwd.sum(axis=0) == v * m).all()
    assert not (sched.is_fwd & sched.is_bwd).any()
    assert sched.utilization > m / (m + s - 1), (
        sched.utilization, m / (m + s - 1))


# ---- toy pipeline: grads vs the unpipelined composition ----

def stage_fn(params, x):
    return x + jax.nn.gelu(x @ params["w"] + params["b"])


def embed_fn(outer, xin):
    return jnp.tanh(xin @ outer["w_in"])


def head_fn(outer, y, labels):
    logits = y @ outer["w_out"]
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.sum(labels * logp, axis=-1))


def _params(key):
    ks = jax.random.split(key, 2 + S)
    outer = {
        "w_in": jax.random.normal(ks[0], (DIN, D), jnp.float32) * 0.4,
        "w_out": jax.random.normal(ks[1], (D, NCLS), jnp.float32) * 0.4,
    }
    per_stage = [
        {
            "w": jax.random.normal(k, (D, D), jnp.float32) * 0.3,
            "b": jnp.zeros((D,), jnp.float32),
        }
        for k in ks[2:]
    ]
    return outer, per_stage


def _reference_loss(outer, per_stage, x, labels, m):
    """Mean over microbatches of the per-microbatch loss — the exact
    quantity the pipeline computes."""
    xs = x.reshape(m, x.shape[0] // m, *x.shape[1:])
    ls = labels.reshape(m, labels.shape[0] // m, *labels.shape[1:])

    def one(x_mb, l_mb):
        h = embed_fn(outer, x_mb)
        for p in per_stage:
            h = stage_fn(p, h)
        return head_fn(outer, h, l_mb)

    return jnp.mean(jax.vmap(one)(xs, ls))


@pytest.mark.parametrize("m", [2, 4, 8, 16])
def test_1f1b_matches_unpipelined_grads(mesh, m):
    outer, per_stage = _params(jax.random.PRNGKey(0))
    rng = np.random.default_rng(1)
    n = 16
    x = jnp.asarray(rng.normal(0, 1, (n, DIN)).astype(np.float32))
    y = rng.integers(0, NCLS, n)
    labels = jnp.asarray(np.eye(NCLS, dtype=np.float32)[y])

    run = pipeline_grads_1f1b(stage_fn, embed_fn, head_fn, mesh, num_microbatches=m)
    stacked = stack_stage_params(per_stage, mesh)
    loss, g_stages, g_outer = jax.jit(run)(stacked, outer, x, labels)

    ref = jax.value_and_grad(_reference_loss, argnums=(0, 1))
    loss_ref, (go_ref, gs_ref) = ref(outer, per_stage, x, labels, m)
    gs_ref_stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *gs_ref)

    np.testing.assert_allclose(float(loss), float(loss_ref), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(g_stages), jax.tree.leaves(gs_ref_stacked)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)
    for a, b in zip(jax.tree.leaves(g_outer), jax.tree.leaves(go_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


@pytest.mark.parametrize("m,v", [(4, 2), (8, 2), (8, 3), (4, 4)])
def test_interleaved_matches_unpipelined_grads(mesh, m, v):
    """interleave=V: logical stage c·S+i on (device i, chunk c); grads
    must still equal jax.grad of the V·S-deep unpipelined composition."""
    outer, _ = _params(jax.random.PRNGKey(7))
    keys = jax.random.split(jax.random.PRNGKey(8), v * S)
    logical = [
        {
            "w": jax.random.normal(k, (D, D), jnp.float32) * 0.2,
            "b": jnp.zeros((D,), jnp.float32),
        }
        for k in keys
    ]
    rng = np.random.default_rng(9)
    n = 16
    x = jnp.asarray(rng.normal(0, 1, (n, DIN)).astype(np.float32))
    labels = jnp.asarray(
        np.eye(NCLS, dtype=np.float32)[rng.integers(0, NCLS, n)])

    # device i's (V, ...) chunk tree: logical stages c*S + i
    per_device = [
        jax.tree.map(lambda *xs: jnp.stack(xs), *[logical[c * S + i] for c in range(v)])
        for i in range(S)
    ]
    stacked = stack_stage_params(per_device, mesh)
    run = pipeline_grads_1f1b(
        stage_fn, embed_fn, head_fn, mesh, num_microbatches=m, interleave=v)
    loss, g_stages, g_outer = jax.jit(run)(stacked, outer, x, labels)

    def ref_loss(outer_, logical_):
        return _reference_loss(outer_, logical_, x, labels, m)

    loss_ref, (go_ref, gl_ref) = jax.value_and_grad(
        ref_loss, argnums=(0, 1))(outer, logical)
    np.testing.assert_allclose(float(loss), float(loss_ref), rtol=1e-5)
    # re-pack the reference logical-stage grads into the (S, V, ...) layout
    want = jax.tree.map(
        lambda *xs: jnp.stack(xs),
        *[jax.tree.map(lambda *cs: jnp.stack(cs),
                       *[gl_ref[c * S + i] for c in range(v)])
          for i in range(S)])
    for a, b in zip(jax.tree.leaves(g_stages), jax.tree.leaves(want)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)
    for a, b in zip(jax.tree.leaves(g_outer), jax.tree.leaves(go_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


def test_1f1b_dp_composition(mesh):
    """(data, pipe) mesh: per-data-row pipelines + grad mean over rows
    equal the single-row result on the same global batch."""
    mesh2 = mesh_lib.make_mesh({"data": 2, "pipe": S})
    outer, per_stage = _params(jax.random.PRNGKey(2))
    rng = np.random.default_rng(3)
    n, m = 16, 4
    x = jnp.asarray(rng.normal(0, 1, (n, DIN)).astype(np.float32))
    y = rng.integers(0, NCLS, n)
    labels = jnp.asarray(np.eye(NCLS, dtype=np.float32)[y])

    run2 = pipeline_grads_1f1b(
        stage_fn, embed_fn, head_fn, mesh2, num_microbatches=m, batch_axis="data"
    )
    stacked2 = stack_stage_params(per_stage, mesh2, "pipe")
    loss2, gs2, go2 = jax.jit(run2)(stacked2, outer, x, labels)

    # reference: mean over the two data shards of the per-shard quantity
    halves = [(x[:8], labels[:8]), (x[8:], labels[8:])]
    ref = jax.value_and_grad(_reference_loss, argnums=(0, 1))
    accs = [ref(outer, per_stage, xh, lh, m) for xh, lh in halves]
    loss_ref = np.mean([float(a[0]) for a in accs])
    np.testing.assert_allclose(float(loss2), loss_ref, rtol=1e-5)
    go_ref = jax.tree.map(lambda a, b: (a + b) / 2, accs[0][1][0], accs[1][1][0])
    for a, b in zip(jax.tree.leaves(go2), jax.tree.leaves(go_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


def test_1f1b_train_step_loss_falls(mesh):
    rng = np.random.default_rng(0)
    n = 32
    y = rng.integers(0, 2, n)
    x = rng.normal(0, 0.3, (n, DIN)).astype(np.float32)
    x[:, 0] += y * 2.0
    labels = np.eye(NCLS, dtype=np.float32)[y]

    outer, per_stage = _params(jax.random.PRNGKey(4))
    params = {"outer": outer, "stages": stack_stage_params(per_stage, mesh)}
    opt = optim.momentum(0.1, 0.9)
    state = TrainState.create(params, opt)
    compile_for = make_train_step_1f1b(
        stage_fn, embed_fn, head_fn, opt, mesh,
        num_microbatches=8, donate=False,
        input_key="x", label_key="label",
    )
    step = compile_for(state)
    batch = {"x": jnp.asarray(x), "label": jnp.asarray(labels)}
    losses = []
    for _ in range(25):
        state, mtr = step(state, batch)
        losses.append(float(mtr["loss"]))
    assert losses[-1] < losses[0] * 0.6, losses[::8]
    assert int(state.step) == 25


# ---- LM wiring ----

def _lm_parity(depth, interleave=False, boundaries=None):
    from fluxdistributed_tpu.models.transformer_lm import (
        TransformerLM, lm_pp_1f1b, next_token_loss,
    )

    mesh = mesh_lib.make_mesh({"pipe": S})
    model = TransformerLM(
        vocab=64, dim=32, depth=depth, num_heads=2, mlp_dim=64,
        dtype=jnp.float32, dropout=0.0,
    )
    rng = np.random.default_rng(5)
    m = 4
    toks = jnp.asarray(rng.integers(0, 64, (8, 16)).astype(np.int32))
    params = model.init(jax.random.PRNGKey(0), toks[:1], train=False)["params"]

    w = lm_pp_1f1b(model, mesh, interleave=interleave, boundaries=boundaries)
    run = pipeline_grads_1f1b(
        *w.fns, mesh, num_microbatches=m, interleave=w.interleave,
    )
    split_params = w.split_params
    sp = split_params(params)
    loss, g_stages, g_outer = jax.jit(run)(sp["stages"], sp["outer"], toks, toks)

    def ref_loss(p):
        logits = model.apply({"params": p}, toks, train=False)
        return next_token_loss(jnp.asarray(logits, jnp.float32), toks)

    loss_ref, g_ref = jax.value_and_grad(ref_loss)(params)
    # per-microbatch mean-of-means == global mean (equal-size microbatches)
    np.testing.assert_allclose(float(loss), float(loss_ref), rtol=1e-5)
    # rebuild the split view of the reference grads via the same splitter
    want = split_params(g_ref)
    for a, b in zip(jax.tree.leaves(g_stages), jax.tree.leaves(want["stages"])):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5)
    for a, b in zip(jax.tree.leaves(g_outer), jax.tree.leaves(want["outer"])):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5)


def test_lm_1f1b_matches_model(mesh):
    _lm_parity(depth=S)


def test_lm_1f1b_chunked_virtual_stages(mesh):
    _lm_parity(depth=2 * S)  # V = 2 logical blocks per pipe device


def test_lm_1f1b_interleaved_virtual_stages(mesh):
    _lm_parity(depth=2 * S, interleave=True)  # Megatron placement, V = 2


def test_lm_1f1b_planned_boundaries(mesh):
    """Planner-placed non-uniform split (depth 6 over 4 devices via the
    padded, cond-skipped chunk scan) still reproduces jax.grad of the
    plain model — the split tree pads grads with zeros identically."""
    _lm_parity(depth=6, boundaries=(0, 1, 3, 5, 6))


def test_gpipe_checkpoint_restores_into_1f1b(mesh, tmp_path):
    """The interchangeability claim, proven: a TrainState saved from a
    GPipe (lm_pp) run restores through orbax into the 1F1B step — same
    split tree, same shardings — and training continues (loss keeps
    falling, step counter resumes)."""
    from fluxdistributed_tpu.models.transformer_lm import TransformerLM, lm_pp, lm_pp_1f1b
    from fluxdistributed_tpu.parallel import make_train_step
    from fluxdistributed_tpu.parallel.pp_1f1b import make_train_step_1f1b
    from fluxdistributed_tpu.train.checkpoint import load_checkpoint, save_checkpoint

    from fluxdistributed_tpu import sharding as sharding_lib

    mesh2 = mesh_lib.make_mesh({"data": 2, "pipe": S})
    model = TransformerLM(
        vocab=64, dim=32, depth=S, num_heads=2, mlp_dim=64,
        dtype=jnp.float32, dropout=0.0,
    )
    rng = np.random.default_rng(11)
    start = rng.integers(0, 32, (8, 1)).astype(np.int32)
    toks = jnp.asarray((start + np.arange(16)[None, :]) % 32, jnp.int32)
    params = model.init(jax.random.PRNGKey(0), toks[:1], train=False)["params"]
    opt = optim.adamw(3e-3)

    # GPipe leg: the framework loss_fn through the generic jit step on a
    # (data, pipe) mesh (the lm_pp composition pattern)
    split_params, loss_fn, state_shardings = lm_pp(
        model, mesh2, batch_axis="data", num_microbatches=4)
    g_state = TrainState.create(split_params(params), opt)
    sh = state_shardings(g_state)
    g_state = jax.tree.map(jax.device_put, g_state, sh)
    g_step = make_train_step(
        loss_fn, opt, mesh2, axis="data", donate=False, state_shardings=sh,
    )
    batch = sharding_lib.shard_batch({"tokens": toks}, mesh2, axis="data")
    for _ in range(5):
        g_state, gm = g_step(g_state, batch)
    save_checkpoint(g_state, str(tmp_path), step=int(g_state.step))

    # 1F1B leg: restore the SAME tree and continue
    w = lm_pp_1f1b(model, mesh2)
    f_state = load_checkpoint(str(tmp_path), target=g_state, mesh=mesh2)
    assert int(f_state.step) == 5
    f_step = make_train_step_1f1b(
        *w.fns, opt, mesh2, num_microbatches=4, batch_axis="data",
        interleave=w.interleave, donate=False,
    )(f_state)
    losses = []
    for _ in range(10):
        f_state, fm = f_step(f_state, batch)
        losses.append(float(fm["loss"]))
    assert int(f_state.step) == 15
    # continuation, not restart: the restored optimizer state keeps the
    # loss moving down from where GPipe left it
    assert losses[-1] < float(gm["loss"]), (losses, float(gm["loss"]))
