"""Cross-run history ledger + regression gating (``runs.jsonl``).

The within-run layers (metrics, spans, profile, memstats, flight
recorder) each explain ONE process.  This module is the memory ACROSS
processes: an append-only JSONL ledger with one record per training
run, bench round, multichip probe or supervisor episode, keyed by the
topology fingerprint (:func:`..compilation.topology_fingerprint` — the
same digest the AOT cache and profile artifacts already use), carrying
the phase reached, the retryable verdict, every forensic stamp the
bench JSON grew (lint/guard/memory/layout_pick), the throughput and
compile-seconds truths, and the path of the flight dump that can
explain the record in step-level detail.

Consumers (``bin/trends.py``):

* **trend tables** — per (metric, topology) history with a rolling
  baseline, so the first green hardware number lands as a defended
  trend row, not a lone point (ROADMAP item 1);
* **regression gating** — the newest value of each metric is compared
  against the rolling **median** of its per-topology predecessors with
  a per-metric tolerance; ``--check`` exits non-zero for CI.  Memory-
  baseline semantics apply: a metric *shrinking* past tolerance in the
  good direction is a NOTE (re-record the baseline), never a failure —
  only movement in the bad direction gates;
* **postmortems** — :func:`postmortem_timeline` merges the newest
  flight dump, the supervisor's episode ledger and the bench status
  file into one human-readable account of how a round died.

Records never lie by omission: a round that died carries ``error`` and
is excluded from baselines (a dead round's 0.0 img/s is not a
throughput observation), but stays in the ledger forever — the five
dead hardware rounds are rows 1-10 (``bin/trends.py --ingest``).
"""

from __future__ import annotations

import json
import math
import os
import statistics
import sys
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "RUNS_SCHEMA",
    "METRIC_SPECS",
    "run_record",
    "append_run",
    "load_runs",
    "check_regressions",
    "trend_table",
    "render_runs",
    "ingest_round_file",
    "ingest_paths",
    "postmortem_timeline",
    "set_run_info",
]

#: ledger record schema tag (every record carries it)
RUNS_SCHEMA = "fdtpu-runs/v1"

#: the gated metrics: direction + relative tolerance per metric.
#: ``higher_is_better`` decides which direction FAILS; movement past
#: tolerance in the good direction is a note (memory-baseline
#: semantics — re-record, don't gate).  Movement exactly AT tolerance
#: passes: the gate trips strictly beyond it.
METRIC_SPECS: Dict[str, Dict[str, Any]] = {
    "throughput": {"higher_is_better": True, "tolerance": 0.10},
    "mfu_pct": {"higher_is_better": True, "tolerance": 0.15},
    "steps_per_sec": {"higher_is_better": True, "tolerance": 0.10},
    "compile_seconds": {"higher_is_better": False, "tolerance": 0.50},
    "peak_hbm_bytes": {"higher_is_better": False, "tolerance": 0.10},
}


# ---------------------------------------------------------------------------
# records
# ---------------------------------------------------------------------------


def run_record(
    kind: str,
    *,
    fingerprint: Optional[str] = None,
    phase: Optional[str] = None,
    retryable: Optional[bool] = None,
    error: Optional[str] = None,
    metrics: Optional[Dict[str, float]] = None,
    stamps: Optional[Dict[str, Any]] = None,
    flight: Optional[str] = None,
    source: Optional[str] = None,
    ts: Optional[float] = None,
    **extra,
) -> dict:
    """Build one normalized ledger record.

    ``kind`` is the producer (``train`` / ``bench`` / ``multichip`` /
    ``episode``); ``metrics`` holds only FINITE numbers (everything
    else is dropped — NaN in a baseline poisons every later median);
    ``error`` marks the record dead for baseline purposes while keeping
    it forever as history.
    """
    clean_metrics: Dict[str, float] = {}
    for k, v in (metrics or {}).items():
        try:
            fv = float(v)
        except (TypeError, ValueError):
            continue
        if math.isfinite(fv):
            clean_metrics[k] = fv
    rec: dict = {
        "schema": RUNS_SCHEMA,
        "kind": str(kind),
        "ts": round(float(ts) if ts is not None else time.time(), 3),
        "fingerprint": fingerprint,
        "metrics": clean_metrics,
    }
    if phase is not None:
        rec["phase"] = phase
    if retryable is not None:
        rec["retryable"] = bool(retryable)
    if error:
        rec["error"] = str(error)[:500]
    if stamps:
        rec["stamps"] = stamps
    if flight:
        rec["flight"] = flight
    if source:
        rec["source"] = source
    rec.update(extra)
    return rec


def append_run(path: str, record: dict) -> bool:
    """Append one record as a JSON line, durably (flush + fsync).
    Best-effort by contract — the ledger must never be the reason a
    run, a bench round or a supervisor dies — so failures warn on
    stderr and return False instead of raising."""
    try:
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "a") as f:
            f.write(json.dumps(record, sort_keys=True) + "\n")
            f.flush()
            os.fsync(f.fileno())
        return True
    except Exception as e:  # noqa: BLE001 — the ledger is forensics
        print(f"obs.runs: append to {path} failed: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return False


def load_runs(path: str) -> List[dict]:
    """Read a ledger tolerantly: unparseable lines (a torn tail from a
    kill mid-append) are skipped, not fatal — this reader exists for
    exactly the files crashes leave behind."""
    out: List[dict] = []
    try:
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    obj = json.loads(line)
                except (json.JSONDecodeError, ValueError):
                    continue
                if isinstance(obj, dict):
                    out.append(obj)
    except OSError:
        return []
    return out


# ---------------------------------------------------------------------------
# regression gating
# ---------------------------------------------------------------------------


def _series(runs: Sequence[dict], metric: str) -> Dict[str, List[float]]:
    """Per-fingerprint value series (ledger order) of one metric over
    the runs that can honestly testify: records carrying ``error`` are
    history, not observations."""
    groups: Dict[str, List[float]] = {}
    for rec in runs:
        if rec.get("error"):
            continue
        v = (rec.get("metrics") or {}).get(metric)
        if not isinstance(v, (int, float)) or not math.isfinite(v):
            continue
        fp = rec.get("fingerprint") or "unknown"
        groups.setdefault(fp, []).append(float(v))
    return groups


def check_regressions(
    runs: Sequence[dict],
    specs: Optional[Dict[str, Dict[str, Any]]] = None,
    window: int = 5,
) -> dict:
    """Gate the NEWEST value of each (metric, topology) series against
    the rolling median of up to ``window`` predecessors.

    Returns ``{"failures", "notes", "rows"}``.  Failures are movement
    strictly beyond tolerance in the BAD direction (below for
    higher-is-better metrics, above for lower-is-better).  Notes cover
    everything an operator should see but CI must not gate on: a
    topology with no baseline yet (first run / unknown fingerprint),
    and movement past tolerance in the GOOD direction — the
    memory-baseline semantics, where a shrink means "re-record the
    baseline", not "fail the build".
    """
    specs = specs if specs is not None else METRIC_SPECS
    failures: List[str] = []
    notes: List[str] = []
    rows: List[dict] = []
    for metric, spec in specs.items():
        hib = bool(spec.get("higher_is_better", True))
        tol = float(spec.get("tolerance", 0.10))
        for fp, vals in sorted(_series(runs, metric).items()):
            short = fp[:12]
            if len(vals) < 2:
                notes.append(
                    f"{metric}@{short}: no baseline yet "
                    f"({len(vals)} observation) — first run on this "
                    "topology, nothing to gate against")
                rows.append({"metric": metric, "fingerprint": fp,
                             "n": len(vals), "newest": vals[-1],
                             "baseline": None, "verdict": "no-baseline"})
                continue
            newest = vals[-1]
            base_vals = vals[max(0, len(vals) - 1 - window):-1]
            baseline = statistics.median(base_vals)
            row = {"metric": metric, "fingerprint": fp, "n": len(vals),
                   "newest": newest, "baseline": baseline,
                   "tolerance": tol, "verdict": "ok"}
            if baseline == 0:
                row["verdict"] = "zero-baseline"
                notes.append(f"{metric}@{short}: zero baseline — "
                             "cannot express a relative tolerance")
                rows.append(row)
                continue
            ratio = newest / baseline
            # strictly beyond tolerance trips; exactly AT passes
            eps = 1e-12
            worse = ratio < (1 - tol) - eps if hib else (
                ratio > (1 + tol) + eps)
            better = ratio > (1 + tol) + eps if hib else (
                ratio < (1 - tol) - eps)
            if worse:
                row["verdict"] = "regression"
                failures.append(
                    f"{metric}@{short}: {newest:g} vs baseline "
                    f"{baseline:g} (x{ratio:.3f}) — beyond the "
                    f"{tol:.0%} tolerance in the bad direction")
            elif better:
                row["verdict"] = "improved"
                notes.append(
                    f"{metric}@{short}: {newest:g} vs baseline "
                    f"{baseline:g} (x{ratio:.3f}) — moved past "
                    f"tolerance in the GOOD direction; re-record the "
                    "baseline (memory-baseline semantics, not a "
                    "failure)")
            rows.append(row)
    return {"failures": failures, "notes": notes, "rows": rows}


def trend_table(runs: Sequence[dict], window: int = 5,
                specs: Optional[Dict[str, Dict[str, Any]]] = None) -> str:
    """Render the per-(metric, topology) trend rows as a text table."""
    verdicts = check_regressions(runs, specs=specs, window=window)
    lines = [f"{'metric':<18} {'topology':<14} {'n':>3} "
             f"{'baseline':>12} {'newest':>12} verdict",
             "-" * 72]
    for r in verdicts["rows"]:
        base = "-" if r.get("baseline") is None else f"{r['baseline']:g}"
        lines.append(
            f"{r['metric']:<18} {(r['fingerprint'] or 'unknown')[:12]:<14} "
            f"{r['n']:>3} {base:>12} {r['newest']:>12g} {r['verdict']}")
    if not verdicts["rows"]:
        lines.append("(no gateable observations yet — every record "
                     "carries an error, or no metrics matched)")
    return "\n".join(lines)


def render_runs(runs: Sequence[dict], limit: int = 20) -> str:
    """Render the newest ``limit`` ledger records, one line each."""
    lines = []
    for rec in runs[-limit:]:
        ts = time.strftime("%Y-%m-%d %H:%M",
                           time.localtime(rec.get("ts", 0)))
        fp = (rec.get("fingerprint") or "unknown")[:12]
        bits = [f"{ts}", f"{rec.get('kind', '?'):<10}", f"{fp:<12}"]
        m = rec.get("metrics") or {}
        if m:
            bits.append(" ".join(f"{k}={v:g}" for k, v in
                                 sorted(m.items())))
        if rec.get("phase"):
            bits.append(f"phase={rec['phase']}")
        if rec.get("retryable") is not None:
            bits.append(f"retryable={rec['retryable']}")
        if rec.get("error"):
            bits.append(f"ERROR: {rec['error'][:80]}")
        lines.append("  ".join(bits))
    return "\n".join(lines) if lines else "(empty ledger)"


# ---------------------------------------------------------------------------
# historical-round ingestion (BENCH_r*.json / MULTICHIP_r*.json backfill)
# ---------------------------------------------------------------------------


def _tail_error(tail: str) -> str:
    """Last non-empty line of a captured stdout/stderr tail — the raw
    pre-error-JSON rounds (r01) recorded only a traceback."""
    lines = [ln.strip() for ln in (tail or "").splitlines() if ln.strip()]
    return lines[-1][:300] if lines else "unknown"


def ingest_round_file(path: str) -> Optional[dict]:
    """One historical round file -> one ledger record.

    Handles both shapes the driver archived: ``BENCH_r*.json``
    (``{"n", "cmd", "rc", "tail", "parsed"}`` — ``parsed`` is the bench
    JSON line, null for pre-error-JSON rounds) and ``MULTICHIP_r*.json``
    (``{"n_devices", "rc", "ok", "skipped", "tail"}``).  Phase,
    retryable and probe_attempts are preserved verbatim; stamps ride
    whole except ``probe_logs`` (raw log tails stay in the archive
    files, the ledger keeps the counts)."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError, ValueError):
        return None
    if not isinstance(doc, dict):
        return None
    base = os.path.basename(path)
    try:
        ts = os.path.getmtime(path)
    except OSError:
        ts = time.time()

    if "n_devices" in doc:  # multichip probe round
        ok = bool(doc.get("ok"))
        return run_record(
            "multichip",
            source=base,
            ts=ts,
            error=None if ok else _tail_error(doc.get("tail", "")),
            rc=doc.get("rc"),
            n_devices=doc.get("n_devices"),
            skipped=doc.get("skipped"),
        )

    parsed = doc.get("parsed")
    if not isinstance(parsed, dict):
        # r01 shape: raw traceback only — the record still testifies
        return run_record(
            "bench",
            source=base,
            ts=ts,
            phase="unknown",
            error=_tail_error(doc.get("tail", "")),
            rc=doc.get("rc"),
            round=doc.get("n"),
        )
    metrics: Dict[str, float] = {}
    if parsed.get("value"):
        metrics["throughput"] = parsed["value"]
    if parsed.get("mfu_pct") is not None:
        metrics["mfu_pct"] = parsed["mfu_pct"]
    if parsed.get("compile_seconds"):
        metrics["compile_seconds"] = parsed["compile_seconds"]
    stamps = {k: parsed[k] for k in
              ("lint", "guard", "memory", "layout_pick", "pp_plan")
              if k in parsed}
    extra: dict = {"rc": doc.get("rc"), "round": doc.get("n")}
    for k in ("probe_attempts", "probe_last", "cache_hits",
              "cache_misses", "resumable", "unit"):
        if k in parsed:
            extra[k] = parsed[k]
    return run_record(
        "bench",
        source=base,
        ts=ts,
        phase=parsed.get("phase"),
        retryable=parsed.get("retryable"),
        error=parsed.get("error"),
        metrics=metrics,
        stamps=stamps or None,
        **extra,
    )


def ingest_paths(ledger: str, paths: Iterable[str],
                 dedupe: bool = True) -> Tuple[int, int]:
    """Ingest round files into ``ledger``; returns ``(added, skipped)``.
    Idempotent by ``source`` basename — re-running the backfill never
    duplicates history."""
    seen = {r.get("source") for r in load_runs(ledger)
            if r.get("source")} if dedupe else set()
    added = skipped = 0
    for p in sorted(paths):
        rec = ingest_round_file(p)
        if rec is None:
            skipped += 1
            continue
        if rec.get("source") in seen:
            skipped += 1
            continue
        if append_run(ledger, rec):
            seen.add(rec.get("source"))
            added += 1
        else:
            skipped += 1
    return added, skipped


# ---------------------------------------------------------------------------
# postmortem
# ---------------------------------------------------------------------------


def _fmt_flight_record(rec: dict) -> str:
    bits = []
    for key in ("step", "tick", "opt_step"):
        if key in rec:
            bits.append(f"{key}={rec[key]}")
    if "loss" in rec:
        bits.append(f"loss={rec['loss']:.5g}")
    if "guard_verdict" in rec:
        bits.append(f"guard={rec['guard_verdict']}")
    if "guard_z" in rec:
        bits.append(f"z={rec['guard_z']:.2f}")
    if rec.get("skipped"):
        bits.append("SKIPPED")
    if "headroom" in rec:
        bits.append(f"headroom={rec['headroom']:.1%}")
    ph = rec.get("phases") or {}
    if ph:
        bits.append("phases(ms) " + " ".join(
            f"{k}={1e3 * v:.0f}" for k, v in sorted(ph.items())))
    for key in ("emitted", "active_slots", "queue_depth", "oom_skipped",
                "compiles", "stalled"):
        if rec.get(key):
            bits.append(f"{key}={rec[key]}")
    return " ".join(bits) or json.dumps(
        {k: v for k, v in rec.items() if k not in ("kind", "t")})[:120]


def postmortem_timeline(
    flight_path: Optional[str] = None,
    supervisor_ledger: Optional[str] = None,
    bench_status: Optional[str] = None,
    runs_path: Optional[str] = None,
    tail: int = 12,
) -> str:
    """Merge the available evidence into ONE human-readable account of
    how a run/round died: the newest flight-dump records (step-level),
    the supervisor's episode ledger (process-level), the bench status
    file (phase-level) and the newest run-ledger row (history-level).
    Every source is optional and read tolerantly — the postmortem runs
    over whatever the crash left behind."""
    lines: List[str] = ["== fdtpu postmortem =="]
    verdict: Optional[str] = None

    if runs_path:
        runs = load_runs(runs_path)
        if runs:
            lines.append(f"-- run ledger ({runs_path}, {len(runs)} "
                         "records; newest last) --")
            lines.append(render_runs(runs, limit=3))

    if flight_path:
        lines.append(f"-- flight dump ({flight_path}) --")
        try:
            fl = __import__(
                "fluxdistributed_tpu.obs.flight",
                fromlist=["read_flight"]).read_flight(flight_path)
        except OSError as e:
            fl = None
            lines.append(f"  unreadable: {type(e).__name__}: {e}")
        if fl is not None:
            hdr = fl.get("header") or {}
            recs = fl.get("records") or []
            flush_every = hdr.get("flush_every", "?")
            lines.append(
                f"  fingerprint={hdr.get('fingerprint')} "
                f"flush_every={flush_every} "
                f"records_flushed={len(recs)} torn={fl.get('torn', 0)}")
            for rec in recs[-tail:]:
                lines.append(f"  {_fmt_flight_record(rec)}")
            end = fl.get("end")
            if end is not None:
                lines.append(
                    f"  end: status={end.get('status')} "
                    f"records={end.get('records')}"
                    + (f" error={end.get('error')}" if end.get("error")
                       else ""))
                verdict = f"soft exit: {end.get('status')}"
            else:
                ck = fl.get("checkpoint") or {}
                lines.append(
                    "  end: MISSING — hard death (SIGKILL / os._exit / "
                    "power); the final record above is at most "
                    f"{flush_every} records (one flush interval) before "
                    "death"
                    + (f"; sidecar saw {ck.get('recorded')} recorded"
                       if ck else ""))
                verdict = "hard death mid-run (no flight footer)"

    if supervisor_ledger:
        lines.append(f"-- supervisor episodes ({supervisor_ledger}) --")
        try:
            with open(supervisor_ledger) as f:
                led = json.load(f)
        except (OSError, json.JSONDecodeError, ValueError) as e:
            led = None
            lines.append(f"  unreadable: {type(e).__name__}: {e}")
        if isinstance(led, dict):
            for ep in led.get("episodes", []):
                lines.append(
                    f"  ep{ep.get('n')}: class={ep.get('class')} "
                    f"rc={ep.get('rc')} steps={ep.get('steps')} "
                    f"wall={ep.get('wall_seconds')}s -> "
                    f"{ep.get('action')}")
            lines.append(f"  result: {led.get('result')} "
                         f"(restarts={led.get('restarts')}, "
                         f"resumes={led.get('resumes')})")
            if led.get("result") and led.get("result") != "done":
                verdict = f"supervision ended: {led['result']}"

    if bench_status:
        lines.append(f"-- bench status ({bench_status}) --")
        try:
            with open(bench_status) as f:
                st = json.load(f)
        except (OSError, json.JSONDecodeError, ValueError) as e:
            st = None
            lines.append(f"  unreadable: {type(e).__name__}: {e}")
        if isinstance(st, dict):
            lines.append(
                f"  phase={st.get('phase')} "
                f"compile_seconds={st.get('compile_seconds')} "
                f"cache_misses={st.get('cache_misses')}")
            if verdict is None:
                verdict = f"bench died in phase {st.get('phase')}"

    lines.append(f"verdict: {verdict or 'no evidence found'}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# the run-info stitch gauge
# ---------------------------------------------------------------------------


def set_run_info(registry, component: str, mode: str = "") -> None:
    """Register the ``fdtpu_run_info`` info-style gauge (value 1, the
    metadata in labels): topology fingerprint, component + spmd/layout
    mode, jax version and the flight/runs schema versions — the join
    key that lets a scrape, a flight dump and a ledger row be stitched
    to the SAME run.  Best-effort: a backend too dead to fingerprint
    must not take the registry down with it."""
    try:
        from .flight import FLIGHT_SCHEMA, _lazy_fingerprint

        try:
            import jax

            jaxver = jax.__version__
        except Exception:  # noqa: BLE001 — info gauge is best-effort
            jaxver = "unknown"
        registry.gauge(
            "fdtpu_run_info",
            "info-style gauge (always 1): topology fingerprint, "
            "component/mode, jax version and obs schema versions — the "
            "stitch key between scrapes, flight dumps and run-ledger "
            "rows",
            labelnames=("component", "mode", "fingerprint", "jax",
                        "schemas"),
        ).labels(
            component=str(component),
            mode=str(mode or ""),
            fingerprint=_lazy_fingerprint() or "unknown",
            jax=jaxver,
            schemas=f"{FLIGHT_SCHEMA},{RUNS_SCHEMA}",
        ).set(1)
    except Exception as e:  # noqa: BLE001
        print(f"obs.runs: run_info gauge failed: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
