"""Request-scoped tracing (obs.reqtrace) through the serve stack.

All fast tier, FakeEngine-driven (no compiles): lifecycle events land
on per-request Perfetto tracks, the queue-wait and inter-token (TBT)
histograms populate next to the pinned TTFT one, the exported trace
JSON is well-formed (ph/ts/pid/tid, request track metadata, span
nesting), the ring stays bounded, and the HTTP layer propagates
``X-Request-Id`` end-to-end and serves ``GET /trace``.
"""

from __future__ import annotations

import json
import threading
import urllib.error
import urllib.request

import pytest

from fluxdistributed_tpu.obs import RequestTracer
from fluxdistributed_tpu.serve import Request, Scheduler
from fluxdistributed_tpu.serve.server import LMServer


class FakeEngine:
    """Whole-prefill pure-python engine (the test_obs pattern)."""

    max_slots = 2

    def validate_request(self, prompt_len, max_new_tokens):
        pass

    def prefill(self, slot, prompt, temperature, key):
        return 7, 8

    def step_decode(self):
        return [1] * self.max_slots

    def reset_slot(self, slot):
        pass

    def compile_stats(self):
        return {"decode_compiles": 1, "prefill_compiles": 1,
                "insert_compiles": 1}


class FakeChunkEngine(FakeEngine):
    """Incremental engine: 4-token chunks — exercises the chunked
    prefill events and the rid riding the engine's prefill state."""

    prefill_incremental = True
    prefill_chunk = 4

    def __init__(self):
        self.begun = []  # (slot, rid) — the propagation evidence

    def can_admit(self, prompt, max_new_tokens):
        return True

    def prefill_begin(self, slot, tokens, temperature, key,
                      max_new_tokens=None, rid=None):
        self.begun.append((slot, rid))
        return {"slot": slot, "pos": 0, "plen": len(tokens)}

    def prefill_step(self, st):
        n = min(self.prefill_chunk, st["plen"] - st["pos"])
        st["pos"] += n
        done = st["pos"] >= st["plen"]
        return (7 if done else None), n, self.prefill_chunk


def _drain(sched, reqs):
    for r in reqs:
        sched.submit(r)
    sched.run_until_idle()


# ---------------------------------------------------------------------------
# lifecycle events + latency histograms
# ---------------------------------------------------------------------------

def test_lifecycle_events_and_latency_histograms():
    rt = RequestTracer()
    sched = Scheduler(FakeEngine(), max_queue=8, reqtrace=rt)
    reqs = [Request(prompt=[1, 2, 3], max_new_tokens=3),
            Request(prompt=[4], max_new_tokens=3)]
    _drain(sched, reqs)

    names = [e["name"] for e in rt.trace_events()]
    for needed in ("enqueue", "queue_wait", "prefill", "first_token",
                   "token", "decode", "finish", "decode_step"):
        assert needed in names, f"{needed} missing from {set(names)}"

    # queue-wait: one sample per admitted request; TBT: every token
    # after each request's first (3 generated => 2 gaps each)
    assert sched.registry.get(
        "fdtpu_serve_queue_wait_seconds").cell_count() == 2
    assert sched.registry.get("fdtpu_serve_tbt_seconds").cell_count() == 4
    m = sched.metrics()
    assert m["queue_wait_count"] == 2 and m["tbt_count"] == 4
    assert m["queue_wait_sec_p50"] >= 0 and m["tbt_sec_p50"] >= 0
    # /metrics exposes all three latency histograms + the p rollups
    text = sched.registry.prometheus_text()
    for series in ("fdtpu_serve_ttft_seconds_bucket",
                   "fdtpu_serve_queue_wait_seconds_bucket",
                   "fdtpu_serve_tbt_seconds_bucket",
                   "fdtpu_serve_queue_wait_sec_p50",
                   "fdtpu_serve_tbt_sec_p95",
                   "fdtpu_serve_ttft_hist_sec_p50"):
        assert series in text, series


def test_histograms_populate_without_tracer():
    """Queue-wait/TBT are first-class metrics — they must not depend on
    a tracer being attached."""
    sched = Scheduler(FakeEngine(), max_queue=8)
    _drain(sched, [Request(prompt=[1], max_new_tokens=4)])
    assert sched.reqtrace is None
    assert sched.registry.get(
        "fdtpu_serve_queue_wait_seconds").cell_count() == 1
    assert sched.registry.get("fdtpu_serve_tbt_seconds").cell_count() == 3
    # request-side stamps exist for the HTTP result fields
    # (admitted_at between submitted_at and first_token_at)


def test_request_timing_fields_ordered():
    sched = Scheduler(FakeEngine(), max_queue=8)
    req = Request(prompt=[1, 2], max_new_tokens=2)
    _drain(sched, [req])
    assert req.submitted_at <= req.admitted_at <= req.first_token_at
    assert req.last_token_at is not None
    assert req.first_token_at <= req.last_token_at <= req.finished_at


# ---------------------------------------------------------------------------
# Perfetto export: well-formed JSON, request tracks, nesting
# ---------------------------------------------------------------------------

def test_perfetto_export_well_formed(tmp_path):
    rt = RequestTracer()
    sched = Scheduler(FakeEngine(), max_queue=8, reqtrace=rt)
    a = Request(prompt=[1, 2], max_new_tokens=2, rid="req-A")
    b = Request(prompt=[3], max_new_tokens=2)
    _drain(sched, [a, b])

    path = tmp_path / "req.trace.json"
    n = rt.export_chrome_trace(str(path))
    doc = json.loads(path.read_text())  # parses
    evs = doc["traceEvents"]
    assert n == len([e for e in evs if e["ph"] not in ("M",)])

    by_ph: dict = {}
    for e in evs:
        by_ph.setdefault(e["ph"], []).append(e)
        assert isinstance(e["pid"], int) and isinstance(e["tid"], int)
        if e["ph"] != "M":
            assert isinstance(e["ts"], float)
        if e["ph"] == "X":
            assert e["dur"] >= 0
    assert set(by_ph) == {"M", "X", "i"}

    # per-request tracks: metadata names a lane per trace id, and the
    # explicit rid wins over the numeric fallback
    lanes = {e["args"]["name"]: e["tid"]
             for e in by_ph["M"] if e["name"] == "thread_name"}
    assert "request req-A" in lanes
    assert f"request {b.id}" in lanes
    assert "scheduler" in lanes  # decode ticks ride their own lane

    # nesting/order on request A's lane: queue_wait ends before the
    # prefill span begins, and the decode span covers its tokens
    tid = lanes["request req-A"]
    mine = [e for e in evs if e.get("tid") == tid and e["ph"] != "M"]
    spans = {e["name"]: e for e in mine if e["ph"] == "X"}
    qw, pf, dec = spans["queue_wait"], spans["prefill"], spans["decode"]
    assert qw["ts"] + qw["dur"] <= pf["ts"] + 1e-3
    toks = [e for e in mine if e["name"] == "token"]
    for t in toks:
        assert dec["ts"] - 1e-3 <= t["ts"] <= dec["ts"] + dec["dur"] + 1e-3
    assert doc["otherData"]["dropped_events"] == 0


def test_ring_bounds_memory_and_counts_drops():
    rt = RequestTracer(max_events=8)
    sched = Scheduler(FakeEngine(), max_queue=16, reqtrace=rt)
    _drain(sched, [Request(prompt=[1], max_new_tokens=4)
                   for _ in range(4)])
    assert len(rt) == 8
    assert rt.dropped > 0
    # the drop count is exported so a truncated timeline says so
    assert rt.trace_document()["otherData"]["dropped_events"] == rt.dropped


def test_lane_map_bounded_and_tids_never_reused():
    """A days-long server sees millions of request ids: the lane map
    must stay bounded like the ring, and an evicted lane's tid must
    never be handed to a different request (old ring events keep their
    number)."""
    rt = RequestTracer(max_events=16, max_lanes=3)
    for i in range(10):
        rt.event(f"r{i}", "enqueue")
    assert len(rt._tids) == 3
    assert rt.lanes_evicted == 7
    doc = rt.trace_document()
    assert doc["otherData"]["evicted_lanes"] == 7
    # monotonic tids: the surviving lanes are the NEWEST three
    lanes = {e["args"]["name"]: e["tid"] for e in doc["traceEvents"]
             if e["name"] == "thread_name"}
    assert set(lanes) == {"request r7", "request r8", "request r9"}
    assert sorted(lanes.values()) == [8, 9, 10]


def test_lane_eviction_is_lru_hot_lanes_survive():
    """Eviction must be least-recently-USED, not first-inserted: the
    scheduler lane (among the FIRST inserted, touched every tick) and a
    long-running stream must keep one track — and its tid — through a
    flood of one-shot request ids."""
    rt = RequestTracer(max_lanes=3)
    rt.event("scheduler", "decode_step")
    sched_tid = rt._tids["scheduler"]
    for i in range(20):
        rt.event(f"one-shot-{i}", "enqueue")
        rt.event("scheduler", "decode_step")  # hot lane refreshed
    assert rt._tids["scheduler"] == sched_tid  # never evicted, one tid
    names = [e["args"]["name"] for e in rt.trace_events()
             if e["name"] == "thread_name"]
    assert "scheduler" in names


def test_queued_cancel_closes_track():
    """A request cancelled BEFORE admission must still emit a terminal
    event — an enqueue with no close reads as a lost request."""
    rt = RequestTracer()
    sched = Scheduler(FakeEngine(), max_queue=4, reqtrace=rt)
    # fill both slots so a third request stays queued
    a = Request(prompt=[1], max_new_tokens=50)
    b = Request(prompt=[2], max_new_tokens=50)
    queued = Request(prompt=[3], max_new_tokens=50, rid="queued-victim")
    sched.submit(a)
    sched.submit(b)
    sched.step()  # admit a+b into the 2 slots
    sched.submit(queued)
    assert sched.cancel(queued) is True  # left the queue immediately
    mine = [e["name"] for e in rt.trace_events()
            if e["ph"] != "M" and e["tid"] == rt._tids["queued-victim"]]
    assert mine == ["enqueue", "cancel"]
    sched.cancel(a)
    sched.cancel(b)
    sched.step()


def test_chunked_prefill_events_and_rid_propagation():
    rt = RequestTracer()
    eng = FakeChunkEngine()
    sched = Scheduler(eng, max_queue=8, reqtrace=rt)
    req = Request(prompt=list(range(10)), max_new_tokens=2, rid="chunky")
    _drain(sched, [req])
    # the trace id rode HTTP->Scheduler->LMEngine.prefill_begin
    assert eng.begun == [(0, "chunky")]
    chunk_spans = [e for e in rt.trace_events()
                   if e["name"] == "prefill_chunk"]
    assert len(chunk_spans) == 3  # 10 tokens / chunk 4
    assert all(e["ph"] == "X" for e in chunk_spans)


def test_cancel_and_drain_events():
    rt = RequestTracer()
    sched = Scheduler(FakeEngine(), max_queue=8, reqtrace=rt)
    victim = Request(prompt=[1], max_new_tokens=50)
    sched.submit(victim)
    sched.step()  # admit + first token
    sched.cancel(victim)
    sched.step()  # teardown on the driver thread
    sched.begin_drain()
    names = [e["name"] for e in rt.trace_events()]
    assert "cancel" in names and "drain_begin" in names
    assert victim.state == "done"


# ---------------------------------------------------------------------------
# HTTP: X-Request-Id end-to-end + GET /trace
# ---------------------------------------------------------------------------

@pytest.fixture()
def http_server():
    rt = RequestTracer()
    sched = Scheduler(FakeEngine(), max_queue=8, reqtrace=rt)
    srv = LMServer(sched, vocab=256)
    httpd = srv.serve("127.0.0.1", 0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{httpd.server_address[1]}", rt
    httpd.shutdown()
    srv.close()


def test_http_request_id_and_trace_endpoint(http_server):
    base, rt = http_server
    req = urllib.request.Request(
        f"{base}/v1/generate",
        data=json.dumps({"prompt_tokens": [1, 2], "max_tokens": 3}).encode(),
        headers={"Content-Type": "application/json",
                 "X-Request-Id": "router-7/a"})
    with urllib.request.urlopen(req, timeout=30) as r:
        out = json.loads(r.read())
    assert out["request_id"] == "router-7/a"
    assert out["queue_wait_ms"] >= 0
    assert out["ttft_ms"] >= 0
    assert out["tbt_ms_avg"] >= 0

    with urllib.request.urlopen(f"{base}/trace", timeout=30) as r:
        doc = json.loads(r.read())
    lanes = [e["args"]["name"] for e in doc["traceEvents"]
             if e["name"] == "thread_name"]
    assert "request router-7/a" in lanes


def test_http_trace_404_without_tracer():
    sched = Scheduler(FakeEngine(), max_queue=4)  # no tracer attached
    srv = LMServer(sched, vocab=256)
    httpd = srv.serve("127.0.0.1", 0)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(
                f"http://127.0.0.1:{httpd.server_address[1]}/trace",
                timeout=30)
        assert ei.value.code == 404
        assert "trace-requests" in json.loads(ei.value.read())["error"]
    finally:
        httpd.shutdown()
        srv.close()
