"""FDT102 positive: host impurity in traced code + wall clock in a
span-bracketed hot path."""
import time

import jax
import numpy as np


@jax.jit
def stamped(x):
    return x + time.time()  # baked into the trace as a constant


@jax.jit
def jittered(x):
    return x + np.random.normal()  # host RNG: one draw, forever


def hot_loop(tracer, items):
    with tracer.span("step"):
        t0 = time.time()  # wall clock in interval math
        for _ in items:
            pass
        return t0
