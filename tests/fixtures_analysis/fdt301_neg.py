"""FDT301 negative: every write to a covered attribute holds the
lock; `ticks` is driver-thread-only state the class never locks, so
it has no coverage to violate (the rule's precision contract)."""
import threading


class Stat:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self.ticks = 0  # single-thread state: never lock-covered

    def inc(self):
        with self._lock:
            self.count += 1

    def snapshot(self):
        with self._lock:
            return self.count

    def tick(self):
        self.ticks += 1
