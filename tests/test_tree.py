"""Tests for the grad-tree machinery (reference: src/overloads.jl,
src/ddp_tasks.jl:4-26, and the test comparator test/runtests.jl:6-41)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fluxdistributed_tpu import tree


def _tree():
    return {
        "conv": {"kernel": jnp.arange(6.0).reshape(2, 3), "bias": jnp.ones(3)},
        "act": None,  # stateless layer — the reference's `nothing` leaf
        "head": (jnp.full((2,), 2.0),),
    }


def test_zeros_like_preserves_structure_and_none():
    z = tree.zeros_like(_tree())
    assert z["act"] is None
    assert np.all(np.asarray(z["conv"]["kernel"]) == 0)
    assert z["head"][0].shape == (2,)


def test_accum_none_identity():
    t = _tree()
    z = tree.zeros_like(t)
    s = tree.accum(t, z)
    tree.assert_close(s, t)
    # None absorbs into the other side, as Zygote.accum does
    s2 = tree.accum({"a": None}, {"a": jnp.ones(2)})
    assert np.all(np.asarray(s2["a"]) == 1)


def test_mean_matches_manual():
    ts = [
        {"w": jnp.full((3,), float(i)), "b": None} for i in range(1, 5)
    ]
    m = tree.mean(ts)
    assert np.allclose(np.asarray(m["w"]), 2.5)
    assert m["b"] is None


def test_div_and_scale_skip_none():
    t = {"w": jnp.full((2,), 4.0), "n": None}
    assert np.all(np.asarray(tree.div(t, 2.0)["w"]) == 2.0)
    assert tree.scale(t, 3.0)["n"] is None


def test_assert_close_reports_paths():
    a = {"w": jnp.zeros(3)}
    b = {"w": jnp.ones(3)}
    with pytest.raises(AssertionError, match="w"):
        tree.assert_close(a, b)
    assert not tree.allclose(a, b)
    assert tree.allclose(a, {"w": jnp.zeros(3) + 1e-6})


def test_getfirst():
    t = {"layers": [{"weight": jnp.ones(2), "bias": jnp.zeros(2)}, {"weight": jnp.full((2,), 5.0)}]}
    w = tree.getfirst(t, "weight")
    assert np.all(np.asarray(w) == 1)
    assert tree.getfirst(t, "missing") is None


def test_count_and_bytes():
    t = {"a": jnp.zeros((2, 3)), "b": jnp.zeros(4)}
    assert tree.count_params(t) == 10
    assert tree.nbytes(t) == 10 * 4


def test_cast_floats_only():
    t = {"w": jnp.zeros(2, jnp.float32), "i": jnp.zeros(2, jnp.int32), "n": None}
    c = tree.cast(t, jnp.bfloat16)
    assert c["w"].dtype == jnp.bfloat16
    assert c["i"].dtype == jnp.int32
    assert c["n"] is None


def test_to_host_and_synchronize():
    t = {"w": jnp.ones(2)}
    h = tree.to_host(t)
    assert isinstance(h["w"], np.ndarray)
    assert tree.synchronize(t) is t
