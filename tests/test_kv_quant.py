"""Quantized KV-cache storage (kv_quant / LMEngine kv_dtype).

The contract: quantization is a STORAGE scenario — every read path
(XLA gather, windowed concat, the decode kernels) attends the same
stored numbers — and its token parity is WITHIN TOLERANCE, not
bit-pinned: the quantizer's round() sits on top of activations, and
ulp-level reduction-order differences between implementations (dense
vs gathered attends, block-walk vs full softmax) can flip a stored
int by one, which perturbs logits by O(scale) — four orders of
magnitude more than the f32 ulps that make UNquantized parity exact
in practice.  So quant tests assert high token-match fractions plus
the structural invariants (ONE decode compile, bytes halved); exact
golden parity stays the bar for kv_quant='none' (test_pallas_decode,
test_serve_*).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fluxdistributed_tpu.models import transformer_lm as tlm


def _model_params(vocab=64, **kw):
    # depth-2/dim-64: compile time dominates every test here and the
    # quant/storage semantics are depth-independent
    model = tlm.lm_tiny(vocab=vocab, dtype=jnp.float32, depth=2, dim=64,
                        mlp_dim=128, **kw)
    params = model.init(jax.random.PRNGKey(0), np.zeros((1, 2), np.int32),
                        train=False)["params"]
    return model, params


def _gen(model, params, prompt, total):
    return np.asarray(tlm.generate(model, params, prompt, total_len=total))


def test_quantize_roundtrip_error_bounded():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(4, 7, 2, 16)), jnp.float32)
    q, s = tlm.quantize_kv(x, "int8")
    assert q.dtype == jnp.int8 and s.shape == (4, 7, 2)
    back = tlm.dequantize_kv(q, s, jnp.float32)
    # absmax scaling: error < scale/2 per element ~ amax/254
    amax = np.abs(np.asarray(x)).max(axis=-1, keepdims=True)
    assert (np.abs(np.asarray(back - x)) <= amax / 127).all()


def test_int8_generate_tokens_match_fp32():
    """Token-parity within tolerance vs the fp32 cache (greedy, tiny
    model: absmax int8 keeps every argmax in place here; the asserted
    bar is 90% to absorb near-ties on other seeds)."""
    model, params = _model_params()
    prompt = np.asarray([[3, 9, 27, 14, 50, 8]], np.int32)
    ref = _gen(model.clone(decode=True), params, prompt, 26)
    out = _gen(model.clone(decode=True, kv_quant="int8"), params, prompt, 26)
    assert (out == ref).mean() >= 0.9
    # (int8 impl-invariance — pallas == xla tokens — is pinned by
    # test_engine_int8_token_parity_vs_generate without a third compile)


def _match_frac(got, ref):
    toks = [(a, b) for g, r in zip(got, ref) for a, b in zip(g, r)]
    return sum(a == b for a, b in toks) / max(1, len(toks))


def test_engine_int8_token_parity_vs_generate():
    """Token parity within tolerance at fixed quant:
    engine(kv_dtype=int8) vs sequential generate(kv_quant=int8), with
    the scale leaves riding the chunk/bind/release programs and the
    decode kernel dequantizing in-kernel (see module docstring for why
    int8 parity is a fraction, not an equality)."""
    from fluxdistributed_tpu.serve import LMEngine, Request, Scheduler

    model, params = _model_params()
    rng = np.random.default_rng(1)
    # equal lengths: one compiled reference program, not one per length
    prompts = [list(rng.integers(0, 64, 7)) for _ in range(2)]
    qm = model.clone(decode=True, kv_quant="int8")
    ref = []
    for p in prompts:
        o = _gen(qm, params, np.asarray([p], np.int32), len(p) + 8)[0]
        ref.append(list(o[len(p):]))
    # the fully-loaded config carries tier-1 (paged + pallas + int8:
    # scale leaves through chunk/bind/release AND in-kernel dequant);
    # the dense-splice int8 path rides the slow windowed matrix
    eng = LMEngine(model, params, max_slots=2, max_len=24,
                   kv_dtype="int8", layout="paged", kv_block_size=8,
                   prefill_chunk=8, attention_impl="pallas")
    sched = Scheduler(eng)
    reqs = [Request(prompt=p, max_new_tokens=8) for p in prompts]
    sched.generate_all(reqs)
    assert _match_frac([r.generated for r in reqs], ref) >= 0.9
    assert eng.compile_stats()["decode_compiles"] == 1


def test_int8_cache_bytes_at_least_halved():
    """The acceptance bar: live KV bytes/token at kv_dtype=int8 are
    at most half the full-precision layout's (4x for f32 storage minus
    the f32 scale overhead)."""
    from fluxdistributed_tpu.serve import LMEngine

    from fluxdistributed_tpu.serve.cache_layout import kv_row_bytes

    model, params = _model_params()
    hkv, dh = model.num_heads, model.dim // model.num_heads
    sizes = {}
    for kvd in (None, "int8"):
        eng = LMEngine(model, params, max_slots=2, max_len=64,
                       layout="paged", kv_block_size=8, kv_dtype=kvd)
        sizes[kvd] = eng.kv_cache_bytes()["reserved"]
        assert eng.pool_stats()["kv_quant"] == (kvd or "none")
        # the sizing model IS the measurement: kv_row_bytes × total
        # pool rows × layers == the bytes counted off the cache leaves
        rows = eng.layout.pool.num_blocks * eng.layout.block_size
        predicted = model.depth * rows * kv_row_bytes(
            hkv, dh, kvd or "none", 4)
        assert predicted == sizes[kvd], (kvd, predicted, sizes[kvd])
    assert sizes["int8"] * 2 <= sizes[None], sizes
    with pytest.raises(ValueError, match="unknown kv_quant"):
        kv_row_bytes(hkv, dh, "int08", 4)


def test_kv_cache_bytes_predicted_parity_both_layouts():
    """ONE source of truth for KV sizing: the engine's measured
    ``kv_cache_bytes()['reserved']`` (counted off the live cache
    leaves, scale leaves included) equals the layout's own
    ``reserved_kv_bytes`` model (its ``predicted`` key) — pinned in
    BOTH layouts for every kv_quant scenario, GQA included, so the
    figure admission control sizes pools with can never drift from
    what the benches and /healthz report."""
    from fluxdistributed_tpu.serve import LMEngine

    model, params = _model_params(num_kv_heads=2)
    for layout_kw in ({}, {"layout": "paged", "kv_block_size": 8}):
        for kvd in (None, "int8", "fp8"):
            eng = LMEngine(model, params, max_slots=2, max_len=64,
                           kv_dtype=kvd, **layout_kw)
            m = eng.kv_cache_bytes()
            assert m["reserved"] == m["predicted"], (
                layout_kw, kvd, m)
            assert m["live"] <= m["reserved"]


def test_validation():
    model, _ = _model_params()
    with pytest.raises(ValueError, match="decode=True"):
        model.clone(kv_quant="int8").init(
            jax.random.PRNGKey(0), np.zeros((1, 4), np.int32), train=False)
    with pytest.raises(ValueError, match="unknown kv_quant"):
        model.clone(decode=True, kv_quant="int4").init(
            jax.random.PRNGKey(0), np.zeros((1, 4), np.int32), train=False)
    from fluxdistributed_tpu.serve import LMEngine

    with pytest.raises(ValueError, match="kv_dtype"):
        LMEngine(model, {}, kv_dtype="int4")


@pytest.mark.slow
def test_fp8_stub_path():
    """fp8 storage works when the dtype exists (this jax has e4m3);
    tokens stay close to fp32 like int8."""
    if not hasattr(jnp, "float8_e4m3fn"):
        pytest.skip("no fp8 dtype in this jaxlib")
    model, params = _model_params()
    prompt = np.asarray([[3, 9, 27, 14]], np.int32)
    ref = _gen(model.clone(decode=True), params, prompt, 20)
    out = _gen(model.clone(decode=True, kv_quant="fp8"), params, prompt, 20)
    assert (out == ref).mean() >= 0.9


@pytest.mark.slow
def test_windowed_int8_engine_parity():
    """Ring + sinks + GQA with int8 storage: engine vs generate at the
    same quant, across both layouts and attention impls (tolerance —
    module docstring)."""
    from fluxdistributed_tpu.serve import LMEngine, Request, Scheduler

    model, params = _model_params(window=8, sinks=2, num_kv_heads=2)
    rng = np.random.default_rng(2)
    prompts = [list(rng.integers(0, 64, n)) for n in (5, 14)]
    qm = model.clone(decode=True, kv_quant="int8")
    ref = []
    for p in prompts:
        o = _gen(qm, params, np.asarray([p], np.int32), len(p) + 12)[0]
        ref.append(list(o[len(p):]))
    for kw in (dict(buckets=(16,), attention_impl="xla"),
               dict(buckets=(16,), attention_impl="pallas"),
               dict(layout="paged", kv_block_size=4, prefill_chunk=8,
                    attention_impl="pallas")):
        eng = LMEngine(model, params, max_slots=2, max_len=32,
                       kv_dtype="int8", **kw)
        sched = Scheduler(eng)
        reqs = [Request(prompt=p, max_new_tokens=12) for p in prompts]
        sched.generate_all(reqs)
        assert _match_frac([r.generated for r in reqs], ref) >= 0.9, kw
