"""Hand-scheduled 1F1B pipeline parallelism.

The GPipe schedule in ``parallel.pp`` derives its backward pass from AD:
differentiate through the forward ``lax.scan`` and the reverse pipeline
falls out.  Elegant — but the scan transpose stores residuals for every
tick, so activation memory grows with the microbatch count M.  That is
GPipe's textbook pathology, and it is measurable: on the benchmark mesh,
per-tick cost inflates >2x from M=S to M=8S as the stashed residuals
grow (benchmarks/pp_bubble.py, docs/parallelism.md).

This module hand-writes the 1F1B (one-forward-one-backward) schedule
instead, the way Megatron-LM runs its pipelines — but TPU-idiomatic:
the whole schedule (all forwards, all backwards, gradient accumulation)
is ONE ``lax.scan`` over lockstep ticks inside ONE ``shard_map``, with
neighbor transfers as ``ppermute`` collectives.  Per tick each pipe
device performs one stage-forward, one stage-backward, or idles,
according to a STATIC schedule table computed in Python at trace time
(S and M are static, so the whole timetable is).  Nothing here is
data-dependent control flow: per-device divergence is a ``lax.cond`` on
a device-varying flag read from the table.

Memory property (the point of 1F1B): a device stashes at most
``min(S, M)`` in-flight microbatch INPUTS — a fixed-size ring buffer —
instead of the O(M·ticks) residuals of AD-through-scan.  Backward ticks
recompute the stage forward under ``jax.vjp`` from the stored input
(same recompute trade as ``pipeline_apply(remat=True)``, which is how
Megatron runs production pipelines too: activation recompute +
schedule).  Net: activation memory O(S), not O(M), so M — and with it
the (S-1)/(M+S-1) bubble — can grow freely.

Because forward and backward interleave *within* the schedule, the loss
must be computable per-microbatch inside the pipeline: the caller
provides ``embed_fn`` (applied at stage 0, e.g. token embedding) and
``head_fn`` (applied at stage S-1: final norm + logits + scalar loss).
Stage-parameter gradients stay local to their pipe device (no gradient
collective at all); ``embed_fn``/``head_fn`` ("outer") parameter
gradients accumulate on devices 0 and S-1 and are summed across the
pipe axis once at the end — which also makes weight tying (embedding
matrix used by both ends) come out right for free.

Reference anchor: net-new scope beyond FluxDistributed.jl (SURVEY §2
"PP: NO"); the reference never pipelines.  Schedule follows the
published 1F1B form (PipeDream-flush / Megatron-LM); implementation is
original and TPU-first.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..optim import Optimizer
from .dp import TrainState
from .pp import PIPE_AXIS, _accepts_stage

Pytree = Any

__all__ = ["Schedule1F1B", "build_schedule", "pipeline_grads_1f1b",
           "make_train_step_1f1b", "split_state_shardings"]


def split_state_shardings(mesh: Mesh, axis: str = PIPE_AXIS) -> Callable:
    """``state_shardings(state)`` builder for the split param tree
    ``{"outer": ..., "stages": ...}``: outer replicated, stages sharded
    on ``axis``, optimizer state following its param.  The single source
    of truth for both pipeline schedules (``lm_pp``/``lm_pp_1f1b`` reuse
    it, and ``make_train_step_1f1b`` compiles with it)."""
    from ..sharding import make_shardings
    from .tp import state_specs

    def state_shardings(state: TrainState) -> TrainState:
        p_specs = {
            "outer": jax.tree.map(lambda _: P(), state.params["outer"]),
            "stages": jax.tree.map(lambda _: P(axis), state.params["stages"]),
        }
        return make_shardings(state_specs(state, p_specs), mesh)

    return state_shardings


class Schedule1F1B(NamedTuple):
    """Static lockstep timetable: ``[T, S]`` arrays, one row per tick.

    ``is_fwd[t, i]``/``is_bwd[t, i]`` — does device i run a stage
    forward / backward at tick t (at most one of the two is set);
    ``fwd_mb``/``bwd_mb`` — which microbatch (0 when inactive);
    ``fwd_slot``/``bwd_slot`` — its ring-buffer slot (mb mod ring);
    ``left_fwd[t, i]`` = is_fwd[t, i-1]: the left neighbor produced an
    activation this tick, so latch the incoming ppermute value;
    ``right_bwd[t, i]`` = is_bwd[t, i+1]: same for cotangents.
    """

    is_fwd: np.ndarray
    is_bwd: np.ndarray
    fwd_mb: np.ndarray
    bwd_mb: np.ndarray
    fwd_slot: np.ndarray
    bwd_slot: np.ndarray
    left_fwd: np.ndarray
    right_bwd: np.ndarray

    @property
    def ticks(self) -> int:
        return self.is_fwd.shape[0]


def build_schedule(S: int, M: int) -> Schedule1F1B:
    """Build and VERIFY the lockstep 1F1B timetable for S stages and M
    microbatches.

    Per-device action order is the classic warmup/steady/cooldown
    sequence — device i runs ``W = min(S-1-i, M)`` warmup forwards, then
    alternates forward/backward until forwards run out, then drains
    backwards.  Actions are placed onto lockstep ticks greedily, each
    device firing its next action as soon as its dependency (upstream
    forward / downstream backward, strictly earlier tick) is met.

    The builder then PROVES the placement safe for the runtime's
    fixed-size buffers, asserting for every edge and every slot:

    * single-latch safety: a produced activation/cotangent is consumed
      before (or exactly when) the producer's next value lands;
    * ring safety: a stored input's slot is not reused until its own
      backward has retired it.

    Greedy lockstep placement lands on the canonical 2(M+S-1) ticks
    (bubble fraction (S-1)/(M+S-1), same as GPipe — 1F1B's win is
    memory, not bubble).
    """
    if S < 2:
        raise ValueError(f"1F1B needs >= 2 pipeline stages, got {S}")
    if M < 1:
        raise ValueError(f"need >= 1 microbatch, got {M}")

    # per-device action sequences: [F]*W + [F,B]*(M-W) + [B]*W
    seqs = []
    for i in range(S):
        w = min(S - 1 - i, M)
        seq = [("F", m) for m in range(w)]
        nxt = w
        for m in range(M - w):
            seq.append(("F", nxt))
            nxt += 1
            seq.append(("B", m))
        seq.extend(("B", m) for m in range(max(0, M - w), M))
        seqs.append(seq)

    pos = [0] * S
    fdone = [[-1] * M for _ in range(S)]
    bdone = [[-1] * M for _ in range(S)]
    rows_f, rows_b, rows_mf, rows_mb = [], [], [], []
    t = 0
    while any(pos[i] < len(seqs[i]) for i in range(S)):
        if t > 4 * (M + S) + 8:  # 2(M+S-1) expected; anything near 4x is a bug
            raise RuntimeError(f"1F1B schedule failed to converge (S={S}, M={M})")
        # decide every device against PRE-tick state, then commit
        decisions = []
        for i in range(S):
            if pos[i] >= len(seqs[i]):
                decisions.append(None)
                continue
            act, m = seqs[i][pos[i]]
            if act == "F":
                ready = i == 0 or 0 <= fdone[i - 1][m] < t
            elif i == S - 1:
                ready = 0 <= fdone[i][m] < t  # loss cotangent is local
            else:
                ready = 0 <= bdone[i + 1][m] < t
            decisions.append((act, m) if ready else None)
        rf, rb = [False] * S, [False] * S
        rmf, rmb = [0] * S, [0] * S
        for i, d in enumerate(decisions):
            if d is None:
                continue
            act, m = d
            if act == "F":
                fdone[i][m] = t
                rf[i], rmf[i] = True, m
            else:
                bdone[i][m] = t
                rb[i], rmb[i] = True, m
            pos[i] += 1
        rows_f.append(rf)
        rows_b.append(rb)
        rows_mf.append(rmf)
        rows_mb.append(rmb)
        t += 1

    # ---- safety proofs for the runtime's fixed-size buffers.  Real
    # exceptions, not asserts: a placement bug here means silently
    # corrupted gradients at runtime, and asserts vanish under -O.
    def _prove(ok: bool, i: int, m: int, what: str):
        if not ok:
            raise RuntimeError(
                f"1F1B schedule unsafe for S={S}, M={M}: {what} "
                f"(device {i}, microbatch {m})"
            )

    for i in range(S - 1):  # activation latch on edge i -> i+1
        for m in range(M):
            _prove(fdone[i][m] < fdone[i + 1][m], i, m, "act order")
            if m + 1 < M:
                _prove(fdone[i][m + 1] >= fdone[i + 1][m], i, m,
                       "act latch overwritten before consumption")
    for i in range(S - 1):  # cotangent latch on edge i+1 -> i
        for m in range(M):
            _prove(bdone[i + 1][m] < bdone[i][m], i, m, "cot order")
            if m + 1 < M:
                _prove(bdone[i + 1][m + 1] >= bdone[i][m], i, m,
                       "cot latch overwritten before consumption")
    ring = min(S, M)
    for i in range(S):  # ring-slot reuse
        for m in range(M - ring):
            _prove(fdone[i][m + ring] > bdone[i][m], i, m,
                   "ring slot reused while occupant still in flight")

    is_fwd = np.asarray(rows_f, dtype=bool)
    is_bwd = np.asarray(rows_b, dtype=bool)
    fwd_mb = np.asarray(rows_mf, dtype=np.int32)
    bwd_mb = np.asarray(rows_mb, dtype=np.int32)
    left_fwd = np.zeros_like(is_fwd)
    left_fwd[:, 1:] = is_fwd[:, :-1]
    right_bwd = np.zeros_like(is_bwd)
    right_bwd[:, :-1] = is_bwd[:, 1:]
    return Schedule1F1B(
        is_fwd, is_bwd, fwd_mb, bwd_mb,
        (fwd_mb % ring).astype(np.int32), (bwd_mb % ring).astype(np.int32),
        left_fwd, right_bwd,
    )


def pipeline_grads_1f1b(
    stage_fn: Callable,
    embed_fn: Callable,
    head_fn: Callable,
    mesh: Mesh,
    axis: str = PIPE_AXIS,
    num_microbatches: Optional[int] = None,
    batch_axis: Optional[str] = None,
):
    """Build ``run(stacked_params, outer, inputs, labels) -> (loss,
    stage_grads, outer_grads)`` executing the full fwd+bwd 1F1B schedule.

    * ``stage_fn(stage_params, x) -> y`` — shape-preserving pipe stage
      (``switch_stage``'s three-argument heterogeneous form and
      ``chunk_stages``-blocked virtual stages both compose);
    * ``embed_fn(outer, inputs_mb) -> x0`` — stage-0 entry (e.g. token
      embedding), re-run under ``vjp`` at backward ticks;
    * ``head_fn(outer, y, labels_mb) -> scalar`` — stage-(S-1) exit:
      per-microbatch mean loss.  The pipeline's loss is the mean over
      microbatches; gradients match ``jax.grad`` of that composition
      (tests/test_pp_1f1b.py proves it against the unpipelined model).

    ``stage_grads`` come back stage-stacked (leading dim sharded on
    ``axis``) exactly like the input params — the optimizer update stays
    local to each pipe device.  ``outer_grads`` are psum'd across the
    pipe axis (embedding contributions from device 0, head contributions
    from device S-1; tied weights sum correctly).  ``batch_axis``
    composes data parallelism on a ``(data, pipe)`` mesh: grads are
    additionally averaged over ``batch_axis`` so each data row sees the
    global mean, matching the framework's DP semantics.
    """
    S = mesh.shape[axis]
    M = num_microbatches or S
    sched = build_schedule(S, M)
    ring = min(S, M)
    with_stage = _accepts_stage(stage_fn)
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]
    bwd_perm = [(i, (i - 1) % S) for i in range(S)]
    rows = tuple(
        jnp.asarray(a) for a in (
            sched.is_fwd, sched.is_bwd, sched.fwd_mb, sched.bwd_mb,
            sched.fwd_slot, sched.bwd_slot, sched.left_fwd, sched.right_bwd,
        )
    )

    def apply_stage(sp, x, idx):
        return stage_fn(sp, x, idx) if with_stage else stage_fn(sp, x)

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(axis), P(), P(batch_axis), P(batch_axis)),
        out_specs=(P(), P(axis), P()),
    )
    def run(stacked_params, outer, inputs, labels):
        sp = jax.tree.map(lambda p: p[0], stacked_params)
        idx = jax.lax.axis_index(axis)
        b = inputs.shape[0]
        assert b % M == 0, f"batch {b} not divisible by {M} microbatches"
        mb_in = inputs.reshape(M, b // M, *inputs.shape[1:])
        mb_lab = labels.reshape(M, b // M, *labels.shape[1:])

        want_axes = (axis,) if batch_axis is None else (axis, batch_axis)

        def _leaf_varying(x):
            # pcast rejects an already-varying operand; consult the
            # aval's varying-manual-axes set and convert only fresh
            # constants (zeros_like of a varying leaf is varying itself).
            # Under a (data, pipe) mesh the buffers must be varying over
            # BOTH axes, or cond branches mixing batch-derived values
            # with carries fail VMA typing.
            for ax in want_axes:
                if ax not in getattr(jax.typeof(x), "vma", frozenset()):
                    x = jax.lax.pcast(x, ax, to="varying")
            return x

        varying = lambda tr: jax.tree.map(_leaf_varying, tr)
        act = jax.eval_shape(embed_fn, outer, mb_in[0])
        # Use fully-VARYING views of the param trees inside the ticks:
        # differentiating w.r.t. a tree that is invariant over any mesh
        # axis makes the vjp transpose insert a psum_invariant INSIDE
        # the cond branch — a collective only some devices execute,
        # which deadlocks the mesh.  With varying params the pullback
        # stays device-local and the psums after the scan combine the
        # contributions (pipe for outer, batch_axis for both).
        outer = varying(outer)
        sp = varying(sp)
        zero_act = varying(jnp.zeros(act.shape, act.dtype))
        zeros_sp = varying(jax.tree.map(jnp.zeros_like, sp))
        zeros_outer = varying(jax.tree.map(jnp.zeros_like, outer))
        f32_0 = varying(jnp.float32(0.0))
        # d(mean over microbatches)/d(l_m); varying like the vjp output
        seed = varying(jnp.float32(1.0 / M))

        def tick(carry, row):
            h_act, h_cot, ringbuf, g_sp, g_out, loss_acc = carry
            isf, isb, mfs, mbs, sfs, sbs, lfs, rbs = row
            f = jnp.take(isf, idx)
            bk = jnp.take(isb, idx)
            mf, mb_ = jnp.take(mfs, idx), jnp.take(mbs, idx)
            sf, sb = jnp.take(sfs, idx), jnp.take(sbs, idx)

            # ---- forward tick: (maybe embed) -> stage -> stash input
            def do_f(_):
                x_in = jax.lax.cond(
                    idx == 0,
                    lambda _: _leaf_varying(
                        embed_fn(outer, jax.lax.dynamic_index_in_dim(
                            mb_in, mf, 0, keepdims=False))),
                    lambda _: h_act,
                    None,
                )
                y = apply_stage(sp, x_in, idx)
                return y, jax.lax.dynamic_update_index_in_dim(
                    ringbuf, x_in, sf, 0)

            y_send, ringbuf = jax.lax.cond(
                f, do_f, lambda _: (zero_act, ringbuf), None)

            # ---- backward tick: recompute fwd under vjp from the
            # stashed input, pull the cotangent through
            def do_b(_):
                x_saved = jax.lax.dynamic_index_in_dim(
                    ringbuf, sb, 0, keepdims=False)
                lab = jax.lax.dynamic_index_in_dim(
                    mb_lab, mb_, 0, keepdims=False)

                def last(_):
                    def fn(sp_, out_, x_):
                        return head_fn(out_, apply_stage(sp_, x_, idx), lab)

                    l, pull = jax.vjp(fn, sp, outer, x_saved)
                    gs, go, gx = pull(seed)
                    return gs, varying(go), gx, l

                def inner(_):
                    y, pull = jax.vjp(
                        lambda sp_, x_: apply_stage(sp_, x_, idx), sp, x_saved)
                    gs, gx = pull(h_cot)
                    return gs, zeros_outer, gx, f32_0

                gs, go, gx, l = jax.lax.cond(idx == S - 1, last, inner, None)

                def embed_bwd(_):
                    tok = jax.lax.dynamic_index_in_dim(
                        mb_in, mb_, 0, keepdims=False)
                    _, pull = jax.vjp(lambda o: embed_fn(o, tok), outer)
                    (go0,) = pull(gx)
                    return jax.tree.map(jnp.add, go, go0)

                go = jax.lax.cond(idx == 0, embed_bwd, lambda _: go, None)
                return gs, go, gx, l

            gs_d, go_d, gx_send, l = jax.lax.cond(
                bk, do_b,
                lambda _: (zeros_sp, zeros_outer, zero_act, f32_0), None)
            g_sp = jax.tree.map(jnp.add, g_sp, gs_d)
            g_out = jax.tree.map(jnp.add, g_out, go_d)
            loss_acc = loss_acc + l

            # ---- neighbor transfers + latches (collectives stay
            # OUTSIDE every cond: all devices participate every tick).
            # The barrier serializes the two transfers: XLA gives every
            # manual-mode collective the same channel id, and the CPU
            # thunk executor runs independent collectives concurrently,
            # so without a data dependency the two permutes join each
            # other's rendezvous and deadlock.  Sequential same-channel
            # collectives are safe (each epoch is a full barrier — the
            # same property every scan-over-ppermute pipeline relies on).
            recv_a = jax.lax.ppermute(y_send, axis, fwd_perm)
            gx_send = jax.lax.optimization_barrier((gx_send, recv_a))[0]
            recv_c = jax.lax.ppermute(gx_send, axis, bwd_perm)
            h_act = jnp.where(jnp.take(lfs, idx), recv_a, h_act)
            h_cot = jnp.where(jnp.take(rbs, idx), recv_c, h_cot)
            return (h_act, h_cot, ringbuf, g_sp, g_out, loss_acc), None

        ringbuf0 = varying(
            jnp.zeros((ring,) + act.shape, act.dtype))
        carry0 = (zero_act, zero_act, ringbuf0, zeros_sp, zeros_outer, f32_0)
        (_, _, _, g_sp, g_out, loss_acc), _ = jax.lax.scan(tick, carry0, rows)

        loss = jax.lax.psum(loss_acc, axis) / M
        g_out = jax.lax.psum(g_out, axis)
        if batch_axis is not None:  # DP composition: mean over data rows
            n = mesh.shape[batch_axis]
            loss = jax.lax.psum(loss, batch_axis) / n
            g_out = jax.tree.map(
                lambda g: jax.lax.psum(g, batch_axis) / n, g_out)
            g_sp = jax.tree.map(
                lambda g: jax.lax.psum(g, batch_axis) / n, g_sp)
        return loss, jax.tree.map(lambda g: g[None], g_sp), g_out

    run.schedule = sched
    run.utilization = 2 * M / sched.ticks
    return run


def make_train_step_1f1b(
    stage_fn: Callable,
    embed_fn: Callable,
    head_fn: Callable,
    optimizer: Optimizer,
    mesh: Mesh,
    axis: str = PIPE_AXIS,
    num_microbatches: Optional[int] = None,
    batch_axis: Optional[str] = None,
    donate: bool = True,
    input_key: str = "tokens",
    label_key: Optional[str] = None,
):
    """Compile a full 1F1B training step.

    ``TrainState.params`` is the split tree ``{"outer": ..., "stages":
    ...}`` (``lm_pp_1f1b``'s ``split_params`` builds it for the LM).
    Gradients never leave their pipe device except the psum'd outer
    tree, so the optimizer update is stage-local like the GPipe step
    (``pp.make_train_step_pp``).  ``label_key`` defaults to
    ``input_key`` (next-token LM losses read the shifted inputs).
    """
    run = pipeline_grads_1f1b(
        stage_fn, embed_fn, head_fn, mesh, axis=axis,
        num_microbatches=num_microbatches, batch_axis=batch_axis,
    )
    repl = NamedSharding(mesh, P())
    state_shardings = split_state_shardings(mesh, axis)

    def step(state: TrainState, batch):
        loss, g_stages, g_outer = run(
            state.params["stages"], state.params["outer"],
            batch[input_key], batch[label_key or input_key],
        )
        grads = {"outer": g_outer, "stages": g_stages}
        new_params, new_opt = optimizer.apply(
            state.params, grads, state.opt_state, state.step
        )
        return TrainState(
            params=new_params, opt_state=new_opt,
            model_state=state.model_state, step=state.step + 1,
        ), {"loss": loss}

    def compile_for(state: TrainState):
        sh = state_shardings(state)
        return jax.jit(
            step,
            in_shardings=(sh, repl),
            out_shardings=(sh, repl),
            donate_argnums=(0,) if donate else (),
        )

    return compile_for
