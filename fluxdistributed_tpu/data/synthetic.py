"""Synthetic datasets for tests, smoke runs and benchmarks.

The reference's tests use ``rand(Float32, ...)`` inputs and random one-hot
labels (test/single_device.jl:117-118) rather than stored fixtures; this
module is the structured version of that idea.  ``SyntheticDataset`` is
*learnable* (each class has a distinct mean image), so end-to-end trainer
tests can assert that the loss actually falls — a stronger check than the
reference's.
"""

from __future__ import annotations

import numpy as np

__all__ = ["SyntheticDataset"]


class SyntheticDataset:
    """Deterministic, learnable fake image classification data.

    Implements the framework's dataset protocol:

    * ``nsamples`` — table size (the analog of the reference's sample-key
      DataFrame row count, src/imagenet.jl:58-75),
    * ``nclasses``,
    * ``batch(rng, n, indices=None) -> (images [n,H,W,C] f32, labels [n] i32)``
      — with-replacement random sampling, as the reference's ``minibatch``
      sampler does (``key[rand(1:nrow, nsamples), :]`` src/imagenet.jl:24).
    """

    def __init__(
        self,
        nsamples: int = 1024,
        nclasses: int = 10,
        shape: tuple[int, int, int] = (32, 32, 3),
        seed: int = 0,
        noise: float = 0.3,
    ):
        self.nsamples = nsamples
        self.nclasses = nclasses
        self.shape = shape
        self.noise = noise
        root = np.random.default_rng(seed)
        # one low-frequency template per class
        self.templates = root.normal(0.0, 1.0, size=(nclasses, *shape)).astype(np.float32)
        self.labels_table = root.integers(0, nclasses, size=nsamples).astype(np.int32)

    def __len__(self) -> int:
        return self.nsamples

    def batch(self, rng: np.random.Generator, n: int, indices=None):
        if indices is None:
            indices = rng.integers(0, self.nsamples, size=n)  # with replacement
        labels = self.labels_table[np.asarray(indices)]
        imgs = self.templates[labels] + rng.normal(
            0.0, self.noise, size=(len(labels), *self.shape)
        ).astype(np.float32)
        return imgs.astype(np.float32), labels
