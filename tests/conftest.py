"""Test harness: 8 virtual CPU devices.

The analog of the reference's fake-device story (test/single_device.jl:
121-151 — integer fake devices that work because ``@device!`` is a no-op
without CUDA): here the very same SPMD mesh code runs against
``--xla_force_host_platform_device_count=8`` CPU devices, so every
sharding/collective path is exercised on CI hardware.

Must run before any test initializes a JAX backend; this image's
sitecustomize imports jax at interpreter start, so the platform override
has to go through ``jax.config`` (which ``force_host_devices`` does).
"""

from fluxdistributed_tpu.mesh import force_host_devices

force_host_devices(8)

# The bench cross-run ledger (bench.append_run_record) defaults to the
# COMMITTED benchmarks/hw/runs.jsonl — a test run must never append to
# repo history.  Empty string disables (tests that exercise the ledger
# monkeypatch.setenv a tmp path over this).
import os  # noqa: E402

os.environ.setdefault("FDTPU_RUNS_LEDGER", "")
