"""Minimal torch ViT with torchvision-compatible parameter names.

Test fixture only (torchvision is not in this image): the standard
Vision Transformer (Dosovitskiy et al.) with exactly the state_dict
layout torchvision's ``VisionTransformer`` exports — ``conv_proj``,
``class_token``, ``encoder.pos_embedding``,
``encoder.layers.encoder_layer_{i}.{ln_1,self_attention,ln_2,mlp}``,
``encoder.ln``, ``heads.head`` — consumed by
``models/torch_import.py::import_torch_vit``.
"""

from __future__ import annotations

import torch
import torch.nn as nn


class EncoderLayer(nn.Module):
    def __init__(self, dim, heads, mlp_dim):
        super().__init__()
        self.ln_1 = nn.LayerNorm(dim, eps=1e-6)
        self.self_attention = nn.MultiheadAttention(dim, heads, batch_first=True)
        self.ln_2 = nn.LayerNorm(dim, eps=1e-6)
        # torchvision MLPBlock is an nn.Sequential: 0 Linear, 1 GELU,
        # 2 Dropout, 3 Linear, 4 Dropout -> keys mlp.0.* / mlp.3.*
        self.mlp = nn.Sequential(
            nn.Linear(dim, mlp_dim), nn.GELU(), nn.Dropout(0.0),
            nn.Linear(mlp_dim, dim), nn.Dropout(0.0),
        )

    def forward(self, x):
        y = self.ln_1(x)
        y, _ = self.self_attention(y, y, y, need_weights=False)
        x = x + y
        return x + self.mlp(self.ln_2(x))


class Encoder(nn.Module):
    def __init__(self, ntok, dim, depth, heads, mlp_dim):
        super().__init__()
        self.pos_embedding = nn.Parameter(torch.empty(1, ntok, dim).normal_(std=0.02))
        self.layers = nn.ModuleDict(
            {f"encoder_layer_{i}": EncoderLayer(dim, heads, mlp_dim)
             for i in range(depth)}
        )
        self.ln = nn.LayerNorm(dim, eps=1e-6)

    def forward(self, x):
        x = x + self.pos_embedding
        for i in range(len(self.layers)):
            x = self.layers[f"encoder_layer_{i}"](x)
        return self.ln(x)


class TorchViT(nn.Module):
    def __init__(self, image_size=32, patch=8, dim=64, depth=2, heads=4,
                 mlp_dim=128, num_classes=10):
        super().__init__()
        ntok = (image_size // patch) ** 2 + 1
        self.patch = patch
        self.conv_proj = nn.Conv2d(3, dim, patch, patch)
        self.class_token = nn.Parameter(torch.zeros(1, 1, dim))
        self.encoder = Encoder(ntok, dim, depth, heads, mlp_dim)
        self.heads = nn.Sequential()
        self.heads.add_module("head", nn.Linear(dim, num_classes))

    def forward(self, x):
        n = x.shape[0]
        x = self.conv_proj(x)  # (N, D, H', W')
        x = x.flatten(2).transpose(1, 2)  # (N, HW, D)
        cls = self.class_token.expand(n, -1, -1)
        x = torch.cat([cls, x], dim=1)
        x = self.encoder(x)
        return self.heads(x[:, 0])
