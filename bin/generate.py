#!/usr/bin/env python
"""LM sampling CLI — generate text from a trained checkpoint.

The LM-side analog of ``bin/infer.py`` (the reference's inference demo
is vision-only, bin/pluto.jl:338-382): loads an orbax checkpoint
produced by ``bin/driver.py --model lm_*``, rebuilds the model in
``decode=True`` KV-cache mode, and samples from a prompt — byte-level
prompts/outputs for ``text:`` corpora (vocab 256), integer token
prompts otherwise.

    # train, then sample from the same checkpoint dir
    python bin/driver.py --model lm_tiny --dataset text:corpus.txt \
        --seqlen 128 --batch-size 64 --epochs 2 --checkpoint-dir ck/
    python bin/generate.py --model lm_tiny --checkpoint ck/ \
        --prompt "The quick" --length 200 --temperature 0.8

    # no checkpoint -> random-init demo (structure smoke test)
    python bin/generate.py --model lm_tiny --vocab 64 --prompt-tokens 3,1,4
"""

from __future__ import annotations

import argparse
import sys


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--model", default="lm_tiny",
                   help="lm factory name in fluxdistributed_tpu.models "
                        "(lm_tiny/lm_small/lm_medium)")
    p.add_argument("--vocab", type=int, default=256,
                   help="vocab size (256 = byte-level, text: corpora)")
    p.add_argument("--checkpoint", default=None,
                   help="checkpoint dir from the trainer (latest step used; "
                        "random init if omitted). May be an http(s):// or "
                        "gs:// URL — a remote .zip of the checkpoint dir is "
                        "fetched and unpacked through the dataset source "
                        "cache (data/sources.py)")
    p.add_argument("--gpt2-weights", default=None,
                   help="a torch-saved HF GPT2LMHeadModel state_dict (.pt): "
                        "the model config is inferred from the weights and "
                        "--model/--vocab/--norm/--mlp are ignored. May be "
                        "an http(s):// or gs:// URL (fetched + cached)")
    p.add_argument("--engine", action="store_true",
                   help="decode through the continuous-batching engine "
                        "(fluxdistributed_tpu.serve) instead of the "
                        "lax.scan sampler — same greedy output token for "
                        "token; temperature sampling uses the engine's "
                        "per-request key stream")
    p.add_argument("--gpt2-heads", type=int, default=None,
                   help="GPT-2 head count (default: dim // 64, the GPT-2 "
                        "family convention)")
    p.add_argument("--step", type=int, default=None, help="specific checkpoint step")
    p.add_argument("--prompt", default=None,
                   help="text prompt, encoded as UTF-8 bytes (needs vocab>=256)")
    p.add_argument("--prompt-tokens", default=None,
                   help="comma-separated integer token prompt")
    p.add_argument("--length", type=int, default=128,
                   help="total sequence length incl. the prompt")
    p.add_argument("--temperature", type=float, default=0.0,
                   help="0 = greedy; >0 samples")
    p.add_argument("--top-k", type=int, default=0,
                   help="keep only the k highest logits (0 = off)")
    p.add_argument("--top-p", type=float, default=1.0,
                   help="nucleus sampling threshold (1.0 = off)")
    p.add_argument("--seed", type=int, default=0, help="sampling seed")
    p.add_argument("--platform", default=None,
                   help="force platform (e.g. cpu)")
    # architecture flags — must MATCH the training run's driver flags or
    # the checkpoint's param tree will not fit the rebuilt decode model
    p.add_argument("--kv-heads", type=int, default=None,
                   help="match the trainer's --kv-heads (GQA)")
    p.add_argument("--window", type=int, default=None,
                   help="match the trainer's --window (rolling KV cache)")
    p.add_argument("--sinks", type=int, default=0,
                   help="match the trainer's --sinks (attention sinks)")
    p.add_argument("--norm", default="layernorm",
                   choices=["layernorm", "rmsnorm"],
                   help="match the trainer's --norm")
    p.add_argument("--mlp", default="gelu", choices=["gelu", "swiglu"],
                   help="match the trainer's --mlp")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)

    import jax
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    import numpy as np

    from fluxdistributed_tpu import models

    if args.prompt is not None and args.prompt_tokens is not None:
        raise SystemExit("pass --prompt OR --prompt-tokens, not both")
    if args.gpt2_weights:
        # GPT-2 vocab/limits come from the weights; validate against
        # THOSE (not the --vocab default) and reject byte prompts (a
        # BPE model has no byte-level mapping and no tokenizer here)
        if args.prompt is not None:
            raise SystemExit("--gpt2-weights has no tokenizer; pass "
                             "--prompt-tokens (BPE ids)")
        return _gpt2_main(args)
    if args.prompt is not None:
        if args.vocab < 256:
            raise SystemExit("--prompt is byte-encoded; needs --vocab >= 256")
        prompt = np.frombuffer(args.prompt.encode("utf-8"), np.uint8).astype(np.int32)
    elif args.prompt_tokens is not None:
        prompt = np.asarray([int(t) for t in args.prompt_tokens.split(",")], np.int32)
        if prompt.min() < 0 or prompt.max() >= args.vocab:
            raise SystemExit(f"prompt tokens must be in [0, {args.vocab})")
    else:
        prompt = np.zeros(1, np.int32)
    # <= : a prompt of exactly --length is valid per the generate()
    # contract (nothing to sample — it returns the prompt unchanged)
    if not (0 < len(prompt) <= args.length):
        raise SystemExit(
            f"prompt length {len(prompt)} must be in (0, --length {args.length}]"
        )

    model_fn = getattr(models, args.model)
    arch = {"num_kv_heads": args.kv_heads, "window": args.window,
            "sinks": args.sinks, "norm": args.norm, "mlp": args.mlp}
    dm = model_fn(vocab=args.vocab, decode=True, **arch)
    train_model = model_fn(vocab=args.vocab, **arch)

    if args.checkpoint:
        from fluxdistributed_tpu.data.sources import fetch_checkpoint
        from fluxdistributed_tpu.train import load_checkpoint

        args.checkpoint = fetch_checkpoint(args.checkpoint)
        restored = load_checkpoint(args.checkpoint, step=args.step)
        params = restored["params"]
        print(f"loaded checkpoint step "
              f"{int(np.asarray(restored.get('step', -1)))} from {args.checkpoint}",
              file=sys.stderr)
    else:
        params = train_model.init(
            jax.random.PRNGKey(0), prompt[None][:, :2], train=False
        )["params"]
        print("no --checkpoint: sampling from a RANDOM-INIT model", file=sys.stderr)

    if args.engine:
        out = _engine_generate(args, train_model, params, prompt)
    else:
        out = np.asarray(models.generate(
            dm, params, prompt[None], total_len=args.length,
            temperature=args.temperature, top_k=args.top_k, top_p=args.top_p,
            rng=jax.random.PRNGKey(args.seed) if args.temperature > 0 else None,
        ))[0]
    _emit(args, out)
    return 0


def _engine_generate(args, train_model, params, prompt):
    """One prompt through the serving engine's decode core — the CLI and
    the server share one compiled-step implementation."""
    import numpy as np

    from fluxdistributed_tpu.serve import LMEngine, Request, Scheduler

    if args.top_k or args.top_p < 1.0:
        raise SystemExit("--engine does not support --top-k/--top-p "
                         "(drop them or use the lax.scan sampler)")
    if args.length == len(prompt):
        return np.asarray(prompt)  # score-only: the generate() contract
    # bucket at the PROMPT length (the ladder tops up to --length
    # itself): prefill then runs over plen positions, not a --length-
    # padded buffer — same work as the lax.scan path's prefill
    engine = LMEngine(train_model, params, max_slots=1, max_len=args.length,
                      buckets=(len(prompt),))
    sched = Scheduler(engine)
    req = Request(prompt=list(prompt),
                  max_new_tokens=args.length - len(prompt),
                  temperature=args.temperature, seed=args.seed)
    return np.asarray(sched.generate_all([req])[0], np.int32)


def _gpt2_main(args) -> int:
    """HF GPT-2 interop: architecture inferred from the weights
    (``models.torch_import.gpt2_config`` owns the key-layout knowledge)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import torch

    from fluxdistributed_tpu import models
    from fluxdistributed_tpu.models import import_gpt2
    from fluxdistributed_tpu.models.torch_import import gpt2_config
    from fluxdistributed_tpu.models.transformer_lm import TransformerLM

    from fluxdistributed_tpu.data.sources import fetch_artifact

    sd = torch.load(fetch_artifact(args.gpt2_weights), map_location="cpu",
                    weights_only=True)
    try:
        cfg = gpt2_config(sd)
    except ValueError as e:
        raise SystemExit(str(e)) from None
    heads = args.gpt2_heads or max(cfg["dim"] // 64, 1)
    if args.length > cfg["n_positions"]:
        raise SystemExit(f"--length {args.length} exceeds the GPT-2 "
                         f"positional table ({cfg['n_positions']})")
    args.vocab = cfg["vocab"]
    if args.prompt_tokens is not None:
        prompt = np.asarray([int(t) for t in args.prompt_tokens.split(",")],
                            np.int32)
        if prompt.min() < 0 or prompt.max() >= args.vocab:
            raise SystemExit(f"prompt tokens must be in [0, {args.vocab})")
    else:
        prompt = np.zeros(1, np.int32)
    if not (0 < len(prompt) <= args.length):
        raise SystemExit(
            f"prompt length {len(prompt)} must be in (0, --length "
            f"{args.length}]")

    params, _ = import_gpt2(sd, num_heads=heads, seqlen=args.length)
    tm = TransformerLM(
        vocab=cfg["vocab"], depth=cfg["depth"], dim=cfg["dim"],
        num_heads=heads, mlp_dim=cfg["mlp_dim"], dtype=jnp.float32,
        dropout=0.0, use_rope=False, norm_eps=1e-5, max_len=args.length,
    )
    print(f"loaded GPT-2 weights: depth={cfg['depth']} d={cfg['dim']} "
          f"heads={heads} vocab={cfg['vocab']}", file=sys.stderr)
    if args.engine:
        out = _engine_generate(args, tm, params, prompt)
    else:
        out = np.asarray(models.generate(
            tm.clone(decode=True), params, prompt[None],
            total_len=args.length,
            temperature=args.temperature, top_k=args.top_k, top_p=args.top_p,
            rng=jax.random.PRNGKey(args.seed) if args.temperature > 0 else None,
        ))[0]
    _emit(args, out)
    return 0


def _emit(args, toks) -> None:
    if args.vocab == 256:
        from fluxdistributed_tpu.data import ByteTextDataset

        print(ByteTextDataset.decode(toks))
    else:
        print(",".join(str(int(t)) for t in toks))


if __name__ == "__main__":
    sys.exit(main())
