"""Worker for multi-process pipeline- and expert-parallel tests.

Launched as ``python tests/_mh_ppep_worker.py <pid> <nproc> <port>`` by
tests/test_multihost.py.  Each process owns 4 virtual CPU devices; the
``pipe`` / ``expert`` mesh axes span all ``4 * nproc`` devices, so the
schedule's ``ppermute`` hops and the MoE dispatch ``all_to_all`` cross a
real process boundary (the DCN stand-in) — single-process 8-device tests
cannot exercise that path (VERDICT r3 weak #5).  Parity is asserted
against locally-computed dense references, shard by shard via
``addressable_shards`` (no cross-process gather needed).
"""

import sys

import numpy as np


def _check_shards(got, want, what: str, rtol=1e-5, atol=1e-5):
    """Compare every locally-addressable shard of a (possibly
    cross-process) jax.Array against the matching slice of a full host
    reference."""
    want = np.asarray(want)
    for sh in got.addressable_shards:
        np.testing.assert_allclose(
            np.asarray(sh.data), want[sh.index], rtol=rtol, atol=atol,
            err_msg=f"{what}: shard {sh.index} mismatch",
        )


def main() -> int:
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

    from fluxdistributed_tpu.parallel import multihost

    multihost.initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=nproc,
        process_id=pid,
        platform="cpu",
        local_devices=4,
    )

    import jax
    import jax.numpy as jnp

    n_dev = 4 * nproc
    assert jax.device_count() == n_dev, jax.device_count()

    import fluxdistributed_tpu.mesh as mesh_lib
    from fluxdistributed_tpu import sharding
    from fluxdistributed_tpu.parallel.ep import (
        moe_apply, router_dispatch, stack_expert_params,
    )
    from fluxdistributed_tpu.parallel.pp import pipeline_apply, stack_stage_params

    D = 16

    # ---- pipeline parallelism across the process boundary -------------
    mesh = mesh_lib.make_mesh({"pipe": n_dev})

    def stage_fn(params, x):
        return x + jax.nn.gelu(x @ params["w"] + params["b"])

    keys = jax.random.split(jax.random.PRNGKey(0), n_dev)
    per_stage = [
        {"w": jax.random.normal(k, (D, D), jnp.float32) * 0.3,
         "b": jnp.zeros((D,), jnp.float32)}
        for k in keys
    ]
    x = jax.random.normal(jax.random.PRNGKey(1), (8, D), jnp.float32)
    stacked = stack_stage_params(per_stage, mesh)
    fwd = pipeline_apply(stage_fn, mesh, num_microbatches=4)
    got = np.asarray(fwd(stacked, sharding.replicate(x, mesh)))

    want = x
    for p in per_stage:
        want = stage_fn(p, want)
    np.testing.assert_allclose(got, np.asarray(want), rtol=1e-5, atol=1e-5)
    print(f"worker {pid}: PP forward parity OK", flush=True)

    # backward: the reverse pipeline's ppermutes cross the boundary too
    xr = sharding.replicate(x, mesh)

    @jax.jit
    def g_pp(params, xin):
        return jax.grad(lambda p: jnp.mean(fwd(p, xin) ** 2))(params)

    grads = g_pp(stacked, xr)

    def loss_seq(stages):
        y = x
        for p in stages:
            y = stage_fn(p, y)
        return jnp.mean(y ** 2)

    g_seq = jax.grad(loss_seq)(per_stage)
    want_g = jax.tree.map(lambda *xs: np.stack([np.asarray(v) for v in xs]), *g_seq)
    for (path_got, lg), (_, lw) in zip(
        jax.tree_util.tree_flatten_with_path(grads)[0],
        jax.tree_util.tree_flatten_with_path(want_g)[0],
    ):
        _check_shards(lg, lw, f"PP grad {path_got}", rtol=1e-4, atol=1e-3)
    print(f"worker {pid}: PP backward parity OK", flush=True)

    # ---- hand-scheduled 1F1B across the process boundary --------------
    # per-tick activation AND cotangent ppermutes, per-device cond
    # divergence, and the end-of-scan psums all cross the DCN stand-in
    from fluxdistributed_tpu.parallel.pp_1f1b import pipeline_grads_1f1b

    DIN, NCLS = 8, 6

    def embed_fn(outer, xin):
        return jnp.tanh(xin @ outer["w_in"])

    def head_fn(outer, y, labels):
        logp = jax.nn.log_softmax(y @ outer["w_out"])
        return -jnp.mean(jnp.sum(labels * logp, axis=-1))

    okeys = jax.random.split(jax.random.PRNGKey(5), 2)
    outer = {
        "w_in": jax.random.normal(okeys[0], (DIN, D), jnp.float32) * 0.4,
        "w_out": jax.random.normal(okeys[1], (D, NCLS), jnp.float32) * 0.4,
    }
    rng1 = np.random.default_rng(6)
    xb = jnp.asarray(rng1.normal(0, 1, (16, DIN)).astype(np.float32))
    labels = jnp.asarray(
        np.eye(NCLS, dtype=np.float32)[rng1.integers(0, NCLS, 16)])

    run = pipeline_grads_1f1b(
        stage_fn, embed_fn, head_fn, mesh, num_microbatches=8)
    loss, g_stages, g_outer = jax.jit(run)(
        stacked, sharding.replicate(outer, mesh),
        sharding.replicate(xb, mesh), sharding.replicate(labels, mesh))

    m_ = 8
    xs = xb.reshape(m_, 16 // m_, DIN)
    ls = labels.reshape(m_, 16 // m_, NCLS)

    def ref_loss(outer_, stages_):
        def one(x_mb, l_mb):
            h = embed_fn(outer_, x_mb)
            for p in stages_:
                h = stage_fn(p, h)
            return head_fn(outer_, h, l_mb)

        return jnp.mean(jax.vmap(one)(xs, ls))

    loss_ref, (go_ref, gs_ref) = jax.value_and_grad(
        ref_loss, argnums=(0, 1))(outer, per_stage)
    np.testing.assert_allclose(float(loss), float(loss_ref), rtol=1e-5)
    want_gs = jax.tree.map(
        lambda *vs: np.stack([np.asarray(v) for v in vs]), *gs_ref)
    for (path_got, lg), (_, lw) in zip(
        jax.tree_util.tree_flatten_with_path(g_stages)[0],
        jax.tree_util.tree_flatten_with_path(want_gs)[0],
    ):
        _check_shards(lg, lw, f"1F1B stage grad {path_got}", rtol=1e-4, atol=1e-4)
    for (path_got, lg), (_, lw) in zip(
        jax.tree_util.tree_flatten_with_path(g_outer)[0],
        jax.tree_util.tree_flatten_with_path(go_ref)[0],
    ):
        _check_shards(lg, lw, f"1F1B outer grad {path_got}", rtol=1e-4, atol=1e-4)
    print(f"worker {pid}: 1F1B cross-process parity OK", flush=True)

    # ---- expert parallelism (MoE all_to_all) across the boundary ------
    E = n_dev
    T = 64
    emesh = mesh_lib.make_mesh({"expert": E})

    def expert_fn(params, x):
        return jax.nn.gelu(x @ params["w1"]) @ params["w2"]

    ekeys = jax.random.split(jax.random.PRNGKey(2), E)
    per_expert = [
        {"w1": jax.random.normal(jax.random.fold_in(k, 0), (D, 2 * D), jnp.float32) * 0.3,
         "w2": jax.random.normal(jax.random.fold_in(k, 1), (2 * D, D), jnp.float32) * 0.3}
        for k in ekeys
    ]
    router_w = jax.random.normal(jax.random.PRNGKey(3), (D, E), jnp.float32)
    toks = np.asarray(
        jax.random.normal(jax.random.PRNGKey(4), (T, D), jnp.float32)
    )

    stacked_e = stack_expert_params(per_expert, emesh)
    router_g = sharding.replicate(router_w, emesh)
    toks_g = sharding.shard_batch({"x": toks}, emesh, axis="expert")["x"]
    fn = moe_apply(expert_fn, emesh, capacity_factor=1.25)
    out, aux = fn(stacked_e, router_g, toks_g)

    # dense reference: routing is per token shard, exactly moe_apply's math
    import math

    t_loc = T // E
    cap = max(1, math.ceil(t_loc / E * 1.25))

    def golden_block(s):
        xs = jnp.asarray(toks[s * t_loc:(s + 1) * t_loc])
        dispatch, combine, a = router_dispatch(xs @ router_w, cap, k=1)
        ein = jnp.einsum("td,tec->ecd", xs, dispatch)
        y = jnp.stack([expert_fn(p, ein[e]) for e, p in enumerate(per_expert)])
        return jnp.einsum("ecd,tec->td", y, combine), a

    blocks = [golden_block(s) for s in range(E)]
    want_out = np.concatenate([np.asarray(o) for o, _ in blocks])
    want_aux = float(np.mean([float(a) for _, a in blocks]))
    _check_shards(out, want_out, "EP forward")
    np.testing.assert_allclose(float(aux), want_aux, rtol=1e-5)
    print(f"worker {pid}: EP forward parity OK", flush=True)

    # backward: grads flow through both all_to_alls across the boundary
    @jax.jit
    def g_ep(params, rw, tks):
        def lossf(p):
            y, a = fn(p, rw, tks)
            return jnp.mean(y ** 2) + a
        return jax.grad(lossf)(params)

    egrads = g_ep(stacked_e, router_g, toks_g)

    def loss_dense(params_list):
        tot = 0.0
        auxes = 0.0
        for s in range(E):
            xs = jnp.asarray(toks[s * t_loc:(s + 1) * t_loc])
            dispatch, combine, a = router_dispatch(xs @ router_w, cap, k=1)
            ein = jnp.einsum("td,tec->ecd", xs, dispatch)
            y = jnp.stack(
                [expert_fn(p, ein[e]) for e, p in enumerate(params_list)]
            )
            o = jnp.einsum("ecd,tec->td", y, combine)
            tot = tot + jnp.sum(o ** 2)
            auxes = auxes + a
        return tot / (T * D) + auxes / E

    eg_seq = jax.grad(loss_dense)(per_expert)
    want_eg = jax.tree.map(lambda *xs: np.stack([np.asarray(v) for v in xs]), *eg_seq)
    for (path_got, lg), (_, lw) in zip(
        jax.tree_util.tree_flatten_with_path(egrads)[0],
        jax.tree_util.tree_flatten_with_path(want_eg)[0],
    ):
        _check_shards(lg, lw, f"EP grad {path_got}", rtol=1e-4, atol=1e-3)
    print(f"worker {pid}: EP backward parity OK", flush=True)

    multihost.sync_global_devices("ppep_done")
    print(f"worker {pid}: OK", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
