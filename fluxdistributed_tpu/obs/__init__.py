"""Unified observability layer (ROADMAP: the instrumentation substrate
every perf PR reports through).

One registry, four producers, three consumers:

* :mod:`.metrics` — process-wide Counter/Gauge/Histogram registry with
  Prometheus text exposition and a JSONL snapshot sink;
* :mod:`.spans` — contextvar-nested step-phase spans exporting
  Chrome/Perfetto trace-event JSON;
* :mod:`.jaxmon` — ``jax.monitoring`` listeners: compile counts/seconds
  and steady-state recompile flagging;
* :mod:`.watchdog` — rolling-median heartbeat stall detection (+ the
  OOM-skip counter and the HBM low-headroom alert);
* :mod:`.memstats` — static per-program memory model
  (``memory_analysis`` through the compat shim) + live per-device HBM
  gauges (``fdtpu_hbm_*``, None-safe on CPU);
* :mod:`.comms` — the collective-traffic ledger (jaxpr + compiled-HLO
  collective counts/bytes per step per mesh axis);
* :mod:`.server` — stdlib-HTTP ``/metrics`` + ``/healthz`` (the
  training-side analog of the LM server's endpoints);
* :mod:`.flight` — the black-box flight recorder: a bounded ring of
  per-step records flushed append-only with atomic checkpoints, so a
  SIGKILL loses at most one flush interval of history;
* :mod:`.runs` — the cross-run ledger (``runs.jsonl``): one record per
  run/round/episode keyed by topology fingerprint, with regression
  gating and the merged postmortem (``bin/trends.py``).

:class:`Observation` bundles the per-run pieces for the trainer:
``train(task, observation=Observation.full(trace_path="run.trace.json"))``
gets phase spans, a stall watchdog, per-step device sync timing and a
trace file; the default (``None``) still feeds step counters, phase
histograms and compile counts into the process registry for free.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from . import comms, jaxmon, memstats, runs
from .flight import FlightRecorder, read_flight
from .memstats import HbmGauges
from .metrics import (
    Counter,
    Gauge,
    Histogram,
    JsonlSink,
    Registry,
    bucket_percentile,
    get_registry,
)
from .profile import Profile, ProfileMismatch, collect_profile
from .reqtrace import RequestTracer
from .server import MetricsServer, start_metrics_server
from .spans import SpanTracer, current_span, innermost_active
from .watchdog import StepWatchdog

__all__ = [
    "Counter",
    "FlightRecorder",
    "Gauge",
    "HbmGauges",
    "Histogram",
    "JsonlSink",
    "MetricsServer",
    "Observation",
    "Profile",
    "ProfileMismatch",
    "Registry",
    "RequestTracer",
    "SpanTracer",
    "StepWatchdog",
    "bucket_percentile",
    "collect_profile",
    "comms",
    "current_span",
    "get_registry",
    "innermost_active",
    "jaxmon",
    "memstats",
    "read_flight",
    "runs",
    "start_metrics_server",
]


@dataclasses.dataclass
class Observation:
    """What the training loop should instrument, bundled.

    Attributes
    ----------
    registry: where counters/histograms live (default: process registry)
    tracer: span tracer, or None for metrics-only (no timeline buffer)
    watchdog: stall watchdog, or None; ``train`` starts/stops it
    trace_path: write the tracer's Chrome trace JSON here when training
        ends (requires ``tracer``)
    device_sync: ``block_until_ready`` each step's outputs inside a
        ``device`` span.  This closes the host's dispatch run-ahead, so
        the device phase is honestly attributed — worth it when you are
        reading a breakdown, wrong as an always-on default (it
        serializes host and device).
    steady_after: after this many loader items, declare
        :func:`jaxmon.mark_steady` — any later XLA compile is flagged as
        a steady-state recompile.  None (default) = never; eval or
        remainder batches legitimately compile late in short runs.
    """

    registry: Registry = dataclasses.field(default_factory=get_registry)
    tracer: Optional[SpanTracer] = None
    watchdog: Optional[StepWatchdog] = None
    trace_path: Optional[str] = None
    device_sync: bool = False
    steady_after: Optional[int] = None
    # append a registry snapshot line here at the print cadence and at
    # exit (offline run diffing — no Prometheus server required)
    jsonl_path: Optional[str] = None
    # write a versioned cost-profile artifact (obs.profile.Profile:
    # static per-layer/step costs + the run's measured phase data) here
    # when training ends — the planner-facing output of a profiled run
    profile_path: Optional[str] = None
    # black-box flight recorder: either pass a live FlightRecorder
    # (``flight``) or a path (``flight_path``) and ``train`` constructs
    # one; the dump survives any exit including SIGKILL (minus at most
    # one flush interval)
    flight: Optional[FlightRecorder] = None
    flight_path: Optional[str] = None

    @classmethod
    def default(cls) -> "Observation":
        """Metrics-only: counters + phase histograms in the process
        registry; no span buffer, no watchdog thread, no device sync."""
        return cls()

    @classmethod
    def full(
        cls,
        trace_path: Optional[str] = None,
        registry: Optional[Registry] = None,
        watchdog_factor: float = 5.0,
        steady_after: Optional[int] = None,
        jsonl_path: Optional[str] = None,
        profile_path: Optional[str] = None,
        flight_path: Optional[str] = None,
    ) -> "Observation":
        """Everything on: spans (the trainer feeds the phase histogram
        from the same brackets), stall watchdog, per-step device sync."""
        registry = registry or get_registry()
        return cls(
            registry=registry,
            tracer=SpanTracer(),
            watchdog=StepWatchdog(factor=watchdog_factor, registry=registry),
            trace_path=trace_path,
            device_sync=True,
            steady_after=steady_after,
            jsonl_path=jsonl_path,
            profile_path=profile_path,
            flight_path=flight_path,
        )
