"""Preemption tolerance: fault injection, retries, and signal handling.

Production TPU fleets are preemptible by design — grant windows expire,
backends go unavailable mid-init, hosts get SIGTERMed, and the device
count on the next grant may differ from the last (see arXiv:2602.18007
for the degraded-/heterogeneous-fleet version of the same lesson).  The
reference implementation's only fault story is OOM-skip
(src/ddp_tasks.jl:230-238); every other interruption loses the run.
This module treats interruption as a *normal operating condition*:

* :class:`FaultPlan` — a deterministic injection registry, so every
  tolerance path is provable on a CPU dev box: SIGTERM at step k,
  transient data-loader exceptions, simulated backend-unavailable on
  init, a simulated device-count change on resume.  Hot paths call
  :func:`fire` at named sites; with no plan installed that is one
  module-global ``None`` check.
* :func:`with_retries` — the one retry/backoff/jitter/budget policy,
  used by backend acquisition (:func:`acquire_backend`) and checkpoint
  I/O (:mod:`.train.checkpoint`).
* :class:`SignalFlag` + :class:`Preempted` — checkpoint-on-signal
  machinery for the trainer: handlers set a flag, the step boundary
  checks it, ``train`` writes a sharded checkpoint + RESUME manifest
  and raises :class:`Preempted`; ``bin/driver.py`` maps that to exit
  code :data:`PREEMPTED_RC` so supervisors can tell "requeue me" from
  "I crashed".

Everything is instrumented with ``fdtpu_fault_*`` counters in the obs
registry, so a run's scrape says how often it was lied to and how often
it shrugged it off.
"""

from __future__ import annotations

import dataclasses
import os
import random
import signal
import threading
import time
from typing import Any, Callable, Dict, List, Optional

__all__ = [
    "FAULT_ACTIONS",
    "HALTED_RC",
    "HANG_DELAY_SECONDS",
    "PREEMPTED_RC",
    "UNAVAILABLE_SIGNATURES",
    "VALUE_ACTIONS",
    "BackendUnavailable",
    "FaultInjected",
    "FaultPlan",
    "Preempted",
    "RetryBudgetExceeded",
    "SignalFlag",
    "acquire_backend",
    "active_plan",
    "clear_plan",
    "fire",
    "fire_value",
    "install_plan",
    "param",
    "record_preemption",
    "with_retries",
]

#: exit code of a driver run that checkpointed and exited on SIGTERM —
#: EX_TEMPFAIL, the sysexits "try again later" code, distinct from both
#: success (0) and a crash (1/tracebacks): a supervisor that sees it
#: should requeue the run with ``--resume``.
PREEMPTED_RC = 75

#: exit code of a driver run the training guard HALTED (rollback loop:
#: anomalies recur faster than checkpoints make progress) — EX_DATAERR,
#: "the input data was incorrect".  Deliberately NOT retryable: a
#: supervisor that sees it must page a human instead of requeueing a
#: run that provably cannot make progress (``train/guard.py``).
HALTED_RC = 65


class FaultInjected(RuntimeError):
    """Base class of every exception a :class:`FaultPlan` raises."""


class BackendUnavailable(FaultInjected):
    """Simulated backend-unavailable (the tunneled-TPU init failure
    every dead bench round hit); :func:`retryable_error` in bench.py
    and :func:`acquire_backend` both treat the real-world signatures
    and this simulation identically."""


class RetryBudgetExceeded(RuntimeError):
    """:func:`with_retries` ran out of attempts/seconds; ``__cause__``
    is the last underlying error."""


class Preempted(RuntimeError):
    """Training was interrupted by SIGTERM/SIGINT and checkpointed at a
    step boundary.  Carries everything a supervisor needs to resume."""

    def __init__(self, message: str, *, step: int = 0, next_item: int = 0,
                 checkpoint_dir: Optional[str] = None,
                 manifest: Optional[dict] = None):
        super().__init__(message)
        self.step = step
        self.next_item = next_item
        self.checkpoint_dir = checkpoint_dir
        self.manifest = manifest or {}


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------


def _metrics():
    """The fdtpu_fault_* instruments, created lazily in the process
    registry (import cycles: obs imports nothing from here)."""
    from .obs import get_registry

    reg = get_registry()
    return {
        "injected": reg.counter(
            "fdtpu_fault_injected_total",
            "faults injected by the active FaultPlan", labelnames=("site",)),
        "retries": reg.counter(
            "fdtpu_fault_retries_total",
            "retry attempts after a retryable error", labelnames=("site",)),
        "giveups": reg.counter(
            "fdtpu_fault_giveups_total",
            "with_retries exhaustions (budget/attempts out)",
            labelnames=("site",)),
        "backoff": reg.counter(
            "fdtpu_fault_backoff_seconds_total",
            "seconds slept between retry attempts", labelnames=("site",)),
        "preemptions": reg.counter(
            "fdtpu_fault_preemptions_total",
            "SIGTERM/SIGINT checkpoint-and-exit events"),
    }


# ---------------------------------------------------------------------------
# fault plan
# ---------------------------------------------------------------------------


#: every action :meth:`FaultPlan.fail` / ``from_spec``'s ``fail`` key
#: accepts — the serve-side fault model needs more than exceptions:
#: ``exit`` is a replica crash (``os._exit`` — no drain, no atexit, the
#: SIGKILL shape), ``sleep`` is a slow replica (delay then continue),
#: ``hang`` is a wedged one (delay defaults to an hour — the caller's
#: timeout machinery is what's under test).  ``nan``/``inf`` are VALUE
#: corruptions: they only trigger at :func:`fire_value` sites (the
#: training guard's ``train.loss``/``train.grad`` sentinel taps) and
#: replace the observed value instead of raising — the RNG-free way to
#: prove every anomaly-detection path on a CPU dev box.
FAULT_ACTIONS = ("raise", "sigterm", "sigint", "exit", "sleep", "hang",
                 "nan", "inf")

#: the subset of :data:`FAULT_ACTIONS` that corrupts an observed value
#: rather than performing a side effect; matched only by
#: :func:`fire_value` (plain :func:`fire` skips them — a value
#: corruption without a value to corrupt is meaningless).
VALUE_ACTIONS = ("nan", "inf")

#: how long a "hang" action sleeps when no explicit delay is given —
#: far beyond any probe/dispatch/request timeout in the tree
HANG_DELAY_SECONDS = 3600.0


@dataclasses.dataclass
class _Fault:
    site: str
    at: Optional[int] = None        # trigger only when fire(index=at)
    times: int = 1                  # how many triggers remain
    action: str = "raise"           # one of FAULT_ACTIONS
    exc: Optional[Callable[[], BaseException]] = None
    message: str = "injected fault"
    delay: float = 0.0              # seconds for sleep/hang actions
    fired: int = 0                  # triggers delivered so far


class FaultPlan:
    """Deterministic injection registry.

    Sites wired into the framework:

    * ``"step"`` — the trainer's step boundary (``fire(index=j)`` with
      the loader-item index);
    * ``"loader"`` — host-side batch assembly inside a prefetch worker
      (``fire(index=i)`` with the batch index; the loader retries
      transient failures via :func:`with_retries`);
    * ``"backend_init"`` — inside :func:`acquire_backend`'s attempt,
      before ``jax.devices()``;
    * ``"resume"`` — entry of ``train.resume_training``;
    * ``"checkpoint_save"`` / ``"checkpoint_load"`` — inside the orbax
      write/read (retried by ``train/checkpoint.py``);
    * ``"serve.tick"`` — top of every serve ``Scheduler.step`` (``index``
      = the scheduler's tick count; an ``exit`` action here is a
      deterministic replica kill mid-burst, ``sleep``/``hang`` a slow or
      wedged engine loop);
    * ``"serve.dispatch"`` — inside the router's per-request dispatch
      attempt (retried across replicas by ``with_retries``);
    * ``"serve.probe"`` — inside the router's health-probe attempt
      (``index`` = the running probe count; failures feed the circuit
      breaker without any real outage);
    * ``"train.loss"`` / ``"train.grad"`` — VALUE sites inside the
      training guard's sentinel read (``fire_value(site, value,
      index=j)`` with the loader-item index): a ``nan``/``inf`` action
      replaces the observed loss / global grad-norm component, so every
      anomaly-detection + quarantine + rollback path is provable
      deterministically, RNG-free, with zero recompiles
      (``train/guard.py``).

    ``params`` is a free-form dict for harness knobs that are not
    exceptions — e.g. ``{"local_devices": 4}`` makes ``bin/driver.py``
    bring the backend up with a different virtual-device count, the
    simulated device-count-change-on-resume scenario.
    """

    def __init__(self):
        self._faults: List[_Fault] = []
        self.params: Dict[str, Any] = {}
        self._lock = threading.Lock()

    # -- construction --------------------------------------------------
    def fail(self, site: str, *, at: Optional[int] = None, times: int = 1,
             exc: Optional[Callable[[], BaseException]] = None,
             message: str = "injected fault", action: str = "raise",
             delay: float = 0.0) -> "FaultPlan":
        """Trigger ``action`` at ``site`` (optionally only at occurrence
        index ``at``), ``times`` times.  The default raises an
        exception; see :data:`FAULT_ACTIONS` for the kill/slow/hang
        shapes (``delay`` is the sleep seconds for ``sleep``/``hang``)."""
        if action not in FAULT_ACTIONS:
            raise ValueError(
                f"unknown fault action {action!r}; one of {FAULT_ACTIONS}")
        if delay < 0:
            raise ValueError(f"delay must be >= 0, got {delay}")
        # under the lock: plans are usually built before installation,
        # but a test arming extra faults while a fire() iterates the
        # list from another thread must not race the traversal
        with self._lock:
            self._faults.append(
                _Fault(site=site, at=at, times=times, exc=exc,
                       message=message, action=action, delay=float(delay)))
        return self

    def sigterm_at_step(self, k: int) -> "FaultPlan":
        """Deliver SIGTERM to this process at the trainer's step
        boundary ``k`` — the deterministic preemption."""
        with self._lock:
            self._faults.append(_Fault(site="step", at=k, action="sigterm"))
        return self

    def sigint_at_step(self, k: int) -> "FaultPlan":
        with self._lock:
            self._faults.append(_Fault(site="step", at=k, action="sigint"))
        return self

    def loader_fail(self, *, at: int = 0, times: int = 1) -> "FaultPlan":
        """Transient data-loader exceptions at batch index ``at``."""
        return self.fail(
            "loader", at=at, times=times, exc=lambda: OSError(
                "injected transient loader failure"))

    def backend_unavailable(self, times: int = 1) -> "FaultPlan":
        """The first ``times`` backend acquisitions fail as if the chip
        were not granting."""
        return self.fail(
            "backend_init", times=times,
            exc=lambda: BackendUnavailable(
                "injected UNAVAILABLE: backend is not granting"))

    @classmethod
    def from_spec(cls, spec: dict) -> "FaultPlan":
        """Build a plan from a JSON-able dict (the ``--fault-plan``
        CLI / env surface)::

            {"sigterm_at_step": 3,
             "loader_fail": {"at": 1, "times": 2},
             "backend_unavailable": 2,
             "params": {"local_devices": 4}}

        The generic ``fail`` key addresses any site/action directly —
        the serve-side surface (replica kill/slow/hang, dispatch and
        probe failures)::

            {"fail": [{"site": "serve.tick", "at": 40, "action": "exit"},
                      {"site": "serve.dispatch", "times": 2},
                      {"site": "serve.probe", "action": "sleep",
                       "delay": 0.5}]}

        — and the training-guard surface: ``nan``/``inf`` value
        corruptions at the sentinel sites, a deterministic step-k
        anomaly with no RNG and no recompile::

            {"fail": [{"site": "train.loss", "at": 2, "action": "nan"},
                      {"site": "train.grad", "at": 5, "action": "inf"}]}
        """
        plan = cls()
        known = {"sigterm_at_step", "sigint_at_step", "loader_fail",
                 "backend_unavailable", "params", "fail"}
        unknown = set(spec) - known
        if unknown:
            raise ValueError(
                f"unknown fault-plan keys {sorted(unknown)}; "
                f"supported: {sorted(known)}")
        if "sigterm_at_step" in spec:
            plan.sigterm_at_step(int(spec["sigterm_at_step"]))
        if "sigint_at_step" in spec:
            plan.sigint_at_step(int(spec["sigint_at_step"]))
        if "loader_fail" in spec:
            lf = spec["loader_fail"] or {}
            plan.loader_fail(at=int(lf.get("at", 0)),
                             times=int(lf.get("times", 1)))
        if "backend_unavailable" in spec:
            plan.backend_unavailable(int(spec["backend_unavailable"]))
        for f in spec.get("fail") or []:
            fkeys = {"site", "at", "times", "action", "delay", "message"}
            unknown = set(f) - fkeys
            if unknown:
                raise ValueError(
                    f"unknown fail-entry keys {sorted(unknown)}; "
                    f"supported: {sorted(fkeys)}")
            if "site" not in f:
                raise ValueError(f"fail entry needs a site: {f!r}")
            plan.fail(
                str(f["site"]),
                at=None if f.get("at") is None else int(f["at"]),
                times=int(f.get("times", 1)),
                action=str(f.get("action", "raise")),
                delay=float(f.get("delay", 0.0)),
                message=str(f.get("message", "injected fault")))
        plan.params.update(spec.get("params") or {})
        return plan

    # -- delivery ------------------------------------------------------
    def fire(self, site: str, index: Optional[int] = None) -> None:
        """Trigger any matching fault.  ``raise`` actions raise; signal
        actions ``os.kill`` this process (a python handler — e.g. the
        trainer's :class:`SignalFlag` — runs before the caller's next
        bytecode, so the very next boundary check observes it);
        ``exit`` is an immediate hard kill (``os._exit`` — a crash, not
        a drain); ``sleep``/``hang`` stall the CALLING thread for the
        fault's delay and then return (the slow/wedged-replica shapes —
        everything else in the process keeps running).  Value actions
        (``nan``/``inf``) never match here — they need a value to
        corrupt and only trigger at :meth:`fire_value` sites."""
        to_signal = None
        exc: Optional[BaseException] = None
        hard_exit = False
        stall = 0.0
        with self._lock:
            for f in self._faults:
                if f.site != site or f.fired >= f.times:
                    continue
                if f.at is not None and index != f.at:
                    continue
                if f.action in VALUE_ACTIONS:
                    continue
                f.fired += 1
                _metrics()["injected"].labels(site=site).inc()
                if f.action == "sigterm":
                    to_signal = signal.SIGTERM
                elif f.action == "sigint":
                    to_signal = signal.SIGINT
                elif f.action == "exit":
                    hard_exit = True
                elif f.action in ("sleep", "hang"):
                    stall = f.delay if (
                        f.action == "sleep" or f.delay > 0
                    ) else HANG_DELAY_SECONDS
                else:
                    exc = f.exc() if f.exc is not None else FaultInjected(
                        f"{f.message} (site={site}, index={index})")
                break
        if hard_exit:
            # the un-drainable crash: no atexit, no finally blocks —
            # the same shape as SIGKILL/OOM, which is the point
            os._exit(1)
        if to_signal is not None:
            os.kill(os.getpid(), to_signal)
            return
        if stall > 0:
            time.sleep(stall)
            return
        if exc is not None:
            raise exc

    def fire_value(self, site: str, value: float,
                   index: Optional[int] = None) -> float:
        """Value-corruption delivery: side-effect actions at ``site``
        run first (via :meth:`fire` — a ``raise``/``hang`` planted on a
        sentinel site still behaves), then the first matching
        ``nan``/``inf`` action replaces ``value``.  With no match the
        value passes through untouched."""
        self.fire(site, index)
        with self._lock:
            for f in self._faults:
                if f.site != site or f.fired >= f.times:
                    continue
                if f.at is not None and index != f.at:
                    continue
                if f.action not in VALUE_ACTIONS:
                    continue
                f.fired += 1
                _metrics()["injected"].labels(site=site).inc()
                return float("nan") if f.action == "nan" else float("inf")
        return value


_PLAN: Optional[FaultPlan] = None


def install_plan(plan: FaultPlan) -> FaultPlan:
    """Make ``plan`` the process-wide active plan (tests/chaos runs)."""
    global _PLAN
    _PLAN = plan
    return plan


def clear_plan() -> None:
    global _PLAN
    _PLAN = None


def active_plan() -> Optional[FaultPlan]:
    return _PLAN


def fire(site: str, index: Optional[int] = None) -> None:
    """Hot-path hook: no-op (one global load + None check) unless a
    plan is installed."""
    if _PLAN is not None:
        _PLAN.fire(site, index)


def fire_value(site: str, value: float, index: Optional[int] = None) -> float:
    """Hot-path VALUE hook (the guard's sentinel taps): returns
    ``value`` untouched unless the active plan plants a ``nan``/``inf``
    corruption at ``site`` — one global load + None check when idle."""
    if _PLAN is not None:
        return _PLAN.fire_value(site, value, index)
    return value


def param(name: str, default: Any = None) -> Any:
    """A harness knob from the active plan (None-safe)."""
    if _PLAN is None:
        return default
    return _PLAN.params.get(name, default)


# ---------------------------------------------------------------------------
# retries
# ---------------------------------------------------------------------------

# deterministic-by-default jitter stream: reseeded per with_retries call
# so two identical runs back off identically (the harness is provable)
_JITTER_SEED = 0x5FDB


#: error-message fragments that mean "the backend/tunnel was not
#: there", not "the code is wrong" — THE canonical list, shared with
#: bench.py's phase-aware ``retryable_error`` so the two classifiers
#: cannot drift
UNAVAILABLE_SIGNATURES = (
    "UNAVAILABLE", "DEADLINE_EXCEEDED", "failed to connect",
    "Connection reset", "Connection refused", "Socket closed",
    "response body closed", "remote_compile", "No visible device",
    "Unable to initialize backend", "timed out", "per-attempt bound",
)


def _default_retryable(err: BaseException) -> bool:
    """Transient by default: injected faults, OS/IO errors, and
    anything carrying a backend-unavailable signature.  Programming
    errors (TypeError, ValueError, ...) are not retried."""
    if isinstance(err, (FaultInjected, OSError, IOError, TimeoutError,
                        ConnectionError)):
        return True
    s = str(err)
    return any(sig in s for sig in UNAVAILABLE_SIGNATURES)


def with_retries(
    fn: Callable[[], Any],
    *,
    tries: int = 3,
    timeout: Optional[float] = None,
    backoff: float = 0.5,
    jitter: float = 0.1,
    budget: Optional[float] = None,
    retryable: Optional[Callable[[BaseException], bool]] = None,
    site: str = "generic",
    sleep: Callable[[float], None] = time.sleep,
) -> Any:
    """Call ``fn()`` with bounded exponential-backoff retries.

    * ``tries`` — max attempts;
    * ``timeout`` — per-attempt wall bound: the attempt runs on a
      daemon thread and a hang counts as a retryable failure (the
      thread itself cannot be interrupted — a truly wedged C call
      leaks it, the same reason bench.py measures in a bounded
      *subprocess*; this is the in-process best effort);
    * ``backoff`` — first sleep; doubles each retry;
    * ``jitter`` — fraction of the sleep randomized (deterministic
      stream, so two identical runs back off identically);
    * ``budget`` — total wall seconds across attempts AND sleeps; when
      exceeded, gives up with :class:`RetryBudgetExceeded`;
    * ``retryable`` — classifier; default retries injected faults,
      OS/IO errors and backend-unavailable signatures only.

    Retry/giveup/backoff tallies land in the ``fdtpu_fault_*`` counters
    under ``site``.
    """
    if tries < 1:
        raise ValueError(f"tries must be >= 1, got {tries}")
    m = _metrics()
    rng = random.Random(_JITTER_SEED)
    classify = retryable or _default_retryable
    t0 = time.monotonic()
    last: Optional[BaseException] = None
    for attempt in range(tries):
        if budget is not None and time.monotonic() - t0 > budget:
            break
        try:
            if timeout is None:
                return fn()
            box: dict = {}

            def run():
                try:
                    box["value"] = fn()
                except BaseException as e:  # noqa: BLE001 — re-raised below
                    box["error"] = e

            th = threading.Thread(target=run, daemon=True)
            th.start()
            th.join(timeout)
            if th.is_alive():
                raise TimeoutError(
                    f"attempt exceeded the {timeout}s per-attempt bound "
                    f"(site={site}); the worker thread is abandoned")
            if "error" in box:
                raise box["error"]
            return box.get("value")
        except BaseException as e:  # noqa: BLE001 — classified below
            last = e
            if not classify(e) or attempt == tries - 1:
                if attempt == tries - 1 and classify(e):
                    break  # exhausted: report as budget/attempts out
                raise
            pause = backoff * (2 ** attempt)
            pause += pause * jitter * rng.random()
            if budget is not None:
                pause = min(pause, max(0.0, budget - (time.monotonic() - t0)))
            m["retries"].labels(site=site).inc()
            m["backoff"].labels(site=site).inc(pause)
            if pause > 0:
                sleep(pause)
    m["giveups"].labels(site=site).inc()
    raise RetryBudgetExceeded(
        f"gave up after {tries} attempt(s) at site={site!r}: "
        f"{type(last).__name__ if last else 'no attempt ran'}: {last}"
    ) from last


def acquire_backend(
    *,
    tries: int = 3,
    timeout: Optional[float] = 120.0,
    backoff: float = 5.0,
    budget: Optional[float] = None,
    sleep: Callable[[float], None] = time.sleep,
):
    """Enumerate devices with retries — THE backend-acquisition
    boundary for bench/serving bring-up.  On a tunneled TPU,
    ``jax.devices()`` *is* the grant wait and can hang for many minutes
    when the chip is not granting; the per-attempt ``timeout`` plus the
    retry policy turn that into a bounded, classified failure instead
    of a wedged process.  Returns the device list."""

    def attempt():
        fire("backend_init")
        import jax

        return jax.devices()

    return with_retries(
        attempt, tries=tries, timeout=timeout, backoff=backoff,
        budget=budget, site="backend_init", sleep=sleep)


# ---------------------------------------------------------------------------
# signals
# ---------------------------------------------------------------------------


class SignalFlag:
    """Install handlers that record a delivered signal instead of
    killing the process.  The trainer polls :meth:`is_set` at its step
    boundary; a SECOND delivery of the same signal restores escalation
    semantics (raises ``KeyboardInterrupt`` from the handler) so a
    stuck run can still be killed interactively.

    Handlers only install from the main thread (CPython restriction);
    elsewhere :meth:`install` is a recorded no-op and :meth:`is_set`
    still works for programmatic ``set()`` use.
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self.signals = tuple(signals)
        self._event = threading.Event()
        self._received: Optional[int] = None
        self._previous: dict = {}
        self.installed = False

    def _handler(self, signum, frame):
        if self._event.is_set():
            raise KeyboardInterrupt(
                f"second signal {signum} during checkpoint-and-exit")
        self._received = signum
        self._event.set()

    def install(self) -> "SignalFlag":
        if threading.current_thread() is not threading.main_thread():
            return self
        for s in self.signals:
            self._previous[s] = signal.signal(s, self._handler)
        self.installed = True
        return self

    def uninstall(self) -> None:
        if not self.installed:
            return
        for s, old in self._previous.items():
            try:
                signal.signal(s, old)
            except (ValueError, OSError):  # not main thread / teardown
                pass
        self._previous.clear()
        self.installed = False

    def set(self) -> None:
        """Programmatic trigger (tests; cooperative preemption)."""
        self._event.set()

    def is_set(self) -> bool:
        return self._event.is_set()

    @property
    def received(self) -> Optional[int]:
        return self._received

    @property
    def reason(self) -> str:
        if self._received == signal.SIGTERM:
            return "sigterm"
        if self._received == signal.SIGINT:
            return "sigint"
        return "requested"

    def __enter__(self) -> "SignalFlag":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()


def record_preemption() -> None:
    """Count a checkpoint-and-exit event (called by the trainer once
    the checkpoint + manifest are durably on disk)."""
    _metrics()["preemptions"].inc()
