#!/usr/bin/env python
"""fdtpu-fit — the memory/comms fit checker: does variant X fit
topology Z, and with how much headroom?

    # sweep every registered variant through the REAL prepare_training/
    # LMEngine builders, compile each once, and write the memory+comms
    # report as a fdtpu-profile/v2 artifact:
    python bin/fit.py --collect memcomms.profile.json --host-devices 8

    # rank variants by HBM headroom under a budget (bytes per device;
    # defaults to the live device bytes_limit when memory_stats() is
    # available — on CPU you must pass --hbm-bytes):
    python bin/fit.py --profile memcomms.profile.json --hbm-bytes 16e9

    # gate on one variant ("does zero1 fit here?"):
    python bin/fit.py --profile p.json --hbm-bytes 16e9 --require zero1

    # memory-baseline workflow (the lint-baseline idiom): fail only on
    # NEW regressions beyond the tolerance, update to accept:
    python bin/fit.py --collect out.json --check
    python bin/fit.py --collect out.json --update-baseline

Exit codes: 0 = ok / informational, 1 = baseline check failed,
2 = usage error, 3 = a --require'd variant does not fit.

The auto-layout picker (``parallel.layout.pick`` / ``bin/driver.py
--layout auto``) consumes this CLI's ranking directly —
``obs.memstats.rank_memory`` is the ONE headroom-ranking
implementation both share — plus the per-step collective ledger as
its tiebreak.
"""

from __future__ import annotations

import argparse
import json
import os
import sys


def _bootstrap() -> None:
    try:
        import fluxdistributed_tpu  # noqa: F401
    except ImportError:
        sys.path.insert(0, os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--collect", metavar="OUT",
                   help="sweep the registered variants (compile each "
                        "once) and write the memory+comms report as a "
                        "fdtpu-profile/v2 artifact")
    p.add_argument("--variants", default=None,
                   help="comma-separated variant subset for --collect "
                        "(default: all registered)")
    p.add_argument("--no-hlo", action="store_true",
                   help="skip the compiled-HLO collective parse in "
                        "--collect (jaxpr ledger only)")
    p.add_argument("--host-devices", type=int, default=0,
                   help="force N virtual CPU devices before jax init "
                        "(the lint idiom — CI/laptops; 0 = use the "
                        "real topology)")
    p.add_argument("--profile", metavar="PATH",
                   help="rank variants from an existing artifact "
                        "instead of sweeping")
    p.add_argument("--allow-mismatch", action="store_true",
                   help="skip the topology-fingerprint gate when "
                        "loading --profile (offline analysis of a "
                        "foreign artifact only)")
    p.add_argument("--hbm-bytes", type=float, default=None,
                   help="per-device HBM budget in bytes (default: the "
                        "live device bytes_limit; REQUIRED on backends "
                        "without memory_stats, e.g. CPU)")
    p.add_argument("--require", action="append", default=[],
                   metavar="VARIANT",
                   help="exit 3 unless this variant fits the budget "
                        "(repeatable)")
    p.add_argument("--check", action="store_true",
                   help="fail (exit 1) on memory regressions vs the "
                        "committed baseline")
    p.add_argument("--baseline", default=None,
                   help="memory-baseline JSON (default: "
                        "fluxdistributed_tpu/analysis/"
                        "memory_baseline.json)")
    p.add_argument("--update-baseline", action="store_true",
                   help="write the sweep's memory figures as the new "
                        "baseline")
    p.add_argument("--tolerance", type=float, default=None,
                   help="override the baseline's regression tolerance "
                        "(fraction, e.g. 0.5)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit the ranking/check as one JSON object")
    return p


def default_baseline_path() -> str:
    import fluxdistributed_tpu

    return os.path.join(
        os.path.dirname(os.path.abspath(fluxdistributed_tpu.__file__)),
        "analysis", "memory_baseline.json")


def _variant_memory(profile) -> dict:
    """{variant: entry} with a memory dict, off a v2 artifact."""
    return {name: entry
            for name, entry in (profile.memory.get("variants") or {}).items()}


def rank_variants(profile, budget: float | None) -> list:
    """Headroom ranking rows: one per variant with a memory model,
    sorted most-headroom-first; variants whose memory_analysis was
    unavailable rank last with fits=None (unknown is not 'fits').
    Thin adapter over ``obs.memstats.rank_memory`` — the ONE ranking
    this CLI and the auto-layout picker (``parallel.layout.pick``)
    share."""
    from fluxdistributed_tpu.obs.memstats import rank_memory

    return rank_memory(_variant_memory(profile), budget)


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    _bootstrap()
    if not args.collect and not args.profile:
        print("fit: pass --collect OUT and/or --profile PATH",
              file=sys.stderr)
        return 2
    if args.host_devices:
        from fluxdistributed_tpu.mesh import force_host_devices

        force_host_devices(args.host_devices)

    from fluxdistributed_tpu.obs import memstats
    from fluxdistributed_tpu.obs.profile import (
        Profile, ProfileMismatch, describe_topology)

    if args.collect:
        from fluxdistributed_tpu.compilation import topology_fingerprint

        names = args.variants.split(",") if args.variants else None
        report = memstats.variant_report(
            names, include_hlo=not args.no_hlo)
        prof = Profile(
            fingerprint=topology_fingerprint(),
            topology=describe_topology(),
            memory={"state": None, "step": None,
                    "variants": {n: {"memory": e.get("memory"),
                                     "args_bytes": e.get("args_bytes"),
                                     "source": e.get("source")}
                                 for n, e in report.items()}},
            comms={"step": {},
                   "variants": {n: e.get("comms", {})
                                for n, e in report.items()}},
            meta={"producer": "bin/fit.py --collect"},
        )
        prof.save(args.collect)
        print(f"fit: wrote {len(report)} variant(s) to {args.collect}")
    else:
        prof = Profile.load(args.profile)
        if args.allow_mismatch:
            print("fit: WARNING — topology gate skipped "
                  "(--allow-mismatch); headroom figures describe the "
                  f"artifact's topology {prof.topology}, not this box",
                  file=sys.stderr)
        else:
            try:
                prof.verify()
            except ProfileMismatch as e:
                raise SystemExit(f"fit: {e}")

    rc = 0
    # -- baseline workflow -------------------------------------------------
    baseline_path = args.baseline or default_baseline_path()
    current = _variant_memory(prof)
    if args.update_baseline:
        doc = memstats.build_baseline(
            current,
            tolerance=(args.tolerance if args.tolerance is not None
                       else memstats.DEFAULT_TOLERANCE))
        tmp = f"{baseline_path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, baseline_path)
        print(f"fit: wrote {len(doc['variants'])} variant baseline "
              f"entr(ies) to {baseline_path}")
        return 0
    check = None
    if args.check:
        if not os.path.exists(baseline_path):
            print(f"fit: baseline {baseline_path} not found",
                  file=sys.stderr)
            return 2
        with open(baseline_path) as f:
            base = json.load(f)
        check = memstats.check_memory_baseline(
            current, base, tolerance=args.tolerance)
        for note in check["notes"]:
            print(f"note: {note}")
        for fail in check["failures"]:
            print(f"FAIL: {fail}")
        print(f"fit: baseline check — {check['checked']} variant(s) "
              f"checked at tolerance {check['tolerance']}, "
              f"{len(check['failures'])} failure(s)")
        if check["failures"]:
            rc = 1

    # -- headroom ranking --------------------------------------------------
    budget = args.hbm_bytes
    if budget is None:
        stats = memstats.hbm_device_stats()
        limits = [d["bytes_limit"] for d in (stats or [])
                  if d["bytes_limit"] > 0]
        if limits:
            budget = float(min(limits))
    rows = rank_variants(prof, budget)
    if args.as_json:
        print(json.dumps({"budget_bytes": budget, "rows": rows,
                          "check": check}, indent=2))
    else:
        if budget is None:
            print("fit: no HBM budget — this backend reports no "
                  "memory_stats (CPU); pass --hbm-bytes to rank "
                  "fits (peak bytes still listed)")
        else:
            print(f"fit: per-device HBM budget {budget:.3e} bytes")
        for r in rows:
            peak = (f"{r['peak_bytes']:>14,}" if r["peak_bytes"]
                    is not None else "   unavailable")
            verdict = {True: "FITS", False: "DOES NOT FIT",
                       None: "?"}[r["fits"]]
            head = (f"  headroom {r['headroom_bytes']:,}"
                    if r["headroom_bytes"] is not None else "")
            print(f"  {r['variant']:<24} peak {peak}  {verdict}{head}")
    for req in args.require:
        row = next((r for r in rows if r["variant"] == req), None)
        if row is None:
            print(f"fit: --require {req}: unknown variant in this "
                  f"artifact ({sorted(r['variant'] for r in rows)})",
                  file=sys.stderr)
            return 2
        if row["fits"] is not True:
            print(f"fit: --require {req}: peak "
                  f"{row['peak_bytes']} bytes does NOT fit the "
                  f"budget {budget} — pick a smaller variant or a "
                  "bigger topology", file=sys.stderr)
            return 3
    return rc


if __name__ == "__main__":
    sys.exit(main())
