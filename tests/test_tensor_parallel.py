"""Tensor parallelism: TP-sharded training == replicated training.

The reference's invariant (distributed == single-device,
test/single_device.jl:115-168) applied to the model axis: a ViT trained
with Megatron-sharded params on a (data=2, model=4) mesh must produce
the same losses and parameters as the plain replicated DP step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import fluxdistributed_tpu as fd
from fluxdistributed_tpu import optim, sharding
from fluxdistributed_tpu.mesh import make_mesh
from fluxdistributed_tpu.models import vit_tiny
from fluxdistributed_tpu.parallel import TrainState, make_train_step
from fluxdistributed_tpu.parallel.dp import flax_loss_fn
from fluxdistributed_tpu.parallel.tp import (
    broadcast_prefix,
    make_train_step_tp,
    param_specs,
    shard_state,
    vit_tp_rules,
)
from jax.sharding import PartitionSpec as P


@pytest.fixture(scope="module")
def setup():
    mesh = make_mesh({"data": 2, "model": 4})
    model = vit_tiny(num_classes=10, dtype=jnp.float32, dropout=0.0)
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (16, 32, 32, 3)).astype(np.float32)
    y = np.asarray(fd.onehot(rng.integers(0, 10, 16), 10))
    variables = model.init(jax.random.PRNGKey(0), x[:1], train=False)
    loss_fn = flax_loss_fn(model, fd.logitcrossentropy)
    opt = optim.momentum(0.1, 0.9)
    return mesh, model, loss_fn, opt, variables["params"], {"image": x, "label": y}


def test_specs_cover_attention_and_mlp(setup):
    _, _, _, _, params, _ = setup
    specs = param_specs(params, vit_tp_rules())
    flat = {
        "/".join(str(k.key) for k in kp): s
        for kp, s in jax.tree_util.tree_flatten_with_path(
            specs, is_leaf=lambda x: isinstance(x, P)
        )[0]
    }
    assert flat["block0/MultiHeadAttention_0/qkv/kernel"] == P(None, None, "model", None)
    assert flat["block0/MultiHeadAttention_0/out/kernel"] == P("model", None, None)
    assert flat["block0/MlpBlock_0/Dense_0/kernel"] == P(None, "model")
    assert flat["block0/MlpBlock_0/Dense_1/kernel"] == P("model", None)
    assert flat["head/kernel"] == P()


def test_broadcast_prefix_handles_adam_tuples(setup):
    _, _, _, _, params, _ = setup
    opt = optim.adam(1e-3)
    st = opt.init(params)
    specs = param_specs(params, vit_tp_rules())
    st_specs = broadcast_prefix(specs, st)
    # The qkv kernel's (m, v) tuple must both carry the qkv spec.
    got = st_specs["block0"]["MultiHeadAttention_0"]["qkv"]["kernel"]
    assert got == (P(None, None, "model", None), P(None, None, "model", None))


def test_tp_matches_dp(setup):
    mesh, model, loss_fn, opt, params, batch = setup

    # Replicated DP baseline on the same mesh (model axis unused).
    state0 = TrainState.create(sharding.replicate(params, mesh), opt)
    dp_step = make_train_step(loss_fn, opt, mesh, donate=False)
    b = sharding.shard_batch(batch, mesh)

    dp_state, m_dp = dp_step(state0, b)
    dp_state, m_dp2 = dp_step(dp_state, b)

    # TP: same initial params, Megatron shardings.
    specs = param_specs(params, vit_tp_rules())
    tp_state = shard_state(TrainState.create(params, opt), mesh, specs)
    tp_step = make_train_step_tp(loss_fn, opt, mesh, specs, tp_state, donate=False)
    tp_state, m_tp = tp_step(tp_state, b)
    tp_state, m_tp2 = tp_step(tp_state, b)

    np.testing.assert_allclose(float(m_tp["loss"]), float(m_dp["loss"]), rtol=1e-5)
    np.testing.assert_allclose(float(m_tp2["loss"]), float(m_dp2["loss"]), rtol=1e-5)
    for a, b_ in zip(jax.tree.leaves(dp_state.params), jax.tree.leaves(tp_state.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_), rtol=2e-4, atol=2e-4)


def test_donated_state_does_not_delete_source_params(setup):
    """replicate/shard_state must copy: donating the state into the
    compiled step would otherwise delete the caller's original arrays
    (device_put is zero-copy on shared devices)."""
    mesh, model, loss_fn, opt, params, batch = setup
    state = TrainState.create(sharding.replicate(params, mesh), opt)
    step = make_train_step(loss_fn, opt, mesh, donate=True)
    b = sharding.shard_batch(batch, mesh)
    state, _ = step(state, b)  # donates the pre-step state buffers
    # Source params must still be alive and usable.
    specs = param_specs(params, vit_tp_rules())
    tp_state = shard_state(TrainState.create(params, opt), mesh, specs)
    assert all(np.isfinite(np.asarray(l)).all() for l in jax.tree.leaves(tp_state.params))


def test_tp_params_actually_sharded(setup):
    mesh, model, loss_fn, opt, params, batch = setup
    specs = param_specs(params, vit_tp_rules())
    tp_state = shard_state(TrainState.create(params, opt), mesh, specs)
    qkv = tp_state.params["block0"]["MultiHeadAttention_0"]["qkv"]["kernel"]
    assert "model" in qkv.sharding.spec
    # Each device holds 1/4 of the heads.
    shard_shape = qkv.sharding.shard_shape(qkv.shape)
    assert shard_shape[2] == qkv.shape[2] // 4
