"""FSDP (ZeRO-3 via GSPMD) invariants, on the 8-device mesh.

Sharding annotations must never change the math: the FSDP step's params
after N steps must match the replicated DP step's bit-for-bit behavior
(same tolerance as the DP-vs-single-device invariant the reference
asserts, test/single_device.jl:153-166).  And the point of FSDP — the
memory win — is asserted directly: each device holds ~1/8th of every
large leaf (``addressable_shards``), not a full copy.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from fluxdistributed_tpu import optim, sharding
from fluxdistributed_tpu.models import SimpleCNN
from fluxdistributed_tpu.ops import logitcrossentropy
from fluxdistributed_tpu.parallel import (
    TrainState,
    fsdp,
    fsdp_specs,
    make_eval_step_fsdp,
    make_train_step,
    make_train_step_fsdp,
)
from fluxdistributed_tpu.parallel.dp import flax_loss_fn

BATCH = 32
NCLASS = 10


@pytest.fixture(scope="module")
def setup():
    import fluxdistributed_tpu.mesh as mesh_lib

    mesh = mesh_lib.data_mesh(8)
    model = SimpleCNN(num_classes=NCLASS)
    x = jax.random.normal(jax.random.PRNGKey(1), (BATCH, 8, 8, 3), jnp.float32)
    y = jax.nn.one_hot(
        jax.random.randint(jax.random.PRNGKey(2), (BATCH,), 0, NCLASS), NCLASS
    )
    params = model.init(jax.random.PRNGKey(0), x[:2], train=True)["params"]
    loss_fn = flax_loss_fn(model, logitcrossentropy)
    return mesh, params, loss_fn, {"image": x, "label": y}


def test_leaf_spec_rule():
    # large 2D leaf: shard the larger dim; trailing wins ties
    assert fsdp.fsdp_leaf_spec((4096, 512), "data", 8) == P("data", None)
    assert fsdp.fsdp_leaf_spec((512, 4096), "data", 8) == P(None, "data")
    assert fsdp.fsdp_leaf_spec((4096, 4096), "data", 8) == P(None, "data")
    # conv HWIO: features dim, not the 3x3 window
    assert fsdp.fsdp_leaf_spec((3, 3, 256, 256), "data", 8) == P(
        None, None, None, "data"
    )
    # small leaves (BN scale etc.) stay replicated
    assert fsdp.fsdp_leaf_spec((64,), "data", 8) == P()
    # no divisible dim -> replicated
    assert fsdp.fsdp_leaf_spec((63, 65), "data", 8, min_size=1) == P()
    # scalars
    assert fsdp.fsdp_leaf_spec((), "data", 8) == P()


def test_fsdp_matches_dp(setup):
    mesh, params, loss_fn, batch = setup
    opt = optim.momentum(0.05, 0.9)
    b = sharding.shard_batch(batch, mesh)

    # replicated DP ground truth
    dp_state = TrainState.create(sharding.replicate(params, mesh), opt)
    dp_step = make_train_step(loss_fn, opt, mesh, donate=False)

    # FSDP: same initial params, sharded state
    fs_state = TrainState.create(params, opt)
    specs = fsdp_specs(fs_state, mesh, min_size=64)  # small model: force sharding
    fs_state = fsdp.shard_state(fs_state, specs, mesh)
    fs_step = make_train_step_fsdp(loss_fn, opt, mesh, specs, donate=False)

    for _ in range(3):
        dp_state, dp_m = dp_step(dp_state, b)
        fs_state, fs_m = fs_step(fs_state, b)
        np.testing.assert_allclose(
            np.asarray(dp_m["loss"]), np.asarray(fs_m["loss"]), rtol=1e-6
        )

    for (pa, a), (pb, bb) in zip(
        jax.tree_util.tree_leaves_with_path(dp_state.params),
        jax.tree_util.tree_leaves_with_path(fs_state.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(bb), rtol=2e-5, atol=1e-6,
            err_msg=f"param mismatch at {jax.tree_util.keystr(pa)}",
        )


def test_fsdp_shards_memory(setup):
    mesh, params, loss_fn, batch = setup
    opt = optim.adam(1e-3)
    state = TrainState.create(params, opt)
    specs = fsdp_specs(state, mesh, min_size=64)
    state = fsdp.shard_state(state, specs, mesh)

    n = mesh.shape["data"]
    sharded = 0
    for spec, leaf in zip(
        jax.tree.leaves(specs.params, is_leaf=lambda x: isinstance(x, P)),
        jax.tree.leaves(state.params),
    ):
        shard = leaf.addressable_shards[0].data
        if spec != P():
            assert shard.size == leaf.size // n, (spec, leaf.shape, shard.shape)
            sharded += 1
        else:
            assert shard.size == leaf.size
    assert sharded > 0, "no leaf was sharded — rule or model shapes changed"
    # optimizer moments follow the same rule (same shapes), incl. adam's
    for leaf in jax.tree.leaves(state.opt_state):
        assert leaf.addressable_shards[0].data.size <= leaf.size


def test_fsdp_through_trainer():
    """The user path: prepare_training(spmd='fsdp') → train → loss falls,
    and the trainer's state really is sharded."""
    import fluxdistributed_tpu.mesh as mesh_lib
    from fluxdistributed_tpu.data import SyntheticDataset
    from fluxdistributed_tpu.train import prepare_training, train
    from fluxdistributed_tpu.train.logging import NullLogger

    mesh = mesh_lib.data_mesh(8)
    ds = SyntheticDataset(nsamples=64, nclasses=4, shape=(8, 8, 3))
    task = prepare_training(
        SimpleCNN(num_classes=4), ds, optim.momentum(0.1, 0.9),
        mesh=mesh, batch_size=16, cycles=30, spmd="fsdp",
    )
    n = mesh.shape["data"]
    assert any(
        l.addressable_shards[0].data.size == l.size // n
        for l in jax.tree.leaves(task.state.params)
    ), "no trainer param leaf is sharded under spmd='fsdp'"
    losses = []
    orig = task.step_fn

    def recording(state, batch):
        state, m = orig(state, batch)
        losses.append(float(m["loss"]))
        return state, m

    task.step_fn = recording
    train(task, print_every=0, eval_every=0, logger=NullLogger())
    assert losses[-1] < losses[0], (losses[0], losses[-1])


def test_fsdp_checkpoint_roundtrip(setup, tmp_path):
    """Save an FSDP-sharded state, restore onto the sharded target: values
    round-trip and the restored leaves keep their FSDP shardings (no
    silent gather-to-replicated on resume)."""
    from fluxdistributed_tpu.train.checkpoint import load_checkpoint, save_checkpoint

    mesh, params, loss_fn, batch = setup
    opt = optim.momentum(0.05, 0.9)
    state = TrainState.create(params, opt)
    specs = fsdp_specs(state, mesh, min_size=64)
    state = fsdp.shard_state(state, specs, mesh)
    step = make_train_step_fsdp(loss_fn, opt, mesh, specs, donate=False)
    state, _ = step(state, sharding.shard_batch(batch, mesh))

    save_checkpoint(state, str(tmp_path), 1)
    restored = load_checkpoint(str(tmp_path), state, mesh=mesh)

    n = mesh.shape["data"]
    resharded = 0
    for old, new in zip(jax.tree.leaves(state.params), jax.tree.leaves(restored.params)):
        np.testing.assert_array_equal(np.asarray(old), np.asarray(new))
        assert new.sharding == old.sharding
        if new.addressable_shards[0].data.size == new.size // n:
            resharded += 1
    assert resharded > 0
    # and the restored state steps
    st2, m = step(restored, sharding.shard_batch(batch, mesh))
    assert np.isfinite(np.asarray(m["loss"]))


def test_hybrid_fsdp_tp_lm():
    """2-D sharding on (data=2, model=4): TP rules + FSDP on the leftover
    dim → per-device shards ~1/8 of large leaves, numerics match DP."""
    import fluxdistributed_tpu.mesh as mesh_lib
    from fluxdistributed_tpu.models import lm_loss_fn, lm_tiny
    from fluxdistributed_tpu.parallel import (
        hybrid_fsdp_tp_specs,
        lm_tp_rules,
        make_train_step,
        make_train_step_tp,
    )
    from fluxdistributed_tpu.parallel.tp import shard_state as tp_shard_state

    vocab = 32
    model = lm_tiny(vocab=vocab, dtype=jnp.float32)
    toks = np.random.default_rng(11).integers(0, vocab, (16, 24)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), toks[:2], train=False)["params"]
    opt = optim.momentum(0.05, 0.9)
    loss_fn = lm_loss_fn(model)

    mesh = mesh_lib.make_mesh({"data": 2, "model": 4})
    specs = hybrid_fsdp_tp_specs(params, mesh, lm_tp_rules(), min_size=64)
    # embedding: vocab over model (TP) + dim over data (FSDP)
    assert specs["embed"]["embedding"] == P("model", "data")
    qkv = specs["block0"]["CausalSelfAttention_0"]["qkv"]["kernel"]
    assert qkv == P("data", None, "model", None)

    hy_state = tp_shard_state(TrainState.create(params, opt), mesh, specs)
    qkv_leaf = hy_state.params["block0"]["CausalSelfAttention_0"]["qkv"]["kernel"]
    assert qkv_leaf.addressable_shards[0].data.size == qkv_leaf.size // 8
    hy_step = make_train_step_tp(loss_fn, opt, mesh, specs, hy_state, donate=False)
    b_hy = sharding.shard_batch({"tokens": toks}, mesh, axis="data")

    dp_mesh = mesh_lib.data_mesh(8)
    dp_state = TrainState.create(sharding.replicate(params, dp_mesh), opt)
    dp_step = make_train_step(loss_fn, opt, dp_mesh, donate=False)
    b_dp = sharding.shard_batch({"tokens": toks}, dp_mesh)

    for _ in range(3):
        dp_state, dp_m = dp_step(dp_state, b_dp)
        hy_state, hy_m = hy_step(hy_state, b_hy)
        np.testing.assert_allclose(
            float(dp_m["loss"]), float(hy_m["loss"]), rtol=1e-5
        )


# slow tier: the trainer-layer fsdp x tp composition re-compiles the
# whole hybrid step; the parallel-layer hybrid (test_hybrid_fsdp_tp_lm)
# keeps the axis composition in tier-1 (870s window, ROADMAP)
@pytest.mark.slow
def test_fsdp_tp_through_trainer():
    """The user path for the hybrid 2-D recipe: prepare_training(
    spmd='fsdp_tp') shards state over BOTH axes and training learns."""
    import fluxdistributed_tpu.mesh as mesh_lib
    from fluxdistributed_tpu.data import SyntheticTextDataset
    from fluxdistributed_tpu.models import lm_loss_fn, lm_tiny
    from fluxdistributed_tpu.train import prepare_training, train
    from fluxdistributed_tpu.train.logging import NullLogger

    mesh = mesh_lib.make_mesh({"data": 2, "model": 4})
    model = lm_tiny(vocab=32, dtype=jnp.float32)
    ds = SyntheticTextDataset(vocab=32, seqlen=32, peak=0.9)
    task = prepare_training(
        model, ds, optim.adam(3e-3), mesh=mesh, batch_size=32, cycles=30,
        loss_fn=lm_loss_fn(model), topk=(), spmd="fsdp_tp",
    )
    emb = task.state.params["embed"]["embedding"]
    assert emb.sharding.spec == P("model", "data")
    assert emb.addressable_shards[0].data.size == emb.size // 8
    losses = []
    orig = task.step_fn

    def rec(state, batch):
        out = orig(state, batch)
        losses.append(float(out[1]["loss"]))
        return out

    task.step_fn = rec
    train(task, print_every=0, eval_every=0, topk=(), logger=NullLogger())
    assert losses[-1] < losses[0]


def test_fsdp_eval_and_accum(setup):
    mesh, params, loss_fn, batch = setup
    opt = optim.momentum(0.05, 0.9)
    b = sharding.shard_batch(batch, mesh)
    state = TrainState.create(params, opt)
    specs = fsdp_specs(state, mesh, min_size=64)
    state = fsdp.shard_state(state, specs, mesh)

    # grad accumulation composes with FSDP (scan over microbatches)
    step = make_train_step_fsdp(loss_fn, opt, mesh, specs, donate=False, accum_steps=2)
    state2, m = step(state, b)
    assert np.isfinite(np.asarray(m["loss"]))

    ev = make_eval_step_fsdp(loss_fn, mesh, specs, topk=(1,))
    loss, metrics = ev(state2, b)
    assert np.isfinite(np.asarray(loss))
    assert 0.0 <= float(metrics["top1"]) <= 1.0
