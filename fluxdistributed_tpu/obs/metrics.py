"""Process-wide metrics registry: Counter / Gauge / Histogram with labels.

The single instrumentation substrate for the repo (ROADMAP: production
serving + training need ONE answer to "what is this process doing").
Before this layer existed the repo had four disconnected fragments — a
``Logger`` protocol, a hand-rolled Prometheus string in the LM server,
a fixed-window profiler capture, and an offline trace analyzer.  Every
subsystem now registers its counters here and two exporters read them:

* :meth:`Registry.prometheus_text` — Prometheus text exposition (the
  ``/metrics`` endpoint of both the LM server and the training driver);
* :meth:`Registry.snapshot` / :class:`JsonlSink` — flat JSON snapshots
  appended to a ``.jsonl`` file for offline diffing between runs.

Design points:

* **get-or-create registration** — ``registry.counter(name, ...)``
  returns the existing metric when called twice with a consistent
  signature (train() may run many times per process; re-registration
  must not raise) and raises on kind/label conflicts (two subsystems
  silently sharing one name would corrupt both).
* **thread-safe** — the loader's prefetch workers, the serve loop
  thread, HTTP handler threads and the watchdog all write concurrently;
  each metric guards its cells with one lock (bounded, uncontended).
* **callback gauges** — ``Gauge.set_function`` renders a value computed
  at scrape time (queue depth, compile-cache size) so hot paths never
  pay for bookkeeping the scraper can derive.
* **naming** — every metric is ``fdtpu_<subsystem>_<what>_<unit>``
  snake_case; the serve parity tests pin the exposition byte-for-byte
  and fdtpu-lint's FDT106 rule enforces the prefix statically at every
  registration site (docs/analysis.md).
"""

from __future__ import annotations

import json
import math
import threading
import time
from typing import Callable, Dict, Optional, Sequence, Tuple

__all__ = [
    "Counter",
    "Gauge",
    "Histogram",
    "JsonlSink",
    "Registry",
    "bucket_percentile",
    "get_registry",
]

# Prometheus-conventional timing buckets, stretched to cover both a
# sub-millisecond decode step and a minutes-long XLA compile.
DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0,
)


def bucket_percentile(bounds: Sequence[float], counts: Sequence[int],
                      q: float) -> float:
    """Percentile ``q`` (0-100) estimated from histogram buckets — the
    ONE percentile implementation every consumer shares (decode_bench,
    the serve rollup gauges, the profile artifact) instead of each
    rolling its own off-by-one bucket walk.

    ``bounds`` are the finite upper bucket bounds (ascending);
    ``counts`` are PER-BUCKET (non-cumulative) counts with one extra
    trailing entry for the +Inf bucket, i.e. ``len(counts) ==
    len(bounds) + 1`` — exactly a :class:`_HistogramCell`'s layout.
    Linear interpolation inside the target bucket (lower edge 0 for the
    first); a percentile landing in the +Inf bucket returns the largest
    finite bound (the honest Prometheus ``histogram_quantile``
    convention — the data says "bigger than everything we bin").
    Returns NaN when the histogram is empty.
    """
    if not 0 <= q <= 100:
        raise ValueError(f"percentile must be in [0, 100], got {q}")
    if len(counts) != len(bounds) + 1:
        raise ValueError(
            f"counts must have one entry per bound plus +Inf "
            f"({len(bounds) + 1}), got {len(counts)}")
    total = sum(counts)
    if total == 0:
        return math.nan
    rank = q / 100.0 * total
    cum = 0.0
    for i, b in enumerate(bounds):
        prev_cum = cum
        cum += counts[i]
        if cum >= rank:
            lo = bounds[i - 1] if i else 0.0
            frac = (rank - prev_cum) / counts[i] if counts[i] else 0.0
            return lo + frac * (b - lo)
    return float(bounds[-1])  # landed in the +Inf bucket


def _escape_label(v: str) -> str:
    """Label-value escaping per the exposition format spec: backslash,
    double-quote, and newline must be escaped inside ``name{k="v"}``."""
    return (
        str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _escape_help(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt(v: float) -> str:
    """Prometheus value rendering — integers stay integral, floats keep
    enough digits to round-trip, +Inf spelled the Prometheus way."""
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    f = float(v)
    if f.is_integer() and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


class _Metric:
    """Shared label plumbing: a metric owns one cell per label-value
    tuple; the unlabeled metric is the single ``()`` cell."""

    kind = "untyped"

    def __init__(self, name: str, help: str, labelnames: Sequence[str] = ()):
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._cells: Dict[Tuple[str, ...], object] = {}
        if not self.labelnames:
            # eager default cell: an unlabeled metric exposes its zero
            # from registration on (absence reads as "not instrumented")
            self._cell(())

    def _new_cell(self):
        raise NotImplementedError

    def _cell(self, labelvalues: Tuple[str, ...]):
        with self._lock:
            cell = self._cells.get(labelvalues)
            if cell is None:
                cell = self._cells[labelvalues] = self._new_cell()
            return cell

    def labels(self, *values, **kv):
        """The child metric for one label-value combination (creates it
        on first use, like prometheus_client)."""
        if values and kv:
            raise ValueError("pass label values positionally OR by name")
        if kv:
            missing = set(self.labelnames) - set(kv)
            extra = set(kv) - set(self.labelnames)
            if missing or extra:
                raise ValueError(
                    f"{self.name} has labels {self.labelnames}; "
                    f"got {sorted(kv)}"
                )
            values = tuple(kv[k] for k in self.labelnames)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name} needs {len(self.labelnames)} label values "
                f"{self.labelnames}, got {len(values)}"
            )
        if not self.labelnames:
            raise ValueError(f"{self.name} has no labels")
        return self._cell(tuple(str(v) for v in values))

    def _default_cell(self):
        if self.labelnames:
            raise ValueError(
                f"{self.name} has labels {self.labelnames} — "
                "call .labels(...) first"
            )
        return self._cell(())

    # -- exposition ----------------------------------------------------
    def _series(self):
        """Yield ``(labelvalues, cell)`` snapshot-safely."""
        with self._lock:
            items = list(self._cells.items())
        return items

    def _label_str(self, labelvalues: Tuple[str, ...]) -> str:
        if not labelvalues:
            return ""
        pairs = ",".join(
            f'{k}="{_escape_label(v)}"'
            for k, v in zip(self.labelnames, labelvalues)
        )
        return "{" + pairs + "}"


class _CounterCell:
    __slots__ = ("value", "_lock")

    def __init__(self):
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(
                f"counters are monotonic; cannot inc by {amount} "
                "(use a Gauge for values that go down)"
            )
        with self._lock:
            self.value += amount


class Counter(_Metric):
    """Monotonically increasing count (requests, steps, compile-seconds)."""

    kind = "counter"

    def _new_cell(self):
        return _CounterCell()

    def inc(self, amount: float = 1.0) -> None:
        self._default_cell().inc(amount)

    def value(self, *labelvalues) -> float:
        cell = self.labels(*labelvalues) if labelvalues else self._default_cell()
        return cell.value

    def expose(self) -> list:
        return [
            (self.name + self._label_str(lv), cell.value)
            for lv, cell in self._series()
        ]

    def sample(self) -> dict:
        return {
            self.name + self._label_str(lv): cell.value
            for lv, cell in self._series()
        }


class _GaugeCell:
    __slots__ = ("_value", "_fn", "_lock")

    def __init__(self):
        self._value = 0.0
        self._fn: Optional[Callable[[], float]] = None
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._fn = None
            self._value = float(v)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    def set_function(self, fn: Callable[[], float]) -> None:
        with self._lock:
            self._fn = fn

    @property
    def value(self) -> float:
        fn = self._fn
        if fn is not None:
            try:
                return float(fn())
            except Exception:  # noqa: BLE001 — a dead callback must not
                return math.nan  # kill the scrape; NaN flags it honestly
        return self._value


class Gauge(_Metric):
    """Point-in-time value (queue depth, active slots, last TTFT)."""

    kind = "gauge"

    def _new_cell(self):
        return _GaugeCell()

    def set(self, v: float) -> None:
        self._default_cell().set(v)

    def inc(self, amount: float = 1.0) -> None:
        self._default_cell().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default_cell().dec(amount)

    def set_function(self, fn: Callable[[], float]) -> None:
        """Compute the value at scrape time (zero hot-path cost)."""
        self._default_cell().set_function(fn)

    def value(self, *labelvalues) -> float:
        cell = self.labels(*labelvalues) if labelvalues else self._default_cell()
        return cell.value

    def expose(self) -> list:
        return [
            (self.name + self._label_str(lv), cell.value)
            for lv, cell in self._series()
        ]

    def sample(self) -> dict:
        return {
            self.name + self._label_str(lv): cell.value
            for lv, cell in self._series()
        }


class _HistogramCell:
    __slots__ = ("buckets", "counts", "sum", "count", "_lock")

    def __init__(self, buckets: Tuple[float, ...]):
        self.buckets = buckets
        self.counts = [0] * (len(buckets) + 1)  # +1: the +Inf bucket
        self.sum = 0.0
        self.count = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.sum += v
            self.count += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self.counts[i] += 1
                    return
            self.counts[-1] += 1


class Histogram(_Metric):
    """Cumulative-bucket distribution (step-phase seconds, TTFT)."""

    kind = "histogram"

    def __init__(self, name, help, labelnames=(), buckets=DEFAULT_BUCKETS):
        b = tuple(sorted(float(x) for x in buckets))
        if not b:
            raise ValueError("histogram needs at least one bucket bound")
        self.buckets = b  # before super(): the eager default cell reads it
        super().__init__(name, help, labelnames)

    def _new_cell(self):
        return _HistogramCell(self.buckets)

    def observe(self, v: float) -> None:
        self._default_cell().observe(v)

    def time(self):
        """``with hist.time():`` — observe the block's wall seconds."""
        return _HistogramTimer(self._default_cell())

    def cell_sum(self, *labelvalues) -> float:
        cell = self.labels(*labelvalues) if labelvalues else self._default_cell()
        return cell.sum

    def cell_count(self, *labelvalues) -> int:
        cell = self.labels(*labelvalues) if labelvalues else self._default_cell()
        return cell.count

    def percentile(self, q: float, *labelvalues) -> float:
        """Estimated percentile ``q`` (0-100) of one cell via
        :func:`bucket_percentile`; NaN while the cell is empty."""
        cell = self.labels(*labelvalues) if labelvalues else self._default_cell()
        with cell._lock:
            counts = list(cell.counts)
        return bucket_percentile(self.buckets, counts, q)

    def series(self) -> dict:
        """Snapshot every cell as ``{label_tuple: {"sum", "count",
        "bounds", "counts"}}`` (counts per-bucket incl. the trailing
        +Inf entry) — the raw material the profile artifact persists so
        offline consumers can recompute any percentile."""
        out = {}
        for lv, cell in self._series():
            with cell._lock:
                out[lv] = {
                    "sum": cell.sum,
                    "count": cell.count,
                    "bounds": list(self.buckets),
                    "counts": list(cell.counts),
                }
        return out

    def expose(self) -> list:
        out = []
        for lv, cell in self._series():
            with cell._lock:
                counts = list(cell.counts)
                csum, ccount = cell.sum, cell.count
            cum = 0
            for bound, c in zip(self.buckets, counts):
                cum += c
                le = (f'le="{_fmt(bound)}"',)
                pairs = ",".join(
                    (*(f'{k}="{_escape_label(v)}"'
                       for k, v in zip(self.labelnames, lv)), *le)
                )
                out.append((f"{self.name}_bucket{{{pairs}}}", cum))
            pairs = ",".join(
                (*(f'{k}="{_escape_label(v)}"'
                   for k, v in zip(self.labelnames, lv)), 'le="+Inf"')
            )
            out.append((f"{self.name}_bucket{{{pairs}}}", cum + counts[-1]))
            out.append((self.name + "_sum" + self._label_str(lv), csum))
            out.append((self.name + "_count" + self._label_str(lv), ccount))
        return out

    def sample(self) -> dict:
        out = {}
        for lv, cell in self._series():
            base = self.name + self._label_str(lv)
            with cell._lock:
                out[base + "_sum"] = cell.sum
                out[base + "_count"] = cell.count
        return out


class _HistogramTimer:
    __slots__ = ("_cell", "_t0")

    def __init__(self, cell: _HistogramCell):
        self._cell = cell

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self._cell.observe(time.perf_counter() - self._t0)
        return False


class Registry:
    """Named collection of metrics with get-or-create registration and
    the two exporters (Prometheus text, JSON snapshot)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, _Metric] = {}

    # -- registration --------------------------------------------------
    def _get_or_create(self, cls, name, help, labelnames, **kw):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if type(existing) is not cls or tuple(
                    existing.labelnames
                ) != tuple(labelnames):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(existing).__name__}{existing.labelnames}; "
                        f"requested {cls.__name__}{tuple(labelnames)}"
                    )
                return existing
            m = cls(name, help, labelnames, **kw)
            self._metrics[name] = m
            return m

    def counter(self, name: str, help: str = "", labelnames=()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str = "", labelnames=()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(
        self, name: str, help: str = "", labelnames=(),
        buckets=DEFAULT_BUCKETS,
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labelnames, buckets=buckets
        )

    def get(self, name: str) -> Optional[_Metric]:
        with self._lock:
            return self._metrics.get(name)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._metrics.pop(name, None)

    # -- exporters -----------------------------------------------------
    def prometheus_text(self) -> str:
        """Prometheus text exposition format (``text/plain; version=0.0.4``)."""
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        lines = []
        for m in metrics:
            series = m.expose()
            if not series:
                continue  # labeled metric with no cells yet
            if m.help:
                lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for key, value in series:
                lines.append(f"{key} {_fmt(value)}")
        return "\n".join(lines) + "\n"

    def snapshot(self) -> dict:
        """Flat ``{series: value}`` dict (histograms as _sum/_count) —
        the JSONL sink's payload, also handy in tests."""
        with self._lock:
            metrics = list(self._metrics.values())
        out: dict = {}
        for m in metrics:
            out.update(m.sample())
        return out

    def value(self, name: str, *labelvalues, default: float = 0.0) -> float:
        """Read one series (0/default when absent) — the test/consumer
        shortcut that avoids parsing exposition text."""
        m = self.get(name)
        if m is None:
            return default
        try:
            return m.value(*labelvalues)  # type: ignore[attr-defined]
        except (ValueError, AttributeError, KeyError):
            return default


class JsonlSink:
    """Append registry snapshots to a ``.jsonl`` file, one JSON object
    per line — the offline-diff exporter (compare two runs with plain
    ``jq``; no Prometheus server needed)."""

    def __init__(self, path: str, registry: Optional[Registry] = None):
        self.path = path
        self.registry = registry or get_registry()
        self._lock = threading.Lock()

    def write(self, step: Optional[int] = None, **extra) -> dict:
        rec = {"ts": time.time()}
        if step is not None:
            rec["step"] = int(step)
        rec.update(extra)
        # non-finite values (a dead callback gauge reads NaN) would emit
        # bare NaN tokens — INVALID JSON that breaks every strict reader
        # of the file; null keeps the record parseable and honest
        rec["metrics"] = {
            k: (None if isinstance(v, float) and not math.isfinite(v) else v)
            for k, v in self.registry.snapshot().items()
        }
        line = json.dumps(rec, default=str, allow_nan=False)
        with self._lock, open(self.path, "a") as f:
            f.write(line + "\n")
        return rec


_REGISTRY = Registry()


def get_registry() -> Registry:
    """The process-wide default registry — what the trainer, loader and
    driver endpoint share (the serve scheduler takes a private one by
    default so engine instances stay isolated)."""
    return _REGISTRY
