"""Context/sequence parallelism: ring attention + Ulysses all-to-all.

Net-new scope beyond the reference (vision-CNN-only, SURVEY §5
"long-context: absent"), built first-class for TPU: long sequences are
sharded across a ``seq`` mesh axis and attention runs without ever
gathering the full sequence on one device.

Two strategies, both SPMD inside ``shard_map``:

* **Ring attention** (`ring_attention`): each device holds one Q shard
  and rotates KV shards around the ring with ``ppermute`` (one ICI hop
  per step), folding each arriving KV block into the shared
  online-softmax accumulator (``ops.attention.attn_block_update``) —
  compute overlaps the next hop's transfer, memory is O(T/P), and the
  numerics are bit-for-bit those of ``blockwise_attention``.
* **Ulysses** (`ulysses_attention`): two ``all_to_all``s re-shard
  [seq-sharded, all heads] ↔ [all seq, head-sharded]; attention itself
  is a dense local op on full sequences for H/P heads.  Cheaper at
  moderate sequence lengths (2 collectives instead of P hops); requires
  ``num_heads % P == 0``.

Use the ``make_*`` wrappers to get an ``attn_fn`` pluggable directly
into ``models.vit.ViT(attn_fn=...)`` — model code does not change when
the sequence axis is sharded.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..ops.attention import (
    NEG_INF,
    _expand_kv,
    _scale,
    attn_block_update,
    attn_finalize,
    attn_init,
)
from ..mesh import SEQ_AXIS

__all__ = [
    "ring_attention",
    "ring_flash_attention",
    "make_ring_attention",
    "ulysses_attention",
    "make_ulysses_attention",
]


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
) -> jax.Array:
    """Ring attention over sequence shards.  Call inside ``shard_map``.

    ``q``/``k``/``v`` are the LOCAL shards [B, T/P, H, D] of a sequence
    sharded on mesh axis ``axis_name``; returns the local output shard.
    Causal masking uses global positions (shard i owns tokens
    [i·T/P, (i+1)·T/P)).
    """
    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)
    t_local = q.shape[1]
    q_scaled = _scale(q)
    q_pos = my_idx * t_local + jnp.arange(t_local)

    # Send KV to the next rank each hop → after i hops this device holds
    # the KV shard originally owned by rank (my_idx - i) mod P.
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    def hop(i, state):
        carry, k_cur, v_cur = state
        blk = (my_idx - i) % axis_size
        mask = None
        if causal:
            k_pos = blk * t_local + jnp.arange(t_local)
            mask = k_pos[None, :] <= q_pos[:, None]
        # grouped KV rides the ring at hkv heads (the GQA bandwidth win
        # applies to ppermute traffic too); expand only for the local
        # block update
        k_blk, v_blk = _expand_kv(q_scaled, k_cur, v_cur)
        carry = attn_block_update(carry, q_scaled, k_blk, v_blk, mask=mask)
        # One more rotation than strictly needed on the last hop would
        # waste a transfer; guard via cond-free arithmetic is not worth
        # it — XLA overlaps the permute with the block compute.
        k_cur, v_cur = jax.lax.ppermute((k_cur, v_cur), axis_name, perm)
        return carry, k_cur, v_cur

    carry, _, _ = jax.lax.fori_loop(0, axis_size, hop, (attn_init(q), k, v))
    return attn_finalize(carry, q.dtype)


def ring_flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
) -> jax.Array:
    """Ring attention with the Pallas flash kernel as the per-hop block
    compute.  Call inside ``shard_map``.

    Each hop runs ``flash_attention_lse`` on the locally-resident KV
    shard (so the [T/P, T/P] score tile lives in VMEM, never HBM) and
    hops are merged by LSE weighting — the associative normalized-block
    combine:

        lse' = logaddexp(lse, lse_i)
        o'   = o·exp(lse − lse') + o_i·exp(lse_i − lse')

    Causality per hop: hop 0 is this device's OWN diagonal KV block →
    standard causal inside the kernel; hop i>0 holds the KV shard of
    rank (my_idx − i) mod P, which is either entirely BEFORE the local
    queries (fully visible, no mask) or entirely AFTER them (wrapped —
    its combine weight is zeroed).  The predicate is traced, so one
    compiled program serves every rank, and the FLOPs match the XLA
    ring (which also computes every hop and masks).

    The hop loop is a Python ``range`` over the static axis size —
    P pallas_call sites, each reverse-differentiable through
    ``flash_attention_lse``'s custom VJP.
    """
    from ..ops.pallas_attention import flash_attention_lse

    axis_size = jax.lax.psum(1, axis_name)
    my_idx = jax.lax.axis_index(axis_name)

    o = jnp.zeros(q.shape, jnp.float32)
    lse = jnp.full((q.shape[0], q.shape[2], q.shape[1]), NEG_INF, jnp.float32)
    k_cur, v_cur = k, v
    perm = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    for i in range(axis_size):
        o_i, lse_i = flash_attention_lse(
            q, k_cur, v_cur, causal and i == 0, block_q, block_k
        )
        if causal and i > 0:
            # KV shard of rank (my_idx - i) mod P: wrapped ranks hold
            # tokens entirely after the local queries → contribute 0
            wrapped = my_idx < i
            lse_i = jnp.where(wrapped, NEG_INF, lse_i)
        lse_new = jnp.logaddexp(lse, lse_i)
        # guard the fully-masked-row case: lse_new == NEG_INF would give
        # exp(0) = 1 weights; keep weights 0 so those rows stay 0
        w_prev = jnp.where(lse == NEG_INF, 0.0, jnp.exp(lse - lse_new))
        w_i = jnp.where(lse_i == NEG_INF, 0.0, jnp.exp(lse_i - lse_new))
        bthd = lambda w: w.transpose(0, 2, 1)[..., None]  # [B,H,T]→[B,T,H,1]
        o = o * bthd(w_prev) + o_i.astype(jnp.float32) * bthd(w_i)
        lse = lse_new
        if i + 1 < axis_size:
            k_cur, v_cur = jax.lax.ppermute((k_cur, v_cur), axis_name, perm)
    return o.astype(q.dtype)


def make_ring_attention(
    mesh: Mesh,
    seq_axis: str = SEQ_AXIS,
    batch_axis: Optional[str] = None,
    causal: bool = False,
    impl: str = "xla",
    block_q: int = 128,
    block_k: int = 128,
):
    """Wrap ring attention in ``shard_map`` → a drop-in ``attn_fn``.

    Takes/returns global [B, T, H, D] arrays with T sharded on
    ``seq_axis`` (and optionally B on ``batch_axis``); composes under an
    outer ``jit`` so a ViT built with this attn_fn trains data- AND
    sequence-parallel from one compiled program.  ``impl="flash"`` uses
    the Pallas kernel per hop (``ring_flash_attention``); ``"xla"`` uses
    the blockwise online-softmax update.
    """
    if impl not in ("xla", "flash"):
        raise ValueError(f"impl must be 'xla' or 'flash', got {impl!r}")
    spec = P(batch_axis, seq_axis)

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    def attn(q, k, v):
        if impl == "flash":
            return ring_flash_attention(
                q, k, v, seq_axis, causal=causal,
                block_q=block_q, block_k=block_k,
            )
        return ring_attention(q, k, v, seq_axis, causal=causal)

    return attn


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    axis_name: str,
    *,
    causal: bool = False,
) -> jax.Array:
    """Ulysses sequence parallelism: all-to-all heads↔sequence re-shard.

    Call inside ``shard_map`` with local shards [B, T/P, H, D]; requires
    H divisible by the axis size.  Attention itself runs dense on the
    full sequence for H/P heads (``blockwise_attention`` would also work;
    dense is fastest at the moderate T where Ulysses wins).
    """
    from ..ops.attention import dot_product_attention

    axis_size = jax.lax.psum(1, axis_name)
    if q.shape[2] % axis_size != 0:
        # a real error, not an assert: without it the tiled all_to_all
        # head re-shard fails later with an obscure reshape mismatch
        raise ValueError(
            f"ulysses_attention needs num_heads ({q.shape[2]}) divisible by "
            f"the '{axis_name}' axis size ({axis_size}): the all_to_all "
            "re-shards heads across the axis in equal chunks. Use a seq "
            "axis that divides the head count, or ring attention "
            "(make_ring_attention), which has no head-count constraint."
        )
    # [B, T/P, H, D] → [B, T, H/P, D]
    gather = partial(
        jax.lax.all_to_all, axis_name=axis_name, split_axis=2, concat_axis=1,
        tiled=True,
    )
    if k.shape[2] != q.shape[2] and k.shape[2] % axis_size != 0:
        # hkv not divisible by the axis (the tiled head re-shard needs
        # equal chunks per rank): expand BEFORE the gather — correct,
        # just without the grouped-comm saving.  Divisible grouped KV
        # rides the all_to_all at hkv heads; the local attention core
        # broadcasts it itself.
        k, v = _expand_kv(q, k, v)
    qg, kg, vg = gather(q), gather(k), gather(v)
    out = dot_product_attention(qg, kg, vg, causal=causal)
    # [B, T, H/P, D] → [B, T/P, H, D]
    return jax.lax.all_to_all(
        out, axis_name=axis_name, split_axis=1, concat_axis=2, tiled=True
    )


def make_ulysses_attention(
    mesh: Mesh,
    seq_axis: str = SEQ_AXIS,
    batch_axis: Optional[str] = None,
    causal: bool = False,
):
    """``shard_map`` wrapper for ``ulysses_attention`` (see
    ``make_ring_attention``)."""
    spec = P(batch_axis, seq_axis)

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    def attn(q, k, v):
        return ulysses_attention(q, k, v, seq_axis, causal=causal)

    return attn
