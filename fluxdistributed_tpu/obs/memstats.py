"""Memory observability: a static per-program HBM model + live device
telemetry.

The repo's only memory story so far was *reactive* — OOM-skip catches
the exception after the allocator already lost — and the pipeline
planner's per-stage byte estimate (``parallel/pp_plan.py``) was never
validated against anything.  This module is the missing data layer
(the memory analog of what ``obs/profile.py`` did for time):

* **static model** — :func:`step_memory` compiles a program (or reuses
  a caller-held ``Compiled``) and reads XLA's own
  ``memory_analysis()`` through the :mod:`..compat` shim:
  argument/output/temp/alias bytes plus the derived ``peak_bytes``
  (args + outputs + temps − aliased donations — XLA's live-HBM
  approximation).  :func:`state_bytes` prices a training state
  EXACTLY from leaf shapes (params / opt state / model state — works
  on live arrays and eval_shape structs alike), and
  :func:`variant_report` sweeps every registered variant through the
  REAL ``prepare_training`` / ``LMEngine`` builders
  (:mod:`..analysis.variants`) — one compile per program, shared with
  the collective ledger (:mod:`.comms`) so memory and comms truth come
  off the same executable.
* **live telemetry** — :class:`HbmGauges` exposes per-device
  ``fdtpu_hbm_bytes_{in_use,peak,limit}`` gauges plus
  ``fdtpu_hbm_headroom_ratio`` (min over devices of
  ``(limit − in_use)/limit``), all computed AT SCRAPE TIME from
  ``device.memory_stats()`` so hot paths pay nothing.  On backends
  that report no memory (CPU) the per-device gauges register no cells,
  ``fdtpu_hbm_available`` reads 0 and the headroom gauge reads NaN —
  "unavailable", never a crash and never a fake zero.

Consumers: ``train(observation=)`` (gauges + the watchdog's
low-headroom alert), the serve scheduler and ``/healthz`` (per-device
memory block), the N-replica router's ``/metrics`` rollup (the gauges
ride the replica-labeled re-exposition for free), ``bench.py``'s
``memory`` stamp, the ``fdtpu-profile/v2`` artifact, and ``bin/fit.py``
— the "does variant X fit topology Z" checker ROADMAP item 3's
auto-layout picker will call.
"""

from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .metrics import Registry, get_registry

__all__ = [
    "HbmGauges",
    "check_memory_baseline",
    "hbm_device_stats",
    "hbm_summary",
    "min_headroom_ratio",
    "pp_plan_memory_check",
    "state_bytes",
    "step_memory",
    "tree_bytes",
    "variant_report",
]

#: memory-baseline artifact schema (analysis/memory_baseline.json)
BASELINE_SCHEMA = "fdtpu-membaseline/v1"

#: default regression tolerance for the baseline ``--check``: a
#: variant's measured peak may grow this fraction over its committed
#: baseline before the check fails.  Deliberately loose — XLA's
#: temp-buffer accounting drifts across jax/jaxlib versions (CI runs a
#: newer wheel than the pinned image) — while still catching the 2x
#: regressions that actually break fits.
DEFAULT_TOLERANCE = 0.5


def tree_bytes(tree) -> int:
    """Exact bytes of every shaped leaf in ``tree`` — live arrays and
    ``eval_shape`` ShapeDtypeStructs price identically (shape × dtype
    itemsize; leaves without both are skipped, e.g. None opt slots)."""
    import jax
    import jax.numpy as jnp

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is None or dtype is None:
            continue
        n = 1
        for d in shape:
            n *= int(d)
        total += n * jnp.dtype(dtype).itemsize
    return int(total)


def state_bytes(state) -> Dict[str, int]:
    """Exact param / optimizer-state / model-state bytes of a training
    state (``parallel.TrainState`` or anything with those attributes).
    These are GLOBAL logical bytes — what the arrays hold across the
    whole mesh; divide by the shard count for per-device footprints
    (ZeRO-1's whole point is that opt bytes / N is what each device
    pays)."""
    params = tree_bytes(getattr(state, "params", None))
    opt = tree_bytes(getattr(state, "opt_state", None))
    mstate = tree_bytes(getattr(state, "model_state", None))
    return {
        "param_bytes": params,
        "opt_state_bytes": opt,
        "model_state_bytes": mstate,
        "total_bytes": params + opt + mstate,
    }


def step_memory(fn, args: Tuple[Any, ...], compiled=None) -> Optional[dict]:
    """XLA's compiled-program memory accounting for ``fn`` at ``args``
    (``{"argument_bytes", "output_bytes", "temp_bytes", "alias_bytes",
    "generated_code_bytes", "peak_bytes"}``), or None when the program
    cannot compile here or this jax build reports no
    ``memory_analysis`` — a missing memory model degrades the artifact,
    never the run.  Pass ``compiled`` to reuse an executable the caller
    already paid for (the variant sweep compiles once and feeds both
    this and the collective ledger)."""
    from .. import compat

    if compiled is None:
        try:
            compiled = fn.lower(*args).compile()
        except Exception:  # noqa: BLE001 — non-lowerable wrappers → None
            return None
    return compat.compiled_memory_analysis(compiled)


# -- live device telemetry --------------------------------------------------

def hbm_device_stats() -> Optional[List[dict]]:
    """Per-local-device memory truth off ``device.memory_stats()``:
    ``[{"device", "kind", "bytes_in_use", "peak_bytes_in_use",
    "bytes_limit"}, ...]`` or None when NO local device reports memory
    (CPU backends return None — the None-safe degradation the gauges
    and ``/healthz`` lean on)."""
    import jax

    from .. import compat

    out = []
    for i, dev in enumerate(jax.local_devices()):
        st = compat.device_memory_stats(dev)
        if st is None:
            continue
        in_use = int(st.get("bytes_in_use", 0))
        limit = int(st.get("bytes_limit")
                    or st.get("bytes_reservable_limit") or 0)
        out.append({
            "device": i,
            "kind": str(getattr(dev, "device_kind", dev.platform)),
            "bytes_in_use": in_use,
            "peak_bytes_in_use": int(st.get("peak_bytes_in_use", in_use)),
            "bytes_limit": limit,
        })
    return out or None


def hbm_summary() -> dict:
    """The ``/healthz`` / bench-stamp memory block: per-device stats
    plus the fleet-facing rollups, or ``{"available": False}`` on
    backends without memory stats.  Never raises."""
    try:
        stats = hbm_device_stats()
    except Exception:  # noqa: BLE001 — telemetry must not kill a scrape
        return {"available": False}
    if not stats:
        return {"available": False}
    ratios = [(d["bytes_limit"] - d["bytes_in_use"]) / d["bytes_limit"]
              for d in stats if d["bytes_limit"] > 0]
    out = {
        "available": True,
        "devices": stats,
        "bytes_in_use_max": max(d["bytes_in_use"] for d in stats),
        "peak_bytes_in_use_max": max(
            d["peak_bytes_in_use"] for d in stats),
    }
    if ratios:
        out["min_headroom_ratio"] = min(ratios)
    return out


def min_headroom_ratio() -> Optional[float]:
    """Min over devices of ``(limit − in_use)/limit`` — the watchdog's
    OOM-margin input; None when unavailable (CPU)."""
    try:
        stats = hbm_device_stats()
    except Exception:  # noqa: BLE001
        return None
    if not stats:
        return None
    ratios = [(d["bytes_limit"] - d["bytes_in_use"]) / d["bytes_limit"]
              for d in stats if d["bytes_limit"] > 0]
    return min(ratios) if ratios else None


class HbmGauges:
    """Scrape-time per-device HBM gauges on a registry.

    Registration is get-or-create (safe to build one per
    train()/Scheduler on a shared registry); availability is probed
    ONCE at construction — a backend does not grow memory stats
    mid-process.  When unavailable, only ``fdtpu_hbm_available`` (0)
    and the NaN headroom gauge expose: the per-device byte gauges
    register no label cells, so a CPU scrape says "unavailable"
    instead of inventing zero-byte devices.  ``gauge_names`` lists
    every name registered here so a retiring scheduler can detach its
    callbacks (:meth:`close`)."""

    #: one device sweep serves every gauge cell read within this window
    #: — a /metrics render touches 3 cells per device plus the headroom
    #: gauge, and each would otherwise re-sweep ALL devices
    #: (O(devices²) memory_stats calls per scrape, multiplied by the
    #: router's per-probe replica scrapes)
    SWEEP_TTL_SECONDS = 0.1

    def __init__(self, registry: Optional[Registry] = None,
                 name_prefix: str = "fdtpu"):
        self.registry = registry or get_registry()
        p = name_prefix
        self._sweep_at = 0.0
        self._sweep: Optional[List[dict]] = None
        try:
            self.available = hbm_device_stats() is not None
        except Exception:  # noqa: BLE001 — a broken backend reads as absent
            self.available = False
        g = self.registry.gauge
        self._avail = g(
            f"{p}_hbm_available",
            "1 when device.memory_stats() reports HBM truth, 0 on "
            "backends without it (CPU)")
        self._avail.set(1.0 if self.available else 0.0)
        self._headroom = g(
            f"{p}_hbm_headroom_ratio",
            "min over devices of (bytes_limit - bytes_in_use) / "
            "bytes_limit — the OOM margin; NaN when unavailable")
        def _headroom_or_nan() -> float:
            stats = self._sweep_stats()
            if not stats:
                return math.nan
            ratios = [(d["bytes_limit"] - d["bytes_in_use"])
                      / d["bytes_limit"]
                      for d in stats if d["bytes_limit"] > 0]
            return min(ratios) if ratios else math.nan

        self._headroom.set_function(_headroom_or_nan)
        self.gauge_names = [f"{p}_hbm_available",
                            f"{p}_hbm_headroom_ratio"]
        self._per_device = []
        if self.available:
            for name, key, txt in (
                (f"{p}_hbm_bytes_in_use", "bytes_in_use",
                 "HBM bytes currently allocated, per device"),
                (f"{p}_hbm_bytes_peak", "peak_bytes_in_use",
                 "peak HBM bytes allocated since process start, "
                 "per device"),
                (f"{p}_hbm_bytes_limit", "bytes_limit",
                 "HBM capacity the allocator may use, per device"),
            ):
                gauge = g(name, txt, labelnames=("device",))
                self.gauge_names.append(name)
                self._per_device.append((gauge, key))
            import jax

            for i in range(len(jax.local_devices())):
                for gauge, key in self._per_device:
                    gauge.labels(device=str(i)).set_function(
                        lambda i=i, key=key: self._read(i, key))

    def _sweep_stats(self) -> Optional[List[dict]]:
        """One :func:`hbm_device_stats` sweep per ``SWEEP_TTL_SECONDS``
        window, shared by every gauge cell a scrape renders."""
        import time

        now = time.monotonic()
        if now - self._sweep_at > self.SWEEP_TTL_SECONDS:
            try:
                self._sweep = hbm_device_stats()
            except Exception:  # noqa: BLE001 — a broken read scrapes NaN
                self._sweep = None
            self._sweep_at = now
        return self._sweep

    def _read(self, device: int, key: str) -> float:
        stats = self._sweep_stats()
        if not stats:
            return math.nan
        for d in stats:
            if d["device"] == device:
                return float(d[key])
        return math.nan

    def summary(self) -> dict:
        """The dict block ``/healthz`` and the bench stamp embed."""
        return hbm_summary()

    def close(self) -> None:
        """Detach the scrape-time callbacks from a SHARED registry
        (mirrors ``Scheduler.close()`` — retired callback closures must
        not pin dead engines or keep scraping stale backends)."""
        for name in self.gauge_names:
            self.registry.unregister(name)


# -- the per-variant sweep (shared with the collective ledger) --------------

def variant_report(names: Optional[Sequence[str]] = None,
                   include_hlo: bool = True) -> Dict[str, dict]:
    """Memory + collective truth for every registered variant, built
    through the REAL ``prepare_training`` / ``LMEngine`` paths
    (:mod:`..analysis.variants`) and compiled ONCE each — the
    executable feeds XLA's ``memory_analysis`` AND the post-
    optimization HLO collective ledger, so both describe the same
    program.  Per entry::

        {"source": ...,              # repo file the program came from
         "args_bytes": N,            # exact input bytes (leaf shapes)
         "memory": {...} | None,     # step_memory; None = unavailable
         "comms": {"jaxpr": [...],   # explicit collectives (shard_map)
                   "hlo": [...]},    # compiled collectives (GSPMD too)
         "unavailable": "reason"}    # only when the compile failed

    Expensive (compiles each variant on the live mesh) — an offline
    artifact/CI path, not a hot one."""
    from ..analysis.variants import build_variants
    from .comms import hlo_collectives, jaxpr_collectives

    out: Dict[str, dict] = {}
    for v in build_variants(names):
        entry: dict = {"source": v.source,
                       "args_bytes": tree_bytes(v.args)}
        comms: dict = {}
        try:
            comms["jaxpr"] = jaxpr_collectives(v.fn, v.args)
        except Exception as e:  # noqa: BLE001 — ledger is best-effort
            comms["jaxpr_unavailable"] = f"{type(e).__name__}: {e}"[:200]
        compiled = None
        try:
            compiled = v.fn.lower(*v.args).compile()
        except Exception as e:  # noqa: BLE001 — a variant that cannot
            # compile here still reports its jaxpr-level ledger
            entry["unavailable"] = f"{type(e).__name__}: {e}"[:200]
        if compiled is not None:
            entry["memory"] = step_memory(v.fn, v.args, compiled=compiled)
            if include_hlo:
                try:
                    comms["hlo"] = hlo_collectives(compiled, mesh=v.mesh)
                except Exception as e:  # noqa: BLE001
                    comms["hlo_unavailable"] = (
                        f"{type(e).__name__}: {e}"[:200])
        entry["comms"] = comms
        out[v.name] = entry
    return out


# -- headroom ranking (shared by bin/fit.py and the layout picker) ----------

def rank_memory(variant_memory: Dict[str, dict],
                budget: Optional[float]) -> List[dict]:
    """Headroom ranking rows over ``{name: {"memory": step_memory-dict
    | None}}``, sorted most-headroom-first; entries whose memory model
    was unavailable rank LAST with ``fits=None`` — unknown is not
    "fits".  This is the ONE ranking both ``bin/fit.py`` (over a
    profile artifact's variants) and ``parallel.layout.pick`` (over
    candidate layouts) consume, so the two CLIs can never drift on
    what "fits" means."""
    rows = []
    for name, entry in sorted(variant_memory.items()):
        mem = entry.get("memory") if isinstance(entry, dict) else None
        row = {"variant": name, "peak_bytes": None,
               "headroom_bytes": None, "fits": None}
        if mem:
            row["peak_bytes"] = int(mem["peak_bytes"])
            if budget is not None:
                row["headroom_bytes"] = int(budget - mem["peak_bytes"])
                row["fits"] = row["headroom_bytes"] >= 0
        rows.append(row)

    def _key(r):
        if r["peak_bytes"] is None:
            return (1, 0.0)  # unknowns last
        if r["headroom_bytes"] is None:
            return (0, float(r["peak_bytes"]))  # no budget: smallest first
        return (0, -float(r["headroom_bytes"]))  # most headroom first

    rows.sort(key=_key)
    return rows


# -- baseline workflow (the lint-baseline idiom for memory) -----------------

def check_memory_baseline(current: Dict[str, dict], baseline: dict,
                          tolerance: Optional[float] = None) -> dict:
    """Compare a :func:`variant_report` sweep against the committed
    baseline (``analysis/memory_baseline.json``).  The lint-baseline
    contract: FAIL only on NEW regressions — a variant whose measured
    ``peak_bytes`` grew beyond ``(1 + tolerance) ×`` its committed
    value, or a variant the baseline does not cover at all (CI must
    force the baseline to stay exhaustive).  Shrinkage and stale
    baseline entries are reported non-fatally.  Returns ``{"failures":
    [...], "notes": [...], "checked": N, "tolerance": t}``."""
    doc = baseline.get("variants", {})
    tol = (tolerance if tolerance is not None
           else float(baseline.get("tolerance", DEFAULT_TOLERANCE)))
    failures, notes = [], []
    checked = 0
    for name, entry in sorted(current.items()):
        mem = entry.get("memory")
        if not mem:
            notes.append(f"{name}: memory_analysis unavailable here — "
                         "not checked")
            continue
        base = doc.get(name)
        if base is None:
            failures.append(
                f"{name}: not covered by the baseline — run "
                "bin/fit.py --update-baseline so every registered "
                "variant stays a CI-gated invariant")
            continue
        checked += 1
        peak = int(mem["peak_bytes"])
        ref = int(base.get("peak_bytes", 0))
        if ref and peak > ref * (1.0 + tol):
            failures.append(
                f"{name}: peak_bytes {peak} regressed beyond "
                f"{ref} x (1 + {tol}) — a real memory regression, or "
                "an intentional change needing --update-baseline")
        elif ref and peak < ref / (1.0 + tol):
            notes.append(f"{name}: peak_bytes {peak} shrank well below "
                         f"baseline {ref} — consider re-recording")
    for name in sorted(set(doc) - set(current)):
        notes.append(f"stale baseline entry {name!r} — variant no "
                     "longer registered; shrink the baseline")
    return {"failures": failures, "notes": notes, "checked": checked,
            "tolerance": tol}


def build_baseline(current: Dict[str, dict],
                   tolerance: float = DEFAULT_TOLERANCE) -> dict:
    """The committed-baseline document for a sweep (drops entries whose
    memory model was unavailable — they cannot regress)."""
    variants = {}
    for name, entry in sorted(current.items()):
        mem = entry.get("memory")
        if mem:
            variants[name] = {
                k: int(mem[k]) for k in (
                    "peak_bytes", "argument_bytes", "output_bytes",
                    "temp_bytes", "alias_bytes")}
    return {"schema": BASELINE_SCHEMA, "tolerance": tolerance,
            "variants": variants}


# -- pp_plan cross-validation ----------------------------------------------

#: documented tolerance band for :func:`pp_plan_memory_check`.  The
#: plan's ``stage_bytes`` model the per-stage WORKING SET the schedule
#: holds live (stage params + the min(S, M)-slot activation input
#: ring).  The compiled step's ``peak_bytes`` additionally carries what
#: the model deliberately leaves out — gradients, optimizer moments,
#: XLA temps and the batch itself — so the honest invariant is a band,
#: not equality: the measured peak must be at least the modeled peak
#: stage (the estimate is a lower bound by construction) and at most
#: ``PP_MEMORY_FACTOR ×`` the modeled TOTAL (params + grads + two Adam
#: moments + activations + temps ≈ 5-6× params; 8 leaves margin for
#: XLA's layout padding without letting an order-of-magnitude modeling
#: bug through).
PP_MEMORY_FACTOR = 8.0


def pp_plan_memory_check(plan, fn, args: Tuple[Any, ...],
                         factor: float = PP_MEMORY_FACTOR) -> dict:
    """Cross-validate a :class:`~..parallel.pp_plan.PipelinePlan`'s
    per-stage memory estimate against XLA's ``memory_analysis`` of the
    REAL compiled step it drives (see :data:`PP_MEMORY_FACTOR` for the
    documented band).  Returns a report dict with ``within`` — False
    when the estimate and the compiler disagree beyond the band, or
    when the plan recorded no estimate; ``measured`` is None (and
    ``within`` None, "unavailable") on builds without a memory model."""
    measured = step_memory(fn, args)
    modeled = [float(b) for b in getattr(plan, "stage_bytes", ()) or ()]
    report: dict = {
        "modeled_stage_bytes": modeled,
        "modeled_peak_stage": max(modeled) if modeled else 0.0,
        "modeled_total": sum(modeled),
        "factor": factor,
        "measured": measured,
        "within": None,
    }
    if measured is None or not modeled:
        return report
    peak = float(measured["peak_bytes"])
    report["within"] = (
        report["modeled_peak_stage"] <= peak
        <= factor * report["modeled_total"])
    return report
