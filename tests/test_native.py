"""Native C++ image-ingest pipeline vs the Python/PIL reference path.

The invariant mirrors the reference's data-path behavior (SURVEY §3.4):
decode → resize-smallest-side → center-crop → normalize must produce the
same training distribution whichever backend runs it.  The no-resize path
must match the Python path exactly; the antialiased resize may differ
from PIL by sub-pixel-level amounts (different but equivalent filters —
the reference itself swaps Gaussian-lowpass+imresize for whatever
Images.jl does, src/preprocess.jl:30-42).
"""

import importlib
import os

import numpy as np
import pytest

from fluxdistributed_tpu.data import native

pp = importlib.import_module("fluxdistributed_tpu.data.preprocess")

pytestmark = pytest.mark.skipif(
    not native.available(), reason="native toolchain/libjpeg unavailable"
)


@pytest.fixture(scope="module")
def img():
    rng = np.random.default_rng(0)
    grad = np.linspace(0, 255, 300)[:, None, None]
    return np.clip(grad + rng.normal(0, 25, (300, 400, 3)), 0, 255).astype(np.uint8)


@pytest.fixture(scope="module")
def jpeg_dir(tmp_path_factory, img):
    from PIL import Image

    d = tmp_path_factory.mktemp("jpegs")
    paths = []
    for i in range(8):
        p = str(d / f"im{i}.jpg")
        Image.fromarray(np.roll(img, i * 7, axis=1)).save(p, quality=95)
        paths.append(p)
    return paths


def test_no_resize_path_matches_python_exactly(img):
    sq = img[:224, :224]
    a = native.preprocess_rgb(sq, crop=224, resize=224)
    b = pp.preprocess(sq, crop=224, resize=224)
    np.testing.assert_allclose(a, b, atol=1e-5)


def test_resize_path_close_to_pil(img):
    a = native.preprocess_rgb(img)
    b = pp.preprocess(img)
    d = np.abs(a - b)
    # normalized units; 0.02 ≈ 1 pixel level (1/255 / 0.225)
    assert d.mean() < 0.02 and np.percentile(d, 99) < 0.06


def test_compat_double_normalize(img):
    a = native.preprocess_rgb(img, compat_double_normalize=True)
    b = pp.preprocess(img, compat_double_normalize=True)
    assert np.abs(a - b).mean() < 0.05
    # quirk output is per-image standardized
    assert abs(a.mean()) < 1e-3 and abs(a.std() - 1) < 1e-2


def test_decode_jpeg_file(jpeg_dir):
    from PIL import Image

    rgb = native.decode_jpeg_file(jpeg_dir[0])
    assert rgb.shape == (300, 400, 3) and rgb.dtype == np.uint8
    # both decoders sit on libjpeg → bit-identical output
    pil = np.asarray(Image.open(jpeg_dir[0]).convert("RGB"))
    np.testing.assert_array_equal(rgb, pil)


def test_load_batch_matches_per_image_pipeline(jpeg_dir):
    out = native.load_batch(jpeg_dir, num_threads=4)
    assert out.shape == (len(jpeg_dir), 224, 224, 3)
    ref = np.stack([pp.preprocess(p) for p in jpeg_dir])
    assert np.abs(out - ref).mean() < 0.02


def test_cmyk_jpeg_decodes(tmp_path, img):
    """ImageNet contains a few CMYK JPEGs; libjpeg can't emit RGB for
    them, so the native decoder converts explicitly."""
    from PIL import Image

    p = str(tmp_path / "cmyk.jpg")
    Image.fromarray(img).convert("CMYK").save(p, quality=95)
    rgb = native.decode_jpeg_file(p)
    pil = np.asarray(Image.open(p).convert("RGB"))
    assert rgb.shape == pil.shape
    # different CMYK→RGB roundings; stay within a couple of levels
    assert np.abs(rgb.astype(int) - pil.astype(int)).mean() < 3


def test_load_batch_fallback_handles_png_disguised_as_jpeg(jpeg_dir, tmp_path, img):
    """PNG bytes behind a .JPEG extension (real ImageNet quirk) must go
    through the Python fallback instead of poisoning the batch."""
    import importlib

    from PIL import Image

    ppm = importlib.import_module("fluxdistributed_tpu.data.preprocess")
    png = str(tmp_path / "sneaky.JPEG")
    Image.fromarray(img).save(png, format="PNG")
    out = native.load_batch([jpeg_dir[0], png], fallback=lambda p: ppm.preprocess(p))
    ref = ppm.preprocess(png)
    np.testing.assert_allclose(out[1], ref, atol=1e-5)


def test_augmented_preprocess_matches_python(img):
    """RandomResizedCrop+flip parity: the native and Python executors
    consume the same relative params and must produce near-identical
    output (shared _aug_rect/aug_rect contract)."""
    rng = np.random.default_rng(7)
    for row in pp.sample_augment_params(rng, 6):
        a = native.preprocess_rgb(img, augment=row)
        b = pp.preprocess(img, augment=row)
        d = np.abs(a - b)
        assert d.mean() < 0.03, f"params {row}: mean diff {d.mean()}"


def test_augmented_flip_actually_flips(img):
    row = np.array([0.5, 1.0, 0.5, 0.5, 0.0], np.float32)
    flipped = row.copy()
    flipped[4] = 1.0
    a = native.preprocess_rgb(img, augment=row)
    b = native.preprocess_rgb(img, augment=flipped)
    np.testing.assert_allclose(a, b[:, ::-1], atol=1e-5)


def test_load_batch_augs_matches_per_image(jpeg_dir):
    rng = np.random.default_rng(3)
    augs = pp.sample_augment_params(rng, len(jpeg_dir))
    out = native.load_batch(jpeg_dir, num_threads=4, augs=augs)
    ref = np.stack([pp.preprocess(p, augment=augs[i]) for i, p in enumerate(jpeg_dir)])
    assert np.abs(out - ref).mean() < 0.03


def test_degenerate_aug_row_is_eval_path_on_both_backends(img):
    """area <= 0 disables augmentation in the C executor; the Python
    executor applies the same gate, so both produce the eval output."""
    zero = np.zeros(5, np.float32)
    a = native.preprocess_rgb(img, augment=zero)
    b = pp.preprocess(img, augment=zero)
    ref = pp.preprocess(img)  # eval path
    np.testing.assert_allclose(b, ref, atol=1e-6)
    assert np.abs(a - ref).mean() < 0.02


def test_load_batch_augs_shape_checked(jpeg_dir):
    with pytest.raises(ValueError, match="augment params"):
        native.load_batch(jpeg_dir, augs=np.zeros((2, 5), np.float32))


def test_load_batch_augmented_fallback_gets_aug_row(jpeg_dir, tmp_path, img):
    """Slow-path (PIL) slots in an augmented batch must apply the same
    per-slot augmentation as the native slots."""
    from PIL import Image

    png = str(tmp_path / "sneaky2.JPEG")
    Image.fromarray(img).save(png, format="PNG")
    paths = [jpeg_dir[0], png]
    augs = pp.sample_augment_params(np.random.default_rng(5), 2)
    out = native.load_batch(
        paths, augs=augs, fallback=lambda p, aug=None: pp.preprocess(p, augment=aug)
    )
    ref = pp.preprocess(png, augment=augs[1])
    np.testing.assert_allclose(out[1], ref, atol=1e-5)


def test_load_batch_rejects_crop_larger_than_resize(jpeg_dir):
    with pytest.raises(ValueError, match="crop <= resize"):
        native.load_batch(jpeg_dir, crop=288, resize=256)


def test_load_batch_rejects_noncontiguous_out(jpeg_dir):
    big = np.empty((len(jpeg_dir), 224, 224, 6), np.float32)
    view = big[..., ::2]  # right shape/dtype, wrong strides
    with pytest.raises(ValueError, match="C-contiguous"):
        native.load_batch(jpeg_dir, out=view)


def test_load_batch_strict_raises_on_corrupt(jpeg_dir, tmp_path):
    bad = str(tmp_path / "bad.jpg")
    with open(bad, "wb") as f:
        f.write(b"not a jpeg at all")
    with pytest.raises(ValueError, match="failed to load"):
        native.load_batch([jpeg_dir[0], bad])
    out = native.load_batch([jpeg_dir[0], bad], strict=False)
    assert np.abs(out[1]).max() == 0.0  # zero-filled slot
    assert np.abs(out[0]).max() > 0.0  # good slot intact


def test_imagenet_dataset_uses_native(tmp_path, img):
    """ImageNetDataset(use_native=True) produces the same batches as the
    PIL path for the same indices."""
    from PIL import Image

    from fluxdistributed_tpu.data.imagenet import ImageNetDataset, SampleTable

    root = tmp_path
    d = root / "ILSVRC" / "Data" / "CLS-LOC" / "train" / "n01440764"
    os.makedirs(d)
    ids = []
    for i in range(4):
        iid = f"n01440764_{i}"
        Image.fromarray(np.roll(img, i * 11, axis=0)).save(
            str(d / f"{iid}.JPEG"), quality=95
        )
        ids.append(iid)
    table = SampleTable(np.asarray(ids, object), np.zeros(4, np.int32))
    ds_nat = ImageNetDataset(str(root), table, nclasses=1, use_native=True, augment=False)
    ds_py = ImageNetDataset(str(root), table, nclasses=1, use_native=False, augment=False)
    idx = np.array([0, 2, 3])
    a, la = ds_nat.batch(np.random.default_rng(0), 3, indices=idx)
    b, lb = ds_py.batch(np.random.default_rng(0), 3, indices=idx)
    np.testing.assert_array_equal(la, lb)
    assert np.abs(a - b).mean() < 0.02


def test_imagenet_dataset_augmented_backends_agree(tmp_path, img):
    """Train split defaults to augment=True; same rng → both backends
    draw the same RandomResizedCrop params → near-identical batches."""
    from PIL import Image

    from fluxdistributed_tpu.data.imagenet import ImageNetDataset, SampleTable

    root = tmp_path
    d = root / "ILSVRC" / "Data" / "CLS-LOC" / "train" / "n01440764"
    os.makedirs(d)
    ids = []
    for i in range(4):
        iid = f"n01440764_{i}"
        Image.fromarray(np.roll(img, i * 11, axis=0)).save(
            str(d / f"{iid}.JPEG"), quality=95
        )
        ids.append(iid)
    table = SampleTable(np.asarray(ids, object), np.zeros(4, np.int32))
    ds_nat = ImageNetDataset(str(root), table, nclasses=1, use_native=True)
    ds_py = ImageNetDataset(str(root), table, nclasses=1, use_native=False)
    assert ds_nat.augment and ds_py.augment  # train split defaults on
    idx = np.array([0, 1, 2, 3])
    a, _ = ds_nat.batch(np.random.default_rng(42), 4, indices=idx)
    b, _ = ds_py.batch(np.random.default_rng(42), 4, indices=idx)
    assert np.abs(a - b).mean() < 0.03
    # and augmentation actually changes the batch vs the eval path
    ds_eval = ImageNetDataset(str(root), table, nclasses=1, use_native=True, augment=False)
    c, _ = ds_eval.batch(np.random.default_rng(42), 4, indices=idx)
    assert np.abs(a - c).mean() > 0.05
