"""Layer-3 (FDT3xx) concurrency-lint tests (ISSUE 20).

Three blocks:

* **rules** — every rule in ``analysis.concurrency`` against its
  fixture pair in ``tests/fixtures_analysis/`` (positive fires exactly
  its rule; negative fires nothing), plus targeted semantics: RMW
  severity, the wholly-locked-callee propagation, Lock re-entry
  self-deadlock, chained-receiver ``set_function`` detection.
* **repo gate** — the default scan (package + bin + bench.py) comes
  back EMPTY: the layer's real findings (unlocked ``FaultPlan``
  appends, the ``Scheduler.begin_drain`` latch store) were fixed in
  the same PR that landed the rules, and the committed baseline stays
  empty.
* **CLI** — the ``--no-concurrency`` layer flag, exit codes, and the
  FDT3xx branch of the ``--update-baseline`` keep semantics.
"""

import ast
import json
import os
import subprocess
import sys
import textwrap

import pytest

from fluxdistributed_tpu import analysis
from fluxdistributed_tpu.analysis import concurrency
from fluxdistributed_tpu.analysis import engine as engine_mod

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures_analysis")
REPO = engine_mod.repo_root()
LINT = os.path.join(REPO, "bin", "lint.py")
CONC_IDS = [r.id for r in concurrency.CONC_RULES]


def _scan(name):
    return concurrency.run_concurrency_checks(
        [os.path.join(FIXTURES, name)])


def _scan_source(src, tmp_path, name="mod.py"):
    path = tmp_path / name
    path.write_text(textwrap.dedent(src))
    return concurrency.run_concurrency_checks([str(path)])


def _lint(*args, timeout=180):
    return subprocess.run(
        [sys.executable, LINT, *args], cwd=REPO,
        capture_output=True, text=True, timeout=timeout)


# ---------------------------------------------------------------- registry

def test_conc_registry_complete():
    # the FDT3xx registry is separate from AST_RULES (whose ids are
    # byte-pinned elsewhere); one fixture pair per rule, same contract
    assert CONC_IDS == [f"FDT30{i}" for i in range(1, 6)]
    assert not (set(CONC_IDS)
                & {r.id for r in analysis.AST_RULES})
    for rid in CONC_IDS:
        for pol in ("pos", "neg"):
            assert os.path.exists(
                os.path.join(FIXTURES, f"{rid.lower()}_{pol}.py"))


@pytest.mark.parametrize("rid", [r.id for r in concurrency.CONC_RULES])
def test_conc_rule_positive(rid):
    findings = _scan(f"{rid.lower()}_pos.py")
    assert findings, f"{rid} positive fixture fired nothing"
    assert {f.rule for f in findings} == {rid}, findings
    for f in findings:
        assert f.line > 0 and f.detail and f.hint, f


@pytest.mark.parametrize("rid", [r.id for r in concurrency.CONC_RULES])
def test_conc_rule_negative(rid):
    findings = _scan(f"{rid.lower()}_neg.py")
    assert findings == [], findings


def test_fdt301_severity_split():
    # RMW shapes are errors (a lost update), plain stores warnings
    # (an unordered flag flip)
    findings = _scan("fdt301_pos.py")
    by_detail = {f.detail: f.severity for f in findings}
    assert by_detail["Stat.racy_bump.count"] == "error"
    assert by_detail["Stat.racy_flag.flag"] == "warning"


def test_fdt301_wholly_locked_callee(tmp_path):
    # the repo's "lock held by caller" idiom: a private helper whose
    # every call site holds the lock is covered, not a violation
    findings = _scan_source(
        """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def bump(self):
                with self._lock:
                    self._bump_locked()

            def _bump_locked(self):
                self.n += 1  # covered: only ever called under the lock
        """, tmp_path)
    assert findings == [], findings


def test_fdt301_read_then_assign_is_error(tmp_path):
    findings = _scan_source(
        """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def locked_read(self):
                with self._lock:
                    return self.n

            def racy(self):
                v = self.n
                self.n = v + 1  # read-then-assign: a torn increment
        """, tmp_path)
    assert [f.rule for f in findings] == ["FDT301"]
    assert findings[0].severity == "error"


def test_fdt302_lock_reentry_self_deadlock(tmp_path):
    # `with self._lock: self.helper()` where helper re-acquires the
    # same non-reentrant Lock deadlocks immediately
    findings = _scan_source(
        """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.Lock()

            def outer(self):
                with self._lock:
                    self.inner_grab()

            def inner_grab(self):
                with self._lock:
                    return 1
        """, tmp_path)
    assert [f.rule for f in findings] == ["FDT302"], findings
    # the same shape on an RLock is legal re-entry — no finding
    clean = _scan_source(
        """
        import threading

        class S:
            def __init__(self):
                self._lock = threading.RLock()

            def outer(self):
                with self._lock:
                    self.inner_grab()

            def inner_grab(self):
                with self._lock:
                    return 1
        """, tmp_path, name="rlock.py")
    assert clean == [], clean


def test_fdt304_chained_receiver_set_function(tmp_path):
    # `registry.gauge(...).set_function(...)` — the receiver is a call
    # result, which breaks dotted-name chains; the rule must still see
    # the registration (this is exactly how the real scheduler/router
    # register their gauges)
    findings = _scan_source(
        """
        class G:
            def __init__(self, registry):
                registry.gauge("fdtpu_x", "x").set_function(lambda: 0.0)
        """, tmp_path)
    assert [f.rule for f in findings] == ["FDT304"], findings


def test_toy_racy_scheduler_is_statically_quiet():
    # the harness fixture is the residual class FDT301 cannot see (every
    # access holds the lock; the bug is the atomicity split BETWEEN two
    # regions) — pinning that keeps the static/dynamic division honest
    findings = _scan("toy_racy_scheduler.py")
    assert findings == [], findings


# ---------------------------------------------------------------- repo gate

def test_repo_concurrency_scan_clean():
    # the acceptance gate: FDT301-305 over the package + bin + bench.py
    # with an EMPTY committed baseline — the real findings this layer
    # surfaced (FaultPlan's unlocked appends, Scheduler.begin_drain's
    # unlocked latch store) are fixed, not baselined
    findings = concurrency.run_concurrency_checks()
    assert findings == [], [analysis.format_finding(f) for f in findings]


def test_fixed_sites_stay_fixed():
    # regression pins for the two fix sites, at source level: the
    # begin_drain latch store sits inside a lock region, and every
    # FaultPlan fault-list append does too (the lint rule would catch a
    # regression repo-wide; this names the exact sites so a failure
    # reads as "you reintroduced THE bug")
    for rel, cls_name, methods in [
        ("fluxdistributed_tpu/serve/scheduler.py", "Scheduler",
         ["begin_drain"]),
        ("fluxdistributed_tpu/faults.py", "FaultPlan",
         ["fail", "sigterm_at_step", "sigint_at_step"]),
    ]:
        path = os.path.join(REPO, rel)
        tree = ast.parse(open(path).read())
        mod = concurrency._build_module(path, rel, tree)
        cls = next(c for c in mod.classes if c.name == cls_name)
        for m in methods:
            mm = cls.methods[m]
            writes = [a for a in mm.accesses
                      if a.kind != "read"
                      and a.attr in ("draining", "_faults")]
            assert writes, (rel, m)
            assert all(a.held for a in writes), (rel, m, writes)


def test_lint_verdict_has_layer_counts():
    v = analysis.lint_verdict()
    assert v["new"] == 0
    assert set(v["layers"]) == {"ast", "concurrency"}
    assert v["layers"]["concurrency"] == 0  # repo is layer-3 clean


# ---------------------------------------------------------------- CLI

def test_cli_concurrency_fires_on_fixture():
    p = _lint(os.path.join("tests", "fixtures_analysis",
                           "fdt301_pos.py"), "--check")
    assert p.returncode == 1, p.stdout + p.stderr
    assert "FDT301" in p.stdout


def test_cli_no_concurrency_flag_skips_layer():
    p = _lint(os.path.join("tests", "fixtures_analysis",
                           "fdt301_pos.py"), "--check",
              "--no-concurrency")
    assert p.returncode == 0, p.stdout + p.stderr


def test_cli_repo_clean_with_concurrency():
    # the full AST + concurrency gate over the repo (jaxpr layer
    # skipped: its own suite compiles variants elsewhere)
    p = _lint("--check", "--no-jaxpr")
    assert p.returncode == 0, p.stdout + p.stderr


def test_cli_update_baseline_keeps_fdt3xx_when_layer_off(tmp_path):
    # a --no-concurrency update must not erase FDT3xx allowlist
    # entries it could not have re-observed
    baseline = tmp_path / "baseline.json"
    fixture = os.path.join("tests", "fixtures_analysis",
                           "fdt301_pos.py")
    p = _lint(fixture, "--update-baseline",
              "--baseline", str(baseline))
    assert p.returncode == 0, p.stdout + p.stderr
    entries = json.load(open(baseline))
    assert {e["rule"] for e in entries} == {"FDT301"}

    # layer off: the FDT3xx entries survive an in-scope re-update ...
    p = _lint(fixture, "--update-baseline", "--no-concurrency",
              "--baseline", str(baseline))
    assert p.returncode == 0, p.stdout + p.stderr
    after = json.load(open(baseline))
    assert {e["rule"] for e in after} == {"FDT301"}

    # ... layer on with the file in scope: stale entries are dropped
    # once the findings are gone (here: scanning the NEG fixture only)
    p = _lint(os.path.join("tests", "fixtures_analysis",
                           "fdt301_neg.py"),
              "--update-baseline", "--baseline", str(baseline))
    assert p.returncode == 0, p.stdout + p.stderr
    kept = json.load(open(baseline))
    assert {e["rule"] for e in kept} == {"FDT301"}  # pos file unscanned

    p = _lint(fixture, "--update-baseline", "--baseline", str(baseline))
    assert p.returncode == 0, p.stdout + p.stderr
    assert json.load(open(baseline)) != []  # re-observed, re-written
