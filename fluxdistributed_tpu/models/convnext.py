"""ConvNeXt family — net-new model scope beyond the reference.

The reference ships Metalhead ResNets only (README.md:27); ConvNeXt-XL
large-batch LARS training is one of this framework's BASELINE configs
(BASELINE.json "configs").  Built TPU-first:

* NHWC throughout; the 7×7 depthwise conv maps to XLA's grouped
  convolution (feature_group_count = channels), the 1×1 "pointwise"
  MLP convs are plain Dense layers on the channel axis → pure MXU
  matmuls over (B·H·W, C);
* bf16 compute / f32 params; LayerNorm statistics in f32;
* stochastic depth via a per-sample keep mask (shape-static, jit-safe:
  ``nn.Dropout`` broadcast over all but the batch dim — no Python
  branching on traced values);
* layer scale (γ per channel) as in the paper, init 1e-6.

No BatchNorm anywhere → no cross-replica statistics problem (the issue
the reference punted on, test/single_device.jl:51-58): every ConvNeXt
config trains identically under data parallelism by construction.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

from .common import maybe_remat

__all__ = [
    "ConvNeXt",
    "convnext_tiny",
    "convnext_small",
    "convnext_base",
    "convnext_large",
    "convnext_xlarge",
    "convnext_test",
]


class ConvNeXtBlock(nn.Module):
    """dwconv7×7 → LN → Dense(4d) → GELU → Dense(d) → layer-scale → droppath."""

    dim: int
    drop_path: float = 0.0
    layer_scale_init: float = 1e-6
    dtype: Any = jnp.bfloat16
    gelu_exact: bool = False  # erf GELU (torch default) vs tanh approx (TPU-fast)

    @nn.compact
    def __call__(self, x, train: bool = True):
        shortcut = x
        x = nn.Conv(
            self.dim, (7, 7), padding="SAME",
            feature_group_count=self.dim,  # depthwise
            dtype=self.dtype, name="dwconv",
        )(x)
        x = nn.LayerNorm(dtype=self.dtype, name="norm")(x)
        x = nn.Dense(4 * self.dim, dtype=self.dtype, name="pwconv1")(x)
        x = nn.gelu(x, approximate=not self.gelu_exact)
        x = nn.Dense(self.dim, dtype=self.dtype, name="pwconv2")(x)
        gamma = self.param(
            "layer_scale",
            nn.initializers.constant(self.layer_scale_init),
            (self.dim,), jnp.float32,
        )
        x = x * gamma.astype(self.dtype)
        if self.drop_path > 0.0:
            # stochastic depth: drop the whole residual branch per sample
            x = nn.Dropout(
                self.drop_path,
                broadcast_dims=tuple(range(1, x.ndim)),
                deterministic=not train,
            )(x)
        return shortcut + x


class Downsample(nn.Module):
    dim: int
    dtype: Any = jnp.bfloat16

    @nn.compact
    def __call__(self, x):
        x = nn.LayerNorm(dtype=self.dtype, name="norm")(x)
        return nn.Conv(
            self.dim, (2, 2), strides=(2, 2), dtype=self.dtype, name="conv"
        )(x)


class ConvNeXt(nn.Module):
    """ConvNeXt classifier (stem 4×4/4, four stages, global-avg head)."""

    depths: Sequence[int] = (3, 3, 9, 3)
    dims: Sequence[int] = (96, 192, 384, 768)
    num_classes: int = 1000
    drop_path_rate: float = 0.0
    layer_scale_init: float = 1e-6
    dtype: Any = jnp.bfloat16
    gelu_exact: bool = False  # torchvision/official-checkpoint compat
    # rematerialize each block in the backward pass (activation memory
    # O(1 block) for ~1 extra forward of FLOPs)
    remat: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = jnp.asarray(x, self.dtype)
        x = nn.Conv(
            self.dims[0], (4, 4), strides=(4, 4), dtype=self.dtype, name="stem"
        )(x)
        x = nn.LayerNorm(dtype=self.dtype, name="stem_norm")(x)
        total = sum(self.depths)
        block_cls = maybe_remat(ConvNeXtBlock, self.remat, train_argnum=2)
        block = 0
        for stage, (depth, dim) in enumerate(zip(self.depths, self.dims)):
            if stage > 0:
                x = Downsample(dim, dtype=self.dtype, name=f"down{stage}")(x)
            for _ in range(depth):
                # linearly increasing drop-path rate, as in the paper
                dp = self.drop_path_rate * block / max(total - 1, 1)
                x = block_cls(
                    dim, drop_path=dp, layer_scale_init=self.layer_scale_init,
                    dtype=self.dtype, gelu_exact=self.gelu_exact,
                    name=f"block{block}",
                )(x, train)
                block += 1
        x = x.mean(axis=(1, 2))
        x = nn.LayerNorm(dtype=jnp.float32, name="head_norm")(x)
        x = nn.Dense(self.num_classes, dtype=jnp.float32, name="head")(x)
        return x.astype(jnp.float32)


def _convnext(kw: dict, **defaults) -> ConvNeXt:
    for key, val in defaults.items():
        kw.setdefault(key, val)
    return ConvNeXt(**kw)


def convnext_test(num_classes: int = 10, **kw) -> ConvNeXt:
    """Tiny config for tests/dryruns (not a published variant)."""
    return _convnext(kw, depths=(1, 1, 2, 1), dims=(16, 32, 64, 128),
                     num_classes=num_classes)


def convnext_tiny(num_classes: int = 1000, **kw) -> ConvNeXt:
    return _convnext(kw, depths=(3, 3, 9, 3), dims=(96, 192, 384, 768),
                     num_classes=num_classes)


def convnext_small(num_classes: int = 1000, **kw) -> ConvNeXt:
    return _convnext(kw, depths=(3, 3, 27, 3), dims=(96, 192, 384, 768),
                     num_classes=num_classes)


def convnext_base(num_classes: int = 1000, **kw) -> ConvNeXt:
    return _convnext(kw, depths=(3, 3, 27, 3), dims=(128, 256, 512, 1024),
                     num_classes=num_classes)


def convnext_large(num_classes: int = 1000, **kw) -> ConvNeXt:
    return _convnext(kw, depths=(3, 3, 27, 3), dims=(192, 384, 768, 1536),
                     num_classes=num_classes)


def convnext_xlarge(num_classes: int = 1000, **kw) -> ConvNeXt:
    """The BASELINE 'ConvNeXt-XL large-batch (LARS)' config's model."""
    return _convnext(kw, depths=(3, 3, 27, 3), dims=(256, 512, 1024, 2048),
                     num_classes=num_classes)
