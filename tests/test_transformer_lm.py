"""Decoder-only LM: causality, learning, and parallelism composition.

The model exists to exercise the long-context machinery on a real
sequence axis, so the tests cover exactly that: the causal invariant
(future tokens cannot influence past logits), genuine learning on the
Markov synthetic task (loss falls far below the uniform ln(V) floor),
ring-attention sequence parallelism matching the dense-attention model,
and FSDP compiling/stepping the same loss unchanged.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# tier-2 (slow): 34 full-model LM tests (~7 min of compiles) — the
# tier-1 iteration loop must fit the 870s verify window (ROADMAP);
# CI's slow job still runs this file, and tier-1 keeps the LM decode/
# generate parity surface via tests/test_serve_engine.py
pytestmark = pytest.mark.slow

from fluxdistributed_tpu import optim, sharding
from fluxdistributed_tpu.data import SyntheticTextDataset
from fluxdistributed_tpu.models import lm_loss_fn, lm_tiny
from fluxdistributed_tpu.models.transformer_lm import next_token_loss, rope
from fluxdistributed_tpu.parallel import (
    TrainState,
    fsdp,
    fsdp_specs,
    make_train_step,
    make_train_step_fsdp,
)

VOCAB = 32


@pytest.fixture(scope="module")
def model_and_params():
    model = lm_tiny(vocab=VOCAB, dtype=jnp.float32)
    toks = np.zeros((2, 16), np.int32)
    params = model.init(jax.random.PRNGKey(0), toks, train=False)["params"]
    return model, params


def test_causality(model_and_params):
    """Perturbing token t must not change logits at positions < t."""
    model, params = model_and_params
    rng = np.random.default_rng(0)
    toks = rng.integers(0, VOCAB, (1, 16)).astype(np.int32)
    base = model.apply({"params": params}, toks, train=False)
    t = 9
    toks2 = toks.copy()
    toks2[0, t] = (toks2[0, t] + 7) % VOCAB
    pert = model.apply({"params": params}, toks2, train=False)
    np.testing.assert_allclose(
        np.asarray(base[0, :t]), np.asarray(pert[0, :t]), rtol=1e-5, atol=1e-5
    )
    # and it MUST change something at/after t (the model isn't ignoring input)
    assert not np.allclose(np.asarray(base[0, t:]), np.asarray(pert[0, t:]))


def test_rope_relative():
    """RoPE scores depend only on relative distance: shifting all
    positions by a constant leaves q·k scores unchanged."""
    rng = jax.random.PRNGKey(1)
    q = jax.random.normal(rng, (1, 8, 2, 16))
    k = jax.random.normal(jax.random.fold_in(rng, 1), (1, 8, 2, 16))
    pos = jnp.arange(8)
    s0 = jnp.einsum(
        "bqhd,bkhd->bhqk", rope(q, pos), rope(k, pos)
    )
    s1 = jnp.einsum(
        "bqhd,bkhd->bhqk", rope(q, pos + 100), rope(k, pos + 100)
    )
    np.testing.assert_allclose(np.asarray(s0), np.asarray(s1), rtol=1e-4, atol=1e-4)


def test_next_token_loss_mask():
    logits = jnp.zeros((2, 5, VOCAB))
    toks = jnp.zeros((2, 5), jnp.int32)
    # uniform logits -> loss == ln(V) regardless of mask
    full = next_token_loss(logits, toks)
    np.testing.assert_allclose(float(full), np.log(VOCAB), rtol=1e-6)
    mask = jnp.asarray([[True] * 5, [False] * 5])
    np.testing.assert_allclose(
        float(next_token_loss(logits, toks, mask)), np.log(VOCAB), rtol=1e-6
    )


def test_lm_learns_markov():
    """DP training on the Markov chain: loss must fall well below the
    uniform floor ln(V) — evidence of learning the transition table."""
    import fluxdistributed_tpu.mesh as mesh_lib

    mesh = mesh_lib.data_mesh(8)
    model = lm_tiny(vocab=VOCAB, dtype=jnp.float32)
    ds = SyntheticTextDataset(vocab=VOCAB, seqlen=32, peak=0.9)
    rng = np.random.default_rng(0)
    params = model.init(jax.random.PRNGKey(0), ds.batch(rng, 2), train=False)["params"]
    opt = optim.adam(3e-3)
    state = TrainState.create(sharding.replicate(params, mesh), opt)
    step = make_train_step(lm_loss_fn(model), opt, mesh, donate=False)
    first = last = None
    for i in range(60):
        b = sharding.shard_batch({"tokens": ds.batch(rng, 32)}, mesh)
        state, m = step(state, b)
        if i == 0:
            first = float(m["loss"])
        last = float(m["loss"])
    assert first == pytest.approx(np.log(VOCAB), rel=0.15)
    # peak=0.9 chain entropy ~= 0.69 nats; reaching <1.6 from 3.47 means
    # the transition structure (not just unigram stats) was learned
    assert last < 1.6, (first, last)


def test_ring_attention_lm_matches_dense():
    """The SAME weights under attn_fn=ring attention (seq-sharded mesh)
    must reproduce the dense-attention model's logits."""
    from fluxdistributed_tpu.mesh import make_mesh
    from fluxdistributed_tpu.parallel import make_ring_attention

    mesh = make_mesh({"seq": 8})
    dense = lm_tiny(vocab=VOCAB, dtype=jnp.float32)
    toks = np.random.default_rng(2).integers(0, VOCAB, (2, 32)).astype(np.int32)
    params = dense.init(jax.random.PRNGKey(0), toks, train=False)["params"]
    ring = lm_tiny(
        vocab=VOCAB, dtype=jnp.float32,
        attn_fn=make_ring_attention(mesh, causal=True),
    )
    out_d = dense.apply({"params": params}, toks, train=False)
    out_r = jax.jit(
        lambda p, t: ring.apply({"params": p}, t, train=False)
    )(params, toks)
    np.testing.assert_allclose(
        np.asarray(out_d), np.asarray(out_r), rtol=2e-4, atol=2e-4
    )


def test_decode_cache_matches_full_forward(model_and_params):
    """Step-by-step KV-cache decoding must reproduce the full-sequence
    forward logits (same params, same tokens)."""
    from fluxdistributed_tpu.models.transformer_lm import TransformerLM

    model, params = model_and_params
    dm = lm_tiny(vocab=VOCAB, dtype=jnp.float32, decode=True)
    toks = np.random.default_rng(5).integers(0, VOCAB, (2, 12)).astype(np.int32)
    full = model.apply({"params": params}, toks, train=False)

    cache = dm.init(jax.random.PRNGKey(0), jnp.zeros_like(toks), train=False)["cache"]
    got = []
    for t in range(toks.shape[1]):
        logits, mut = dm.apply(
            {"params": params, "cache": cache}, toks[:, t : t + 1],
            train=False, mutable=["cache"],
        )
        cache = mut["cache"]
        got.append(np.asarray(logits[:, 0]))
    got = np.stack(got, axis=1)
    np.testing.assert_allclose(np.asarray(full), got, rtol=2e-4, atol=2e-4)

    # batched prefill (first 7 tokens in ONE pass) + single-token steps
    cache = dm.init(jax.random.PRNGKey(0), jnp.zeros_like(toks), train=False)["cache"]
    pre, mut = dm.apply(
        {"params": params, "cache": cache}, toks[:, :7],
        train=False, mutable=["cache"],
    )
    cache = mut["cache"]
    got2 = [np.asarray(pre)]
    for t in range(7, toks.shape[1]):
        logits, mut = dm.apply(
            {"params": params, "cache": cache}, toks[:, t : t + 1],
            train=False, mutable=["cache"],
        )
        cache = mut["cache"]
        got2.append(np.asarray(logits))
    got2 = np.concatenate(got2, axis=1)
    np.testing.assert_allclose(np.asarray(full), got2, rtol=2e-4, atol=2e-4)


def test_generate_follows_markov_chain():
    """Train on the chain, then generate greedily: every sampled
    transition must be the chain's high-probability successor."""
    import fluxdistributed_tpu.mesh as mesh_lib
    from fluxdistributed_tpu.models import generate

    mesh = mesh_lib.data_mesh(8)
    model = lm_tiny(vocab=VOCAB, dtype=jnp.float32)
    ds = SyntheticTextDataset(vocab=VOCAB, seqlen=32, peak=0.95)
    rng = np.random.default_rng(0)
    params = model.init(jax.random.PRNGKey(0), ds.batch(rng, 2), train=False)["params"]
    opt = optim.adam(3e-3)
    state = TrainState.create(sharding.replicate(params, mesh), opt)
    step = make_train_step(lm_loss_fn(model), opt, mesh, donate=False)
    for _ in range(60):
        b = sharding.shard_batch({"tokens": ds.batch(rng, 32)}, mesh)
        state, _ = step(state, b)

    host_params = jax.tree.map(
        lambda x: np.asarray(x.addressable_shards[0].data), state.params
    )
    dm = lm_tiny(vocab=VOCAB, dtype=jnp.float32, decode=True)
    prompt = np.asarray([[3], [17]], np.int32)
    out = np.asarray(generate(dm, host_params, prompt, total_len=12))
    succ = np.argmax(ds.transition, axis=1)
    for row in out:
        for a, b_ in zip(row[:-1], row[1:]):
            assert b_ == succ[a], (row, succ[a], a, b_)


def test_generate_top_k_top_p():
    """top_k=1 at any temperature is greedy; top_p near 0 likewise; bad
    filter configs are rejected."""
    from fluxdistributed_tpu.models import generate

    dm = lm_tiny(vocab=VOCAB, dtype=jnp.float32, decode=True)
    params = lm_tiny(vocab=VOCAB, dtype=jnp.float32).init(
        jax.random.PRNGKey(0), np.zeros((1, 2), np.int32), train=False
    )["params"]
    prompt = np.asarray([[3, 7]], np.int32)
    greedy = np.asarray(generate(dm, params, prompt, 10))
    k1 = np.asarray(generate(
        dm, params, prompt, 10, temperature=1.5, top_k=1,
        rng=jax.random.PRNGKey(0),
    ))
    np.testing.assert_array_equal(greedy, k1)
    p_tiny = np.asarray(generate(
        dm, params, prompt, 10, temperature=1.5, top_p=1e-6,
        rng=jax.random.PRNGKey(1),
    ))
    np.testing.assert_array_equal(greedy, p_tiny)
    # top_k >= vocab keeps everything == plain sampling
    plain = np.asarray(generate(
        dm, params, prompt, 10, temperature=1.0, rng=jax.random.PRNGKey(2),
    ))
    k_all = np.asarray(generate(
        dm, params, prompt, 10, temperature=1.0, top_k=10 * VOCAB,
        rng=jax.random.PRNGKey(2),
    ))
    np.testing.assert_array_equal(plain, k_all)
    # filters without sampling make no sense
    with pytest.raises(ValueError, match="temperature"):
        generate(dm, params, prompt, 10, top_k=5)
    with pytest.raises(ValueError, match="top_p"):
        generate(dm, params, prompt, 10, temperature=1.0, top_p=0.0,
                 rng=jax.random.PRNGKey(0))


def test_generate_rejects_bad_config(model_and_params):
    from fluxdistributed_tpu.models import generate

    model, params = model_and_params  # decode=False
    with pytest.raises(ValueError, match="decode=True"):
        generate(model, params, np.zeros((1, 1), np.int32), 4)


def test_decode_rejects_custom_attn_fn():
    """The KV-cache path always uses the dense attention core; a custom
    attn_fn (e.g. ring attention) must fail loudly, not be dropped."""
    from fluxdistributed_tpu.models.transformer_lm import CausalSelfAttention

    attn = CausalSelfAttention(
        num_heads=2, dtype=jnp.float32, decode=True,
        attn_fn=lambda q, k, v: v,
    )
    with pytest.raises(ValueError, match="attn_fn"):
        attn.init(jax.random.PRNGKey(0), jnp.zeros((1, 4, 8), jnp.float32))


def test_lm_through_trainer():
    """The full user path for LM training: SyntheticTextDataset →
    PrefetchLoader (token protocol) → prepare_training(loss_fn=...) →
    train, with val eval, and the loss falls."""
    import fluxdistributed_tpu.mesh as mesh_lib
    from fluxdistributed_tpu.train import prepare_training, train
    from fluxdistributed_tpu.train.logging import NullLogger

    mesh = mesh_lib.data_mesh(8)
    model = lm_tiny(vocab=VOCAB, dtype=jnp.float32)
    ds = SyntheticTextDataset(vocab=VOCAB, seqlen=32, peak=0.9)

    class Rec(NullLogger):
        def __init__(self):
            self.metrics = []

        def log(self, m, step):
            self.metrics.append(m)

    logger = Rec()
    task = prepare_training(
        model, ds, optim.adam(3e-3),
        mesh=mesh, batch_size=64, cycles=40, loss_fn=lm_loss_fn(model),
        # same seed = same chain; batch() draws fresh sequences, so this
        # is held-out data from the SAME distribution (a different seed
        # would be a different transition table entirely)
        val_dataset=SyntheticTextDataset(vocab=VOCAB, seqlen=32, peak=0.9),
        val_samples=32, topk=(),
    )
    train(task, print_every=0, eval_every=20, topk=(), logger=logger)
    vals = [m["val_loss"] for m in logger.metrics if "val_loss" in m]
    assert len(vals) >= 2 and vals[-1] < vals[0], vals


def test_ulysses_attention_lm_matches_dense():
    """Same weights under attn_fn=Ulysses (all-to-all) sequence
    parallelism: logits match the dense model (4-way seq mesh; heads=4
    divisible by the axis)."""
    from fluxdistributed_tpu.mesh import make_mesh
    from fluxdistributed_tpu.parallel import make_ulysses_attention

    mesh = make_mesh({"seq": 4})
    dense = lm_tiny(vocab=VOCAB, dtype=jnp.float32)
    toks = np.random.default_rng(4).integers(0, VOCAB, (2, 32)).astype(np.int32)
    params = dense.init(jax.random.PRNGKey(0), toks, train=False)["params"]
    uly = lm_tiny(
        vocab=VOCAB, dtype=jnp.float32,
        attn_fn=make_ulysses_attention(mesh, causal=True),
    )
    out_d = dense.apply({"params": params}, toks, train=False)
    out_u = jax.jit(
        lambda p, t: uly.apply({"params": p}, t, train=False)
    )(params, toks)
    np.testing.assert_allclose(
        np.asarray(out_d), np.asarray(out_u), rtol=2e-4, atol=2e-4
    )


def test_lm_tensor_parallel_matches_dp():
    """Megatron-sharded LM over a (data=2, model=4) mesh: same initial
    params, same batch → same loss/params trajectory as replicated DP."""
    import fluxdistributed_tpu.mesh as mesh_lib
    from fluxdistributed_tpu.parallel import lm_tp_rules, make_train_step_tp
    from fluxdistributed_tpu.parallel.tp import param_specs, shard_state

    model = lm_tiny(vocab=VOCAB, dtype=jnp.float32)  # heads=4, mlp=512, vocab 32
    toks = np.random.default_rng(7).integers(0, VOCAB, (16, 24)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), toks[:2], train=False)["params"]
    opt = optim.momentum(0.05, 0.9)
    loss_fn = lm_loss_fn(model)

    dp_mesh = mesh_lib.data_mesh(8)
    dp_state = TrainState.create(sharding.replicate(params, dp_mesh), opt)
    dp_step = make_train_step(loss_fn, opt, dp_mesh, donate=False)
    b_dp = sharding.shard_batch({"tokens": toks}, dp_mesh)

    tp_mesh = mesh_lib.make_mesh({"data": 2, "model": 4})
    specs = param_specs(params, lm_tp_rules())
    # the vocab table must actually be sharded (rule fired)
    from jax.sharding import PartitionSpec as P
    assert specs["embed"]["embedding"] == P("model", None)
    tp_state = shard_state(TrainState.create(params, opt), tp_mesh, specs)
    tp_step = make_train_step_tp(loss_fn, opt, tp_mesh, specs, tp_state, donate=False)
    b_tp = sharding.shard_batch({"tokens": toks}, tp_mesh)

    for _ in range(3):
        dp_state, dp_m = dp_step(dp_state, b_dp)
        tp_state, tp_m = tp_step(tp_state, b_tp)
        np.testing.assert_allclose(
            float(dp_m["loss"]), float(tp_m["loss"]), rtol=1e-5
        )
    for (pa, a), (_, bb) in zip(
        jax.tree_util.tree_leaves_with_path(dp_state.params),
        jax.tree_util.tree_leaves_with_path(tp_state.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(bb), rtol=2e-4, atol=1e-5,
            err_msg=f"param mismatch at {jax.tree_util.keystr(pa)}",
        )


def test_lm_tp_untied_head_specs_and_step():
    """The untied-head + shard_vocab=False branches: specs are rank-valid
    and one compiled TP step runs (loss matches an unsharded forward)."""
    import fluxdistributed_tpu.mesh as mesh_lib
    from jax.sharding import PartitionSpec as P
    from fluxdistributed_tpu.parallel import lm_tp_rules, make_train_step_tp
    from fluxdistributed_tpu.parallel.tp import param_specs, shard_state

    model = lm_tiny(vocab=VOCAB, dtype=jnp.float32, tie_embeddings=False)
    toks = np.random.default_rng(8).integers(0, VOCAB, (8, 16)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), toks[:2], train=False)["params"]
    specs = param_specs(params, lm_tp_rules(shard_vocab=False))
    assert specs["embed"]["embedding"] == P()
    assert specs["head"]["kernel"] == P(None, "model")
    assert specs["head"]["bias"] == P("model")

    tp_mesh = mesh_lib.make_mesh({"data": 2, "model": 4})
    opt = optim.momentum(0.05, 0.9)
    loss_fn = lm_loss_fn(model)
    st = shard_state(TrainState.create(params, opt), tp_mesh, specs)
    step = make_train_step_tp(loss_fn, opt, tp_mesh, specs, st, donate=False)
    st, m = step(st, sharding.shard_batch({"tokens": toks}, tp_mesh))
    ref, _ = loss_fn(params, {}, {"tokens": toks}, True)
    np.testing.assert_allclose(float(m["loss"]), float(ref), rtol=1e-5)


def test_lm_pipeline_matches_dense():
    """Blocks as GPipe stages on a (data=2, pipe=4) mesh: forward loss
    matches the dense model, and a short momentum trajectory matches
    replicated DP training."""
    import fluxdistributed_tpu.mesh as mesh_lib
    from fluxdistributed_tpu.models import lm_pp

    model = lm_tiny(vocab=VOCAB, dtype=jnp.float32)  # depth 4
    toks = np.random.default_rng(9).integers(0, VOCAB, (16, 24)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), toks[:2], train=False)["params"]
    opt = optim.momentum(0.05, 0.9)

    mesh = mesh_lib.make_mesh({"data": 2, "pipe": 4})
    split, pp_loss_fn, shardings_fn = lm_pp(
        model, mesh, batch_axis="data", num_microbatches=4
    )

    # forward-loss parity vs the dense model
    dense_loss, _ = lm_loss_fn(model)(params, {}, {"tokens": toks}, False)
    pp_loss, _ = jax.jit(
        lambda p, b: pp_loss_fn(p, {}, b, False)
    )(split(params), {"tokens": toks})
    np.testing.assert_allclose(float(dense_loss), float(pp_loss), rtol=1e-5)

    # training-trajectory parity vs replicated DP
    dp_mesh = mesh_lib.data_mesh(8)
    dp_state = TrainState.create(sharding.replicate(params, dp_mesh), opt)
    dp_step = make_train_step(lm_loss_fn(model), opt, dp_mesh, donate=False)
    b_dp = sharding.shard_batch({"tokens": toks}, dp_mesh)

    pp_state = TrainState.create(split(params), opt)
    sh = shardings_fn(pp_state)
    pp_state = jax.tree.map(jax.device_put, pp_state, sh)
    pp_step = make_train_step(
        pp_loss_fn, opt, mesh, axis="data", donate=False, state_shardings=sh
    )
    b_pp = sharding.shard_batch({"tokens": toks}, mesh, axis="data")

    for _ in range(3):
        dp_state, dp_m = dp_step(dp_state, b_dp)
        pp_state, pp_m = pp_step(pp_state, b_pp)
        np.testing.assert_allclose(
            float(dp_m["loss"]), float(pp_m["loss"]), rtol=1e-5
        )


def test_lm_tp_through_trainer():
    """prepare_training(spmd='tp') on a (data=2, model=4) mesh: state is
    model-sharded, training runs, eval works, loss falls."""
    import fluxdistributed_tpu.mesh as mesh_lib
    from fluxdistributed_tpu.train import prepare_training, train
    from fluxdistributed_tpu.train.logging import NullLogger

    mesh = mesh_lib.make_mesh({"data": 2, "model": 4})
    model = lm_tiny(vocab=VOCAB, dtype=jnp.float32)
    ds = SyntheticTextDataset(vocab=VOCAB, seqlen=32, peak=0.9)
    task = prepare_training(
        model, ds, optim.adam(3e-3), mesh=mesh, batch_size=32, cycles=30,
        loss_fn=lm_loss_fn(model), topk=(), spmd="tp",
        val_dataset=SyntheticTextDataset(vocab=VOCAB, seqlen=32, peak=0.9),
        val_samples=16,
    )
    emb = task.state.params["embed"]["embedding"]
    assert emb.addressable_shards[0].data.shape[0] == emb.shape[0] // 4
    losses = []
    orig = task.step_fn

    def rec(state, batch):
        out = orig(state, batch)
        losses.append(float(out[1]["loss"]))
        return out

    task.step_fn = rec
    train(task, print_every=0, eval_every=15, topk=(), logger=NullLogger())
    assert losses[-1] < losses[0]


def test_trainer_tp_rejects_cnn():
    import fluxdistributed_tpu.mesh as mesh_lib
    from fluxdistributed_tpu.data import SyntheticDataset
    from fluxdistributed_tpu.models import SimpleCNN
    from fluxdistributed_tpu.train import prepare_training

    mesh = mesh_lib.make_mesh({"data": 2, "model": 4})
    with pytest.raises(ValueError, match="no TP sharding rules"):
        prepare_training(
            SimpleCNN(num_classes=4),
            SyntheticDataset(nsamples=32, nclasses=4, shape=(8, 8, 3)),
            optim.momentum(0.1, 0.9), mesh=mesh, batch_size=16, cycles=1,
            spmd="tp",
        )


def test_moe_lm_trains_on_expert_mesh():
    """MoE LM (every 2nd block routed, 8 experts on an 8-way expert
    mesh): expert params shard, the aux loss reaches the objective, and
    training learns the Markov chain."""
    from fluxdistributed_tpu.mesh import make_mesh
    from fluxdistributed_tpu.models import lm_moe_specs, moe_expert_fn
    from fluxdistributed_tpu.parallel.ep import moe_apply
    from fluxdistributed_tpu.parallel.tp import state_specs
    from fluxdistributed_tpu.sharding import make_shardings

    mesh = make_mesh({"expert": 8})
    moe_fn = moe_apply(moe_expert_fn, mesh, capacity_factor=2.0)
    model = lm_tiny(
        vocab=VOCAB, dtype=jnp.float32,
        moe_every=2, num_experts=8, moe_fn=moe_fn,
    )
    ds = SyntheticTextDataset(vocab=VOCAB, seqlen=32, peak=0.9)
    rng = np.random.default_rng(0)
    params = model.init(jax.random.PRNGKey(0), ds.batch(rng, 2), train=False)["params"]
    assert "router" in params["block1"] and "w1" in params["block1"]
    assert "router" not in params["block0"]  # dense block

    opt = optim.adam(3e-3)
    state = TrainState.create(params, opt)
    specs = lm_moe_specs(params)
    from jax.sharding import PartitionSpec as P
    assert specs["block1"]["w1"] == P("expert", None, None)
    assert specs["block1"]["router"] == P()
    sh = make_shardings(state_specs(state, specs), mesh)
    state = jax.tree.map(jax.device_put, state, sh)
    # batch replicated on the pure expert mesh (axis=None); the MoE
    # shard_map does its own token split
    step = make_train_step(
        lm_loss_fn(model), opt, mesh, axis=None, donate=False, state_shardings=sh
    )
    w1 = state.params["block1"]["w1"]
    assert w1.addressable_shards[0].data.shape[0] == 1  # 1 of 8 experts
    first = last = None
    for i in range(60):
        b = {"tokens": jnp.asarray(ds.batch(rng, 32))}
        state, m = step(state, b)
        if i == 0:
            first = float(m["loss"])
        last = float(m["loss"])
    # loss includes the small aux term; the Markov floor is ~0.67
    assert np.isfinite(first) and last < 1.8, (first, last)


def test_moe_lm_decode_matches_full_forward():
    """KV-cache decoding of an MoE LM reproduces the full forward logits
    (capacity set explicitly so per-step routing never drops tokens)."""
    from fluxdistributed_tpu.mesh import make_mesh
    from fluxdistributed_tpu.models import moe_expert_fn
    from fluxdistributed_tpu.parallel.ep import moe_apply

    mesh = make_mesh({"expert": 8})
    moe_fn = moe_apply(moe_expert_fn, mesh, capacity=64, pad_tokens=True)
    kw = dict(
        vocab=VOCAB, dtype=jnp.float32, moe_every=2, num_experts=8, moe_fn=moe_fn,
    )
    full_model = lm_tiny(**kw)
    dm = lm_tiny(**kw, decode=True)
    toks = np.random.default_rng(13).integers(0, VOCAB, (2, 12)).astype(np.int32)
    params = full_model.init(jax.random.PRNGKey(0), toks, train=False)["params"]
    full = full_model.apply({"params": params}, toks, train=False)

    cache = dm.init(jax.random.PRNGKey(0), jnp.zeros_like(toks), train=False)["cache"]
    got = []
    for t in range(toks.shape[1]):
        logits, mut = dm.apply(
            {"params": params, "cache": cache}, toks[:, t : t + 1],
            train=False, mutable=["cache"],
        )
        cache = mut["cache"]
        got.append(np.asarray(logits[:, 0]))
    np.testing.assert_allclose(
        np.asarray(full), np.stack(got, axis=1), rtol=2e-4, atol=2e-4
    )


def test_moe_lm_dp_ep_mesh():
    """dp x ep composition: (data=2, expert=4) mesh, batch sharded over
    data, 8 experts (2 local per device); training learns the chain."""
    from fluxdistributed_tpu.mesh import make_mesh
    from fluxdistributed_tpu.models import lm_moe_specs, moe_expert_fn
    from fluxdistributed_tpu.parallel.ep import moe_apply
    from fluxdistributed_tpu.parallel.tp import state_specs
    from fluxdistributed_tpu.sharding import make_shardings

    mesh = make_mesh({"data": 2, "expert": 4})
    moe_fn = moe_apply(
        moe_expert_fn, mesh, capacity_factor=2.0, batch_axis="data"
    )
    model = lm_tiny(
        vocab=VOCAB, dtype=jnp.float32,
        moe_every=2, num_experts=8, moe_fn=moe_fn,
    )
    ds = SyntheticTextDataset(vocab=VOCAB, seqlen=32, peak=0.9)
    rng = np.random.default_rng(0)
    params = model.init(jax.random.PRNGKey(0), ds.batch(rng, 2), train=False)["params"]
    opt = optim.adam(3e-3)
    state = TrainState.create(params, opt)
    sh = make_shardings(state_specs(state, lm_moe_specs(params)), mesh)
    state = jax.tree.map(jax.device_put, state, sh)
    step = make_train_step(
        lm_loss_fn(model), opt, mesh, axis="data", donate=False,
        state_shardings=sh,
    )
    last = None
    for i in range(60):
        b = sharding.shard_batch({"tokens": ds.batch(rng, 32)}, mesh, axis="data")
        state, m = step(state, b)
        last = float(m["loss"])
    assert last < 1.8, last


def test_lm_pipeline_chunked_stages():
    """depth=4 on a pipe=2 mesh: two blocks per device (blocked virtual
    pipeline); forward loss matches the dense model."""
    import fluxdistributed_tpu.mesh as mesh_lib
    from fluxdistributed_tpu.models import lm_pp

    model = lm_tiny(vocab=VOCAB, dtype=jnp.float32)  # depth 4
    toks = np.random.default_rng(15).integers(0, VOCAB, (8, 16)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), toks[:2], train=False)["params"]

    mesh = mesh_lib.make_mesh({"data": 4, "pipe": 2})
    split, pp_loss_fn, shardings_fn = lm_pp(
        model, mesh, batch_axis="data", num_microbatches=2
    )
    sp = split(params)
    qkv = sp["stages"]["CausalSelfAttention_0"]["qkv"]["kernel"]
    assert qkv.shape[:2] == (2, 2)  # (S, V) leading dims

    dense_loss, _ = lm_loss_fn(model)(params, {}, {"tokens": toks}, False)
    pp_loss, _ = jax.jit(lambda p, b: pp_loss_fn(p, {}, b, False))(
        sp, {"tokens": toks}
    )
    np.testing.assert_allclose(float(dense_loss), float(pp_loss), rtol=1e-5)


def test_lm_fsdp_step():
    """FSDP shards the LM state (embedding table is the biggest leaf)
    and the compiled step runs the same lm loss unchanged."""
    import fluxdistributed_tpu.mesh as mesh_lib

    mesh = mesh_lib.data_mesh(8)
    model = lm_tiny(vocab=64, dtype=jnp.float32)
    toks = np.random.default_rng(3).integers(0, 64, (16, 32)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), toks[:2], train=False)["params"]
    opt = optim.adam(1e-3)
    state = TrainState.create(params, opt)
    specs = fsdp_specs(state, mesh)
    state = fsdp.shard_state(state, specs, mesh)
    step = make_train_step_fsdp(lm_loss_fn(model), opt, mesh, specs, donate=False)
    b = sharding.shard_batch({"tokens": toks}, mesh)
    n = mesh.shape["data"]
    emb = state.params["embed"]["embedding"]
    assert emb.addressable_shards[0].data.size == emb.size // n
    state, m = step(state, b)
    assert np.isfinite(float(m["loss"]))


@pytest.mark.slow
def test_driver_cli_attn_flash_one_flag():
    """--attn flash is a one-flag attention-core swap on the LM trainer:
    the full train step runs through the Pallas kernels (fwd + bwd)."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, os.path.join("bin", "driver.py"),
         "--model", "lm_tiny", "--dataset", "synthetic-text",
         "--vocab", "32", "--seqlen", "32", "--batch-size", "8",
         "--cycles", "2", "--opt", "adam", "--lr", "1e-3",
         "--print-every", "1", "--eval-every", "0",
         "--attn", "flash", "--attn-block", "16",
         "--platform", "cpu", "--local-devices", "8"],
        capture_output=True, text=True, timeout=600, cwd=repo, env=env,
    )
    assert out.returncode == 0, out.stderr[-3000:]
    assert "done: 2 steps" in out.stdout, out.stdout[-2000:]


def test_driver_cli_attn_rejects_sp_combo():
    """--attn + --spmd sp is ambiguous (sp owns the attention core)."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, os.path.join("bin", "driver.py"),
         "--model", "lm_tiny", "--dataset", "synthetic-text",
         "--seqlen", "32", "--batch-size", "8", "--cycles", "1",
         "--attn", "flash", "--spmd", "sp",
         "--platform", "cpu", "--local-devices", "8"],
        capture_output=True, text=True, timeout=300, cwd=repo, env=env,
    )
    assert out.returncode != 0
    assert "conflicts with --spmd sp" in out.stderr, out.stderr[-2000:]


def test_gqa_lm_trains_and_decodes():
    """num_kv_heads < num_heads: separate q/kv projections, grouped KV
    cache (memory / group), and decode logits == full forward."""
    gm = lm_tiny(vocab=VOCAB, dtype=jnp.float32, num_kv_heads=2)
    toks = np.random.default_rng(7).integers(0, VOCAB, (2, 12)).astype(np.int32)
    variables = gm.init(jax.random.PRNGKey(0), toks, train=False)
    params = variables["params"]
    # grouped projections exist and the fused qkv does not
    attn0 = params["block0"]["CausalSelfAttention_0"]
    assert "kv" in attn0 and "q" in attn0 and "qkv" not in attn0
    assert attn0["kv"]["kernel"].shape[-2] == 2  # hkv heads

    # grads flow through the grouped path
    def loss(p):
        return (gm.apply({"params": p}, toks, train=False) ** 2).mean()

    g = jax.grad(loss)(params)
    assert all(np.isfinite(np.asarray(x)).all() for x in jax.tree.leaves(g))

    # decode cache holds hkv heads and reproduces the full forward
    dm = lm_tiny(vocab=VOCAB, dtype=jnp.float32, num_kv_heads=2, decode=True)
    full = gm.apply({"params": params}, toks, train=False)
    cache = dm.init(jax.random.PRNGKey(0), jnp.zeros_like(toks), train=False)["cache"]
    ck = cache["block0"]["CausalSelfAttention_0"]["cached_k"]
    assert ck.shape[2] == 2  # the GQA memory win: hkv not num_heads
    got = []
    for t in range(toks.shape[1]):
        logits, mut = dm.apply(
            {"params": params, "cache": cache}, toks[:, t : t + 1],
            train=False, mutable=["cache"],
        )
        cache = mut["cache"]
        got.append(np.asarray(logits[:, 0]))
    np.testing.assert_allclose(
        np.asarray(full), np.stack(got, axis=1), rtol=2e-4, atol=2e-4
    )


def test_gqa_lm_with_flash_kernel():
    """GQA LM through the Pallas kernel == GQA LM through the dense core."""
    from functools import partial

    from fluxdistributed_tpu.ops.pallas_attention import flash_attention

    gm = lm_tiny(vocab=VOCAB, dtype=jnp.float32, num_kv_heads=2)
    gf = lm_tiny(
        vocab=VOCAB, dtype=jnp.float32, num_kv_heads=2,
        attn_fn=partial(flash_attention, causal=True, block_q=8, block_k=8),
    )
    toks = np.random.default_rng(9).integers(0, VOCAB, (2, 16)).astype(np.int32)
    variables = gm.init(jax.random.PRNGKey(0), toks, train=False)
    a = gm.apply(variables, toks, train=False)
    b = gf.apply(variables, toks, train=False)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4)


def test_gqa_lm_tensor_parallel_matches_dp():
    """GQA LM under TP: the separate q/kv projections must be head-
    sharded by lm_tp_rules (not silently replicated), and the TP
    trajectory must match replicated DP."""
    import fluxdistributed_tpu.mesh as mesh_lib
    from jax.sharding import PartitionSpec as P
    from fluxdistributed_tpu.parallel import lm_tp_rules, make_train_step_tp
    from fluxdistributed_tpu.parallel.tp import param_specs, shard_state

    # heads=4, kv_heads=2: model axis 2 divides both
    model = lm_tiny(vocab=VOCAB, dtype=jnp.float32, num_kv_heads=2)
    toks = np.random.default_rng(11).integers(0, VOCAB, (16, 24)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), toks[:2], train=False)["params"]
    opt = optim.momentum(0.05, 0.9)
    loss_fn = lm_loss_fn(model)

    dp_mesh = mesh_lib.data_mesh(8)
    dp_state = TrainState.create(sharding.replicate(params, dp_mesh), opt)
    dp_step = make_train_step(loss_fn, opt, dp_mesh, donate=False)
    b_dp = sharding.shard_batch({"tokens": toks}, dp_mesh)

    tp_mesh = mesh_lib.make_mesh({"data": 4, "model": 2})
    specs = param_specs(params, lm_tp_rules())
    attn = specs["block0"]["CausalSelfAttention_0"]
    assert attn["q"]["kernel"] == P(None, "model", None)
    assert attn["kv"]["kernel"] == P(None, None, "model", None)
    tp_state = shard_state(TrainState.create(params, opt), tp_mesh, specs)
    tp_step = make_train_step_tp(loss_fn, opt, tp_mesh, specs, tp_state, donate=False)
    b_tp = sharding.shard_batch({"tokens": toks}, tp_mesh)

    for _ in range(3):
        dp_state, dp_m = dp_step(dp_state, b_dp)
        tp_state, tp_m = tp_step(tp_state, b_tp)
        np.testing.assert_allclose(
            float(dp_m["loss"]), float(tp_m["loss"]), rtol=1e-5
        )


def test_gqa_lm_ring_attention_matches_dense():
    """GQA through ring attention: grouped KV rotates the ring (hkv
    heads of ppermute traffic), output equals the dense GQA forward."""
    from fluxdistributed_tpu.mesh import make_mesh
    from fluxdistributed_tpu.parallel import make_ring_attention

    mesh = make_mesh({"seq": 8})
    dense = lm_tiny(vocab=VOCAB, dtype=jnp.float32, num_kv_heads=2)
    ring = lm_tiny(
        vocab=VOCAB, dtype=jnp.float32, num_kv_heads=2,
        attn_fn=make_ring_attention(mesh, causal=True),
    )
    toks = np.random.default_rng(13).integers(0, VOCAB, (2, 32)).astype(np.int32)
    params = dense.init(jax.random.PRNGKey(0), toks, train=False)["params"]
    a = dense.apply({"params": params}, toks, train=False)
    b = jax.jit(lambda p, t: ring.apply({"params": p}, t, train=False))(params, toks)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4)


def test_windowed_lm_decode_matches_full_forward():
    """window=8 LM: full forward (banded mask) == step-by-step decode
    (windowed cache reads), and windowing actually changes the logits
    vs the unwindowed model."""
    m = lm_tiny(vocab=VOCAB, dtype=jnp.float32, window=8)
    m_full = lm_tiny(vocab=VOCAB, dtype=jnp.float32)
    toks = np.random.default_rng(17).integers(0, VOCAB, (2, 24)).astype(np.int32)
    variables = m.init(jax.random.PRNGKey(0), toks, train=False)
    full = m.apply(variables, toks, train=False)
    unwindowed = m_full.apply(variables, toks, train=False)
    # beyond the window the outputs must differ (the mask is live)
    assert not np.allclose(np.asarray(full[:, -1]), np.asarray(unwindowed[:, -1]))
    # within the first `window` positions they are identical
    np.testing.assert_allclose(
        np.asarray(full[:, :8]), np.asarray(unwindowed[:, :8]),
        rtol=1e-5, atol=1e-5,
    )

    dm = lm_tiny(vocab=VOCAB, dtype=jnp.float32, window=8, decode=True)
    cache = dm.init(jax.random.PRNGKey(0), jnp.zeros_like(toks), train=False)["cache"]
    got = []
    for t in range(toks.shape[1]):
        logits, mut = dm.apply(
            {"params": variables["params"], "cache": cache},
            toks[:, t : t + 1], train=False, mutable=["cache"],
        )
        cache = mut["cache"]
        got.append(np.asarray(logits[:, 0]))
    np.testing.assert_allclose(
        np.asarray(full), np.stack(got, axis=1), rtol=2e-4, atol=2e-4
    )


def test_windowed_lm_flash_matches_dense():
    """Windowed flash kernel through the LM == windowed dense core."""
    from fluxdistributed_tpu.ops import attention_core

    md = lm_tiny(vocab=VOCAB, dtype=jnp.float32, window=8)
    mf = lm_tiny(
        vocab=VOCAB, dtype=jnp.float32, window=8,
        attn_fn=attention_core("flash", 8, window=8),
    )
    toks = np.random.default_rng(19).integers(0, VOCAB, (2, 32)).astype(np.int32)
    variables = md.init(jax.random.PRNGKey(0), toks, train=False)
    a = md.apply(variables, toks, train=False)
    b = mf.apply(variables, toks, train=False)
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4)


def test_windowed_rolling_cache_is_ring_sized():
    """The windowed decode cache holds `window` slots, not T — O(window)
    generation memory — and a prefill longer than the window still
    reproduces the full forward (rolling writes keep only the newest
    window of keys)."""
    W, T = 8, 24
    m = lm_tiny(vocab=VOCAB, dtype=jnp.float32, window=W)
    dm = lm_tiny(vocab=VOCAB, dtype=jnp.float32, window=W, decode=True)
    toks = np.random.default_rng(23).integers(0, VOCAB, (2, T)).astype(np.int32)
    variables = m.init(jax.random.PRNGKey(0), toks, train=False)
    full = m.apply(variables, toks, train=False)

    cache = dm.init(jax.random.PRNGKey(0), jnp.zeros_like(toks), train=False)["cache"]
    attn_cache = cache["block0"]["CausalSelfAttention_0"]
    assert attn_cache["cached_k"].shape[1] == W  # ring, not T
    assert attn_cache["slot_pos"].shape == (W,)

    # prefill 20 tokens (> W) in ONE pass, then single-token steps
    pre, mut = dm.apply(
        {"params": variables["params"], "cache": cache}, toks[:, :20],
        train=False, mutable=["cache"],
    )
    cache = mut["cache"]
    got = [np.asarray(pre)]
    for t in range(20, T):
        logits, mut = dm.apply(
            {"params": variables["params"], "cache": cache},
            toks[:, t : t + 1], train=False, mutable=["cache"],
        )
        cache = mut["cache"]
        got.append(np.asarray(logits))
    np.testing.assert_allclose(
        np.asarray(full), np.concatenate(got, axis=1), rtol=2e-4, atol=2e-4
    )


def test_windowed_generate_short_prompt_matches_decode():
    """generate() with window set and a prompt SHORTER than the window:
    its internally-built cache must mark unwritten ring slots invalid
    (slot_pos = -1), or phantom position-0 keys pollute early steps.
    Greedy generate must equal a hand-rolled argmax decode loop."""
    from fluxdistributed_tpu.models import generate

    W, T = 8, 16
    m = lm_tiny(vocab=VOCAB, dtype=jnp.float32, window=W)
    dm = lm_tiny(vocab=VOCAB, dtype=jnp.float32, window=W, decode=True)
    toks = np.random.default_rng(29).integers(0, VOCAB, (2, 2)).astype(np.int32)
    params = m.init(jax.random.PRNGKey(0), np.zeros((2, T), np.int32),
                    train=False)["params"]

    out = generate(dm, params, jnp.asarray(toks), total_len=T, temperature=0.0)

    # hand-rolled: real init (slot_pos = -1), prefill, greedy steps
    cache = dm.init(jax.random.PRNGKey(0), jnp.zeros((2, T), np.int32),
                    train=False)["cache"]
    logits, mut = dm.apply(
        {"params": params, "cache": cache}, jnp.asarray(toks),
        train=False, mutable=["cache"],
    )
    cache = mut["cache"]
    cur = np.asarray(jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32))
    seq = [toks[:, 0], toks[:, 1], cur]
    for _ in range(T - 3):
        logits, mut = dm.apply(
            {"params": params, "cache": cache}, jnp.asarray(cur[:, None]),
            train=False, mutable=["cache"],
        )
        cache = mut["cache"]
        cur = np.asarray(jnp.argmax(logits[:, 0], axis=-1).astype(jnp.int32))
        seq.append(cur)
    np.testing.assert_array_equal(np.asarray(out), np.stack(seq, axis=1))


def test_rmsnorm_swiglu_lm_learns_and_decodes():
    """Llama-style blocks (rmsnorm + swiglu): learns the Markov chain
    and the decode cache reproduces the full forward."""
    import fluxdistributed_tpu.mesh as mesh_lib

    mesh = mesh_lib.data_mesh(8)
    model = lm_tiny(vocab=VOCAB, dtype=jnp.float32, norm="rmsnorm", mlp="swiglu")
    ds = SyntheticTextDataset(vocab=VOCAB, seqlen=32, peak=0.9)
    rng = np.random.default_rng(0)
    params = model.init(jax.random.PRNGKey(0), ds.batch(rng, 2), train=False)["params"]
    # llama-style param tree: biasless gated MLP, scale-only norms
    blk = params["block0"]
    assert "gate" in blk and "up" in blk and "down" in blk
    assert "bias" not in blk["gate"] and "RMSNorm_0" in blk

    opt = optim.adam(3e-3)
    state = TrainState.create(sharding.replicate(params, mesh), opt)
    step = make_train_step(lm_loss_fn(model), opt, mesh, donate=False)
    first = last = None
    for i in range(60):
        b = sharding.shard_batch({"tokens": ds.batch(rng, 32)}, mesh)
        state, m = step(state, b)
        first = first if first is not None else float(m["loss"])
        last = float(m["loss"])
    assert last < 1.6, (first, last)

    # decode parity with the same block options
    params = jax.tree.map(lambda x: np.asarray(x), state.params)
    dm = lm_tiny(vocab=VOCAB, dtype=jnp.float32, norm="rmsnorm", mlp="swiglu",
                 decode=True)
    toks = np.random.default_rng(31).integers(0, VOCAB, (2, 10)).astype(np.int32)
    full = model.apply({"params": params}, toks, train=False)
    cache = dm.init(jax.random.PRNGKey(0), jnp.zeros_like(toks), train=False)["cache"]
    got = []
    for t in range(toks.shape[1]):
        logits, mut = dm.apply(
            {"params": params, "cache": cache}, toks[:, t : t + 1],
            train=False, mutable=["cache"],
        )
        cache = mut["cache"]
        got.append(np.asarray(logits[:, 0]))
    np.testing.assert_allclose(
        np.asarray(full), np.stack(got, axis=1), rtol=2e-4, atol=2e-4
    )


def test_rmsnorm_swiglu_tp_specs_and_step():
    """SwiGLU projections must be Megatron-paired under lm_tp_rules
    (gate/up column, down row) and the TP step must run."""
    import fluxdistributed_tpu.mesh as mesh_lib
    from jax.sharding import PartitionSpec as P
    from fluxdistributed_tpu.parallel import lm_tp_rules, make_train_step_tp
    from fluxdistributed_tpu.parallel.tp import param_specs, shard_state

    model = lm_tiny(vocab=VOCAB, dtype=jnp.float32, norm="rmsnorm", mlp="swiglu")
    toks = np.random.default_rng(37).integers(0, VOCAB, (8, 16)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), toks[:2], train=False)["params"]
    specs = param_specs(params, lm_tp_rules())
    blk = specs["block0"]
    assert blk["gate"]["kernel"] == P(None, "model")
    assert blk["up"]["kernel"] == P(None, "model")
    assert blk["down"]["kernel"] == P("model", None)

    tp_mesh = mesh_lib.make_mesh({"data": 2, "model": 4})
    opt = optim.adam(1e-3)
    st = shard_state(TrainState.create(params, opt), tp_mesh, specs)
    step = make_train_step_tp(lm_loss_fn(model), opt, tp_mesh, specs, st,
                              donate=False)
    st, m = step(st, sharding.shard_batch({"tokens": toks}, tp_mesh))
    assert int(st.step) == 1 and np.isfinite(float(m["loss"]))


def test_sinks_lm_decode_matches_full_forward():
    """window+sinks LM: pinned sink slots survive ring eviction — decode
    (single-step AND chunked prefill past wraparound) equals the full
    forward, and sinks demonstrably change logits past the window."""
    W, SK, T = 8, 2, 24
    m = lm_tiny(vocab=VOCAB, dtype=jnp.float32, window=W, sinks=SK)
    m_nosink = lm_tiny(vocab=VOCAB, dtype=jnp.float32, window=W)
    dm = lm_tiny(vocab=VOCAB, dtype=jnp.float32, window=W, sinks=SK, decode=True)
    toks = np.random.default_rng(41).integers(0, VOCAB, (2, T)).astype(np.int32)
    variables = m.init(jax.random.PRNGKey(0), toks, train=False)
    full = m.apply(variables, toks, train=False)
    assert not np.allclose(
        np.asarray(full[:, -1]),
        np.asarray(m_nosink.apply(variables, toks, train=False)[:, -1]),
    )

    cache = dm.init(jax.random.PRNGKey(0), jnp.zeros_like(toks), train=False)["cache"]
    assert cache["block0"]["CausalSelfAttention_0"]["cached_k"].shape[1] == W + SK
    got = []
    for t in range(T):
        logits, mut = dm.apply(
            {"params": variables["params"], "cache": cache},
            toks[:, t : t + 1], train=False, mutable=["cache"],
        )
        cache = mut["cache"]
        got.append(np.asarray(logits[:, 0]))
    np.testing.assert_allclose(
        np.asarray(full), np.stack(got, axis=1), rtol=2e-4, atol=2e-4
    )

    # chunked prefill crossing both the sink region and the wrap point
    cache = dm.init(jax.random.PRNGKey(0), jnp.zeros_like(toks), train=False)["cache"]
    pre, mut = dm.apply(
        {"params": variables["params"], "cache": cache}, toks[:, :18],
        train=False, mutable=["cache"],
    )
    cache = mut["cache"]
    got2 = [np.asarray(pre)]
    for t in range(18, T):
        logits, mut = dm.apply(
            {"params": variables["params"], "cache": cache},
            toks[:, t : t + 1], train=False, mutable=["cache"],
        )
        cache = mut["cache"]
        got2.append(np.asarray(logits))
    np.testing.assert_allclose(
        np.asarray(full), np.concatenate(got2, axis=1), rtol=2e-4, atol=2e-4
    )
