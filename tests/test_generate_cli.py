"""bin/generate.py — LM sampling CLI (the LM analog of bin/infer.py)."""

from __future__ import annotations

import pathlib
import sys

import numpy as np
import pytest

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent / "bin"))
import generate as gen_cli  # noqa: E402


def test_token_mode_random_init(capsys):
    rc = gen_cli.main([
        "--model", "lm_tiny", "--vocab", "16",
        "--prompt-tokens", "3,1,4", "--length", "10",
    ])
    assert rc == 0
    out = capsys.readouterr().out.strip()
    toks = [int(t) for t in out.split(",")]
    assert len(toks) == 10 and toks[:3] == [3, 1, 4]
    assert all(0 <= t < 16 for t in toks)


# slow tier: byte-mode rides the same CLI machinery as token mode
# (fast); only the tokenizer wrapper differs
@pytest.mark.slow
def test_byte_mode_roundtrip(tmp_path, capsys):
    """Checkpoint round-trip: params saved by the trainer drive the
    sampler; byte prompt survives into the decoded output."""
    import jax

    from fluxdistributed_tpu.models import lm_tiny
    from fluxdistributed_tpu.parallel import TrainState
    from fluxdistributed_tpu.train import save_checkpoint
    from fluxdistributed_tpu import optim

    model = lm_tiny(vocab=256)
    params = model.init(
        jax.random.PRNGKey(0), np.zeros((1, 2), np.int32), train=False
    )["params"]
    save_checkpoint(TrainState.create(params, optim.descent(0.1)), str(tmp_path), 0)

    rc = gen_cli.main([
        "--model", "lm_tiny", "--checkpoint", str(tmp_path),
        "--prompt", "ab", "--length", "8", "--temperature", "0.5",
    ])
    assert rc == 0
    out = capsys.readouterr().out
    assert out.startswith("ab")


def test_arg_validation():
    with pytest.raises(SystemExit, match="not both"):
        gen_cli.main(["--prompt", "x", "--prompt-tokens", "1"])
    with pytest.raises(SystemExit, match="vocab"):
        gen_cli.main(["--vocab", "16", "--prompt", "x"])
    with pytest.raises(SystemExit, match="in \\[0, 16\\)"):
        gen_cli.main(["--vocab", "16", "--prompt-tokens", "99", "--length", "4"])
    with pytest.raises(SystemExit, match="must be in"):
        gen_cli.main(["--vocab", "16", "--prompt-tokens", "1,2,3", "--length", "2"])


def test_full_length_prompt_is_score_only(capsys):
    """A prompt of exactly --length is accepted (the generate() contract:
    nothing to sample, the prompt comes back unchanged) — not rejected
    by an off-by-one CLI guard."""
    rc = gen_cli.main([
        "--model", "lm_tiny", "--vocab", "16",
        "--prompt-tokens", "3,1,4", "--length", "3",
    ])
    assert rc == 0
    out = capsys.readouterr().out.strip()
    assert [int(t) for t in out.split(",")] == [3, 1, 4]


@pytest.mark.slow  # torch+transformers import plus a JAX CLI subprocess
def test_generate_cli_gpt2_weights(tmp_path):
    """bin/generate.py --gpt2-weights samples from a torch-saved HF
    GPT-2 state_dict, config inferred from the weights, output equal to
    HF's own greedy generate."""
    import os
    import subprocess

    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")

    torch.manual_seed(3)
    cfg = transformers.GPT2Config(
        vocab_size=64, n_positions=16, n_embd=32, n_layer=2, n_head=2,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )
    hm = transformers.GPT2LMHeadModel(cfg).eval()
    pt = tmp_path / "gpt2.pt"
    torch.save(hm.state_dict(), pt)
    with torch.no_grad():
        ref = hm.generate(
            torch.tensor([[3, 1, 4]]), max_length=10, do_sample=False,
            pad_token_id=0,
        )[0]

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    out = subprocess.run(
        [sys.executable, os.path.join("bin", "generate.py"),
         "--gpt2-weights", str(pt), "--gpt2-heads", "2",
         "--prompt-tokens", "3,1,4", "--length", "10", "--platform", "cpu"],
        capture_output=True, text=True, timeout=300, cwd=repo, env=env,
    )
    assert out.returncode == 0, out.stderr[-2000:]
    got = out.stdout.strip().splitlines()[-1]
    assert got == ",".join(str(int(t)) for t in ref), (got, ref)
