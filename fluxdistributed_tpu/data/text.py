"""Byte-level text dataset: train the LM on any local file.

The token-protocol analog of the image datasets (the reference's data
layer is vision-only, src/imagenet.jl — this extends the same registry/
loader machinery to the LM family): a UTF-8/binary file is memory-mapped
and batches are random fixed-length byte windows, vocab = 256.  No
tokenizer dependency — byte-level modeling needs none — and windows are
drawn with replacement, matching the framework's sampling semantics
(``key[rand(1:nrow, n), :]`` src/imagenet.jl:24).
"""

from __future__ import annotations

import os

import numpy as np

__all__ = ["ByteTextDataset"]


class ByteTextDataset:
    """Random ``seqlen``-byte windows over a memory-mapped file.

    Protocol: ``batch(rng, n) -> tokens [n, seqlen] int32`` (the
    PrefetchLoader's bare-array/token protocol); ``len(ds)`` is the
    number of non-overlapping windows, so ``epochs``-based cycle
    derivation works.
    """

    vocab = 256

    def __init__(self, path: str, seqlen: int = 256):
        self.path = os.fspath(path)
        self.seqlen = int(seqlen)
        size = os.path.getsize(self.path)
        if size < self.seqlen:
            raise ValueError(
                f"{self.path}: {size} bytes < seqlen ({self.seqlen}) — "
                "need at least one full window (the next-token shift happens "
                "inside the window, so no extra target byte is required)"
            )
        # mmap: no copy of the corpus per worker thread, OS page cache
        # shared across processes on a host
        self._data = np.memmap(self.path, dtype=np.uint8, mode="r")

    def __len__(self) -> int:
        # non-overlapping full windows; the next-token shift is
        # intra-window, so no trailing target byte is reserved
        return len(self._data) // self.seqlen

    def batch(self, rng: np.random.Generator, n: int, indices=None) -> np.ndarray:
        """Random windows by default; ``indices`` selects the
        NON-OVERLAPPING windows ``indices[i]·seqlen`` (the ``len(self)``
        windows ``__len__`` counts) — the deterministic-coverage protocol
        ``train.evaluate`` uses for exact whole-corpus perplexity."""
        if indices is None:
            # inclusive upper bound: the last valid window start is
            # len - seqlen, so the corpus's final byte is reachable
            starts = rng.integers(0, len(self._data) - self.seqlen + 1, size=n)
        else:
            indices = np.asarray(indices)
            if (indices.max(initial=0) >= len(self)
                    or indices.min(initial=0) < 0):
                raise IndexError(
                    f"window indices must be in [0, {len(self)}); got "
                    f"[{int(indices.min())}, {int(indices.max())}]"
                )
            starts = indices * self.seqlen
        idx = starts[:, None] + np.arange(self.seqlen)[None, :]
        return self._data[idx].astype(np.int32)

    @staticmethod
    def decode(tokens) -> str:
        """Bytes → text (lossy on invalid UTF-8), for eyeballing samples."""
        return bytes(np.asarray(tokens, np.uint8)).decode("utf-8", errors="replace")
