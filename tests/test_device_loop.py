"""Device loop (steps_per_call / chunked dispatch) tests.

K optimizer steps per dispatch must be EXACTLY K separate dispatches:
same sampled data (the loader derives sub-batch rng from the global step
index), same math (lax.scan of the same step), same final state.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import fluxdistributed_tpu as fd
from fluxdistributed_tpu import optim, sharding
from fluxdistributed_tpu.data import PrefetchLoader, SyntheticDataset
from fluxdistributed_tpu.models import SimpleCNN
from fluxdistributed_tpu.parallel import TrainState, make_train_step
from fluxdistributed_tpu.parallel.dp import flax_loss_fn
from fluxdistributed_tpu.train import prepare_training, train
from fluxdistributed_tpu.train.logging import NullLogger


@pytest.fixture(scope="module")
def mesh():
    return fd.data_mesh(8)


def test_chunked_loader_layout_and_determinism(mesh):
    """chunk=K stacks K per-step batches; sub-batch j of item c equals
    batch c*K+j of an unchunked loader with the same seed."""
    ds = SyntheticDataset(nsamples=256, nclasses=4, shape=(8, 8, 3))
    flat = list(PrefetchLoader(ds, mesh, 16, cycles=8, seed=3))
    chunked = list(PrefetchLoader(ds, mesh, 16, cycles=8, seed=3, chunk=4))
    assert len(flat) == 8 and len(chunked) == 2
    for c, item in enumerate(chunked):
        assert item["image"].shape == (4, 16, 8, 8, 3)
        for j in range(4):
            np.testing.assert_array_equal(
                np.asarray(item["image"][j]), np.asarray(flat[c * 4 + j]["image"])
            )
            np.testing.assert_array_equal(
                np.asarray(item["label"][j]), np.asarray(flat[c * 4 + j]["label"])
            )

    with pytest.raises(ValueError, match="multiple of chunk"):
        PrefetchLoader(ds, mesh, 16, cycles=7, chunk=4)


def test_chunked_step_matches_sequential(mesh):
    """One steps_per_call=4 dispatch == four plain dispatches, to float
    tolerance, on identical stacked data."""
    model = SimpleCNN(num_classes=4)
    rng = np.random.default_rng(0)
    xs = rng.normal(0, 1, (4, 16, 8, 8, 3)).astype(np.float32)
    ys = np.stack([
        np.asarray(fd.onehot(rng.integers(0, 4, 16), 4)) for _ in range(4)
    ])

    variables = model.init(jax.random.PRNGKey(0), xs[0, :1], train=True)
    params = variables["params"]
    loss_fn = flax_loss_fn(model, fd.logitcrossentropy)
    opt = optim.momentum(0.1, 0.9)

    plain = make_train_step(loss_fn, opt, mesh, donate=False)
    state = TrainState.create(sharding.replicate(params, mesh), opt)
    losses = []
    for j in range(4):
        b = sharding.shard_batch({"image": xs[j], "label": ys[j]}, mesh)
        state, m = plain(state, b)
        losses.append(float(m["loss"]))

    chunked = make_train_step(loss_fn, opt, mesh, donate=False, steps_per_call=4)
    state_c = TrainState.create(sharding.replicate(params, mesh), opt)
    from jax.sharding import NamedSharding, PartitionSpec as P

    stacked = {
        "image": jax.device_put(xs, NamedSharding(mesh, P(None, "data"))),
        "label": jax.device_put(ys, NamedSharding(mesh, P(None, "data"))),
    }
    state_c, mc = chunked(state_c, stacked)
    assert int(state_c.step) == 4
    np.testing.assert_allclose(np.asarray(mc["loss"]), losses, rtol=1e-5, atol=1e-6)
    for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(state_c.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_device_loop_through_trainer(mesh):
    """prepare_training(steps_per_call=4) + train(): 8 optimizer steps in
    2 dispatches, same final step count, finite loss, eval works."""
    ds = SyntheticDataset(nsamples=128, nclasses=4, shape=(8, 8, 3))
    task = prepare_training(
        SimpleCNN(num_classes=4), ds, optim.momentum(0.05, 0.9),
        mesh=mesh, batch_size=16, cycles=8, topk=(1,),
        steps_per_call=4, val_dataset=ds, val_samples=16,
    )
    assert len(task.loader) == 2
    train(task, print_every=1, eval_every=1, topk=(1,), logger=NullLogger())
    assert int(task.state.step) == 8

    with pytest.raises(ValueError, match="spmd='jit'"):
        prepare_training(
            SimpleCNN(num_classes=4), ds, optim.momentum(0.05, 0.9),
            mesh=mesh, batch_size=16, cycles=8, steps_per_call=2,
            spmd="shard_map",
        )


def test_device_loop_composes_with_grad_accum(mesh):
    """steps_per_call scans whole steps; accum_steps microbatches within
    each step — composed, they must still match plain sequential steps."""
    model = SimpleCNN(num_classes=4)
    rng = np.random.default_rng(1)
    xs = rng.normal(0, 1, (2, 32, 8, 8, 3)).astype(np.float32)
    ys = np.stack([
        np.asarray(fd.onehot(rng.integers(0, 4, 32), 4)) for _ in range(2)
    ])
    params = model.init(jax.random.PRNGKey(0), xs[0, :1], train=True)["params"]
    loss_fn = flax_loss_fn(model, fd.logitcrossentropy)
    opt = optim.momentum(0.1, 0.9)

    plain = make_train_step(loss_fn, opt, mesh, donate=False, accum_steps=2)
    state = TrainState.create(sharding.replicate(params, mesh), opt)
    for j in range(2):
        b = sharding.shard_batch({"image": xs[j], "label": ys[j]}, mesh)
        state, _ = plain(state, b)

    both = make_train_step(
        loss_fn, opt, mesh, donate=False, accum_steps=2, steps_per_call=2
    )
    state_c = TrainState.create(sharding.replicate(params, mesh), opt)
    from jax.sharding import NamedSharding, PartitionSpec as P

    stacked = {
        "image": jax.device_put(xs, NamedSharding(mesh, P(None, "data"))),
        "label": jax.device_put(ys, NamedSharding(mesh, P(None, "data"))),
    }
    state_c, m = both(state_c, stacked)
    assert int(state_c.step) == 2 and m["loss"].shape == (2,)
    for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(state_c.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)
