"""FDT305 negative: the same mutation under a module-level lock."""
import threading

_STATS = {}
_STATS_LOCK = threading.Lock()


def _worker():
    with _STATS_LOCK:
        _STATS["ticks"] = _STATS.get("ticks", 0) + 1


def start():
    threading.Thread(target=_worker, daemon=True).start()
