"""FDT303 negative: blocking work happens after release (snapshot
under the lock), and the in-region join carries a timeout bound."""
import threading
import urllib.request


class Prober:
    def __init__(self):
        self._lock = threading.Lock()
        self.status = {}

    def probe(self, url, worker):
        resp = urllib.request.urlopen(url)  # block BEFORE the lock
        with self._lock:
            worker.join(timeout=0.5)  # bounded — cannot stall forever
            self.status[url] = resp.status
