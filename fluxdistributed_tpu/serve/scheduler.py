"""Prefill/decode scheduler: FIFO admission, per-request stopping,
backpressure, and serving metrics.

One loop drives the engine's two compiled programs:

* **decode phase** — if any slot is live, ONE fixed-shape step over all
  slots; per-slot next tokens are emitted, stop conditions checked
  (``max_new_tokens`` / EOS), and finished requests free their slot.
* **admit phase** — free slots are filled from the bounded FIFO queue:
  each admission runs one bucketed prefill and splices the result into
  its slot, so waiting requests join MID-FLIGHT without recompiling or
  disturbing live slots.  The first generated token comes from the
  prefill logits (that draw is the time-to-first-token).

Decode-before-admit means a slot freed by an EOS in step N is re-filled
within the same ``step()`` call — continuous batching, not gang
scheduling.  Backpressure is the bounded queue: ``submit`` raises
:class:`QueueFull` (the HTTP front end maps it to 429).

Thread model: ``submit``/``metrics`` may be called from any thread;
``step``/``run_until_idle`` must run on ONE driver thread (the server's
engine loop, or the test body).
"""

from __future__ import annotations

import itertools
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence

import jax
import numpy as np

from .engine import LMEngine

__all__ = ["Request", "Scheduler", "QueueFull"]

_ids = itertools.count()


class QueueFull(RuntimeError):
    """Admission queue at capacity — shed load (HTTP 429)."""


@dataclass
class Request:
    """One generation request riding the slot pool."""

    prompt: Sequence[int]
    max_new_tokens: int
    temperature: float = 0.0
    seed: int = 0
    eos_id: Optional[int] = None
    # called from the scheduler thread per emitted token (streaming)
    on_token: Optional[Callable[["Request", int], None]] = None
    id: int = field(default_factory=lambda: next(_ids))

    # scheduler-owned state
    generated: List[int] = field(default_factory=list)
    state: str = "queued"  # queued | active | done
    slot: Optional[int] = None
    done: threading.Event = field(default_factory=threading.Event)
    submitted_at: Optional[float] = None
    first_token_at: Optional[float] = None
    finished_at: Optional[float] = None

    def __post_init__(self):
        self.prompt = [int(t) for t in self.prompt]
        self._key = np.asarray(jax.random.PRNGKey(self.seed))

    @property
    def tokens(self) -> List[int]:
        """Prompt + generated — the ``models.generate`` output layout."""
        return list(self.prompt) + list(self.generated)


class Scheduler:
    def __init__(self, engine: LMEngine, max_queue: int = 64):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        self.engine = engine
        self.max_queue = max_queue
        self._queue: deque[Request] = deque()
        self._lock = threading.Lock()
        self._work = threading.Event()
        self.slots: List[Optional[Request]] = [None] * engine.max_slots
        self._m = {
            "requests_submitted": 0,
            "requests_finished": 0,
            "requests_rejected": 0,
            "prefill_tokens": 0,       # real prompt tokens prefilled
            "prefill_padded_tokens": 0,  # bucket-padded tokens computed
            "prefill_sec": 0.0,
            "decode_tokens": 0,        # live-slot tokens generated
            "decode_sec": 0.0,
            "ttft_sec_last": 0.0,
            "ttft_sec_sum": 0.0,
            "ttft_count": 0,
        }

    # ---- producer side (any thread) ---------------------------------------

    def submit(self, req: Request) -> Request:
        """Validate + enqueue; raises ``ValueError`` (bad shape) or
        :class:`QueueFull` (backpressure)."""
        self.engine.validate_request(len(req.prompt), req.max_new_tokens)
        with self._lock:
            if len(self._queue) >= self.max_queue:
                self._m["requests_rejected"] += 1
                raise QueueFull(
                    f"admission queue full ({self.max_queue} waiting)")
            req.state = "queued"
            req.submitted_at = time.monotonic()
            self._queue.append(req)
            self._m["requests_submitted"] += 1
        self._work.set()
        return req

    def wait_for_work(self, timeout: float = 0.05) -> None:
        """Block the driver thread until a submit arrives (or timeout)."""
        self._work.wait(timeout)
        self._work.clear()

    # ---- driver side (one thread) -----------------------------------------

    @property
    def active_slots(self) -> int:
        return sum(r is not None for r in self.slots)

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    @property
    def idle(self) -> bool:
        return self.active_slots == 0 and self.queue_depth == 0

    def step(self) -> int:
        """One scheduler tick: decode live slots, then admit from the
        queue into whatever is free (including slots freed THIS tick).
        Returns the number of tokens emitted."""
        emitted = 0
        live = [s for s, r in enumerate(self.slots) if r is not None]
        if live:
            t0 = time.monotonic()
            nxt = self.engine.step_decode()
            self._m["decode_sec"] += time.monotonic() - t0
            self._m["decode_tokens"] += len(live)
            for s in live:
                self._emit(self.slots[s], int(nxt[s]))
                emitted += 1
        # admit into free slots (possibly just freed by EOS above)
        while True:
            try:
                free = self.slots.index(None)
            except ValueError:
                break
            with self._lock:
                if not self._queue:
                    break
                req = self._queue.popleft()
            t0 = time.monotonic()
            first, bucket = self.engine.prefill(
                free, req.prompt, req.temperature, req._key)
            self._m["prefill_sec"] += time.monotonic() - t0
            self._m["prefill_tokens"] += len(req.prompt)
            self._m["prefill_padded_tokens"] += bucket
            req.state = "active"
            req.slot = free
            self.slots[free] = req
            self._emit(req, first)
            emitted += 1
        return emitted

    def run_until_idle(self, max_steps: int = 1_000_000) -> None:
        for _ in range(max_steps):
            if self.idle:
                return
            self.step()
        raise RuntimeError(f"scheduler did not drain in {max_steps} steps")

    def generate_all(self, requests: Sequence[Request]) -> List[List[int]]:
        """Convenience (tests/bench): submit everything, drain, return
        each request's prompt+generated token list."""
        for r in requests:
            self.submit(r)
        self.run_until_idle()
        return [r.tokens for r in requests]

    # ---- internals --------------------------------------------------------

    def _emit(self, req: Request, tok: int) -> None:
        now = time.monotonic()
        req.generated.append(tok)
        if req.first_token_at is None:
            req.first_token_at = now
            if req.submitted_at is not None:
                ttft = now - req.submitted_at
                self._m["ttft_sec_last"] = ttft
                self._m["ttft_sec_sum"] += ttft
                self._m["ttft_count"] += 1
        if req.on_token is not None:
            try:
                req.on_token(req, tok)
            except Exception as e:  # noqa: BLE001
                # a streaming callback must not be able to kill the
                # whole serving loop (or skip this request's stop check)
                print(f"serve: on_token callback failed for request "
                      f"{req.id}: {type(e).__name__}: {e}", file=sys.stderr)
        hit_eos = req.eos_id is not None and tok == req.eos_id
        if hit_eos or len(req.generated) >= req.max_new_tokens:
            self._finish(req)

    def _finish(self, req: Request) -> None:
        req.state = "done"
        req.finished_at = time.monotonic()
        if req.slot is not None:
            self.slots[req.slot] = None
            self.engine.reset_slot(req.slot)
            req.slot = None
        self._m["requests_finished"] += 1
        req.done.set()

    def metrics(self) -> dict:
        """Serving counters + derived rates + engine compile stats."""
        with self._lock:
            m = dict(self._m)
            m["queue_depth"] = len(self._queue)
        m["active_slots"] = self.active_slots
        m["max_slots"] = self.engine.max_slots
        m["prefill_tokens_per_sec"] = (
            m["prefill_tokens"] / m["prefill_sec"] if m["prefill_sec"] else 0.0
        )
        m["decode_tokens_per_sec"] = (
            m["decode_tokens"] / m["decode_sec"] if m["decode_sec"] else 0.0
        )
        n = m["ttft_count"]  # every request that GOT a first token —
        # dividing by requests_finished would overstate the average
        # whenever active requests have already produced TTFT samples
        m["ttft_sec_avg"] = m["ttft_sec_sum"] / n if n else 0.0
        m.update(self.engine.compile_stats())
        return m
