"""Fused packed ZeRO-1 update (parallel/zero1_fused.py) invariants.

The fusion changes HOW the update executes (one packed buffer, one
collective each way, one kernel), never WHAT it computes — so the bar
is bit-for-bit parity with the composable GSPMD ZeRO-1 step on an f32
model, plus the memory layout claim and kernel-impl agreement.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import fluxdistributed_tpu.mesh as mesh_lib
from fluxdistributed_tpu import optim, sharding
from fluxdistributed_tpu.models import MLP
from fluxdistributed_tpu.ops import logitcrossentropy
from fluxdistributed_tpu.parallel import make_train_step_zero1, zero1_state
from fluxdistributed_tpu.parallel import zero1_fused as zf
from fluxdistributed_tpu.parallel.dp import flax_loss_fn

STEPS = 4


@pytest.fixture(scope="module")
def setup():
    mesh = mesh_lib.data_mesh(8)
    # odd feature sizes force real padding in the packed buffer
    model = MLP(features=(13, 10))
    x = jax.random.normal(jax.random.PRNGKey(1), (32, 6, 6, 3), jnp.float32)
    y = jax.nn.one_hot(
        jax.random.randint(jax.random.PRNGKey(2), (32,), 0, 10), 10)
    params = model.init(jax.random.PRNGKey(0), x[:2], train=True)["params"]
    loss_fn = flax_loss_fn(model, logitcrossentropy, has_aux_state=False)
    batch = sharding.shard_batch({"image": x, "label": y}, mesh)
    return mesh, params, loss_fn, batch


def test_bitwise_parity_with_gspmd_zero1(setup):
    """Same losses, bit-identical params after STEPS Adam steps."""
    mesh, params, loss_fn, batch = setup
    opt = optim.adam(1e-2)
    ref_state, sh = zero1_state(params, opt, mesh)
    ref_step = make_train_step_zero1(loss_fn, opt, mesh, sh, donate=False)
    ref_losses = []
    for _ in range(STEPS):
        ref_state, m = ref_step(ref_state, batch)
        ref_losses.append(float(m["loss"]))

    state, _ = zf.zero1_fused_state(params, mesh)
    step = zf.make_train_step_zero1_fused(
        loss_fn, mesh, state, lr=1e-2, donate=False)
    losses = []
    for _ in range(STEPS):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses == ref_losses
    for a, b in zip(jax.tree.leaves(ref_state.params),
                    jax.tree.leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_opt_state_sharded_eighth_and_donation(setup):
    """m/v live as flat f32 buffers, 1/8 per device; the donated step
    updates in place without error."""
    mesh, params, loss_fn, batch = setup
    state, _ = zf.zero1_fused_state(params, mesh)
    leaf = state.opt_state["m"]
    assert leaf.dtype == jnp.float32 and leaf.ndim == 1
    assert leaf.shape[0] % (8 * 1024) == 0  # whole tiles per shard
    assert leaf.addressable_shards[0].data.shape[0] == leaf.shape[0] // 8
    step = zf.make_train_step_zero1_fused(
        loss_fn, mesh, state, lr=1e-2, donate=True)
    state2, m = step(state, batch)
    assert np.isfinite(float(m["loss"]))
    assert int(state2.step) == 1


def test_pack_unpack_roundtrip():
    tree = {
        "a": jnp.arange(13.0),
        "b": jnp.arange(12.0).reshape(3, 4),
        "frozen": None,
    }
    flat = zf.pack_tree(tree, 4)
    assert flat.shape[0] % (4 * 1024) == 0
    back = zf.unpack_tree(flat, tree)
    for k in ("a", "b"):
        np.testing.assert_array_equal(np.asarray(back[k]),
                                      np.asarray(tree[k]))
        assert back[k].shape == tree[k].shape
    assert back["frozen"] is None
    # pad tail is zero (inert through Adam)
    np.testing.assert_array_equal(np.asarray(flat[25:]), 0.0)


def test_kernel_impls_agree():
    """The real Pallas kernel (interpreter) and the XLA rendering of
    the same chain produce the same update — and both match optim.adam
    applied to the flat buffer."""
    rng = np.random.default_rng(0)
    n = 2 * 1024
    p = jnp.asarray(rng.normal(size=n), jnp.float32)
    g = jnp.asarray(rng.normal(size=n), jnp.float32) * 0.1
    m = jnp.asarray(rng.normal(size=n), jnp.float32) * 0.01
    v = jnp.abs(jnp.asarray(rng.normal(size=n), jnp.float32)) * 0.01
    outs = {}
    for impl in ("xla", "interpret"):
        outs[impl] = zf.fused_adam_update(p, g, m, v, 7, lr=3e-3, impl=impl)
    for a, b in zip(outs["xla"], outs["interpret"]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)
    # vs optim.adam on the same buffer: same math, but the standalone
    # expression may fuse FMAs differently — 1-ULP tolerance
    ref_p, (ref_m, ref_v) = optim.adam(3e-3).apply(p, g, (m, v), 7)
    for got, ref in zip(outs["xla"], (ref_p, ref_m, ref_v)):
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=1e-6, atol=1e-8)


def test_kernel_rejects_ragged_buffer():
    x = jnp.zeros((1000,), jnp.float32)
    with pytest.raises(ValueError, match="pack_tree"):
        zf.fused_adam_update(x, x, x, x, 0)


def test_kernel_covers_tail_when_block_does_not_divide():
    """block_rows not dividing the row count must not drop the tail
    (a dropped grid block would all-gather uninitialized memory into
    the params): every element updates, interpret == xla."""
    rng = np.random.default_rng(1)
    n = 3 * 1024  # 24 rows; block_rows=16 does not divide
    p = jnp.asarray(rng.normal(size=n), jnp.float32)
    g = jnp.full((n,), 0.25, jnp.float32)
    z = jnp.zeros((n,), jnp.float32)
    ref = zf.fused_adam_update(p, g, z, z, 0, lr=1e-2, impl="xla",
                               block_rows=16)
    out = zf.fused_adam_update(p, g, z, z, 0, lr=1e-2, impl="interpret",
                               block_rows=16)
    for a, b in zip(ref, out):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-6, atol=1e-7)
    # nonzero grad everywhere → every m element moved off zero
    assert (np.asarray(out[1]) != 0).all()


@pytest.mark.slow
def test_lr_schedule_rides_as_data(setup):
    """A schedule changes eta per step without retracing (the scalars
    are data): parity with the GSPMD variant under the same schedule."""
    mesh, params, loss_fn, batch = setup
    sched = optim.step_decay(1e-2, 0.5, 2)
    opt = optim.adam(sched)
    ref_state, sh = zero1_state(params, opt, mesh)
    ref_step = make_train_step_zero1(loss_fn, opt, mesh, sh, donate=False)
    state, _ = zf.zero1_fused_state(params, mesh)
    step = zf.make_train_step_zero1_fused(
        loss_fn, mesh, state, lr=sched, donate=False)
    for _ in range(STEPS):
        ref_state, _ = ref_step(ref_state, batch)
        state, _ = step(state, batch)
    for a, b in zip(jax.tree.leaves(ref_state.params),
                    jax.tree.leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
