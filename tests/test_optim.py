"""Optimizer tests — the functional ``state``/``opt(m, g, st)`` contract
(reference: src/overloads.jl:1-34) plus numeric checks vs optax."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from fluxdistributed_tpu import optim, tree


def params():
    return {"w": jnp.array([1.0, -2.0, 3.0]), "frozen": None, "b": jnp.array([0.5])}


def grads():
    return {"w": jnp.array([0.1, 0.2, -0.3]), "frozen": None, "b": jnp.array([1.0])}


def test_descent():
    opt = optim.descent(0.1)
    st = opt.init(params())
    p2, st2 = opt.apply(params(), grads(), st, 0)
    assert np.allclose(np.asarray(p2["w"]), [0.99, -2.02, 3.03])
    assert p2["frozen"] is None


def test_reference_call_syntax():
    # The reference applies optimizers as ``m, st = opt(m, gs, st)``
    # (src/overloads.jl:1-12); Optimizer.__call__ mirrors that.
    opt = optim.momentum(0.01, 0.9)
    st = opt.init(params())
    p2, st2 = opt(params(), grads(), st)
    assert p2["w"].shape == (3,)


def test_momentum_matches_flux_semantics():
    # Flux Momentum: v = rho*v + eta*g ; x -= v
    opt = optim.momentum(0.1, 0.5)
    p = {"w": jnp.array([1.0])}
    g = {"w": jnp.array([1.0])}
    st = opt.init(p)
    p, st = opt.apply(p, g, st, 0)   # v=0.1, w=0.9
    assert np.allclose(np.asarray(p["w"]), 0.9)
    p, st = opt.apply(p, g, st, 1)   # v=0.15, w=0.75
    assert np.allclose(np.asarray(p["w"]), 0.75)


def test_adam_matches_optax():
    p = {"w": jnp.linspace(-1, 1, 5)}
    opt = optim.adam(1e-2)
    st = opt.init(p)
    ox = optax.adam(1e-2, b1=0.9, b2=0.999, eps=1e-8, eps_root=0.0)
    ox_st = ox.init(p)
    px = p
    for step in range(5):
        g = {"w": jnp.sin(jnp.linspace(0, 3, 5)) * (step + 1)}
        p, st = opt.apply(p, g, st, step)
        upd, ox_st = ox.update(g, ox_st, px)
        px = optax.apply_updates(px, upd)
    tree.assert_close(p, px, rtol=1e-5, atol=1e-6)


def test_adamw_decays():
    opt_a = optim.adam(1e-3)
    opt_w = optim.adamw(1e-3, weight_decay=0.1)
    p = {"w": jnp.array([10.0])}
    g = {"w": jnp.array([0.0])}
    pa, _ = opt_a.apply(p, g, opt_a.init(p), 0)
    pw, _ = opt_w.apply(p, g, opt_w.init(p), 0)
    assert float(pw["w"][0]) < float(pa["w"][0])


def test_lars_trust_ratio_scales():
    opt = optim.lars(lr=1.0, momentum_coef=0.0, trust_coefficient=1e-3)
    p = {"w": jnp.full((4,), 2.0)}
    g = {"w": jnp.full((4,), 1.0)}
    p2, _ = opt.apply(p, g, opt.init(p), 0)
    # update magnitude = trust * |p|/|g| * |g| elementwise = 1e-3 * 2.0
    assert np.allclose(np.asarray(p["w"] - p2["w"]), 2e-3, rtol=1e-5)


def test_schedules():
    s = optim.step_decay(1.0, 0.2, 10)  # the legacy LR/5-every-10 analog
    assert np.isclose(float(s(0)), 1.0)
    assert np.isclose(float(s(10)), 0.2)
    assert np.isclose(float(s(25)), 0.04)
    c = optim.cosine_decay(1.0, 100)
    assert np.isclose(float(c(0)), 1.0)
    assert np.isclose(float(c(100)), 0.0, atol=1e-6)
    w = optim.warmup_cosine(1.0, 10, 110)
    assert float(w(5)) == pytest.approx(0.5)
    assert float(w(10)) == pytest.approx(1.0)


def test_optimizer_jits_with_schedule():
    opt = optim.momentum(optim.step_decay(0.1, 0.5, 2), 0.9)
    p = {"w": jnp.ones(3)}
    st = opt.init(p)

    @jax.jit
    def step(p, g, st, i):
        return opt.apply(p, g, st, i)

    g = {"w": jnp.ones(3)}
    for i in range(4):
        p, st = step(p, g, st, jnp.asarray(i))
    assert np.all(np.isfinite(np.asarray(p["w"])))


def test_clip_by_global_norm():
    from fluxdistributed_tpu.optim import clip_by_global_norm, descent, global_norm

    params = {"a": jnp.zeros((3,)), "b": jnp.zeros((2,)), "frozen": jnp.zeros(())}
    grads = {"a": jnp.asarray([3.0, 0.0, 0.0]), "b": jnp.asarray([0.0, 4.0]),
             "frozen": None}
    assert float(global_norm(grads)) == 5.0

    opt = clip_by_global_norm(descent(1.0), max_norm=1.0)
    st = opt.init(params)
    new_params, _ = jax.jit(opt.apply)(params, grads, st, 0)
    # effective grad rescaled to norm exactly 1 -> update = -g/5
    np.testing.assert_allclose(np.asarray(new_params["a"]), [-0.6, 0, 0], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(new_params["b"]), [0, -0.8], rtol=1e-6)
    np.testing.assert_allclose(np.asarray(new_params["frozen"]), 0.0)

    # below the threshold: untouched
    opt2 = clip_by_global_norm(descent(1.0), max_norm=10.0)
    p2, _ = opt2.apply(params, grads, opt2.init(params), 0)
    np.testing.assert_allclose(np.asarray(p2["a"]), [-3.0, 0, 0], rtol=1e-6)


def test_clip_in_compiled_train_step():
    """Clipping composes with the compiled DP step."""
    import fluxdistributed_tpu as fd
    from fluxdistributed_tpu import optim, sharding
    from fluxdistributed_tpu.models import SimpleCNN
    from fluxdistributed_tpu.parallel import TrainState, make_train_step
    from fluxdistributed_tpu.parallel.dp import flax_loss_fn

    mesh = fd.data_mesh()
    model = SimpleCNN(num_classes=10)
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (16, 16, 16, 3)).astype(np.float32)
    y = np.asarray(fd.onehot(rng.integers(0, 10, 16), 10))
    variables = model.init(jax.random.PRNGKey(0), x[:1], train=False)
    loss_fn = flax_loss_fn(model, fd.logitcrossentropy, has_aux_state=False)
    opt = optim.clip_by_global_norm(optim.momentum(0.1, 0.9), 1.0)
    step = make_train_step(loss_fn, opt, mesh, donate=False)
    state = TrainState.create(sharding.replicate(variables["params"], mesh), opt)
    b = sharding.shard_batch({"image": x, "label": y}, mesh)
    state, m = step(state, b)
    assert int(state.step) == 1 and float(m["loss"]) > 0


def test_ema_tracks_params():
    from fluxdistributed_tpu.optim import descent, ema_params, with_ema

    opt = with_ema(descent(0.5), decay=0.9)
    params = {"w": jnp.asarray([1.0, 2.0])}
    st = opt.init(params)
    np.testing.assert_array_equal(np.asarray(ema_params(st)["w"]), [1.0, 2.0])

    g = {"w": jnp.asarray([1.0, 1.0])}
    p1, st = opt.apply(params, g, st, 0)  # params -> [0.5, 1.5]
    # warmup-corrected decay at t=0: min(0.9, 1/10) = 0.1
    want = 0.1 * np.asarray([1.0, 2.0]) + 0.9 * np.asarray(p1["w"])
    np.testing.assert_allclose(np.asarray(ema_params(st)["w"]), want, rtol=1e-6)

    # late steps use the configured decay
    p2, st2 = opt.apply(p1, g, st, 1000)
    want2 = 0.9 * np.asarray(ema_params(st)["w"]) + 0.1 * np.asarray(p2["w"])
    np.testing.assert_allclose(np.asarray(ema_params(st2)["w"]), want2, rtol=1e-6)

    with pytest.raises(ValueError, match="EMA"):
        ema_params({"not": "ema"})


def test_ema_in_compiled_train_step():
    """EMA params converge toward trained params through the DP step."""
    import fluxdistributed_tpu as fd
    from fluxdistributed_tpu import optim, sharding
    from fluxdistributed_tpu.models import SimpleCNN
    from fluxdistributed_tpu.parallel import TrainState, make_train_step
    from fluxdistributed_tpu.parallel.dp import flax_loss_fn

    mesh = fd.data_mesh()
    model = SimpleCNN(num_classes=10)
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (16, 16, 16, 3)).astype(np.float32)
    y = np.asarray(fd.onehot(rng.integers(0, 10, 16), 10))
    variables = model.init(jax.random.PRNGKey(0), x[:1], train=False)
    loss_fn = flax_loss_fn(model, fd.logitcrossentropy, has_aux_state=False)
    opt = optim.with_ema(optim.momentum(0.1, 0.9), decay=0.5)
    step = make_train_step(loss_fn, opt, mesh, donate=False)
    state = TrainState.create(sharding.replicate(variables["params"], mesh), opt)
    b = sharding.shard_batch({"image": x, "label": y}, mesh)
    for _ in range(20):
        state, _ = step(state, b)
    ema = optim.ema_params(state.opt_state)
    # after 20 steps at decay .5 the shadow is close to the live params
    for e, p in zip(jax.tree.leaves(ema), jax.tree.leaves(state.params)):
        assert np.abs(np.asarray(e) - np.asarray(p)).max() < 0.5
