"""N-replica router (serve/router.py) — fast tier, FakeEngine replicas
over live HTTP (no compiles).

The router's contract is about processes and sockets: health-checked
replica registry, per-replica circuit breakers, pre-first-token
failover with the request id preserved, least-loaded dispatch off the
queue-wait rollup, drain-aware routing, the fleet metrics/trace
rollups, and zero-downtime rolling restarts.  Probes are driven
MANUALLY (``probe_now``) throughout so every transition is
deterministic — no sleeping on prober-thread timing.
"""

from __future__ import annotations

import json
import math
import threading
import time
import urllib.error
import urllib.request

import pytest

from fluxdistributed_tpu import faults
from fluxdistributed_tpu.obs import RequestTracer
from fluxdistributed_tpu.serve import (LMServer, Replica, Router,
                                       RouterError, Scheduler)
from fluxdistributed_tpu.serve.testing import FakeLMEngine, fake_tokens


@pytest.fixture(autouse=True)
def _no_leaked_fault_plan():
    yield
    faults.clear_plan()


class _Rep:
    """One in-process replica: FakeLMEngine scheduler + LMServer +
    live ThreadingHTTPServer."""

    def __init__(self, step_delay=0.001, max_queue=16, trace=False,
                 max_slots=4):
        self.engine = FakeLMEngine(step_delay=step_delay,
                                   max_slots=max_slots)
        self.sched = Scheduler(self.engine, max_queue=max_queue,
                               reqtrace=RequestTracer() if trace else None)
        self.srv = LMServer(self.sched, vocab=256)
        self.httpd = self.srv.serve("127.0.0.1", 0)
        # tight poll so teardown's shutdown() returns in ~ms, not 0.5s
        self.thread = threading.Thread(
            target=lambda: self.httpd.serve_forever(poll_interval=0.02),
            daemon=True)
        self.thread.start()
        self.url = f"http://127.0.0.1:{self.srv.bound_port}"

    def kill(self):
        """Hard in-process death: the port stops answering."""
        self.httpd.shutdown()
        self.httpd.server_close()
        self.srv.stop_loop()

    def close(self):
        try:
            self.kill()
        except OSError:
            pass
        self.srv.close()


@pytest.fixture
def fleet(request):
    made = []

    def make(n=2, **kw):
        reps = [_Rep(**kw) for _ in range(n)]
        made.extend(reps)
        return reps

    yield make
    for r in made:
        r.close()


def make_router(reps, **kw):
    """Router over in-process replicas with manual probing (the prober
    interval is effectively infinite; tests call probe_now)."""
    kw.setdefault("probe_interval", 3600.0)
    kw.setdefault("probe_timeout", 5.0)
    kw.setdefault("failure_threshold", 2)
    kw.setdefault("breaker_cooldown", 0.2)
    kw.setdefault("dispatch_tries", 3)
    kw.setdefault("dispatch_backoff", 0.01)
    kw.setdefault("upstream_timeout", 60.0)
    router = Router(
        [Replica(f"r{i}", r.url) for i, r in enumerate(reps)], **kw)
    return router


@pytest.fixture
def served(request):
    """Start the router's front HTTP server; yields base-url factory."""
    started = []

    def start(router):
        httpd = router.serve("127.0.0.1", 0)
        t = threading.Thread(
            target=lambda: httpd.serve_forever(poll_interval=0.02),
            daemon=True)
        t.start()
        started.append((router, httpd))
        return f"http://127.0.0.1:{router.bound_port}"

    yield start
    for router, httpd in started:
        httpd.shutdown()
        httpd.server_close()
        router.close()


def _post(base, body, rid=None, timeout=30):
    headers = {"Content-Type": "application/json"}
    if rid:
        headers["X-Request-Id"] = rid
    req = urllib.request.Request(
        f"{base}/v1/generate", data=json.dumps(body).encode(),
        method="POST", headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _get(base, path, timeout=10):
    try:
        with urllib.request.urlopen(f"{base}{path}", timeout=timeout) as r:
            return r.status, r.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


# ---------------------------------------------------------------------------
# health + breaker state machine
# ---------------------------------------------------------------------------


def test_probe_health_then_failures_open_breaker(fleet):
    a, b = fleet(2)
    router = make_router([a, b])
    try:
        router.probe_now()
        r0, r1 = router.replicas
        assert r0.healthy and r1.healthy
        assert router.registry.value(
            "fdtpu_router_replica_healthy", "r0") == 1
        a.kill()
        # threshold is 2 consecutive failures: first probe degrades,
        # second opens
        router.probe_now()
        assert not r0.healthy and r0.breaker == "closed"
        router.probe_now()
        assert r0.breaker == "open"
        assert router.registry.value(
            "fdtpu_router_breaker_state", "r0") == 2
        assert router.registry.value(
            "fdtpu_router_breaker_opens_total", "r0") == 1
        # the healthy replica keeps the fleet serving
        h = router.health()
        assert h["ok"] and h["dispatchable"] == 1
    finally:
        router.close()


def test_half_open_trial_request_recloses_breaker(fleet, served):
    """The open → half-open → closed path driven by a TRIAL REQUEST
    (not a probe): after the cooldown the next dispatch is allowed one
    trial on the suspect replica; its success re-closes the breaker."""
    (a,) = fleet(1)
    router = make_router([a], failure_threshold=1, breaker_cooldown=0.05)
    base = served(router)
    router.probe_now()
    a.kill()
    code, body, _ = _post(base, {"prompt_tokens": [1], "max_tokens": 2})
    assert code in (502, 503), body
    assert router.replicas[0].breaker == "open"
    # replica returns on the SAME port (allow_reuse_address) — only a
    # trial request may discover that, probes are off
    a.httpd = a.srv.serve("127.0.0.1", a.srv.bound_port)
    threading.Thread(
        target=lambda: a.httpd.serve_forever(poll_interval=0.02),
        daemon=True).start()
    time.sleep(0.06)  # past the cooldown
    code, body, _ = _post(base, {"prompt_tokens": [1, 2], "max_tokens": 3})
    assert code == 200, body
    assert body["generated"] == fake_tokens([1, 2], 3)
    assert router.replicas[0].breaker == "closed"


def test_probe_success_also_recovers_open_breaker(fleet):
    a, b = fleet(2)
    router = make_router([a, b], failure_threshold=1)
    try:
        router.probe_now()
        a.kill()
        router.probe_now()
        assert router.replicas[0].breaker == "open"
        a.httpd = a.srv.serve("127.0.0.1", a.srv.bound_port)
        threading.Thread(
            target=lambda: a.httpd.serve_forever(poll_interval=0.02),
            daemon=True).start()
        router.probe_now()
        assert router.replicas[0].breaker == "closed"
        assert router.replicas[0].healthy
    finally:
        router.close()


# ---------------------------------------------------------------------------
# dispatch: failover, request-id preservation, determinism
# ---------------------------------------------------------------------------


def test_failover_pre_first_token_preserves_request_id(fleet, served):
    """A replica that died since its last probe still LOOKS healthy —
    dispatch discovers the death, fails over, and the client sees one
    clean 200 with its own X-Request-Id and the exact tokens the dead
    replica would have produced (pure-function engine = the greedy
    determinism the guarantee rides on in production)."""
    a, b = fleet(2)
    router = make_router([a, b])
    base = served(router)
    router.probe_now()  # both healthy
    a.kill()            # ...but the router does not know yet
    hit_dead = False
    for i in range(4):  # round-robin guarantees the dead one is tried
        rid = f"req-{i}"
        code, body, headers = _post(
            base, {"prompt_tokens": [i + 1, 2], "max_tokens": 5}, rid=rid)
        assert code == 200, body
        assert body["request_id"] == rid
        assert headers.get("X-Request-Id") == rid
        assert body["generated"] == fake_tokens([i + 1, 2], 5)
        hit_dead = hit_dead or headers.get("X-Fdtpu-Replica") == "r1"
    assert router.registry.value(
        "fdtpu_router_dispatch_failures_total", "r0") >= 1
    assert router.registry.value("fdtpu_router_failovers_total") >= 1


def test_injected_dispatch_fault_is_retried(fleet, served):
    """serve.dispatch injection: the first dispatch attempt raises
    inside the router (no replica involved) and the retry completes —
    the failover machinery is provable with zero real failures."""
    (a,) = fleet(1)
    router = make_router([a])
    base = served(router)
    router.probe_now()
    faults.install_plan(faults.FaultPlan().fail("serve.dispatch", times=1))
    code, body, _ = _post(base, {"prompt_tokens": [9], "max_tokens": 3})
    assert code == 200, body
    assert body["generated"] == fake_tokens([9], 3)
    assert router.registry.value("fdtpu_router_failovers_total") >= 1
    reg = faults._metrics()
    assert reg["injected"].value("serve.dispatch") >= 1


def test_all_replicas_down_returns_503(fleet, served):
    (a,) = fleet(1)
    router = make_router([a], dispatch_tries=2, dispatch_backoff=0.0)
    base = served(router)
    router.probe_now()
    a.kill()
    router.probe_now()
    router.probe_now()  # breaker open; nothing dispatchable
    code, body, _ = _post(base, {"prompt_tokens": [1], "max_tokens": 2})
    assert code == 503, body
    assert "no dispatchable replica" in body["error"]
    assert "request_id" in body


def test_replica_5xx_fails_over_and_feeds_breaker(fleet, served):
    """A 5xx from a replica is the REPLICA's failure: nothing reached
    the client, so the router must retry elsewhere and count the
    failure — not pass the 500 through and reset the breaker."""
    import http.server as hs

    class Broken(hs.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def do_GET(self):  # healthz: looks perfectly healthy
            body = json.dumps({"ok": True, "draining": False}).encode()
            self.send_response(200)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_POST(self):  # every generate blows up server-side
            body = json.dumps({"error": "engine exploded"}).encode()
            self.send_response(500)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

    broken = hs.ThreadingHTTPServer(("127.0.0.1", 0), Broken)
    threading.Thread(
        target=lambda: broken.serve_forever(poll_interval=0.02),
        daemon=True).start()
    (good,) = fleet(1)
    router = Router(
        [Replica("r0", f"http://127.0.0.1:{broken.server_address[1]}"),
         Replica("r1", good.url)],
        probe_interval=3600.0, failure_threshold=2,
        dispatch_backoff=0.0, upstream_timeout=30.0)
    base = served(router)
    try:
        router.probe_now()
        for i in range(4):  # round-robin makes r0 answer 500 at least once
            code, body, headers = _post(
                base, {"prompt_tokens": [i + 1], "max_tokens": 3})
            assert code == 200, body
            assert headers.get("X-Fdtpu-Replica") == "r1"
            assert body["generated"] == fake_tokens([i + 1], 3)
        assert router.registry.value(
            "fdtpu_router_dispatch_failures_total", "r0") >= 1
        assert router.replicas[0].consecutive_failures >= 1
    finally:
        broken.shutdown()
        broken.server_close()


def test_client_errors_pass_through_without_failover(fleet, served):
    a, b = fleet(2)
    router = make_router([a, b])
    base = served(router)
    router.probe_now()
    code, body, _ = _post(base, {"max_tokens": 4})  # no prompt: 400
    assert code == 400
    assert router.registry.value("fdtpu_router_failovers_total") == 0
    # a replying replica is a LIVE replica — no breaker movement
    assert all(r.breaker == "closed" for r in router.replicas)


# ---------------------------------------------------------------------------
# streaming: retry before first token, fail fast after
# ---------------------------------------------------------------------------


def test_stream_failover_before_first_token(fleet, served):
    a, b = fleet(2)
    router = make_router([a, b])
    base = served(router)
    router.probe_now()
    a.kill()
    for i in range(3):
        req = urllib.request.Request(
            f"{base}/v1/generate",
            data=json.dumps({"prompt_tokens": [7, i], "max_tokens": 4,
                             "stream": True}).encode(),
            method="POST", headers={"X-Request-Id": f"s-{i}"})
        with urllib.request.urlopen(req, timeout=30) as r:
            assert r.headers["X-Request-Id"] == f"s-{i}"
            lines = [json.loads(x)
                     for x in r.read().decode().strip().splitlines()]
        toks = [x["token"] for x in lines if "token" in x]
        assert toks == fake_tokens([7, i], 4)
        assert lines[-1]["done"] is True
    assert router.registry.value(
        "fdtpu_router_dispatch_failures_total", "r0") >= 1


def test_stream_after_first_token_fails_fast_naming_replica(fleet, served):
    """Once a token has been forwarded, an upstream stall/death cannot
    be transparently retried — the stream must end promptly with an
    error line naming the replica, NOT hang for the full request
    timeout or silently report done."""
    (a,) = fleet(1)
    a.engine.step_delay = 0.01
    router = make_router([a], upstream_timeout=0.8)
    base = served(router)
    router.probe_now()

    def wedge_soon():
        time.sleep(0.1)  # a few tokens out first
        a.engine.step_delay = 2.0  # the replica wedges mid-decode
        # (2s >> the 0.8s upstream timeout, small enough that the
        # sleeping loop thread wakes before teardown's join gives up)

    threading.Thread(target=wedge_soon, daemon=True).start()
    req = urllib.request.Request(
        f"{base}/v1/generate",
        data=json.dumps({"prompt_tokens": [3, 4], "max_tokens": 500,
                         "stream": True}).encode(), method="POST")
    t0 = time.monotonic()
    with urllib.request.urlopen(req, timeout=30) as r:
        lines = [json.loads(x)
                 for x in r.read().decode().strip().splitlines()]
    assert time.monotonic() - t0 < 10
    assert any("token" in x for x in lines), lines
    last = lines[-1]
    assert last["done"] is False
    assert "r0" in last["error"] and "mid-stream" in last["error"]
    assert last["replica"] == "r0"
    assert router.registry.value(
        "fdtpu_router_midstream_failures_total") == 1
    assert router.registry.value("fdtpu_router_failovers_total") == 0
    a.engine.step_delay = 0.0  # unwedge for teardown


def test_client_disconnect_midstream_does_not_blame_replica(fleet, served):
    """A CLIENT leaving mid-stream is not the replica's fault: no
    breaker movement, no mid-stream-failure tally (regression: a write
    failure is also an OSError and must not be classified as an
    upstream death)."""
    import http.client as hc

    (a,) = fleet(1, step_delay=0.01)
    router = make_router([a], failure_threshold=1)
    base = served(router)
    router.probe_now()
    host, port = "127.0.0.1", router.bound_port
    conn = hc.HTTPConnection(host, port, timeout=10)
    conn.request("POST", "/v1/generate",
                 body=json.dumps({"prompt_tokens": [1, 2],
                                  "max_tokens": 200, "stream": True}),
                 headers={"Content-Type": "application/json"})
    resp = conn.getresponse()
    resp.read(8)  # a token or two have flowed
    conn.sock.close()  # the client walks away mid-stream
    deadline = time.monotonic() + 5
    while router.replicas[0].inflight and time.monotonic() < deadline:
        time.sleep(0.02)
    assert router.replicas[0].inflight == 0
    assert router.replicas[0].breaker == "closed"
    assert router.replicas[0].consecutive_failures == 0
    assert router.registry.value(
        "fdtpu_router_midstream_failures_total") == 0
    a.engine.step_delay = 0.0  # let the abandoned decode finish fast


# ---------------------------------------------------------------------------
# drain-under-load ordering (the rolling-restart building block)
# ---------------------------------------------------------------------------


def test_drain_under_load_streams_finish_and_router_routes_around(
        fleet, served):
    """The SIGTERM-shaped drain under live traffic: a stream in flight
    on the draining replica runs to completion, the replica's own 503
    carries draining:true, the router treats draining as out-of-rotation
    (NOT a breaker failure) and re-dispatches new work to the healthy
    replica."""
    a, b = fleet(2, step_delay=0.01)
    router = make_router([a, b])
    base = served(router)
    router.probe_now()

    # a long stream pinned mid-flight on A (direct submit, so the test
    # controls which replica drains under it)
    stream_lines = []
    stream_done = threading.Event()

    def long_stream():
        req = urllib.request.Request(
            f"{a.url}/v1/generate",
            data=json.dumps({"prompt_tokens": [5, 6], "max_tokens": 60,
                             "stream": True}).encode(), method="POST")
        with urllib.request.urlopen(req, timeout=60) as r:
            for line in r:
                stream_lines.append(json.loads(line))
        stream_done.set()

    t = threading.Thread(target=long_stream, daemon=True)
    t.start()
    while not any("token" in x for x in stream_lines):
        time.sleep(0.005)  # mid-stream

    # the bin/serve.py SIGTERM handler shape: drain on a background
    # thread (test_serve_drain covers the real-signal wiring)
    drain_result = {}
    dt = threading.Thread(
        target=lambda: drain_result.setdefault("ok", a.srv.drain(30.0)),
        daemon=True)
    dt.start()
    while not a.sched.draining:
        time.sleep(0.001)

    # 1. queued-at-the-replica behavior: a direct submit gets 503 with
    #    draining:true — the router's cue to go elsewhere
    code, body, _ = _post(a.url, {"prompt_tokens": [1], "max_tokens": 2})
    assert code == 503 and body.get("draining") is True

    # 2. the router re-dispatches around the draining replica: before
    #    any probe ran, round-robin still tries A, absorbs its 503 and
    #    completes on B; afterwards A is marked draining
    for i in range(4):
        code, body, headers = _post(
            base, {"prompt_tokens": [8, i], "max_tokens": 4},
            rid=f"d-{i}")
        assert code == 200, body
        assert headers.get("X-Fdtpu-Replica") == "r1"
        assert body["generated"] == fake_tokens([8, i], 4)
    r0 = router.replicas[0]
    assert r0.draining is True
    # 3. a deliberate drain is NOT a failure: breaker untouched
    assert r0.breaker == "closed" and r0.consecutive_failures == 0
    router.probe_now()
    assert r0.breaker == "closed"
    h = router.health()
    assert h["ok"] and h["dispatchable"] == 1

    # 4. the in-flight stream on A completed fully
    dt.join(timeout=60)
    assert stream_done.wait(timeout=60)
    toks = [x["token"] for x in stream_lines if "token" in x]
    assert toks == fake_tokens([5, 6], 60), "drain cut a stream short"
    assert stream_lines[-1]["done"] is True
    assert drain_result["ok"] is True


# ---------------------------------------------------------------------------
# least-loaded dispatch off the queue-wait rollup
# ---------------------------------------------------------------------------


def test_least_loaded_prefers_low_queue_wait_p50(fleet, served):
    a, b = fleet(2)
    router = make_router([a, b])
    base = served(router)
    # A's queue-wait p50 rollup says requests wait ~1s there; B has no
    # samples (NaN = unloaded)
    for _ in range(4):
        a.sched._h_queue_wait.observe(1.0)
    router.probe_now()  # scrapes both replicas' /metrics
    r0 = router.replicas[0]
    assert r0.queue_wait_p50 > 0.1
    assert math.isnan(router.replicas[1].queue_wait_p50)
    for i in range(4):
        code, _, headers = _post(
            base, {"prompt_tokens": [i + 1], "max_tokens": 2})
        assert code == 200
        assert headers.get("X-Fdtpu-Replica") == "r1", (
            "least-loaded dispatch must prefer the unloaded replica")


def test_stale_metrics_fall_back_to_round_robin(fleet, served):
    a, b = fleet(2)
    router = make_router([a, b], metrics_stale_after=0.5)
    base = served(router)
    for _ in range(4):
        a.sched._h_queue_wait.observe(1.0)
    router.probe_now()
    with router._lock:
        for rep in router.replicas:
            rep.load_at -= 100.0  # both scrapes long stale
    seen = set()
    for i in range(4):
        code, _, headers = _post(
            base, {"prompt_tokens": [i + 1], "max_tokens": 2})
        assert code == 200
        seen.add(headers.get("X-Fdtpu-Replica"))
    assert seen == {"r0", "r1"}, (
        "stale load truth must fall back to round-robin, not keep "
        "trusting it")


# ---------------------------------------------------------------------------
# fleet rollups: /metrics parity pin, /healthz, /trace stitching
# ---------------------------------------------------------------------------


def _family_names(text, prefix="fdtpu_serve_"):
    return {line.split(" ")[2] for line in text.splitlines()
            if line.startswith("# TYPE " + prefix)}


def test_metrics_rollup_names_byte_identical(fleet, served):
    """The parity pin: every fdtpu_serve_* family a replica exposes
    appears under the SAME name in the router rollup (with a replica
    label on each series) — PRs 3/6/9's byte-identical guarantee
    extended through the router."""
    a, b = fleet(2)
    router = make_router([a, b])
    base = served(router)
    router.probe_now()
    _post(base, {"prompt_tokens": [1, 2], "max_tokens": 3})
    _, direct = _get(a.url, "/metrics")
    direct = direct.decode()
    _, rolled = _get(base, "/metrics")
    rolled = rolled.decode()
    direct_names = _family_names(direct)
    assert direct_names  # the pin is vacuous if the scrape broke
    assert _family_names(rolled) == direct_names
    # every rolled serve series carries the replica label
    for line in rolled.splitlines():
        if line.startswith("fdtpu_serve_"):
            assert 'replica="' in line, line
    # and the router's own series ride the same page
    assert "# TYPE fdtpu_router_breaker_state gauge" in rolled
    assert 'fdtpu_router_dispatches_total{replica="' in rolled


def test_healthz_rollup_shape(fleet, served):
    a, b = fleet(2)
    router = make_router([a, b])
    base = served(router)
    router.probe_now()
    code, raw = _get(base, "/healthz")
    assert code == 200
    h = json.loads(raw)
    assert h["ok"] and h["dispatchable"] == 2 and h["role"] == "router"
    names = {r["name"] for r in h["replicas"]}
    assert names == {"r0", "r1"}
    for r in h["replicas"]:
        assert r["breaker"] == "closed" and r["healthy"]
    a.kill()
    b.kill()
    router.probe_now()
    router.probe_now()
    code, raw = _get(base, "/healthz")
    assert code == 503
    assert json.loads(raw)["ok"] is False


def test_trace_rollup_stitches_replica_timelines(fleet, served):
    a, b = fleet(2, trace=True)
    router = make_router([a, b])
    base = served(router)
    router.probe_now()
    for i in range(4):
        code, _, _ = _post(base, {"prompt_tokens": [i + 1],
                                  "max_tokens": 2}, rid=f"tr-{i}")
        assert code == 200
    code, raw = _get(base, "/trace")
    assert code == 200
    doc = json.loads(raw)
    pids = {e["pid"] for e in doc["traceEvents"]}
    assert pids == {1, 2}, "one process row per replica"
    labels = {e["args"]["name"] for e in doc["traceEvents"]
              if e.get("ph") == "M" and e.get("name") == "process_name"}
    assert any("r0" in x for x in labels)
    assert any("r1" in x for x in labels)
    # the client-supplied ids stitched through: every enqueue event in
    # the fleet timeline belongs to a known request id
    enq = [e for e in doc["traceEvents"] if e.get("name") == "enqueue"]
    assert len(enq) == 4
    assert {r["name"] for r in doc["otherData"]["replicas"]} == {
        "r0", "r1"}


# ---------------------------------------------------------------------------
# rolling restart
# ---------------------------------------------------------------------------


def test_rolling_restart_zero_drops_under_load(fleet, served):
    """The in-process rolling restart: each replica's restart hook
    tears the old server down and brings a successor up on a fresh
    port, one replica at a time, while a client keeps sending requests
    through the router — none may fail."""
    reps = fleet(2, step_delay=0.002)

    def make_restart(idx):
        def restart(replica):
            old = reps[idx]
            old.close()
            reps[idx] = _Rep(step_delay=0.002)
            return reps[idx].url
        return restart

    router = Router(
        [Replica(f"r{i}", r.url, restart=make_restart(i))
         for i, r in enumerate(reps)],
        probe_interval=3600.0, failure_threshold=2,
        dispatch_backoff=0.01, upstream_timeout=30.0)
    base = served(router)
    router.probe_now()
    stop = threading.Event()
    outcomes = []

    def load():
        i = 0
        while not stop.is_set():
            code, body, _ = _post(
                base, {"prompt_tokens": [i % 9 + 1], "max_tokens": 3},
                timeout=30)
            outcomes.append((i, code, body))
            i += 1
            time.sleep(0.01)

    t = threading.Thread(target=load, daemon=True)
    t.start()
    old_urls = [r.url for r in router.replicas]
    try:
        results = router.rolling_restart(drain_timeout=10.0,
                                         ready_timeout=10.0)
    finally:
        stop.set()
        t.join(timeout=10)
    assert len(results) == 2
    new_urls = [r.url for r in router.replicas]
    assert set(new_urls).isdisjoint(old_urls), "successors on new ports"
    assert all(r["drained_clean"] for r in results)
    bad = [(i, c, b) for i, c, b in outcomes if c != 200]
    assert not bad, f"rolling restart dropped requests: {bad[:3]}"
    assert len(outcomes) > 0
    assert all(rep.healthy and rep.breaker == "closed"
               for rep in router.replicas)
    assert router.registry.value(
        "fdtpu_router_restarts_total", "r0") == 1
    # and the fleet still serves
    code, body, _ = _post(base, {"prompt_tokens": [2, 3],
                                 "max_tokens": 4})
    assert code == 200 and body["generated"] == fake_tokens([2, 3], 4)


def test_rolling_restart_requires_restart_hooks(fleet):
    a, b = fleet(2)
    router = make_router([a, b])
    try:
        router.probe_now()
        with pytest.raises(RouterError, match="restart hook"):
            router.rolling_restart()
    finally:
        router.close()


def test_duplicate_replica_name_rejected(fleet):
    (a,) = fleet(1)
    router = make_router([a])
    try:
        with pytest.raises(RouterError, match="duplicate"):
            router.add_replica(Replica("r0", a.url))
    finally:
        router.close()
