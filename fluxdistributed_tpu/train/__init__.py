from .logging import ConsoleLogger, Logger, NullLogger, current_logger, with_logger
from .trainer import TrainTask, evaluate, prepare_training, restore_training, train
from .checkpoint import latest_step, load_checkpoint, save_checkpoint, wait_for_pending
from .model_selection import (
    SelectionTask,
    prepare_model_selection,
    train_model_selection,
)

__all__ = [
    "ConsoleLogger",
    "Logger",
    "NullLogger",
    "current_logger",
    "with_logger",
    "TrainTask",
    "evaluate",
    "prepare_training",
    "restore_training",
    "train",
    "save_checkpoint",
    "wait_for_pending",
    "load_checkpoint",
    "latest_step",
    "SelectionTask",
    "prepare_model_selection",
    "train_model_selection",
]
