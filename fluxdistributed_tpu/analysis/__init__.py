"""fdtpu-lint: JAX-hazard static analysis for this repo.

Three layers (see ISSUE 5 / ISSUE 20 / docs/analysis.md):

* **AST rules** (:mod:`analysis.rules_ast`, run by
  :mod:`analysis.engine`) — stdlib-``ast`` scanning for tracer
  branches, host impurity in hot paths, weak-typed scalars, mutable
  closure captures, hardcoded mesh-axis literals, off-convention metric
  names, and undeclared donation.  Milliseconds, no jax import.
* **jaxpr checks** (:mod:`analysis.jaxpr_checks` over
  :mod:`analysis.variants`) — abstract-trace every registered
  train-step variant and the serve engine's program pool on the
  8-virtual-device CPU mesh, verifying sharding-spec validity,
  donation consumability, retrace determinism (= AOT-key stability)
  and transfer-cleanliness.
* **concurrency rules** (:mod:`analysis.concurrency`, FDT3xx) —
  lock-coverage inference, a cross-module lock-order graph with cycle
  detection, blocking-while-locked, thread-lifecycle and
  global-mutation-in-thread audits over the host-side orchestration;
  paired with the deterministic-schedule race harness
  (:mod:`analysis.schedules`).  Still stdlib-``ast``, no jax.

``bin/lint.py`` is the CLI; ``analysis/baseline.json`` allowlists
pre-existing findings so CI fails only on NEW ones.
"""

from __future__ import annotations

import os
from typing import Optional

from .findings import (  # noqa: F401
    Finding,
    SEVERITIES,
    baseline_key,
    diff_findings,
    format_finding,
    load_baseline,
    save_baseline,
    severity_rank,
    summarize,
)
from .engine import (  # noqa: F401
    default_roots,
    repo_root,
    scan_paths,
    scan_repo,
    scanned_files,
)
from .rules_ast import AST_RULES, declared_mesh_axes  # noqa: F401
from .concurrency import CONC_RULES, run_concurrency_checks  # noqa: F401

__all__ = [
    "AST_RULES",
    "CONC_RULES",
    "run_concurrency_checks",
    "Finding",
    "SEVERITIES",
    "baseline_key",
    "declared_mesh_axes",
    "default_baseline_path",
    "diff_findings",
    "format_finding",
    "lint_verdict",
    "load_baseline",
    "repo_root",
    "save_baseline",
    "scan_paths",
    "scan_repo",
    "scanned_files",
    "severity_rank",
    "summarize",
]


def default_baseline_path() -> str:
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "baseline.json")


def lint_verdict(baseline: Optional[str] = None) -> dict:
    """The static-health stamp for harness output (``bench.py`` embeds
    it in its JSON line): the AST-layer + concurrency-layer rule-count
    summary plus how many findings are NEW vs the checked-in baseline.
    jaxpr-free by design — it must cost seconds at most and never trace
    jax programs inside a bounded hardware-bench subprocess."""
    ast_findings = scan_repo()
    conc_findings = run_concurrency_checks()
    findings = sorted(ast_findings + conc_findings,
                      key=lambda f: (f.file, f.line, f.rule))
    base = load_baseline(baseline or default_baseline_path())
    new, _ = diff_findings(findings, base)
    out = summarize(findings, new)
    out["baseline"] = len(base)
    out["layers"] = {"ast": len(ast_findings),
                     "concurrency": len(conc_findings)}
    return out
