"""Every model family has a learns-not-just-steps acceptance check.

Drives ``benchmarks/convergence.py`` (the acceptance harness the
hardware sessions run) as a CLI per family — the same stack as the
reference's convergence expectations (SURVEY §4: the reference's only
learning evidence is its single-device gradient test; these go further
and demand actual loss/accuracy movement through the full pipeline).
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(args, timeout):
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "convergence.py"),
         "--platform", "cpu", *args],
        capture_output=True, text=True, timeout=timeout, cwd=REPO, env=env,
    )
    assert out.returncode == 0, out.stderr[-3000:]
    return json.loads(out.stdout.strip().splitlines()[-1])


@pytest.mark.slow
def test_lm_family_approaches_entropy_floor():
    s = _run(["--family", "lm", "--cycles", "120", "--batch", "32",
              "--eval-every", "60", "--vocab", "32", "--seqlen", "32"],
             timeout=900)
    assert s["metric"].startswith("lm_tiny")
    # must close most of the uniform→entropy-floor gap: real learning,
    # not just loss wiggle (0.9884 observed on CPU at these settings)
    assert s["fraction_of_gap_closed"] > 0.8, s
    assert s["final_val_loss"] < s["first_val_loss"] * 0.5, s


@pytest.mark.slow
def test_vit_family_learns_cifar_format():
    s = _run(["--family", "vit", "--cycles", "150", "--batch", "64",
              "--eval-every", "75"], timeout=1800)
    assert s["metric"].startswith("ViT")
    # 10 classes: chance is 0.1; the template dataset is separable
    assert s["final_val_top1"] > 0.5, s
    assert s["final_val_top1"] > s["first_val_top1"], s
