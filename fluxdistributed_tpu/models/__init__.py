from .resnet import ResNet, resnet18, resnet34, resnet50, resnet101, resnet152
from .simple import SimpleCNN, MLP
from .vit import ViT, vit_tiny, vit_b16, vit_l16, vit_h14

__all__ = [
    "ResNet",
    "resnet18",
    "resnet34",
    "resnet50",
    "resnet101",
    "resnet152",
    "SimpleCNN",
    "MLP",
    "ViT",
    "vit_tiny",
    "vit_b16",
    "vit_l16",
    "vit_h14",
]
