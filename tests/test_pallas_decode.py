"""Flash-decode kernel suite vs the XLA reference (ops/pallas_decode.py).

Tier-1 pins the XLA-fallback schedule (the CPU-default path) and one
interpreter-mode run of the REAL kernel per layout at tiny shapes, plus
the engine-level golden parity: `LMEngine(attention_impl="pallas")`
must be token-for-token identical to sequential `generate()` on every
cache layout.  The heavier interpret matrices (quant × GQA × layouts)
ride the slow tier.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fluxdistributed_tpu.ops.attention import dot_product_attention
from fluxdistributed_tpu.ops.pallas_decode import (
    flash_decode, flash_decode_paged, resolve_decode_impl,
)


def _rand(rng, *shape):
    return jnp.asarray(rng.normal(size=shape), jnp.float32)


def _dense_ref(q, k, v, idx):
    r = k.shape[1]
    allow = (jnp.arange(r)[None, :] <= idx[:, None])[:, None, None, :]
    return dot_product_attention(q, k, v, mask=allow)


def _ring_ref(q, k, v, idx, sp, window, sinks):
    qg = idx[:, None]
    allow = (sp >= 0) & (sp <= qg)
    band = sp > qg - window
    if sinks:
        band |= sp < sinks
    return dot_product_attention(q, k, v, mask=(allow & band)[:, None, None])


def _ring_state(rng, b, rows, sinks, cursors):
    """slot_pos for a ring of `rows` total slots at the given cursors."""
    sp = np.full((b, rows), -1, np.int32)
    ring = rows - sinks
    for bb, cur in enumerate(cursors):
        for p in range(cur + 1):
            if p < sinks:
                sp[bb, p] = p
            elif p > cur - ring:
                sp[bb, sinks + (p - sinks) % ring] = p
    return jnp.asarray(sp)


@pytest.mark.parametrize("impl", ["xla", "interpret"])
def test_dense_cursor_parity(impl):
    rng = np.random.default_rng(0)
    q = _rand(rng, 3, 1, 4, 16)
    k, v = _rand(rng, 3, 40, 4, 16), _rand(rng, 3, 40, 4, 16)
    idx = jnp.asarray([0, 17, 39], jnp.int32)  # first token / mid / full
    out = flash_decode(q, k, v, idx, block_k=16, impl=impl)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_dense_ref(q, k, v, idx)),
        rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("impl", ["xla", "interpret"])
def test_windowed_ring_sinks_parity(impl):
    window, sinks, rows = 8, 2, 13  # ring shorter than history
    rng = np.random.default_rng(1)
    b = 3
    q = _rand(rng, b, 1, 2, 16)
    k, v = _rand(rng, b, rows, 2, 16), _rand(rng, b, rows, 2, 16)
    cursors = [0, 7, 25]  # pre-wrap, at-window, post-wrap
    sp = _ring_state(rng, b, rows, sinks, cursors)
    idx = jnp.asarray(cursors, jnp.int32)
    out = flash_decode(q, k, v, idx, slot_pos=sp, window=window,
                       sinks=sinks, block_k=8, impl=impl)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_ring_ref(q, k, v, idx, sp, window,
                                              sinks)),
        rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("impl", ["xla", "interpret"])
def test_paged_page_table_walk_parity(impl):
    """Bound pages anywhere in the pool, unbound (-1) pages skipped —
    and the result equals attention over the gathered masked view."""
    rng = np.random.default_rng(2)
    b, bs, nb, pages = 3, 8, 16, 5
    q = _rand(rng, b, 1, 4, 16)
    kp, vp = _rand(rng, nb, bs, 4, 16), _rand(rng, nb, bs, 4, 16)
    pt = jnp.asarray([[3, 7, -1, -1, -1],
                      [0, 1, 2, 9, -1],
                      [5, -1, -1, -1, -1]], jnp.int32)
    idx = jnp.asarray([9, 30, 3], jnp.int32)
    gk = kp[jnp.maximum(pt, 0)].reshape(b, pages * bs, 4, 16)
    gv = vp[jnp.maximum(pt, 0)].reshape(b, pages * bs, 4, 16)
    allow = (jnp.arange(pages * bs)[None, :] <= idx[:, None])
    allow &= jnp.repeat(pt >= 0, bs, axis=1)
    ref = dot_product_attention(q, gk, gv, mask=allow[:, None, None, :])
    out = flash_decode_paged(q, kp, vp, pt, idx, impl=impl)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_gqa_grouped_heads_parity():
    """8 query heads on 2 KV heads: the kernel's [group, block] tiles
    must equal dense attention over explicitly repeated KV."""
    rng = np.random.default_rng(3)
    b, h, hkv, d, r = 2, 8, 2, 16, 24
    q = _rand(rng, b, 1, h, d)
    k, v = _rand(rng, b, r, hkv, d), _rand(rng, b, r, hkv, d)
    idx = jnp.asarray([5, 23], jnp.int32)
    rep = lambda x: jnp.repeat(x, h // hkv, axis=2)
    ref = _dense_ref(q, rep(k), rep(v), idx)
    for impl in ("xla", "interpret"):
        out = flash_decode(q, k, v, idx, block_k=8, impl=impl)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


def test_quantized_dequant_in_kernel():
    """int8 K/V with per-row-per-head scales dequantize inside the
    kernel to exactly what pre-dequantized attention computes."""
    rng = np.random.default_rng(4)
    b, h, d, r = 2, 2, 16, 32
    q = _rand(rng, b, 1, h, d)
    kq = jnp.asarray(rng.integers(-127, 128, (b, r, h, d)), jnp.int8)
    vq = jnp.asarray(rng.integers(-127, 128, (b, r, h, d)), jnp.int8)
    ks = jnp.asarray(rng.uniform(0.01, 0.1, (b, r, h)), jnp.float32)
    vs = jnp.asarray(rng.uniform(0.01, 0.1, (b, r, h)), jnp.float32)
    idx = jnp.asarray([9, 31], jnp.int32)
    ref = _dense_ref(q, kq.astype(jnp.float32) * ks[..., None],
                     vq.astype(jnp.float32) * vs[..., None], idx)
    for impl in ("xla", "interpret"):
        out = flash_decode(q, kq, vq, idx, k_scale=ks, v_scale=vs,
                           block_k=16, impl=impl)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


def test_nothing_attendable_is_zero():
    """A slot with every page unbound (parked) returns exactly 0."""
    rng = np.random.default_rng(5)
    q = _rand(rng, 1, 1, 2, 8)
    kp, vp = _rand(rng, 4, 4, 2, 8), _rand(rng, 4, 4, 2, 8)
    pt = jnp.full((1, 4), -1, jnp.int32)
    out = flash_decode_paged(q, kp, vp, pt, jnp.zeros((1,), jnp.int32),
                             impl="xla")
    assert np.abs(np.asarray(out)).max() == 0.0


def test_validation_errors():
    rng = np.random.default_rng(6)
    q = _rand(rng, 1, 1, 2, 8)
    k = v = _rand(rng, 1, 8, 2, 8)
    idx = jnp.zeros((1,), jnp.int32)
    with pytest.raises(ValueError, match="slot_pos"):
        flash_decode(q, k, v, idx, window=4)
    with pytest.raises(ValueError, match="slot_pos"):
        flash_decode(q, k, v, idx, slot_pos=jnp.zeros((1, 8), jnp.int32))
    with pytest.raises(ValueError, match="k_scale"):
        flash_decode(q, k, v, idx, k_scale=jnp.zeros((1, 8, 2)))
    with pytest.raises(ValueError, match="query row"):
        flash_decode(k, k, v, idx)  # Tq=8, not decode-shaped
    with pytest.raises(ValueError, match="unknown decode impl"):
        resolve_decode_impl("mosaic")
    assert resolve_decode_impl(None) in ("pallas", "xla")


def test_attention_core_flash_rejects_decode_shape():
    """The training flash kernel points decode-shaped callers at the
    decode kernels instead of failing with a shape error."""
    from fluxdistributed_tpu.ops import attention_core

    fn = attention_core("flash")
    rng = np.random.default_rng(7)
    q1 = _rand(rng, 1, 1, 2, 8)
    k = v = _rand(rng, 1, 16, 2, 8)
    with pytest.raises(ValueError, match="flash_decode"):
        fn(q1, k, v)
    # non-decode shapes still run the training kernel
    out = fn(k, k, v)
    assert out.shape == k.shape


def test_ops_lazy_exports():
    import fluxdistributed_tpu.ops as ops

    assert ops.flash_decode is flash_decode
    assert ops.flash_decode_paged is flash_decode_paged
    assert callable(ops.flash_attention)
    with pytest.raises(AttributeError):
        ops.no_such_kernel


# ---- engine-level golden parity -------------------------------------------


def _seq_ref(model, params, prompts, new):
    from fluxdistributed_tpu.models.transformer_lm import generate

    outs = []
    for p in prompts:
        o = np.asarray(generate(model, params, np.asarray([p], np.int32),
                                total_len=len(p) + new))[0]
        outs.append(list(o[len(p):]))
    return outs


def _engine_run(engine, prompts, new):
    from fluxdistributed_tpu.serve import Request, Scheduler

    sched = Scheduler(engine)
    reqs = [Request(prompt=list(p), max_new_tokens=new) for p in prompts]
    sched.generate_all(reqs)
    return [r.generated for r in reqs]


def test_engine_pallas_paged_token_parity():
    """The acceptance core: a paged engine decoding through the flash
    path is token-identical to sequential generate(), at ONE decode
    compile.  (depth-2/dim-64 model: compile time is the whole cost of
    this test and the parity math is depth-independent)"""
    from fluxdistributed_tpu.models import transformer_lm as tlm
    from fluxdistributed_tpu.serve import LMEngine

    model = tlm.lm_tiny(vocab=64, dtype=jnp.float32, depth=2, dim=64,
                        mlp_dim=128)
    params = model.init(jax.random.PRNGKey(0), np.zeros((1, 2), np.int32),
                        train=False)["params"]
    rng = np.random.default_rng(1)
    # equal prompt lengths: the sequential reference then compiles ONE
    # generate program instead of one per length
    prompts = [list(rng.integers(0, 64, 6)) for _ in range(2)]
    ref = _seq_ref(model.clone(decode=True), params, prompts, 8)
    eng = LMEngine(model, params, max_slots=2, max_len=24, layout="paged",
                   kv_block_size=8, prefill_chunk=8,
                   attention_impl="pallas")
    assert _engine_run(eng, prompts, 8) == ref
    assert eng.compile_stats()["decode_compiles"] == 1


@pytest.mark.slow
def test_engine_pallas_dense_and_windowed_parity():
    """Dense-layout flash decode, plain and windowed-ring+sinks+GQA."""
    from fluxdistributed_tpu.models import transformer_lm as tlm
    from fluxdistributed_tpu.serve import LMEngine

    rng = np.random.default_rng(2)
    for kw in (dict(), dict(window=8, sinks=2, num_kv_heads=2)):
        model = tlm.lm_tiny(vocab=64, dtype=jnp.float32, **kw)
        params = model.init(jax.random.PRNGKey(0),
                            np.zeros((1, 2), np.int32),
                            train=False)["params"]
        prompts = [list(rng.integers(0, 64, n)) for n in (5, 14)]
        ref = _seq_ref(model.clone(decode=True), params, prompts, 12)
        eng = LMEngine(model, params, max_slots=2, max_len=32,
                       buckets=(16,), attention_impl="pallas")
        assert _engine_run(eng, prompts, 12) == ref, kw
        # paged windowed too
        eng = LMEngine(model, params, max_slots=2, max_len=32,
                       layout="paged", kv_block_size=4, prefill_chunk=8,
                       attention_impl="pallas")
        assert _engine_run(eng, prompts, 12) == ref, kw


@pytest.mark.slow
@pytest.mark.parametrize("window,sinks", [(6, 0), (8, 2)])
def test_interpret_ring_matrix(window, sinks):
    """The REAL kernel (interpreter) across ring geometries and GQA."""
    rng = np.random.default_rng(8)
    b, h, hkv, d = 2, 4, 2, 16
    rows = sinks + window + 5
    q = _rand(rng, b, 1, h, d)
    k, v = _rand(rng, b, rows, hkv, d), _rand(rng, b, rows, hkv, d)
    cursors = [window - 1, rows + 3]
    sp = _ring_state(rng, b, rows, sinks, cursors)
    idx = jnp.asarray(cursors, jnp.int32)
    rep = lambda x: jnp.repeat(x, h // hkv, axis=2)
    ref = _ring_ref(q, rep(k), rep(v), idx, sp, window, sinks)
    out = flash_decode(q, k, v, idx, slot_pos=sp, window=window,
                       sinks=sinks, block_k=8, impl="interpret")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.slow
def test_interpret_paged_windowed_quantized():
    """Paged + windowed ring + int8 scales, real kernel under the
    interpreter — the fully-loaded configuration."""
    rng = np.random.default_rng(9)
    b, bs, nb, pages, hkv, d = 2, 4, 12, 4, 2, 16
    window, sinks = 6, 2
    r_pad = pages * bs
    q = _rand(rng, b, 1, hkv, d)
    kq = jnp.asarray(rng.integers(-127, 128, (nb, bs, hkv, d)), jnp.int8)
    vq = jnp.asarray(rng.integers(-127, 128, (nb, bs, hkv, d)), jnp.int8)
    ks = jnp.asarray(rng.uniform(0.01, 0.1, (nb, bs, hkv)), jnp.float32)
    vs = jnp.asarray(rng.uniform(0.01, 0.1, (nb, bs, hkv)), jnp.float32)
    pt = jnp.asarray([[0, 3, 7, -1], [1, 2, 5, 9]], jnp.int32)
    cursors = [10, 30]
    sp = _ring_state(rng, b, r_pad, sinks, cursors)
    # mask rows whose page is unbound (mirrors the device layout where
    # slot_pos rows only exist for bound pages)
    bound = np.repeat(np.asarray(pt) >= 0, bs, axis=1)
    sp = jnp.where(jnp.asarray(bound), sp, -1)
    idx = jnp.asarray(cursors, jnp.int32)
    gk = (kq.astype(jnp.float32) * ks[..., None])[jnp.maximum(pt, 0)]
    gv = (vq.astype(jnp.float32) * vs[..., None])[jnp.maximum(pt, 0)]
    ref = _ring_ref(q, gk.reshape(b, r_pad, hkv, d),
                    gv.reshape(b, r_pad, hkv, d), idx, sp, window, sinks)
    for impl in ("xla", "interpret"):
        out = flash_decode_paged(q, kq, vq, pt, idx, slot_pos=sp,
                                 window=window, sinks=sinks,
                                 k_scale=ks, v_scale=vs, impl=impl)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
