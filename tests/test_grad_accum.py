"""Gradient accumulation: N microbatches == one big batch.

For a mean loss, accumulating gradients over ``accum_steps`` microbatches
and averaging must equal the single-pass gradient on the full batch —
the same invariant family as the reference's per-sample accumulation
test (test/single_device.jl:42-62), applied to the microbatch axis.
"""

import jax
import numpy as np
import pytest

import fluxdistributed_tpu as fd
from fluxdistributed_tpu import mesh as mesh_lib, optim, sharding, tree as tree_lib
from fluxdistributed_tpu.models import MLP, SimpleCNN
from fluxdistributed_tpu.parallel import TrainState, make_train_step
from fluxdistributed_tpu.parallel.dp import flax_loss_fn


@pytest.fixture(scope="module")
def mesh():
    return mesh_lib.data_mesh(8)


def _batch(mesh, n=32, nclasses=4, shape=(8, 8, 3), seed=0):
    rng = np.random.default_rng(seed)
    x = rng.normal(0, 1, (n, *shape)).astype(np.float32)
    y = np.asarray(fd.onehot(rng.integers(0, nclasses, n), nclasses))
    return sharding.shard_batch({"image": x, "label": y}, mesh)


def _run(model, mesh, batch, accum_steps, steps=3):
    variables = model.init(jax.random.PRNGKey(0), np.zeros((1, 8, 8, 3), np.float32),
                           train=True)
    params = variables["params"]
    mstate = {k: v for k, v in variables.items() if k != "params"}
    opt = optim.momentum(0.05, 0.9)
    step = make_train_step(
        flax_loss_fn(model, fd.logitcrossentropy), opt, mesh,
        donate=False, accum_steps=accum_steps,
    )
    state = TrainState.create(
        sharding.replicate(params, mesh), opt,
        model_state=sharding.replicate(mstate, mesh),
    )
    losses = []
    for _ in range(steps):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    return state, losses


def test_accumulated_equals_single_pass(mesh):
    batch = _batch(mesh)
    model = MLP(features=(16, 4))
    s1, l1 = _run(model, mesh, batch, accum_steps=1)
    s4, l4 = _run(model, mesh, batch, accum_steps=4)
    np.testing.assert_allclose(l1, l4, rtol=1e-5, atol=1e-6)
    tree_lib.assert_close(
        tree_lib.to_host(s1.params), tree_lib.to_host(s4.params),
        rtol=1e-5, atol=1e-6,
    )


def test_accum_with_batchnorm_trains(mesh):
    """BatchNorm stats thread through the scan; not bit-equal to the
    single-pass (per-microbatch stats), but training must work and stats
    must move."""
    batch = _batch(mesh)
    model = SimpleCNN(num_classes=4)
    state, losses = _run(model, mesh, batch, accum_steps=2, steps=5)
    assert losses[-1] < losses[0]
    assert int(state.step) == 5


def test_accum_rejects_indivisible_batch(mesh):
    batch = _batch(mesh, n=24)  # 24 not divisible by accum 5? 24/5 no
    model = MLP(features=(16, 4))
    with pytest.raises(Exception):
        _run(model, mesh, batch, accum_steps=5, steps=1)
