"""LM serving HTTP front end (bin/serve.py --lm + serve/server.py).

Covers the /v1/generate contract (blocking + chunked streaming), the
operational endpoints (/healthz, /metrics), input validation (400), and
backpressure (bounded queue -> 429).
"""

from __future__ import annotations

import http.server
import json
import pathlib
import sys
import threading
import urllib.error
import urllib.request

import pytest

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1] / "bin"))

import serve as serve_cli  # noqa: E402


@pytest.fixture(scope="module")
def lm_server():
    args = serve_cli.build_parser().parse_args(
        ["--lm", "--model", "lm_tiny", "--vocab", "256",
         "--max-slots", "2", "--max-len", "64", "--buckets", "8,16",
         "--max-queue", "4", "--port", "0"]
    )
    lm, sched = serve_cli.make_lm_app(args)
    srv = lm.serve("127.0.0.1", 0)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    try:
        yield f"http://127.0.0.1:{srv.server_address[1]}"
    finally:
        srv.shutdown()
        t.join(timeout=5)
        lm.stop_loop()


def _post(base, body, timeout=180):
    req = urllib.request.Request(
        f"{base}/v1/generate", data=json.dumps(body).encode(), method="POST")
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read()


def test_generate_roundtrip(lm_server):
    status, raw = _post(lm_server, {"prompt": "ab", "max_tokens": 5})
    data = json.loads(raw)
    assert status == 200
    assert data["tokens"][:2] == [97, 98]  # byte-level prompt echoed
    assert len(data["generated"]) == 5
    assert data["text"].startswith("ab")
    assert data["ttft_ms"] > 0


def test_generate_token_prompt_deterministic(lm_server):
    body = {"prompt_tokens": [5, 3, 7], "max_tokens": 6}
    a = json.loads(_post(lm_server, body)[1])
    b = json.loads(_post(lm_server, body)[1])
    assert a["tokens"] == b["tokens"]  # greedy is reproducible


def test_streaming_chunks(lm_server):
    req = urllib.request.Request(
        f"{lm_server}/v1/generate",
        data=json.dumps({"prompt": "xy", "max_tokens": 4,
                         "stream": True}).encode(),
        method="POST")
    with urllib.request.urlopen(req, timeout=180) as r:
        lines = [json.loads(l) for l in r.read().decode().strip().splitlines()]
    toks = [l["token"] for l in lines if "token" in l]
    assert len(toks) == 4
    assert lines[-1]["done"] and lines[-1]["generated"] == toks


def test_healthz_and_metrics(lm_server):
    with urllib.request.urlopen(f"{lm_server}/healthz", timeout=30) as r:
        health = json.loads(r.read())
    assert health["ok"] and health["max_slots"] == 2
    with urllib.request.urlopen(f"{lm_server}/metrics", timeout=30) as r:
        text = r.read().decode()
    for gauge in ("fdtpu_serve_queue_depth", "fdtpu_serve_active_slots",
                  "fdtpu_serve_decode_tokens_per_sec",
                  "fdtpu_serve_prefill_tokens_per_sec",
                  "fdtpu_serve_ttft_sec_last"):
        assert gauge in text, text


def test_bad_requests_400_and_404(lm_server):
    for body in ({}, {"prompt_tokens": [999]}, {"prompt": "x",
                                                "prompt_tokens": [1]},
                 {"prompt": "a" * 100, "max_tokens": 4}):
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(lm_server, body)
        assert ei.value.code == 400, body
        assert "error" in json.loads(ei.value.read())
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(f"{lm_server}/nope", timeout=30)
    assert ei.value.code == 404


def test_backpressure_429():
    """With the engine loop parked, the bounded queue fills and the
    next request is shed with 429 + Retry-After; starting the loop
    drains the accepted request normally."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from fluxdistributed_tpu.models import lm_tiny
    from fluxdistributed_tpu.serve import LMEngine, LMServer, Scheduler

    model = lm_tiny(vocab=64, depth=2, dim=64, mlp_dim=128,
                    dtype=jnp.float32)
    params = model.init(
        jax.random.PRNGKey(0), np.zeros((1, 2), np.int32), train=False
    )["params"]
    engine = LMEngine(model, params, max_slots=1, max_len=16, buckets=(4,))
    sched = Scheduler(engine, max_queue=1)
    lm = LMServer(sched, vocab=64, request_timeout=60)
    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), lm.make_handler())
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    base = f"http://127.0.0.1:{srv.server_address[1]}"
    try:
        results = []
        blocked = threading.Thread(
            target=lambda: results.append(_post(
                base, {"prompt_tokens": [1], "max_tokens": 2})),
            daemon=True)
        blocked.start()
        # wait until the first request occupies the (undrained) queue
        for _ in range(200):
            if sched.queue_depth == 1:
                break
            threading.Event().wait(0.01)
        assert sched.queue_depth == 1
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(base, {"prompt_tokens": [2], "max_tokens": 2})
        assert ei.value.code == 429
        assert ei.value.headers.get("Retry-After") == "1"
        lm.start_loop()  # now drain the accepted request
        blocked.join(timeout=120)
        assert results and results[0][0] == 200
    finally:
        srv.shutdown()
        t.join(timeout=5)
        lm.stop_loop()
