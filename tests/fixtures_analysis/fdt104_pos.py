"""FDT104 positive: a traced function reads a mutable module global."""
import jax

SCALE_TABLE = {"lr": 0.1}


@jax.jit
def scaled(x):
    # the trace snapshots SCALE_TABLE["lr"] once; later mutation is
    # silently ignored by every compiled execution
    return x * SCALE_TABLE["lr"]
