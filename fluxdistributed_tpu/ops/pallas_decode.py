"""Pallas TPU flash-decode attention: one query row vs the KV cache.

The serve engine's hot loop is the all-slot decode step — every live
request contributes ONE query row attending against its cache — and
``ops/pallas_attention.py``'s training kernel is the wrong shape for it
(its whole schedule amortizes over many query rows; a decode call would
pay a full [block_q, block_k] tile for one live row).  This module is
the decode-shaped member of the kernel family, and it understands the
engine's cache layouts NATIVELY (ROADMAP Open item 2):

* **dense slot cache** ``[B, R, Hkv, D]`` with per-slot cursors
  ``idx [B]`` — KV blocks wholly above a slot's cursor are skipped
  (no MXU work, data-dependent ``pl.when``), so cost tracks the LIVE
  prefix, not the reserved ``max_len``;
* **windowed ring + attention sinks** — the ring is already compact
  (``sinks + window`` rows), so the kernel iterates the ring
  blocks directly and recovers causality from the ``slot_pos`` side
  buffer: no gather, no scatter, and no dead full-length cache rows to
  mask (the band mask is over ring slots, not absolute positions);
* **paged block pool** ``[NB, bs, Hkv, D]`` — the kernel WALKS the
  per-slot int32 page table: each grid step DMAs the physical block the
  table names (scalar-prefetch index map), unbound pages (``-1``) are
  skipped, and the gather/reshape the XLA path pays per step never
  happens.

Grouped-query attention is native: the grid runs over ``B × Hkv`` and
each program attends all ``H/Hkv`` query heads of its group against the
SHARED KV block ([group, block] score tiles — decode's MXU utilization
comes from the group dimension).  Quantized caches (int8 / fp8 K/V with
per-row-per-head scales, ``models/transformer_lm.py``) dequantize
INSIDE the kernel — HBM traffic shrinks by the storage dtype, and the
f32 dequant rides the VPU between the DMA and the MXU.

Three implementations behind one call (``impl=``):

* ``"pallas"`` — the compiled TPU kernel (default on TPU);
* ``"interpret"`` — the SAME kernel under the Pallas interpreter (what
  the CPU parity tests run, so kernel code is exercised off-TPU);
* ``"xla"`` — a fallback that executes the kernel's exact block-walk
  schedule (same online softmax, same block skipping, same page-table
  walk, `lax.cond`-guarded per block) as plain XLA ops.  This is the
  default off TPU: the Pallas interpreter copies whole buffers per grid
  step and is orders of magnitude slower, while this fallback keeps the
  algorithmic wins — block skip beyond the cursor and no dead-page
  gather — measurable on CPU (benchmarks/attention_bench.py --decode).

Numerics match ``dot_product_attention`` to f32 accumulation on every
path (the shared ``online_softmax_update``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import NEG_INF, online_softmax_update
from .pallas_attention import interpret_mode

__all__ = ["flash_decode", "flash_decode_paged", "resolve_decode_impl"]

_LANES = 128


def resolve_decode_impl(impl: str | None = None) -> str:
    """``None``/``"auto"`` → ``"pallas"`` on TPU, the ``"xla"``
    block-walk fallback elsewhere (the interpreter is for parity tests,
    never the default — it is slower than either real path).  Pass
    ``impl="interpret"`` explicitly to run the real kernel under the
    interpreter anywhere (how the CPU kernel-parity tests drive it)."""
    if impl in (None, "auto"):
        return "pallas" if not interpret_mode() else "xla"
    if impl not in ("pallas", "interpret", "xla"):
        raise ValueError(
            f"unknown decode impl {impl!r} (pallas|interpret|xla|auto)")
    return impl


def _validate(window, sinks, slot_pos, k_scale, v_scale):
    if (window is None) != (slot_pos is None):
        raise ValueError(
            "windowed decode needs BOTH window= and slot_pos= (the ring's "
            "position side buffer); plain decode needs neither")
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    if sinks and window is None:
        raise ValueError("sinks only make sense with a window")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("quantized decode needs BOTH k_scale and v_scale")


def _gqa_fold(q):
    """[B, 1, H, D] → [B, Hkv-major] layout pieces: (q4, b, h, d)."""
    if q.ndim != 4 or q.shape[1] != 1:
        raise ValueError(
            f"flash decode takes one query row per slot: q must be "
            f"[B, 1, H, D], got {q.shape}")
    b, _, h, d = q.shape
    return q[:, 0], b, h, d


def _group_dims(h, hkv):
    if h % hkv:
        raise ValueError(
            f"num query heads ({h}) must be a multiple of num KV heads "
            f"({hkv}) for grouped-query attention")
    return h // hkv


# ---------------------------------------------------------------------------
# The XLA fallback: the kernel's schedule as plain ops
# ---------------------------------------------------------------------------


def _xla_block_walk(qh, idx, nblocks, block_rows, get_block, get_mask):
    """Shared fallback loop: online softmax over KV blocks with a
    ``lax.cond`` skip per block — dead blocks (beyond every cursor /
    unbound pages / unwritten ring slots) cost one predicate, not a
    gather + matmul.  ``qh``: [B, Hkv, G, D] f32, pre-scaled."""
    b, hkv, g, d = qh.shape
    acc = jnp.zeros((b, hkv, g, d), jnp.float32)
    m = jnp.full((b, hkv, g), NEG_INF, jnp.float32)
    l = jnp.zeros((b, hkv, g), jnp.float32)

    def body(j, carry):
        acc, m, l = carry
        allow = get_mask(j)  # [B, block_rows] bool — cheap (no K/V touch)

        def live(carry):
            acc, m, l = carry
            kb, vb = get_block(j)  # [B, block_rows, Hkv, D] f32 each
            s = jnp.einsum("bhgd,bkhd->bhgk", qh, kb,
                           preferred_element_type=jnp.float32)
            p, corr, m2, l2 = online_softmax_update(
                s, m, l, mask=allow[:, None, None, :])
            acc2 = acc * corr[..., None] + jnp.einsum(
                "bhgk,bkhd->bhgd", p, vb, preferred_element_type=jnp.float32)
            return acc2, m2, l2

        return jax.lax.cond(jnp.any(allow), live, lambda c: c, carry)

    if nblocks <= 4:
        # compact caches (windowed rings, short reserved rows): the
        # loop/cond dispatch overhead outweighs any skip — unroll and
        # let XLA fuse the handful of block updates into one program
        carry = (acc, m, l)
        for j in range(nblocks):
            carry = body(j, carry)
        acc, m, l = carry
    else:
        acc, m, l = jax.lax.fori_loop(0, nblocks, body, (acc, m, l))
    return acc / jnp.maximum(l, 1e-30)[..., None]


def _dequant(x, scale):
    x = x.astype(jnp.float32)
    return x if scale is None else x * scale.astype(jnp.float32)[..., None]


# ---------------------------------------------------------------------------
# Pallas kernels (dense + paged share the body via masking closures)
# ---------------------------------------------------------------------------


def _decode_kernel(refs, *, scale, window, sinks, hkv, block_rows,
                   windowed, quant, paged):
    """One (slot×KV-head, KV-block) grid step of flash decode.

    ``refs`` is the flat pallas argument list: scalar-prefetch refs
    first (idx; page table too when paged), then inputs (q, k, v
    [, slot_pos][, k_scale, v_scale]), then the output and the
    (acc, m, l) scratch.  KV innermost — the grid is sequential per
    core, so scratch carries the online softmax across blocks.
    """
    i = 0
    if paged:
        pt_ref = refs[i]; i += 1
    idx_ref = refs[i]; i += 1
    q_ref = refs[i]; i += 1
    k_ref = refs[i]; i += 1
    v_ref = refs[i]; i += 1
    sp_ref = None
    if windowed:
        sp_ref = refs[i]; i += 1
    ks_ref = vs_ref = None
    if quant:
        ks_ref = refs[i]; i += 1
        vs_ref = refs[i]; i += 1
    o_ref = refs[i]; i += 1
    acc_ref, m_ref, l_ref = refs[i:]

    bh = pl.program_id(0)
    j = pl.program_id(1)
    nk = pl.num_programs(1)
    b = bh // hkv
    group = q_ref.shape[2]

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    cursor = idx_ref[b]
    if windowed:
        # ring slots carry their global position (-1 = unwritten); band
        # semantics are recovered from positions, never from slot order
        sp = sp_ref[0]  # [block_rows] int32
        allow = (sp >= 0) & (sp <= cursor)
        band = sp > cursor - window
        if sinks:
            band |= sp < sinks
        allow &= band
        allow = jnp.broadcast_to(allow[None, :], (group, block_rows))
    else:
        pos = j * block_rows + jax.lax.broadcasted_iota(
            jnp.int32, (group, block_rows), 1)
        allow = pos <= cursor
    if paged:
        allow &= pt_ref[b, j] >= 0  # unbound page: every row dead

    def _body():
        q = q_ref[0, 0]  # [group, D]
        k = k_ref[0, :, 0]  # [block_rows, D]
        v = v_ref[0, :, 0]
        if quant:
            k = k.astype(jnp.float32) * ks_ref[0, :, 0][:, None]
            v = v.astype(jnp.float32) * vs_ref[0, :, 0][:, None]
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)  # [group, block_rows]
        p, corr, m_new, l_new = online_softmax_update(
            s, m_ref[:, 0], l_ref[:, 0], mask=allow)
        acc_ref[:] = acc_ref[:] * corr[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    # dead blocks (above every cursor / out of band / unbound page)
    # skip the MXU entirely — this is where decode cost becomes
    # O(live tokens) instead of O(reserved rows)
    pl.when(jnp.any(allow))(_body)

    @pl.when(j == nk - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0, 0] = (acc_ref[:] / l).astype(o_ref.dtype)


def _pad_rows(x, block, fill=0):
    pad = -x.shape[1] % block
    if pad:
        cfg = [(0, 0)] * x.ndim
        cfg[1] = (0, pad)
        x = jnp.pad(x, cfg, constant_values=fill)
    return x


# ---------------------------------------------------------------------------
# dense slot cache
# ---------------------------------------------------------------------------


@functools.partial(
    jax.jit,
    static_argnames=("window", "sinks", "block_k", "impl"),
)
def _flash_decode_impl(q, k, v, idx, slot_pos, k_scale, v_scale,
                       window, sinks, block_k, impl):
    qh, b, h, d = _gqa_fold(q)
    hkv = k.shape[2]
    group = _group_dims(h, hkv)
    r = k.shape[1]
    scale = 1.0 / (d ** 0.5)
    block_k = min(block_k, r)
    idx = idx.astype(jnp.int32)

    if impl == "xla":
        q4 = qh.reshape(b, hkv, group, d).astype(jnp.float32) * scale
        nb = -(-r // block_k)

        def get_block(j):
            kb = jax.lax.dynamic_slice_in_dim(k, j * block_k, block_k, 1)
            vb = jax.lax.dynamic_slice_in_dim(v, j * block_k, block_k, 1)
            ks = vs = None
            if k_scale is not None:
                ks = jax.lax.dynamic_slice_in_dim(
                    k_scale, j * block_k, block_k, 1)
                vs = jax.lax.dynamic_slice_in_dim(
                    v_scale, j * block_k, block_k, 1)
            return _dequant(kb, ks), _dequant(vb, vs)

        def get_mask(j):
            if window is None:
                pos = j * block_k + jnp.arange(block_k)
                return pos[None, :] <= idx[:, None]
            sp = jax.lax.dynamic_slice_in_dim(
                slot_pos, j * block_k, block_k, 1)
            qg = idx[:, None]
            allow = (sp >= 0) & (sp <= qg)
            band = sp > qg - window
            if sinks:
                band |= sp < sinks
            return allow & band

        if r % block_k:  # pad once so the loop's slices are uniform
            k = _pad_rows(k, block_k)
            v = _pad_rows(v, block_k)
            if slot_pos is not None:
                slot_pos = _pad_rows(slot_pos, block_k, fill=-1)
            if k_scale is not None:
                k_scale = _pad_rows(k_scale, block_k)
                v_scale = _pad_rows(v_scale, block_k)
        out = _xla_block_walk(q4, idx, nb, block_k, get_block, get_mask)
        return out.reshape(b, 1, h, d).astype(q.dtype)

    # pallas / interpret: pad the row axis to whole blocks (pad slot_pos
    # with -1 = never attendable; pad positions exceed any cursor)
    kp = _pad_rows(k, block_k)
    vp = _pad_rows(v, block_k)
    nb = kp.shape[1] // block_k
    q4 = qh.reshape(b, hkv, group, d)
    windowed = window is not None
    quant = k_scale is not None

    in_specs = [
        pl.BlockSpec((1, 1, group, d), lambda bh, j, idx: (bh // hkv, bh % hkv, 0, 0)),
        pl.BlockSpec((1, block_k, 1, d), lambda bh, j, idx: (bh // hkv, j, bh % hkv, 0)),
        pl.BlockSpec((1, block_k, 1, d), lambda bh, j, idx: (bh // hkv, j, bh % hkv, 0)),
    ]
    args = [q4, kp, vp]
    if windowed:
        in_specs.append(
            pl.BlockSpec((1, block_k), lambda bh, j, idx: (bh // hkv, j)))
        args.append(_pad_rows(slot_pos, block_k, fill=-1).astype(jnp.int32))
    if quant:
        spec = pl.BlockSpec(
            (1, block_k, 1), lambda bh, j, idx: (bh // hkv, j, bh % hkv))
        in_specs += [spec, spec]
        args += [_pad_rows(k_scale, block_k).astype(jnp.float32),
                 _pad_rows(v_scale, block_k).astype(jnp.float32)]

    kernel = functools.partial(
        _decode_kernel, scale=scale, window=window, sinks=sinks, hkv=hkv,
        block_rows=block_k, windowed=windowed, quant=quant, paged=False)
    out = pl.pallas_call(
        lambda *refs: kernel(refs),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b * hkv, nb),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, 1, group, d), lambda bh, j, idx: (bh // hkv, bh % hkv, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((group, d), jnp.float32),
                pltpu.VMEM((group, _LANES), jnp.float32),
                pltpu.VMEM((group, _LANES), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, hkv, group, d), q.dtype),
        interpret=impl == "interpret",
    )(idx, *args)
    return out.reshape(b, 1, h, d)


def flash_decode(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    idx: jax.Array,
    *,
    slot_pos: jax.Array | None = None,
    window: int | None = None,
    sinks: int = 0,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
    block_k: int = 128,
    impl: str | None = None,
) -> jax.Array:
    """Flash decode over a dense slot cache.

    ``q`` [B, 1, H, D] (ONE query row per slot), ``k``/``v``
    [B, R, Hkv, D] (the slot cache AFTER this step's write), ``idx``
    [B] int32 per-slot cursors (the position of this step's token).
    Plain caches attend positions ``<= idx`` with KV blocks beyond the
    cursor skipped; windowed rings pass ``slot_pos`` [B, R] (+
    ``window``/``sinks``) and the band mask runs over ring slots.
    Quantized caches pass ``k_scale``/``v_scale`` [B, R, Hkv] — dequant
    happens inside the kernel.  → [B, 1, H, D]; slots with nothing
    attendable return exactly 0.
    """
    _validate(window, sinks, slot_pos, k_scale, v_scale)
    return _flash_decode_impl(
        q, k, v, idx, slot_pos, k_scale, v_scale,
        window=window, sinks=sinks, block_k=block_k,
        impl=resolve_decode_impl(impl))


# ---------------------------------------------------------------------------
# paged block pool
# ---------------------------------------------------------------------------


@functools.partial(
    jax.jit,
    static_argnames=("window", "sinks", "impl"),
)
def _flash_decode_paged_impl(q, k_pool, v_pool, page_table, idx, slot_pos,
                             k_scale, v_scale, window, sinks, impl):
    qh, b, h, d = _gqa_fold(q)
    nb_pool, bs, hkv, _ = k_pool.shape
    group = _group_dims(h, hkv)
    pages = page_table.shape[1]
    scale = 1.0 / (d ** 0.5)
    idx = idx.astype(jnp.int32)
    pt = page_table.astype(jnp.int32)

    if impl == "xla":
        q4 = qh.reshape(b, hkv, group, d).astype(jnp.float32) * scale

        def get_block(j):
            blk = jnp.maximum(pt[:, j], 0)
            kb, vb = k_pool[blk], v_pool[blk]  # [B, bs, Hkv, D]
            ks = vs = None
            if k_scale is not None:
                ks, vs = k_scale[blk], v_scale[blk]
            return _dequant(kb, ks), _dequant(vb, vs)

        def get_mask(j):
            bound = pt[:, j] >= 0
            if window is None:
                pos = j * bs + jnp.arange(bs)
                allow = pos[None, :] <= idx[:, None]
            else:
                sp = jax.lax.dynamic_slice_in_dim(slot_pos, j * bs, bs, 1)
                qg = idx[:, None]
                allow = (sp >= 0) & (sp <= qg)
                band = sp > qg - window
                if sinks:
                    band |= sp < sinks
                allow &= band
            return allow & bound[:, None]

        out = _xla_block_walk(q4, idx, pages, bs, get_block, get_mask)
        return out.reshape(b, 1, h, d).astype(q.dtype)

    q4 = qh.reshape(b, hkv, group, d)
    windowed = window is not None
    quant = k_scale is not None

    def kv_map(bh, j, pt, idx):
        # THE page-table walk: the physical block this grid step DMAs
        # is named by the slot's page table (clamped for -1; the kernel
        # masks the whole block via pt[b, j] < 0)
        return (jnp.maximum(pt[bh // hkv, j], 0), 0, bh % hkv, 0)

    in_specs = [
        pl.BlockSpec((1, 1, group, d),
                     lambda bh, j, pt, idx: (bh // hkv, bh % hkv, 0, 0)),
        pl.BlockSpec((1, bs, 1, d), kv_map),
        pl.BlockSpec((1, bs, 1, d), kv_map),
    ]
    args = [q4, k_pool, v_pool]
    if windowed:
        in_specs.append(
            pl.BlockSpec((1, bs), lambda bh, j, pt, idx: (bh // hkv, j)))
        args.append(slot_pos.astype(jnp.int32))
    if quant:
        spec = pl.BlockSpec(
            (1, bs, 1),
            lambda bh, j, pt, idx: (jnp.maximum(pt[bh // hkv, j], 0), 0,
                                    bh % hkv))
        in_specs += [spec, spec]
        args += [k_scale.astype(jnp.float32), v_scale.astype(jnp.float32)]

    kernel = functools.partial(
        _decode_kernel, scale=scale, window=window, sinks=sinks, hkv=hkv,
        block_rows=bs, windowed=windowed, quant=quant, paged=True)
    out = pl.pallas_call(
        lambda *refs: kernel(refs),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b * hkv, pages),
            in_specs=in_specs,
            out_specs=pl.BlockSpec(
                (1, 1, group, d),
                lambda bh, j, pt, idx: (bh // hkv, bh % hkv, 0, 0)),
            scratch_shapes=[
                pltpu.VMEM((group, d), jnp.float32),
                pltpu.VMEM((group, _LANES), jnp.float32),
                pltpu.VMEM((group, _LANES), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((b, hkv, group, d), q.dtype),
        interpret=impl == "interpret",
    )(pt, idx, *args)
    return out.reshape(b, 1, h, d)


def flash_decode_paged(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    page_table: jax.Array,
    idx: jax.Array,
    *,
    slot_pos: jax.Array | None = None,
    window: int | None = None,
    sinks: int = 0,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
    impl: str | None = None,
) -> jax.Array:
    """Flash decode over the paged block pool.

    ``q`` [B, 1, H, D]; ``k_pool``/``v_pool`` [NB, bs, Hkv, D] (the
    shared per-layer pools AFTER this step's write); ``page_table``
    [B, P] int32 (-1 = unbound: the block is skipped, not gathered);
    ``idx`` [B] cursors.  Windowed rings pass ``slot_pos`` [B, P*bs];
    quantized pools pass ``k_scale``/``v_scale`` [NB, bs, Hkv].  The
    page indirection stays DATA (scalar-prefetched index maps), so one
    compiled kernel serves every allocation decision — the engine's
    ONE-decode-compile invariant extends into the kernel.
    """
    _validate(window, sinks, slot_pos, k_scale, v_scale)
    return _flash_decode_paged_impl(
        q, k_pool, v_pool, page_table, idx, slot_pos, k_scale, v_scale,
        window=window, sinks=sinks, impl=resolve_decode_impl(impl))
