"""Checkpoint atomicity: a death mid-write NEVER costs the previous
checkpoint.

Protocol under test (train/checkpoint.py): orbax streams into
``step_<n>.tmp.<pid>`` and the staging dir is renamed into place only
once fully written — so ``latest_step`` can only ever see complete
checkpoints.  Fast tier simulates the two death points (mid-write,
write-done-rename-pending) in-process; the slow tier does it for real
with ``kill -9`` on a subprocess.  Parametrized over a dense host
pytree and a ZeRO-1 sharded ``TrainState`` (flat data-sharded optimizer
leaves), since orbax writes those through different codepaths.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

from fluxdistributed_tpu import data_mesh, faults, optim
from fluxdistributed_tpu.parallel import zero1
from fluxdistributed_tpu.train import (
    latest_step,
    load_checkpoint,
    save_checkpoint,
    wait_for_pending,
)
import fluxdistributed_tpu.train.checkpoint as ck_mod

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _dense_state(value=1.0):
    return {"params": {"w": np.full((4, 3), value, np.float32),
                       "b": np.full((10,), value, np.float32)},
            "step": np.asarray(7, np.int32)}


def _sharded_state(value=1.0):
    import jax

    params = {"w": np.full((4, 3), value, np.float32),
              "b": np.full((10,), value, np.float32)}
    state, _ = zero1.zero1_state(
        jax.tree.map(lambda x: x, params), optim.adam(1e-3), data_mesh())
    return state


STATES = {"dense": _dense_state, "sharded": _sharded_state}


@pytest.fixture(params=sorted(STATES))
def make_state(request):
    return STATES[request.param]


def _leaves(tree):
    import jax

    return [np.asarray(x) for x in jax.tree.leaves(tree)]


def test_interrupted_rename_leaves_previous_loadable(
        tmp_path, make_state, monkeypatch):
    """Death between write-finish and publish-rename: the staging dir is
    complete but uncommitted — latest_step still answers step 1."""
    save_checkpoint(make_state(1.0), str(tmp_path), 1)

    def die(tmp, final):
        raise RuntimeError("simulated kill between write and rename")

    monkeypatch.setattr(ck_mod, "_commit_rename", die)
    with pytest.raises(RuntimeError, match="simulated kill"):
        save_checkpoint(make_state(2.0), str(tmp_path), 2)
    monkeypatch.undo()

    assert latest_step(str(tmp_path)) == 1
    names = os.listdir(tmp_path)
    assert not any(n == "step_2" for n in names)
    assert any(".tmp." in n for n in names), "staging dir left (harmless)"
    restored = load_checkpoint(str(tmp_path), make_state(1.0))
    for got, want in zip(_leaves(restored), _leaves(make_state(1.0))):
        np.testing.assert_allclose(got, want)
    # the next save of the same step sweeps the stale staging dir and
    # commits clean
    save_checkpoint(make_state(3.0), str(tmp_path), 2)
    assert latest_step(str(tmp_path)) == 2
    assert not any(".tmp." in n for n in os.listdir(tmp_path))


def test_interrupted_write_leaves_previous_loadable(
        tmp_path, make_state, monkeypatch):
    """Death MID-write: only partial staging garbage exists — never a
    committed half-checkpoint."""
    save_checkpoint(make_state(1.0), str(tmp_path), 1)

    class DyingCkptr:
        def save(self, path, state):
            os.makedirs(path, exist_ok=True)
            with open(os.path.join(path, "partial"), "w") as f:
                f.write("garbage")
            raise RuntimeError("simulated kill mid-write")

        def wait_until_finished(self):
            pass

    monkeypatch.setattr(ck_mod.ocp, "StandardCheckpointer", DyingCkptr)
    with pytest.raises(RuntimeError, match="mid-write"):
        save_checkpoint(make_state(2.0), str(tmp_path), 2)
    monkeypatch.undo()

    assert latest_step(str(tmp_path)) == 1
    restored = load_checkpoint(str(tmp_path), make_state(1.0))
    for got, want in zip(_leaves(restored), _leaves(make_state(1.0))):
        np.testing.assert_allclose(got, want)


def test_async_save_commits_at_wait(tmp_path, make_state):
    """block=False publishes at wait_for_pending — after the drain the
    step dir exists, is complete, and no staging dir remains."""
    save_checkpoint(make_state(5.0), str(tmp_path), 3, block=False)
    wait_for_pending()
    assert latest_step(str(tmp_path)) == 3
    assert not any(".tmp." in n for n in os.listdir(tmp_path))
    restored = load_checkpoint(str(tmp_path), make_state(1.0))
    for got, want in zip(_leaves(restored), _leaves(make_state(5.0))):
        np.testing.assert_allclose(got, want)


def test_overwrite_same_step_swaps_atomically(tmp_path, make_state):
    save_checkpoint(make_state(1.0), str(tmp_path), 1)
    save_checkpoint(make_state(9.0), str(tmp_path), 1)
    assert sorted(os.listdir(tmp_path)) == ["step_1"]
    restored = load_checkpoint(str(tmp_path), make_state(0.0))
    for got, want in zip(_leaves(restored), _leaves(make_state(9.0))):
        np.testing.assert_allclose(got, want)
    with pytest.raises(FileExistsError):
        save_checkpoint(make_state(2.0), str(tmp_path), 1, overwrite=False)


def test_failed_async_commit_does_not_wedge_later_saves(tmp_path):
    """A commit that REFUSES (overwrite=False on an existing step)
    surfaces once at wait_for_pending and is then dropped — it must not
    poison the pending list and wedge every later save."""
    save_checkpoint(_dense_state(1.0), str(tmp_path), 1)
    save_checkpoint(_dense_state(2.0), str(tmp_path), 1,
                    overwrite=False, block=False)
    with pytest.raises(FileExistsError):
        wait_for_pending()
    wait_for_pending()  # drained: no re-raise
    save_checkpoint(_dense_state(3.0), str(tmp_path), 2)
    assert latest_step(str(tmp_path)) == 2
    # step 1 kept its original content (the refused save changed nothing)
    restored = load_checkpoint(str(tmp_path), _dense_state(0.0), step=1)
    np.testing.assert_allclose(_leaves(restored)[0],
                               _leaves(_dense_state(1.0))[0])


def test_checkpoint_save_retries_injected_transient(tmp_path):
    """The checkpoint-I/O with_retries boundary: one injected OSError
    costs a backoff, not the checkpoint."""
    faults.install_plan(
        faults.FaultPlan().fail(
            "checkpoint_save", times=1,
            exc=lambda: OSError("injected disk hiccup")))
    try:
        save_checkpoint(_dense_state(4.0), str(tmp_path), 1)
    finally:
        faults.clear_plan()
    assert latest_step(str(tmp_path)) == 1
    restored = load_checkpoint(str(tmp_path), _dense_state(0.0))
    np.testing.assert_allclose(_leaves(restored)[0],
                               _leaves(_dense_state(4.0))[0])


def test_checkpoint_load_retries_injected_transient(tmp_path):
    save_checkpoint(_dense_state(4.0), str(tmp_path), 1)
    faults.install_plan(
        faults.FaultPlan().fail(
            "checkpoint_load", times=1,
            exc=lambda: OSError("injected read hiccup")))
    try:
        restored = load_checkpoint(str(tmp_path), _dense_state(0.0))
    finally:
        faults.clear_plan()
    np.testing.assert_allclose(_leaves(restored)[0],
                               _leaves(_dense_state(4.0))[0])


# ---------------------------------------------------------------------------
# the real thing: kill -9 (slow tier)
# ---------------------------------------------------------------------------

_CHILD = r"""
import os, sys, time
import numpy as np
from fluxdistributed_tpu.mesh import force_host_devices
force_host_devices(8)
import fluxdistributed_tpu.train.checkpoint as ck

directory = sys.argv[1]
mode = sys.argv[2]
state1 = {"w": np.full((64, 64), 1.0, np.float32)}
state2 = {"w": np.full((64, 64), 2.0, np.float32)}
ck.save_checkpoint(state1, directory, 1)

if mode == "rename":
    orig = ck._commit_rename
    def pending(tmp, final):
        print("KILL_ME_NOW", flush=True)
        time.sleep(120)
        orig(tmp, final)
    ck._commit_rename = pending
else:
    import orbax.checkpoint as ocp
    class Partial:
        def save(self, path, state):
            os.makedirs(path, exist_ok=True)
            open(os.path.join(path, "partial"), "w").write("junk")
            print("KILL_ME_NOW", flush=True)
            time.sleep(120)
        def wait_until_finished(self):
            pass
    ck.ocp.StandardCheckpointer = Partial
ck.save_checkpoint(state2, directory, 2)
"""


@pytest.mark.slow
@pytest.mark.parametrize("mode", ["write", "rename"])
def test_kill_9_mid_write_previous_checkpoint_survives(tmp_path, mode):
    child = tmp_path / "child.py"
    child.write_text(_CHILD)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    p = subprocess.Popen(
        [sys.executable, str(child), str(tmp_path / "ck"), mode],
        stdout=subprocess.PIPE, text=True, env=env, cwd=REPO)
    try:
        deadline = time.monotonic() + 240
        for line in p.stdout:
            if "KILL_ME_NOW" in line:
                break
            if time.monotonic() > deadline:
                raise TimeoutError("child never reached the kill point")
        os.kill(p.pid, signal.SIGKILL)
        p.wait(timeout=30)
    finally:
        if p.poll() is None:
            p.kill()
    assert p.returncode == -signal.SIGKILL
    ck_dir = str(tmp_path / "ck")
    assert latest_step(ck_dir) == 1, os.listdir(ck_dir)
    restored = load_checkpoint(ck_dir, {"w": np.zeros((64, 64), np.float32)})
    np.testing.assert_allclose(restored["w"], 1.0)
