"""Minimal torch ConvNeXt with official parameter names.

Test fixture only: the ConvNeXt architecture (Liu et al. 2022) with
exactly the state_dict layout the official facebookresearch/ConvNeXt
code (and timm) exports — ``downsample_layers.{s}``,
``stages.{s}.{b}.{dwconv,norm,pwconv1,pwconv2,gamma}``, ``norm``,
``head`` — consumed by ``models/torch_import.py::import_torch_convnext``.
Computes in channels-last internally so plain nn.LayerNorm matches the
official channels_first/last LayerNorm numerics.
"""

from __future__ import annotations

import torch
import torch.nn as nn


class Block(nn.Module):
    def __init__(self, dim):
        super().__init__()
        self.dwconv = nn.Conv2d(dim, dim, 7, padding=3, groups=dim)
        self.norm = nn.LayerNorm(dim, eps=1e-6)
        self.pwconv1 = nn.Linear(dim, 4 * dim)
        self.act = nn.GELU()
        self.pwconv2 = nn.Linear(4 * dim, dim)
        self.gamma = nn.Parameter(1e-6 * torch.ones(dim))

    def forward(self, x):  # x: (N, H, W, C)
        shortcut = x
        x = self.dwconv(x.permute(0, 3, 1, 2)).permute(0, 2, 3, 1)
        x = self.norm(x)
        x = self.pwconv2(self.act(self.pwconv1(x)))
        return shortcut + self.gamma * x


class TorchConvNeXt(nn.Module):
    def __init__(self, depths=(1, 1, 2, 1), dims=(16, 32, 64, 128), num_classes=10):
        super().__init__()
        self.downsample_layers = nn.ModuleList()
        self.downsample_layers.append(nn.Sequential(
            nn.Conv2d(3, dims[0], 4, 4), nn.LayerNorm(dims[0], eps=1e-6),
        ))
        for s in range(3):
            self.downsample_layers.append(nn.Sequential(
                nn.LayerNorm(dims[s], eps=1e-6),
                nn.Conv2d(dims[s], dims[s + 1], 2, 2),
            ))
        self.stages = nn.ModuleList(
            nn.Sequential(*[Block(dims[s]) for _ in range(depths[s])])
            for s in range(4)
        )
        self.norm = nn.LayerNorm(dims[-1], eps=1e-6)
        self.head = nn.Linear(dims[-1], num_classes)

    def forward(self, x):  # x: (N, C, H, W)
        for s in range(4):
            if s == 0:
                x = self.downsample_layers[0][0](x).permute(0, 2, 3, 1)
                x = self.downsample_layers[0][1](x)
            else:
                x = self.downsample_layers[s][0](x)
                x = self.downsample_layers[s][1](x.permute(0, 3, 1, 2)).permute(0, 2, 3, 1)
            x = self.stages[s](x)
        x = self.norm(x.mean(dim=(1, 2)))
        return self.head(x)
