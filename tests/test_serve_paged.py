"""Paged KV-cache layout: block pool, chunked prefill, prefix reuse.

The golden invariant carries over from the dense engine: every request
served through the paged layout must reproduce sequential
``models.generate`` token for token, with page-table churn compiling
NOTHING (the ONE-decode-compile invariant, asserted via jit cache
stats).  The host-side allocator (``serve/cache_layout.py``) is pure
Python, so refcount/free/reservation accounting and the scheduler's
chunk interleave are tested without touching jax; the compile-bearing
parity matrix for windowed/GQA/learned-position configs rides the slow
tier (tests/conftest budget policy).
"""

from __future__ import annotations

import numpy as np
import pytest

from fluxdistributed_tpu.serve import QueueFull, Request, Scheduler
from fluxdistributed_tpu.serve.cache_layout import (
    BlockPool, PagedLayout, prefix_digests)

# ---------------------------------------------------------------- host-only


def test_prefix_digests_chain():
    toks = [3, 1, 4, 1, 5, 9, 2, 6]
    d4 = prefix_digests(toks, 4)
    assert len(d4) == 2
    # a digest commits to the WHOLE prefix, not just its own block
    other = prefix_digests([9, 9, 9, 9, 5, 9, 2, 6], 4)
    assert d4[1] != other[1]
    assert prefix_digests(toks[:4], 4) == d4[:1]
    assert prefix_digests(toks[:3], 4) == []  # partial blocks never hash


def test_block_pool_refcount_lifecycle():
    p = BlockPool(4)
    a, b = p.alloc(), p.alloc()
    assert p.stats()["kv_blocks_active"] == 2
    p.release(a)
    assert p.stats()["kv_blocks_free"] == 3  # unregistered → straight back
    # registered blocks become reclaimable-cached at ref 0, not free
    p.register(b, b"digest")
    p.release(b)
    s = p.stats()
    assert s["kv_blocks_cached"] == 1 and s["kv_blocks_free"] == 3
    assert p.available() == 4
    # claiming the cached digest revives the block with a reference
    assert p.claim([b"digest"]) == [b]
    assert p.stats()["kv_blocks_active"] == 1
    assert p.hits == 1


def test_block_pool_eviction_under_pressure():
    p = BlockPool(2)
    a = p.alloc()
    p.register(a, b"d1")
    p.release(a)          # cached, reclaimable
    b = p.alloc()         # free list
    c = p.alloc()         # must EVICT the cached block
    assert {b, c} == {0, 1} and p.evictions == 1
    assert p.claim([b"d1"]) == []  # evicted digest is gone
    with pytest.raises(RuntimeError, match="exhausted"):
        p.alloc()


def test_paged_layout_reservation_and_release():
    lay = PagedLayout(max_slots=2, rows_per_slot=32, block_size=4,
                      num_blocks=10)
    assert lay.pages_for(9) == 3
    assert lay.pages_for(1000) == 8  # capped at r_pad
    prompt = list(range(6))
    assert lay.can_admit(prompt, 10)  # needs 4 blocks
    assert lay.admit(0, prompt, 10) == 0  # no prefix cache → start at 0
    assert lay._promised[0] == 4
    binds = lay.alloc_rows(0, 6)
    assert [pg for pg, _ in binds] == [0, 1] and lay._promised[0] == 2
    # a second worst-case admission that would overcommit must wait:
    # 10 - 2 allocated - 2 promised = 6 available-for-new
    assert not lay.can_admit(list(range(8)), 21)   # needs 8 > 6
    assert lay.can_admit(list(range(8)), 16)       # needs 6 == 6
    lay.release(0)
    assert lay.pool.stats()["kv_blocks_free"] == 10
    assert lay._promised[0] == 0 and lay.slot_pages[0] == [-1] * 8


def test_paged_layout_prefix_claim_and_register():
    lay = PagedLayout(max_slots=2, rows_per_slot=16, block_size=4,
                      num_blocks=8, prefix_cache=True)
    sys_prompt = [7, 1, 4, 9, 2, 6, 5, 3]  # two full blocks
    lay.admit(0, sys_prompt + [11], 4)
    lay.alloc_rows(0, 9)
    lay.register_prompt(0, sys_prompt + [11])
    lay.release(0)
    assert lay.pool.stats()["kv_blocks_cached"] == 2
    # a new admission sharing the prefix starts AFTER the cached blocks
    start = lay.admit(1, sys_prompt + [13], 4)
    assert start == 8
    assert lay.slot_pages[1][:2] == lay.slot_pages[0][:2] or \
        lay.slot_pages[1][0] >= 0
    # the last-full-block cap: an exactly-block-aligned prompt keeps its
    # final block private so the first-token logits can be recomputed
    # without ever writing a shared block
    start = lay.admit(0, list(sys_prompt), 4)
    assert start == 4
    lay.release(0)
    lay.release(1)


class _FakeChunkEngine:
    """Pure-python incremental engine: 2 chunks of 4 tokens per call,
    exercising the scheduler's chunk interleave, admission gating, and
    cancel teardown without compiling anything."""

    max_slots = 2
    prefill_incremental = True
    prefill_chunk = 4

    def __init__(self):
        self.reset_calls = []
        self.admitted = []

    def validate_request(self, prompt_len, max_new_tokens):
        pass

    def can_admit(self, prompt, max_new_tokens):
        return True

    def prefill_begin(self, slot, tokens, temperature, key,
                      max_new_tokens=None, rid=None):
        self.admitted.append(slot)
        return {"slot": slot, "pos": 0, "plen": len(tokens), "rid": rid}

    def prefill_step(self, st):
        n = min(self.prefill_chunk, st["plen"] - st["pos"])
        st["pos"] += n
        done = st["pos"] >= st["plen"]
        return (7 if done else None), n, self.prefill_chunk

    def step_decode(self):
        return [1] * self.max_slots

    def reset_slot(self, slot):
        self.reset_calls.append(slot)

    def compile_stats(self):
        return {"decode_compiles": 1, "prefill_compiles": 1,
                "insert_compiles": 0}


def test_scheduler_interleaves_chunks_round_robin():
    eng = _FakeChunkEngine()
    sched = Scheduler(eng, max_queue=8)
    long_req = Request(prompt=list(range(12)), max_new_tokens=2)  # 3 chunks
    short_req = Request(prompt=[1, 2], max_new_tokens=2)          # 1 chunk
    sched.submit(long_req)
    sched.submit(short_req)
    sched.step()  # admit both, run ONE chunk (long's first)
    assert long_req.state == "prefilling" and short_req.state == "prefilling"
    sched.step()  # round-robin: SHORT's chunk → its first token
    assert short_req.state == "active" and len(short_req.generated) == 1
    assert long_req.state == "prefilling"
    sched.run_until_idle()
    assert long_req.state == "done" and short_req.state == "done"
    m = sched.metrics()
    # 3 long chunks + 1 short chunk, each padded to the chunk size
    assert m["prefill_chunks"] == 4
    assert m["prefill_padded_tokens"] == 16
    assert m["prefill_tokens"] == 14


def test_scheduler_admission_waits_on_can_admit():
    eng = _FakeChunkEngine()
    gate = {"open": False}
    eng.can_admit = lambda prompt, max_new: gate["open"]
    sched = Scheduler(eng, max_queue=8)
    req = Request(prompt=[1, 2], max_new_tokens=2)
    sched.submit(req)
    sched.step()
    # pool "exhausted": the head QUEUES instead of being admitted
    assert req.state == "queued" and sched.queue_depth == 1
    assert sched.active_slots == 0
    gate["open"] = True
    sched.step()
    assert req.state in ("prefilling", "active")
    sched.run_until_idle()
    assert req.state == "done"


def test_scheduler_cancel_queued_and_active():
    eng = _FakeChunkEngine()
    sched = Scheduler(eng, max_queue=8)
    r1 = Request(prompt=list(range(8)), max_new_tokens=4)
    r2 = Request(prompt=[1], max_new_tokens=4)
    sched.submit(r1)
    assert sched.cancel(r1) is True  # still queued: gone immediately
    assert sched.queue_depth == 0 and r1.done.is_set()
    sched.submit(r2)
    sched.step()  # admitted (prefilling or active)
    assert sched.cancel(r2) is False  # driver tears it down next tick
    sched.step()
    assert r2.state == "done" and r2.done.is_set()
    assert eng.reset_calls == [0]  # engine released the slot
    assert sched.metrics()["requests_cancelled"] == 2
    assert sched.idle


def test_queue_full_unchanged_with_gating():
    eng = _FakeChunkEngine()
    eng.can_admit = lambda *a: False  # nothing ever admitted
    sched = Scheduler(eng, max_queue=2)
    sched.submit(Request(prompt=[1], max_new_tokens=1))
    sched.submit(Request(prompt=[2], max_new_tokens=1))
    with pytest.raises(QueueFull):
        sched.submit(Request(prompt=[3], max_new_tokens=1))


# ---------------------------------------------------------------- engine

def _make(vocab=32, **mk):
    import jax
    import jax.numpy as jnp

    from fluxdistributed_tpu.models import lm_tiny

    model = lm_tiny(vocab=vocab, depth=2, dim=64, mlp_dim=128,
                    dtype=jnp.float32, **mk)
    params = model.init(
        jax.random.PRNGKey(0), np.zeros((1, 2), np.int32), train=False
    )["params"]
    return model, params


def _ref(model, params, prompt, new):
    from fluxdistributed_tpu.models import generate

    dm = model.clone(decode=True)
    out = generate(dm, params, np.asarray([prompt], np.int32),
                   total_len=len(prompt) + new)
    return list(np.asarray(out)[0])


def _paged(model, params, **kw):
    from fluxdistributed_tpu.serve import LMEngine

    kw.setdefault("max_slots", 3)
    kw.setdefault("max_len", 32)
    kw.setdefault("kv_block_size", 4)
    kw.setdefault("prefill_chunk", 4)
    return LMEngine(model, params, layout="paged", **kw)


def test_paged_engine_validation():
    from fluxdistributed_tpu.serve import LMEngine

    model, params = _make()
    with pytest.raises(ValueError, match="paged"):
        LMEngine(model, params, max_slots=1, max_len=8, prefix_cache=True)
    wmodel, wparams = _make(window=8, sinks=2)
    with pytest.raises(ValueError, match="window"):
        _paged(wmodel, wparams, prefix_cache=True)
    with pytest.raises(ValueError, match="layout"):
        LMEngine(model, params, max_slots=1, max_len=8, layout="blocky")
    # a request whose worst case exceeds the WHOLE pool is rejected at
    # validation with the fix spelled out (not admitted and wedged)
    eng = _paged(model, params, kv_blocks=4)
    with pytest.raises(ValueError, match="kv_blocks >= 8"):
        eng.validate_request(4, 28)
    eng.validate_request(4, 8)  # within pool: fine


def test_paged_parity_prefix_reuse_one_compile():
    """The fast-tier acceptance core: paged + chunked + prefix-hit
    parity vs sequential generate() under interleaved admissions, with
    the whole program pool pinned at ONE compile each and the block
    accounting clean after the drain."""
    model, params = _make()
    engine = _paged(model, params, prefix_cache=True)
    stats = engine.compile_stats()
    if stats["decode_compiles"] < 0:
        pytest.skip("this jax exposes no jit cache stats")
    sched = Scheduler(engine, max_queue=16)
    sys_prompt = [7, 1, 4, 9, 2, 6, 5, 3]  # two full blocks
    prompts = [sys_prompt + [11], [5, 3],       # miss, miss
               sys_prompt + [13, 8],            # 2-block prefix HIT
               list(sys_prompt),                # aligned-prompt hit (cap)
               sys_prompt[:4] + [20, 21]]       # 1-block prefix hit
    reqs = [Request(prompt=p, max_new_tokens=7) for p in prompts]
    sched.submit(reqs[0]); sched.submit(reqs[1])
    sched.step(); sched.step()
    for r in reqs[2:]:
        sched.submit(r)
    sched.run_until_idle()
    for r, p in zip(reqs, prompts):
        assert r.tokens == _ref(model, params, p, 7), p
    m = sched.metrics()
    assert m["prefix_cache_hits"] > 0
    # page-table churn (admissions, growth, frees, prefix claims)
    # compiled NOTHING beyond the initial pool: one program each
    assert m["decode_compiles"] == 1
    assert m["prefill_compiles"] == 1  # the single chunk program
    cs = engine.compile_stats()
    assert cs["bind_compiles"] == 1 and cs["release_compiles"] == 1
    # accounting: nothing live after the drain; cached prefix blocks are
    # reclaimable, everything else is back on the free list
    ps = engine.pool_stats()
    assert ps["kv_blocks_active"] == 0
    assert ps["kv_blocks_free"] + ps["kv_blocks_cached"] == \
        ps["kv_blocks_total"]
    assert ps["kv_blocks_promised"] == 0


def test_dense_chunked_final_chunk_overshoot_parity():
    """A padded FINAL chunk whose window crosses max_len must not
    corrupt earlier KV rows: dynamic_update_slice clamps the write
    start back, so the engine shifts the chunk window instead
    (re-prefilling a few positions idempotently).  Regression: prompt
    17, chunk 8, max_len 20 — the last chunk starts at 16 and would
    clamp to 12, destroying rows 12-15."""
    from fluxdistributed_tpu.serve import LMEngine

    model, params = _make()
    engine = LMEngine(model, params, max_slots=2, max_len=20,
                      prefill_chunk=8)
    assert engine.prefill_incremental
    sched = Scheduler(engine, max_queue=4)
    prompt = list(np.random.default_rng(3).integers(0, 32, 17))
    req = Request(prompt=prompt, max_new_tokens=3)
    sched.generate_all([req])
    assert req.tokens == _ref(model, params, prompt, 3)


# ---------------------------------------------------------------- slow tier

@pytest.mark.slow
def test_paged_windowed_drift_parity():
    """Golden parity when one slot DECODES while many slots prefill:
    every decode tick used to drift the mid-prefill cursors and write
    garbage through their bound page tables, evicting in-band windowed
    keys once the drift outran the ring slack.  The slot_live write
    gate drops those writes, so any decode/prefill interleave holds
    parity.  Regression: window 4, chunk 2, 7 prompts prefilling
    round-robin behind 1 decoding request (gap ~ 7 ticks > slack 2)."""
    model, params = _make(window=4)
    engine = _paged(model, params, max_slots=8, max_len=24,
                    kv_block_size=2, prefill_chunk=2)
    sched = Scheduler(engine, max_queue=16)
    rng = np.random.default_rng(11)
    first = Request(prompt=list(rng.integers(0, 32, 3)), max_new_tokens=14)
    sched.submit(first)
    while first.state != "active":
        sched.step()
    rest = [Request(prompt=list(rng.integers(0, 32, 8)), max_new_tokens=4)
            for _ in range(7)]
    for r in rest:
        sched.submit(r)
    sched.run_until_idle()
    for r in [first] + rest:
        assert r.tokens == _ref(model, params, r.prompt,
                                r.max_new_tokens), r.prompt
    assert engine.compile_stats()["decode_compiles"] == 1


@pytest.mark.slow
@pytest.mark.parametrize("config", ["window_sinks", "gqa", "window_gqa"])
def test_paged_parity_matrix(config):
    """Golden parity for the remaining attention configs (the plain
    config rides the fast tier above)."""
    cfg = {"window_sinks": {"window": 8, "sinks": 2},
           "gqa": {"num_kv_heads": 2},
           "window_gqa": {"window": 6, "sinks": 1, "num_kv_heads": 2}}
    model, params = _make(**cfg[config])
    engine = _paged(model, params)
    sched = Scheduler(engine, max_queue=16)
    rng = np.random.default_rng(7)
    prompts = [list(rng.integers(0, 32, n)) for n in (3, 2, 5, 1, 9, 7)]
    reqs = [Request(prompt=p, max_new_tokens=9) for p in prompts]
    sched.submit(reqs[0]); sched.submit(reqs[1])
    sched.step(); sched.step()
    sched.submit(reqs[2]); sched.submit(reqs[3])
    sched.step()
    sched.submit(reqs[4]); sched.submit(reqs[5])
    sched.run_until_idle()
    for r, p in zip(reqs, prompts):
        assert r.tokens == _ref(model, params, p, 9), (config, p)
    assert engine.compile_stats()["decode_compiles"] == 1


@pytest.mark.slow
def test_paged_parity_learned_positions():
    model, params = _make(use_rope=False, max_len=24)
    engine = _paged(model, params, max_slots=2, max_len=24)
    sched = Scheduler(engine)
    prompts = [[5, 3, 7], [1, 2], [4, 4, 4, 1, 2, 3]]
    reqs = [Request(prompt=p, max_new_tokens=6) for p in prompts]
    sched.generate_all(reqs)
    for r, p in zip(reqs, prompts):
        assert r.tokens == _ref(model, params, p, 6)


@pytest.mark.slow
def test_dense_chunked_prefill_parity():
    """Chunked prefill is a layout-independent scheduler feature: the
    dense engine accumulates chunks into the batch-1 cache and splices
    at the end — same parity bar."""
    from fluxdistributed_tpu.serve import LMEngine

    model, params = _make(window=8, sinks=2)
    engine = LMEngine(model, params, max_slots=2, max_len=32,
                      prefill_chunk=4)
    assert engine.prefill_incremental
    sched = Scheduler(engine, max_queue=8)
    prompts = [[5, 3, 7, 2, 9, 1, 8], [28, 18], [4, 4, 4, 1, 2]]
    reqs = [Request(prompt=p, max_new_tokens=8) for p in prompts]
    sched.generate_all(reqs)
    for r, p in zip(reqs, prompts):
        assert r.tokens == _ref(model, params, p, 8)
    assert sched.metrics()["prefill_chunks"] > 0
    # windowed final-chunk overshoot (30 + padded chunk > max_len=32):
    # the shifted window must hold parity on the ring path too
    long = list(np.random.default_rng(5).integers(0, 32, 30))
    req = Request(prompt=long, max_new_tokens=2)
    sched.generate_all([req])
    assert req.tokens == _ref(model, params, long, 2)


@pytest.mark.slow
def test_block_accounting_after_eos_and_disconnect():
    """Blocks free on EOS and on client cancel (the HTTP disconnect
    path), and pool exhaustion backpressures instead of wedging."""
    model, params = _make()
    # tiny pool: 8 blocks of 4 rows — two 12-token-budget requests fill it
    engine = _paged(model, params, max_slots=3, kv_blocks=8)
    sched = Scheduler(engine, max_queue=16)
    # EOS: probe what the model emits so an EOS fires mid-decode
    probe = _ref(model, params, [5, 3], 4)
    r_eos = Request(prompt=[5, 3], max_new_tokens=8, eos_id=probe[3])
    sched.generate_all([r_eos])
    assert r_eos.generated[-1] == probe[3]
    ps = engine.pool_stats()
    assert ps["kv_blocks_active"] == 0 and ps["kv_blocks_promised"] == 0
    # disconnect: cancel an active request mid-decode → blocks come back
    r1 = Request(prompt=[1, 2, 3], max_new_tokens=9)
    r2 = Request(prompt=[9, 9], max_new_tokens=9)
    sched.submit(r1); sched.submit(r2)
    sched.step(); sched.step()
    assert engine.pool_stats()["kv_blocks_active"] > 0
    sched.cancel(r1)
    sched.cancel(r2)
    sched.step()  # driver services the teardown
    assert r1.done.is_set() and r2.done.is_set()
    ps = engine.pool_stats()
    assert ps["kv_blocks_active"] == 0
    assert ps["kv_blocks_free"] == ps["kv_blocks_total"]
    # exhaustion backpressure: three worst-case requests can't coexist
    # on 8 blocks; everyone still finishes with parity
    reqs = [Request(prompt=[i, i + 1], max_new_tokens=12) for i in range(4)]
    for r in reqs:
        sched.submit(r)
    saw_waiting = False
    while not sched.idle:
        sched.step()
        if sched.queue_depth > 0 and None in sched.slots:
            saw_waiting = True  # free slot + queued head = pool gating
    for i, r in enumerate(reqs):
        assert r.tokens == _ref(model, params, [i, i + 1], 12)
    assert saw_waiting
    assert sched.metrics()["requests_cancelled"] == 2


@pytest.mark.slow
def test_serve_cli_paged_flags():
    """bin/serve.py --lm --paged/--prefill-chunk/--prefix-cache builds a
    paged engine (the driver-CLI smoke for the new flags)."""
    import os
    import sys

    sys.path.insert(0, os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "bin"))
    import serve as serve_cli

    args = serve_cli.build_parser().parse_args(
        ["--lm", "--model", "lm_tiny", "--vocab", "64", "--max-slots", "2",
         "--max-len", "64", "--platform", "cpu", "--paged",
         "--kv-block-size", "8", "--kv-blocks", "12",
         "--prefill-chunk", "16", "--prefix-cache"])
    lm, sched = serve_cli.make_lm_app(args)
    eng = sched.engine
    try:
        assert eng.layout_name == "paged"
        assert eng.prefill_chunk == 16
        assert eng.layout.block_size == 8
        assert eng.layout.pool.num_blocks == 12
        assert eng.layout.prefix_enabled
        # one request through the full stack for good measure
        req = Request(prompt=list(range(20)), max_new_tokens=4)
        sched.submit(req)
        sched.run_until_idle()
        assert len(req.generated) == 4
    finally:
        lm.close()
