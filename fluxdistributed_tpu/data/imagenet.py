"""ImageNet (ILSVRC CLS-LOC) metadata parsing and sample loading.

Replaces the reference's ImageNet data layer (src/imagenet.jl):

* ``labels``          — parse ``LOC_synset_mapping.txt`` into a label
                        table (:8-21);
* ``train_solutions`` — parse ``LOC_train_solution.csv`` into a sample
                        table with ``class_idx``, filtered to requested
                        classes (:58-75);
* ``makepaths``       — train/val file layout (:50-56);
* ``ImageNetDataset`` — with-replacement minibatch sampling (:23-26) +
                        threaded JPEG decode/preprocess into a
                        preallocated float32 batch (:28-48, one
                        ``Threads.@spawn`` per image → here a thread
                        pool), one-hot handled by the loader.

No pandas/DataFrames dependency — plain numpy arrays and dicts.
"""

from __future__ import annotations

import csv
import os
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Optional, Sequence

import numpy as np

from .preprocess import preprocess, sample_augment_params
from .sources import make_source

__all__ = [
    "LabelTable", "SampleTable", "labels", "train_solutions", "relpath",
    "makepaths", "ImageNetDataset",
]


@dataclass
class LabelTable:
    """wnid ↔ class-index ↔ human-readable names (``labels`` analog,
    src/imagenet.jl:8-21: DataFrame of (label, name, class_idx))."""

    wnids: list
    names: list
    class_idx: dict = field(default_factory=dict)  # wnid -> 0-based index

    def __post_init__(self):
        if not self.class_idx:
            self.class_idx = {w: i for i, w in enumerate(self.wnids)}

    def __len__(self):
        return len(self.wnids)


def labels(synset_mapping_path: str) -> LabelTable:
    """Parse ``LOC_synset_mapping.txt``: one line per class,
    ``<wnid> <comma separated names>``."""
    wnids, names = [], []
    with open(synset_mapping_path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            wnid, _, rest = line.partition(" ")
            wnids.append(wnid)
            names.append(rest)
    return LabelTable(wnids, names)


@dataclass
class SampleTable:
    """image-id / class-index table — the sampling ``key`` the reference
    threads through ``prepare_training``/``minibatch``
    (src/imagenet.jl:58-75)."""

    image_ids: np.ndarray  # str array
    class_idx: np.ndarray  # int32
    split: str = "train"

    def __len__(self):
        return len(self.image_ids)

    def shard(self, i: int, n: int) -> "SampleTable":
        """Contiguous row shard, as ``prepare_training`` partitions the
        key across devices (src/ddp_tasks.jl:257-258)."""
        idx = np.array_split(np.arange(len(self)), n)[i]
        return SampleTable(self.image_ids[idx], self.class_idx[idx], self.split)


def train_solutions(
    csv_path: str,
    label_table: LabelTable,
    classes: Optional[Sequence[str]] = None,
    split: str = "train",
) -> SampleTable:
    """Parse ``LOC_train_solution.csv`` (columns ``ImageId,
    PredictionString`` where the prediction string starts with the wnid),
    keeping rows whose class is in ``classes`` (all classes if None) —
    the reference's class filter (src/imagenet.jl:58-75).

    ``split`` stamps the resulting table (``LOC_val_solution.csv`` has
    the same schema); it controls both the file layout (``makepaths``)
    and whether ``ImageNetDataset`` augments by default.
    """
    keep = set(classes) if classes is not None else None
    ids, cls = [], []
    with open(csv_path, newline="") as f:
        for row in csv.DictReader(f):
            wnid = row["PredictionString"].split()[0]
            if keep is not None and wnid not in keep:
                continue
            if wnid not in label_table.class_idx:
                continue
            ids.append(row["ImageId"])
            cls.append(label_table.class_idx[wnid])
    return SampleTable(np.asarray(ids, object), np.asarray(cls, np.int32), split)


def relpath(image_id: str, split: str = "train") -> str:
    """Dataset-relative file layout (src/imagenet.jl:50-56): train images
    live under ``ILSVRC/Data/CLS-LOC/train/<wnid>/<id>.JPEG`` (wnid
    prefix of the id), val/test flat under their split dir."""
    if split == "train":
        wnid = image_id.split("_")[0]
        return f"ILSVRC/Data/CLS-LOC/train/{wnid}/{image_id}.JPEG"
    return f"ILSVRC/Data/CLS-LOC/{split}/{image_id}.JPEG"


def makepaths(image_id: str, root: str, split: str = "train") -> str:
    """Absolute local path for a sample under a filesystem root."""
    return os.path.join(root, relpath(image_id, split))


class ImageNetDataset:
    """Dataset-protocol view over an ImageNet directory tree.

    ``batch(rng, n)`` samples rows with replacement (src/imagenet.jl:24),
    decodes + preprocesses each image on a worker thread into a
    preallocated ``(n, crop, crop, 3)`` float32 array (:37-48), and
    returns integer labels (the loader one-hots them).

    ``augment`` (default: on for the train split) switches the geometric
    stage to torchvision-style RandomResizedCrop + p=0.5 hflip — the
    train-time augmentation the reference lacks but the 75.9% top-1
    target requires.  Params are sampled in Python from the batch RNG
    (after the index draw), so the native and PIL backends produce
    identical batches for identical ``(rng_state, indices)``.
    """

    def __init__(
        self,
        root: str,
        table: SampleTable,
        nclasses: int,
        crop: int = 224,
        resize: int = 256,
        compat_double_normalize: bool = False,
        num_threads: int = 8,
        use_native: Optional[bool] = None,
        augment: Optional[bool] = None,
        cache_dir: Optional[str] = None,
    ):
        # ``root`` may be a local dir, a remote URL (gs:// or http(s)://,
        # fetched through a caching source — the reference's S3-backed
        # dataset analog, Data.toml:14-27), or a source object.
        self.source = root if hasattr(root, "local_path") else make_source(
            str(root), cache_dir=cache_dir
        )
        # the user-facing dataset location: a directory for filesystem
        # sources, the gs://... or http(s)://... URL for remote ones
        self.root = (
            getattr(self.source, "location", None)
            or getattr(self.source, "root", None)
            or str(root)
        )
        self.table = table
        self.nclasses = nclasses
        self.crop = crop
        self.resize = resize
        self.compat = compat_double_normalize
        self._num_threads = num_threads
        self._pool = None  # created lazily, released by close()
        if use_native is None:
            from . import native as _native

            use_native = _native.available()
        self.use_native = use_native
        if augment is None:
            augment = table.split == "train"
        self.augment = augment

    def __len__(self):
        return len(self.table)

    def close(self):
        """Release decode worker threads (also runs on GC / context exit)."""
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None

    __del__ = close

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _ensure_pool(self) -> ThreadPoolExecutor:
        if self._pool is None:
            self._pool = ThreadPoolExecutor(max_workers=self._num_threads)
        return self._pool

    def _path(self, image_id: str) -> str:
        """Local path of a sample (remote sources fetch-to-cache here).

        On the PIL path this runs on the decode worker, so fetch I/O
        overlaps other slots' decode; on the native path ``_paths``
        fetches the whole batch concurrently *before* handing local
        files to the C++ pool (cold-cache batches pay fetch-then-decode
        as two phases — steady-state cache hits make it a pure local
        read)."""
        return self.source.local_path(relpath(image_id, self.table.split))

    def _paths(self, indices) -> list:
        ids = [self.table.image_ids[j] for j in indices]
        # unknown duck-typed sources default to the remote path: the
        # concurrent fetch is harmless for local files, while serial
        # fetches on a remote source cost ~100ms/object
        if getattr(self.source, "is_local", False):
            return [self._path(i) for i in ids]
        # remote: fetch-to-cache concurrently, not one file at a time
        return list(self._ensure_pool().map(self._path, ids))

    def _load_one(self, out: np.ndarray, i: int, image_id: str, aug=None):
        path = self._path(image_id)
        out[i] = preprocess(
            path,
            crop=self.crop,
            resize=self.resize,
            compat_double_normalize=self.compat,
            augment=aug,
        )

    def batch(self, rng: np.random.Generator, n: int, indices=None):
        if indices is None:
            indices = rng.integers(0, len(self.table), size=n)
        indices = np.asarray(indices)
        # one RandomResizedCrop+flip draw per slot, consumed identically
        # by both backends (and by the native path's PIL fallback)
        augs = sample_augment_params(rng, len(indices)) if self.augment else None
        if self.use_native:
            from . import native as _native

            paths = self._paths(indices)
            # PIL fallback per file: ImageNet hides a few PNG/odd-format
            # files behind .JPEG extensions that libjpeg rejects.
            arr = _native.load_batch(
                paths,
                crop=self.crop,
                resize=self.resize,
                compat_double_normalize=self.compat,
                num_threads=self._num_threads,
                augs=augs,
                fallback=lambda p, aug=None: preprocess(
                    p,
                    crop=self.crop,
                    resize=self.resize,
                    compat_double_normalize=self.compat,
                    augment=aug,
                ),
            )
            return arr, self.table.class_idx[indices]
        pool = self._ensure_pool()
        arr = np.zeros((len(indices), self.crop, self.crop, 3), np.float32)
        futures = [
            pool.submit(
                self._load_one, arr, i, self.table.image_ids[j],
                augs[i] if augs is not None else None,
            )
            for i, j in enumerate(indices)
        ]
        for f in futures:
            f.result()  # propagate decode errors
        return arr, self.table.class_idx[indices]
