#!/usr/bin/env python
"""Trainer supervisor — keep a ``bin/driver.py`` run finishing itself.

The trainer-side analogue of the router's ``SupervisedReplica`` and the
tested-Python generalization of ``benchmarks/hw_watch.sh``: spawn the
driver, watch its heartbeats, classify every exit, and restart within a
bounded budget — so a grant window survives crashes, preemptions AND
wedged collectives with zero human input::

    python bin/supervise.py --ledger run/ledger.json -- \
        python bin/driver.py --model lm_tiny ... \
            --checkpoint-dir run/ck --guard --metrics-port 0

Exit classification (the supervisor's whole job):

* **rc 0** — done; the supervisor exits 0.
* **rc 75** (``faults.PREEMPTED_RC``) — the run checkpointed on
  SIGTERM; restart immediately with ``--resume`` (bounded by
  ``--max-resumes``, no backoff — preemption is expected weather).
* **rc 65** (``faults.HALTED_RC``) — the guard halted: NOT retryable by
  construction; the supervisor stops and propagates the rc.
* **stall** — heartbeats stop: the scraped
  ``fdtpu_train_steps_total`` counter freezes past ``--stall-timeout``
  (the metrics endpoint keeps answering from its own thread even while
  the loop is wedged), or ``fdtpu_watchdog_escalations_total`` ticks
  (the in-process wedged-collective verdict).  While the child's
  pause-aware watchdog reports NOT stalled, a frozen counter is read
  as legitimate long work (first-step compile, a blocking checkpoint)
  and the kill is deferred — bounded by ``--startup-grace``.  Then
  SIGKILL — a wedged loop cannot run a SIGTERM checkpoint anyway —
  and restart with ``--resume``: the guard's blocking checkpoints +
  eagerly-written RESUME manifest make the kill lossless, and a
  changed device count on the way back rides the elastic restore.
* **any other rc** — a crash; restart with ``--resume`` under
  exponential backoff, bounded by ``--max-restarts``.

Heartbeats come from the driver's ``--metrics-port`` endpoint (the
supervisor reads the bound port off the ``metrics: http://...`` stdout
line, so ``--metrics-port 0`` works); before that line appears, stdout
activity itself is the liveness signal (compiles are long and silent —
bounded by ``--startup-grace``).

``--fault-plan`` is STRIPPED from restart argv by default: an injected
fault models one occurrence of weather, and replaying it on every
restart would wedge the supervisor in the exact loop it exists to break
(``--keep-fault-plan`` restores the old behavior for chaos soaks).

Every episode lands in the guard ledger JSON (``--ledger``): rc,
classification, action taken, wall seconds, last step count and a
snapshot of the ``fdtpu_guard_* / fdtpu_fault_* / fdtpu_watchdog_*``
counters scraped before the exit — a dead run's ledger says exactly
why it died and what the supervisor did about it.

``--smoke`` runs the self-contained CI gate: a tiny CPU driver run
under a fault plan that injects a NaN (quarantined by the guard) and
then a hang (SIGKILLed + resumed by the supervisor), asserting the run
still completes.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import signal
import subprocess
import sys
import threading
import time
import urllib.request
from collections import deque
from typing import List, Optional

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:  # direct `python bin/supervise.py` launches
    sys.path.insert(0, REPO)

from fluxdistributed_tpu.faults import HALTED_RC, PREEMPTED_RC  # noqa: E402

#: stdout line the driver prints once its metrics endpoint is bound
METRICS_LINE_RE = re.compile(r"metrics: http://[^:]+:(\d+)/metrics")

#: metric families snapshotted into each ledger episode — the "why it
#: died" forensics (mirrors bench.py's guard stamp)
LEDGER_PREFIXES = ("fdtpu_guard_", "fdtpu_fault_", "fdtpu_watchdog_",
                   "fdtpu_train_steps_total",
                   "fdtpu_train_oom_skipped_total")


def parse_metrics(text: str) -> dict:
    """Prometheus exposition -> ``{series: value}`` (labels kept in the
    series name, like ``Registry.snapshot()``)."""
    out: dict = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        series, _, value = line.rpartition(" ")
        try:
            out[series] = float(value)
        except ValueError:
            continue
    return out


def series_value(metrics: dict, name: str) -> float:
    """Sum of every sample of family ``name`` (labeled or not)."""
    total = 0.0
    for k, v in metrics.items():
        if k == name or k.startswith(name + "{"):
            total += v
    return total


class Supervisor:
    """Spawn-watch-classify-restart for one driver command.

    ``cmd`` is the full child argv (``[python, bin/driver.py, ...]``).
    The class is importable so tests drive it against fake children;
    :func:`main` is the CLI.
    """

    def __init__(
        self,
        cmd: List[str],
        ledger: Optional[str] = None,
        max_restarts: int = 3,
        max_resumes: int = 32,
        stall_timeout: float = 120.0,
        startup_grace: float = 600.0,
        poll_interval: float = 0.5,
        backoff: float = 5.0,
        backoff_cap: float = 300.0,
        keep_fault_plan: bool = False,
        verbose: bool = True,
        env: Optional[dict] = None,
        runs_ledger: Optional[str] = None,
    ):
        self.cmd = list(cmd)
        # the child must resolve the package even when it is not
        # installed (dev checkouts, CI): front-load the repo root, the
        # same contract the test harness's driver e2e uses
        self.env = dict(os.environ, **(env or {}))
        self.env["PYTHONPATH"] = REPO + os.pathsep + self.env.get(
            "PYTHONPATH", "")
        self.ledger_path = ledger
        self.max_restarts = max_restarts
        self.max_resumes = max_resumes
        self.stall_timeout = stall_timeout
        self.startup_grace = startup_grace
        self.poll_interval = poll_interval
        self.backoff = backoff
        self.backoff_cap = backoff_cap
        self.keep_fault_plan = keep_fault_plan
        self.verbose = verbose
        #: cross-run ledger (obs.runs JSONL): one record per EPISODE,
        #: so the history survives even when the per-run ledger JSON is
        #: overwritten by the next supervision
        self.runs_ledger = runs_ledger
        self.episodes: List[dict] = []
        self.restarts = 0  # crash/stall restarts (budgeted + backoff)
        self.resumes = 0   # rc-75 requeues (budgeted, no backoff)
        self._terminate = threading.Event()
        self._metrics_url: Optional[str] = None
        self._last_line_at = time.monotonic()
        self._tail: deque = deque(maxlen=30)

    # -- argv shaping --------------------------------------------------
    def episode_argv(self, first: bool) -> List[str]:
        """The child argv for this episode: restarts gain ``--resume``
        (when a ``--checkpoint-dir`` exists to resume from) and drop
        the fault plan — an injected fault is one occurrence of
        weather, not a curse on every successor."""
        argv = list(self.cmd)
        if first:
            return argv
        if not self.keep_fault_plan:
            # both argparse spellings: "--fault-plan X" and
            # "--fault-plan=X"
            out = []
            skip_next = False
            for tok in argv:
                if skip_next:
                    skip_next = False
                    continue
                if tok == "--fault-plan":
                    skip_next = True
                    continue
                if tok.startswith("--fault-plan="):
                    continue
                out.append(tok)
            argv = out
        has_ckpt = any(t == "--checkpoint-dir"
                       or t.startswith("--checkpoint-dir=") for t in argv)
        if has_ckpt and "--resume" not in argv:
            argv.append("--resume")
        return argv

    # -- child watching ------------------------------------------------
    def _pump(self, proc: subprocess.Popen, name: str) -> None:
        try:
            for line in proc.stdout:  # type: ignore[union-attr]
                self._last_line_at = time.monotonic()
                m = METRICS_LINE_RE.search(line)
                if m:
                    self._metrics_url = (
                        f"http://127.0.0.1:{m.group(1)}/metrics")
                self._tail.append(line.rstrip()[:300])
                if self.verbose:
                    sys.stderr.write(f"[{name}] {line}")
        except (ValueError, OSError):
            pass  # stream closed at teardown

    def _scrape(self) -> Optional[dict]:
        url = self._metrics_url
        if url is None:
            return None
        try:
            with urllib.request.urlopen(url, timeout=2.0) as r:
                return parse_metrics(r.read().decode())
        except Exception:  # noqa: BLE001 — an unscrapeable endpoint is
            # just "no heartbeat this poll", never a supervisor crash
            return None

    def _watch(self, proc: subprocess.Popen) -> dict:
        """Block until the child exits (or we kill it); returns
        ``{rc, cls, steps, counters}`` — the raw episode verdict."""
        started = time.monotonic()
        self._metrics_url = None
        self._last_line_at = started
        last_steps = -1.0
        last_progress = started
        esc_seen: Optional[float] = None
        counters: dict = {}
        kill_cls: Optional[str] = None
        # the in-process watchdog's stalled gauge from the last good
        # scrape (None = absent/disabled): it is pause-aware (compiles,
        # blocking checkpoints, evals are exempt in-process), so while
        # it reads healthy a frozen step counter is long legitimate
        # work, not a wedge — deferral is bounded by startup_grace
        wd_gauge: Optional[float] = None
        scrape_ok = False
        while True:
            rc = proc.poll()
            if rc is not None:
                break
            if self._terminate.is_set():
                # forward the supervisor's own SIGTERM: the child gets
                # its graceful checkpoint-and-exit window
                try:
                    proc.send_signal(signal.SIGTERM)
                except (ProcessLookupError, OSError):
                    pass
                try:
                    proc.wait(timeout=self.stall_timeout)
                except subprocess.TimeoutExpired:
                    proc.kill()
                rc = proc.wait()
                kill_cls = "terminated"
                break
            time.sleep(self.poll_interval)
            now = time.monotonic()
            m = self._scrape()
            scrape_ok = m is not None
            if m is not None:
                counters = {k: v for k, v in m.items()
                            if k.startswith(LEDGER_PREFIXES)}
                steps = series_value(m, "fdtpu_train_steps_total")
                if steps > last_steps:
                    last_steps = steps
                    last_progress = now
                wd_gauge = (m["fdtpu_watchdog_stalled"]
                            if "fdtpu_watchdog_stalled" in m else None)
                esc = series_value(m, "fdtpu_watchdog_escalations_total")
                if esc_seen is None:
                    esc_seen = esc
                elif esc > esc_seen:
                    kill_cls = "escalated"
            elif self._metrics_url is None:
                # pre-endpoint (import + compile): stdout is the pulse
                if self._last_line_at > last_progress:
                    last_progress = self._last_line_at
                if now - last_progress <= self.startup_grace:
                    continue
                kill_cls = "stalled"
            if kill_cls is None and now - last_progress > self.stall_timeout:
                # frozen steps, but the endpoint answers and the
                # pause-aware watchdog says not-stalled: a long compile
                # or blocking checkpoint, not a wedge — hold fire until
                # startup_grace bounds even that (a dead watchdog
                # thread must not grant immortality)
                healthy_wait = (scrape_ok and wd_gauge is not None
                                and wd_gauge < 1)
                if not healthy_wait or now - last_progress > max(
                        self.stall_timeout, self.startup_grace):
                    kill_cls = "stalled"
            if kill_cls is not None:
                # SIGKILL, not SIGTERM: a wedged collective cannot run
                # the checkpoint-on-signal path, and the guard's
                # blocking checkpoints already made the kill lossless
                proc.kill()
                rc = proc.wait()
                break
        return {"rc": rc, "cls": kill_cls, "steps": max(last_steps, 0.0),
                "counters": counters}

    # -- the supervision loop ------------------------------------------
    def run(self) -> int:
        previous = {}
        for s in (signal.SIGTERM, signal.SIGINT):
            try:
                previous[s] = signal.signal(
                    s, lambda *_: self._terminate.set())
            except ValueError:
                pass  # not the main thread (tests)
        try:
            return self._run()
        finally:
            for s, old in previous.items():
                try:
                    signal.signal(s, old)
                except (ValueError, OSError):
                    pass

    def _run(self) -> int:
        result = "running"
        rc = 1
        n = 0
        while True:
            n += 1
            argv = self.episode_argv(first=n == 1)
            t0 = time.monotonic()
            self._tail.clear()  # each episode's ledger tail is its own
            proc = subprocess.Popen(
                argv, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, bufsize=1, cwd=REPO, env=self.env)
            pump = threading.Thread(
                target=self._pump, args=(proc, f"ep{n}"), daemon=True)
            pump.start()
            verdict = self._watch(proc)
            pump.join(timeout=5)
            rc = verdict["rc"]
            cls = verdict["cls"] or {
                0: "done", PREEMPTED_RC: "preempted", HALTED_RC: "halted",
            }.get(rc, "crashed")
            episode = {
                "n": n, "argv": argv, "rc": rc, "class": cls,
                "wall_seconds": round(time.monotonic() - t0, 2),
                "steps": verdict["steps"],
                "counters": verdict["counters"],
                "log_tail": list(self._tail),
            }
            action, result = self._decide(cls)
            episode["action"] = action
            self.episodes.append(episode)
            self._append_episode_record(episode, result)
            self._log(f"episode {n}: rc={rc} class={cls} -> {action}")
            self.write_ledger(result)
            if action == "stop":
                break
            if action == "restart_backoff":
                pause = min(self.backoff * (2 ** (self.restarts - 1)),
                            self.backoff_cap)
                self._log(f"backing off {pause:.1f}s before restart")
                time.sleep(pause)
        self.write_ledger(result)
        if result not in ("done", "terminated"):
            # the supervision ended badly: merge the evidence into ONE
            # timeline NOW, while it is fresh — the operator reads a
            # postmortem, not four artifact files
            self.write_postmortem(result)
        if result == "done":
            return 0
        # a SIGKILLed child reports a negative rc; normalize so the
        # shell-visible code stays meaningful (75/65 propagate)
        return rc if isinstance(rc, int) and rc > 0 else 1

    def _decide(self, cls: str):
        """(action, running-result) for one classified exit."""
        if cls == "done":
            return "stop", "done"
        if cls in ("halted", "terminated"):
            # halted: retryable=false by construction; terminated: the
            # OPERATOR stopped us — both end supervision, rc propagates
            return "stop", cls
        if cls == "preempted":
            self.resumes += 1
            if self.resumes > self.max_resumes:
                return "stop", "resume_budget_exhausted"
            return "restart", "running"
        # crashed / stalled / escalated consume the restart budget;
        # stalls restart immediately (the chip was fine, the process
        # was not), crashes back off
        self.restarts += 1
        if self.restarts > self.max_restarts:
            return "stop", "restart_budget_exhausted"
        if cls == "crashed":
            return "restart_backoff", "running"
        return "restart", "running"

    # -- ledger --------------------------------------------------------
    def write_ledger(self, result: str) -> None:
        if not self.ledger_path:
            return
        payload = {
            "version": 1,
            "cmd": self.cmd,
            "episodes": self.episodes,
            "restarts": self.restarts,
            "resumes": self.resumes,
            "result": result,
            "completed": result == "done",
        }
        path = self.ledger_path
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(payload, f, indent=2)
            f.write("\n")
        os.replace(tmp, path)

    def child_flight(self) -> Optional[str]:
        """The child's ``--flight`` dump path, scanned off the argv —
        the black box the postmortem opens when an episode hard-dies."""
        for i, tok in enumerate(self.cmd):
            if tok == "--flight" and i + 1 < len(self.cmd):
                return self.cmd[i + 1]
            if tok.startswith("--flight="):
                return tok.split("=", 1)[1]
        return None

    def _append_episode_record(self, episode: dict, result: str) -> None:
        """One cross-run ledger record per episode (obs.runs schema).
        The per-run ledger JSON is atomically OVERWRITTEN each episode;
        the append-only runs ledger is where episode history outlives
        the next supervision.  Best-effort by contract."""
        if not self.runs_ledger:
            return
        try:
            from fluxdistributed_tpu.obs import runs as runs_lib

            cls = episode["class"]
            runs_lib.append_run(self.runs_ledger, runs_lib.run_record(
                "episode",
                phase=cls,
                retryable=cls in ("preempted", "crashed", "stalled",
                                  "escalated"),
                error=(None if cls == "done" else
                       f"episode class={cls} rc={episode['rc']}"),
                metrics={"steps": episode["steps"],
                         "wall_seconds": episode["wall_seconds"]},
                flight=self.child_flight(),
                episode=episode["n"],
                action=episode["action"],
                result=result,
            ))
        except Exception as e:  # noqa: BLE001 — forensics only
            self._log(f"runs-ledger append failed: "
                      f"{type(e).__name__}: {e}")

    def write_postmortem(self, result: str) -> Optional[str]:
        """Merge the child's flight dump + this supervision's episode
        ledger into one human-readable timeline (obs.runs), print it to
        stderr and (with ``--ledger``) persist it alongside as
        ``<ledger>.postmortem.txt``.  Returns the written path."""
        try:
            from fluxdistributed_tpu.obs import runs as runs_lib

            text = runs_lib.postmortem_timeline(
                flight_path=self.child_flight(),
                supervisor_ledger=self.ledger_path,
                runs_path=self.runs_ledger)
            text += f"\nsupervision result: {result}"
            # static-health stamp (own guard: the lint pass parsing the
            # tree must not take the postmortem down with it) — a crash
            # report that says "new: 3, concurrency: 2" points straight
            # at an unlocked write before anyone replays the run
            try:
                import json as _json

                from fluxdistributed_tpu import analysis
                text += ("\nlint stamp: "
                         + _json.dumps(analysis.lint_verdict(),
                                       sort_keys=True))
            except Exception as e:  # noqa: BLE001 — forensics only
                text += (f"\nlint stamp: unavailable "
                         f"({type(e).__name__}: {e})"[:200])
            print(text, file=sys.stderr)
            if not self.ledger_path:
                return None
            path = self.ledger_path + ".postmortem.txt"
            with open(path, "w") as f:
                f.write(text + "\n")
            self._log(f"postmortem written to {path}")
            return path
        except Exception as e:  # noqa: BLE001 — the postmortem must
            # never mask the real exit code
            self._log(f"postmortem failed: {type(e).__name__}: {e}")
            return None

    def _log(self, msg: str) -> None:
        if self.verbose:
            print(f"supervise: {msg}", file=sys.stderr)


# ---------------------------------------------------------------------------
# CI smoke
# ---------------------------------------------------------------------------


def smoke(args) -> int:
    """The self-contained supervise gate: NaN at step 2 (guard
    quarantines it), hang at step 5 (supervisor SIGKILLs + resumes),
    and the run must still COMPLETE — asserted, not hoped.  The first
    episode runs on 4 virtual devices (the fault plan's ``params``
    override); the restart — plan stripped — comes back on the argv's
    2, so the post-SIGKILL resume is a real ELASTIC resume onto a
    different device count, not just a reload."""
    import tempfile

    work = tempfile.mkdtemp(prefix="fdtpu-supervise-smoke-")
    ledger = args.ledger or os.path.join(work, "ledger.json")
    plan = {"fail": [
        {"site": "train.loss", "at": 2, "action": "nan"},
        {"site": "step", "at": 5, "action": "hang"},
    ], "params": {"local_devices": 4}}
    cmd = [
        sys.executable, os.path.join(REPO, "bin", "driver.py"),
        "--model", "SimpleCNN", "--dataset", "synthetic",
        "--num-classes", "4", "--image-size", "8",
        "--batch-size", "8", "--cycles", "8",
        "--print-every", "1", "--eval-every", "0",
        "--checkpoint-dir", os.path.join(work, "ck"),
        "--checkpoint-every", "2",
        "--guard", "--metrics-port", "0",
        "--platform", "cpu", "--local-devices", "2",
        "--fault-plan", json.dumps(plan),
    ]
    sup = Supervisor(
        cmd, ledger=ledger, max_restarts=3,
        stall_timeout=args.stall_timeout if args.stall_timeout != 120.0
        else 20.0,
        startup_grace=300.0, poll_interval=0.25, backoff=1.0,
        verbose=not args.quiet)
    rc = sup.run()
    with open(ledger) as f:
        led = json.load(f)
    classes = [e["class"] for e in led["episodes"]]
    problems = []
    if rc != 0 or not led["completed"]:
        problems.append(f"run did not complete (rc={rc}, {led['result']})")
    if classes[-1:] != ["done"]:
        problems.append(f"last episode not done: {classes}")
    if not any(c in ("stalled", "escalated") for c in classes):
        problems.append(f"the hang was never killed: {classes}")
    quarantined = max(
        (series_value(e["counters"], "fdtpu_guard_quarantined_total")
         for e in led["episodes"]), default=0.0)
    if quarantined < 1:
        problems.append("the injected NaN was never quarantined")
    final_tail = "\n".join(led["episodes"][-1]["log_tail"])
    if "resumed from step" not in final_tail:
        problems.append(
            "the post-SIGKILL episode did not resume from the "
            "checkpoint+manifest (elastic resume missing)")
    if problems:
        print("supervise smoke FAILED:", "; ".join(problems),
              file=sys.stderr)
        print(json.dumps(led, indent=2)[-3000:], file=sys.stderr)
        return 1
    print(f"supervise smoke OK: episodes={classes}, "
          f"quarantined={int(quarantined)}, restarts={led['restarts']}, "
          f"ledger={ledger}")
    return 0


def crash_smoke(args) -> int:
    """The crash-forensics CI gate: a fault plan ``os._exit``s the
    driver at step 12 — the SIGKILL shape (no ``finally``, no flight
    footer) — with the flight recorder on, then asserts the black box
    did its one job: the dump is readable, footer-LESS, and its last
    flushed record names a step within one flush interval of death;
    and the merged postmortem calls the death hard."""
    import tempfile

    work = args.artifacts or tempfile.mkdtemp(prefix="fdtpu-crash-smoke-")
    os.makedirs(work, exist_ok=True)
    flight = os.path.join(work, "crash-flight.jsonl")
    runs_ledger = os.path.join(work, "crash-runs.jsonl")
    ledger = args.ledger or os.path.join(work, "crash-ledger.json")
    kill_at = 12
    plan = {"fail": [{"site": "step", "at": kill_at, "action": "exit"}]}
    cmd = [
        sys.executable, os.path.join(REPO, "bin", "driver.py"),
        "--model", "SimpleCNN", "--dataset", "synthetic",
        "--num-classes", "4", "--image-size", "8",
        "--batch-size", "8", "--cycles", "20",
        "--print-every", "5", "--eval-every", "0",
        "--platform", "cpu", "--local-devices", "2",
        "--flight", flight,
        "--runs-ledger", runs_ledger,
        "--fault-plan", json.dumps(plan),
    ]
    sup = Supervisor(
        cmd, ledger=ledger, runs_ledger=runs_ledger,
        max_restarts=0,  # forensics gate: the DEATH is the product
        startup_grace=300.0, poll_interval=0.25,
        verbose=not args.quiet)
    rc = sup.run()
    from fluxdistributed_tpu.obs.flight import read_flight
    from fluxdistributed_tpu.obs.runs import load_runs

    problems = []
    if rc == 0:
        problems.append("the killed run reported rc 0")
    try:
        fl = read_flight(flight)
    except OSError as e:
        print(f"crash smoke FAILED: no flight dump at {flight}: {e}",
              file=sys.stderr)
        return 1
    recs = fl["records"]
    flush_every = int((fl["header"] or {}).get("flush_every", 8))
    if fl["header"] is None:
        problems.append("flight dump has no header")
    if not recs:
        problems.append("flight dump has no records")
    if fl["end"] is not None:
        problems.append(
            f"a hard death left an end footer: {fl['end']} — dump() ran "
            "on a path that must not reach it")
    last_step = recs[-1].get("step", -1) if recs else -1
    if recs and not (kill_at - 1 - flush_every
                     <= last_step <= kill_at - 1):
        problems.append(
            f"last flushed record step {last_step} is not within one "
            f"flush interval ({flush_every}) of death step {kill_at}")
    pm_path = ledger + ".postmortem.txt"
    try:
        with open(pm_path) as f:
            pm = f.read()
    except OSError:
        pm, problems = "", problems + [
            f"no postmortem written at {pm_path}"]
    if pm and "hard death" not in pm:
        problems.append("postmortem does not call the death hard")
    if pm and "lint stamp:" not in pm:
        problems.append("postmortem lacks the static-health lint stamp")
    eps = [r for r in load_runs(runs_ledger) if r.get("kind") == "episode"]
    if not eps:
        problems.append("no episode record in the runs ledger")
    if problems:
        print("crash smoke FAILED:", "; ".join(problems), file=sys.stderr)
        return 1
    print(f"crash smoke OK: {len(recs)} records flushed, last step "
          f"{last_step} (death at {kill_at}, flush interval "
          f"{flush_every}), footer absent, postmortem at {pm_path}")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description=__doc__.split("\n")[0],
        usage="supervise.py [options] -- python bin/driver.py ...")
    p.add_argument("--ledger", default=None, metavar="PATH",
                   help="write the guard ledger JSON (per-episode rc/"
                        "class/action + scraped counters) here, "
                        "atomically, after every episode")
    p.add_argument("--max-restarts", type=int, default=3,
                   help="crash/stall restarts before giving up")
    p.add_argument("--max-resumes", type=int, default=32,
                   help="rc-75 preemption requeues before giving up")
    p.add_argument("--stall-timeout", type=float, default=120.0,
                   help="seconds without step progress (scraped "
                        "fdtpu_train_steps_total) before SIGKILL")
    p.add_argument("--startup-grace", type=float, default=600.0,
                   help="seconds of stdout silence tolerated before the "
                        "metrics endpoint appears (imports + compiles)")
    p.add_argument("--backoff", type=float, default=5.0,
                   help="first crash-restart pause; doubles per crash")
    p.add_argument("--keep-fault-plan", action="store_true",
                   help="do NOT strip --fault-plan from restart argv "
                        "(chaos soaks; default strips it so an injected "
                        "hang is not replayed forever)")
    p.add_argument("--quiet", action="store_true",
                   help="suppress child log forwarding")
    p.add_argument("--runs-ledger", default=None, metavar="PATH",
                   help="append one obs.runs record per EPISODE here "
                        "(the append-only cross-run history "
                        "bin/trends.py reads; the --ledger JSON is "
                        "overwritten per episode, this is not)")
    p.add_argument("--smoke", action="store_true",
                   help="run the self-contained NaN+hang CI smoke "
                        "instead of a user command")
    p.add_argument("--crash-smoke", action="store_true",
                   help="run the crash-forensics CI smoke: fault-plan "
                        "hard kill -> flight dump + postmortem asserted")
    p.add_argument("--artifacts", default=None, metavar="DIR",
                   help="where --crash-smoke leaves its flight dump / "
                        "ledgers / postmortem (default: a tmpdir)")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="child command after `--`")
    args = p.parse_args(argv)
    if args.smoke:
        return smoke(args)
    if args.crash_smoke:
        return crash_smoke(args)
    cmd = args.cmd
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        p.error("no child command given (append `-- python bin/driver.py "
                "...`, or use --smoke / --crash-smoke)")
    sup = Supervisor(
        cmd, ledger=args.ledger, max_restarts=args.max_restarts,
        max_resumes=args.max_resumes, stall_timeout=args.stall_timeout,
        startup_grace=args.startup_grace, backoff=args.backoff,
        keep_fault_plan=args.keep_fault_plan, verbose=not args.quiet,
        runs_ledger=args.runs_ledger)
    return sup.run()


if __name__ == "__main__":
    sys.exit(main())
