"""Pipeline parallelism: GPipe schedule == sequential stage application.

Invariants: the pipelined forward matches applying the S stages in
sequence on one device; gradients through the pipeline match sequential
gradients; the compiled PP train step trains (loss falls) with
stage-sharded params and optimizer state.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

# tier-2 (slow): GPipe train/forward compiles on the 8-device mesh — the tier-1 iteration loop must fit the
# 870s verify window (ROADMAP); CI's slow job still runs this file
pytestmark = pytest.mark.slow

from fluxdistributed_tpu import mesh as mesh_lib, optim
from fluxdistributed_tpu.ops import logitcrossentropy, onehot
from fluxdistributed_tpu.parallel.dp import TrainState
from fluxdistributed_tpu.parallel.pp import (
    make_train_step_pp,
    pipeline_apply,
    stack_stage_params,
)

S = 4  # stages
D = 16  # residual width


@pytest.fixture(scope="module")
def mesh():
    return mesh_lib.make_mesh({"pipe": S})


def stage_fn(params, x):
    """One homogeneous stage: residual Dense+gelu (same in/out shape)."""
    return x + jax.nn.gelu(x @ params["w"] + params["b"])


def _stage_params(key):
    kw, _ = jax.random.split(key)
    return {
        "w": jax.random.normal(kw, (D, D), jnp.float32) * 0.3,
        "b": jnp.zeros((D,), jnp.float32),
    }


def _sequential(per_stage, x):
    for p in per_stage:
        x = stage_fn(p, x)
    return x


@pytest.fixture(scope="module")
def per_stage():
    keys = jax.random.split(jax.random.PRNGKey(0), S)
    return [_stage_params(k) for k in keys]


def test_pipeline_matches_sequential_forward(mesh, per_stage):
    x = jax.random.normal(jax.random.PRNGKey(1), (8, D), jnp.float32)
    stacked = stack_stage_params(per_stage, mesh)
    for m in (2, 4, 8):  # microbatch counts, incl. M != S
        fwd = pipeline_apply(stage_fn, mesh, num_microbatches=m)
        got = np.asarray(fwd(stacked, x))
        want = np.asarray(_sequential(per_stage, x))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_pipeline_gradients_match_sequential(mesh, per_stage):
    x = jax.random.normal(jax.random.PRNGKey(2), (8, D), jnp.float32)
    stacked = stack_stage_params(per_stage, mesh)
    fwd = pipeline_apply(stage_fn, mesh, num_microbatches=4)

    def loss_pp(params):
        return jnp.mean(fwd(params, x) ** 2)

    def loss_seq(stages):
        return jnp.mean(_sequential(stages, x) ** 2)

    g_pp = jax.grad(loss_pp)(stacked)
    g_seq = jax.grad(loss_seq)(per_stage)
    g_seq_stacked = jax.tree.map(lambda *xs: jnp.stack(xs), *g_seq)
    for a, b in zip(jax.tree.leaves(g_pp), jax.tree.leaves(g_seq_stacked)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


def test_pp_train_step_loss_falls(mesh):
    """Stage-sharded end-to-end training: readout folded into the loss,
    stages trained through the compiled pipelined step."""
    nclasses = D  # use the residual stream's last layer as logits
    rng = np.random.default_rng(0)
    n = 32
    y = rng.integers(0, 2, n)  # 2 distinguishable classes
    x = rng.normal(0, 0.3, (n, D)).astype(np.float32)
    x[:, 0] += y * 2.0  # separable signal in feature 0
    labels = np.asarray(onehot(y, nclasses))

    keys = jax.random.split(jax.random.PRNGKey(3), S)
    per_stage = [_stage_params(k) for k in keys]
    stacked = stack_stage_params(per_stage, mesh)
    opt = optim.momentum(0.1, 0.9)
    state = TrainState.create(stacked, opt)
    compile_for = make_train_step_pp(
        stage_fn, logitcrossentropy, opt, mesh, num_microbatches=4, donate=False
    )
    step = compile_for(state)
    batch = {"image": jnp.asarray(x), "label": jnp.asarray(labels)}
    losses = []
    for _ in range(25):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.6, losses[::8]
    assert int(state.step) == 25


# ---- heterogeneous stages (stage_fn(params, x, stage) + switch_stage) ----

def test_heterogeneous_pipeline_matches_sequential(mesh, per_stage):
    """Alternating gelu/tanh stages via switch_stage match sequential."""
    from fluxdistributed_tpu.parallel.pp import switch_stage

    def gelu_stage(p, x):
        return x + jax.nn.gelu(x @ p["w"] + p["b"])

    def tanh_stage(p, x):
        return x + jnp.tanh(x @ p["w"] + p["b"])

    fns = [gelu_stage if s % 2 == 0 else tanh_stage for s in range(S)]
    het = switch_stage(fns)

    x = jax.random.normal(jax.random.PRNGKey(3), (8, D), jnp.float32)
    stacked = stack_stage_params(per_stage, mesh)
    fwd = pipeline_apply(het, mesh, num_microbatches=4)
    got = np.asarray(jax.jit(fwd)(stacked, x))

    want = x
    for s, p in enumerate(per_stage):
        want = fns[s](p, want)
    np.testing.assert_allclose(got, np.asarray(want), rtol=1e-5, atol=1e-5)


def test_heterogeneous_pipeline_grads_match_sequential(mesh, per_stage):
    from fluxdistributed_tpu.parallel.pp import switch_stage

    def gelu_stage(p, x):
        return x + jax.nn.gelu(x @ p["w"] + p["b"])

    def tanh_stage(p, x):
        return x + jnp.tanh(x @ p["w"] + p["b"])

    fns = [gelu_stage if s % 2 == 0 else tanh_stage for s in range(S)]
    het = switch_stage(fns)
    x = jax.random.normal(jax.random.PRNGKey(4), (8, D), jnp.float32)
    stacked = stack_stage_params(per_stage, mesh)
    fwd = pipeline_apply(het, mesh, num_microbatches=4)

    def loss_pp(params):
        return jnp.sum(fwd(params, x) ** 2)

    g_pp = jax.jit(jax.grad(loss_pp))(stacked)

    def loss_seq(per_stage_tuple):
        y = x
        for s, p in enumerate(per_stage_tuple):
            y = fns[s](p, y)
        return jnp.sum(y ** 2)

    g_seq = jax.grad(loss_seq)(tuple(per_stage))
    for s in range(S):
        for k in ("w", "b"):
            np.testing.assert_allclose(
                np.asarray(g_pp[k][s]), np.asarray(g_seq[s][k]),
                rtol=1e-4, atol=1e-5,
            )


def test_switch_stage_wrong_count_rejected(mesh):
    from fluxdistributed_tpu.parallel.pp import switch_stage

    het = switch_stage([stage_fn] * (S - 1))
    with pytest.raises(ValueError, match="stage fns"):
        pipeline_apply(het, mesh)


def test_defaulted_third_arg_not_treated_as_stage(mesh, per_stage):
    """A stage_fn with a defaulted third param keeps its default — the
    stage index must not silently replace it."""

    def scaled_stage(p, x, scale=0.5):
        return x + scale * jax.nn.gelu(x @ p["w"] + p["b"])

    x = jax.random.normal(jax.random.PRNGKey(5), (8, D), jnp.float32)
    stacked = stack_stage_params(per_stage, mesh)
    fwd = pipeline_apply(scaled_stage, mesh, num_microbatches=4)
    got = np.asarray(jax.jit(fwd)(stacked, x))
    want = x
    for p in per_stage:
        want = scaled_stage(p, want)
    np.testing.assert_allclose(got, np.asarray(want), rtol=1e-5, atol=1e-5)


def test_chunked_virtual_pipeline_matches_sequential(mesh):
    """16 logical stages on 4 devices (V=4 chunks each, blocked
    placement) match sequential application, forward and gradients."""
    from fluxdistributed_tpu.parallel.pp import chunk_stages

    V = 4
    G = V * S
    keys = jax.random.split(jax.random.PRNGKey(6), G)
    # 0.1-scale weights: 16 residual stages at the default 0.3 scale
    # explode activations to ~1e3 and grads to ~1e6, where f32
    # accumulation-order noise swamps per-element tolerances
    per_stage = [
        {"w": jax.random.normal(k, (D, D), jnp.float32) * 0.1,
         "b": jnp.zeros((D,), jnp.float32)}
        for k in keys
    ]
    # (G, ...) stacked leaves -> (S, V, ...) so the pipe axis shards the
    # leading dim into per-device (V, ...) chunk blocks
    stacked = jax.tree.map(
        lambda *ls: jnp.stack(ls).reshape(S, V, *ls[0].shape),
        *per_stage,
    )
    from jax.sharding import NamedSharding, PartitionSpec as P

    stacked = jax.device_put(stacked, NamedSharding(mesh, P("pipe")))

    x = jax.random.normal(jax.random.PRNGKey(7), (8, D), jnp.float32)
    fwd = pipeline_apply(chunk_stages(stage_fn), mesh, num_microbatches=4)
    got = np.asarray(jax.jit(fwd)(stacked, x))

    want = x
    for p in per_stage:
        want = stage_fn(p, want)
    np.testing.assert_allclose(got, np.asarray(want), rtol=1e-5, atol=1e-5)

    # gradients
    def loss_pp(params):
        return jnp.sum(fwd(params, x) ** 2)

    g_pp = jax.jit(jax.grad(loss_pp))(stacked)

    def loss_seq(ps):
        y = x
        for p in ps:
            y = stage_fn(p, y)
        return jnp.sum(y ** 2)

    g_seq = jax.grad(loss_seq)(tuple(per_stage))
    for g in range(G):
        s, v = g // V, g % V
        for k in ("w", "b"):
            a, b = np.asarray(g_pp[k][s, v]), np.asarray(g_seq[g][k])
            rel = np.abs(a - b).max() / (np.abs(b).max() + 1e-9)
            assert rel < 1e-5, (g, k, rel)


def test_pipeline_remat_matches_plain(mesh, per_stage):
    """remat=True recomputes stage internals in the backward — forward
    and gradients must be identical to the plain schedule."""
    x = jax.random.normal(jax.random.PRNGKey(5), (8, D), jnp.float32)
    stacked = stack_stage_params(per_stage, mesh)
    plain = pipeline_apply(stage_fn, mesh, num_microbatches=4)
    remat = pipeline_apply(stage_fn, mesh, num_microbatches=4, remat=True)

    np.testing.assert_allclose(
        np.asarray(remat(stacked, x)), np.asarray(plain(stacked, x)),
        rtol=1e-6, atol=1e-6,
    )
    g_plain = jax.grad(lambda p: jnp.mean(plain(p, x) ** 2))(stacked)
    g_remat = jax.grad(lambda p: jnp.mean(remat(p, x) ** 2))(stacked)
    for a, b in zip(jax.tree.leaves(g_plain), jax.tree.leaves(g_remat)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)


def test_lm_pp_remat_matches_plain(mesh):
    """lm_pp(remat=True): same loss and grads as the plain pipeline."""
    from fluxdistributed_tpu.models import lm_tiny
    from fluxdistributed_tpu.models.transformer_lm import lm_pp

    model = lm_tiny(vocab=32, dim=32, num_heads=2, mlp_dim=64, depth=S,
                    dtype=jnp.float32, dropout=0.0)
    toks = np.random.default_rng(0).integers(0, 32, (8, 16)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), toks, train=False)["params"]

    outs = []
    for flag in (False, True):
        split, loss_fn, _ = lm_pp(model, mesh, num_microbatches=4, remat=flag)
        pp = split(params)
        l, g = jax.value_and_grad(
            lambda p: loss_fn(p, {}, {"tokens": toks}, False)[0]
        )(pp)
        outs.append((float(l), g))
    assert outs[0][0] == pytest.approx(outs[1][0], rel=1e-6)
    for a, b in zip(jax.tree.leaves(outs[0][1]), jax.tree.leaves(outs[1][1])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6)
