"""Run-history ledger + regression gating (obs/runs.py, bin/trends.py).

The detector's contract, pinned: baselines are per-(metric, topology)
rolling medians over error-free predecessors; exactly AT tolerance
passes; movement past tolerance in the GOOD direction is a note, not a
failure (memory-baseline semantics); a topology with <2 observations
has nothing to gate against.  Plus the ``--ingest`` backfill (field
preservation + idempotency), ``--check`` exit codes, and the
postmortem merge."""

import importlib.util
import json
import math
import os
import shutil

from fluxdistributed_tpu.obs import Registry
from fluxdistributed_tpu.obs import runs as runs_lib
from fluxdistributed_tpu.obs.flight import FlightRecorder

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _bench(throughput, fp="TPUv4:dp8", error=None, **metrics):
    metrics["throughput"] = throughput
    return runs_lib.run_record("bench", fingerprint=fp, phase="done",
                               error=error, metrics=metrics)


# ---------------------------------------------------------------------------
# record normalization + ledger IO
# ---------------------------------------------------------------------------


def test_run_record_drops_poisonous_metrics():
    """NaN/inf/non-numeric values must never reach a median."""
    rec = runs_lib.run_record(
        "bench", fingerprint="fp",
        metrics={"throughput": 100.0, "bad_nan": math.nan,
                 "bad_inf": math.inf, "bad_str": "fast", "ok_int": 3},
        error="x" * 1000)
    assert rec["schema"] == runs_lib.RUNS_SCHEMA
    assert rec["metrics"] == {"throughput": 100.0, "ok_int": 3.0}
    assert len(rec["error"]) == 500  # truncated, never unbounded


def test_append_load_roundtrip_tolerates_torn_tail(tmp_path):
    p = str(tmp_path / "runs.jsonl")
    assert runs_lib.append_run(p, _bench(100.0))
    assert runs_lib.append_run(p, _bench(101.0))
    with open(p, "a") as f:
        f.write('{"schema": "fdtpu-runs/v1", "kind": "ben')  # the tear
    runs = runs_lib.load_runs(p)
    assert [r["metrics"]["throughput"] for r in runs] == [100.0, 101.0]
    assert runs_lib.load_runs(str(tmp_path / "absent.jsonl")) == []


def test_append_run_never_raises(tmp_path, capsys):
    # a regular file poses as the parent dir: fails even as root
    (tmp_path / "ro").write_text("not a directory")
    assert runs_lib.append_run(str(tmp_path / "ro" / "runs.jsonl"),
                               _bench(1.0)) is False
    assert "obs.runs" in capsys.readouterr().err


# ---------------------------------------------------------------------------
# the regression detector
# ---------------------------------------------------------------------------


def test_exactly_at_tolerance_passes_strictly_beyond_fails():
    """The 10% throughput tolerance is inclusive: 90 vs baseline 100
    passes, 89 fails."""
    history = [_bench(100.0), _bench(100.0), _bench(100.0)]
    at_edge = runs_lib.check_regressions(history + [_bench(90.0)])
    assert at_edge["failures"] == []
    assert any(r["verdict"] == "ok" and r["metric"] == "throughput"
               for r in at_edge["rows"])
    beyond = runs_lib.check_regressions(history + [_bench(89.0)])
    assert len(beyond["failures"]) == 1
    assert "throughput" in beyond["failures"][0]
    assert "bad direction" in beyond["failures"][0]


def test_unknown_topology_and_first_run_are_notes_not_failures():
    """One observation — or a fingerprint nobody has seen — has no
    baseline; CI must not gate on it."""
    out = runs_lib.check_regressions([_bench(50.0, fp="TPUv5:new")])
    assert out["failures"] == []
    assert any("no baseline yet" in n for n in out["notes"])
    assert out["rows"][0]["verdict"] == "no-baseline"
    # fingerprint=None groups under "unknown" and behaves the same
    out = runs_lib.check_regressions(
        [runs_lib.run_record("bench", metrics={"throughput": 5.0})])
    assert out["failures"] == []


def test_shrinking_lower_is_better_metric_is_a_note():
    """Memory-baseline semantics: peak HBM (or compile time) dropping
    past tolerance means 're-record the baseline', never 'fail CI'."""
    mk = lambda v: runs_lib.run_record(
        "bench", fingerprint="fp", metrics={"peak_hbm_bytes": v})
    out = runs_lib.check_regressions([mk(1000.0), mk(1000.0), mk(500.0)])
    assert out["failures"] == []
    assert any("GOOD direction" in n and "peak_hbm_bytes" in n
               for n in out["notes"])
    assert any(r["verdict"] == "improved" for r in out["rows"])
    # ...while GROWING past tolerance on the same metric does gate
    out = runs_lib.check_regressions([mk(1000.0), mk(1000.0), mk(1200.0)])
    assert len(out["failures"]) == 1 and "peak_hbm_bytes" in out["failures"][0]


def test_error_records_are_history_not_observations():
    """A dead round carrying a (bogus) metric must not drag the
    baseline or trip the gate."""
    runs = [_bench(100.0), _bench(100.0),
            _bench(1.0, error="OOM"),  # dead — excluded from series
            _bench(98.0)]
    out = runs_lib.check_regressions(runs)
    assert out["failures"] == []
    row = next(r for r in out["rows"] if r["metric"] == "throughput")
    assert row["n"] == 3  # the error record never entered the series


def test_baselines_are_per_topology():
    """dp8's history must not gate dp16's first real run."""
    runs = [_bench(100.0), _bench(100.0), _bench(100.0),
            _bench(40.0, fp="TPUv4:dp16")]  # different topology, slower
    out = runs_lib.check_regressions(runs)
    assert out["failures"] == []  # dp16 has no baseline of its own


# ---------------------------------------------------------------------------
# ingest backfill
# ---------------------------------------------------------------------------


def test_ingest_preserves_fields_and_dedupes(tmp_path):
    """BENCH_r05 (phase/retryable/probe_attempts=91) and a multichip
    round survive the trip into the ledger verbatim; re-ingesting adds
    nothing."""
    src = [shutil.copy(os.path.join(REPO, n), tmp_path)
           for n in ("BENCH_r05.json", "MULTICHIP_r03.json")]
    ledger = str(tmp_path / "runs.jsonl")
    added, skipped = runs_lib.ingest_paths(ledger, src)
    assert (added, skipped) == (2, 0)
    runs = runs_lib.load_runs(ledger)
    bench = next(r for r in runs if r["kind"] == "bench")
    orig = json.load(open(os.path.join(REPO, "BENCH_r05.json")))["parsed"]
    assert bench["source"] == "BENCH_r05.json"
    assert bench.get("phase") == orig.get("phase")
    assert bench.get("retryable") == orig.get("retryable")
    assert bench["probe_attempts"] == orig["probe_attempts"] == 91
    assert "probe_logs" not in json.dumps(bench)  # log tails stay out
    multi = next(r for r in runs if r["kind"] == "multichip")
    assert multi["n_devices"] and "error" not in multi  # ok round
    # idempotent by source basename
    assert runs_lib.ingest_paths(ledger, src) == (0, 2)
    assert len(runs_lib.load_runs(ledger)) == 2


def test_committed_ledger_is_clean():
    """The acceptance criterion's first half: ``--check`` on the
    repo's own history must pass."""
    runs = runs_lib.load_runs(
        os.path.join(REPO, "benchmarks", "hw", "runs.jsonl"))
    assert len(runs) >= 10  # the five dead bench + five multichip rounds
    assert runs_lib.check_regressions(runs)["failures"] == []


# ---------------------------------------------------------------------------
# the trends CLI gate
# ---------------------------------------------------------------------------


def _trends():
    spec = importlib.util.spec_from_file_location(
        "trends", os.path.join(REPO, "bin", "trends.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_trends_check_exit_codes(tmp_path, capsys):
    """The acceptance criterion's second half: ``--check`` exits 0 on
    clean history and 2 the moment an injected throughput regression
    lands."""
    trends = _trends()
    ledger = str(tmp_path / "runs.jsonl")
    for v in (100.0, 101.0, 99.0):
        runs_lib.append_run(ledger, _bench(v))
    assert trends.main(["--check", "--ledger", ledger]) == 0
    assert "no regressions" in capsys.readouterr().out
    # inject a regression: 80 vs median ~100 is past the 10% tolerance
    runs_lib.append_run(ledger, _bench(80.0))
    assert trends.main(["--check", "--ledger", ledger]) == 2
    assert "REGRESSION" in capsys.readouterr().out
    # a missing ledger is usage error 1, not a silent pass
    assert trends.main(["--check", "--ledger",
                        str(tmp_path / "absent.jsonl")]) == 1


def test_trends_ingest_cli(tmp_path, capsys):
    trends = _trends()
    shutil.copy(os.path.join(REPO, "BENCH_r05.json"), tmp_path)
    ledger = str(tmp_path / "runs.jsonl")
    pat = str(tmp_path / "BENCH_r*.json")
    assert trends.main(["--ledger", ledger, "--ingest", pat]) == 0
    assert "ingested 1 record(s)" in capsys.readouterr().out
    assert trends.main(["--ledger", ledger, "--ingest", pat]) == 0
    assert "1 skipped" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# postmortem merge
# ---------------------------------------------------------------------------


def test_postmortem_names_hard_death_and_merges_evidence(tmp_path):
    """A footer-less flight dump + a supervisor episode ledger merge
    into one timeline that names the death for what it was."""
    flight = str(tmp_path / "flight.jsonl")
    fr = FlightRecorder(flight, flush_every=1, fingerprint="fpX")
    for i in range(3):
        fr.record(step=i, loss=0.5)
    # no dump(): the process "died" here
    sup = str(tmp_path / "ledger.json")
    with open(sup, "w") as f:
        json.dump({"result": "crashed", "episodes": [
            {"n": 1, "class": "crashed", "rc": -9, "steps": 2,
             "wall_seconds": 1.0, "action": "restart_budget_exhausted"},
        ]}, f)
    text = runs_lib.postmortem_timeline(flight_path=flight,
                                        supervisor_ledger=sup)
    assert "fdtpu postmortem" in text
    assert "hard death" in text  # missing footer named as such
    assert "step=2" in text or '"step": 2' in text or "step 2" in text
    assert "crashed" in text
    assert text.strip().splitlines()[-1].startswith("verdict:")


def test_postmortem_with_clean_exit_reports_footer(tmp_path):
    flight = str(tmp_path / "flight.jsonl")
    fr = FlightRecorder(flight, flush_every=1)
    fr.record(step=0)
    fr.dump("done", steps=1)
    text = runs_lib.postmortem_timeline(flight_path=flight)
    assert "hard death" not in text
    assert "done" in text


# ---------------------------------------------------------------------------
# the run_info stitch gauge
# ---------------------------------------------------------------------------


def test_set_run_info_registers_labeled_gauge():
    reg = Registry()
    runs_lib.set_run_info(reg, "train", mode="spmd")
    text = reg.prometheus_text()
    assert "fdtpu_run_info{" in text
    assert 'component="train"' in text
    assert 'mode="spmd"' in text
    assert runs_lib.RUNS_SCHEMA in text  # schemas label stitches dumps
    # idempotent: a second call must not raise on re-registration
    runs_lib.set_run_info(reg, "train", mode="spmd")
