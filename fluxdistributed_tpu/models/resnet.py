"""ResNet family — TPU-native replacement for the reference's Metalhead
models (``ResNet(depth)`` used at README.md:27, src/sync.jl:215,
test/single_device.jl:59).

Layout is NHWC (TPU-preferred; XLA tiles NHWC convs onto the MXU without
transposes), compute dtype is configurable (bfloat16 by default for the
MXU, parameters kept float32).

BatchNorm semantics — the reference's unsolved problem: its tests must
run ``Flux.testmode!`` because per-replica running stats break replica
equivalence (test/single_device.jl:51-58).  Here there are two modes:

* under plain ``jit`` with the batch sharded on the ``data`` axis, batch
  statistics are computed over the *global* batch (XLA inserts the
  cross-replica reductions automatically) — i.e. sync-BN by default, and
  running stats are identical on every replica by construction;
* under ``shard_map`` (explicit SPMD), pass ``bn_cross_replica_axis`` to
  get the same via an explicit ``pmean`` inside BatchNorm
  (flax's ``axis_name``).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

from .common import maybe_remat

__all__ = [
    "ResNet", "resnet18", "resnet34", "resnet50", "resnet101", "resnet152",
    "space_to_depth", "s2d_stem_kernel",
]

ModuleDef = Any


def space_to_depth(x, block: int = 2):
    """(B, H, W, C) → (B, H/b, W/b, b²·C): each b×b pixel block becomes
    channels, ordered (row-offset, col-offset, channel).

    The MLPerf-style stem transform: the 7×7/2 stem conv reads 3-channel
    input — a contraction dim of 3 that strands most of the MXU's 128
    lanes and whose stride-2 taps defeat clean tiling.  On the s2d
    layout the equivalent conv (see :func:`s2d_stem_kernel`) is 4×4/1
    over 12 channels — same arithmetic, MXU-shaped.  Works on numpy or
    jax arrays; do it host-side in the input pipeline when feeding a
    ``space_to_depth=True`` model (in-graph fallback otherwise).
    """
    b, h, w, c = x.shape
    assert h % block == 0 and w % block == 0, (h, w, block)
    x = x.reshape(b, h // block, block, w // block, block, c)
    x = x.transpose(0, 1, 3, 2, 4, 5)
    return x.reshape(b, h // block, w // block, block * block * c)


def s2d_stem_kernel(w):
    """Exact re-layout of a 7×7-stride-2 stem kernel (7, 7, C, O) into
    the equivalent 4×4-stride-1 kernel (4, 4, 4·C, O) over
    :func:`space_to_depth`-transformed input.

    Derivation: pad the 7-tap kernel to 8 with one leading zero (the
    stride-2 window ``x[2p + k - 3]``, k∈[0,7) equals a 4-tap stride-1
    window over pixel pairs with taps at p-2…p+1); each 2×2 sub-block of
    the 8×8 kernel contracts against the matching s2d channel group.
    With this kernel and padding (2, 1), ``conv(s2d(x))`` reproduces the
    original stem exactly — proven in tests/test_resnet_s2d.py.
    """
    import numpy as np

    w = np.asarray(w)
    kh, kw, c, o = w.shape
    assert (kh, kw) == (7, 7), "s2d transform is for the 7x7 stem"
    w8 = np.zeros((8, 8, c, o), w.dtype)
    w8[1:, 1:] = w
    w8 = w8.reshape(4, 2, 4, 2, c, o).transpose(0, 2, 1, 3, 4, 5)
    return w8.reshape(4, 4, 4 * c, o)


_PAD3 = ((1, 1), (1, 1))  # torch-convention padding for 3x3 convs


class BasicBlock(nn.Module):
    """3x3 + 3x3 residual block (ResNet-18/34)."""

    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(
            self.filters, (3, 3), (self.strides, self.strides), padding=_PAD3
        )(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters, (3, 3), padding=_PAD3)(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters, (1, 1), (self.strides, self.strides), name="downsample_conv"
            )(residual)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu(y + residual)


class BottleneckBlock(nn.Module):
    """1x1 → 3x3 → 1x1 bottleneck block (ResNet-50/101/152)."""

    filters: int
    strides: int
    conv: ModuleDef
    norm: ModuleDef

    @nn.compact
    def __call__(self, x):
        residual = x
        y = self.conv(self.filters, (1, 1))(x)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(
            self.filters, (3, 3), (self.strides, self.strides), padding=_PAD3
        )(y)
        y = self.norm()(y)
        y = nn.relu(y)
        y = self.conv(self.filters * 4, (1, 1))(y)
        y = self.norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = self.conv(
                self.filters * 4, (1, 1), (self.strides, self.strides), name="downsample_conv"
            )(residual)
            residual = self.norm(name="downsample_bn")(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    """Configurable ResNet, NHWC, bf16 compute / f32 params by default."""

    stage_sizes: Sequence[int]
    block: type
    num_classes: int = 1000
    width: int = 64
    dtype: Any = jnp.bfloat16
    norm_dtype: Any = None  # BatchNorm compute dtype; defaults to ``dtype``
    bn_momentum: float = 0.9
    bn_cross_replica_axis: str | None = None
    # rematerialize each residual block in the backward pass — activation
    # memory drops from O(total blocks) to O(1 block) for ~1 extra
    # forward of FLOPs (jax.checkpoint): the HBM lever for bigger
    # per-chip batches
    remat: bool = False
    # MXU-shaped stem: accept space_to_depth(x) input (B, H/2, W/2, 12)
    # and run the equivalent 4x4/1 conv instead of 7x7/2 on 3 channels.
    # Raw (B, H, W, 3) input is transformed in-graph as a fallback; feed
    # pre-transformed batches for peak rate.  Stem kernel shape changes
    # to (4, 4, 12, width) — import 7x7 weights via s2d_stem_kernel.
    space_to_depth: bool = False

    @nn.compact
    def __call__(self, x, train: bool = True):
        conv = partial(
            nn.Conv,
            use_bias=False,
            dtype=self.dtype,
            kernel_init=nn.initializers.he_normal(),
            padding="SAME",
        )
        norm = partial(
            nn.BatchNorm,
            use_running_average=not train,
            momentum=self.bn_momentum,
            epsilon=1e-5,
            dtype=self.norm_dtype if self.norm_dtype is not None else self.dtype,
            axis_name=self.bn_cross_replica_axis,
        )
        # Torch-convention explicit padding throughout (stem 3, 3x3 convs
        # 1, maxpool 1): identical to SAME at stride 1, but at stride 2
        # SAME pads asymmetrically — explicit padding keeps the model
        # numerically importable from torchvision-layout weights
        # (models/torch_import.py), the analog of the reference's
        # pretrained-weight path (src/preprocess.jl:9-24).
        x = jnp.asarray(x, self.dtype)
        if self.space_to_depth:
            if x.shape[-1] == 3:
                x = space_to_depth(x)  # in-graph fallback; prefer host-side
            # padding (2,1): the 8-padded stride-2 window spans s2d
            # positions p-2..p+1 (see s2d_stem_kernel)
            x = conv(
                self.width, (4, 4), (1, 1), padding=((2, 1), (2, 1)),
                name="stem_conv",
            )(x)
        else:
            x = conv(
                self.width, (7, 7), (2, 2), padding=((3, 3), (3, 3)),
                name="stem_conv",
            )(x)
        x = norm(name="stem_bn")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding=((1, 1), (1, 1)))
        block_cls = maybe_remat(self.block, self.remat)
        k = 0
        for i, nblocks in enumerate(self.stage_sizes):
            for j in range(nblocks):
                strides = 2 if i > 0 and j == 0 else 1
                x = block_cls(
                    filters=self.width * (2**i),
                    strides=strides,
                    conv=conv,
                    norm=norm,
                    # pin the unwrapped auto-name (BasicBlock_3, ...): the
                    # remat wrapper would otherwise rename the scope and
                    # orphan existing checkpoints / imported torch weights
                    name=f"{self.block.__name__}_{k}",
                )(x)
                k += 1
        x = jnp.mean(x, axis=(1, 2))
        x = nn.Dense(
            self.num_classes,
            dtype=jnp.float32,
            kernel_init=nn.initializers.variance_scaling(1.0, "fan_in", "truncated_normal"),
        )(x)
        return x.astype(jnp.float32)


def resnet18(num_classes: int = 1000, **kw) -> ResNet:
    return ResNet([2, 2, 2, 2], BasicBlock, num_classes=num_classes, **kw)


def resnet34(num_classes: int = 1000, **kw) -> ResNet:
    return ResNet([3, 4, 6, 3], BasicBlock, num_classes=num_classes, **kw)


def resnet50(num_classes: int = 1000, **kw) -> ResNet:
    return ResNet([3, 4, 6, 3], BottleneckBlock, num_classes=num_classes, **kw)


def resnet101(num_classes: int = 1000, **kw) -> ResNet:
    return ResNet([3, 4, 23, 3], BottleneckBlock, num_classes=num_classes, **kw)


def resnet152(num_classes: int = 1000, **kw) -> ResNet:
    return ResNet([3, 8, 36, 3], BottleneckBlock, num_classes=num_classes, **kw)
