"""Black-box flight recorder: the last N steps survive a SIGKILL.

Every observability layer before this one is *within-run*: metrics are
scraped while the process lives, spans and profiles export in a
``finally`` block — and a SIGKILL (preemption, OOM-killer, a wedged
collective the supervisor shoots) runs no ``finally``.  All five
hardware bench rounds died exactly like that, and the evidence was
whatever stamps made it into one error JSON.  This module is the
aircraft recorder for that case: a bounded ring of per-step structured
records, flushed APPEND-ONLY to disk every ``flush_every`` records with
an atomically-replaced sidecar checkpoint, so a hard kill at any
instant loses at most one flush interval of history and can never tear
the already-flushed prefix.

Write path (crash-ordered by construction):

* :meth:`FlightRecorder.record` — O(1): append to the in-memory ring
  and a pending buffer; every ``flush_every`` records the buffer is
  appended to ``<path>`` (one JSON object per line) and fsync'd, then
  the tiny ``<path>.ckpt`` sidecar is atomically replaced (write tmp +
  ``os.replace``) with the flush summary.  A SIGKILL mid-append can
  tear only the final line — the reader tolerates that — and the
  sidecar is either the previous complete summary or the new one,
  never a hybrid.
* :meth:`FlightRecorder.dump` — the soft-exit path (done / guard halt /
  crash-with-traceback / preemption): flush the remainder and append a
  terminal ``end`` line carrying the exit status and the topology
  fingerprint.  A dump-less file IS the hard-death signature the
  postmortem keys on.

Read path: :func:`read_flight` parses a dump tolerantly (torn tail
line skipped, missing footer reported as ``end=None``) so forensics
works on exactly the files crashes leave behind.

The recorder is deliberately jax-free on the hot path: the topology
fingerprint is resolved lazily (best-effort) at header time, and every
I/O error is swallowed after one stderr warning — a black box that can
crash the plane is worse than no black box.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional

__all__ = ["FLIGHT_SCHEMA", "FlightRecorder", "read_flight"]

#: on-disk schema tag (header + every sidecar checkpoint carry it)
FLIGHT_SCHEMA = "fdtpu-flight/v1"


def _lazy_fingerprint() -> Optional[str]:
    """Topology fingerprint, best-effort: on a wedged backend
    ``jax.devices()`` can hang, so this only runs where jax is already
    live (header/footer of an in-flight run) and any failure reads as
    ``None``, never a crash."""
    try:
        from ..compilation import topology_fingerprint

        return topology_fingerprint()
    except Exception:  # noqa: BLE001 — forensics must never raise
        return None


class FlightRecorder:
    """Bounded per-step black box with crash-durable flushes.

    Parameters
    ----------
    path: the append-only JSONL dump (``<path>.ckpt`` rides alongside)
    ring: in-memory record bound (the dump file is bounded by the run,
        not the ring — the ring exists so ``records()`` and the final
        checkpoint stay O(ring) however long the run)
    flush_every: records per durable flush — the maximum history a
        SIGKILL can lose
    fingerprint: topology fingerprint for the header/footer; ``None``
        resolves lazily (best-effort) at first flush
    meta: free-form run metadata for the header (component, argv, ...)
    """

    def __init__(
        self,
        path: str,
        *,
        ring: int = 512,
        flush_every: int = 8,
        fingerprint: Optional[str] = None,
        meta: Optional[Dict[str, Any]] = None,
    ):
        if ring < 1:
            raise ValueError(f"ring must be >= 1, got {ring}")
        if flush_every < 1:
            raise ValueError(f"flush_every must be >= 1, got {flush_every}")
        self.path = path
        self.flush_every = flush_every
        self._ring: deque = deque(maxlen=ring)
        self._pending: List[dict] = []
        self._lock = threading.Lock()
        self._fh = None
        self._fingerprint = fingerprint
        self._meta = dict(meta or {})
        self._recorded = 0
        self._flushed = 0
        self._flushes = 0
        self._ended = False
        self._warned = False

    # -- producer side -------------------------------------------------
    def record(self, **fields) -> None:
        """Append one structured record (a step, a serve tick, ...).
        O(1) between flushes; never raises — a black box that can kill
        the loop it watches is a liability, not an instrument."""
        rec = {"kind": "record", "t": round(time.time(), 3), **fields}
        with self._lock:
            if self._ended:
                return
            self._ring.append(rec)
            self._pending.append(rec)
            self._recorded += 1
            if len(self._pending) >= self.flush_every:
                self._flush_locked()

    def flush(self) -> None:
        """Force-flush pending records (the cadence flush is automatic;
        this is for callers bracketing known-risky work)."""
        with self._lock:
            self._flush_locked()

    def dump(self, status: str, error: Optional[str] = None,
             **extra) -> Optional[str]:
        """The soft-exit dump: flush everything and append a terminal
        ``end`` line with ``status`` (done/halt/crash/preempted/stall/
        closed), the error text and the topology fingerprint.  Returns
        the dump path (None when writing failed).  Idempotent — only
        the first call writes the footer; a SIGKILL simply never calls
        it, which is itself the signal :func:`read_flight` reports."""
        with self._lock:
            if self._ended:
                return self.path
            self._ended = True
            foot = {
                "kind": "end",
                "t": round(time.time(), 3),
                "status": str(status),
                "records": self._recorded,
                "fingerprint": self._resolved_fingerprint(),
            }
            if error:
                foot["error"] = str(error)[:500]
            if extra:
                foot.update(extra)
            self._pending.append(foot)
            self._flush_locked(final=True)
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None
        return self.path

    def close(self) -> None:
        """Flush-and-close without a status verdict (serve schedulers
        being retired mid-process)."""
        self.dump("closed")

    # -- introspection (tests / postmortem in-process) -----------------
    def records(self) -> List[dict]:
        """Snapshot of the in-memory ring (newest last)."""
        with self._lock:
            return list(self._ring)

    @property
    def recorded(self) -> int:
        return self._recorded

    @property
    def flushed(self) -> int:
        return self._flushed

    # -- internals -----------------------------------------------------
    def _resolved_fingerprint(self) -> Optional[str]:
        if self._fingerprint is None:
            self._fingerprint = _lazy_fingerprint()
        return self._fingerprint

    def _flush_locked(self, final: bool = False) -> None:
        if not self._pending and not final:
            return
        try:
            if self._fh is None:
                d = os.path.dirname(os.path.abspath(self.path))
                os.makedirs(d, exist_ok=True)
                fresh = (not os.path.exists(self.path)
                         or os.path.getsize(self.path) == 0)
                self._fh = open(self.path, "a")
                if fresh:
                    header = {
                        "kind": "header",
                        "schema": FLIGHT_SCHEMA,
                        "t": round(time.time(), 3),
                        "flush_every": self.flush_every,
                        "fingerprint": self._resolved_fingerprint(),
                        "meta": self._meta,
                    }
                    self._fh.write(json.dumps(header) + "\n")
            for rec in self._pending:
                self._fh.write(json.dumps(rec) + "\n")
            self._flushed += len(
                [r for r in self._pending if r["kind"] == "record"])
            self._pending.clear()
            self._flushes += 1
            self._fh.flush()
            os.fsync(self._fh.fileno())
            self._checkpoint()
        except Exception as e:  # noqa: BLE001 — never kill the run
            self._pending.clear()
            if not self._warned:
                self._warned = True
                print(f"obs.flight: flush to {self.path} failed "
                      f"({type(e).__name__}: {e}) — recording continues "
                      "in memory only", file=sys.stderr)

    def _checkpoint(self) -> None:
        """Atomically replace the sidecar summary: a reader that finds
        a torn dump tail still gets a consistent (previous-or-current,
        never hybrid) snapshot of how far the recorder provably got."""
        ck = {
            "schema": FLIGHT_SCHEMA,
            "t": round(time.time(), 3),
            "fingerprint": self._fingerprint,
            "recorded": self._recorded,
            "flushed": self._flushed,
            "flushes": self._flushes,
            "last": self._ring[-1] if self._ring else None,
        }
        path = self.path + ".ckpt"
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(ck, f)
        os.replace(tmp, path)


def read_flight(path: str) -> dict:
    """Tolerant dump reader for exactly the files crashes leave behind.

    Returns ``{"header", "records", "end", "torn", "checkpoint"}``:
    ``header``/``end`` are the framing lines (either may be ``None`` —
    a missing ``end`` is the hard-death signature), ``records`` the
    per-step lines in order, ``torn`` counts unparseable lines (a
    SIGKILL mid-append tears at most the final one), ``checkpoint`` the
    sidecar summary when present."""
    out: dict = {"header": None, "records": [], "end": None, "torn": 0,
                 "checkpoint": None}
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                obj = json.loads(line)
            except (json.JSONDecodeError, ValueError):
                out["torn"] += 1
                continue
            kind = obj.get("kind")
            if kind == "header" and out["header"] is None:
                out["header"] = obj
            elif kind == "end":
                out["end"] = obj
            elif kind == "record":
                out["records"].append(obj)
    try:
        with open(path + ".ckpt") as f:
            out["checkpoint"] = json.load(f)
    except (OSError, json.JSONDecodeError, ValueError):
        pass
    return out
