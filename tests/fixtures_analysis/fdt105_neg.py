"""FDT105 negative: axis names sourced from the mesh.py constants."""
from jax.sharding import PartitionSpec as P

from fluxdistributed_tpu.mesh import DATA_AXIS, PIPE_AXIS


def good_spec():
    return P(DATA_AXIS, None)


def shard_over(mesh, batch_axis=DATA_AXIS):
    return mesh.shape[batch_axis]


def stage_count(mesh):
    return mesh.shape[PIPE_AXIS]


def free_string():
    # a string equal to no declared axis, outside P()/axis positions —
    # out of the rule's scope entirely
    return "datalog"


def good_rule_table(ShardLargest, FSDP_AXIS):
    # rule-table values sourced from the mesh constants
    return [(r".*", ShardLargest(axis=FSDP_AXIS))]
