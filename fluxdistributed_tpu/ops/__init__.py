from .losses import logitcrossentropy, crossentropy, mse
from .metrics import topkaccuracy, onehot

__all__ = ["logitcrossentropy", "crossentropy", "mse", "topkaccuracy", "onehot"]
