"""Unified observability layer (fluxdistributed_tpu.obs).

Covers the four obs modules at unit level — Prometheus exposition
format (label escaping, counter monotonicity, histogram cumulation),
span nesting + Chrome/Perfetto trace-event validity, watchdog stall
detection, jax.monitoring recompile flagging — plus the serve-metrics
parity contract: every pre-registry ``fdtpu_serve_*`` series name and
the ``Scheduler.metrics()`` dict keys survive the registry migration
byte-identically.
"""

from __future__ import annotations

import json
import threading
import time

import pytest

from fluxdistributed_tpu.obs import (
    JsonlSink,
    Observation,
    Registry,
    SpanTracer,
    StepWatchdog,
    current_span,
    get_registry,
    jaxmon,
    start_metrics_server,
)


# ---------------------------------------------------------------------------
# metrics: registry + exposition format
# ---------------------------------------------------------------------------

def test_counter_monotonic():
    r = Registry()
    c = r.counter("c_total", "a counter")
    c.inc()
    c.inc(2.5)
    assert c.value() == 3.5
    with pytest.raises(ValueError, match="monotonic"):
        c.inc(-1)
    assert c.value() == 3.5


def test_gauge_set_inc_dec_and_callback():
    r = Registry()
    g = r.gauge("g", "a gauge")
    g.set(10)
    g.dec(3)
    assert g.value() == 7
    cb = r.gauge("g_cb", "computed at scrape time")
    cb.set_function(lambda: 42)
    assert cb.value() == 42
    # a dead callback must not kill the scrape — it reads NaN
    cb.set_function(lambda: 1 / 0)
    text = r.prometheus_text()
    assert "g_cb nan" in text.lower()


def test_get_or_create_and_conflicts():
    r = Registry()
    a = r.counter("x_total", "first")
    assert r.counter("x_total", "again") is a  # idempotent re-register
    with pytest.raises(ValueError, match="already registered"):
        r.gauge("x_total")  # kind conflict
    with pytest.raises(ValueError, match="already registered"):
        r.counter("x_total", labelnames=("k",))  # label conflict


def test_label_escaping_and_exposition_lines():
    r = Registry()
    c = r.counter("esc_total", 'tricky "help"', labelnames=("path",))
    c.labels(path='a"b\\c\nd').inc(2)
    text = r.prometheus_text()
    assert "# TYPE esc_total counter" in text
    # backslash, quote and newline must be escaped inside the quotes
    assert 'esc_total{path="a\\"b\\\\c\\nd"} 2' in text
    # unlabeled metrics expose as bare `name value`
    g = r.gauge("plain", "no labels")
    g.set(1.5)
    assert "\nplain 1.5" in r.prometheus_text()


def test_labels_validation():
    r = Registry()
    c = r.counter("l_total", "", labelnames=("a", "b"))
    with pytest.raises(ValueError, match="label values"):
        c.labels("only-one")
    with pytest.raises(ValueError, match="has labels"):
        c.labels(a="x", wrong="y")
    with pytest.raises(ValueError, match="call .labels"):
        c.inc()  # labeled metric has no default cell
    c.labels(a="x", b="y").inc()
    assert c.value("x", "y") == 1


def test_histogram_cumulative_buckets_sum_count():
    r = Registry()
    h = r.histogram("lat_seconds", "latency", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 99.0):
        h.observe(v)
    text = r.prometheus_text()
    assert 'lat_seconds_bucket{le="0.1"} 1' in text
    assert 'lat_seconds_bucket{le="1"} 2' in text  # cumulative
    assert 'lat_seconds_bucket{le="+Inf"} 3' in text
    assert "lat_seconds_count 3" in text
    assert "# TYPE lat_seconds histogram" in text
    assert h.cell_sum() == pytest.approx(99.55)
    with h.time():
        pass
    assert h.cell_count() == 4


def test_snapshot_and_jsonl_sink(tmp_path):
    r = Registry()
    r.counter("s_total", "").inc(2)
    r.histogram("h_seconds", "").observe(0.25)
    snap = r.snapshot()
    assert snap["s_total"] == 2
    assert snap["h_seconds_count"] == 1
    path = tmp_path / "m.jsonl"
    sink = JsonlSink(str(path), r)
    sink.write(step=5)
    r.counter("s_total", "").inc()
    sink.write(step=6, final=True)
    lines = [json.loads(l) for l in path.read_text().splitlines()]
    assert len(lines) == 2
    assert lines[0]["step"] == 5 and lines[0]["metrics"]["s_total"] == 2
    assert lines[1]["final"] and lines[1]["metrics"]["s_total"] == 3


def test_registry_value_reader():
    r = Registry()
    assert r.value("missing", default=-1) == -1
    r.counter("v_total", "").inc(4)
    assert r.value("v_total") == 4


# ---------------------------------------------------------------------------
# spans: nesting + Chrome trace-event JSON
# ---------------------------------------------------------------------------

def test_span_nesting_and_chrome_export(tmp_path):
    t = SpanTracer()
    assert current_span() is None
    with t.span("step", idx=3):
        assert current_span() == "step"
        with t.span("dispatch"):
            assert current_span() == "dispatch"
            time.sleep(0.002)
        assert current_span() == "step"
    assert current_span() is None

    path = tmp_path / "trace.json"
    n = t.export_chrome_trace(str(path))
    assert n == 2
    doc = json.loads(path.read_text())  # valid JSON by construction
    evs = doc["traceEvents"]
    assert {e["name"] for e in evs} == {"step", "dispatch"}
    for e in evs:
        # the trace-event schema fields Perfetto/chrome://tracing need
        assert e["ph"] == "X"
        assert isinstance(e["ts"], float) and isinstance(e["dur"], float)
        assert "pid" in e and "tid" in e
    outer = next(e for e in evs if e["name"] == "step")
    inner = next(e for e in evs if e["name"] == "dispatch")
    # proper nesting: the inner complete-event lies within the outer
    assert outer["ts"] <= inner["ts"]
    assert inner["ts"] + inner["dur"] <= outer["ts"] + outer["dur"] + 1e-3
    assert outer["args"] == {"idx": 3}


def test_span_disabled_is_noop_and_histogram_feed():
    r = Registry()
    h = r.histogram("phase_seconds", "", labelnames=("phase",))
    off = SpanTracer(enabled=False)
    with off.span("x"):
        assert current_span() is None  # no stack push on the noop path
    assert len(off) == 0

    on = SpanTracer(histogram=h)
    with on.span("fit"):
        pass
    assert h.labels(phase="fit").count == 1


def test_span_ring_bounds_memory():
    t = SpanTracer(max_events=4)
    for i in range(10):
        with t.span(f"s{i}"):
            pass
    assert len(t) == 4
    assert t.dropped == 6
    assert [e["name"] for e in t.trace_events()] == ["s6", "s7", "s8", "s9"]


# ---------------------------------------------------------------------------
# watchdog
# ---------------------------------------------------------------------------

def test_watchdog_quiet_on_cadence_fires_on_stall():
    r = Registry()
    fired = []
    w = StepWatchdog(factor=3.0, min_interval=0.05, warmup=2,
                     registry=r, on_stall=lambda e, th: fired.append((e, th)))
    for _ in range(8):
        w.beat()
        time.sleep(0.01)
    assert w.poll() is False  # steady cadence: quiet
    assert r.value("fdtpu_watchdog_stalls_total") == 0
    time.sleep(0.3)  # ~30x the median interval > threshold
    assert w.poll() is True
    assert w.poll() is False  # one warning per stall episode
    assert fired and fired[0][0] > fired[0][1]
    assert r.value("fdtpu_watchdog_stalls_total") == 1
    assert r.value("fdtpu_watchdog_stalled") == 1
    w.beat()  # recovery re-arms and clears the stalled gauge
    assert r.value("fdtpu_watchdog_stalled") == 0
    assert w.poll() is False


def test_watchdog_pause_exempts_known_long_work():
    """A checkpoint/eval longer than the threshold must NOT read as a
    stall (train() wraps those phases in pause()), and the paused span
    must not pollute the rolling median."""
    r = Registry()
    w = StepWatchdog(factor=3.0, min_interval=0.02, warmup=2, registry=r)
    for _ in range(6):
        w.beat()
        time.sleep(0.005)
    med_before = w.threshold()
    with w.pause():
        time.sleep(0.2)  # a long checkpoint: way past the threshold
        assert w.poll() is False  # suspended while paused
    assert w.poll() is False  # interval restarted on exit — no stall
    assert r.value("fdtpu_watchdog_stalls_total") == 0
    w.beat()
    assert w.threshold() == pytest.approx(med_before, rel=0.9)


def test_watchdog_escalates_persistent_stall():
    """A stall that persists escalate_after further threshold windows
    fires ONE escalation (counter + abort callback) — today's
    warn-once would otherwise sit silent on a permanently wedged loop."""
    r = Registry()
    escalated = []
    w = StepWatchdog(factor=2.0, min_interval=0.01, warmup=2, registry=r,
                     escalate_after=2,
                     on_escalate=lambda e, th: escalated.append((e, th)))
    for i in range(6):
        w.beat()
    # drive poll() with synthetic clocks: the threshold is
    # min_interval-floored, escalation sits at (1 + 2) x threshold
    thr = w.threshold()
    base = time.monotonic()
    assert w.poll(now=base + 2 * thr) is True  # the stall fires first
    assert r.value("fdtpu_watchdog_stalls_total") >= 1
    assert r.value("fdtpu_watchdog_escalations_total") == 0
    # inside the escalation window: nothing yet
    w.poll(now=base + 2.5 * thr)
    assert escalated == []
    # past (1 + escalate_after) x threshold: exactly one escalation
    w.poll(now=base + 3.5 * thr)
    w.poll(now=base + 5.0 * thr)
    assert len(escalated) == 1
    assert r.value("fdtpu_watchdog_escalations_total") == 1
    # a beat re-arms the whole episode machinery
    w.beat()
    assert r.value("fdtpu_watchdog_stalled") == 0
    w.poll(now=base + 100.0)
    w.poll(now=base + 200.0)
    assert r.value("fdtpu_watchdog_escalations_total") == 2


def test_watchdog_escalation_disabled_by_default():
    r = Registry()
    w = StepWatchdog(factor=2.0, min_interval=0.01, warmup=2, registry=r)
    for _ in range(6):
        w.beat()
    thr = w.threshold()
    w.poll(now=time.monotonic() + thr * 2)
    w.poll(now=time.monotonic() + thr * 1000)
    assert r.value("fdtpu_watchdog_stalls_total") == 1
    assert r.value("fdtpu_watchdog_escalations_total") == 0
    with pytest.raises(ValueError, match="escalate_after"):
        StepWatchdog(escalate_after=-1, registry=r)


def test_watchdog_pause_does_not_collapse_median():
    """The beat that ends a pause-containing iteration measures only
    the post-pause remainder; recording it would drive the rolling
    median toward zero and floor the threshold (false stalls on every
    slow-but-healthy step when eval runs every iteration)."""
    w = StepWatchdog(factor=3.0, min_interval=0.0, warmup=2,
                     registry=Registry())
    for _ in range(4):
        w.beat()
        time.sleep(0.02)
    med = statistics_median(w)
    for _ in range(6):  # eval_every=1 shape: pause inside EVERY iteration
        with w.pause():
            pass
        w.beat()  # immediately after pause exit: near-zero remainder
        time.sleep(0.02)
    assert statistics_median(w) == pytest.approx(med, rel=0.9), (
        "post-pause beats polluted the rolling median"
    )


def statistics_median(w: StepWatchdog) -> float:
    import statistics

    return statistics.median(w._intervals)


def test_jsonl_sink_writes_valid_json_for_nan_gauges(tmp_path):
    """A dead callback gauge reads NaN; the sink must still emit strict
    JSON (bare NaN tokens break jq — the file's whole purpose)."""
    r = Registry()
    r.gauge("dead", "").set_function(lambda: 1 / 0)
    r.counter("ok_total", "").inc()
    path = tmp_path / "m.jsonl"
    JsonlSink(str(path), r).write(step=1)
    rec = json.loads(path.read_text(), parse_constant=lambda c: pytest.fail(
        f"non-strict JSON constant {c} in sink output"))
    assert rec["metrics"]["dead"] is None
    assert rec["metrics"]["ok_total"] == 1


def test_watchdog_unarmed_during_warmup():
    w = StepWatchdog(factor=2.0, min_interval=0.0, warmup=5, registry=Registry())
    w.beat()
    w.beat()
    assert w.threshold() is None
    assert w.poll() is False  # never fires before the warmup beats


def test_watchdog_stall_names_innermost_active_phase(capsys):
    """A stall episode must say WHERE the loop wedged: the warning names
    the innermost active span/phase (registered cross-thread — the
    watchdog polls from its own thread) and last_where keeps it for
    callbacks."""
    from fluxdistributed_tpu.obs.spans import innermost_active, phase_scope

    r = Registry()
    w = StepWatchdog(factor=2.0, min_interval=0.01, warmup=2, registry=r)
    for _ in range(5):
        w.beat()
        time.sleep(0.005)
    entered, release = threading.Event(), threading.Event()

    def wedged_loop():  # the "hung dispatch" on the loop's own thread
        with phase_scope("dispatch"):
            entered.set()
            release.wait(5)

    t = threading.Thread(target=wedged_loop)
    t.start()
    try:
        assert entered.wait(2)
        assert innermost_active() == "dispatch"
        time.sleep(0.06)  # well past factor x median
        assert w.poll() is True
        assert w.last_where == "dispatch"
        err = capsys.readouterr().err
        assert "STALL" in err and "'dispatch'" in err
    finally:
        release.set()
        t.join()
    assert innermost_active() is None  # registry cleaned up on exit


def test_span_tracer_registers_active_span():
    from fluxdistributed_tpu.obs.spans import innermost_active

    t = SpanTracer()
    assert innermost_active() is None
    with t.span("step"):
        with t.span("h2d"):
            assert innermost_active() == "h2d"
        assert innermost_active() == "step"
    assert innermost_active() is None


def test_watchdog_thread_and_oom_fold_in():
    r = Registry()
    fired = threading.Event()
    w = StepWatchdog(factor=2.5, min_interval=0.02, warmup=2,
                     check_every=0.02, registry=r,
                     on_stall=lambda e, th: fired.set())
    with w:
        for _ in range(6):
            w.beat()
            time.sleep(0.01)
        w.note_skip(2)  # OOM skip: heartbeat + counted lost work
        assert fired.wait(2.0), "watchdog thread never fired on a stall"
    assert r.value("fdtpu_train_oom_skipped_total") == 2


# ---------------------------------------------------------------------------
# jaxmon: compile counters + steady-state recompile detector
# ---------------------------------------------------------------------------

def test_jaxmon_counts_compiles_and_flags_steady_recompiles():
    import jax
    import jax.numpy as jnp

    jaxmon.install()
    reg = get_registry()

    f = jax.jit(lambda x: x * 2 + 1)
    before = reg.value("fdtpu_jax_compiles_total")
    f(jnp.ones(3))  # warmup compile
    assert reg.value("fdtpu_jax_compiles_total") > before
    assert reg.value("fdtpu_jax_compile_seconds_total") > 0

    steady_before = reg.value("fdtpu_jax_steady_recompiles_total")
    warnings = []
    jaxmon.install(warn=warnings.append)
    with jaxmon.steady_state():
        f(jnp.ones(3))  # cache hit: not a recompile
        assert reg.value("fdtpu_jax_steady_recompiles_total") == steady_before
        f(jnp.ones(5))  # deliberate shape change -> recompile, flagged
    assert reg.value("fdtpu_jax_steady_recompiles_total") > steady_before
    assert any("RECOMPILE" in w for w in warnings)
    # outside the block the flag is restored: compiles count but don't flag
    after = reg.value("fdtpu_jax_steady_recompiles_total")
    f(jnp.ones(7))
    assert reg.value("fdtpu_jax_steady_recompiles_total") == after


# ---------------------------------------------------------------------------
# metrics endpoint (the trainer-side /metrics + /healthz)
# ---------------------------------------------------------------------------

def test_metrics_server_endpoints():
    import urllib.error
    import urllib.request

    r = Registry()
    r.counter("up_total", "").inc(3)
    health = {"ok": True, "steps": 7}
    srv = start_metrics_server(host="127.0.0.1", port=0, registry=r,
                               health_fn=lambda: dict(health))
    base = f"http://127.0.0.1:{srv.port}"
    try:
        with urllib.request.urlopen(f"{base}/metrics", timeout=10) as resp:
            assert resp.headers["Content-Type"].startswith("text/plain")
            assert "up_total 3" in resp.read().decode()
        with urllib.request.urlopen(f"{base}/healthz", timeout=10) as resp:
            assert json.loads(resp.read())["steps"] == 7
        health["ok"] = False  # unhealthy hook -> 503
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/healthz", timeout=10)
        assert ei.value.code == 503
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(f"{base}/nope", timeout=10)
        assert ei.value.code == 404
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# serve parity: the registry migration preserves every metric name
# ---------------------------------------------------------------------------

class _FakeEngine:
    """Pure-python stand-in for LMEngine: the scheduler's metrics
    surface is exercised without compiling anything."""

    max_slots = 2

    def validate_request(self, prompt_len, max_new_tokens):
        pass

    def prefill(self, slot, prompt, temperature, key):
        return 7, 8  # (first token, padded bucket size)

    def step_decode(self):
        return [1] * self.max_slots

    def reset_slot(self, slot):
        pass

    def compile_stats(self):
        return {"decode_compiles": 1, "prefill_compiles": 2,
                "insert_compiles": 1}


# every series the pre-registry hand-rolled exposition emitted;
# the refactor must keep them all (dashboards and scrapers depend on it)
PRE_REFACTOR_SERIES = [
    "fdtpu_serve_requests_submitted",
    "fdtpu_serve_requests_finished",
    "fdtpu_serve_requests_rejected",
    "fdtpu_serve_prefill_tokens",
    "fdtpu_serve_prefill_padded_tokens",
    "fdtpu_serve_prefill_sec",
    "fdtpu_serve_decode_tokens",
    "fdtpu_serve_decode_sec",
    "fdtpu_serve_ttft_sec_last",
    "fdtpu_serve_ttft_sec_sum",
    "fdtpu_serve_ttft_count",
    "fdtpu_serve_queue_depth",
    "fdtpu_serve_active_slots",
    "fdtpu_serve_max_slots",
    "fdtpu_serve_prefill_tokens_per_sec",
    "fdtpu_serve_decode_tokens_per_sec",
    "fdtpu_serve_ttft_sec_avg",
    "fdtpu_serve_decode_compiles",
    "fdtpu_serve_prefill_compiles",
    "fdtpu_serve_insert_compiles",
]


def _drained_scheduler():
    from fluxdistributed_tpu.serve import Request, Scheduler
    from fluxdistributed_tpu.serve.server import LMServer

    sched = Scheduler(_FakeEngine(), max_queue=4)
    lm = LMServer(sched, vocab=256)
    for prompt in ([1, 2, 3], [4]):
        sched.submit(Request(prompt=prompt, max_new_tokens=2))
    sched.run_until_idle()
    return sched, lm


def test_serve_metrics_text_parity():
    sched, lm = _drained_scheduler()
    text = lm.metrics_text()
    lines = text.splitlines()
    for series in PRE_REFACTOR_SERIES:
        # the exact pre-refactor line shape: `name value`, no labels
        assert any(
            l.startswith(f"{series} ") and not l.startswith("#")
            for l in lines
        ), f"{series} missing from /metrics:\n{text}"
    # values flow through: 2 requests were submitted and finished
    assert "fdtpu_serve_requests_submitted 2" in text
    assert "fdtpu_serve_requests_finished 2" in text
    assert "fdtpu_serve_decode_compiles 1" in text
    # and the registry adds proper TYPE metadata on top
    assert "# TYPE fdtpu_serve_requests_submitted counter" in text
    assert "# TYPE fdtpu_serve_queue_depth gauge" in text


def test_scheduler_metrics_dict_parity():
    sched, _ = _drained_scheduler()
    m = sched.metrics()
    expected = {s[len("fdtpu_serve_"):] for s in PRE_REFACTOR_SERIES}
    assert expected <= set(m), f"missing keys: {expected - set(m)}"
    for k, v in m.items():
        assert isinstance(v, (int, float)), (k, type(v))
    assert m["requests_submitted"] == 2
    assert m["requests_finished"] == 2
    assert m["prefill_tokens"] == 4          # 3 + 1 real prompt tokens
    assert m["prefill_padded_tokens"] == 16  # two bucket-8 prefills
    assert m["decode_tokens"] > 0
    assert m["max_slots"] == 2
    # two schedulers do not share counters (private registry each)
    fresh, _ = _drained_scheduler()
    assert fresh.metrics()["requests_submitted"] == 2


def test_scheduler_close_detaches_shared_registry_callbacks():
    """With a SHARED registry, close() must drop the scrape-time
    closures so a retired engine (and its KV cache) can be collected
    and /metrics stops reporting its stale stats; monotonic counters
    stay (process-cumulative totals are correct across restarts)."""
    from fluxdistributed_tpu.serve import Request, Scheduler
    from fluxdistributed_tpu.serve.server import LMServer

    shared = Registry()
    sched = Scheduler(_FakeEngine(), max_queue=4, registry=shared)
    lm = LMServer(sched, vocab=256)
    sched.submit(Request(prompt=[1], max_new_tokens=1))
    sched.run_until_idle()
    assert "fdtpu_serve_decode_compiles" in lm.metrics_text()
    lm.close()
    text = shared.prometheus_text()
    assert "fdtpu_serve_decode_compiles" not in text
    assert "fdtpu_serve_queue_depth" not in text
    assert "fdtpu_serve_loop_errors" not in text
    assert "fdtpu_serve_requests_finished 1" in text  # counters persist
    # a successor on the same registry re-registers cleanly and
    # continues the cumulative counters
    sched2 = Scheduler(_FakeEngine(), max_queue=4, registry=shared)
    sched2.submit(Request(prompt=[2], max_new_tokens=1))
    sched2.run_until_idle()
    assert sched2.metrics()["requests_finished"] == 2
    assert "fdtpu_serve_queue_depth" in shared.prometheus_text()


# ---------------------------------------------------------------------------
# satellites: ConsoleLogger robustness, trace_analysis path resolution
# ---------------------------------------------------------------------------

def test_console_logger_renders_nested_and_nonscalar(capsys):
    import numpy as np

    from fluxdistributed_tpu.train.logging import ConsoleLogger, NullLogger

    log = ConsoleLogger()
    log.log(
        {
            "loss": 0.123456,
            "phase": {"data_wait": 0.01, "dispatch": np.float32(0.5)},
            "losses": [1.0, 2.0],
            "arr": np.arange(3),
            "note": None,
            "tag": "steady",
        },
        step=7,
    )
    out = capsys.readouterr().out
    assert out.count("\n") == 1  # one record, one line — grep-able
    assert "loss=0.1235" in out
    assert "phase={data_wait:0.0100,dispatch:0.5000}" in out
    assert "losses=[1.0000,2.0000]" in out
    assert "arr=[0 1 2]" in out
    assert "note=None" in out and "tag=steady" in out
    # NullLogger is exported public API
    from fluxdistributed_tpu.train import NullLogger as FromPackage

    assert FromPackage is NullLogger


def test_trace_analysis_resolves_trainer_profile_dir(tmp_path):
    import pathlib
    import sys

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
    from benchmarks.trace_analysis import resolve_xplane

    # the trainer profile_dir layout: plugins/profile/<session>/<host>.xplane.pb
    old = tmp_path / "plugins" / "profile" / "2026_01_01" / "h.xplane.pb"
    new = tmp_path / "plugins" / "profile" / "2026_02_02" / "h.xplane.pb"
    for i, p in enumerate((old, new)):
        p.parent.mkdir(parents=True)
        p.write_bytes(b"x")
        t = time.time() + i * 100
        import os

        os.utime(p, (t, t))
    assert resolve_xplane(str(tmp_path)) == str(new)  # newest session
    assert resolve_xplane(str(new)) == str(new)       # direct file path
    with pytest.raises(SystemExit, match="xplane"):
        resolve_xplane(str(tmp_path / "plugins" / "profile" / "2026_01_01" / "nope"))
    empty = tmp_path / "empty"
    empty.mkdir()
    with pytest.raises(SystemExit, match="profile_dir"):
        resolve_xplane(str(empty))
