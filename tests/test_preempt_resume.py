"""Interrupt-resume loss parity — the acceptance core of the
preemption-tolerance subsystem (docs/robustness.md).

A run SIGTERMed at step k (via the deterministic fault plan), resumed
from its checkpoint + RESUME manifest, must produce step-for-step
identical losses to an uninterrupted run — on the same topology
(bit-identical) and on a DIFFERENT virtual-device count (the elastic
case: ZeRO-1's padded flat optimizer shards re-split for the new mesh;
allclose, since reduction order across a different device count may
legally reassociate).

Fast tier: in-process trainer runs on the 8-virtual-device fake mesh.
Slow tier: bin/driver.py subprocess e2e (SIGTERM → rc 75 → --resume),
including the device-count-change resume, and the fsdp elastic form.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from fluxdistributed_tpu import faults, optim
from fluxdistributed_tpu.data import SyntheticDataset
from fluxdistributed_tpu.mesh import data_mesh
from fluxdistributed_tpu.models import MLP
from fluxdistributed_tpu.train import (
    latest_step,
    prepare_training,
    read_resume_manifest,
    resume_training,
    train,
)
from fluxdistributed_tpu.train.logging import NullLogger

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CYCLES = 6
PREEMPT_AT = 3


@pytest.fixture(autouse=True)
def _clean_plan():
    yield
    faults.clear_plan()


def make_task(mesh=None, cycles=CYCLES, zero1=False, spmd="jit"):
    # MLP (10, 10): deliberately non-multiple-of-8 leaf sizes so the
    # ZeRO-1 flat pad CHANGES between 8- and 4-device meshes (bias 10
    # pads to 16 vs 12) — the elastic re-split is actually exercised
    ds = SyntheticDataset(nsamples=64, nclasses=10, shape=(8, 8, 3))
    return prepare_training(
        MLP(features=(10, 10)), ds, optim.adam(1e-3),
        mesh=mesh, batch_size=8, cycles=cycles, topk=(),
        zero1=zero1, spmd=spmd)


def record_losses(task):
    """Per-step losses in call order, by wrapping the compiled step."""
    losses = []
    orig = task.step_fn

    def wrapped(state, batch):
        out = orig(state, batch)
        losses.append(float(out[1]["loss"]))
        return out

    task.step_fn = wrapped
    return losses


def run_uninterrupted(**kw):
    task = make_task(**kw)
    losses = record_losses(task)
    train(task, print_every=0, eval_every=0, logger=NullLogger())
    return losses


def run_preempted(tmp_path, at=PREEMPT_AT, **kw):
    """Train under a sigterm-at-step-``at`` plan; returns the losses of
    the steps that ran before the checkpoint-and-exit."""
    task = make_task(**kw)
    losses = record_losses(task)
    faults.install_plan(faults.FaultPlan().sigterm_at_step(at))
    try:
        with pytest.raises(faults.Preempted) as ei:
            train(task, print_every=0, eval_every=0, logger=NullLogger(),
                  checkpoint_dir=str(tmp_path), checkpoint_every=0,
                  handle_signals=True)
    finally:
        faults.clear_plan()
    assert ei.value.step == at
    assert ei.value.next_item == at
    assert len(losses) == at
    return losses


def run_resumed(tmp_path, **kw):
    task = make_task(**kw)
    losses = record_losses(task)
    manifest = resume_training(task, str(tmp_path))
    # checkpoint_dir passed so completion clears the RESUME manifest
    # (what a real resumed run does — bin/driver.py keeps the flag)
    train(task, print_every=0, eval_every=0, logger=NullLogger(),
          checkpoint_dir=str(tmp_path), checkpoint_every=0)
    return losses, manifest


@pytest.fixture(scope="module")
def dp_baseline():
    return run_uninterrupted()


@pytest.fixture(scope="module")
def zero1_baseline():
    return run_uninterrupted(zero1=True)


# ---------------------------------------------------------------------------
# same-topology parity (bit-identical)
# ---------------------------------------------------------------------------


def test_preempt_resume_parity_dp(tmp_path, dp_baseline):
    head = run_preempted(tmp_path)
    m = read_resume_manifest(tmp_path)
    assert m is not None
    assert m["checkpoint_step"] == PREEMPT_AT
    assert m["next_item"] == PREEMPT_AT
    assert m["reason"] == "sigterm"
    assert m["mesh"] == {"data": 8} and m["device_count"] == 8
    assert latest_step(str(tmp_path)) == PREEMPT_AT
    tail, manifest = run_resumed(tmp_path)
    assert manifest is not None
    # step-for-step identical, and bit-identical on the same topology
    assert head + tail == dp_baseline
    # a completed run clears the manifest (stale cursors must not leak
    # into the next resume)
    assert read_resume_manifest(tmp_path) is None


def test_preempt_resume_parity_zero1(tmp_path, zero1_baseline):
    head = run_preempted(tmp_path, zero1=True)
    tail, _ = run_resumed(tmp_path, zero1=True)
    assert head + tail == zero1_baseline


# ---------------------------------------------------------------------------
# elastic: resume on a DIFFERENT virtual-device count
# ---------------------------------------------------------------------------


def test_elastic_resume_dp_8_to_4(tmp_path, dp_baseline):
    head = run_preempted(tmp_path)  # 8 devices
    tail, manifest = run_resumed(tmp_path, mesh=data_mesh(4))
    assert manifest is not None
    np.testing.assert_allclose(
        np.asarray(head + tail), np.asarray(dp_baseline),
        rtol=1e-4, atol=1e-6)


@pytest.mark.slow  # the 4→8 direction below keeps tier-1 coverage
def test_elastic_resume_zero1_8_to_4(tmp_path, zero1_baseline):
    """The trim branch: saved flat shards padded to multiples of 8
    re-split onto a 4-way mesh."""
    head = run_preempted(tmp_path, zero1=True)
    tail, _ = run_resumed(tmp_path, zero1=True, mesh=data_mesh(4))
    np.testing.assert_allclose(
        np.asarray(head + tail), np.asarray(zero1_baseline),
        rtol=1e-4, atol=1e-6)


def test_elastic_resume_zero1_4_to_8(tmp_path, zero1_baseline):
    """The pad branch: flat shards saved on 4 devices (bias 10 padded
    to 12) grow to the 8-way pad (16) on resume."""
    head = run_preempted(tmp_path, zero1=True, mesh=data_mesh(4))
    tail, _ = run_resumed(tmp_path, zero1=True)  # back to all 8
    np.testing.assert_allclose(
        np.asarray(head + tail), np.asarray(zero1_baseline),
        rtol=1e-4, atol=1e-6)


# ---------------------------------------------------------------------------
# edges
# ---------------------------------------------------------------------------


def test_preempt_without_checkpoint_dir_persists_nothing(tmp_path):
    task = make_task()
    faults.install_plan(faults.FaultPlan().sigterm_at_step(1))
    with pytest.raises(faults.Preempted) as ei:
        train(task, print_every=0, eval_every=0, logger=NullLogger(),
              handle_signals=True)
    assert ei.value.checkpoint_dir is None
    assert not os.listdir(tmp_path)


def test_resume_without_manifest_uses_step_cursor(tmp_path, dp_baseline):
    """A cadence checkpoint from a run killed without signal handling
    (kill -9) still resumes: the cursor derives from the step counter
    (correct whenever nothing was OOM-skipped)."""
    run_preempted(tmp_path)
    os.remove(tmp_path / "RESUME.json")
    tail, manifest = run_resumed(tmp_path)
    assert manifest is None
    assert tail == dp_baseline[PREEMPT_AT:]


def test_resume_on_empty_dir_is_fresh_run(tmp_path):
    task = make_task()
    assert resume_training(task, str(tmp_path / "nothing")) is None
    assert int(task.state.step) == 0
    assert getattr(task.loader, "start", 0) == 0


# three extra prepares; the single-preempt parity above is the tier-1 form
@pytest.mark.slow
def test_fresh_signal_mid_resumed_run_preempts_again(tmp_path):
    """Preemption is re-entrant: a resumed run can itself be preempted
    and resumed, and parity still holds."""
    baseline = run_uninterrupted()
    head = run_preempted(tmp_path, at=2)
    # resumed run preempted again at absolute item 4
    task = make_task()
    mid = record_losses(task)
    resume_training(task, str(tmp_path))
    faults.install_plan(faults.FaultPlan().sigterm_at_step(4))
    with pytest.raises(faults.Preempted):
        train(task, print_every=0, eval_every=0, logger=NullLogger(),
              checkpoint_dir=str(tmp_path), checkpoint_every=0,
              handle_signals=True)
    faults.clear_plan()
    m = read_resume_manifest(tmp_path)
    assert m["next_item"] == 4 and m["checkpoint_step"] == 4
    tail, _ = run_resumed(tmp_path)
    assert head + mid + tail == baseline


# ---------------------------------------------------------------------------
# driver e2e (subprocess; slow tier)
# ---------------------------------------------------------------------------


def _driver_env():
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _driver(extra, tmp_path, devices=8):
    return subprocess.run(
        [sys.executable, os.path.join("bin", "driver.py"),
         "--model", "SimpleCNN", "--dataset", "synthetic",
         "--num-classes", "4", "--image-size", "8",
         "--batch-size", "8", "--cycles", "6",
         "--print-every", "0", "--eval-every", "0",
         "--checkpoint-dir", str(tmp_path / "ck"),
         "--checkpoint-every", "0",
         "--platform", "cpu", "--local-devices", str(devices),
         *extra],
        capture_output=True, text=True, timeout=600, env=_driver_env(),
        cwd=REPO,
    )


@pytest.mark.slow
def test_driver_sigterm_checkpoint_resume_e2e(tmp_path):
    """The whole chain through the CLI: a fault-plan SIGTERM at step 3
    exits with the DISTINCT rc 75 after writing checkpoint + manifest;
    --resume completes the remaining steps; the manifest is cleared."""
    p1 = _driver(["--fault-plan", '{"sigterm_at_step": 3}'], tmp_path)
    assert p1.returncode == faults.PREEMPTED_RC, (
        p1.returncode, p1.stdout[-1500:], p1.stderr[-1500:])
    assert "preempted" in p1.stdout
    ck = tmp_path / "ck"
    manifest = json.loads((ck / "RESUME.json").read_text())
    assert manifest["checkpoint_step"] == 3 and manifest["next_item"] == 3

    p2 = _driver(["--resume"], tmp_path)
    assert p2.returncode == 0, (p2.stdout[-1500:], p2.stderr[-1500:])
    assert "resumed from step 3 at item 3 via RESUME manifest" in p2.stdout
    assert "done: 6 steps" in p2.stdout, p2.stdout[-1500:]
    assert not (ck / "RESUME.json").exists()


@pytest.mark.slow
def test_driver_elastic_resume_different_device_count(tmp_path):
    """Preempt on 8 virtual devices, resume on 4 — the fault plan's
    params knob models the next grant window handing back a smaller
    slice; the elastic restore path re-commits to the new mesh."""
    p1 = _driver(["--fault-plan", '{"sigterm_at_step": 3}'], tmp_path)
    assert p1.returncode == faults.PREEMPTED_RC, p1.stderr[-1500:]
    p2 = _driver(
        ["--resume",
         "--fault-plan", '{"params": {"local_devices": 4}}'],
        tmp_path, devices=4)
    assert p2.returncode == 0, (p2.stdout[-1500:], p2.stderr[-1500:])
    assert "resumed from step 3" in p2.stdout
    assert "done: 6 steps" in p2.stdout, p2.stdout[-1500:]


@pytest.mark.slow
def test_elastic_resume_fsdp(tmp_path):
    """fsdp state (per-leaf data-axis shardings, full global shapes)
    rides the same elastic restore: shapes need no adaptation, only the
    re-commit to the new mesh's shardings."""
    baseline = run_uninterrupted(spmd="fsdp")
    head = run_preempted(tmp_path, spmd="fsdp")
    tail, _ = run_resumed(tmp_path, spmd="fsdp", mesh=data_mesh(4))
    np.testing.assert_allclose(
        np.asarray(head + tail), np.asarray(baseline),
        rtol=1e-4, atol=1e-6)
