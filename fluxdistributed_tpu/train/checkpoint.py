"""Checkpoint save / load / resume.

The reference is save-only: ``BSON.@save`` of the CPU model every 20
cycles per worker (src/sync.jl:156-161), no optimizer state on disk and
no resume path (SURVEY §5).  This module closes that gap TPU-natively:

* ``save_checkpoint`` — orbax-backed save of the FULL ``TrainState``
  (params + optimizer state + mutable model state + step), written
  per-step under ``<dir>/step_<n>`` like the reference's
  ``weights/$(p)/resnet_50_cycle_$(n)...`` layout;
* ``load_checkpoint`` — restore onto host or onto a mesh (replicated),
  defaulting to the latest step — the resume path the reference lacks;
* ``latest_step`` — scan a checkpoint dir.

Orbax handles sharded arrays natively, so the same call works on a
multi-host pod slice (each host writes its addressable shards).
"""

from __future__ import annotations

import os
import re
import threading
from typing import Any, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from .. import tree as tree_lib

Pytree = Any

__all__ = ["save_checkpoint", "load_checkpoint", "latest_step", "wait_for_pending"]

_STEP_RE = re.compile(r"^step_(\d+)$")

# Checkpointers with an async write still in flight (block=False saves).
# At most one at a time: save_checkpoint drains it before starting the
# next, and train()/callers drain at exit via wait_for_pending().  The
# expected owner is a single train loop per process; the locks make a
# stray second caller (e.g. an eval thread saving best-so-far)
# serialize instead of corrupting the drain: _PENDING_LOCK protects the
# list, _SAVE_LOCK spans a whole save (drain → write → append) so two
# concurrent saves cannot both observe an empty pending list and race
# their rmtree/write phases.
_PENDING: list = []
_PENDING_LOCK = threading.Lock()
_SAVE_LOCK = threading.Lock()


def _step_dir(directory: str, step: int) -> str:
    return os.path.join(os.path.abspath(directory), f"step_{step}")


def wait_for_pending() -> None:
    """Block until any in-flight async save has committed to disk.

    Single-threaded savers assumed (one train loop per process — the
    module-global ``_PENDING`` is not lock-protected).  The pending
    reference is removed only after a successful wait, so a failed wait
    leaves it in place and a retry can still await the write.
    """
    with _PENDING_LOCK:
        while _PENDING:
            _PENDING[-1].wait_until_finished()
            _PENDING.pop()


def save_checkpoint(
    state: Pytree, directory: str, step: int, overwrite: bool = True,
    block: bool = True,
) -> str:
    """Write ``state`` (any pytree, e.g. ``TrainState``) at ``directory/step_<n>``.

    ``block=False`` makes the disk write asynchronous: orbax's save copies
    device arrays to host synchronously (so later donation/mutation of the
    state cannot corrupt the snapshot) and streams to disk in a background
    thread — the train loop keeps stepping during the write.  Call
    :func:`wait_for_pending` (train() does) before relying on the file.

    Multi-host: the orbax save itself is collective (every host writes its
    addressable shards), but the pre-delete of an existing step dir runs
    on the coordinator only, behind a barrier — concurrent ``rmtree`` from
    N hosts on a shared filesystem would race the save.
    """
    with _SAVE_LOCK:  # one save (drain → write → append) at a time
        wait_for_pending()
        path = _step_dir(directory, step)
        ckptr = ocp.StandardCheckpointer()
        if overwrite and os.path.exists(path):
            if jax.process_index() == 0:
                import shutil

                shutil.rmtree(path, ignore_errors=True)
            if jax.process_count() > 1:
                from jax.experimental import multihost_utils

                multihost_utils.sync_global_devices("ckpt_rmtree")
        ckptr.save(path, state)
        if block:
            ckptr.wait_until_finished()
        else:
            with _PENDING_LOCK:
                _PENDING.append(ckptr)
    return path


def latest_step(directory: str) -> Optional[int]:
    """Largest ``step_<n>`` present in ``directory``, or None."""
    if not os.path.isdir(directory):
        return None
    steps = [
        int(m.group(1))
        for name in os.listdir(directory)
        if (m := _STEP_RE.match(name))
    ]
    return max(steps) if steps else None


def load_checkpoint(
    directory: str,
    target: Optional[Pytree] = None,
    step: Optional[int] = None,
    mesh=None,
) -> Pytree:
    """Restore a checkpoint onto the structure of ``target``.

    ``target=None`` restores the raw pytree as saved (nested dicts of
    host arrays) with no structure requirements — useful when the saving
    optimizer is unknown (e.g. inference tools that only need
    ``restored["params"]``).  ``step=None`` picks the latest (resume
    semantics).  With ``mesh`` given, restored arrays are placed on the
    mesh ready to hand back to a compiled train step: each leaf takes its
    ``target`` leaf's sharding when the target is device-placed (so an
    FSDP-sharded state — or a ZeRO-1 state's flat data-sharded optimizer
    leaves — restores sharded, not gathered), else replicated.
    Restore is topology-independent either way — the placement comes from
    the *restoring* target/mesh, never from the saved run's devices.
    """
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {directory}")
    path = _step_dir(directory, step)
    ckptr = ocp.StandardCheckpointer()
    if target is None:
        # Build a host-numpy target from the saved metadata instead of
        # restoring blind: a blind restore re-applies the SAVED device
        # shardings, which fails when the saving topology (e.g. 8 CPU
        # devices) differs from the restoring one (e.g. 1 TPU).
        meta = ckptr.metadata(path)
        # newer orbax wraps the metadata pytree (CompositeCheckpointMetadata
        # .item_metadata.tree); older releases return the tree itself
        item = getattr(meta, "item_metadata", None)
        meta = item.tree if item is not None and hasattr(item, "tree") else meta
        target = jax.tree.map(
            lambda m: np.zeros(m.shape, m.dtype) if hasattr(m, "shape") else m,
            meta,
        )
        restored = ckptr.restore(path, target=target)
        if mesh is not None:
            from ..sharding import replicate

            restored = replicate(restored, mesh)
        return restored

    if mesh is not None:
        # Restore straight into device-sharded arrays via an ABSTRACT
        # target carrying each target leaf's sharding (its own when
        # device-placed — so FSDP/TP state restores sharded — else
        # replicated).  No host round-trip: to_host on a sharded state
        # would both re-materialize the full model per host (undoing the
        # FSDP memory bound at resume time) and crash outright on
        # multi-host leaves that span non-addressable devices.
        from jax.sharding import NamedSharding, PartitionSpec

        repl = NamedSharding(mesh, PartitionSpec())

        def abstract(t):
            if hasattr(t, "shape") and hasattr(t, "dtype"):
                sh = getattr(t, "sharding", None)
                sh = sh if isinstance(sh, NamedSharding) else repl
                return jax.ShapeDtypeStruct(np.shape(t), t.dtype, sharding=sh)
            return t

        return ckptr.restore(path, target=jax.tree.map(abstract, target))

    return ckptr.restore(
        path, target=jax.tree.map(np.asarray, tree_lib.to_host(target))
    )
