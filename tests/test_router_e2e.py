"""Router end-to-end over real replica PROCESSES (bin/serve.py fleets).

The in-process suite (test_router.py) proves the state machines; this
one proves them against real process death: replicas are ``bin/serve.py
--lm`` subprocesses orchestrated through the ``--port 0`` +
``FDTPU_SERVE_PORT=`` contract, and the mid-burst kill is a
deterministic fault plan (``serve.tick`` → ``exit``, the SIGKILL/OOM
shape — no drain, no goodbye).

Fast tier: fake-engine replicas (no compiles — subprocess cost is the
jax import).  Slow tier: real lm_tiny engines sharing one AOT
executable pool, where a rolling restart must come back at ONE decode
compile.
"""

from __future__ import annotations

import json
import pathlib
import sys
import threading
import time
import urllib.request

import pytest

from fluxdistributed_tpu.serve.router import (Replica, Router,
                                              SupervisedReplica,
                                              wait_http_ready)
from fluxdistributed_tpu.serve.testing import fake_tokens

ROOT = pathlib.Path(__file__).resolve().parents[1]
SERVE = str(ROOT / "bin" / "serve.py")
ENV = {"JAX_PLATFORMS": "cpu", "PYTHONPATH": str(ROOT)}


def _fake_argv(extra=()):
    return [sys.executable, SERVE, "--lm", "--fake-engine",
            "--max-slots", "4", "--max-len", "256", "--max-queue", "64",
            "--fake-step-delay", "0.004", "--port", "0", *extra]


def _spawn_fleet(argvs, names):
    """Spawn all replicas concurrently (each pays a jax import).
    verbose=False: replica logs interleaving with pytest's progress
    lines corrupt the tier-1 dot counting."""
    sups = [SupervisedReplica(argv, name=name, env=ENV, verbose=False)
            for argv, name in zip(argvs, names)]
    urls = [None] * len(sups)

    def go(i):
        urls[i] = sups[i].spawn()

    threads = [threading.Thread(target=go, args=(i,))
               for i in range(len(sups))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for url in urls:
        wait_http_ready(url + "/healthz", timeout=60)
    return sups, urls


def _post(base, body, rid=None, timeout=60):
    headers = {"Content-Type": "application/json"}
    if rid:
        headers["X-Request-Id"] = rid
    req = urllib.request.Request(
        f"{base}/v1/generate", data=json.dumps(body).encode(),
        method="POST", headers=headers)
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def test_kill_midburst_failover_and_breaker_recovery(tmp_path):
    """The acceptance core: 2-replica fleet over live HTTP, one replica
    hard-killed mid-burst by a deterministic fault plan.  Every request
    completes via failover with its X-Request-Id intact and
    byte-identical tokens; the dead replica's breaker opens; once the
    replica is brought back at its old port the breaker recovers."""
    kill_plan = json.dumps(
        {"fail": [{"site": "serve.tick", "at": 30, "action": "exit"}]})
    sups, urls = _spawn_fleet(
        [_fake_argv(["--fault-plan", kill_plan]), _fake_argv()],
        ["r0", "r1"])
    router = Router(
        [Replica("r0", urls[0], restart=sups[0].restart),
         Replica("r1", urls[1], restart=sups[1].restart)],
        probe_interval=3600.0, probe_timeout=5.0, failure_threshold=2,
        breaker_cooldown=0.2, dispatch_tries=4, dispatch_backoff=0.02,
        upstream_timeout=60.0)
    httpd = router.serve("127.0.0.1", 0)
    threading.Thread(
        target=lambda: httpd.serve_forever(poll_interval=0.02),
        daemon=True).start()
    base = f"http://127.0.0.1:{router.bound_port}"
    try:
        results = {}

        def one(i):
            prompt = [i % 7 + 1, i % 5 + 1]
            try:
                results[i] = _post(
                    base, {"prompt_tokens": prompt, "max_tokens": 24},
                    rid=f"e2e-{i}")
            except Exception as e:  # noqa: BLE001 — asserted below
                results[i] = (None, f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        # ZERO failed requests, ids intact, tokens byte-identical to
        # what the dead replica would have produced
        for i, (code, body) in sorted(results.items()):
            assert code == 200, f"request {i}: {code} {body}"
            assert body["request_id"] == f"e2e-{i}"
            assert body["generated"] == fake_tokens(
                [i % 7 + 1, i % 5 + 1], 24)
        # the fault plan really killed r0 (rc from os._exit)
        deadline = time.monotonic() + 15
        while sups[0].alive() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not sups[0].alive(), "fault plan did not kill r0"
        r0 = router.replicas[0]
        router.probe_now()
        router.probe_now()
        assert r0.breaker == "open"
        assert router.registry.value(
            "fdtpu_router_breaker_opens_total", "r0") >= 1
        assert router.registry.value(
            "fdtpu_router_failovers_total") >= 1
        # fleet still green on the survivor
        assert router.health()["ok"]

        # recovery: the replica returns at its OLD port (no fault plan
        # this time); breaker transitions back through half-open/probe
        old_port = sups[0].port
        sups[0].stop()
        sups[0].argv = _fake_argv()
        new_url = sups[0].spawn(port=old_port)
        assert new_url == urls[0]
        wait_http_ready(new_url + "/healthz", timeout=60)
        time.sleep(0.25)  # past the breaker cooldown
        router.probe_now()
        assert r0.breaker == "closed" and r0.healthy
        code, body = _post(base, {"prompt_tokens": [9, 9],
                                  "max_tokens": 4}, rid="post-recovery")
        assert code == 200
        assert body["generated"] == fake_tokens([9, 9], 4)
    finally:
        httpd.shutdown()
        httpd.server_close()
        router.close()
        for sup in sups:
            sup.stop()


@pytest.mark.slow
def test_real_engine_rolling_restart_holds_one_decode_compile(tmp_path):
    """Real lm_tiny replicas sharing one AOT executable pool: a rolling
    restart under light load drops nothing, and every restarted replica
    comes back having loaded its programs from the pool — the
    ONE-decode-compile invariant (fdtpu_serve_decode_compiles == 1)
    held across the redeploy."""
    aot = str(tmp_path / "aot-pool")
    argv = [sys.executable, SERVE, "--lm", "--model", "lm_tiny",
            "--vocab", "256", "--max-slots", "2", "--max-len", "64",
            "--buckets", "8,16", "--prewarm", "--aot-dir", aot,
            "--port", "0"]
    # sequential spawn ON PURPOSE: the first replica compiles and
    # serializes the pool, the second (and every restart) loads it
    sup0 = SupervisedReplica(argv, name="r0", env=ENV,
                             startup_timeout=600.0, verbose=False)
    url0 = sup0.spawn()
    wait_http_ready(url0 + "/healthz", timeout=60)
    sup1 = SupervisedReplica(argv, name="r1", env=ENV,
                             startup_timeout=600.0, verbose=False)
    url1 = sup1.spawn()
    wait_http_ready(url1 + "/healthz", timeout=60)
    router = Router(
        [Replica("r0", url0, restart=sup0.restart),
         Replica("r1", url1, restart=sup1.restart)],
        probe_interval=3600.0, failure_threshold=2,
        dispatch_backoff=0.02, upstream_timeout=300.0)
    httpd = router.serve("127.0.0.1", 0)
    threading.Thread(
        target=lambda: httpd.serve_forever(poll_interval=0.02),
        daemon=True).start()
    base = f"http://127.0.0.1:{router.bound_port}"
    try:
        code, body = _post(base, {"prompt_tokens": [3, 1, 4],
                                  "max_tokens": 6}, rid="warm-1")
        assert code == 200 and len(body["generated"]) == 6
        golden = body["generated"]

        stop = threading.Event()
        outcomes = []

        def load():
            i = 0
            while not stop.is_set():
                try:
                    outcomes.append(_post(
                        base, {"prompt_tokens": [3, 1, 4],
                               "max_tokens": 6}, timeout=120))
                except Exception as e:  # noqa: BLE001
                    outcomes.append((None, f"{type(e).__name__}: {e}"))
                i += 1
                time.sleep(0.2)

        t = threading.Thread(target=load, daemon=True)
        t.start()
        results = router.rolling_restart(drain_timeout=60.0,
                                         ready_timeout=300.0)
        stop.set()
        t.join(timeout=30)
        assert len(results) == 2
        bad = [(c, b) for c, b in outcomes if c != 200]
        assert not bad, f"rolling restart dropped requests: {bad[:3]}"
        # parity across the restart (greedy determinism end-to-end)
        assert all(b["generated"] == golden for c, b in outcomes)
        # every restarted replica holds the ONE-decode-compile
        # invariant live on /metrics: 0 compiles means the whole pool
        # deserialized from the shared AOT dir (the restart was a LOAD
        # — the point of riding compilation.py), 1 would be a fresh
        # compile, anything more is the violation
        for rep in router.replicas:
            with urllib.request.urlopen(rep.url + "/metrics",
                                        timeout=10) as r:
                text = r.read().decode()
            line = next(l for l in text.splitlines()
                        if l.startswith("fdtpu_serve_decode_compiles "))
            assert float(line.split()[1]) == 0.0, (
                f"restarted replica {rep.name} recompiled instead of "
                f"loading the AOT pool: {line}")
        code, body = _post(base, {"prompt_tokens": [3, 1, 4],
                                  "max_tokens": 6})
        assert code == 200 and body["generated"] == golden
    finally:
        httpd.shutdown()
        httpd.server_close()
        router.close()
        sup0.stop()
        sup1.stop()
