"""Pipeline parallelism: GPipe-style microbatch pipelining over a
``pipe`` mesh axis.

Net-new scope beyond the reference (SURVEY §2: "PP: NO"), built the
TPU-idiomatic way: the schedule is a ``lax.scan`` over ticks inside one
``shard_map`` program — device *s* applies stage *s* and hands its
activation to device *s+1* with a ``ppermute`` each tick, so stage
compute overlaps neighbor-to-neighbor ICI transfers.  The backward pass
is not hand-written: differentiating through ``scan`` + ``ppermute``
yields the reverse pipeline schedule automatically (the transpose of a
``ppermute`` is the reverse permutation).

Model contract: one ``stage_fn(params, x) -> y`` applied on every pipe
device with that device's slice of the stacked stage parameters;
activations keep one shape across stages (the ``d_model``
residual-stream invariant transformers already satisfy).  Stages may be
*heterogeneous in behavior*: a ``stage_fn(params, x, stage) -> y``
signature receives the stage index (a traced scalar) and may
``lax.switch`` on it — ``switch_stage([f0, f1, ...])`` builds exactly
that from per-stage callables.  Parameters stay structurally identical
across stages: give every stage the superset parameter tree (unused
leaves still occupy their stage's memory, so keep supersets lean).
Embed/head layers that change the activation shape compose outside the
pipelined middle.

Schedule shape: M microbatches through S stages take M + S - 1 ticks;
the (S-1)/(M+S-1) bubble shrinks as M grows — pick ``num_microbatches >=
2*S`` in production.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..optim import Optimizer
from .dp import TrainState

Pytree = Any

__all__ = [
    "pipeline_apply",
    "make_train_step_pp",
    "stack_stage_params",
    "switch_stage",
    "chunk_stages",
]

# sourced from the device layer's single declaration (lint rule FDT105:
# a re-declared literal drifts silently on rename); re-exported here for
# the callers that import it from the pp module
from ..mesh import PIPE_AXIS


def _accepts_stage(fn: Callable) -> bool:
    """Does ``fn`` require a third positional arg (the stage index)?

    Deliberately strict: only callables with >= 3 *non-defaulted*
    positional parameters opt in.  A defaulted third parameter
    (``def f(p, x, scale=0.5)``) or ``*args`` must NOT silently receive
    the traced stage index — that would corrupt previously-valid
    two-argument stage functions.  ``switch_stage`` is the explicit
    opt-in for heterogeneous pipelines.
    """
    import inspect

    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    required = [
        p for p in sig.parameters.values()
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
        and p.default is p.empty
    ]
    return len(required) >= 3


def chunk_stages(stage_fn: Callable, counts=None,
                 axis: str = PIPE_AXIS) -> Callable:
    """Host V consecutive logical stages per pipe device (blocked virtual
    pipeline): wraps ``stage_fn`` to ``lax.scan`` over a leading chunk
    dim in its params, so device *s* applies logical stages
    ``s·V … s·V+V-1`` in sequence each tick.

    Build the params by stacking ALL ``V·S`` per-stage trees, reshaping
    each leaf to ``(S, V, ...)``, and sharding the leading dim on the
    pipe axis (``stack_stage_params`` of per-device ``(V, ...)`` trees
    does exactly that).

    ``counts`` (one int per pipe device) turns on NON-uniform splits —
    the profile-guided planner's output (``parallel/pp_plan.py``):
    every device's param slab is padded to ``max(counts)`` chunks, and
    device *i* applies only its first ``counts[i]`` per tick — the rest
    are ``lax.cond``-skipped identity chunks (their zero params are
    never touched, their grads stay zero).  The counts table is
    trace-time STATIC (baked like the 1F1B schedule tables, read per
    device via ``axis_index``), so a plan change recompiles exactly
    like a depth change would — it never enters a jit argument
    signature, and within a run there is still exactly ONE compile.

    Under the GPipe schedule, blocked placement keeps the bubble at
    ``(S-1)/(M+S-1)`` ticks (each tick is V stage-times) — the same
    relative bubble as a V-times-deeper per-device stage, which is what
    it is.  Interleaved (Megatron 1F1B) placement is not implemented
    here: the backward is AD-derived from the forward scan, so there is
    no hand-written 1F1B schedule to interleave.
    """
    if counts is None:
        def fn(params, x):
            h, _ = jax.lax.scan(lambda h, p: (stage_fn(p, h), None), x, params)
            return h

        return fn

    import numpy as np

    counts_arr = np.asarray(list(counts), np.int32)

    def fn(params, x):
        mine = jnp.take(jnp.asarray(counts_arr), jax.lax.axis_index(axis))
        vmax = jax.tree.leaves(params)[0].shape[0]

        def body(h, pc):
            p, c = pc
            h2 = jax.lax.cond(
                c < mine,
                lambda p_, h_: stage_fn(p_, h_),
                lambda p_, h_: h_,
                p, h)
            return h2, None

        h, _ = jax.lax.scan(
            body, x, (params, jnp.arange(vmax, dtype=jnp.int32)))
        return h

    return fn


def switch_stage(stage_fns: list) -> Callable:
    """Compose per-stage callables into one ``stage_fn(params, x, stage)``
    that ``lax.switch``es on the (traced) stage index — the heterogeneous
    pipeline form.  Every callable must accept the same params structure
    (use a superset tree) and preserve the activation shape.

    The callable records ``len(stage_fns)`` so ``pipeline_apply`` can
    reject a list whose length does not match the pipeline's stage count
    (``lax.switch`` clamps out-of-range indices, which would otherwise
    silently reuse the last stage)."""

    branches = [lambda p, x, f=f: f(p, x) for f in stage_fns]

    def fn(params, x, stage):
        return jax.lax.switch(stage, branches, params, x)

    fn._num_stage_fns = len(stage_fns)
    return fn


def stack_stage_params(per_stage: list, mesh: Mesh, axis: str = PIPE_AXIS) -> Pytree:
    """Stack S per-stage param trees along a new leading dim sharded over
    the ``pipe`` axis — stage s's params live on pipe device s."""
    from ..sharding import stack_on_axis

    return stack_on_axis(per_stage, mesh, axis)


def pipeline_apply(
    stage_fn: Callable,
    mesh: Mesh,
    axis: str = PIPE_AXIS,
    num_microbatches: Optional[int] = None,
    batch_axis: Optional[str] = None,
    remat: bool = False,
):
    """Build ``fwd(stacked_params, x) -> y`` running the GPipe schedule.

    ``stacked_params`` leaves have leading dim S sharded on ``axis``;
    ``x`` is the batch (replicated input spec — only stage 0 reads it;
    the compiler keeps the unused copies unrealized).  Output is the
    last stage's activations, same batch layout as the input.

    ``batch_axis`` composes data parallelism with the pipeline on a 2-D
    ``(data, pipe)`` mesh: ``x``'s leading dim is sharded over
    ``batch_axis`` and each data-parallel row of the mesh pipelines its
    own shard (microbatch count M divides the per-shard batch).

    ``remat=True`` wraps the per-tick stage apply in ``jax.checkpoint``:
    the backward scan then stores only each tick's stage INPUT and
    recomputes the stage internals — per-device activation memory drops
    from O(ticks · stage-internals) to O(ticks · microbatch), the same
    memory effect 1F1B targets, obtained without a hand-written
    schedule (the AD-derived reverse pipeline is unchanged).  Cost: one
    extra stage forward per tick in the backward pass.
    """
    S = mesh.shape[axis]
    M = num_microbatches or S
    fwd_perm = [(i, (i + 1) % S) for i in range(S)]
    with_stage = _accepts_stage(stage_fn)
    n_fns = getattr(stage_fn, "_num_stage_fns", None)
    if remat:
        # wrap AFTER signature/attr inspection: jax.checkpoint obscures
        # both.  prevent_cse=False: the wrapped fn runs inside lax.scan,
        # where the CSE-prevention barriers are unnecessary (per the
        # jax.checkpoint docs) and only hinder XLA fusion
        stage_fn = jax.checkpoint(stage_fn, prevent_cse=False)
    if n_fns is not None and n_fns != S:
        raise ValueError(
            f"switch_stage got {n_fns} stage fns but the '{axis}' axis has "
            f"{S} stages (lax.switch would silently clamp the stage index)"
        )

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(axis), P(batch_axis)),
        out_specs=P(batch_axis),
    )
    def run(stacked_params, x):
        params = jax.tree.map(lambda p: p[0], stacked_params)  # my stage's slice
        idx = jax.lax.axis_index(axis)
        b = x.shape[0]
        assert b % M == 0, f"batch {b} not divisible by {M} microbatches"
        mb = x.reshape(M, b // M, *x.shape[1:])
        # mark the stream device-varying up front: the scan carry crosses
        # a ppermute, so its type must be varying over the pipe axis from
        # the start (shard_map's VMA typing)
        mb = jax.lax.pcast(mb, axis, to="varying")
        zero = jnp.zeros_like(mb[0])

        def tick(state, t):
            # stage 0 feeds microbatch t (while any remain); later stages
            # consume the activation ppermuted in last tick
            feed = jax.lax.dynamic_index_in_dim(
                mb, jnp.minimum(t, M - 1), 0, keepdims=False
            )
            x_in = jnp.where(idx == 0, jnp.where(t < M, feed, zero), state)
            y = stage_fn(params, x_in, idx) if with_stage else stage_fn(params, x_in)
            # the last stage's result for microbatch t-(S-1) is ready
            out = jnp.where(idx == S - 1, y, jnp.zeros_like(y))
            state_next = jax.lax.ppermute(y, axis, fwd_perm)
            return state_next, out

        _, outs = jax.lax.scan(tick, zero, jnp.arange(M + S - 1))
        outs = outs[S - 1 :]  # (M, mb, ...) valid last-stage outputs
        # all-reduce broadcasts the last stage's outputs (others are zero)
        outs = jax.lax.psum(outs, axis)
        return outs.reshape(b, *outs.shape[2:])

    return run


def make_train_step_pp(
    stage_fn: Callable,
    loss: Callable,
    optimizer: Optimizer,
    mesh: Mesh,
    axis: str = PIPE_AXIS,
    num_microbatches: Optional[int] = None,
    donate: bool = True,
    remat: bool = False,
):
    """Compile a full pipelined training step.

    ``loss(y, labels)`` consumes the pipeline output.  Params and
    optimizer state stay stage-sharded on ``axis``; gradients arrive
    stage-sharded for free (the AD transpose of the stacked-slice read),
    so the optimizer update is local to each pipe device — no gradient
    collective at all, the pipeline's communication is activations only.
    """
    from ..sharding import make_shardings
    from .tp import state_specs

    fwd = pipeline_apply(
        stage_fn, mesh, axis=axis, num_microbatches=num_microbatches, remat=remat
    )
    repl = NamedSharding(mesh, P())

    def state_shardings(state: TrainState) -> TrainState:
        p_specs = jax.tree.map(lambda _: P(axis), state.params)
        return make_shardings(state_specs(state, p_specs), mesh)

    def step(state: TrainState, batch):
        def lossf(params):
            y = fwd(params, batch["image"])
            return loss(y, batch["label"])

        lval, grads = jax.value_and_grad(lossf)(state.params)
        new_params, new_opt = optimizer.apply(
            state.params, grads, state.opt_state, state.step
        )
        new_state = TrainState(
            params=new_params,
            opt_state=new_opt,
            model_state=state.model_state,
            step=state.step + 1,
        )
        return new_state, {"loss": lval}

    def compile_for(state: TrainState):
        sh = state_shardings(state)
        return jax.jit(
            step,
            in_shardings=(sh, repl),
            out_shardings=(sh, repl),
            donate_argnums=(0,) if donate else (),
        )

    return compile_for
