"""FDT107 negative: donation declared where documented, or not
documented at all."""
import jax


def make_toy_step(loss_fn, donate=True):
    """Build the compiled step.  Donates the incoming state when
    ``donate=True`` so buffers are updated in place."""

    def step(state, batch):
        return state

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_copying_step(loss_fn):
    """Build the compiled step (state copied every call, by design)."""

    def step(state, batch):
        return state

    return jax.jit(step)
