"""FDT106 positive: metric names off the fdtpu_* convention."""


def register(reg):
    reg.counter("serve_requests_total")  # missing prefix
    reg.gauge("Fdtpu_queue_depth")  # wrong case
    reg.histogram("fdtpu-step-seconds")  # dashes
