"""CIFAR-10 loader.

The reference's ``src/cifar.jl`` is dead code — a Metalhead
``trainimgs(CIFAR10)`` constant plus an ``assemble`` batch-stacker, never
``include``d (SURVEY §2 #14).  Here it's a live loader for the standard
CIFAR-10 binary format (``data_batch_*.bin`` / ``test_batch.bin``: 1
label byte + 3072 CHW pixel bytes per record), implementing the dataset
protocol so the ResNet-34/CIFAR-10 reference config (BASELINE.json) runs.
"""

from __future__ import annotations

import os

import numpy as np

__all__ = ["CIFAR10Dataset"]

_RECORD = 3073
_TRAIN_FILES = [f"data_batch_{i}.bin" for i in range(1, 6)]
_TEST_FILES = ["test_batch.bin"]

CIFAR10_MEAN = np.array([0.4914, 0.4822, 0.4465], np.float32)
CIFAR10_STD = np.array([0.2470, 0.2435, 0.2616], np.float32)


class CIFAR10Dataset:
    """CIFAR-10 from the binary distribution at ``root`` (optionally
    under a ``cifar-10-batches-bin/`` subdir)."""

    nclasses = 10

    def __init__(self, root: str, split: str = "train", normalize: bool = True):
        sub = os.path.join(root, "cifar-10-batches-bin")
        base = sub if os.path.isdir(sub) else root
        files = _TRAIN_FILES if split == "train" else _TEST_FILES
        paths = [os.path.join(base, f) for f in files]
        missing = [p for p in paths if not os.path.exists(p)]
        if missing:
            raise FileNotFoundError(
                f"CIFAR-10 binaries not found: {missing[0]} (download the "
                "'binary version' archive and point path= at it)"
            )
        raw = np.concatenate([np.fromfile(p, np.uint8).reshape(-1, _RECORD) for p in paths])
        self.labels_table = raw[:, 0].astype(np.int32)
        imgs = raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)  # CHW→HWC
        x = imgs.astype(np.float32) / 255.0
        if normalize:
            x = (x - CIFAR10_MEAN) / CIFAR10_STD
        self.images = x

    def __len__(self):
        return len(self.labels_table)

    def batch(self, rng: np.random.Generator, n: int, indices=None):
        if indices is None:
            indices = rng.integers(0, len(self), size=n)
        indices = np.asarray(indices)
        return self.images[indices], self.labels_table[indices]
