"""dp×fsdp×tp layouts and the auto-layout picker — "fit this model on
this topology" as one flag instead of per-model spec code.

A :class:`Layout` is just three axis sizes on the
``mesh.make_mesh_3d`` mesh ``(data, fsdp, model)``:

* ``dp`` — replicas (batch shards, parameters replicated),
* ``fsdp`` — ZeRO-style parameter/optimizer sharding (the batch ALSO
  shards over it, jointly with ``data``),
* ``tp`` — the Megatron model axis (rule tables decide which dims).

The parameter placement comes from the declarative rules engine
(:mod:`.rules`): the model family's committed table decides the
tensor-parallel dims, :func:`.rules.with_fsdp` overlays the ZeRO
sharding on every large leaf's leftover dim, and the derived spec tree
drives the UNCHANGED dp train step (``dp.make_train_step`` with
``state_shardings`` + a ``("data", "fsdp")`` batch) — GSPMD composes
the collectives exactly as it already does for the hand-built fsdp/tp
variants (arXiv:1810.09868's full-program partitioning).

:func:`pick` is the auto-layout picker ROADMAP item 3 promised: it
prices every candidate layout by compiling the REAL train step
abstractly (eval_shape'd state — no buffer is ever allocated), ranks
the candidates by per-device HBM headroom through the same
``rank_memory`` ranking ``bin/fit.py`` uses, and breaks ties among
fitting layouts by the compiled-HLO collective ledger
(:mod:`..obs.comms` — fewest bytes moved per step wins; plain dp
all-reduces grads once and beats fsdp's per-layer all-gathers whenever
it fits, which is exactly the intuition, now measured).
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any, Callable, Optional, Sequence, Tuple

import numpy as np

from .. import mesh as mesh_lib

__all__ = [
    "Layout",
    "LayoutError",
    "PickReport",
    "LAYOUT_PRESETS",
    "resolve_layout",
    "layout_candidates",
    "state_specs_for",
    "price_layouts",
    "pick",
]


class LayoutError(ValueError):
    """A layout cannot be built/priced/picked on this topology."""


@dataclasses.dataclass(frozen=True)
class Layout:
    """One point on the dp×fsdp×tp grid.  ``dp * fsdp * tp`` must
    equal the device count the mesh is built over."""

    name: str
    dp: int = 1
    fsdp: int = 1
    tp: int = 1

    @property
    def sizes(self) -> dict:
        return {mesh_lib.DATA_AXIS: self.dp, mesh_lib.FSDP_AXIS: self.fsdp,
                mesh_lib.MODEL_AXIS: self.tp}

    @property
    def batch_axes(self) -> Tuple[str, str]:
        """The batch dim shards over data AND fsdp jointly (size-1
        axes are harmless in a PartitionSpec entry)."""
        return (mesh_lib.DATA_AXIS, mesh_lib.FSDP_AXIS)

    @property
    def batch_shards(self) -> int:
        return self.dp * self.fsdp

    def devices(self) -> int:
        return self.dp * self.fsdp * self.tp

    def build_mesh(self, devs: Sequence | None = None):
        return mesh_lib.make_mesh_3d(self.dp, self.fsdp, self.tp,
                                     devs=devs)

    def validate_mesh(self, mesh) -> None:
        """A caller-supplied mesh must carry exactly this layout's
        axis sizes — a mismatch means the compiled specs and the
        physical mesh disagree."""
        got = {k: int(v) for k, v in dict(mesh.shape).items()}
        if got != self.sizes:
            raise LayoutError(
                f"mesh axes {got} do not match layout {self.name!r} "
                f"{self.sizes} — build the mesh with "
                "layout.build_mesh() or mesh.make_mesh_3d")

    def describe(self) -> str:
        return (f"{self.name}: dp={self.dp} x fsdp={self.fsdp} x "
                f"tp={self.tp}")


def _even_split(n: int) -> int:
    """The smallest non-trivial factor of ``n`` (2 for even counts) —
    the conservative dp extent the mixed presets use."""
    for k in (2, 3, 5, 7):
        if n % k == 0:
            return k
    return 1


#: preset name → (ndev -> Layout | None).  None = the preset does not
#: exist at this device count (e.g. dp_fsdp on 1 device).
LAYOUT_PRESETS: dict = {
    "dp": lambda n: Layout("dp", dp=n),
    "fsdp": lambda n: Layout("fsdp", fsdp=n) if n > 1 else None,
    "tp": lambda n: Layout("tp", tp=n) if n > 1 else None,
    "dp_fsdp": lambda n: (
        Layout("dp_fsdp", dp=_even_split(n), fsdp=n // _even_split(n))
        if n >= 4 and _even_split(n) > 1 else None),
    "fsdp_tp": lambda n: (
        Layout("fsdp_tp", fsdp=n // _even_split(n), tp=_even_split(n))
        if n >= 4 and _even_split(n) > 1 else None),
    "dp_fsdp_tp": lambda n: (
        Layout("dp_fsdp_tp", dp=2, fsdp=n // 4, tp=2)
        if n >= 8 and n % 4 == 0 else None),
}


def resolve_layout(spec, ndev: Optional[int] = None) -> Layout:
    """A Layout from a Layout (validated) or a preset name.  ``ndev``
    defaults to the process's device count."""
    import jax

    n = ndev if ndev is not None else jax.device_count()
    if isinstance(spec, Layout):
        if spec.devices() != n:
            raise LayoutError(
                f"layout {spec.describe()} covers {spec.devices()} "
                f"devices but the topology has {n}")
        return spec
    if isinstance(spec, str):
        fn = LAYOUT_PRESETS.get(spec)
        if fn is None:
            raise LayoutError(
                f"unknown layout preset {spec!r} "
                f"(known: {sorted(LAYOUT_PRESETS)}, or pass a Layout)")
        lay = fn(n)
        if lay is None:
            raise LayoutError(
                f"layout preset {spec!r} does not exist on {n} "
                "device(s)")
        return lay
    raise TypeError(f"layout must be a Layout or preset name, got "
                    f"{type(spec).__name__}")


def layout_candidates(ndev: Optional[int] = None) -> list:
    """Every preset that exists at this device count — the picker's
    default candidate set."""
    import jax

    n = ndev if ndev is not None else jax.device_count()
    out = []
    for name in LAYOUT_PRESETS:
        lay = LAYOUT_PRESETS[name](n)
        if lay is not None:
            out.append(lay)
    return out


def state_specs_for(model, state, layout: Layout, mesh,
                    min_size: Optional[int] = None):
    """The rule-derived ``TrainState`` spec tree for ``model`` under
    ``layout``: the model family's committed table decides the
    tensor-parallel dims (empty table when ``tp == 1``), the fsdp
    overlay shards every large leaf's leftover dim, optimizer state
    broadcasts from its param, and the whole tree is validated
    (axis names + divisibility) BEFORE any placement happens.  A
    ``tp > 1`` layout whose model family has no tensor-parallel table
    is rejected — a silently replicated model axis would burn devices.
    """
    from . import rules

    kw = {} if min_size is None else {"min_size": min_size}
    table = rules.rules_for_model(model, tp=layout.tp > 1)
    if layout.tp > 1 and not table:
        raise LayoutError(
            f"layout {layout.name!r} has a model axis (tp={layout.tp}) "
            f"but {type(model).__name__} has no tensor-parallel rule "
            "table — every leaf would replicate over it.  Use a dp/"
            "fsdp layout, or register a table in parallel/rules.py")
    p_specs = rules.match_partition_rules(
        table, state.params, mesh=mesh, **kw)
    if layout.fsdp > 1:
        p_specs = rules.with_fsdp(
            p_specs, state.params, mesh, axis=mesh_lib.FSDP_AXIS, **kw)
    spec_state = rules.train_state_specs(state, p_specs)
    rules.validate_specs(spec_state, state, mesh,
                         where=f"layout:{layout.name}")
    return spec_state


# -- the picker -------------------------------------------------------------


@dataclasses.dataclass
class PickReport:
    """What the picker decided and why — the artifact the driver
    prints and CI uploads next to the profile artifacts."""

    chosen: Optional[Layout]
    rows: list
    budget_bytes: Optional[float]
    reason: str

    def to_json(self) -> dict:
        return {
            "schema": "fdtpu-layout-pick/v1",
            "chosen": self.chosen.name if self.chosen else None,
            "chosen_sizes": self.chosen.sizes if self.chosen else None,
            "budget_bytes": self.budget_bytes,
            "reason": self.reason,
            "rows": self.rows,
        }

    def save(self, path: str) -> None:
        import os

        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(self.to_json(), f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)

    def describe(self) -> str:
        lines = []
        if self.budget_bytes is not None:
            lines.append(
                f"layout pick: per-device HBM budget "
                f"{self.budget_bytes:.3e} bytes")
        else:
            lines.append(
                "layout pick: NO HBM budget (backend reports no "
                "memory_stats and none was passed) — ranked by "
                "collective bytes only")
        for r in self.rows:
            peak = (f"peak {r['peak_bytes']:>13,}"
                    if r.get("peak_bytes") is not None
                    else "peak   unavailable")
            fits = {True: "FITS", False: "DOES NOT FIT",
                    None: "fit unknown"}[r.get("fits")]
            if r.get("comms_bytes") is not None:
                comms = f"collective bytes/step {r['comms_bytes']:,}"
            elif "invalid" in r:
                comms = f"invalid: {r['invalid']}"
            else:
                # priced fine, ledger extraction failed — a fitting
                # candidate must never read as "invalid"
                comms = ("collective ledger unavailable"
                         + (f" ({r['comms_unavailable']})"
                            if r.get("comms_unavailable") else ""))
            mark = " <== chosen" if (
                self.chosen and r["layout"] == self.chosen.name) else ""
            lines.append(
                f"  {r['layout']:<12} {peak}  {fits:<13} {comms}{mark}")
        lines.append(f"layout pick: {self.reason}")
        return "\n".join(lines)


def _loss_fn_for(model, loss_fn=None):
    from ..models.transformer_lm import TransformerLM, lm_loss_fn
    from ..ops import logitcrossentropy
    from .dp import flax_loss_fn

    if loss_fn is not None:
        return loss_fn
    if isinstance(model, TransformerLM):
        return lm_loss_fn(model)
    return flax_loss_fn(model, logitcrossentropy)


def _abstract_state(model, batch_struct, optimizer):
    """TrainState of ShapeDtypeStructs — the picker prices layouts
    without ever allocating a parameter buffer."""
    import jax

    from .dp import TrainState

    # the model_input convention (data/loader.py) without np coercion —
    # these are ShapeDtypeStructs, not arrays
    sample = None
    for k in ("image", "tokens"):
        if k in batch_struct:
            sample = batch_struct[k]
            break
    if sample is None:
        sample = next(iter(batch_struct.values()))

    def build(s):
        variables = model.init(
            {"params": jax.random.PRNGKey(0),
             "dropout": jax.random.PRNGKey(1)}, s, train=True)
        params = variables["params"]
        mstate = {k: v for k, v in variables.items() if k != "params"}
        return TrainState.create(params, optimizer, model_state=mstate)

    return jax.eval_shape(build, sample)


def price_layouts(
    model,
    batch_struct: dict,
    optimizer=None,
    *,
    layouts: Optional[Sequence[Layout]] = None,
    loss_fn: Optional[Callable] = None,
    ndev: Optional[int] = None,
    min_size: Optional[int] = None,
) -> list:
    """Compile each candidate layout's REAL train step abstractly and
    return one row per candidate: ``peak_bytes`` off XLA's
    ``memory_analysis`` (None when this build lacks it), the compiled
    collective ledger rolled up per mesh axis, or ``invalid`` with the
    reason (indivisible batch, no TP table, indivisible heads, ...).

    ``batch_struct`` is a batch dict of arrays or ShapeDtypeStructs —
    shapes and dtypes are all that matters; nothing is executed."""
    import jax

    from ..obs import memstats
    from ..obs.comms import hlo_collectives, total_bytes
    from ..sharding import make_shardings
    from . import dp as dp_lib

    if optimizer is None:
        from .. import optim

        optimizer = optim.adam(1e-3)
    batch_struct = {
        k: jax.ShapeDtypeStruct(np.shape(v), getattr(v, "dtype", None))
        for k, v in batch_struct.items()}
    bsz = next(iter(batch_struct.values())).shape[0]
    lf = _loss_fn_for(model, loss_fn)
    cands = list(layouts) if layouts is not None else layout_candidates(ndev)
    state_struct = _abstract_state(model, batch_struct, optimizer)
    rows = []
    for lay in cands:
        row: dict = {"layout": lay.name, "sizes": lay.sizes,
                     "peak_bytes": None, "comms_bytes": None}
        if bsz % lay.batch_shards:
            row["invalid"] = (f"batch {bsz} not divisible by dp x fsdp "
                              f"= {lay.batch_shards}")
            rows.append(row)
            continue
        try:
            mesh = lay.build_mesh()
            spec_state = state_specs_for(
                model, state_struct, lay, mesh, min_size=min_size)
            sh = make_shardings(spec_state, mesh)
            step = dp_lib.make_train_step(
                lf, optimizer, mesh, axis=lay.batch_axes,
                donate=True, state_shardings=sh)
            compiled = step.lower(state_struct, batch_struct).compile()
        except (LayoutError, ValueError) as e:
            row["invalid"] = str(e)[:300]
            rows.append(row)
            continue
        mem = memstats.step_memory(step, (state_struct, batch_struct),
                                   compiled=compiled)
        if mem:
            row["peak_bytes"] = int(mem["peak_bytes"])
            row["memory"] = mem
        try:
            entries = hlo_collectives(compiled, mesh=mesh)
            row["comms"] = entries
            row["comms_bytes"] = int(total_bytes(entries))
            per_axis: dict = {}
            for e in entries:
                key = "+".join(e["axes"]) if e["axes"] else "?"
                per_axis[key] = per_axis.get(key, 0) + int(e["bytes"])
            row["comms_bytes_per_axis"] = per_axis
        except Exception as e:  # noqa: BLE001 — ledger is best-effort
            row["comms_unavailable"] = f"{type(e).__name__}: {e}"[:200]
        rows.append(row)
    return rows


def pick(
    model,
    batch_struct: dict,
    optimizer=None,
    *,
    hbm_bytes: Optional[float] = None,
    layouts: Optional[Sequence[Layout]] = None,
    loss_fn: Optional[Callable] = None,
    ndev: Optional[int] = None,
    min_size: Optional[int] = None,
    rows: Optional[list] = None,
) -> PickReport:
    """Choose the fastest layout that fits this topology.

    The HBM headroom ranking rides the same ``rank_memory`` the fit
    checker (``bin/fit.py``) uses — ``hbm_bytes`` defaults to the live
    per-device ``bytes_limit`` and MUST be passed on backends without
    ``memory_stats()`` (CPU) for fit verdicts.  Among fitting layouts
    the per-step collective ledger breaks the tie: fewest buffer bytes
    moved wins (then most headroom).  With no budget at all the
    verdicts stay unknown and the ledger alone ranks — documented
    degradation, never a silent guess of "fits".

    Raises :class:`LayoutError` when a budget is known and NO
    candidate fits (the report rides the exception's ``report``
    attribute so callers can still print the ranking).

    ``rows`` short-circuits the pricing: pass a prior
    :func:`price_layouts` result to re-pick under a different budget
    without recompiling (rows are copied; the input list is never
    mutated).
    """
    import copy

    from ..obs import memstats

    if rows is None:
        rows = price_layouts(
            model, batch_struct, optimizer, layouts=layouts,
            loss_fn=loss_fn, ndev=ndev, min_size=min_size)
    else:
        rows = copy.deepcopy(list(rows))
    budget = hbm_bytes
    if budget is None:
        stats = memstats.hbm_device_stats()
        limits = [d["bytes_limit"] for d in (stats or [])
                  if d["bytes_limit"] > 0]
        if limits:
            budget = float(min(limits))
    # the fit checker's ranking over the same row shape it consumes
    ranked = memstats.rank_memory(
        {r["layout"]: {"memory": r.get("memory")} for r in rows
         if "invalid" not in r},
        budget)
    verdicts = {r["variant"]: r for r in ranked}
    for r in rows:
        v = verdicts.get(r["layout"])
        r["fits"] = v["fits"] if v else None
        r["headroom_bytes"] = v["headroom_bytes"] if v else None

    def _tiebreak(r):
        comms = r.get("comms_bytes")
        head = r.get("headroom_bytes")
        return (comms if comms is not None else float("inf"),
                -(head if head is not None else float("-inf")))

    valid = [r for r in rows if "invalid" not in r]
    fitting = [r for r in valid if r["fits"]]
    # "does not fit" is only a verdict when a peak was actually
    # measured: on builds without memory_analysis every row prices to
    # peak_bytes=None / fits=None, and the honest behavior is the same
    # ledger-only degradation as no-budget — never a false "exceeds
    # the budget" hard failure about peaks nobody measured
    any_peak = any(r.get("peak_bytes") is not None for r in valid)
    if fitting:
        best = min(fitting, key=_tiebreak)
        comms_txt = (f"{best['comms_bytes']:,} bytes/step"
                     if best.get("comms_bytes") is not None
                     else "ledger unavailable")
        reason = (f"chose {best['layout']} — fits with headroom "
                  f"{best['headroom_bytes']:,} bytes and the smallest "
                  f"collective traffic ({comms_txt}) among "
                  f"{len(fitting)} fitting layout(s)")
    elif budget is not None and valid and any_peak:
        report = PickReport(None, rows, budget,
                            "no candidate layout fits the budget")
        err = LayoutError(
            f"no layout fits: every candidate's peak exceeds the "
            f"per-device budget {budget:.3e} bytes "
            f"({[(r['layout'], r.get('peak_bytes')) for r in valid]})")
        err.report = report
        raise err
    elif valid:
        best = min(valid, key=_tiebreak)
        why = ("memory model unavailable on this build"
               if budget is not None and not any_peak
               else "no HBM budget — pass hbm_bytes for fit verdicts")
        reason = (f"chose {best['layout']} by collective traffic alone "
                  f"({why})")
    else:
        report = PickReport(None, rows, budget,
                            "no valid candidate layout")
        err = LayoutError(
            "no valid candidate layout on this topology: "
            + "; ".join(f"{r['layout']}: {r.get('invalid')}"
                        for r in rows))
        err.report = report
        raise err
    # resolve the winner from the ROW'S recorded axis sizes, never by
    # name alone: rows from a custom price_layouts(layouts=...) call
    # may share a preset's name with DIFFERENT sizes, and the caller
    # must train on exactly the mesh whose figures won the ranking
    sizes = best.get("sizes") or {}
    if sizes:
        chosen = Layout(best["layout"],
                        dp=int(sizes.get(mesh_lib.DATA_AXIS, 1)),
                        fsdp=int(sizes.get(mesh_lib.FSDP_AXIS, 1)),
                        tp=int(sizes.get(mesh_lib.MODEL_AXIS, 1)))
    else:
        chosen = next(l for l in (layouts or layout_candidates(ndev))
                      if l.name == best["layout"])
    return PickReport(chosen, rows, budget, reason)
