"""Device and mesh discovery — the TPU-native device layer.

Replaces the reference's device abstraction (src/utils.jl:1-18: the
``@device!`` macro that dispatches work to a CUDA device and compiles to a
no-op on CPU, and the ``CUDA.devices()`` enumeration consumed by
``prepare_training``, src/ddp_tasks.jl:249-258).

On TPU there is no per-device task dispatch: one jitted SPMD program spans
a ``jax.sharding.Mesh`` and XLA inserts the collectives.  The device layer
therefore reduces to

* enumerating devices (``devices``/``device_count``),
* building meshes with named axes (``data_mesh``/``make_mesh``), and
* the *fake device* story for CI and GPU-less development: with
  ``JAX_PLATFORMS=cpu`` and
  ``XLA_FLAGS=--xla_force_host_platform_device_count=N`` the very same
  mesh/sharding code runs on N virtual CPU devices — the analog of the
  reference's integer "fake devices" that work because ``@device!`` is a
  CPU no-op (test/single_device.jl:121-151).

Axis-name conventions used throughout the framework:
``data`` (batch/DP), ``fsdp`` (ZeRO-style parameter/optimizer sharding
— batches shard over it jointly with ``data``, parameters shard over it
alone), ``model`` (tensor parallel), ``seq`` (sequence/context
parallel), ``pipe`` (pipeline), ``expert`` (MoE).  The reference only has
DP; the extra axes exist so the same mesh plumbing scales past it.

``make_mesh_3d`` builds the standard large-model 3-D mesh
``(data, fsdp, model)`` the declarative sharding-rules engine
(``parallel/rules.py`` + ``parallel/layout.py``) targets: pure dp is
``(N, 1, 1)``, pure ZeRO-3 is ``(1, N, 1)``, and any mixed layout is a
size assignment — one mesh recipe instead of one per variant.
"""

from __future__ import annotations

import os
from typing import Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

__all__ = [
    "DATA_AXIS",
    "FSDP_AXIS",
    "MODEL_AXIS",
    "SEQ_AXIS",
    "PIPE_AXIS",
    "EXPERT_AXIS",
    "devices",
    "device_count",
    "data_mesh",
    "make_mesh",
    "make_mesh_3d",
    "force_host_devices",
]

DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
PIPE_AXIS = "pipe"
EXPERT_AXIS = "expert"


def devices(platform: str | None = None):
    """All addressable devices, optionally filtered by platform name."""
    return jax.devices(platform) if platform else jax.devices()


def device_count() -> int:
    return jax.device_count()


def data_mesh(n: int | None = None, devs: Sequence | None = None) -> Mesh:
    """A 1-D mesh over ``n`` devices with the single axis ``data``.

    This is the reference's world: N replicas, gradients mean-reduced
    across them (src/ddp_tasks.jl:174-247).  ``n`` defaults to all
    devices.
    """
    devs = list(devs if devs is not None else jax.devices())
    if n is not None:
        if n > len(devs):
            raise ValueError(f"requested {n} devices but only {len(devs)} available")
        devs = devs[:n]
    return Mesh(np.array(devs), (DATA_AXIS,))


def make_mesh(axes: Mapping[str, int], devs: Sequence | None = None) -> Mesh:
    """An N-D mesh with named axes, e.g. ``{"data": 4, "model": 2}``.

    Axis order follows the mapping order; sizes must multiply to the
    number of devices used.  Uses ``mesh_utils.create_device_mesh`` so the
    physical layout rides ICI links where possible.
    """
    from jax.experimental import mesh_utils

    names = tuple(axes.keys())
    shape = tuple(int(v) for v in axes.values())
    total = int(np.prod(shape))
    devs = list(devs if devs is not None else jax.devices())
    if total > len(devs):
        raise ValueError(f"mesh {dict(axes)} needs {total} devices, have {len(devs)}")
    devs = devs[:total]
    if len(devs) == jax.device_count() and devs == list(jax.devices()):
        arr = mesh_utils.create_device_mesh(shape)
    else:
        arr = np.array(devs).reshape(shape)
    return Mesh(arr, names)


def make_mesh_3d(dp: int = 1, fsdp: int = 1, tp: int = 1,
                 devs: Sequence | None = None) -> Mesh:
    """The dp×fsdp×tp 3-D mesh ``(data, fsdp, model)`` — axis order is
    outermost-first so tensor-parallel groups (the latency-sensitive
    per-layer collectives) land on the innermost, fastest links of the
    physical topology.  Size-1 axes are kept (not squeezed): every
    PartitionSpec a rule table derives names the same three axes
    whatever the layout, so changing a layout never changes the spec
    vocabulary, only the sizes.

    ``dp`` replicates parameters (pure data parallelism), ``fsdp``
    shards parameters + optimizer state ZeRO-style (batches shard over
    ``data`` AND ``fsdp`` jointly), ``tp`` is the Megatron model axis.
    """
    for name, v in (("dp", dp), ("fsdp", fsdp), ("tp", tp)):
        if v < 1:
            raise ValueError(f"make_mesh_3d {name}={v} must be >= 1")
    return make_mesh(
        {DATA_AXIS: dp, FSDP_AXIS: fsdp, MODEL_AXIS: tp}, devs=devs)


def force_host_devices(n: int = 8) -> None:
    """Configure the process for ``n`` virtual CPU devices.

    Must run before JAX initializes its backends (XLA_FLAGS is read at
    backend init; the platform override goes through ``jax.config`` so it
    also wins over an environment-pinned platform).  This is the
    fake-device test harness: the same SPMD programs that target a TPU
    slice run on N host devices (the analog of the reference's CPU
    fake-device mode, src/utils.jl:1-18 + test/single_device.jl:144-150).
    """
    import re

    flags = os.environ.get("XLA_FLAGS", "")
    flag = f"--xla_force_host_platform_device_count={n}"
    if "xla_force_host_platform_device_count" in flags:
        # Rewrite a pre-set count rather than substring-skip it: the
        # pre-set value may differ from ``n``.
        flags = re.sub(
            r"--xla_force_host_platform_device_count=\d+", flag, flags
        )
    else:
        flags = (flags + " " + flag).strip()
    os.environ["XLA_FLAGS"] = flags
    os.environ["JAX_PLATFORMS"] = "cpu"
    jax.config.update("jax_platforms", "cpu")
