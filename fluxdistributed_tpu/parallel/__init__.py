from . import multihost
from .collectives import pmean, psum, all_gather, reduce_scatter, ppermute_ring
from .context import (
    make_ring_attention,
    make_ulysses_attention,
    ring_attention,
    ulysses_attention,
)
from .dp import TrainState, make_train_step, make_eval_step, make_train_step_shardmap
from . import fsdp
from .fsdp import fsdp_specs, hybrid_fsdp_tp_specs, make_train_step_fsdp, make_eval_step_fsdp
from . import zero1
from . import zero1_fused
from .zero1_fused import (
    fused_adam_update,
    make_train_step_zero1_fused,
    zero1_fused_state,
)
from .zero1 import (
    make_train_step_zero1,
    make_train_step_zero1_shardmap,
    zero1_optimizer,
    zero1_state,
    zero1_state_shardings,
)
from .ep import (
    moe_apply,
    router_dispatch,
    router_dispatch_expert_choice,
    stack_expert_params,
)
from .pp import make_train_step_pp, pipeline_apply, stack_stage_params, switch_stage
from .pp_1f1b import build_schedule, make_train_step_1f1b, pipeline_grads_1f1b
from . import pp_plan
from .pp_plan import PipelinePlan, plan_from_model, plan_from_profile, plan_stages
from .tp import lm_tp_rules, make_train_step_tp, param_specs, shard_state, vit_tp_rules
from . import rules
from .rules import (
    RULE_TABLES,
    ShardLargest,
    match_partition_rules,
    rules_for_model,
    with_fsdp,
)
from . import layout
from .layout import Layout, LayoutError, layout_candidates, resolve_layout

__all__ = [
    "multihost",
    "pmean",
    "psum",
    "all_gather",
    "reduce_scatter",
    "ppermute_ring",
    "TrainState",
    "make_train_step",
    "make_eval_step",
    "make_train_step_shardmap",
    "fsdp",
    "fsdp_specs",
    "hybrid_fsdp_tp_specs",
    "make_train_step_fsdp",
    "make_eval_step_fsdp",
    "zero1",
    "zero1_fused",
    "fused_adam_update",
    "make_train_step_zero1_fused",
    "zero1_fused_state",
    "make_train_step_zero1",
    "make_train_step_zero1_shardmap",
    "zero1_optimizer",
    "zero1_state",
    "zero1_state_shardings",
    "ring_attention",
    "make_ring_attention",
    "ulysses_attention",
    "make_ulysses_attention",
    "make_train_step_tp",
    "param_specs",
    "shard_state",
    "vit_tp_rules",
    "lm_tp_rules",
    "pipeline_apply",
    "make_train_step_pp",
    "build_schedule",
    "pipeline_grads_1f1b",
    "make_train_step_1f1b",
    "stack_stage_params",
    "switch_stage",
    "PipelinePlan",
    "plan_stages",
    "plan_from_profile",
    "plan_from_model",
    "pp_plan",
    "moe_apply",
    "router_dispatch_expert_choice",
    "router_dispatch",
    "stack_expert_params",
    "rules",
    "RULE_TABLES",
    "ShardLargest",
    "match_partition_rules",
    "rules_for_model",
    "with_fsdp",
    "layout",
    "Layout",
    "LayoutError",
    "layout_candidates",
    "resolve_layout",
]
