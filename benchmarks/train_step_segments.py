"""Segment-level timing of the ResNet-50 train step on the real chip.

Breaks the step time into segments — forward (eval/train mode),
forward+backward, full step (fwd+bwd+update) — plus XLA's cost analysis
(flops, bytes) for the compiled step, to locate where time goes before
reaching for flags or kernels.  Companion to bench.py (which records the
single headline number).

Run under `timeout` and let it exit normally (never kill a TPU process —
the device grant can stay held server-side and wedge the chip for all
subsequent clients).
"""

from __future__ import annotations

import time

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, n=20, warmup=3):
    for _ in range(warmup):
        out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n


def main():
    import fluxdistributed_tpu as fd
    from fluxdistributed_tpu import optim, sharding
    from fluxdistributed_tpu.models import resnet50
    from fluxdistributed_tpu.parallel import TrainState, make_train_step
    from fluxdistributed_tpu.parallel.dp import flax_loss_fn

    dev = jax.devices()[0]
    print(f"device: {dev}, platform {dev.platform}")

    # --- 1. matmul peak through the tunnel -----------------------------
    k = 8192
    a = jnp.asarray(np.random.default_rng(0).normal(0, 1, (k, k)), jnp.bfloat16)
    b = jnp.asarray(np.random.default_rng(1).normal(0, 1, (k, k)), jnp.bfloat16)

    @jax.jit
    def mm(a, b):
        return a @ b

    dt = timeit(mm, a, b)
    print(f"matmul {k}^3 bf16: {dt*1e3:.2f} ms -> {2*k**3/dt/1e12:.1f} TFLOP/s")

    # --- 2. ResNet-50 segments -----------------------------------------
    batch = 256
    mesh = fd.data_mesh()
    model = resnet50(num_classes=1000)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(0, 1, (batch, 224, 224, 3)), jnp.bfloat16)
    y = jnp.asarray(np.asarray(fd.onehot(rng.integers(0, 1000, batch), 1000)))

    variables = model.init(jax.random.PRNGKey(0), x[:1], train=True)
    params = variables["params"]
    mstate = {k2: v for k2, v in variables.items() if k2 != "params"}

    # fwd eval mode (no BN stats update)
    @jax.jit
    def fwd_eval(params, mstate, x):
        return model.apply({"params": params, **mstate}, x, train=False)

    print(f"fwd (eval mode):  {timeit(fwd_eval, params, mstate, x)*1e3:.2f} ms")

    # fwd train mode (BN batch stats)
    @jax.jit
    def fwd_train(params, mstate, x):
        out, mut = model.apply(
            {"params": params, **mstate}, x, train=True,
            mutable=list(mstate.keys()),
        )
        return out

    print(f"fwd (train mode): {timeit(fwd_train, params, mstate, x)*1e3:.2f} ms")

    # fwd+bwd
    loss_fn = flax_loss_fn(model, fd.logitcrossentropy)

    @jax.jit
    def fwdbwd(params, mstate, x, y):
        def lf(p):
            return loss_fn(p, mstate, {"image": x, "label": y}, True)

        (l, _), g = jax.value_and_grad(lf, has_aux=True)(params)
        return l, g

    print(f"fwd+bwd:          {timeit(fwdbwd, params, mstate, x, y)*1e3:.2f} ms")

    # full step
    opt = optim.momentum(0.1, 0.9)
    step = make_train_step(loss_fn, opt, mesh, donate=False)
    state = TrainState.create(
        sharding.replicate(params, mesh), opt,
        model_state=sharding.replicate(mstate, mesh),
    )
    bt = {"image": x, "label": y}
    dt = timeit(lambda s: step(s, bt)[0], state, n=10)
    print(f"full step:        {dt*1e3:.2f} ms  ({batch/dt:.0f} img/s)")

    # cost analysis
    lowered = jax.jit(lambda s, b: step(s, b)).lower(state, bt)
    comp = lowered.compile()
    ca = comp.cost_analysis()
    if ca:
        d = ca[0] if isinstance(ca, (list, tuple)) else ca
        fl = d.get("flops", 0)
        bytes_ = d.get("bytes accessed", 0)
        print(f"cost_analysis: flops={fl/1e12:.2f} TFLOP, bytes={bytes_/1e9:.1f} GB")
        print(f"  -> flops/img = {fl/batch/1e9:.1f} GFLOP")
        print(f"  -> at measured step: {fl/dt/1e12:.0f} TFLOP/s achieved")
        print(f"  -> HBM bw needed: {bytes_/dt/1e9:.0f} GB/s")


if __name__ == "__main__":
    main()
