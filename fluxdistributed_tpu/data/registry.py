"""Named-dataset registry — the ``Data.toml`` analog.

The reference selects datasets by hard-coded name strings
(``"imagenet_local"`` / ``"imagenet"`` / ``"imagenet_cyclops"``) resolved
through DataSets.jl against a ``Data.toml`` listing driver + location
(Data.toml:4-27; call sites src/ddp_tasks.jl:277, src/sync.jl:112).  Its
README admits the hard-coding should become an API (README.md:11).

Here that API: a TOML file (``datasets.toml``) declaring named datasets,

    [[datasets]]
    name = "imagenet_local"
    driver = "imagenet"             # imagenet | cifar10 | synthetic
    path = "/data/imagenet"         # filesystem root
    # driver-specific keys: split, classes, crop, ...

plus programmatic registration (``register_dataset``) and
``open_dataset(name)`` returning a dataset-protocol object.
"""

from __future__ import annotations

import tomllib
from typing import Any, Callable

__all__ = ["register_dataset", "open_dataset", "load_registry", "DRIVERS"]

_REGISTRY: dict[str, dict] = {}


def _driver_imagenet(spec: dict):
    from .imagenet import ImageNetDataset, labels, train_solutions
    from .sources import make_source

    # ``path`` may be a local dir, gs://bucket/prefix, or http(s)://…
    # (the reference's Data.toml registers both a FileSystem and an
    # S3-backed driver for the same dataset, Data.toml:4-27); remote
    # metadata files are fetched through the caching source.
    source = make_source(str(spec["path"]), cache_dir=spec.get("cache_dir"))
    split = spec.get("split", "train")
    synset = spec.get("synset_mapping") or source.local_path("LOC_synset_mapping.txt")
    lt = labels(synset)
    csv_path = spec.get("solution_csv", spec.get("train_solution"))
    if csv_path is None:
        csv_path = source.local_path(f"LOC_{split}_solution.csv")
    table = train_solutions(csv_path, lt, classes=spec.get("classes"), split=split)
    kwargs = {}
    for k in ("augment", "use_native"):
        # None keeps the dataset's auto/per-split default
        if spec.get(k) is not None:
            kwargs[k] = bool(spec[k])
    return ImageNetDataset(
        source,
        table,
        nclasses=len(lt),
        crop=int(spec.get("crop", 224)),
        resize=int(spec.get("resize", 256)),
        compat_double_normalize=bool(spec.get("compat_double_normalize", False)),
        num_threads=int(spec.get("num_threads", 8)),
        **kwargs,
    )


def _driver_cifar10(spec: dict):
    from .cifar import CIFAR10Dataset

    return CIFAR10Dataset(spec["path"], split=spec.get("split", "train"))


def _driver_synthetic(spec: dict):
    from .synthetic import SyntheticDataset

    shape = tuple(spec.get("shape", (32, 32, 3)))
    return SyntheticDataset(
        nsamples=int(spec.get("nsamples", 1024)),
        nclasses=int(spec.get("nclasses", 10)),
        shape=shape,
        seed=int(spec.get("seed", 0)),
    )


def _driver_text(spec: dict):
    from .text import ByteTextDataset

    return ByteTextDataset(spec["path"], seqlen=int(spec.get("seqlen", 256)))


DRIVERS: dict[str, Callable[[dict], Any]] = {
    "imagenet": _driver_imagenet,
    "cifar10": _driver_cifar10,
    "synthetic": _driver_synthetic,
    "text": _driver_text,
}


def register_dataset(name: str, driver: str, **spec) -> None:
    """Programmatic analog of a Data.toml entry."""
    if driver not in DRIVERS:
        raise ValueError(f"unknown driver {driver!r}; have {sorted(DRIVERS)}")
    _REGISTRY[name] = {"driver": driver, **spec}


def load_registry(toml_path: str) -> None:
    """Load ``[[datasets]]`` entries from a TOML file into the registry."""
    with open(toml_path, "rb") as f:
        doc = tomllib.load(f)
    for entry in doc.get("datasets", []):
        entry = dict(entry)
        name = entry.pop("name")
        driver = entry.pop("driver")
        register_dataset(name, driver, **entry)


def open_dataset(name: str, **overrides):
    """Instantiate the named dataset (``open(BlobTree, dataset(name))``
    analog, src/sync.jl:112)."""
    if name not in _REGISTRY:
        raise KeyError(
            f"dataset {name!r} not registered; known: {sorted(_REGISTRY)} "
            "(load a datasets.toml with load_registry or call register_dataset)"
        )
    spec = {**_REGISTRY[name], **overrides}
    driver = spec.pop("driver")
    return DRIVERS[driver](spec)
