"""Memory observability (obs/memstats.py + compat shims): the static
memory model, the None-safe HBM gauges, the watchdog's OOM-margin
alert, the baseline workflow, profile v2, and the graceful-degradation
contract (no memory model anywhere → everything reports "unavailable",
nothing crashes).
"""

from __future__ import annotations

import json
import math

import jax
import jax.numpy as jnp
import pytest

from fluxdistributed_tpu import compat
from fluxdistributed_tpu.obs import memstats
from fluxdistributed_tpu.obs.metrics import Registry
from fluxdistributed_tpu.obs.watchdog import StepWatchdog

FAKE_STATS = [
    {"device": 0, "kind": "fake-tpu", "bytes_in_use": 6_000,
     "peak_bytes_in_use": 9_000, "bytes_limit": 10_000},
    {"device": 1, "kind": "fake-tpu", "bytes_in_use": 9_800,
     "peak_bytes_in_use": 9_900, "bytes_limit": 10_000},
]


# ---- static model ---------------------------------------------------------

def test_tree_bytes_exact_on_eval_shape():
    tree = jax.eval_shape(
        lambda: {"a": jnp.zeros((4, 8), jnp.float32),
                 "b": jnp.zeros((3,), jnp.int8),
                 "none": None})
    assert memstats.tree_bytes(tree) == 4 * 8 * 4 + 3


def test_state_bytes_breakdown():
    class S:
        params = {"w": jnp.zeros((8, 8), jnp.float32)}
        opt_state = {"m": jnp.zeros((8, 8), jnp.float32),
                     "v": jnp.zeros((8, 8), jnp.float32)}
        model_state = {}

    sb = memstats.state_bytes(S())
    assert sb["param_bytes"] == 256
    assert sb["opt_state_bytes"] == 512
    assert sb["total_bytes"] == 768


def test_step_memory_real_program():
    f = jax.jit(lambda x: (x * 2.0).sum())
    mem = memstats.step_memory(f, (jnp.zeros((16, 16), jnp.float32),))
    assert mem is not None
    for key in ("argument_bytes", "output_bytes", "temp_bytes",
                "alias_bytes", "peak_bytes"):
        assert isinstance(mem[key], int), key
    assert mem["argument_bytes"] == 16 * 16 * 4
    assert mem["peak_bytes"] == (mem["argument_bytes"]
                                 + mem["output_bytes"]
                                 + mem["temp_bytes"] - mem["alias_bytes"])


def test_step_memory_unavailable_paths(monkeypatch):
    # a callable that cannot lower → None, never a raise
    assert memstats.step_memory(lambda x: x, (1,)) is None

    # a jax build whose Compiled lacks/breaks memory_analysis → None
    class NoMA:
        pass

    class RaisingMA:
        def memory_analysis(self):
            raise RuntimeError("unimplemented on this backend")

    class NoneMA:
        def memory_analysis(self):
            return None

    for compiled in (NoMA(), RaisingMA(), NoneMA()):
        assert compat.compiled_memory_analysis(compiled) is None
    f = jax.jit(lambda x: x * 2)
    args = (jnp.zeros((2,)),)
    monkeypatch.setattr(compat, "compiled_memory_analysis",
                        lambda compiled: None)
    assert memstats.step_memory(f, args) is None


# ---- live telemetry (CPU = unavailable; fakes = available) ----------------

def test_device_memory_stats_none_safe_on_cpu():
    # this suite runs on CPU: the shim must report absence, not crash
    for dev in jax.local_devices():
        assert compat.device_memory_stats(dev) is None
    assert memstats.hbm_device_stats() is None
    assert memstats.hbm_summary() == {"available": False}
    assert memstats.min_headroom_ratio() is None


def test_hbm_gauges_unavailable_report(monkeypatch):
    reg = Registry()
    g = memstats.HbmGauges(reg)
    assert g.available is False
    text = reg.prometheus_text()
    # the availability flag IS the "unavailable" report; no fake
    # zero-byte per-device series appear
    assert "fdtpu_hbm_available 0" in text
    assert "fdtpu_hbm_bytes_in_use" not in text
    assert math.isnan(reg.value("fdtpu_hbm_headroom_ratio"))
    assert g.summary() == {"available": False}
    g.close()
    assert reg.get("fdtpu_hbm_available") is None


def test_hbm_gauges_live_values(monkeypatch):
    monkeypatch.setattr(memstats, "hbm_device_stats", lambda: FAKE_STATS)
    reg = Registry()
    g = memstats.HbmGauges(reg)
    assert g.available is True
    assert reg.value("fdtpu_hbm_available") == 1
    assert reg.value("fdtpu_hbm_bytes_in_use", "0") == 6_000
    assert reg.value("fdtpu_hbm_bytes_peak", "1") == 9_900
    assert reg.value("fdtpu_hbm_bytes_limit", "0") == 10_000
    # headroom = min over devices = device 1's 2%
    assert reg.value("fdtpu_hbm_headroom_ratio") == pytest.approx(0.02)
    s = g.summary()
    assert s["available"] and s["min_headroom_ratio"] == pytest.approx(
        0.02)
    assert s["peak_bytes_in_use_max"] == 9_900
    # scrape-time truth: mutate the fake, the gauge follows once the
    # per-scrape sweep memo (SWEEP_TTL_SECONDS — one device sweep
    # serves a whole render, not one per cell) expires
    FAKE_STATS[1]["bytes_in_use"] = 5_000
    g._sweep_at = 0.0  # expire the memo deterministically
    try:
        assert reg.value("fdtpu_hbm_headroom_ratio") == pytest.approx(0.4)
    finally:
        FAKE_STATS[1]["bytes_in_use"] = 9_800


# ---- watchdog OOM-margin alert -------------------------------------------

def test_watchdog_headroom_episode_semantics(capsys):
    reg = Registry()
    wd = StepWatchdog(registry=reg, headroom_warn=0.05)
    # unavailable → no-op: gauge stays NaN, no episode
    assert wd.note_headroom(None) is False
    assert math.isnan(reg.value("fdtpu_hbm_headroom_ratio"))
    # healthy margin: gauge tracks, no alert
    assert wd.note_headroom(0.5) is False
    assert reg.value("fdtpu_hbm_headroom_ratio") == 0.5
    assert reg.value("fdtpu_watchdog_low_headroom_total") == 0
    # low margin: ONE warning per episode, not one per step
    assert wd.note_headroom(0.02) is True
    assert wd.note_headroom(0.01) is False
    assert wd.note_headroom(0.02) is False
    assert reg.value("fdtpu_watchdog_low_headroom_total") == 1
    assert "LOW HBM HEADROOM" in capsys.readouterr().err
    # recovery re-arms: the next dip is a NEW episode
    assert wd.note_headroom(0.5) is False
    assert wd.note_headroom(0.03) is True
    assert reg.value("fdtpu_watchdog_low_headroom_total") == 2
    # headroom_warn=0 disables the alert, gauge stays live
    wd2 = StepWatchdog(registry=Registry(), headroom_warn=0.0)
    assert wd2.note_headroom(0.001) is False
    with pytest.raises(ValueError, match="headroom_warn"):
        StepWatchdog(headroom_warn=1.5)


# ---- baseline workflow ----------------------------------------------------

def _mem(peak):
    return {"memory": {"peak_bytes": peak, "argument_bytes": 1,
                       "output_bytes": 1, "temp_bytes": 1,
                       "alias_bytes": 0}}


def test_check_memory_baseline_semantics():
    baseline = memstats.build_baseline(
        {"a": _mem(1000), "b": _mem(2000)}, tolerance=0.5)
    assert baseline["schema"] == memstats.BASELINE_SCHEMA

    # unchanged → clean
    res = memstats.check_memory_baseline(
        {"a": _mem(1000), "b": _mem(2000)}, baseline)
    assert res["failures"] == [] and res["checked"] == 2

    # within tolerance → clean; beyond → the regression failure
    ok = memstats.check_memory_baseline({"a": _mem(1400),
                                         "b": _mem(2000)}, baseline)
    assert ok["failures"] == []
    bad = memstats.check_memory_baseline({"a": _mem(1600),
                                          "b": _mem(2000)}, baseline)
    assert len(bad["failures"]) == 1 and "regressed" in bad["failures"][0]

    # a NEW variant the baseline does not cover fails (CI forces the
    # baseline to stay exhaustive), a stale entry only notes
    new = memstats.check_memory_baseline(
        {"a": _mem(1000), "c": _mem(10)}, baseline)
    assert any("not covered" in f for f in new["failures"])
    assert any("stale" in n for n in new["notes"])

    # unavailable memory model → note, never a failure
    degraded = memstats.check_memory_baseline(
        {"a": {"memory": None}, "b": _mem(2000)}, baseline)
    assert degraded["failures"] == []
    assert any("unavailable" in n for n in degraded["notes"])

    # shrinkage notes (re-record hint), never fails
    shrunk = memstats.check_memory_baseline(
        {"a": _mem(100), "b": _mem(2000)}, baseline)
    assert shrunk["failures"] == []
    assert any("shrank" in n for n in shrunk["notes"])


# ---- profile v2 -----------------------------------------------------------

def test_profile_v2_roundtrip_and_v1_accepted(tmp_path):
    from fluxdistributed_tpu.compilation import topology_fingerprint
    from fluxdistributed_tpu.obs.profile import ACCEPTED_SCHEMAS, Profile

    p2 = tmp_path / "v2.json"
    prof = Profile(
        fingerprint=topology_fingerprint(),
        memory={"state": {"param_bytes": 7}, "step": None,
                "variants": {"dp": {"memory": {"peak_bytes": 5}}}},
        comms={"step": {"jaxpr": [{"kind": "all_reduce", "axes": ["data"],
                                   "count": 1, "bytes": 4,
                                   "bytes_per_call": 4}]},
               "variants": {}},
    )
    prof.save(str(p2))
    back = Profile.load(str(p2)).verify()
    assert back.schema == "fdtpu-profile/v2"
    assert back.memory == prof.memory and back.comms == prof.comms

    # a v1 artifact (no memory/comms keys) still loads — additive schema
    doc = json.loads(p2.read_text())
    doc["schema"] = "fdtpu-profile/v1"
    del doc["memory"], doc["comms"]
    p1 = tmp_path / "v1.json"
    p1.write_text(json.dumps(doc))
    old = Profile.load(str(p1)).verify()
    assert old.schema == "fdtpu-profile/v1"
    assert old.memory == {} and old.comms == {}
    assert "fdtpu-profile/v1" in ACCEPTED_SCHEMAS

    # anything else is still rejected with the actionable message
    doc["schema"] = "fdtpu-profile/v0"
    p0 = tmp_path / "v0.json"
    p0.write_text(json.dumps(doc))
    with pytest.raises(ValueError, match="not a .*artifact"):
        Profile.load(str(p0))


# ---- serve /healthz + scheduler degradation -------------------------------

def test_serve_healthz_memory_block():
    """The LMServer memory block: unavailable on CPU but present, KV
    figures riding along when the engine reports them; a broken
    telemetry read degrades to {'available': False} instead of taking
    down /healthz."""
    from fluxdistributed_tpu.serve.scheduler import Scheduler
    from fluxdistributed_tpu.serve.server import LMServer
    from fluxdistributed_tpu.serve.testing import FakeLMEngine

    sched = Scheduler(FakeLMEngine(max_slots=2), max_queue=4)
    srv = LMServer(sched, vocab=32)
    block = srv._memory_block()
    assert block["available"] is False
    # the scheduler's registry carries the availability flag + NaN
    # headroom (the gauges' "unavailable" report) and close() detaches
    text = sched.registry.prometheus_text()
    assert "fdtpu_hbm_available 0" in text
    sched.close()
    assert sched.registry.get("fdtpu_hbm_available") is None

    class BrokenEngine(FakeLMEngine):
        def kv_cache_bytes(self):
            raise RuntimeError("boom")

    srv2 = LMServer(Scheduler(BrokenEngine(max_slots=2), max_queue=4),
                    vocab=32)
    assert srv2._memory_block() == {"available": False}


def test_scheduler_kv_byte_gauges():
    from fluxdistributed_tpu.serve.scheduler import Scheduler
    from fluxdistributed_tpu.serve.testing import FakeLMEngine

    class KVEngine(FakeLMEngine):
        def kv_cache_bytes(self):
            return {"reserved": 1024, "live": 256, "predicted": 1024}

    sched = Scheduler(KVEngine(max_slots=2), max_queue=4)
    assert sched.registry.value(
        "fdtpu_serve_kv_cache_reserved_bytes") == 1024
    assert sched.registry.value("fdtpu_serve_kv_cache_live_bytes") == 256
    # engines without the method read 0, not a crash
    sched2 = Scheduler(FakeLMEngine(max_slots=2), max_queue=4)
    assert sched2.registry.value(
        "fdtpu_serve_kv_cache_reserved_bytes") == 0


# ---- pp_plan cross-validation --------------------------------------------

def test_pp_plan_memory_check_band():
    """The tentpole loop-closer: the planner's per-stage byte estimate
    against XLA's memory_analysis of the REAL planned step, inside the
    documented band (PP_MEMORY_FACTOR: the estimate is the schedule's
    working-set lower bound; the measured peak adds grads, moments and
    temps — ≤ 8x the modeled total)."""
    from fluxdistributed_tpu import mesh as mesh_lib, optim
    from fluxdistributed_tpu.data.synthetic import SyntheticTextDataset
    from fluxdistributed_tpu.models.transformer_lm import TransformerLM
    from fluxdistributed_tpu.parallel.pp_plan import plan_from_model
    from fluxdistributed_tpu.train.trainer import (
        _dummy_batch, prepare_training)

    model = TransformerLM(vocab=64, dim=16, depth=6, num_heads=2,
                          mlp_dim=32, dtype=jnp.float32, dropout=0.0)
    ds = SyntheticTextDataset(vocab=64, seqlen=16)
    mesh = mesh_lib.make_mesh(
        {mesh_lib.DATA_AXIS: 2, mesh_lib.PIPE_AXIS: 4})
    plan = plan_from_model(model, 4, 2, batch_size=8, seqlen=16)
    assert plan.stage_bytes and max(plan.stage_bytes) > 0
    task = prepare_training(
        model, ds, optim.adam(1e-3), mesh=mesh, batch_size=16, cycles=1,
        donate=True, spmd="pp_1f1b", num_microbatches=2, topk=(),
        pp_plan=plan)
    batch = _dummy_batch(ds, None, 16, mesh, 1, seed=0)
    report = memstats.pp_plan_memory_check(
        plan, task.step_fn, (task.state, batch))
    assert report["measured"] is not None
    assert report["within"] is True, report
    # the band really is a band: a degenerate factor must fail it
    tight = memstats.pp_plan_memory_check(
        plan, task.step_fn, (task.state, batch), factor=0.001)
    assert tight["within"] is False


def test_pp_plan_memory_check_degrades(monkeypatch):
    from fluxdistributed_tpu.parallel.pp_plan import plan_stages

    plan = plan_stages([1.0] * 4, 2, 2, block_bytes=[10.0] * 4)
    monkeypatch.setattr(memstats, "step_memory",
                        lambda fn, args, compiled=None: None)
    report = memstats.pp_plan_memory_check(plan, None, ())
    assert report["within"] is None and report["measured"] is None
