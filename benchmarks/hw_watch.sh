#!/bin/sh
# Unattended availability watcher (round-4 workflow, docs/benchmarks.md):
# keep attempting the headline measurement; the FIRST success proves the
# chip is granting, after which the FULL staged session runs
# (benchmarks/hw_session.sh).  Survives the driver's turn boundaries via
# nohup; one TPU client at a time is preserved by (a) waiting for any
# pre-existing bench process and (b) an flock on this script's lockfile.
#
#   nohup sh benchmarks/hw_watch.sh >> benchmarks/hw/watch.log 2>&1 &
set -u
cd "$(dirname "$0")/.."
OUT="${1:-benchmarks/hw}"
mkdir -p "$OUT"
LOCK="$OUT/.watch.lock"
exec 9> "$LOCK"
if ! flock -n 9; then
    echo "watch: another watcher holds $LOCK; exiting"
    exit 0
fi
stamp() { date -u +%Y-%m-%dT%H:%M:%SZ; }

# HARD DEADLINE: the driver runs the official bench.py at round end,
# and the axon runtime grants ONE client at a time — a watcher attempt
# still holding (or queued for) the grant at that moment would wedge
# the official artifact even on a healthy chip.  An attempt is only
# launched if its full 2400 s bound FITS before the deadline, so the
# slot is guaranteed free at the deadline itself.  Also honors a
# benchmarks/hw/.stop kill file.  Default: 8 h from watcher START
# (computed before the wait-for-in-flight loop, which can itself take
# a while); override with WATCH_DEADLINE_EPOCH.
DEADLINE="${WATCH_DEADLINE_EPOCH:-$(( $(date +%s) + 8 * 3600 ))}"

# a stop request or an already-unreachable deadline exits BEFORE the
# wait-for-in-flight loop: with a wedged client in flight, waiting
# first would delay (or swallow) an exit that needs no waiting at all
if [ -e "$OUT/.stop" ]; then
    echo "[$(stamp)] watch: stop file present; exiting"
    exit 0
fi
if [ "$(date +%s)" -ge "$(( DEADLINE - 2400 ))" ]; then
    echo "[$(stamp)] watch: no attempt fits before the deadline; exiting"
    exit 0
fi

# wait for any in-flight bench client (grant contention wedges init);
# the .stop kill file is honored here too, or a wedged client would
# make the watcher ignore stop requests forever
while pgrep -f "bench\.py --one" > /dev/null 2>&1; do
    if [ -e "$OUT/.stop" ]; then
        echo "[$(stamp)] watch: stop file present while waiting; exiting"
        exit 0
    fi
    echo "[$(stamp)] watch: waiting for in-flight bench client"
    sleep 60
done

attempt=0
while :; do
    if [ -e "$OUT/.stop" ]; then
        echo "[$(stamp)] watch: stop file present; exiting"
        exit 0
    fi
    if [ "$(date +%s)" -ge "$(( DEADLINE - 2400 ))" ]; then
        echo "[$(stamp)] watch: attempt would straddle the deadline; exiting to free the slot"
        exit 0
    fi
    attempt=$((attempt + 1))
    echo "[$(stamp)] watch: bench attempt $attempt"
    timeout 2400 python bench.py --one > "$OUT/.try.json" 2>> "$OUT/watch.err"
    rc=$?
    if [ "$rc" = 0 ] && grep -q '"value"' "$OUT/.try.json" 2>/dev/null; then
        echo "[$(stamp)] watch: SUCCESS on attempt $attempt"
        cat "$OUT/.try.json" >> "$OUT/bench.jsonl"
        cat "$OUT/.try.json"
        break
    fi
    echo "[$(stamp)] watch: attempt $attempt failed rc=$rc ($(tail -c 200 "$OUT/watch.err" | tr '\n' ' '))"
    sleep 300
done

# chip is granting: run the rest of the staged chain (stage 1 re-runs
# bench.py, giving the required second reproduction of the headline) —
# but only with >= 2 h of runway, and only if no stop was requested
# while the last attempt ran.  The 2 h gate alone cannot bound the
# whole chain (the stages' summed worst-case timeouts far exceed it),
# so the deadline is EXPORTED: hw_session checks it before each stage
# and step_sweep between children — the kill-free safe points — and
# they skip whatever no longer fits.
if [ -e "$OUT/.stop" ]; then
    echo "[$(stamp)] watch: stop file present; keeping only the captured bench row"
    exit 0
fi
if [ $(( DEADLINE - $(date +%s) )) -lt 7200 ]; then
    echo "[$(stamp)] watch: <2h to deadline; keeping only the captured bench row"
    exit 0
fi
echo "[$(stamp)] watch: launching full hw_session (deadline $(date -u -d "@$DEADLINE" +%H:%MZ 2>/dev/null || echo "$DEADLINE"))"
HW_DEADLINE_EPOCH="$DEADLINE" sh benchmarks/hw_session.sh "$OUT"
echo "[$(stamp)] watch: hw_session complete"
