"""Prefill/decode scheduler: FIFO admission, per-request stopping,
backpressure, and serving metrics.

One loop drives the engine's compiled programs:

* **decode phase** — if any slot is live, ONE fixed-shape step over all
  slots; per-slot next tokens are emitted, stop conditions checked
  (``max_new_tokens`` / EOS), and finished requests free their slot.
* **admit phase** — free slots are filled from the bounded FIFO queue.
  Admission is gated on the engine's ``can_admit`` (paged layout: the
  block pool must cover the request's worst case on top of every
  already-admitted slot's — pool exhaustion queues at the head instead
  of admitting a request that could then never run to its budget).
  Without chunked prefill an admission runs one bucketed prefill and
  splices the result into its slot; with it the admission only BEGINS
  the prefill.
* **chunk phase** — at most ``prefill_chunks_per_tick`` prefill chunks
  advance per tick, round-robin over prefilling slots.  A long prompt's
  ingestion is spread across ticks between decode steps, so it can no
  longer spike TTFT for every resident request; the first generated
  token still comes from the (final chunk's) prefill logits.

Decode-before-admit means a slot freed by an EOS in step N is re-filled
within the same ``step()`` call — continuous batching, not gang
scheduling.  Backpressure is the bounded queue: ``submit`` raises
:class:`QueueFull` (the HTTP front end maps it to 429).  ``cancel``
aborts a request (client disconnect): queued requests leave the queue
immediately, active ones are torn down — slot freed, paged blocks
returned to the pool — on the driver thread's next tick.

Thread model: ``submit``/``metrics``/``cancel`` may be called from any
thread; ``step``/``run_until_idle`` must run on ONE driver thread (the
server's engine loop, or the test body).
"""

from __future__ import annotations

import itertools
import sys
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, List, Optional, Sequence

import jax
import numpy as np

from .. import faults
from ..obs.metrics import Registry
from ..obs.reqtrace import RequestTracer
from .engine import LMEngine

__all__ = ["Request", "Scheduler", "QueueFull", "Draining"]

# every serving series carries this prefix in Prometheus exposition;
# Scheduler.metrics() returns the same series WITHOUT it (the dict API
# predates the shared registry and its keys are stable)
METRIC_PREFIX = "fdtpu_serve_"

_ids = itertools.count()


class QueueFull(RuntimeError):
    """Admission queue at capacity — shed load (HTTP 429)."""


class Draining(RuntimeError):
    """Server is draining for shutdown — new admissions refused (HTTP
    503: unlike 429/QueueFull, retrying THIS instance is pointless;
    a load balancer should route elsewhere)."""


@dataclass
class Request:
    """One generation request riding the slot pool."""

    prompt: Sequence[int]
    max_new_tokens: int
    temperature: float = 0.0
    seed: int = 0
    eos_id: Optional[int] = None
    # called from the scheduler thread per emitted token (streaming)
    on_token: Optional[Callable[["Request", int], None]] = None
    id: int = field(default_factory=lambda: next(_ids))
    # caller-supplied trace id (the HTTP layer forwards X-Request-Id
    # here); every reqtrace event for this request lands on the track
    # it names — None falls back to the scheduler id (see trace_id)
    rid: Optional[str] = None

    # scheduler-owned state
    generated: List[int] = field(default_factory=list)
    state: str = "queued"  # queued | prefilling | active | done
    cancelled: bool = False  # set by cancel(); serviced on driver thread
    slot: Optional[int] = None
    done: threading.Event = field(default_factory=threading.Event)
    submitted_at: Optional[float] = None
    admitted_at: Optional[float] = None
    first_token_at: Optional[float] = None
    last_token_at: Optional[float] = None
    finished_at: Optional[float] = None

    def __post_init__(self):
        self.prompt = [int(t) for t in self.prompt]
        self._key = np.asarray(jax.random.PRNGKey(self.seed))

    @property
    def trace_id(self) -> str:
        """The id request-scoped events carry end-to-end."""
        return self.rid if self.rid is not None else str(self.id)

    @property
    def tokens(self) -> List[int]:
        """Prompt + generated — the ``models.generate`` output layout."""
        return list(self.prompt) + list(self.generated)


class Scheduler:
    """``registry=None`` builds a PRIVATE :class:`~..obs.Registry` per
    scheduler — engine instances stay isolated (tests spin several per
    process); pass a shared registry (e.g. ``obs.get_registry()``) to
    co-expose serving metrics with trainer/jax metrics on one scrape."""

    def __init__(self, engine: LMEngine, max_queue: int = 64,
                 registry: Optional[Registry] = None,
                 prefill_chunks_per_tick: int = 1,
                 reqtrace: Optional[RequestTracer] = None,
                 flight=None):
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        if prefill_chunks_per_tick < 1:
            raise ValueError(f"prefill_chunks_per_tick must be >= 1, got "
                             f"{prefill_chunks_per_tick}")
        self.engine = engine
        self.max_queue = max_queue
        #: chunk budget per tick when the engine prefills incrementally —
        #: 1 keeps decode cadence tight (one chunk rides between steps);
        #: raise it to favor prompt ingestion over decode latency
        self.prefill_chunks_per_tick = prefill_chunks_per_tick
        self._rr = -1  # round-robin cursor over prefilling slots
        self._ticks = 0  # step() calls — the serve.tick fault index
        #: graceful-drain latch (see :meth:`begin_drain`): True refuses
        #: NEW submissions while everything already accepted (queued or
        #: in a slot) runs to completion
        self.draining = False
        self._queue: deque[Request] = deque()
        self._lock = threading.Lock()
        self._work = threading.Event()
        self.slots: List[Optional[Request]] = [None] * engine.max_slots
        #: request-scoped lifecycle tracer (obs.reqtrace), or None —
        #: events cost nothing when absent, a bounded ring when present
        self.reqtrace = reqtrace
        #: black-box flight recorder (obs.flight.FlightRecorder), or
        #: None — one record per tick, so a replica killed mid-serve
        #: leaves a dump saying which tick it died on and what the
        #: queue/slots looked like
        self.flight = flight
        self.registry = registry if registry is not None else Registry()
        r, p = self.registry, METRIC_PREFIX
        c, g = r.counter, r.gauge
        self._c_submitted = c(p + "requests_submitted", "requests accepted into the queue")
        self._c_finished = c(p + "requests_finished", "requests fully generated")
        self._c_rejected = c(p + "requests_rejected", "requests shed with QueueFull (429)")
        self._c_prefill_tokens = c(p + "prefill_tokens", "real prompt tokens prefilled")
        self._c_prefill_padded = c(p + "prefill_padded_tokens", "bucket-padded tokens computed")
        self._c_prefill_sec = c(p + "prefill_sec", "seconds spent in prefill")
        self._c_decode_tokens = c(p + "decode_tokens", "live-slot tokens generated")
        self._c_decode_sec = c(p + "decode_sec", "seconds spent in decode steps")
        self._g_ttft_last = g(p + "ttft_sec_last", "most recent time-to-first-token")
        self._c_ttft_sum = c(p + "ttft_sec_sum", "sum of TTFT seconds")
        self._c_ttft_count = c(p + "ttft_count", "requests that produced a first token")
        self._h_ttft = r.histogram(
            p + "ttft_seconds", "time-to-first-token distribution")
        # the per-request latency truth the N-replica router needs and
        # aggregate counters cannot give: how long requests WAIT before
        # a slot admits them, and the inter-token (TBT) cadence once
        # they decode — both full histograms next to the TTFT one
        self._h_queue_wait = r.histogram(
            p + "queue_wait_seconds",
            "submit-to-admission wait distribution")
        self._h_tbt = r.histogram(
            p + "tbt_seconds",
            "inter-token (time-between-tokens) distribution")
        # chunked-prefill + paged-pool series (all zero / static for a
        # dense whole-prefill engine — the names are registered either
        # way so scrapes and close() are layout-independent)
        self._c_prefill_chunks = c(
            p + "prefill_chunks", "prefill chunks executed")
        self._g_chunks_last = g(
            p + "prefill_chunks_last_tick",
            "prefill chunks run in the most recent tick")
        self._c_cancelled = c(
            p + "requests_cancelled",
            "requests aborted (client disconnect / cancel)")
        self._c_prefix_hits = c(
            p + "prefix_cache_hits", "prefix-cache block hits")
        self._c_prefix_misses = c(
            p + "prefix_cache_misses", "prefix-cache block misses")
        self._c_prefix_evictions = c(
            p + "prefix_cache_evictions",
            "prefix-cached blocks evicted under pool pressure")
        # point-in-time values render at scrape time (zero hot-path cost);
        # the compile gauges make the engine's ONE-decode-compile
        # invariant a LIVE metric, not just an offline test assertion
        g(p + "queue_depth", "requests waiting for a slot").set_function(
            lambda: self.queue_depth)
        g(p + "active_slots", "slots generating right now").set_function(
            lambda: self.active_slots)
        g(p + "max_slots", "slot-pool capacity").set_function(
            lambda: self.engine.max_slots)
        g(p + "prefill_tokens_per_sec", "prefill throughput").set_function(
            lambda: self._rate(self._c_prefill_tokens, self._c_prefill_sec))
        g(p + "decode_tokens_per_sec", "decode throughput").set_function(
            lambda: self._rate(self._c_decode_tokens, self._c_decode_sec))
        g(p + "ttft_sec_avg", "mean time-to-first-token").set_function(
            lambda: self._rate(self._c_ttft_sum, self._c_ttft_count))
        for key in ("decode_compiles", "prefill_compiles", "insert_compiles"):
            g(p + key, "compiled-program count (steady state: decode "
                       "stays at 1)").set_function(
                lambda key=key: self.engine.compile_stats()[key])
        # block-pool occupancy (paged layout; reads 0 on dense engines):
        # free + cached is what admission reservations can draw on
        for key, txt in (
            ("kv_blocks_total", "KV block pool size per layer"),
            ("kv_blocks_free", "KV blocks on the free list"),
            ("kv_blocks_active", "KV blocks referenced by live slots"),
            ("kv_blocks_cached", "prefix-cached KV blocks (reclaimable)"),
        ):
            g(p + key, txt).set_function(
                lambda key=key: float(self._pool_stat(key)))
        # latency percentile rollups, computed AT SCRAPE TIME from the
        # histograms via the shared bucket_percentile helper (NaN while
        # empty — absence-of-data must not read as zero latency)
        for hist, stem in ((self._h_queue_wait, "queue_wait_sec"),
                           (self._h_tbt, "tbt_sec"),
                           (self._h_ttft, "ttft_hist_sec")):
            for q in (50, 95):
                g(p + f"{stem}_p{q}",
                  f"p{q} of {hist.name} (bucket-estimated)").set_function(
                    lambda hist=hist, q=q: hist.percentile(q))
        # KV-cache HBM truth next to the block-pool gauges: reserved =
        # what the cache tensors occupy, live = the fraction backing
        # live tokens (dense: equal; paged: the gap IS the layout win)
        self._kv_bytes_at = 0.0
        self._kv_bytes_memo: dict = {}
        for key, txt in (
            ("kv_cache_reserved_bytes",
             "HBM bytes the KV cache tensors occupy"),
            ("kv_cache_live_bytes",
             "KV cache bytes backing LIVE tokens"),
        ):
            g(p + key, txt).set_function(
                lambda key=key: float(self._kv_bytes(key)))
        # per-device HBM gauges (fdtpu_hbm_bytes_* / headroom at scrape
        # time; availability flag + NaN headroom on CPU) — the router's
        # /metrics rollup re-exposes them replica-labeled for free
        from ..obs.memstats import HbmGauges

        self.hbm = HbmGauges(self.registry)
        # the fdtpu_run_info stitch gauge (fingerprint/jax/schema
        # labels) on THIS registry, so a replica scrape names the run
        # its flight dump and ledger rows belong to
        from ..obs import runs as runs_lib

        runs_lib.set_run_info(self.registry, "serve")
        self._callback_gauges = [
            p + k for k in (
                "queue_depth", "active_slots", "max_slots",
                "prefill_tokens_per_sec", "decode_tokens_per_sec",
                "ttft_sec_avg", "decode_compiles", "prefill_compiles",
                "insert_compiles", "kv_blocks_total", "kv_blocks_free",
                "kv_blocks_active", "kv_blocks_cached",
                "kv_cache_reserved_bytes", "kv_cache_live_bytes",
                "queue_wait_sec_p50", "queue_wait_sec_p95",
                "tbt_sec_p50", "tbt_sec_p95",
                "ttft_hist_sec_p50", "ttft_hist_sec_p95",
            )
        ] + list(self.hbm.gauge_names)

    def _pool_stat(self, key: str) -> float:
        ps = getattr(self.engine, "pool_stats", None)
        return (ps() if callable(ps) else {}).get(key, 0)

    def _kv_bytes(self, key: str) -> float:
        # one kv_cache_bytes() tree walk serves BOTH gauges of a scrape
        # (each /metrics render reads reserved then live back-to-back)
        kb = getattr(self.engine, "kv_cache_bytes", None)
        if not callable(kb):
            return 0.0
        now = time.monotonic()
        if now - self._kv_bytes_at > 0.1:
            self._kv_bytes_memo = kb()
            self._kv_bytes_at = now
        return float(self._kv_bytes_memo.get(
            "reserved" if key.endswith("reserved_bytes") else "live", 0))

    def _sync_prefix_counters(self) -> None:
        """Fold the engine's cumulative prefix-cache tallies into the
        registry counters (delta-sync keeps Prometheus counter
        semantics — a shared registry's totals stay monotone across
        scheduler restarts)."""
        ps = getattr(self.engine, "pool_stats", None)
        if not callable(ps):
            return
        s = ps()
        for ctr, key in ((self._c_prefix_hits, "prefix_cache_hits"),
                         (self._c_prefix_misses, "prefix_cache_misses"),
                         (self._c_prefix_evictions,
                          "prefix_cache_evictions")):
            d = s.get(key, 0) - ctr.value()
            if d > 0:
                ctr.inc(d)

    @staticmethod
    def _rate(num, den) -> float:
        d = den.value()
        return num.value() / d if d else 0.0

    def close(self) -> None:
        """Detach this scheduler's scrape-time callbacks from the
        registry.  Irrelevant for the default PRIVATE registry (it dies
        with the scheduler), but with a shared registry the callback
        closures would otherwise pin the retired engine — and its slot
        KV cache — forever, and keep scraping its stale stats.  Plain
        counters stay registered deliberately: process-cumulative
        totals are correct Prometheus semantics across restarts (a
        successor scheduler's get-or-create continues them)."""
        for name in self._callback_gauges:
            self.registry.unregister(name)
        if self.flight is not None:
            # a retired scheduler is a SOFT exit — footer it (a killed
            # replica never reaches here, which is the signature)
            self.flight.dump("closed", ticks=self._ticks)

    # ---- producer side (any thread) ---------------------------------------

    def begin_drain(self) -> None:
        """Stop admissions for graceful shutdown.  Requests already
        accepted (queued or decoding) run to completion — bounding that
        is the caller's job (:meth:`LMServer.drain`'s timeout)."""
        if self.reqtrace is not None:
            self.reqtrace.event("scheduler", "drain_begin",
                                active=self.active_slots,
                                queued=self.queue_depth)
        # under the lock: submit() checks the latch inside its locked
        # region, so the store must be ordered against in-flight
        # admissions.  The gauge/tracer calls stay OUTSIDE — they take
        # the registry lock, and nesting it under the scheduler lock
        # would create a lock-order edge FDT302 exists to forbid.
        with self._lock:
            self.draining = True
        self.registry.gauge(
            "fdtpu_serve_draining",
            "1 while the scheduler refuses new admissions for shutdown",
        ).set(1)
        self._work.set()

    def submit(self, req: Request) -> Request:
        """Validate + enqueue; raises ``ValueError`` (bad shape),
        :class:`QueueFull` (backpressure) or :class:`Draining`
        (shutting down)."""
        self.engine.validate_request(len(req.prompt), req.max_new_tokens)
        with self._lock:
            if self.draining:
                self._c_rejected.inc()
                raise Draining(
                    "server is draining for shutdown; route elsewhere")
            if len(self._queue) >= self.max_queue:
                self._c_rejected.inc()
                raise QueueFull(
                    f"admission queue full ({self.max_queue} waiting)")
            req.state = "queued"
            req.submitted_at = time.monotonic()
            self._queue.append(req)
            self._c_submitted.inc()
            depth = len(self._queue)
        if self.reqtrace is not None:
            self.reqtrace.event(req.trace_id, "enqueue",
                                ts=req.submitted_at,
                                prompt_tokens=len(req.prompt),
                                max_new_tokens=req.max_new_tokens,
                                queue_depth=depth)
        self._work.set()
        return req

    def wait_for_work(self, timeout: float = 0.05) -> None:
        """Block the driver thread until a submit arrives (or timeout)."""
        self._work.wait(timeout)
        self._work.clear()

    def cancel(self, req: Request) -> bool:
        """Abort a request (client disconnect).  A queued request leaves
        the queue immediately (returns True); a prefilling/active one is
        flagged and torn down — slot freed, paged KV blocks back to the
        pool — at the start of the driver thread's next tick (returns
        False; ``req.done`` is set once the teardown ran)."""
        with self._lock:
            if req.state == "queued":
                try:
                    self._queue.remove(req)
                except ValueError:
                    pass  # raced with admission; fall through to the flag
                else:
                    req.state = "done"
                    req.finished_at = time.monotonic()
                    self._c_cancelled.inc()
                    if self.reqtrace is not None:
                        # a queued cancel must close its track too — an
                        # enqueue with no terminal event reads as a
                        # lost request in the timeline
                        self.reqtrace.event(req.trace_id, "cancel",
                                            ts=req.finished_at,
                                            generated=0)
                    req.done.set()
                    return True
            if req.state == "done":
                return True
            req.cancelled = True
        self._work.set()
        return False

    def _service_cancels(self) -> None:
        """Driver-thread half of :meth:`cancel`: free the slot and the
        engine-side resources of every flagged request."""
        for s, r in enumerate(self.slots):
            if r is not None and r.cancelled:
                self.slots[s] = None
                self.engine.reset_slot(s)
                r.slot = None
                r.state = "done"
                r.finished_at = time.monotonic()
                self._c_cancelled.inc()
                if self.reqtrace is not None:
                    self.reqtrace.event(r.trace_id, "cancel",
                                        ts=r.finished_at,
                                        generated=len(r.generated))
                r.done.set()

    def _admitted(self, req: Request) -> None:
        """Admission bookkeeping shared by both prefill paths: stamp
        the admission, observe the queue wait, close the request's
        queue_wait span."""
        now = time.monotonic()
        req.admitted_at = now
        if req.submitted_at is not None:
            self._h_queue_wait.observe(now - req.submitted_at)
            if self.reqtrace is not None:
                self.reqtrace.span(req.trace_id, "queue_wait",
                                   req.submitted_at, now)

    # ---- driver side (one thread) -----------------------------------------

    @property
    def active_slots(self) -> int:
        return sum(r is not None for r in self.slots)

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    @property
    def idle(self) -> bool:
        return self.active_slots == 0 and self.queue_depth == 0

    def step(self) -> int:
        """One scheduler tick: tear down cancelled requests, decode live
        slots, admit from the queue into whatever is free (including
        slots freed THIS tick), then advance at most
        ``prefill_chunks_per_tick`` prefill chunks (chunked engines).
        Returns the number of tokens emitted."""
        # deterministic serve-side injection point: a plan can crash
        # (action "exit"), stall, or raise at tick k — the replica-kill
        # and wedged-loop scenarios the router's failover tests need.
        # One global None check when no plan is installed.
        faults.fire("serve.tick", index=self._ticks)
        self._ticks += 1
        emitted = 0
        self._service_cancels()
        live = [s for s, r in enumerate(self.slots)
                if r is not None and r.state == "active"]
        if live:
            t0 = time.monotonic()
            nxt = self.engine.step_decode()
            t1 = time.monotonic()
            self._c_decode_sec.inc(t1 - t0)
            self._c_decode_tokens.inc(len(live))
            if self.reqtrace is not None:
                # the engine-program dispatch on its own scheduler lane:
                # request tracks show WHOSE token, this shows the tick
                self.reqtrace.span("scheduler", "decode_step", t0, t1,
                                   live=len(live))
            for s in live:
                self._emit(self.slots[s], int(nxt[s]))
                emitted += 1
        # admit into free slots (possibly just freed by EOS above).
        # Admission is FIFO: when the HEAD cannot be admitted (paged
        # block-pool reservation would overcommit), it WAITS — no
        # head-of-line skipping, so a big request cannot be starved by
        # a stream of small ones.
        incremental = bool(getattr(self.engine, "prefill_incremental",
                                   False))
        can_admit = getattr(self.engine, "can_admit", None)
        while True:
            try:
                free = self.slots.index(None)
            except ValueError:
                break
            with self._lock:
                if not self._queue:
                    break
                req = self._queue[0]
                if (can_admit is not None
                        and not can_admit(req.prompt, req.max_new_tokens)):
                    break
                self._queue.popleft()
            self._admitted(req)
            if incremental:
                # the request id rides INTO the engine on the prefill
                # state, so engine-side chunk advances stay attributable
                req._pf = self.engine.prefill_begin(
                    free, req.prompt, req.temperature, req._key,
                    max_new_tokens=req.max_new_tokens,
                    rid=req.trace_id)
                req.state = "prefilling"
                req.slot = free
                self.slots[free] = req
                continue
            t0 = time.monotonic()
            first, bucket = self.engine.prefill(
                free, req.prompt, req.temperature, req._key)
            t1 = time.monotonic()
            self._c_prefill_sec.inc(t1 - t0)
            self._c_prefill_tokens.inc(len(req.prompt))
            self._c_prefill_padded.inc(bucket)
            if self.reqtrace is not None:
                self.reqtrace.span(req.trace_id, "prefill", t0, t1,
                                   tokens=len(req.prompt), padded=bucket)
            req.state = "active"
            req.slot = free
            self.slots[free] = req
            self._emit(req, first)
            emitted += 1
        # chunk phase: round-robin the budget over prefilling slots so a
        # long prompt shares the tick with everyone else's chunks
        chunks_run = 0
        if incremental:
            for _ in range(self.prefill_chunks_per_tick):
                pf = [s for s, r in enumerate(self.slots)
                      if r is not None and r.state == "prefilling"]
                if not pf:
                    break
                s = next((x for x in pf if x > self._rr), pf[0])
                self._rr = s
                req = self.slots[s]
                t0 = time.monotonic()
                first, nreal, npad = self.engine.prefill_step(req._pf)
                t1 = time.monotonic()
                self._c_prefill_sec.inc(t1 - t0)
                self._c_prefill_tokens.inc(nreal)
                self._c_prefill_padded.inc(npad)
                self._c_prefill_chunks.inc()
                if self.reqtrace is not None:
                    self.reqtrace.span(
                        req.trace_id, "prefill_chunk", t0, t1,
                        pos=getattr(req._pf, "pos", None),
                        tokens=nreal, padded=npad)
                chunks_run += 1
                if first is not None:
                    req.state = "active"
                    self._emit(req, first)
                    emitted += 1
            self._g_chunks_last.set(chunks_run)
        self._sync_prefix_counters()
        if self.flight is not None:
            # per-tick black-box record: the serve analog of the
            # trainer's per-step record (a killed replica's dump says
            # which tick died and what the queue looked like)
            self.flight.record(
                tick=self._ticks - 1,
                emitted=emitted,
                active_slots=self.active_slots,
                queue_depth=self.queue_depth,
                chunks=chunks_run,
            )
        return emitted

    def run_until_idle(self, max_steps: int = 1_000_000) -> None:
        for _ in range(max_steps):
            if self.idle:
                return
            self.step()
        raise RuntimeError(f"scheduler did not drain in {max_steps} steps")

    def generate_all(self, requests: Sequence[Request]) -> List[List[int]]:
        """Convenience (tests/bench): submit everything, drain, return
        each request's prompt+generated token list."""
        for r in requests:
            self.submit(r)
        self.run_until_idle()
        return [r.tokens for r in requests]

    # ---- internals --------------------------------------------------------

    def _emit(self, req: Request, tok: int) -> None:
        now = time.monotonic()
        req.generated.append(tok)
        if req.first_token_at is None:
            req.first_token_at = now
            if req.submitted_at is not None:
                ttft = now - req.submitted_at
                self._g_ttft_last.set(ttft)
                self._c_ttft_sum.inc(ttft)
                self._c_ttft_count.inc()
                self._h_ttft.observe(ttft)
            if self.reqtrace is not None:
                self.reqtrace.event(req.trace_id, "first_token", ts=now)
        else:
            if req.last_token_at is not None:
                self._h_tbt.observe(now - req.last_token_at)
            if self.reqtrace is not None:
                # decode ticks on the request's own track — bounded by
                # the ring, only recorded while a tracer is attached
                self.reqtrace.event(req.trace_id, "token", ts=now,
                                    n=len(req.generated))
        req.last_token_at = now
        if req.on_token is not None:
            try:
                req.on_token(req, tok)
            except Exception as e:  # noqa: BLE001
                # a streaming callback must not be able to kill the
                # whole serving loop (or skip this request's stop check)
                print(f"serve: on_token callback failed for request "
                      f"{req.id}: {type(e).__name__}: {e}", file=sys.stderr)
        hit_eos = req.eos_id is not None and tok == req.eos_id
        if hit_eos or len(req.generated) >= req.max_new_tokens:
            self._finish(req)

    def _finish(self, req: Request) -> None:
        req.state = "done"
        req.finished_at = time.monotonic()
        if req.slot is not None:
            self.slots[req.slot] = None
            self.engine.reset_slot(req.slot)
            req.slot = None
        self._c_finished.inc()
        if self.reqtrace is not None:
            if req.first_token_at is not None:
                self.reqtrace.span(req.trace_id, "decode",
                                   req.first_token_at, req.finished_at,
                                   tokens=len(req.generated))
            self.reqtrace.event(req.trace_id, "finish",
                                ts=req.finished_at,
                                generated=len(req.generated))
        req.done.set()

    def metrics(self) -> dict:
        """Serving counters + derived rates + engine compile stats —
        the pre-registry dict API, now a READ of the registry (same
        keys as ever, sans the ``fdtpu_serve_`` exposition prefix)."""
        m = {
            "requests_submitted": self._c_submitted.value(),
            "requests_finished": self._c_finished.value(),
            "requests_rejected": self._c_rejected.value(),
            "prefill_tokens": self._c_prefill_tokens.value(),
            "prefill_padded_tokens": self._c_prefill_padded.value(),
            "prefill_sec": self._c_prefill_sec.value(),
            "decode_tokens": self._c_decode_tokens.value(),
            "decode_sec": self._c_decode_sec.value(),
            "ttft_sec_last": self._g_ttft_last.value(),
            "ttft_sec_sum": self._c_ttft_sum.value(),
            "ttft_count": self._c_ttft_count.value(),
            "queue_depth": self.queue_depth,
            "active_slots": self.active_slots,
            "max_slots": self.engine.max_slots,
            "prefill_tokens_per_sec": self._rate(
                self._c_prefill_tokens, self._c_prefill_sec),
            "decode_tokens_per_sec": self._rate(
                self._c_decode_tokens, self._c_decode_sec),
            # averaged over requests that GOT a first token — dividing
            # by requests_finished would overstate the average whenever
            # active requests have already produced TTFT samples
            "ttft_sec_avg": self._rate(self._c_ttft_sum, self._c_ttft_count),
        }
        self._sync_prefix_counters()
        m["prefill_chunks"] = self._c_prefill_chunks.value()
        m["requests_cancelled"] = self._c_cancelled.value()
        # per-request latency rollups (NaN while no sample exists):
        # bucket-estimated percentiles through the SHARED helper
        m["queue_wait_count"] = self._h_queue_wait.cell_count()
        m["queue_wait_sec_p50"] = self._h_queue_wait.percentile(50)
        m["queue_wait_sec_p95"] = self._h_queue_wait.percentile(95)
        m["tbt_count"] = self._h_tbt.cell_count()
        m["tbt_sec_p50"] = self._h_tbt.percentile(50)
        m["tbt_sec_p95"] = self._h_tbt.percentile(95)
        ps = getattr(self.engine, "pool_stats", None)
        if callable(ps):
            m.update(ps())
        m.update(self.engine.compile_stats())
        return m
