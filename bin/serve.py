#!/usr/bin/env python
"""Inference server — webcam demo (vision) or LM serving (``--lm``).

The reference's Pluto notebook embeds an HTML/JS webcam widget
(bin/pluto.jl:133-334) and classifies captured frames with a trained
model (:338-382).  The analog here is a tiny stdlib HTTP server:

* ``GET /``        — a self-contained HTML page that opens the webcam
                     (``getUserMedia``), draws frames to a canvas, and
                     POSTs JPEG snapshots to ``/predict``;
* ``POST /predict``— decode → preprocess (the training pipeline's
                     resize-256/center-crop-224/normalize) → one jitted
                     forward pass → JSON top-k labels.

    python bin/serve.py --model resnet50 --torch-weights r50.pt \
        --synset LOC_synset_mapping.txt --port 8000

Then open http://localhost:8000 in a browser.  Works with trainer
checkpoints (``--checkpoint``), torchvision-layout weights
(``--torch-weights``), or random init (demo mode).  Remote weights
(``http(s)://`` / ``gs://``) are fetched through the dataset source
cache.

With ``--lm`` the server instead fronts the continuous-batching LM
engine (``fluxdistributed_tpu.serve``): ``POST /v1/generate`` with
optional chunked streaming plus ``/healthz`` and ``/metrics``:

    python bin/serve.py --lm --model lm_tiny --checkpoint ck/ \
        --max-slots 8 --max-len 1024 --port 8000
    curl -d '{"prompt": "The quick", "max_tokens": 64}' \
        localhost:8000/v1/generate
"""

from __future__ import annotations

import argparse
import io
import json
import sys

HTML = """<!doctype html>
<html><head><title>fluxdistributed_tpu live inference</title><style>
 body{font-family:sans-serif;max-width:720px;margin:2em auto}
 video,canvas{width:320px;height:240px;background:#222;border-radius:8px}
 table{border-collapse:collapse;margin-top:1em}
 td,th{padding:4px 12px;border-bottom:1px solid #ccc;text-align:left}
</style></head><body>
<h2>Live inference</h2>
<p>Frames are captured from your camera and classified server-side.</p>
<video id="v" autoplay playsinline muted></video>
<canvas id="c" width="320" height="240" style="display:none"></canvas>
<p><button id="go">start</button> <span id="status"></span></p>
<table id="preds"><thead><tr><th>#</th><th>class</th><th>p</th></tr></thead>
<tbody></tbody></table>
<script>
const v=document.getElementById('v'),c=document.getElementById('c'),
      ctx=c.getContext('2d'),tb=document.querySelector('#preds tbody'),
      st=document.getElementById('status');
let running=false;
async function tick(){
  if(!running) return;
  ctx.drawImage(v,0,0,c.width,c.height);
  const blob=await new Promise(r=>c.toBlob(r,'image/jpeg',0.8));
  try{
    const resp=await fetch('/predict',{method:'POST',body:blob});
    const data=await resp.json();
    tb.innerHTML=data.predictions.map((p,i)=>
      `<tr><td>${i+1}</td><td>${p.label}</td><td>${p.prob.toFixed(3)}</td></tr>`).join('');
    st.textContent=`${data.ms.toFixed(0)} ms/frame`;
  }catch(e){st.textContent=e; running=false;}
  setTimeout(tick,250);
}
document.getElementById('go').onclick=async()=>{
  if(running){running=false;return;}
  const s=await navigator.mediaDevices.getUserMedia({video:true});
  v.srcObject=s; running=true; tick();
};
</script></body></html>"""


def build_parser():
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("--model", default="resnet50")
    p.add_argument("--num-classes", type=int, default=1000)
    p.add_argument("--checkpoint", default=None,
                   help="trainer checkpoint dir (http(s)://- or gs://-"
                        "fetched; remote .zip dirs are unpacked)")
    p.add_argument("--torch-weights", default=None)
    p.add_argument("--synset", default=None)
    p.add_argument("--topk", type=int, default=3)
    p.add_argument("--port", type=int, default=8000,
                   help="0 binds an ephemeral port; LM mode announces "
                        "the bound port as an FDTPU_SERVE_PORT=<n> "
                        "stdout line (and on /healthz) so a router or "
                        "test can orchestrate a fleet race-free")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--platform", default=None)
    # --- LM serving mode (continuous-batching engine) ---
    p.add_argument("--lm", action="store_true",
                   help="serve a TransformerLM through the continuous-"
                        "batching engine (POST /v1/generate) instead of "
                        "the vision webcam demo")
    p.add_argument("--vocab", type=int, default=256,
                   help="LM vocab size (256 = byte-level text prompts)")
    p.add_argument("--step", type=int, default=None,
                   help="specific checkpoint step (LM mode)")
    p.add_argument("--max-slots", type=int, default=8,
                   help="concurrent decode slots (the fixed compiled "
                        "batch of the decode step)")
    p.add_argument("--max-len", type=int, default=1024,
                   help="per-slot KV budget: prompt + generated tokens")
    p.add_argument("--buckets", default="128,512,2048",
                   help="comma-separated prefill shape buckets (prompts "
                        "pad up to the smallest covering bucket)")
    p.add_argument("--max-queue", type=int, default=64,
                   help="admission queue bound; beyond it /v1/generate "
                        "returns 429 (backpressure)")
    # paged KV cache (serve.cache_layout): HBM scales with live tokens
    p.add_argument("--paged", action="store_true",
                   help="paged KV cache layout: a shared pool of fixed-"
                        "size blocks with per-slot page tables instead "
                        "of worst-case rows per slot; freed blocks "
                        "return to the pool on EOS (LM mode)")
    p.add_argument("--kv-block-size", type=int, default=16,
                   help="rows per KV block (--paged)")
    p.add_argument("--kv-blocks", type=int, default=None,
                   help="blocks per layer in the pool (--paged); default "
                        "sizes for full capacity — set it SMALLER to "
                        "make HBM scale with live tokens and let "
                        "admission backpressure cover the tail")
    p.add_argument("--prefill-chunk", type=int, default=None,
                   help="prompt positions per prefill chunk; chunks "
                        "interleave with decode ticks so a long prompt "
                        "cannot spike TTFT for resident requests "
                        "(--paged defaults to 128; also valid on the "
                        "dense layout)")
    p.add_argument("--prefix-cache", action="store_true",
                   help="hash + refcount completed prompt blocks so "
                        "shared system prompts prefill once "
                        "(needs --paged, plain attention)")
    p.add_argument("--attention-impl", default="xla",
                   choices=["xla", "pallas"],
                   help="decode attention core: 'pallas' runs the "
                        "flash-decode kernel suite (ops/pallas_decode) — "
                        "cursor block-skip, native windowed-ring/paged "
                        "walks — with an XLA fallback off TPU; 'xla' is "
                        "the reference gather+mask path")
    p.add_argument("--kv-dtype", default=None,
                   choices=["int8", "fp8"],
                   help="quantize KV-cache storage (per-row scales ride "
                        "in the cache; dequant is fused into reads): "
                        "int8 halves bf16 KV bytes, quarters f32")
    p.add_argument("--kv-heads", type=int, default=None,
                   help="match the trainer's --kv-heads (GQA)")
    p.add_argument("--window", type=int, default=None,
                   help="match the trainer's --window (ring KV cache)")
    p.add_argument("--sinks", type=int, default=0,
                   help="match the trainer's --sinks (attention sinks)")
    p.add_argument("--norm", default="layernorm",
                   choices=["layernorm", "rmsnorm"],
                   help="match the trainer's --norm")
    p.add_argument("--mlp", default="gelu", choices=["gelu", "swiglu"],
                   help="match the trainer's --mlp")
    p.add_argument("--trace-requests", default=None, metavar="PATH",
                   help="record request-scoped lifecycle events "
                        "(enqueue/queue-wait/prefill chunks/first "
                        "token/decode ticks/finish) in a bounded ring "
                        "and write a Perfetto trace with one track per "
                        "request here at shutdown; the live ring is "
                        "also served at GET /trace (LM mode)")
    # cold-start controls (fluxdistributed_tpu.compilation)
    p.add_argument("--drain-timeout", type=float, default=30.0,
                   help="graceful-drain bound for --lm: on SIGTERM the "
                        "server stops admissions (503), finishes "
                        "in-flight decodes for up to this many seconds "
                        "(healthz reports draining), then exits 0 — "
                        "kube-style rolling restarts lose no tokens")
    p.add_argument("--prewarm", action="store_true",
                   help="pre-compile every prefill bucket, the splice "
                        "and the all-slot decode step BEFORE binding the "
                        "port — the first request pays decode latency, "
                        "not the engine's whole compile pool (LM mode)")
    p.add_argument("--compile-cache", default=None, metavar="DIR",
                   help="enable JAX's persistent compilation cache here "
                        "(topology-namespaced): a restarted server reads "
                        "its XLA compiles from disk instead of redoing "
                        "them")
    p.add_argument("--aot-dir", default=None, metavar="DIR",
                   help="serialized-executable pool for the engine's "
                        "programs: load from disk when topology+model "
                        "match, else compile now and serialize for the "
                        "next process (skips tracing AND compiling on "
                        "restart; LM mode)")
    p.add_argument("--fault-plan", default=None, metavar="JSON",
                   help="install a deterministic fault-injection plan "
                        "(fluxdistributed_tpu.faults) before serving — "
                        "JSON object or @path/to/plan.json, e.g. "
                        "'{\"fail\": [{\"site\": \"serve.tick\", "
                        "\"at\": 40, \"action\": \"exit\"}]}' is a "
                        "replica crash at scheduler tick 40 (the "
                        "router failover test harness)")
    p.add_argument("--fake-engine", action="store_true",
                   help="serve a deterministic pure-python engine "
                        "(serve.testing.FakeLMEngine) instead of a real "
                        "model — no compiles, instant startup; the "
                        "router fleet test/dev scaffold (LM mode)")
    p.add_argument("--fake-step-delay", type=float, default=0.002,
                   help="seconds each fake-engine decode tick sleeps "
                        "(gives drains and kills measurable width)")
    return p


def make_lm_app(args):
    """Build the LM-serving stack: ``(LMServer, Scheduler)``.

    Separate from HTTP wiring so tests can drive the scheduler directly
    (the ``make_app`` pattern below).
    """
    if args.fake_engine:
        # no model, no compiles: the router fleet scaffold — the HTTP/
        # scheduler surface is real, only the tokens are fake
        from fluxdistributed_tpu.serve.testing import FakeLMEngine

        engine = FakeLMEngine(max_slots=args.max_slots,
                              max_len=args.max_len,
                              step_delay=args.fake_step_delay,
                              vocab=args.vocab)
        return _wire_lm_stack(args, engine)

    import time

    import jax
    import numpy as np

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from fluxdistributed_tpu import compilation, models
    from fluxdistributed_tpu.serve import LMEngine

    if args.compile_cache:
        compilation.enable_persistent_cache(args.compile_cache)

    model_fn = getattr(models, args.model, None)
    if model_fn is None or not args.model.startswith("lm_"):
        raise SystemExit(f"--lm needs an lm_* model factory, got {args.model!r}")
    model = model_fn(vocab=args.vocab, num_kv_heads=args.kv_heads,
                     window=args.window, sinks=args.sinks, norm=args.norm,
                     mlp=args.mlp)
    if args.checkpoint:
        from fluxdistributed_tpu.data.sources import fetch_checkpoint
        from fluxdistributed_tpu.train import load_checkpoint

        restored = load_checkpoint(fetch_checkpoint(args.checkpoint),
                                   step=args.step)
        params = restored["params"]
        print(f"loaded checkpoint step "
              f"{int(np.asarray(restored.get('step', -1)))} "
              f"from {args.checkpoint}", file=sys.stderr)
    else:
        params = model.init(
            jax.random.PRNGKey(0), np.zeros((1, 2), np.int32), train=False
        )["params"]
        print("no --checkpoint: serving a RANDOM-INIT model", file=sys.stderr)

    try:
        buckets = tuple(int(b) for b in args.buckets.split(","))
    except ValueError:
        raise SystemExit(f"--buckets must be comma-separated ints, got "
                         f"{args.buckets!r}")
    t0 = time.perf_counter()
    engine = LMEngine(model, params, max_slots=args.max_slots,
                      max_len=args.max_len, buckets=buckets,
                      prewarm=args.prewarm, aot_dir=args.aot_dir,
                      layout="paged" if args.paged else "dense",
                      kv_block_size=args.kv_block_size,
                      kv_blocks=args.kv_blocks,
                      prefill_chunk=args.prefill_chunk,
                      prefix_cache=args.prefix_cache,
                      attention_impl=args.attention_impl,
                      kv_dtype=args.kv_dtype)
    if args.prewarm or args.aot_dir:
        print(f"engine ready in {time.perf_counter() - t0:.1f}s "
              f"(compile_stats={engine.compile_stats()})", file=sys.stderr)
    return _wire_lm_stack(args, engine)


def _wire_lm_stack(args, engine):
    """Scheduler + LMServer over any engine (real or fake) — ONE place
    so the fake-engine fleet cannot diverge from the real serving
    path."""
    from fluxdistributed_tpu.serve import LMServer, Scheduler

    reqtrace = None
    if getattr(args, "trace_requests", None):
        from fluxdistributed_tpu.obs import RequestTracer

        reqtrace = RequestTracer()
    scheduler = Scheduler(engine, max_queue=args.max_queue,
                          reqtrace=reqtrace)
    return LMServer(scheduler, args.vocab), scheduler


def make_app(args):
    """Build the ``predict(jpeg_bytes) -> [{label, prob}]`` closure;
    separate from serving so tests can drive it directly."""
    import jax
    import numpy as np

    if args.platform:
        jax.config.update("jax_platforms", args.platform)

    from fluxdistributed_tpu import models as models_lib
    from fluxdistributed_tpu.data.preprocess import preprocess

    if args.compile_cache:
        from fluxdistributed_tpu import compilation

        compilation.enable_persistent_cache(args.compile_cache)

    factory = getattr(models_lib, args.model, None)
    if factory is None:
        raise SystemExit(f"unknown model {args.model!r}")
    if args.torch_weights and args.checkpoint:
        raise SystemExit("--torch-weights and --checkpoint are mutually exclusive")
    from fluxdistributed_tpu.data.sources import fetch_artifact, fetch_checkpoint

    dummy = np.zeros((1, 224, 224, 3), np.float32)
    if args.torch_weights:
        from fluxdistributed_tpu.models.torch_import import load_torch_weights_for

        try:
            model, variables = load_torch_weights_for(
                args.model, args.num_classes, fetch_artifact(args.torch_weights)
            )
        except ValueError as e:
            raise SystemExit(str(e))
    elif args.checkpoint:
        model = factory(num_classes=args.num_classes)
        from fluxdistributed_tpu.train.checkpoint import load_checkpoint

        restored = load_checkpoint(fetch_checkpoint(args.checkpoint))
        variables = {"params": restored["params"], **restored.get("model_state", {})}
    else:
        model = factory(num_classes=args.num_classes)
        variables = model.init(jax.random.PRNGKey(0), dummy, train=False)

    names = None
    if args.synset:
        from fluxdistributed_tpu.data.imagenet import labels

        names = [n.split(",")[0] for n in labels(fetch_artifact(args.synset)).names]

    fwd = jax.jit(lambda v, x: model.apply(v, x, train=False))
    fwd(variables, dummy)  # compile before the first request

    def predict(jpeg_bytes: bytes):
        from PIL import Image

        img = Image.open(io.BytesIO(jpeg_bytes)).convert("RGB")
        x = preprocess(np.asarray(img, np.uint8))[None]
        logits = np.asarray(fwd(variables, x))[0]
        p = np.exp(logits - logits.max())
        p /= p.sum()
        top = np.argsort(-p)[: args.topk]
        return [
            {"label": names[i] if names else f"class {i}", "prob": float(p[i])}
            for i in top
        ]

    return predict


def serve(args, predict):
    import http.server
    import time

    class Handler(http.server.BaseHTTPRequestHandler):
        def log_message(self, *a):
            pass

        def _send(self, code, body: bytes, ctype: str):
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self):
            if self.path in ("/", "/index.html"):
                self._send(200, HTML.encode(), "text/html")
            else:
                self._send(404, b"not found", "text/plain")

        def do_POST(self):
            if self.path != "/predict":
                self._send(404, b"not found", "text/plain")
                return
            n = int(self.headers.get("Content-Length", 0))
            data = self.rfile.read(n)
            t0 = time.perf_counter()
            try:
                preds = predict(data)
            except Exception as e:  # bad frame: report, don't die
                self._send(400, json.dumps({"error": str(e)}).encode(),
                           "application/json")
                return
            body = json.dumps({
                "predictions": preds,
                "ms": (time.perf_counter() - t0) * 1e3,
            }).encode()
            self._send(200, body, "application/json")

    srv = http.server.ThreadingHTTPServer((args.host, args.port), Handler)
    print(f"serving on http://{args.host}:{srv.server_address[1]}/ (ctrl-c to stop)")
    return srv


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if getattr(args, "fault_plan", None):
        from fluxdistributed_tpu import faults

        spec = args.fault_plan
        if spec.startswith("@"):
            with open(spec[1:]) as f:
                spec = f.read()
        faults.install_plan(faults.FaultPlan.from_spec(json.loads(spec)))
    if args.lm:
        lm_server, scheduler = make_lm_app(args)
        srv = lm_server.serve(args.host, args.port)
        # SIGTERM → stop admissions, finish in-flight decodes (bounded),
        # shut the HTTP server down, exit 0 — the graceful-drain path
        lm_server.install_drain_handler(httpd=srv,
                                        timeout=args.drain_timeout)
        # the machine-readable bound-port announcement (--port 0 gives
        # an ephemeral one): routers and tests read THIS line, humans
        # read the next one
        print(f"FDTPU_SERVE_PORT={srv.server_address[1]}", flush=True)
        print(f"serving LM on http://{args.host}:{srv.server_address[1]}/"
              f"v1/generate (ctrl-c to stop; SIGTERM drains "
              f"<= {args.drain_timeout:.0f}s)", flush=True)
        try:
            srv.serve_forever()
        except KeyboardInterrupt:
            pass
        finally:
            lm_server.stop_loop()
            if scheduler.reqtrace is not None:
                n = scheduler.reqtrace.export_chrome_trace(
                    args.trace_requests)
                print(f"request trace ({n} events) written to "
                      f"{args.trace_requests}", file=sys.stderr)
        return 0
    predict = make_app(args)
    srv = serve(args, predict)
    try:
        srv.serve_forever()
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
