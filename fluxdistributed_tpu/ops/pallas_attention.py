"""Pallas TPU flash-attention kernel (forward AND backward).

Net-new TPU scope (the reference has no attention and no custom kernels;
its native compute all comes from CUDNN via dependencies — SURVEY §2
"native dependencies").  This is the framework's hand-written hot-op:
fused flash attention that keeps the [block_q, block_k] score tile in
VMEM, accumulates the online softmax in f32 scratch, and never
materializes the [Tq, Tk] score matrix in HBM.

Design (standard TPU flash schedule):

* forward grid = (batch*heads, Tq/block_q, Tk/block_k), KV innermost —
  the TPU grid is sequential per core, so VMEM scratch (acc, m, l)
  carries the online-softmax state across the KV dimension; the kernel
  also emits the per-row logsumexp (LSE) so the backward can recompute
  the block softmax without a second online pass;
* Q/K/V blocks are DMA'd HBM→VMEM by ``pallas_call`` per the BlockSpecs;
  the two matmuls (q·kᵀ and p·v) hit the MXU with f32 accumulation;
* causal masking uses global positions; fully-masked KV blocks are
  skipped with ``pl.when`` (no MXU work);
* backward = two dedicated Pallas kernels (FlashAttention-2 schedule):
  - dQ kernel, grid (BH, Tq/bq, Tk/bk) with KV innermost: recomputes
    p = exp(s − LSE) per tile, folds dS·K into a VMEM f32 accumulator,
    writes dQ once on the last KV step;
  - dK/dV kernel, grid (BH, Tk/bk, Tq/bq) with Q innermost: same tile
    recompute, accumulates Pᵀ·dO and dSᵀ·Q in VMEM, writes dK/dV once
    on the last Q step.
  ``delta = rowsum(dO ∘ O)`` is a cheap XLA elementwise-reduce done
  outside the kernels.  Padded query rows are self-masking: their LSE is
  padded to +1e30 so exp(s − LSE) is exactly 0.  Padded key rows are
  zero, so their dQ contribution (dS·K) vanishes without a mask; their
  dK/dV rows are garbage that the caller slices off.

On non-TPU backends the same kernels run in interpreter mode, so tests
exercise identical code on the CPU CI mesh.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import NEG_INF, online_softmax_update

__all__ = [
    "flash_attention",
    "flash_attention_lse",
    "interpret_mode",
]

# m/l scratch rows are replicated across the VPU lane width.
_LANES = 128


def interpret_mode() -> bool:
    """Whether Pallas kernels in this process run under the interpreter.

    A pure function of the backend — a per-process constant — resolved
    at TRACE time inside the jitted kernel wrappers, so the flag is NOT
    an argument of any compiled program: it never enters a jit cache
    key or an AOT argument-signature digest
    (``compilation.abstract_signature``), and toggling backends cannot
    retrace anything (there is nothing to toggle within a process).
    CPU-built and TPU-built executables are still keyed apart, by the
    *platform* field of ``compilation.topology_fingerprint`` — the
    correct split: interpretation is a consequence of the platform, not
    an independent axis.  (To run a specific kernel interpreted on TPU,
    use the decode kernels' explicit ``impl="interpret"`` argument or
    ``pltpu.force_tpu_interpret_mode()``.)
    """
    return jax.default_backend() != "tpu"
# LSE pad value for rows beyond Tq: exp(s - 1e30) == 0, so padded query
# rows contribute exactly nothing to dK/dV (and can never produce inf*0
# NaNs the way a garbage LSE could).
_LSE_PAD = 1e30


def _block_relevant(q_start, k_start, block_q, block_k,
                    causal, causal_offset, window, sinks):
    """Static-shape predicate: does KV block ``kj`` intersect the causal
    (and sliding-window) band of Q block ``qi`` at all?  False blocks are
    skipped with ``pl.when`` — with a window this is where the FLOPs
    saving comes from: far-past KV blocks never touch the MXU.  ``sinks``
    (attention sinks, StreamingLLM-style) keeps the first ``sinks`` key
    positions attendable from everywhere, so their blocks stay live."""
    cond = True
    if causal:
        # any (q, k) with k <= q + offset?
        cond = k_start <= q_start + block_q - 1 + causal_offset
        if window is not None:
            # any (q, k) with k >= q + offset - (window-1)?
            in_band = (k_start + block_k - 1
                       >= q_start + causal_offset - (window - 1))
            if sinks:
                in_band |= k_start < sinks  # sink blocks never go dead
            cond &= in_band
    return cond


def _band_mask(s_shape, q_start, k_start, *,
               causal, tk_valid, causal_offset, window, padded, sinks):
    """The shared fwd/bwd attend-mask for one [block_q, block_k] tile
    (None when every position is attendable)."""
    if not (causal or padded):
        return None
    k_pos = k_start + jax.lax.broadcasted_iota(jnp.int32, s_shape, 1)
    mask = k_pos < tk_valid
    if causal:
        q_pos = q_start + jax.lax.broadcasted_iota(jnp.int32, s_shape, 0)
        causal_ok = k_pos <= q_pos + causal_offset
        mask &= causal_ok
        if window is not None:
            in_band = k_pos >= q_pos + causal_offset - (window - 1)
            if sinks:
                # sinks stay attendable (still causally: causal_ok above)
                in_band |= k_pos < sinks
            mask &= in_band
    return mask


def _flash_kernel(
    q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, m_ref, l_ref,
    *, scale, causal, tk_valid, causal_offset, padded, window, sinks,
):
    """``causal_offset = Tk_valid - Tq_valid`` end-aligns the causal mask
    (query i attends keys <= i + offset), matching
    ``dot_product_attention``'s KV-cache-decode convention.  ``window``
    (sliding-window attention, causal only) restricts each query to its
    ``window`` most recent keys."""
    _, block_q, _ = q_ref.shape
    _, block_k, _ = k_ref.shape
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q_start = qi * block_q
    k_start = kj * block_k

    def _body():
        # Operands stay in their stored dtype: bf16 inputs ride the
        # MXU's native bf16×bf16→f32-accumulate path (casting to f32
        # first would halve MXU throughput).  The scale multiplies the
        # f32 scores, not the inputs, so no precision is lost to it.
        q = q_ref[0]
        k = k_ref[0]
        v = v_ref[0]
        s = scale * jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [block_q, block_k] f32

        mask = _band_mask(
            s.shape, q_start, k_start, causal=causal, tk_valid=tk_valid,
            causal_offset=causal_offset, window=window, padded=padded,
            sinks=sinks,
        )
        p, corr, m_new, l_new = online_softmax_update(
            s, m_ref[:, 0], l_ref[:, 0], mask=mask
        )
        acc_ref[:] = acc_ref[:] * corr[:, None] + jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_ref[:] = jnp.broadcast_to(m_new[:, None], m_ref.shape)
        l_ref[:] = jnp.broadcast_to(l_new[:, None], l_ref.shape)

    if causal:
        # Skip KV blocks entirely outside the causal/window band.
        pl.when(_block_relevant(
            q_start, k_start, block_q, block_k, causal, causal_offset,
            window, sinks,
        ))(_body)
    else:
        _body()

    @pl.when(kj == nk - 1)
    def _finalize():
        l = jnp.maximum(l_ref[:, :1], 1e-30)
        o_ref[0] = (acc_ref[:] / l).astype(o_ref.dtype)
        # LSE of a fully-masked row is ~NEG_INF; its backward tiles are
        # all-masked anyway, so the value is never observed.
        lse_ref[0] = m_ref[:, 0] + jnp.log(jnp.maximum(l_ref[:, 0], 1e-30))


def _bwd_tile(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
              *, scale, causal, tk_valid, causal_offset, padded, window,
              sinks, q_start, k_start):
    """Shared dQ/dKV tile recompute: returns (p, ds), both [bq, bk] f32.

    ``p`` is the exact forward block softmax, rebuilt from LSE;
    ``ds = p * (dP - delta)`` is the score gradient.  Masked positions
    are zeroed in ``p`` (NEG_INF-before-exp alone is unsafe: a fully-
    masked row has LSE ~ NEG_INF, making exp(s - LSE) explode).  Padded
    K columns are re-masked too: their K rows are zero so a FINITE p
    contributes nothing to dQ, but their score is 0 and exp(0 - LSE)
    can overflow to inf when a row's LSE < ~-88, and inf · 0 = NaN.
    """
    # native-dtype operands → bf16 MXU path, f32 accumulation (see fwd)
    q = q_ref[0]
    k = k_ref[0]
    v = v_ref[0]
    do = do_ref[0]
    lse = lse_ref[0]
    delta = delta_ref[0]
    s = scale * jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [block_q, block_k] f32
    p = jnp.exp(s - lse[:, None])
    mask = _band_mask(
        s.shape, q_start, k_start, causal=causal, tk_valid=tk_valid,
        causal_offset=causal_offset, window=window, padded=padded,
        sinks=sinks,
    )
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    dp = jax.lax.dot_general(
        do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )  # [block_q, block_k]
    ds = p * (dp - delta[:, None])
    return p, ds


def _flash_dq_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc_ref,
    *, scale, causal, tk_valid, causal_offset, padded, window, sinks,
):
    _, block_q, _ = q_ref.shape
    _, block_k, _ = k_ref.shape
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(kj == 0)
    def _init():
        dq_acc_ref[:] = jnp.zeros_like(dq_acc_ref)

    q_start = qi * block_q
    k_start = kj * block_k

    def _body():
        _, ds = _bwd_tile(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
            scale=scale, causal=causal, tk_valid=tk_valid,
            causal_offset=causal_offset, padded=padded, window=window,
            sinks=sinks, q_start=q_start, k_start=k_start,
        )
        k = k_ref[0]
        dq_acc_ref[:] += scale * jax.lax.dot_general(
            ds.astype(k.dtype), k,
            (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        )

    if causal:
        pl.when(_block_relevant(
            q_start, k_start, block_q, block_k, causal, causal_offset,
            window, sinks,
        ))(_body)
    else:
        _body()

    @pl.when(kj == nk - 1)
    def _finalize():
        dq_ref[0] = dq_acc_ref[:].astype(dq_ref.dtype)


def _flash_dkv_kernel(
    q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_acc_ref, dv_acc_ref,
    *, scale, causal, tk_valid, causal_offset, padded, nq, window, sinks,
):
    """Inner grid axis t = member * nq + qi: with GQA, each KV head's
    accumulator folds the q-blocks of all `group` query heads sharing
    it (group == 1 degenerates to t == qi)."""
    _, block_q, _ = q_ref.shape
    _, block_k, _ = k_ref.shape
    kj = pl.program_id(1)
    t = pl.program_id(2)
    ntot = pl.num_programs(2)
    qi = t % nq

    @pl.when(t == 0)
    def _init():
        dk_acc_ref[:] = jnp.zeros_like(dk_acc_ref)
        dv_acc_ref[:] = jnp.zeros_like(dv_acc_ref)

    q_start = qi * block_q
    k_start = kj * block_k

    def _body():
        p, ds = _bwd_tile(
            q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
            scale=scale, causal=causal, tk_valid=tk_valid,
            causal_offset=causal_offset, padded=padded, window=window,
            sinks=sinks, q_start=q_start, k_start=k_start,
        )
        do = do_ref[0]
        q = q_ref[0]
        dv_acc_ref[:] += jax.lax.dot_general(
            p.astype(do.dtype), do,
            (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        )  # pᵀ·dO: contract over the q dimension → [block_k, d]
        dk_acc_ref[:] += scale * jax.lax.dot_general(
            ds.astype(q.dtype), q,
            (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32,
        )  # dSᵀ·Q → [block_k, d]

    if causal:
        pl.when(_block_relevant(
            q_start, k_start, block_q, block_k, causal, causal_offset,
            window, sinks,
        ))(_body)
    else:
        _body()

    @pl.when(t == ntot - 1)
    def _finalize():
        dk_ref[0] = dk_acc_ref[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc_ref[:].astype(dv_ref.dtype)


def _pad_seq(x, block):
    pad = -x.shape[1] % block
    if pad:
        x = jnp.pad(x, ((0, 0), (0, pad), (0, 0)))
    return x


def _fold(x):
    """[B, T, H, D] → [B*H, T, D] (the kernels' layout)."""
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _unfold(x, b, h, t):
    return x[:, :t].reshape(b, h, t, x.shape[-1]).transpose(0, 2, 1, 3)


def _gqa_dims(q, k):
    """(h, hkv, group) with the divisibility check — GQA folds q heads
    into batch as usual while the BlockSpec index maps point each group
    of query heads at its SHARED KV head, so grouped KV is never
    repeated in HBM (the whole point of GQA's memory saving)."""
    h, hkv = q.shape[2], k.shape[2]
    if h % hkv:
        raise ValueError(
            f"num query heads ({h}) must be a multiple of num KV heads "
            f"({hkv}) for grouped-query attention")
    return h, hkv, h // hkv


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "window", "sinks"),
)
def _flash_fwd_impl(q, k, v, causal, block_q, block_k,
                    window=None, sinks=0):
    # trace-time constant (per-process) — deliberately NOT an argument,
    # so it cannot enter jit/AOT signature digests (see interpret_mode)
    interpret = interpret_mode()
    b, tq, h, d = q.shape
    tk = k.shape[1]
    h, hkv, group = _gqa_dims(q, k)
    scale = 1.0 / (d**0.5)
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)

    # Fold heads into batch: kernel operates on [BH, T, D].
    qf = _pad_seq(_fold(q), block_q)
    kf = _pad_seq(_fold(k), block_k)
    vf = _pad_seq(_fold(v), block_k)
    tq_p, tk_p = qf.shape[1], kf.shape[1]

    def kv_bh(bh):  # query-head program → its KV head's fold index
        return (bh // h) * hkv + (bh % h) // group

    grid = (b * h, tq_p // block_q, tk_p // block_k)
    kernel = functools.partial(
        _flash_kernel, scale=scale, causal=causal, tk_valid=tk,
        causal_offset=tk - tq, padded=tk_p != tk, window=window, sinks=sinks,
    )
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (kv_bh(bh), j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (kv_bh(bh), j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_q), lambda bh, i, j: (bh, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tq_p, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, tq_p), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
            pltpu.VMEM((block_q, _LANES), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf)
    return _unfold(out, b, h, tq), lse[:, :tq]


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "window", "sinks"),
)
def _flash_bwd_impl(q, k, v, o, lse, g, causal, block_q, block_k,
                    g_lse=None, window=None, sinks=0):
    interpret = interpret_mode()
    b, tq, h, d = q.shape
    tk = k.shape[1]
    h, hkv, group = _gqa_dims(q, k)
    scale = 1.0 / (d**0.5)
    block_q = min(block_q, tq)
    block_k = min(block_k, tk)

    qf = _pad_seq(_fold(q), block_q)
    kf = _pad_seq(_fold(k), block_k)
    vf = _pad_seq(_fold(v), block_k)
    dof = _pad_seq(_fold(g), block_q)
    of = _pad_seq(_fold(o), block_q)
    tq_p, tk_p = qf.shape[1], kf.shape[1]

    # delta_i = Σ_d dO ∘ O — one XLA fusion; zero on padded rows (dO pad).
    delta = (dof.astype(jnp.float32) * of.astype(jnp.float32)).sum(-1)
    if g_lse is not None:
        # Upstream gradient into the LSE output: ∂lse_r/∂s_rc = p_rc, so
        # ds = p∘(dP − delta + g_lse) — fold it into delta, the kernels
        # are untouched.  g_lse: [BH, tq] f32.
        delta = delta - jnp.pad(
            g_lse.astype(jnp.float32), ((0, 0), (0, tq_p - tq))
        )
    lse_p = jnp.pad(
        lse, ((0, 0), (0, tq_p - tq)), constant_values=_LSE_PAD
    )

    nq, nk = tq_p // block_q, tk_p // block_k
    bh = b * h

    def kv_bh(bh_):  # query-head program → its KV head's fold index
        return (bh_ // h) * hkv + (bh_ % h) // group

    def q_bh(bh_, t):  # (KV-head program, inner step) → q-head fold index
        return (bh_ // hkv) * h + (bh_ % hkv) * group + t // nq

    q_spec_i = pl.BlockSpec((1, block_q, d), lambda bh_, i, j: (bh_, i, 0))
    kv_spec_j = pl.BlockSpec(
        (1, block_k, d), lambda bh_, i, j: (kv_bh(bh_), j, 0))
    row_spec_i = pl.BlockSpec((1, block_q), lambda bh_, i, j: (bh_, i))
    # dKV grid is (b*hkv, j, t) where the inner axis t enumerates the
    # nq q-blocks of each of the `group` query heads sharing this KV
    # head: t = member * nq + qi.
    q_spec_inner = pl.BlockSpec(
        (1, block_q, d), lambda bh_, j, t: (q_bh(bh_, t), t % nq, 0))
    kv_spec_outer = pl.BlockSpec(
        (1, block_k, d), lambda bh_, j, t: (bh_, j, 0))
    row_spec_inner = pl.BlockSpec(
        (1, block_q), lambda bh_, j, t: (q_bh(bh_, t), t % nq))

    common = dict(
        scale=scale, causal=causal, tk_valid=tk, causal_offset=tk - tq,
        padded=tk_p != tk, window=window, sinks=sinks,
    )
    dq = pl.pallas_call(
        functools.partial(_flash_dq_kernel, **common),
        grid=(bh, nq, nk),
        in_specs=[q_spec_i, kv_spec_j, kv_spec_j, q_spec_i,
                  row_spec_i, row_spec_i],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh_, i, j: (bh_, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tq_p, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qf, kf, vf, dof, lse_p, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_flash_dkv_kernel, **common, nq=nq),
        grid=(b * hkv, nk, nq * group),
        in_specs=[q_spec_inner, kv_spec_outer, kv_spec_outer, q_spec_inner,
                  row_spec_inner, row_spec_inner],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda bh_, j, t: (bh_, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh_, j, t: (bh_, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * hkv, tk_p, d), k.dtype),
            jax.ShapeDtypeStruct((b * hkv, tk_p, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(qf, kf, vf, dof, lse_p, delta)

    return (
        _unfold(dq, b, h, tq),
        _unfold(dk, b, hkv, tk),
        _unfold(dv, b, hkv, tk),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7))
def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    window: int | None = None,
    sinks: int = 0,
) -> jax.Array:
    """Fused flash attention, [B, T, H, D] → [B, T, H, D].

    Runs the Pallas TPU kernels on TPU and the same kernels under the
    Pallas interpreter elsewhere (so CPU tests cover the real kernels),
    forward and backward.  Numerics match ``dot_product_attention`` to
    f32 accumulation.  Grouped-query KV ([B, T, Hkv, D]) is consumed
    natively (never repeated in HBM).  ``window`` (requires ``causal``)
    restricts each query to its ``window`` most recent keys — KV blocks
    outside the band are SKIPPED, so long-T cost is O(T·window), not
    O(T²).  ``sinks`` (StreamingLLM attention sinks; needs ``window``)
    keeps the first ``sinks`` key positions always attendable — their
    blocks stay live while everything between sink and band is skipped.
    """
    _validate_window(causal, window, sinks)
    out, _ = _flash_fwd_impl(q, k, v, causal, block_q, block_k,
                             window=window, sinks=sinks)
    return out


def _validate_window(causal, window, sinks):
    if window is not None and not causal:
        raise ValueError("window requires causal=True (sliding-window "
                         "attention is a causal-LM construct)")
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    if sinks:
        if sinks < 0:
            raise ValueError(f"sinks must be >= 0, got {sinks}")
        if window is None:
            raise ValueError("sinks only make sense with a window "
                             "(unwindowed causal attention already "
                             "attends every past position)")


def _fwd(q, k, v, causal, block_q, block_k, window, sinks):
    # custom_vjp skips the primal body under jax.grad — re-validate here
    # or invalid combos would silently trace through in training steps
    _validate_window(causal, window, sinks)
    out, lse = _flash_fwd_impl(q, k, v, causal, block_q, block_k,
                               window=window, sinks=sinks)
    return out, (q, k, v, out, lse)


def _bwd(causal, block_q, block_k, window, sinks, res, g):
    q, k, v, o, lse = res
    return _flash_bwd_impl(
        q, k, v, o, lse, g, causal, block_q, block_k,
        window=window, sinks=sinks,
    )


flash_attention.defvjp(_fwd, _bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    block_q: int = 128,
    block_k: int = 128,
    window: int | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Flash attention that ALSO returns the per-row logsumexp.

    → ``(out [B, Tq, H, D], lse [B, H, Tq] f32)`` where
    ``lse = log Σ_k exp(q·kᵀ/√D)``.  The LSE output is differentiable
    (its gradient folds into the same Pallas backward kernels), which is
    what lets ring attention use this kernel as its per-hop block
    compute and combine hops by LSE weighting.  Rows with no attendable
    position have ``lse ≈ -1e30`` (their combine weight underflows to
    exactly 0).
    """
    if window is not None and not causal:
        raise ValueError("window requires causal=True (sliding-window "
                         "attention is a causal-LM construct)")
    if window is not None and window < 1:
        raise ValueError(f"window must be >= 1, got {window}")
    out, lse = _flash_fwd_impl(q, k, v, causal, block_q, block_k,
                               window=window)
    b, tq, h, _ = q.shape
    return out, lse.reshape(b, h, tq)


def _fwd_lse(q, k, v, causal, block_q, block_k, window):
    out, lse = _flash_fwd_impl(q, k, v, causal, block_q, block_k,
                               window=window)
    b, tq, h, _ = q.shape
    return (out, lse.reshape(b, h, tq)), (q, k, v, out, lse)


def _bwd_lse(causal, block_q, block_k, window, res, g):
    q, k, v, o, lse = res
    g_out, g_lse = g
    b, tq, h, _ = q.shape
    return _flash_bwd_impl(
        q, k, v, o, lse, g_out, causal, block_q, block_k,
        g_lse=g_lse.reshape(b * h, tq), window=window,
    )


flash_attention_lse.defvjp(_fwd_lse, _bwd_lse)
