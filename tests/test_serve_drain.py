"""Graceful drain of the LM server (fast tier, FakeEngine — no
compiles).

SIGTERM-shaped shutdown contract: admissions stop (Draining → HTTP
503, distinct from 429 backpressure), in-flight requests finish within
the drain timeout, ``/healthz`` reports 503 + ``draining: true`` for
the whole window so a load balancer pulls the replica, and the process
can then exit 0 — a rolling restart loses no tokens.
"""

from __future__ import annotations

import json
import os
import signal
import threading
import time
import urllib.error
import urllib.request

import pytest

from fluxdistributed_tpu.serve import Draining, Request, Scheduler
from fluxdistributed_tpu.serve.server import LMServer


class FakeEngine:
    """Pure-python engine: decode emits token 1 per live slot; a small
    sleep per step gives the drain window measurable width."""

    max_slots = 2

    def __init__(self, step_delay=0.0):
        self.step_delay = step_delay

    def validate_request(self, prompt_len, max_new_tokens):
        pass

    def prefill(self, slot, prompt, temperature, key):
        return 7, 8

    def step_decode(self):
        if self.step_delay:
            time.sleep(self.step_delay)
        return [1] * self.max_slots

    def reset_slot(self, slot):
        pass

    def compile_stats(self):
        return {"decode_compiles": 1, "prefill_compiles": 1,
                "insert_compiles": 1}


def test_drain_finishes_inflight_then_refuses_admissions():
    sched = Scheduler(FakeEngine(step_delay=0.005), max_queue=8)
    srv = LMServer(sched, vocab=256)
    reqs = [Request(prompt=[1, 2], max_new_tokens=20) for _ in range(3)]
    for r in reqs:
        sched.submit(r)
    srv.start_loop()
    try:
        drained = srv.drain(timeout=10.0)
        assert drained is True
        assert all(r.done.is_set() for r in reqs)
        assert all(len(r.generated) == 20 for r in reqs), (
            "drain must FINISH in-flight decodes, not abort them")
        with pytest.raises(Draining):
            sched.submit(Request(prompt=[3], max_new_tokens=2))
        assert sched.registry.value("fdtpu_serve_draining") == 1
    finally:
        srv.close()


def test_drain_timeout_cuts_short_and_reports_false():
    sched = Scheduler(FakeEngine(step_delay=0.05), max_queue=8)
    srv = LMServer(sched, vocab=256)
    req = Request(prompt=[1], max_new_tokens=10_000)
    sched.submit(req)
    srv.start_loop()
    try:
        t0 = time.monotonic()
        drained = srv.drain(timeout=0.3)
        assert drained is False
        assert time.monotonic() - t0 < 5.0
        assert not req.done.is_set()  # client sees its own timeout
    finally:
        srv.close()


def _get(url):
    try:
        with urllib.request.urlopen(url, timeout=10) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def _post(url, body):
    req = urllib.request.Request(
        url, data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


def test_healthz_and_submit_report_503_while_draining():
    sched = Scheduler(FakeEngine(step_delay=0.02), max_queue=8)
    srv = LMServer(sched, vocab=256)
    httpd = srv.serve("127.0.0.1", 0)
    port = httpd.server_address[1]
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    base = f"http://127.0.0.1:{port}"
    try:
        code, body = _get(f"{base}/healthz")
        assert code == 200 and body["ok"] and not body["draining"]
        # park one long request so the drain window is observable
        sched.submit(Request(prompt=[1], max_new_tokens=200))
        sched.begin_drain()
        code, body = _get(f"{base}/healthz")
        assert code == 503
        assert body["draining"] is True and body["ok"] is False
        code, body = _post(f"{base}/v1/generate",
                           {"prompt_tokens": [1, 2], "max_tokens": 2})
        assert code == 503, body
        assert body.get("draining") is True
        assert srv.drain(timeout=30.0) is True
    finally:
        httpd.shutdown()
        srv.close()


def test_sigterm_handler_drains_and_stops_http():
    """The bin/serve.py wiring end-to-end in-process: SIGTERM → drain →
    httpd.shutdown → serve_forever returns → exit 0 path."""
    sched = Scheduler(FakeEngine(step_delay=0.01), max_queue=8)
    srv = LMServer(sched, vocab=256)
    httpd = srv.serve("127.0.0.1", 0)
    req = Request(prompt=[1, 2], max_new_tokens=30)
    sched.submit(req)
    uninstall = srv.install_drain_handler(httpd=httpd, timeout=10.0)
    served = threading.Event()

    def run():
        httpd.serve_forever()
        served.set()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    try:
        os.kill(os.getpid(), signal.SIGTERM)
        assert served.wait(timeout=30), "SIGTERM must stop serve_forever"
        assert req.done.is_set()
        assert len(req.generated) == 30
        assert sched.draining
    finally:
        uninstall()
        srv.close()
