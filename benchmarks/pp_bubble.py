#!/usr/bin/env python
"""Measured pipeline-schedule scaling vs the (S-1)/(M+S-1) formula,
GPipe (AD-derived backward) vs hand-scheduled 1F1B — with modeled-vs-
measured bubble accounting from a cost-profile artifact.

The GPipe schedule (parallel/pp.py:26-28) predicts utilization
M/(M+S-1) for M microbatches over S stages.  This script times the
pipelined LM forward+backward at M in {S, 2S, 4S, 8S} for either
schedule (``--schedule gpipe|1f1b``) and reports per-microbatch cost
scaling (VERDICT r3 weak #6).

Bubble accounting (ROADMAP item 4): the run stages out the model for
per-layer static costs (``obs.profile.lm_layer_costs``), fits the
measured rows to separate steady per-microbatch cost from fixed
fill/drain overhead, and reports the MODELED bubble fraction (schedule
formula over the static per-stage costs) next to the MEASURED one per
row (``obs.profile.bubble_report``).  ``--profile-out`` persists
everything as a versioned, topology-fingerprinted Profile artifact;
``--profile`` replays the report from a saved artifact without timing
anything (rejecting cross-topology artifacts unless
``--allow-mismatch``).

What each substrate can show:

* a real multi-chip slice measures the BUBBLE itself (idle devices);
* the shared-core fake-device mesh cannot (devices are never idle),
  but it exposes the schedules' MEMORY behavior: GPipe's AD-through-
  scan stores residuals for all M microbatches, so per-tick cost
  inflates with M (cache/allocator pressure), while 1F1B's fixed
  min(S,M)-slot input ring keeps per-microbatch cost ~flat — that
  contrast is the point of the comparison here.  The measured-bubble
  column follows suit: on real chips it is idle time, on the CPU mesh
  it is the schedule's fixed-overhead fraction.

    python benchmarks/pp_bubble.py --platform cpu --dim 128 --depth 8 \
        --profile-out pp_profile.json
    python benchmarks/pp_bubble.py --platform cpu --profile pp_profile.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def report_from_artifact(args) -> None:
    """``--profile``: modeled-vs-measured bubble report from a saved
    artifact — no timing run, no model build."""
    from fluxdistributed_tpu.obs.profile import (
        Profile, ProfileMismatch, bubble_report,
    )

    prof = Profile.load(args.profile)
    if args.allow_mismatch:
        print(json.dumps({"note": "fingerprint check skipped "
                                  "(--allow-mismatch)",
                          "artifact_topology": prof.topology}))
    else:
        # rebuild the artifact's recorded topology so the fingerprint
        # recipe can match; a box that cannot reproduce it is exactly
        # the cross-topology case the check exists to reject
        if args.platform == "cpu":
            from fluxdistributed_tpu.mesh import force_host_devices

            force_host_devices(int(prof.topology.get(
                "device_count", args.devices)))
        from fluxdistributed_tpu.mesh import make_mesh

        try:
            mesh_shape = prof.topology.get("mesh") or {}
            prof.verify(make_mesh({k: int(v) for k, v in
                                   mesh_shape.items()}) if mesh_shape
                        else None)
        except (ProfileMismatch, ValueError) as e:
            raise SystemExit(
                f"{e}\n(pass --allow-mismatch to analyze anyway)")
    rows = bubble_report(prof)
    for r in rows:
        print(json.dumps(r), flush=True)
    print(json.dumps({
        "metric": "pp bubble fraction, modeled vs measured "
                  f"(from {args.profile})",
        "schedule": prof.meta.get("schedule"),
        "rows": rows,
    }))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--platform", default=None)
    ap.add_argument("--devices", type=int, default=8,
                    help="pipe-axis size when forcing the cpu platform")
    ap.add_argument("--dim", type=int, default=128)
    ap.add_argument("--depth", type=int, default=8, help="decoder blocks (= stages)")
    ap.add_argument("--heads", type=int, default=4)
    ap.add_argument("--seqlen", type=int, default=128)
    ap.add_argument("--mb-size", type=int, default=4,
                    help="sequences per microbatch (fixed; M scales total batch)")
    ap.add_argument("--vocab", type=int, default=512)
    ap.add_argument("--seconds", type=float, default=2.0)
    ap.add_argument("--schedule", choices=("gpipe", "1f1b"), default="gpipe")
    ap.add_argument("--remat", action="store_true",
                    help="gpipe only: lm_pp(remat=True) — per-tick input "
                         "checkpointing, the AD-side answer to the residual "
                         "blowup (compare against the 1f1b rows)")
    ap.add_argument("--profile-out", default=None, metavar="PATH",
                    help="persist this run (static per-layer costs + "
                         "measured rows + topology fingerprint) as an "
                         "obs.profile artifact the planner / a later "
                         "--profile replay consumes")
    ap.add_argument("--profile", default=None, metavar="PATH",
                    help="skip the timing run: print the modeled-vs-"
                         "measured bubble report from this saved "
                         "artifact (topology-checked)")
    ap.add_argument("--allow-mismatch", action="store_true",
                    help="with --profile: analyze an artifact recorded "
                         "on a DIFFERENT topology (numbers then "
                         "describe that topology, not this box)")
    args = ap.parse_args()
    if args.remat and args.schedule != "gpipe":
        ap.error("--remat applies to --schedule gpipe only (1f1b always "
                 "recomputes from its input ring)")
    if args.profile:
        report_from_artifact(args)
        return

    import jax

    if args.platform == "cpu":
        from fluxdistributed_tpu.mesh import force_host_devices

        force_host_devices(args.devices)
    import jax.numpy as jnp

    from fluxdistributed_tpu import mesh as mesh_lib
    from fluxdistributed_tpu.models.transformer_lm import (
        TransformerLM, lm_pp, lm_pp_1f1b,
    )

    S = jax.device_count()
    mesh = mesh_lib.make_mesh({"pipe": S})
    model = TransformerLM(
        vocab=args.vocab, dim=args.dim, depth=args.depth,
        num_heads=args.heads, mlp_dim=4 * args.dim,
        dtype=jnp.float32, dropout=0.0,
    )
    rng = np.random.default_rng(0)
    toks1 = rng.integers(0, args.vocab, (args.mb_size, args.seqlen)).astype(np.int32)
    params = model.init(jax.random.PRNGKey(0), toks1, train=False)["params"]

    rows = []
    base_per_mb = None
    for mult in (1, 2, 4, 8):
        M = S * mult
        batch = args.mb_size * M
        toks = rng.integers(0, args.vocab, (batch, args.seqlen)).astype(np.int32)
        if args.schedule == "1f1b":
            from fluxdistributed_tpu.parallel.pp_1f1b import pipeline_grads_1f1b

            w = lm_pp_1f1b(model, mesh)
            pp = w.split_params(params)
            run = pipeline_grads_1f1b(
                *w.fns, mesh, num_microbatches=M, interleave=w.interleave)

            @jax.jit
            def fwdbwd(p, t):
                # the 1F1B program IS fwd+bwd: loss and both grad trees
                return run(p["stages"], p["outer"], t, t)

        else:
            split_params, loss_fn, _ = lm_pp(
                model, mesh, num_microbatches=M, remat=args.remat)
            pp = split_params(params)

            @jax.jit
            def fwdbwd(p, t):
                # loss on the pipelined forward; grads run the reverse schedule
                def loss(pp_):
                    l, _aux = loss_fn(pp_, {}, {"tokens": t}, False)
                    return l

                return jax.value_and_grad(loss)(p)

        l, *g = fwdbwd(pp, toks)
        jax.block_until_ready(l)
        t0 = time.perf_counter()
        iters = 0
        while time.perf_counter() - t0 < args.seconds:
            l, *g = fwdbwd(pp, toks)
            iters += 1
        jax.block_until_ready(l)
        dt = (time.perf_counter() - t0) / iters
        per_mb = dt / M
        if base_per_mb is None:
            base_per_mb = per_mb  # M=S row anchors the comparison
        util_pred = M / (M + S - 1)
        # measured utilization relative to the M=S anchor's prediction
        util_meas = (base_per_mb / per_mb) * (S / (2 * S - 1))
        rows.append({
            "M": M, "S": S, "batch": batch,
            "step_ms": round(dt * 1e3, 2),
            "ms_per_microbatch": round(per_mb * 1e3, 3),
            "util_formula": round(util_pred, 4),
            "util_measured": round(util_meas, 4),
        })
        print(json.dumps(rows[-1]), flush=True)

    print(json.dumps({
        "metric": f"{args.schedule}{'-remat' if args.remat else ''} "
                  "pipeline: measured vs (S-1)/(M+S-1)",
        "platform": jax.devices()[0].platform,
        "rows": rows,
    }))

    # ---- modeled vs measured bubble accounting (obs.profile) ----------
    # Static per-layer costs from the STAGED-OUT model (forward FLOPs;
    # fwd+bwd scales every block ~uniformly, so the stage-cost RATIOS
    # the schedule model needs are preserved) + the measured rows above,
    # bundled as the topology-fingerprinted artifact the planner reads.
    from fluxdistributed_tpu.compilation import topology_fingerprint
    from fluxdistributed_tpu.obs.profile import (
        Profile, bubble_report, describe_topology, lm_layer_costs,
    )

    prof = Profile(
        fingerprint=topology_fingerprint(mesh=mesh),
        topology=describe_topology(mesh),
        static={"model": lm_layer_costs(model, args.mb_size, args.seqlen),
                "step": None, "variants": {}},
        measured={"pp_rows": rows},
        meta={"schedule": args.schedule, "remat": bool(args.remat),
              "mb_size": args.mb_size, "seqlen": args.seqlen,
              "vocab": args.vocab, "producer": "benchmarks/pp_bubble.py"},
    )
    if args.profile_out:
        prof.save(args.profile_out)
        print(json.dumps({"profile_artifact": args.profile_out,
                          "fingerprint": prof.fingerprint}), flush=True)
    breport = bubble_report(prof)
    print(json.dumps({
        "metric": f"{args.schedule} pp bubble fraction, modeled "
                  "(static per-stage costs through the schedule model) "
                  "vs measured (fixed-cost share of wall time)",
        "platform": jax.devices()[0].platform,
        "rows": breport,
    }))


if __name__ == "__main__":
    main()
