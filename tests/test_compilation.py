"""Cold-start subsystem (fluxdistributed_tpu.compilation).

Fast tier: topology fingerprinting, the serialize→deserialize round
trip of AOT executables, the load-or-compile fallback on fingerprint
mismatch, engine prewarm/AOT invariants, and the trainer's
``cache_dir``/``aot``/``warmup`` wiring — all on the 8-device fake CPU
mesh.  Slow tier: the headline demonstration — a SECOND process
pointed at a warm persistent cache registers ZERO compilation-cache
misses (every XLA compile served from disk).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fluxdistributed_tpu import compilation
from fluxdistributed_tpu.obs import get_registry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------- fingerprint


def test_topology_fingerprint_stable_and_tag_sensitive():
    a, b = compilation.topology_fingerprint(), compilation.topology_fingerprint()
    assert a == b and len(a) == 16
    assert compilation.topology_fingerprint(tag="zero1") != a
    from fluxdistributed_tpu.mesh import data_mesh

    assert compilation.topology_fingerprint(mesh=data_mesh()) != a


def test_topology_namespace_is_readable():
    ns = compilation.topology_namespace()
    # platform, device/process counts and jax version are all legible —
    # the cache dir layout documents itself
    assert ns.startswith("cpu-")
    assert f"d{jax.device_count()}p{jax.process_count()}" in ns
    assert jax.__version__ in ns
    assert "/" not in ns and " " not in ns


def test_abstract_signature_tracks_shapes_and_structure():
    x, y = jnp.ones((4, 4)), jnp.ones((8, 4))
    assert (compilation.abstract_signature((x,))
            == compilation.abstract_signature((jnp.zeros((4, 4)),)))
    assert (compilation.abstract_signature((x,))
            != compilation.abstract_signature((y,)))
    assert (compilation.abstract_signature(({"a": x},))
            != compilation.abstract_signature(({"b": x},)))
    assert (compilation.abstract_signature((x,))
            != compilation.abstract_signature((x.astype(jnp.bfloat16),)))


# ------------------------------------------------------------ cache enablement


@pytest.fixture
def restore_cache_config():
    prev = jax.config.jax_compilation_cache_dir
    yield
    from fluxdistributed_tpu import compat

    if prev:
        compat.configure_compilation_cache(prev)
    else:
        jax.config.update("jax_compilation_cache_dir", prev)
        from jax._src import compilation_cache as _icc

        _icc.reset_cache()  # drop the memoized cache-in-use decision
    compilation._cache_dir = None


def test_enable_persistent_cache(tmp_path, restore_cache_config):
    resolved = compilation.enable_persistent_cache(str(tmp_path / "cc"))
    assert resolved is not None and os.path.isdir(resolved)
    # namespaced per topology under the requested root
    assert os.path.dirname(resolved) == str(tmp_path / "cc")
    assert os.path.basename(resolved) == compilation.topology_namespace()
    assert jax.config.jax_compilation_cache_dir == resolved
    assert compilation.persistent_cache_dir() == resolved
    assert get_registry().value("fdtpu_compile_cache_enabled") == 1
    # falsy dir = disabled, no side effects
    assert compilation.enable_persistent_cache(None) is None
    assert compilation.enable_persistent_cache("") is None


def test_configure_compilation_cache_shim_never_raises(tmp_path, monkeypatch,
                                                       restore_cache_config):
    """On a jax build without ANY cache knob the shim warns and reports
    False — enablement must be a no-op, not a crash."""
    from fluxdistributed_tpu import compat

    assert compat.configure_compilation_cache(str(tmp_path)) is True
    # simulate the knob-less build: every config update fails and the
    # legacy set_cache_dir import path is absent
    monkeypatch.setattr(compat, "_try_config_update", lambda *a: False)
    import jax.experimental.compilation_cache.compilation_cache as legacy

    monkeypatch.delattr(legacy, "set_cache_dir", raising=False)
    with pytest.warns(RuntimeWarning, match="no persistent compilation cache"):
        assert compat.configure_compilation_cache(str(tmp_path)) is False
    assert compilation.enable_persistent_cache(str(tmp_path / "x")) is None


# ------------------------------------------------------------------ AOT files


def test_aot_serialize_deserialize_round_trip(tmp_path):
    f = jax.jit(lambda x, y: {"out": x @ y + 1.0})
    x = jnp.ones((8, 8))
    compiled = compilation.aot_compile(f, x, x)
    path = str(tmp_path / "f.jaxexec")
    compilation.save_executable(path, compiled)
    loaded = compilation.load_executable(path)
    assert loaded is not None
    np.testing.assert_allclose(loaded(x, x)["out"], compiled(x, x)["out"])


def test_load_executable_rejects_mismatch_and_corruption(tmp_path):
    f = jax.jit(lambda x: x * 2)
    x = jnp.ones((4,))
    path = str(tmp_path / "f.jaxexec")
    compilation.save_executable(
        path, compilation.aot_compile(f, x), fingerprint="not-this-topology")
    assert compilation.load_executable(path) is None  # fingerprint mismatch
    with open(path, "wb") as fh:
        fh.write(b"garbage")
    assert compilation.load_executable(path) is None  # corrupt
    assert compilation.load_executable(str(tmp_path / "missing")) is None


def test_load_or_compile_falls_back_then_reuses(tmp_path):
    f = jax.jit(lambda x: jnp.sum(x * 3))
    x = jnp.arange(16.0)
    reg = get_registry()
    c0 = reg.value("fdtpu_aot_compiles_total")
    l0 = reg.value("fdtpu_aot_loads_total")
    a = compilation.load_or_compile(f, (x,), directory=str(tmp_path), name="s")
    assert reg.value("fdtpu_aot_compiles_total") == c0 + 1
    b = compilation.load_or_compile(f, (x,), directory=str(tmp_path), name="s")
    assert reg.value("fdtpu_aot_loads_total") == l0 + 1
    assert float(a(x)) == float(b(x)) == float(jnp.sum(x * 3))
    # stamp the on-disk file with a foreign fingerprint: next call must
    # fall back to a fresh compile AND re-serialize for this topology
    fp = compilation.topology_fingerprint()
    sig = compilation.abstract_signature((x,))
    path = tmp_path / f"s-{fp}-{sig}{compilation.AOT_SUFFIX}"
    compilation.save_executable(
        str(path), compilation.aot_compile(f, x), fingerprint="stale")
    c1 = reg.value("fdtpu_aot_compiles_total")
    compilation.load_or_compile(f, (x,), directory=str(tmp_path), name="s")
    assert reg.value("fdtpu_aot_compiles_total") == c1 + 1
    l1 = reg.value("fdtpu_aot_loads_total")
    compilation.load_or_compile(f, (x,), directory=str(tmp_path), name="s")
    assert reg.value("fdtpu_aot_loads_total") == l1 + 1  # rewritten, loads now
    # a different argument signature selects a different file
    c2 = reg.value("fdtpu_aot_compiles_total")
    compilation.load_or_compile(
        f, (jnp.arange(8.0),), directory=str(tmp_path), name="s")
    assert reg.value("fdtpu_aot_compiles_total") == c2 + 1


def test_aot_compile_requires_jitted_callable():
    with pytest.raises(ValueError, match="lower"):
        compilation.aot_compile(lambda x: x, 1.0)


def test_callable_tag_sees_hyperparameters_not_addresses():
    """Two optimizers differing ONLY in a closed-over hyperparameter
    (identical program shapes) must tag differently; the same
    configuration must tag identically (no memory addresses)."""
    from fluxdistributed_tpu import optim

    a = compilation.callable_tag(optim.momentum(0.1, 0.9).update)
    b = compilation.callable_tag(optim.momentum(0.01, 0.9).update)
    c = compilation.callable_tag(optim.momentum(0.1, 0.9).update)
    assert a != b and a == c
    assert "0x" not in a  # address-free — stable across processes
    # schedules one level down are visible too
    sched = compilation.callable_tag(
        optim.momentum(optim.warmup_cosine(0.1, 5, 100)).update)
    assert sched != a


def test_config_tag_scrubs_addresses_and_digests_callables():
    """config_tag is THE AOT key builder: reprs carrying memory
    addresses (a model whose attn_fn prints '<function ... at 0x..>')
    must hash identically across processes, and two processes' different
    addresses must not change the key."""
    a = compilation.config_tag("attn_fn=<function core at 0x7f01>", 8)
    b = compilation.config_tag("attn_fn=<function core at 0x9e22>", 8)
    assert a == b and len(a) == 12
    assert compilation.config_tag("x", 8) != compilation.config_tag("x", 16)
    from fluxdistributed_tpu import optim

    assert (compilation.config_tag(optim.momentum(0.1).update)
            != compilation.config_tag(optim.momentum(0.2).update))


def test_prepare_training_aot_distinguishes_optimizers(tmp_path):
    """A changed learning rate must NOT load the previous run's
    serialized train step (the hyperparameter is a baked-in constant)."""
    from fluxdistributed_tpu import optim
    from fluxdistributed_tpu.data import SyntheticDataset
    from fluxdistributed_tpu.models import SimpleCNN
    from fluxdistributed_tpu.train import prepare_training

    def prep(opt):
        ds = SyntheticDataset(nsamples=64, nclasses=4, shape=(16, 16, 3))
        return prepare_training(SimpleCNN(num_classes=4), ds, opt,
                                batch_size=16, cycles=1, aot=str(tmp_path))

    reg = get_registry()
    c0 = reg.value("fdtpu_aot_compiles_total")
    prep(optim.momentum(0.1, 0.9))
    prep(optim.momentum(0.01, 0.9))  # different lr → different file
    assert reg.value("fdtpu_aot_compiles_total") == c0 + 2
    assert len(os.listdir(tmp_path)) == 2


# ------------------------------------------------------------- engine prewarm


def _tiny_lm():
    from fluxdistributed_tpu.models import lm_tiny

    model = lm_tiny(vocab=32, depth=2, dim=64, mlp_dim=128,
                    dtype=jnp.float32)
    params = model.init(
        jax.random.PRNGKey(0), np.zeros((1, 2), np.int32), train=False
    )["params"]
    return model, params


def _serve_all(engine, prompts, new=6):
    from fluxdistributed_tpu.serve import Request, Scheduler

    sched = Scheduler(engine, max_queue=16)
    reqs = [Request(prompt=p, max_new_tokens=new) for p in prompts]
    sched.generate_all(reqs)
    return [r.tokens for r in reqs]


def _ref_tokens(model, params, prompt, new):
    from fluxdistributed_tpu.models import generate

    dm = model.clone(decode=True)
    out = generate(dm, params, np.asarray([prompt], np.int32),
                   total_len=len(prompt) + new)
    return list(np.asarray(out)[0])


def test_engine_prewarm_prepays_every_compile():
    """prewarm=True compiles each bucket's prefill, the splice and the
    decode step BEFORE traffic; serving then adds zero compiles and
    keeps token-for-token parity — the ONE-decode-compile invariant
    with the compile moved ahead of the first request."""
    model, params = _tiny_lm()
    from fluxdistributed_tpu.serve import LMEngine

    engine = LMEngine(model, params, max_slots=3, max_len=32,
                      buckets=(4, 8), prewarm=True)
    warm = engine.compile_stats()
    if warm["decode_compiles"] < 0:
        pytest.skip("this jax cannot report jit cache sizes")
    assert warm["decode_compiles"] == 1
    assert warm["insert_compiles"] == 1
    assert warm["prefill_compiles"] == len(engine.buckets)
    prompts = [[1, 2, 3], [5, 6], [7, 1, 2, 3, 4]]
    got = _serve_all(engine, prompts)
    assert engine.compile_stats() == warm, "traffic recompiled a program"
    for tokens, p in zip(got, prompts):
        assert tokens == _ref_tokens(model, params, p, 6)


def test_engine_aot_pool_round_trip(tmp_path):
    """aot_dir engines serve through deserialized executables: engine 2
    loads engine 1's serialized pool (counted in the registry) and
    produces identical tokens."""
    model, params = _tiny_lm()
    from fluxdistributed_tpu.serve import LMEngine

    reg = get_registry()
    c0 = reg.value("fdtpu_aot_compiles_total")
    e1 = LMEngine(model, params, max_slots=2, max_len=32,
                  buckets=(4,), aot_dir=str(tmp_path))
    n_programs = len(e1._aot)
    assert n_programs == 5  # insert, step, sample1, prefill x {4, 32}
    assert reg.value("fdtpu_aot_compiles_total") == c0 + n_programs
    l0 = reg.value("fdtpu_aot_loads_total")
    e2 = LMEngine(model, params, max_slots=2, max_len=32,
                  buckets=(4,), aot_dir=str(tmp_path))
    assert reg.value("fdtpu_aot_loads_total") == l0 + n_programs
    assert e2.compile_stats()["aot_programs"] == n_programs
    prompts = [[1, 2], [3, 1, 4]]
    assert _serve_all(e1, prompts) == _serve_all(e2, prompts)
    for tokens, p in zip(_serve_all(e2, prompts), prompts):
        assert tokens == _ref_tokens(model, params, p, 6)


# ------------------------------------------------------------- trainer wiring


def _prepare(**kw):
    from fluxdistributed_tpu import optim
    from fluxdistributed_tpu.data import SyntheticDataset
    from fluxdistributed_tpu.models import SimpleCNN
    from fluxdistributed_tpu.train import prepare_training

    dataset = SyntheticDataset(nsamples=64, nclasses=4, shape=(16, 16, 3))
    return prepare_training(
        SimpleCNN(num_classes=4), dataset, optim.momentum(0.1, 0.9),
        batch_size=16, cycles=2, **kw)


def test_prepare_training_aot_compiles_then_loads(tmp_path):
    reg = get_registry()
    c0 = reg.value("fdtpu_aot_compiles_total")
    task = _prepare(aot=str(tmp_path))
    assert reg.value("fdtpu_aot_compiles_total") == c0 + 1
    files = [f for f in os.listdir(tmp_path) if f.startswith("train_step-")]
    assert len(files) == 1
    # the AOT step trains: run the loop end to end
    from fluxdistributed_tpu.train import train
    from fluxdistributed_tpu.train.logging import NullLogger

    params, _, task = train(task, print_every=0, eval_every=0,
                            logger=NullLogger())
    assert int(task.state.step) == 2
    # a second prepare with identical config LOADS the executable
    l0 = reg.value("fdtpu_aot_loads_total")
    task2 = _prepare(aot=str(tmp_path))
    assert reg.value("fdtpu_aot_loads_total") == l0 + 1
    state2, m = task2.step_fn(task2.state, task2.val_batch or _first_batch(task2))
    assert np.isfinite(float(m["loss"]))


def _first_batch(task):
    it = iter(task.loader)
    return next(it)


def test_prepare_training_warmup_leaves_state_pristine():
    """warmup=True pre-pays the step compile on donated zero dummies:
    the returned task's real state is bit-untouched (step counter still
    0) and the first train step reuses the warmed compile."""
    from fluxdistributed_tpu.obs import jaxmon

    task = _prepare(warmup=True)
    assert int(task.state.step) == 0
    c0 = jaxmon.compile_count()
    batch = _first_batch(task)
    state, m = task.step_fn(task.state, batch)
    assert int(state.step) == 1 and np.isfinite(float(m["loss"]))
    assert jaxmon.compile_count() == c0, "first real step recompiled"


def test_prepare_training_cache_dir_enables_cache(tmp_path,
                                                  restore_cache_config):
    task = _prepare(cache_dir=str(tmp_path / "cc"))
    resolved = compilation.persistent_cache_dir()
    assert resolved and resolved.startswith(str(tmp_path / "cc"))
    assert jax.config.jax_compilation_cache_dir == resolved
    # the prepare-time compiles (model init) already populated it
    batch = _first_batch(task)
    task.step_fn(task.state, batch)
    assert os.listdir(resolved), "no cache entries written"


# ------------------------------------------------- cross-process cache reuse

_CHILD = r"""
import json, sys
import jax, jax.numpy as jnp
from fluxdistributed_tpu import compilation

resolved = compilation.enable_persistent_cache(sys.argv[1])
assert resolved, "cache must enable on this jax"

@jax.jit
def program(x, y):
    z = jnp.tanh(x @ y)
    return jnp.sum(z * z, axis=0)

x = jnp.ones((64, 64)); y = jnp.ones((64, 64))
jax.block_until_ready(program(x, y))
jax.block_until_ready(jax.jit(lambda a: jnp.cumsum(a, axis=1) / 7)(x))
print("METRICS " + json.dumps(compilation.compile_metrics()))
"""


@pytest.mark.slow
def test_second_process_zero_cache_misses(tmp_path):
    """THE acceptance demonstration: run 2 against run 1's persistent
    cache performs zero new XLA compiles — every compile request is a
    cache hit (``cache_misses == 0`` via the jaxmon counters; the raw
    compile-event counter fires on hits too on this jax, which is why
    misses are the honest signal)."""
    cache = str(tmp_path / "cc")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env.pop("XLA_FLAGS", None)  # plain 1-device CPU children
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")

    def run():
        p = subprocess.run(
            [sys.executable, "-c", _CHILD, cache],
            capture_output=True, text=True, timeout=300, env=env, cwd=REPO,
        )
        assert p.returncode == 0, p.stderr[-3000:]
        line = [l for l in p.stdout.splitlines() if l.startswith("METRICS ")][-1]
        return json.loads(line[len("METRICS "):])

    first = run()
    assert first["cache_misses"] > 0, first   # cold: everything compiles
    assert first["cache_hits"] == 0, first
    second = run()
    assert second["cache_misses"] == 0, second  # warm: zero new compiles
    assert second["cache_hits"] == first["cache_misses"], second
    assert second["compile_seconds_saved"] >= 0.0
