"""Data-layer tests: ImageNet metadata parsing, preprocessing, the
dataset registry and the CIFAR-10 binary loader — against generated
fixtures (the reference stores no data fixtures either, SURVEY §4)."""

import os

import numpy as np
import pytest

from fluxdistributed_tpu.data import (
    CIFAR10Dataset,
    ImageNetDataset,
    SyntheticDataset,
    labels,
    makepaths,
    minibatch,
    open_dataset,
    register_dataset,
    train_solutions,
)
from fluxdistributed_tpu.data.preprocess import (
    IMAGENET_MEAN,
    IMAGENET_STD,
    center_crop,
    decode_image,
    preprocess,
    resize_smallest_dimension,
)
from fluxdistributed_tpu.data.registry import load_registry

WNIDS = ["n01440764", "n01443537", "n01484850"]


@pytest.fixture(scope="module")
def imagenet_root(tmp_path_factory):
    """A miniature ILSVRC tree: synset mapping, train solution CSV, and
    real JPEG files (generated with PIL)."""
    from PIL import Image

    root = tmp_path_factory.mktemp("imagenet")
    with open(root / "LOC_synset_mapping.txt", "w") as f:
        f.write("n01440764 tench, Tinca tinca\n")
        f.write("n01443537 goldfish, Carassius auratus\n")
        f.write("n01484850 great white shark, white shark\n")
    rows = ["ImageId,PredictionString"]
    rng = np.random.default_rng(0)
    for wnid in WNIDS:
        d = root / "ILSVRC" / "Data" / "CLS-LOC" / "train" / wnid
        d.mkdir(parents=True)
        for i in range(3):
            image_id = f"{wnid}_{i}"
            arr = rng.integers(0, 255, (80, 100, 3), dtype=np.uint8)
            Image.fromarray(arr).save(d / f"{image_id}.JPEG")
            rows.append(f"{image_id},{wnid} 1 2 3 4 {wnid} 5 6 7 8")
    with open(root / "LOC_train_solution.csv", "w") as f:
        f.write("\n".join(rows) + "\n")
    return str(root)


def test_labels_parse(imagenet_root):
    lt = labels(os.path.join(imagenet_root, "LOC_synset_mapping.txt"))
    assert len(lt) == 3
    assert lt.wnids == WNIDS
    assert lt.names[0].startswith("tench")
    assert lt.class_idx["n01443537"] == 1


def test_train_solutions_parse_and_filter(imagenet_root):
    lt = labels(os.path.join(imagenet_root, "LOC_synset_mapping.txt"))
    csv = os.path.join(imagenet_root, "LOC_train_solution.csv")
    table = train_solutions(csv, lt)
    assert len(table) == 9
    # class filter, as the reference filters to requested classes
    sub = train_solutions(csv, lt, classes=["n01484850"])
    assert len(sub) == 3
    assert set(sub.class_idx.tolist()) == {2}


def test_sample_table_shard(imagenet_root):
    lt = labels(os.path.join(imagenet_root, "LOC_synset_mapping.txt"))
    table = train_solutions(os.path.join(imagenet_root, "LOC_train_solution.csv"), lt)
    shards = [table.shard(i, 4) for i in range(4)]
    assert sum(len(s) for s in shards) == len(table)


def test_makepaths_layout():
    p = makepaths("n01440764_42", "/data", "train")
    assert p == "/data/ILSVRC/Data/CLS-LOC/train/n01440764/n01440764_42.JPEG"
    v = makepaths("ILSVRC2012_val_00000001", "/data", "val")
    assert v.endswith("CLS-LOC/val/ILSVRC2012_val_00000001.JPEG")


def test_preprocess_pipeline_stats(imagenet_root):
    path = makepaths(f"{WNIDS[0]}_0", imagenet_root, "train")
    img = decode_image(path)
    assert img.dtype == np.uint8 and img.shape == (80, 100, 3)
    r = resize_smallest_dimension(img, 64)
    assert min(r.shape[:2]) == 64
    c = center_crop(r, 48)
    assert c.shape == (48, 48, 3)
    x = preprocess(path, crop=64, resize=72)
    assert x.shape == (64, 64, 3) and x.dtype == np.float32
    # uniform-random pixels: after (x-mu)/sigma the mean should sit near
    # (0.5 - mean)/std per channel
    expect = ((0.5 - IMAGENET_MEAN) / IMAGENET_STD)
    assert np.allclose(x.mean(axis=(0, 1)), expect, atol=0.3)
    # compat mode reproduces the reference's per-image standardization
    q = preprocess(path, crop=64, resize=72, compat_double_normalize=True)
    assert abs(float(q.mean())) < 1e-3 and abs(float(q.std()) - 1.0) < 1e-2


def test_imagenet_dataset_batch(imagenet_root):
    lt = labels(os.path.join(imagenet_root, "LOC_synset_mapping.txt"))
    table = train_solutions(os.path.join(imagenet_root, "LOC_train_solution.csv"), lt)
    ds = ImageNetDataset(imagenet_root, table, nclasses=3, crop=32, resize=40)
    imgs, y = ds.batch(np.random.default_rng(0), 8)
    assert imgs.shape == (8, 32, 32, 3) and y.shape == (8,)
    assert set(y.tolist()) <= {0, 1, 2}
    # exported minibatch analog gives one-hot labels
    mi, my = minibatch(ds, 4, np.random.default_rng(1))
    assert my.shape == (4, 3) and np.allclose(my.sum(axis=1), 1.0)


def test_registry_toml_and_overrides(imagenet_root, tmp_path):
    toml = tmp_path / "datasets.toml"
    toml.write_text(
        f"""
[[datasets]]
name = "imagenet_local"
driver = "imagenet"
path = "{imagenet_root}"
crop = 32
resize = 40

[[datasets]]
name = "fake"
driver = "synthetic"
nsamples = 64
nclasses = 5
shape = [8, 8, 3]
"""
    )
    load_registry(str(toml))
    ds = open_dataset("imagenet_local")
    assert isinstance(ds, ImageNetDataset) and ds.crop == 32
    fake = open_dataset("fake")
    assert isinstance(fake, SyntheticDataset) and fake.nclasses == 5
    with pytest.raises(KeyError, match="not registered"):
        open_dataset("nope")
    register_dataset("fake2", "synthetic", nsamples=16)
    assert len(open_dataset("fake2")) == 16
    with pytest.raises(ValueError, match="unknown driver"):
        register_dataset("bad", "imaginary")


def test_cifar10_binary_loader(tmp_path):
    # forge two records of the binary format: 1 label byte + 3072 CHW bytes
    rng = np.random.default_rng(0)
    base = tmp_path / "cifar-10-batches-bin"
    base.mkdir()
    for fname in [f"data_batch_{i}.bin" for i in range(1, 6)] + ["test_batch.bin"]:
        recs = []
        for lbl in (3, 7):
            recs.append(np.concatenate([[lbl], rng.integers(0, 255, 3072)]).astype(np.uint8))
        np.stack(recs).tofile(base / fname)
    ds = CIFAR10Dataset(str(tmp_path))
    assert len(ds) == 10  # 5 files x 2 records
    imgs, y = ds.batch(np.random.default_rng(1), 4)
    assert imgs.shape == (4, 32, 32, 3)
    assert set(y.tolist()) <= {3, 7}
    test = CIFAR10Dataset(str(tmp_path), split="test")
    assert len(test) == 2
    with pytest.raises(FileNotFoundError, match="binary"):
        CIFAR10Dataset(str(tmp_path / "missing"))


def test_registry_split_and_augment_keys(imagenet_root):
    """The registry plumbs split/augment through to ImageNetDataset:
    split selects the solution CSV + file layout and augment overrides
    the per-split default."""
    from fluxdistributed_tpu.data.registry import register_dataset

    register_dataset("inet_train", "imagenet", path=imagenet_root, crop=32, resize=40)
    ds = open_dataset("inet_train")
    assert ds.table.split == "train" and ds.augment is True
    ds2 = open_dataset("inet_train", augment=False)
    assert ds2.augment is False
    # a val registration reuses the same CSV via solution_csv but stamps
    # the val split → augment defaults off
    register_dataset(
        "inet_val", "imagenet", path=imagenet_root, split="val",
        solution_csv=os.path.join(imagenet_root, "LOC_train_solution.csv"),
        crop=32, resize=40,
    )
    dv = open_dataset("inet_val")
    assert dv.table.split == "val" and dv.augment is False


def test_byte_text_dataset(tmp_path):
    """Windows are exact byte slices; len counts non-overlapping windows;
    the registry's text driver opens it; decode round-trips."""
    from fluxdistributed_tpu.data import ByteTextDataset
    from fluxdistributed_tpu.data.registry import register_dataset

    corpus = (b"the quick brown fox jumps over the lazy dog. " * 50)
    p = tmp_path / "corpus.txt"
    p.write_bytes(corpus)

    ds = ByteTextDataset(str(p), seqlen=16)
    assert ds.vocab == 256
    assert len(ds) == len(corpus) // 16
    rng = np.random.default_rng(0)
    toks = ds.batch(rng, 8)
    assert toks.shape == (8, 16) and toks.dtype == np.int32
    # every window is a literal slice of the file
    blob = corpus
    for row in toks:
        assert bytes(row.astype(np.uint8)) in blob
    assert ByteTextDataset.decode(np.frombuffer(b"fox", np.uint8)) == "fox"

    register_dataset("corpus", "text", path=str(p), seqlen=16)
    ds2 = open_dataset("corpus")
    assert ds2.seqlen == 16 and len(ds2) == len(ds)

    with pytest.raises(ValueError, match="seqlen"):
        small = tmp_path / "small.txt"
        small.write_bytes(b"xy")
        ByteTextDataset(str(small), seqlen=16)


def test_byte_text_dataset_boundary(tmp_path):
    """A file of exactly seqlen bytes is one valid window, and the final
    byte of any corpus is reachable (window starts have an inclusive
    upper bound of len - seqlen)."""
    from fluxdistributed_tpu.data import ByteTextDataset

    exact = tmp_path / "exact.txt"
    exact.write_bytes(b"0123456789abcdef")  # exactly 16 bytes
    ds = ByteTextDataset(str(exact), seqlen=16)
    toks = ds.batch(np.random.default_rng(0), 4)
    assert (toks == np.frombuffer(b"0123456789abcdef", np.uint8)).all()

    tail = tmp_path / "tail.txt"
    tail.write_bytes(b"aaaaaaaaZ")  # 9 bytes, seqlen 8: starts in {0, 1}
    ds = ByteTextDataset(str(tail), seqlen=8)
    toks = ds.batch(np.random.default_rng(0), 256)
    assert (toks[:, -1] == ord("Z")).any(), "final corpus byte never sampled"
