"""Zero-bubble pipeline schedule + planner execution: parity and wiring.

The acceptance bar: ``schedule="zb"`` matches ``pp_1f1b`` loss/grads
BIT-FOR-BIT on the 8-virtual-device CPU mesh (the B and W ticks re-run
the same vjp on the same operands the joint backward used, so equality
is exact, not approximate), the planner's non-uniform boundaries
execute through the padded chunk scan at plain-model gradient parity,
and both ride ``prepare_training``/``bin/driver.py`` end-to-end at ONE
compile per schedule.

Fast tier carries the toy-model bit-parity core plus every validation
path; the LM-level matrices and the driver subprocess e2e live in the
slow tier (compile-heavy).
"""

import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fluxdistributed_tpu import mesh as mesh_lib, optim
from fluxdistributed_tpu.parallel.pp import stack_stage_params
from fluxdistributed_tpu.parallel.pp_1f1b import pipeline_grads_1f1b
from fluxdistributed_tpu.parallel.pp_plan import plan_stages

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

S = 4
D = 12
DIN = 6
NCLS = 5


@pytest.fixture(scope="module")
def mesh():
    return mesh_lib.make_mesh({"pipe": S})


def stage_fn(params, x):
    return x + jax.nn.gelu(x @ params["w"] + params["b"])


def embed_fn(outer, xin):
    return jnp.tanh(xin @ outer["w_in"])


def head_fn(outer, y, labels):
    logits = y @ outer["w_out"]
    logp = jax.nn.log_softmax(logits)
    return -jnp.mean(jnp.sum(labels * logp, axis=-1))


def _toy(key, v=1):
    ks = jax.random.split(key, 2 + v * S)
    outer = {
        "w_in": jax.random.normal(ks[0], (DIN, D), jnp.float32) * 0.4,
        "w_out": jax.random.normal(ks[1], (D, NCLS), jnp.float32) * 0.4,
    }
    logical = [
        {"w": jax.random.normal(k, (D, D), jnp.float32) * 0.3,
         "b": jnp.zeros((D,), jnp.float32)}
        for k in ks[2:]
    ]
    return outer, logical


def _bitwise_equal(a_tree, b_tree):
    for a, b in zip(jax.tree.leaves(a_tree), jax.tree.leaves(b_tree)):
        if np.asarray(a).tobytes() != np.asarray(b).tobytes():
            return False
    return True


def test_zb_bit_parity_toy(mesh):
    """The acceptance core: loss, stage grads, and outer grads from the
    zb timetable are byte-identical to the 1F1B ones."""
    outer, per_stage = _toy(jax.random.PRNGKey(0))
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(0, 1, (12, DIN)).astype(np.float32))
    labels = jnp.asarray(
        np.eye(NCLS, dtype=np.float32)[rng.integers(0, NCLS, 12)])
    stacked = stack_stage_params(per_stage, mesh)

    outs = {}
    for sched in ("1f1b", "zb"):
        run = pipeline_grads_1f1b(
            stage_fn, embed_fn, head_fn, mesh, num_microbatches=6,
            schedule=sched)
        outs[sched] = jax.jit(run)(stacked, outer, x, labels)
    l1, gs1, go1 = outs["1f1b"]
    lz, gsz, goz = outs["zb"]
    assert np.asarray(l1).tobytes() == np.asarray(lz).tobytes()
    assert _bitwise_equal(gs1, gsz)
    assert _bitwise_equal(go1, goz)


def test_schedule_validation():
    from fluxdistributed_tpu.parallel.pp_1f1b import build_schedule

    with pytest.raises(ValueError, match="unknown pipeline schedule"):
        build_schedule(4, 4, schedule="eager")


def test_trainer_validation_surface():
    """pipeline_schedule / pp_plan / hoisted microbatch checks all fire
    BEFORE any pipeline-specific model wiring."""
    from fluxdistributed_tpu.data import SyntheticTextDataset
    from fluxdistributed_tpu.models import SimpleCNN
    from fluxdistributed_tpu.train import prepare_training

    ds = SyntheticTextDataset(vocab=16, seqlen=8)
    cnn = SimpleCNN(num_classes=4)
    # hoisted ordering: an invalid microbatch count reports AS ITSELF,
    # not as a downstream model-type error, for every pipeline mode
    for spmd in ("pp", "pp_1f1b"):
        with pytest.raises(ValueError, match="must be >= 1"):
            prepare_training(
                cnn, ds, optim.adam(1e-3), batch_size=8, spmd=spmd,
                num_microbatches=0, input_shape=(8, 8, 3))
    with pytest.raises(ValueError, match="unknown pipeline_schedule"):
        prepare_training(
            cnn, ds, optim.adam(1e-3), batch_size=8, spmd="pp_1f1b",
            pipeline_schedule="eager", input_shape=(8, 8, 3))
    with pytest.raises(ValueError, match="requires spmd='pp_1f1b'"):
        prepare_training(
            cnn, ds, optim.adam(1e-3), batch_size=8, spmd="jit",
            pipeline_schedule="zb", input_shape=(8, 8, 3))
    with pytest.raises(ValueError, match="pp_plan requires"):
        prepare_training(
            cnn, ds, optim.adam(1e-3), batch_size=8, spmd="jit",
            pp_plan=plan_stages([1.0] * 4, 2, 2),
            input_shape=(8, 8, 3))
    with pytest.raises(ValueError, match="pipeline_interleave"):
        prepare_training(
            cnn, ds, optim.adam(1e-3), batch_size=8, spmd="pp_1f1b",
            pp_plan=plan_stages([1.0] * 4, 2, 2), pipeline_interleave=True,
            input_shape=(8, 8, 3))


def test_lm_boundaries_validation(mesh):
    from fluxdistributed_tpu.models.transformer_lm import (
        TransformerLM, lm_pp, lm_pp_1f1b,
    )

    model = TransformerLM(
        vocab=16, dim=16, depth=8, num_heads=2, mlp_dim=32,
        dtype=jnp.float32, dropout=0.0)
    with pytest.raises(ValueError, match="S\\+1"):
        lm_pp(model, mesh, boundaries=(0, 4, 8))
    with pytest.raises(ValueError, match="span the whole stack"):
        lm_pp(model, mesh, boundaries=(0, 2, 4, 6, 7))
    with pytest.raises(ValueError, match=">= 1 block"):
        lm_pp(model, mesh, boundaries=(0, 4, 4, 6, 8))
    with pytest.raises(ValueError, match="interleave"):
        lm_pp_1f1b(model, mesh, interleave=True,
                   boundaries=(0, 2, 4, 6, 8))
    # a non-divisible depth WITHOUT a plan names the pp-plan escape hatch
    odd = TransformerLM(
        vocab=16, dim=16, depth=6, num_heads=2, mlp_dim=32,
        dtype=jnp.float32, dropout=0.0)
    with pytest.raises(ValueError, match="pp plan"):
        lm_pp(odd, mesh)


def test_trainer_plan_mismatch_rejected():
    from fluxdistributed_tpu.data import SyntheticTextDataset
    from fluxdistributed_tpu.models.transformer_lm import TransformerLM
    from fluxdistributed_tpu.train import prepare_training

    mesh2 = mesh_lib.make_mesh({"data": 2, "pipe": 4})
    ds = SyntheticTextDataset(vocab=16, seqlen=8)
    model = TransformerLM(
        vocab=16, dim=16, depth=8, num_heads=2, mlp_dim=32,
        dtype=jnp.float32, dropout=0.0)
    with pytest.raises(ValueError, match="re-plan for this mesh"):
        prepare_training(
            model, ds, optim.adam(1e-3), mesh=mesh2, batch_size=16,
            spmd="pp_1f1b", num_microbatches=4, topk=(),
            pp_plan=plan_stages([1.0] * 8, 2, 4))
    with pytest.raises(ValueError, match="re-plan for this model"):
        prepare_training(
            model, ds, optim.adam(1e-3), mesh=mesh2, batch_size=16,
            spmd="pp_1f1b", num_microbatches=4, topk=(),
            pp_plan=plan_stages([1.0] * 12, 4, 4))


def test_trainer_planned_zb_e2e():
    """prepare_training(pp_plan=..., pipeline_schedule="zb") on a
    non-divisible depth (6 over 4 pipe devices): trains through the
    full trainer surface at ONE compile, and the GPipe eval reads the
    same planned split tree."""
    from fluxdistributed_tpu.data import SyntheticTextDataset
    from fluxdistributed_tpu.models.transformer_lm import TransformerLM
    from fluxdistributed_tpu.train import prepare_training

    mesh2 = mesh_lib.make_mesh({"data": 2, "pipe": 4})
    ds = SyntheticTextDataset(vocab=32, seqlen=16, peak=0.95)
    model = TransformerLM(
        vocab=32, dim=32, depth=6, num_heads=2, mlp_dim=64,
        dtype=jnp.float32, dropout=0.0)
    plan = plan_stages([1.0] * 6, 4, 4, outer=(1.0, 1.0))
    assert plan.counts == (1, 2, 2, 1)  # genuinely non-uniform
    task = prepare_training(
        model, ds, optim.adam(3e-3), mesh=mesh2, batch_size=16,
        cycles=8, topk=(), spmd="pp_1f1b", num_microbatches=4,
        pp_plan=plan, pipeline_schedule="zb",
        val_dataset=ds, val_samples=8)
    losses = []
    for batch in task.loader:
        task.state, m = task.step_fn(task.state, batch)
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0], losses
    assert int(task.state.step) == 8
    # ONE compile per schedule: the jit cache holds exactly one entry
    assert task.step_fn._cache_size() == 1
    loss, _ = task.eval_fn(task.state, task.val_batch)
    assert np.isfinite(float(loss))


# ---- slow tier: LM matrices + driver subprocess ----

@pytest.mark.slow
@pytest.mark.parametrize("m,v,bounds", [
    (2, 1, None),            # M < S drain-heavy shape
    (8, 1, None),
    (8, 2, None),            # interleaved chunks
    (4, 1, (0, 1, 3, 5, 6)),  # planned non-uniform split (depth 6)
])
def test_lm_zb_bit_parity_matrix(mesh, m, v, bounds):
    """LM-level zb-vs-1f1b bit parity: real DecoderBlocks, tied
    embeddings, chunked/planned splits."""
    from fluxdistributed_tpu.models.transformer_lm import (
        TransformerLM, lm_pp_1f1b,
    )

    if bounds is not None:
        depth = bounds[-1]
        interleave = False
    else:
        depth = v * S
        interleave = v > 1
    model = TransformerLM(
        vocab=64, dim=32, depth=depth, num_heads=2, mlp_dim=64,
        dtype=jnp.float32, dropout=0.0)
    rng = np.random.default_rng(5)
    toks = jnp.asarray(rng.integers(0, 64, (8, 16)).astype(np.int32))
    params = model.init(jax.random.PRNGKey(0), toks[:1], train=False)["params"]
    w = lm_pp_1f1b(model, mesh, interleave=interleave, boundaries=bounds)
    sp = w.split_params(params)
    outs = {}
    for sched in ("1f1b", "zb"):
        run = pipeline_grads_1f1b(
            *w.fns, mesh, num_microbatches=m, interleave=w.interleave,
            schedule=sched)
        outs[sched] = jax.jit(run)(sp["stages"], sp["outer"], toks, toks)
    (l1, gs1, go1), (lz, gsz, goz) = outs["1f1b"], outs["zb"]
    assert np.asarray(l1).tobytes() == np.asarray(lz).tobytes()
    assert _bitwise_equal(gs1, gsz) and _bitwise_equal(go1, goz)


@pytest.mark.slow
def test_driver_pp_plan_zb_e2e(tmp_path):
    """bin/driver.py --pp-plan auto --pp-schedule zb end-to-end, then a
    second run consuming the FIRST run's profile artifact as the plan
    source (the artifact -> plan -> run workflow)."""
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    prof = str(tmp_path / "prof.json")
    base = [
        sys.executable, os.path.join("bin", "driver.py"),
        "--model", "lm_tiny", "--dataset", "synthetic-text",
        "--batch-size", "8", "--seqlen", "32", "--cycles", "3",
        "--print-every", "0", "--eval-every", "0",
        "--platform", "cpu", "--local-devices", "4",
        "--spmd", "pp_1f1b", "--pipe", "4", "--microbatches", "4",
        "--pp-schedule", "zb",
    ]
    p = subprocess.run(
        base + ["--pp-plan", "auto", "--profile-out", prof],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    assert p.returncode == 0, p.stderr[-1500:]
    assert "pp plan: S=4" in p.stdout and "done: 3 steps" in p.stdout
    # second run plans FROM the artifact (fingerprint-gated)
    p2 = subprocess.run(
        base + ["--pp-plan", prof],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    assert p2.returncode == 0, p2.stderr[-1500:]
    assert "pp plan: S=4" in p2.stdout and "done: 3 steps" in p2.stdout
