"""Image decode + preprocessing for ImageNet-style training.

Replaces the reference's preprocessing stack (src/preprocess.jl):
``resize_smallest_dimension`` 256 with a Gaussian lowpass when
downscaling (:30-42), ``center_crop`` 224 (:45-49), mean/std ImageNet
normalization and CHW→WHCN permute (:51-67).  Here decode and resize run
on host CPU via PIL (JPEG decode stays host-side on TPU too — SURVEY §2
native-dep table), arrays are NHWC float32, and the device copy happens
in the prefetch loader.

**The double-normalize quirk.**  The reference multiplies the normalized
image by 255 (src/preprocess.jl:66) and then ``fproc`` re-standardizes
each image with ``Flux.normalise`` (src/imagenet.jl:34), so the de-facto
training distribution is per-image zero-mean/unit-var — the ImageNet
mean/std wash out.  The clean behavior (resize → crop → (x-μ)/σ) is the
default here; ``compat_double_normalize=True`` reproduces the
reference's exact pipeline for parity testing.
"""

from __future__ import annotations

import io
from typing import Sequence

import numpy as np

__all__ = [
    "IMAGENET_MEAN",
    "IMAGENET_STD",
    "decode_image",
    "resize_smallest_dimension",
    "center_crop",
    "preprocess",
]

# Reference constants, src/preprocess.jl:51-53
IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


def decode_image(src) -> np.ndarray:
    """JPEG/PNG bytes, path, or file-like → RGB uint8 HWC array.

    The ``jpeg_decode`` analog (src/imagenet.jl:32, via libjpeg-turbo);
    PIL uses libjpeg on the host here.
    """
    from PIL import Image

    if isinstance(src, (bytes, bytearray)):
        src = io.BytesIO(src)
    img = Image.open(src)
    if img.mode != "RGB":
        img = img.convert("RGB")  # handles grayscale/CMYK ImageNet files
    return np.asarray(img, np.uint8)


def resize_smallest_dimension(img: np.ndarray, size: int = 256) -> np.ndarray:
    """Scale so the smallest side equals ``size`` (aspect preserved).

    The reference lowpass-filters with a Gaussian before downscaling
    (src/preprocess.jl:30-42, ``imfilter`` + ``imresize``); PIL's
    ``BILINEAR`` with ``reducing_gap`` performs the equivalent
    antialiased area reduction.
    """
    from PIL import Image

    h, w = img.shape[:2]
    scale = size / min(h, w)
    nh, nw = max(size, round(h * scale)), max(size, round(w * scale))
    pil = Image.fromarray(img)
    pil = pil.resize((nw, nh), Image.BILINEAR, reducing_gap=2.0)
    return np.asarray(pil, np.uint8)


def center_crop(img: np.ndarray, size: int = 224) -> np.ndarray:
    """Central ``size``×``size`` crop (src/preprocess.jl:45-49)."""
    h, w = img.shape[:2]
    top = (h - size) // 2
    left = (w - size) // 2
    return img[top : top + size, left : left + size]


def preprocess(
    img,
    crop: int = 224,
    resize: int = 256,
    mean: Sequence[float] = IMAGENET_MEAN,
    std: Sequence[float] = IMAGENET_STD,
    compat_double_normalize: bool = False,
) -> np.ndarray:
    """Full pipeline: decode (if needed) → resize → crop → normalize.

    Returns HWC float32 (NHWC once batched) — the TPU-native layout; the
    reference's WHCN permute (src/preprocess.jl:64-65) is a Julia
    memory-order artifact with no analog here.
    """
    if not isinstance(img, np.ndarray):
        img = decode_image(img)
    img = resize_smallest_dimension(img, resize)
    img = center_crop(img, crop)
    x = img.astype(np.float32) / 255.0
    x = (x - np.asarray(mean, np.float32)) / np.asarray(std, np.float32)
    if compat_double_normalize:
        # Reference quirk: .* 255 after normalizing (src/preprocess.jl:66)
        # then per-image standardization (Flux.normalise, src/imagenet.jl:34).
        x = x * 255.0
        x = (x - x.mean()) / (x.std() + 1e-5)
    return x
