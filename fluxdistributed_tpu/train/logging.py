"""Pluggable metric logging.

The reference logs through the Julia ``Logging`` stdlib: ``@info`` records
with key=value pairs for losses/accuracies (src/ddp_tasks.jl:136-139),
console ``println`` for cycle cadence (:186), and any ``AbstractLogger``
(e.g. ``WandbLogger``) can be swapped in by wrapping the call in
``with_logger`` (README.md:72-92; the Wandb glue is ``@require``-gated at
src/FluxDistributed.jl:22-24).

Here the same shape: a ``Logger`` protocol, a default ``ConsoleLogger``,
a ``with_logger`` context manager backed by a contextvar, and an optional
``WandbLogger`` that activates only if the ``wandb`` package is importable
(the ``@require`` analog).
"""

from __future__ import annotations

import contextlib
import contextvars
import sys
import time
from typing import Any, Mapping, Protocol

__all__ = [
    "Logger",
    "ConsoleLogger",
    "NullLogger",
    "WandbLogger",
    "with_logger",
    "current_logger",
]


class Logger(Protocol):
    def log(self, metrics: Mapping[str, Any], step: int) -> None: ...

    def info(self, msg: str) -> None: ...


def _fmt_value(v: Any) -> str:
    """One key=value cell, whatever the value is.

    Caller metrics dicts carry more than floats: numpy scalars, 0-d
    arrays, nested dicts (per-phase breakdowns), lists (per-microbatch
    losses), None.  Everything renders on ONE line — a metric record
    that wraps breaks `grep step=`-ability — and nothing raises (a
    logger that can crash the train loop is worse than no logger)."""
    if isinstance(v, float):
        return f"{v:.4f}"
    if isinstance(v, (bool, int, str)) or v is None:
        return str(v)
    if isinstance(v, Mapping):
        return "{" + ",".join(
            f"{k}:{_fmt_value(x)}" for k, x in v.items()) + "}"
    if isinstance(v, (list, tuple)):
        return "[" + ",".join(_fmt_value(x) for x in v) + "]"
    try:
        return f"{float(v):.4f}"  # numpy/jax scalars and 0-d arrays
    except (TypeError, ValueError):
        pass
    try:
        return " ".join(str(v).split())  # collapse multi-line reprs
    except Exception:  # noqa: BLE001 — even a broken __str__ must not
        return f"<unprintable {type(v).__name__}>"  # kill the loop


class ConsoleLogger:
    """``@info``-style key=value console records with wall-clock stamps."""

    def __init__(self, stream=None):
        self.stream = stream or sys.stdout
        self._t0 = time.time()

    def log(self, metrics: Mapping[str, Any], step: int) -> None:
        kv = " ".join(f"{k}={_fmt_value(v)}" for k, v in metrics.items())
        print(f"[info] t={time.time() - self._t0:8.1f}s step={step} {kv}", file=self.stream)

    def info(self, msg: str) -> None:
        print(msg, file=self.stream)


class NullLogger:
    def log(self, metrics: Mapping[str, Any], step: int) -> None:
        pass

    def info(self, msg: str) -> None:
        pass


class WandbLogger:
    """Weights & Biases sink, import-gated like the reference's Requires
    hook (src/FluxDistributed.jl:22-24).  Raises ImportError at
    construction if wandb isn't installed.

    ``config`` pushes the RUN CONFIGURATION (architecture, spmd mode,
    optimizer hyperparameters — whatever dict the driver assembles) at
    init, the reference's ``WandbLogger(...; config=...)`` behavior
    (src/loggers/wandb.jl:1): runs are comparable in the W&B UI by what
    they trained, not just by their metric curves.  ``log_config``
    merges additions later (e.g. values only known after mesh build).
    """

    def __init__(self, config: Mapping[str, Any] | None = None,
                 **init_kwargs):
        import wandb  # gated import — absent from this environment is fine

        self._wandb = wandb
        if config is not None:
            init_kwargs.setdefault("config", dict(config))
        self.run = wandb.init(**init_kwargs)

    def log_config(self, config: Mapping[str, Any]) -> None:
        """Merge more run config after init (wandb.config.update)."""
        self.run.config.update(dict(config), allow_val_change=True)

    def log(self, metrics: Mapping[str, Any], step: int) -> None:
        self._wandb.log(dict(metrics), step=step)

    def info(self, msg: str) -> None:
        print(msg)


_current: contextvars.ContextVar[Logger] = contextvars.ContextVar(
    "fluxdistributed_tpu_logger", default=ConsoleLogger()
)


def current_logger() -> Logger:
    return _current.get()


@contextlib.contextmanager
def with_logger(logger: Logger):
    """Route framework logging through ``logger`` for the dynamic extent —
    the ``Logging.with_logger`` analog (README.md:72-92)."""
    token = _current.set(logger)
    try:
        yield logger
    finally:
        _current.reset(token)
