"""Layer-2 checks: abstract-trace every registered compiled program and
verify its GSPMD-style metadata — no hardware, no backend compile.

Sharding annotations, donation vectors and argument signatures are
compile-time metadata (arXiv:2004.13336, arXiv:1810.09868); each check
here verifies one piece of it on the 8-virtual-device CPU mesh, where a
violation costs milliseconds instead of a dead 2400s hardware round:

========  =============================================================
FDT200    a registered variant failed to BUILD (the factory itself is
          broken — the finding carries the exception)
FDT201    a PartitionSpec names a mesh axis that does not exist on the
          variant's mesh (GSPMD rejects the program at compile time)
FDT202    a sharded dimension is not divisible by its mesh-axis size
          (uneven shards: silent padding at best, compile error at
          worst)
FDT203    a buffer declared in ``donate_argnums`` has no same-shape/
          dtype output to alias — XLA silently DROPS the donation and
          the step pays a full copy every call
FDT204    re-tracing with identical arguments yields a different
          program digest — the trace is nondeterministic (host RNG /
          wall clock / mutable global baked in), which breaks the
          persistent compile cache AND the AOT on-disk keys
          (compilation.py) on every process restart
FDT205    executing one step under ``jax.transfer_guard("disallow")``
          raised — the program implicitly moves data between host and
          device on its hot path
FDT108    a committed sharding rule table (``parallel/rules.py``
          ``RULE_TABLES``) contains a DEAD rule — a pattern matching
          no leaf on ANY of its registered probe models (a typo'd
          path or a stale layer name shards nothing, silently) — or a
          probe model carries a LARGE leaf no rule matches, silently
          falling to replication (the 4 GB-embedding-on-every-device
          trap).  Numbered 1xx (it needs no mesh) but run in this
          layer: probing a table means eval_shape-ing real models.
========  =============================================================

``check_spec_tree`` is exposed directly (shapes + specs + mesh, no
variant required) so tests — and future call sites like a checkpoint
loader — can validate sharding layouts before committing memory to them.
"""

from __future__ import annotations

import collections
import hashlib
import re
import warnings
from typing import Any, Iterable, List, Optional, Sequence, Tuple

from .findings import Finding
from .variants import StepVariant, build_variants

__all__ = [
    "check_spec_tree",
    "check_variant_sharding",
    "check_donation",
    "check_retrace",
    "check_transfers",
    "check_variant",
    "check_rule_tables",
    "run_jaxpr_checks",
]

_RULES_SRC = "fluxdistributed_tpu/parallel/rules.py"

_ADDR_RE = re.compile(r"0x[0-9a-fA-F]+")
_VARIANTS_SRC = "fluxdistributed_tpu/analysis/variants.py"


def _keystr(path) -> str:
    from jax.tree_util import keystr

    s = keystr(path)
    return s if s else "<root>"


def _spec_entries(entry) -> Tuple[str, ...]:
    """A PartitionSpec dim entry is None, an axis name, or a tuple of
    axis names (multi-axis sharding of one dim)."""
    if entry is None:
        return ()
    if isinstance(entry, (tuple, list)):
        return tuple(entry)
    return (entry,)


def check_spec_tree(shapes, specs, mesh, *, where: str,
                    source: str = _VARIANTS_SRC) -> List[Finding]:
    """Validate a tree of PartitionSpecs against a tree of shapes on a
    mesh: every named axis must exist (FDT201) and every sharded dim
    must divide by the product of its axis sizes (FDT202).

    ``shapes`` leaves are anything with ``.shape`` (arrays, ShapeDtype-
    Structs) or raw shape tuples; ``specs`` leaves are PartitionSpecs
    (``None`` = replicated).
    """
    import jax
    from jax.sharding import PartitionSpec

    mesh_axes = dict(mesh.shape)
    out: List[Finding] = []

    sflat = jax.tree_util.tree_flatten_with_path(
        specs, is_leaf=lambda x: x is None or isinstance(x, PartitionSpec))[0]
    # raw shape tuples are leaves here, not containers of ints
    aflat = jax.tree_util.tree_flatten_with_path(
        shapes, is_leaf=lambda x: isinstance(x, tuple) or hasattr(x, "shape"))[0]
    if len(sflat) != len(aflat):
        out.append(Finding(
            rule="FDT201", severity="error", file=source, line=0,
            message=f"{where}: spec tree has {len(sflat)} leaves but the "
                    f"shape tree has {len(aflat)} — layouts out of sync",
            hint="regenerate the spec tree from the live state tree",
            detail=f"{where}:tree-mismatch"))
        return out

    for (path, aval), (_, spec) in zip(aflat, sflat):
        shape = tuple(getattr(aval, "shape", aval if isinstance(aval, tuple) else ()))
        if spec is None:
            continue
        leaf = _keystr(path)
        for d, entry in enumerate(spec):
            names = _spec_entries(entry)
            if not names:
                continue
            if d >= len(shape):
                out.append(Finding(
                    rule="FDT201", severity="error", file=source, line=0,
                    message=f"{where}: spec {tuple(spec)!r} at {leaf} has "
                            f"more sharded dims than the rank-{len(shape)} "
                            "array",
                    hint="trim the PartitionSpec to the array rank",
                    detail=f"{where}:{leaf}:rank"))
                continue
            size = 1
            for a in names:
                if a not in mesh_axes:
                    out.append(Finding(
                        rule="FDT201", severity="error", file=source, line=0,
                        message=f"{where}: axis {a!r} in spec "
                                f"{tuple(spec)!r} at {leaf} is not on the "
                                f"mesh (axes: {sorted(mesh_axes)})",
                        hint="use a mesh.py axis constant and build the "
                             "mesh with that axis",
                        detail=f"{where}:{leaf}:{a}"))
                else:
                    size *= mesh_axes[a]
            if size > 1 and shape[d] % size != 0:
                out.append(Finding(
                    rule="FDT202", severity="error", file=source, line=0,
                    message=f"{where}: dim {d} of {leaf} (shape {shape}) "
                            f"is not divisible by {'x'.join(names)}="
                            f"{size}",
                    hint="pad the dim, resize the mesh axis, or replicate "
                         "the leaf",
                    detail=f"{where}:{leaf}:dim{d}"))
    return out


def check_variant_sharding(v: StepVariant) -> List[Finding]:
    """Validate every concrete sharding the variant's arguments carry
    (state AND batch) against its mesh."""
    import jax
    from jax.sharding import NamedSharding

    if v.mesh is None:
        return []
    flat = jax.tree_util.tree_flatten_with_path(v.args)[0]
    shapes_tree = {}
    specs_tree = {}
    for i, (path, leaf) in enumerate(flat):
        if isinstance(leaf, jax.Array) and isinstance(leaf.sharding, NamedSharding):
            key = f"{i}{_keystr(path)}"
            shapes_tree[key] = tuple(leaf.shape)
            specs_tree[key] = leaf.sharding.spec
    return check_spec_tree(
        shapes_tree, specs_tree, v.mesh, where=v.name, source=v.source)


def _aval_sig(x) -> Tuple[Tuple[int, ...], str]:
    return (tuple(getattr(x, "shape", ())), str(getattr(x, "dtype", "?")))


def check_donation(v: StepVariant) -> List[Finding]:
    """Abstract-eval the program and verify every DECLARED donation has
    a same-shape/dtype output to alias.  A donated buffer with no
    consumer is silently dropped by XLA — the step then copies the full
    state every call, which on a memory-tight run is the difference
    between fitting and OOM."""
    import jax

    if not v.donate_argnums:
        return []
    outs = jax.eval_shape(v.fn, *v.args)
    avail = collections.Counter(_aval_sig(x) for x in jax.tree_util.tree_leaves(outs))
    findings: List[Finding] = []
    dropped: collections.Counter = collections.Counter()
    for i in v.donate_argnums:
        for path, leaf in jax.tree_util.tree_flatten_with_path(v.args[i])[0]:
            sig = _aval_sig(leaf)
            if avail[sig] > 0:
                avail[sig] -= 1
            else:
                dropped[sig] += 1
                if dropped[sig] == 1:  # one finding per distinct aval
                    findings.append(Finding(
                        rule="FDT203", severity="error", file=v.source, line=0,
                        message=f"{v.name}: donated input arg{i}"
                                f"{_keystr(path)} {sig[0]}:{sig[1]} has no "
                                "matching output to alias — XLA drops the "
                                "donation and copies instead",
                        hint="return an updated buffer of the same "
                             "shape/dtype, or remove it from "
                             "donate_argnums",
                        detail=f"{v.name}:arg{i}:{sig[0]}:{sig[1]}"))
    return findings


def _lowered_digest(fn, args) -> Optional[str]:
    lower = getattr(fn, "lower", None)
    if lower is None:
        return None
    text = lower(*args).as_text()
    return hashlib.sha256(_ADDR_RE.sub("0x", text).encode()).hexdigest()[:16]


def check_retrace(v: StepVariant) -> List[Finding]:
    """Trace the program twice with the SAME arguments and compare
    program digests (memory addresses scrubbed, like compilation.py's
    config_tag).  A digest that moves between traces means the trace
    captures ambient state — exactly what breaks the persistent compile
    cache and the AOT on-disk keys across process restarts, i.e. a lint
    failure here predicts an AOT-key break."""
    from .. import compilation

    d1 = _lowered_digest(v.fn, v.args)
    if d1 is None:
        return []
    d2 = _lowered_digest(v.fn, v.args)
    if d1 == d2:
        return []
    # the AOT on-disk key this break invalidates (compilation.py keys
    # executables on exactly this argument signature)
    sig = compilation.abstract_signature(v.args)
    return [Finding(
        rule="FDT204", severity="error", file=v.source, line=0,
        message=f"{v.name}: re-tracing with identical inputs produced a "
                f"different program digest ({d1} → {d2}) — the trace "
                "bakes in ambient state (host RNG / wall clock / mutable "
                "global), so the compile cache and the AOT executable "
                f"keyed on argument signature {sig} break every restart",
        hint="move the ambient value into an argument or a fixed "
             "constant; see FDT102/FDT104 for the usual sources",
        detail=f"{v.name}:digest")]


def check_transfers(v: StepVariant) -> List[Finding]:
    """Execute the program under ``jax.transfer_guard("disallow")`` —
    any implicit host↔device transfer on the hot path raises.

    The guard applies to the STEADY-STATE call: the first call runs
    unguarded (committing an uncommitted input once at step 0 is
    legitimate and self-healing — the step's outputs carry the compiled
    shardings), then the variant's ``carry`` hook threads those outputs
    back into a second, guarded call.  A finding therefore means every
    step of a long run pays the transfer, which is what serializes the
    dispatch pipeline.  This is the only check that compiles and runs
    the program, so it is opt-in per variant (``StepVariant.execute``) /
    via ``--execute``.  NOTE: donated buffers in ``v.args`` are
    consumed; run this check last."""
    import jax

    with warnings.catch_warnings():
        # CPU has no donation support; the "donated buffers were not
        # usable" warning is expected noise here, not a finding
        # (FDT203 checks donation consumability abstractly instead)
        warnings.simplefilter("ignore")
        try:
            out = v.fn(*v.args)
            jax.block_until_ready(jax.tree_util.tree_leaves(out))
            if v.carry is None and v.donate_argnums:
                return []  # cannot safely re-invoke with consumed buffers
            args2 = v.carry(v.args, out) if v.carry is not None else v.args
        except Exception as e:  # noqa: BLE001 — sweep must survive one variant
            # the warm-up call runs UNGUARDED — its failure is a broken
            # program/carry hook, not a transfer violation, and must not
            # masquerade as FDT205
            return [Finding(
                rule="FDT200", severity="error", file=v.source, line=0,
                message=f"{v.name}: unguarded warm-up execution failed: "
                        f"{type(e).__name__}: {str(e)[:200]}",
                hint="run the variant's fn/carry directly for the full "
                     "traceback",
                detail=f"{v.name}:execute")]
        try:
            with jax.transfer_guard("disallow"):
                out2 = v.fn(*args2)
                jax.block_until_ready(jax.tree_util.tree_leaves(out2))
        except Exception as e:  # noqa: BLE001 — the guard raises jax-internal types
            return [Finding(
                rule="FDT205", severity="error", file=v.source, line=0,
                message=f"{v.name}: a steady-state step under "
                        f"transfer_guard('disallow') raised "
                        f"{type(e).__name__}: {str(e)[:200]}",
                hint="commit inputs with jax.device_put up front; implicit "
                     "per-step transfers serialize the dispatch pipeline",
                detail=f"{v.name}:transfer")]
    return []


def check_rule_tables(tables=None) -> List[Finding]:
    """FDT108 — sweep the committed sharding rule tables
    (``parallel.rules.RULE_TABLES``, or ``tables`` for tests) against
    their registered probe models.  A pattern is DEAD when it decides
    no leaf on any probe (aggregated across the table's probes: the
    GQA-only ``kv/kernel`` rule is alive because the GQA probe carries
    it); a probe leaf at/above the fallback size threshold matched by
    nothing is a silent replication — flagged unless the table opts
    out (``check_unmatched=False``: the dp/fsdp tables replicate or
    catch-all by DOCUMENTED design).  Probes are eval_shape'd — no
    buffer allocates, no mesh is needed."""
    from ..parallel import rules as rules_mod

    findings: List[Finding] = []
    for name, table in sorted((tables or
                               rules_mod.registered_rule_tables()).items()):
        try:
            rule_list = table.build()
        except Exception as e:  # noqa: BLE001 — a broken builder is a finding
            findings.append(Finding(
                rule="FDT108", severity="error", file=_RULES_SRC, line=0,
                message=f"rule table {name!r} failed to build: "
                        f"{type(e).__name__}: {str(e)[:200]}",
                hint="run the table's build() directly for the traceback",
                detail=f"{name}:build"))
            continue
        # duplicate patterns are unreachable under first-match-wins —
        # and would also collapse in the aliveness dict below, so the
        # stale copy's spec could silently never apply.  Flag them
        # outright before the probe sweep.
        seen_pats: set = set()
        for pat, _ in rule_list:
            if pat in seen_pats:
                findings.append(Finding(
                    rule="FDT108", severity="error", file=_RULES_SRC,
                    line=0,
                    message=f"rule table {name!r}: pattern {pat!r} "
                            "appears more than once — the later entry "
                            "is unreachable under first-match-wins, so "
                            "its spec silently never applies",
                    hint="delete the duplicate (keep whichever spec is "
                         "intended as the single entry)",
                    detail=f"{name}:duplicate:{pat}"))
            seen_pats.add(pat)
        alive = {pat: False for pat, _ in rule_list}
        large: List[tuple] = []
        for probe in table.probes:
            try:
                params, note = probe()
            except Exception as e:  # noqa: BLE001
                findings.append(Finding(
                    rule="FDT108", severity="error", file=_RULES_SRC,
                    line=0,
                    message=f"rule table {name!r}: probe failed to "
                            f"build: {type(e).__name__}: {str(e)[:200]}",
                    hint="run the probe directly for the traceback",
                    detail=f"{name}:probe"))
                continue
            rep = rules_mod.rule_report(rule_list, params)
            for pat, hits in rep.matched.items():
                if hits:
                    alive[pat] = True
            if table.check_unmatched:
                large += [(note, path, n)
                          for path, n in rep.large_unmatched]
        for pat, hit in alive.items():
            if not hit:
                findings.append(Finding(
                    rule="FDT108", severity="error", file=_RULES_SRC,
                    line=0,
                    message=f"rule table {name!r}: pattern {pat!r} "
                            "matches NO leaf on any registered probe "
                            "model — a dead rule (typo'd path or stale "
                            "layer name shards nothing, silently)",
                    hint="fix the regex, or register a probe model "
                         "that carries the leaf it targets",
                    detail=f"{name}:dead:{pat}"))
        for note, path, n in large:
            findings.append(Finding(
                rule="FDT108", severity="error", file=_RULES_SRC, line=0,
                message=f"rule table {name!r}: {note} leaf {path} "
                        f"({n} elements) matches no rule and silently "
                        "falls to replication — at scale that is a "
                        "full copy on every device",
                hint="add a rule for it (or a ShardLargest catch-all); "
                     "sub-threshold leaves replicate by design",
                detail=f"{name}:unmatched:{path}"))
    return findings


def check_variant(v: StepVariant, execute: Optional[bool] = None) -> List[Finding]:
    out: List[Finding] = []
    out += check_variant_sharding(v)
    out += check_donation(v)
    out += check_retrace(v)
    if execute if execute is not None else v.execute:
        out += check_transfers(v)
    return out


def run_jaxpr_checks(
    names: Optional[Sequence[str]] = None,
    execute: Optional[bool] = None,
    variants: Optional[Iterable[StepVariant]] = None,
) -> List[Finding]:
    """Run every jaxpr-layer check over the registered variants (or the
    given prebuilt ones).  A variant whose BUILD raises becomes an
    FDT200 finding rather than aborting the sweep — one broken factory
    must not mask findings in the other five."""
    import jax

    if jax.device_count() < 8 and variants is None:
        raise RuntimeError(
            f"jaxpr checks need the 8-virtual-device mesh, have "
            f"{jax.device_count()} — call "
            "fluxdistributed_tpu.mesh.force_host_devices(8) before any "
            "jax use (bin/lint.py does)")
    findings: List[Finding] = []
    if variants is not None:
        for v in variants:
            findings.extend(check_variant(v, execute=execute))
        return findings
    if names is None:
        # the full sweep also audits the committed rule tables (a
        # --variants-filtered run stays scoped to those variants)
        findings.extend(check_rule_tables())
    from .variants import VARIANT_BUILDERS

    for name in (names or list(VARIANT_BUILDERS)):
        try:
            built = build_variants([name])
        except Exception as e:  # noqa: BLE001 — a broken factory is a finding
            findings.append(Finding(
                rule="FDT200", severity="error", file=_VARIANTS_SRC, line=0,
                message=f"variant {name!r} failed to build: "
                        f"{type(e).__name__}: {str(e)[:300]}",
                hint="run the builder directly for the full traceback: "
                     f"analysis.variants.build_variants(['{name}'])",
                detail=f"{name}:build"))
            continue
        for v in built:
            findings.extend(check_variant(v, execute=execute))
    return findings
