"""Logit-parity test: torchvision-layout weights -> flax model.

Validates both the importer (models/torch_import.py) and the flax ResNet
definitions (stride placement, padding convention, BN eps) against the
canonical torch architecture — the numeric check the reference never had
for its Metalhead weight path (src/preprocess.jl:9-24).
"""

from __future__ import annotations

import numpy as np
import pytest

# tier-2 (slow): torch imports + full-model weight-parity compiles — the tier-1 iteration loop must fit the
# 870s verify window (ROADMAP); CI's slow job still runs this file
pytestmark = pytest.mark.slow

torch = pytest.importorskip("torch")

import jax.numpy as jnp  # noqa: E402

from fluxdistributed_tpu.models import resnet18, resnet50  # noqa: E402
from fluxdistributed_tpu.models.torch_import import import_torch_resnet  # noqa: E402

from _torch_resnet import torch_resnet  # noqa: E402


@pytest.mark.parametrize("depth,factory", [(18, resnet18), (50, resnet50)])
def test_logit_parity(depth, factory):
    torch.manual_seed(0)
    tm = torch_resnet(depth, num_classes=1000).eval()
    params, mstate = import_torch_resnet(tm.state_dict(), depth=depth)

    model = factory(num_classes=1000, dtype=jnp.float32)
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (2, 224, 224, 3)).astype(np.float32)

    with torch.no_grad():
        ref = tm(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()

    out = np.asarray(model.apply({"params": params, **mstate}, x, train=False))
    np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-3)


def test_infer_cli_torch_weights(tmp_path, capsys):
    """bin/infer.py --torch-weights serves predictions from a .pt file."""
    import pathlib
    import sys

    sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1] / "bin"))
    import infer

    torch.manual_seed(0)
    tm = torch_resnet(18, num_classes=1000)
    pt = tmp_path / "resnet18.pt"
    torch.save(tm.state_dict(), pt)

    rc = infer.main(["--model", "resnet18", "--torch-weights", str(pt)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "loaded torch-layout weights" in out


def test_param_tree_shapes_match_init():
    """The imported tree must be structurally identical to a fresh init
    (same keys, same shapes) so it drops into TrainState/checkpointing."""
    import jax

    torch.manual_seed(1)
    tm = torch_resnet(50, num_classes=1000)
    params, mstate = import_torch_resnet(tm.state_dict(), depth=50)

    model = resnet50(num_classes=1000, dtype=jnp.float32)
    ref_vars = model.init(jax.random.PRNGKey(0), np.zeros((1, 64, 64, 3), np.float32),
                          train=False)

    got = jax.tree.map(np.shape, params)
    want = jax.tree.map(np.shape, ref_vars["params"])
    assert got == want
    got_s = jax.tree.map(np.shape, mstate["batch_stats"])
    want_s = jax.tree.map(np.shape, ref_vars["batch_stats"])
    assert got_s == want_s


def test_vit_logit_parity():
    """torchvision-layout ViT weights -> flax ViT, logit parity."""
    import jax.numpy as jnp2

    from fluxdistributed_tpu.models import ViT
    from fluxdistributed_tpu.models.torch_import import import_torch_vit

    from _torch_vit import TorchViT

    torch.manual_seed(0)
    tm = TorchViT(image_size=32, patch=8, dim=64, depth=2, heads=4,
                  mlp_dim=128, num_classes=10).eval()
    # random weights everywhere (default init leaves cls_token zero)
    with torch.no_grad():
        tm.class_token.normal_(std=0.02)
    params, mstate = import_torch_vit(tm.state_dict(), num_heads=4)

    model = ViT(patch=8, depth=2, dim=64, num_heads=4, mlp_dim=128,
                num_classes=10, dtype=jnp2.float32,
                use_class_token=True, gelu_exact=True)
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (2, 32, 32, 3)).astype(np.float32)

    with torch.no_grad():
        ref = tm(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    out = np.asarray(model.apply({"params": params, **mstate}, x, train=False))
    np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-3)


def test_vit_import_tree_matches_init():
    import jax

    from fluxdistributed_tpu.models import ViT
    from fluxdistributed_tpu.models.torch_import import import_torch_vit

    from _torch_vit import TorchViT

    torch.manual_seed(1)
    tm = TorchViT(image_size=32, patch=8, dim=64, depth=2, heads=4,
                  mlp_dim=128, num_classes=10)
    params, _ = import_torch_vit(tm.state_dict(), num_heads=4)

    import jax.numpy as jnp2

    model = ViT(patch=8, depth=2, dim=64, num_heads=4, mlp_dim=128,
                num_classes=10, dtype=jnp2.float32, use_class_token=True)
    ref = model.init(jax.random.PRNGKey(0), np.zeros((1, 32, 32, 3), np.float32),
                     train=False)
    got = jax.tree.map(np.shape, params)
    want = jax.tree.map(np.shape, ref["params"])
    assert got == want


def test_convnext_logit_parity():
    """Official-layout ConvNeXt weights -> flax ConvNeXt, logit parity."""
    import jax.numpy as jnp3

    from fluxdistributed_tpu.models import convnext_test
    from fluxdistributed_tpu.models.torch_import import import_torch_convnext

    from _torch_convnext import TorchConvNeXt

    torch.manual_seed(0)
    tm = TorchConvNeXt(depths=(1, 1, 2, 1), dims=(16, 32, 64, 128),
                       num_classes=10).eval()
    params, mstate = import_torch_convnext(tm.state_dict())

    model = convnext_test(num_classes=10, dtype=jnp3.float32, gelu_exact=True)
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (2, 64, 64, 3)).astype(np.float32)

    with torch.no_grad():
        ref = tm(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    out = np.asarray(model.apply({"params": params, **mstate}, x, train=False))
    np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-3)


def test_convnext_import_tree_matches_init():
    import jax
    import jax.numpy as jnp3

    from fluxdistributed_tpu.models import convnext_test
    from fluxdistributed_tpu.models.torch_import import import_torch_convnext

    from _torch_convnext import TorchConvNeXt

    torch.manual_seed(1)
    tm = TorchConvNeXt(depths=(1, 1, 2, 1), dims=(16, 32, 64, 128), num_classes=10)
    params, _ = import_torch_convnext(tm.state_dict())
    model = convnext_test(num_classes=10, dtype=jnp3.float32)
    ref = model.init(jax.random.PRNGKey(0), np.zeros((1, 64, 64, 3), np.float32),
                     train=False)
    got = jax.tree.map(np.shape, params)
    want = jax.tree.map(np.shape, ref["params"])
    assert got == want


def test_logit_parity_s2d_stem():
    """Torch weights imported with space_to_depth=True match the torch
    reference through the MXU-shaped stem — pretrained weights survive
    the stem re-layout exactly."""
    from fluxdistributed_tpu.models.resnet import space_to_depth

    torch.manual_seed(0)
    tm = torch_resnet(18, num_classes=1000).eval()
    params, mstate = import_torch_resnet(
        tm.state_dict(), depth=18, space_to_depth=True
    )
    model = resnet18(num_classes=1000, dtype=jnp.float32, space_to_depth=True)
    rng = np.random.default_rng(0)
    x = rng.normal(0, 1, (2, 64, 64, 3)).astype(np.float32)
    with torch.no_grad():
        ref = tm(torch.from_numpy(x.transpose(0, 3, 1, 2))).numpy()
    out = np.asarray(
        model.apply({"params": params, **mstate}, space_to_depth(x), train=False)
    )
    np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-3)


def test_gpt2_logit_parity():
    """HF transformers GPT-2 (random init — no network needed) ->
    TransformerLM(use_rope=False, norm_eps=1e-5): exact logit parity.
    Validates the Conv1D (no-transpose) qkv/mlp mapping, the learned
    positional table slice, tied embeddings, and the LN epsilon."""
    transformers = pytest.importorskip("transformers")

    from fluxdistributed_tpu.models import import_gpt2
    from fluxdistributed_tpu.models.transformer_lm import TransformerLM

    torch.manual_seed(0)
    cfg = transformers.GPT2Config(
        vocab_size=100, n_positions=32, n_embd=48, n_layer=2, n_head=3,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )
    hm = transformers.GPT2LMHeadModel(cfg).eval()
    params, mstate = import_gpt2(hm.state_dict(), num_heads=3, seqlen=32)
    assert mstate == {}

    m = TransformerLM(
        vocab=100, depth=2, dim=48, num_heads=3, mlp_dim=192,
        dtype=jnp.float32, dropout=0.0, use_rope=False, norm_eps=1e-5,
    )
    toks = np.random.default_rng(0).integers(0, 100, (2, 32)).astype(np.int32)
    with torch.no_grad():
        ref = hm(torch.from_numpy(toks.astype(np.int64))).logits.numpy()
    out = np.asarray(m.apply({"params": params}, jnp.asarray(toks), train=False))
    np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


def test_gpt2_import_rejects_non_gpt2():
    from fluxdistributed_tpu.models import import_gpt2

    with pytest.raises(ValueError, match="wte"):
        import_gpt2({"foo": 1}, num_heads=2)


def test_gpt2_import_decode_matches_full_forward():
    """Imported GPT-2 weights must also DECODE correctly: learned
    positional rows are sliced at the cache cursor (a naive broadcast
    would silently add the whole table to each single-token step)."""
    transformers = pytest.importorskip("transformers")

    import jax

    from fluxdistributed_tpu.models import import_gpt2
    from fluxdistributed_tpu.models.transformer_lm import TransformerLM

    torch.manual_seed(1)
    cfg = transformers.GPT2Config(
        vocab_size=64, n_positions=16, n_embd=32, n_layer=2, n_head=2,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )
    hm = transformers.GPT2LMHeadModel(cfg).eval()
    params, _ = import_gpt2(hm.state_dict(), num_heads=2, seqlen=16)

    kw = dict(vocab=64, depth=2, dim=32, num_heads=2, mlp_dim=128,
              dtype=jnp.float32, dropout=0.0, use_rope=False, norm_eps=1e-5,
              max_len=16)
    m = TransformerLM(**kw)
    dm = TransformerLM(**kw, decode=True)
    toks = np.random.default_rng(2).integers(0, 64, (2, 16)).astype(np.int32)
    full = m.apply({"params": params}, jnp.asarray(toks), train=False)

    # prefill 5 + single-token steps, through the positional cursor
    cache = dm.init(jax.random.PRNGKey(0), jnp.zeros_like(toks), train=False)["cache"]
    pre, mut = dm.apply(
        {"params": params, "cache": cache}, jnp.asarray(toks[:, :5]),
        train=False, mutable=["cache"],
    )
    cache = mut["cache"]
    got = [np.asarray(pre)]
    for t in range(5, toks.shape[1]):
        logits, mut = dm.apply(
            {"params": params, "cache": cache}, jnp.asarray(toks[:, t : t + 1]),
            train=False, mutable=["cache"],
        )
        cache = mut["cache"]
        got.append(np.asarray(logits))
    np.testing.assert_allclose(
        np.asarray(full), np.concatenate(got, axis=1), rtol=2e-4, atol=2e-4
    )


def test_gpt2_generate_and_bounds():
    """generate() works with imported-GPT-2-style models (use_rope=False
    + max_len) and rejects sampling past the positional table."""
    transformers = pytest.importorskip("transformers")

    import jax

    from fluxdistributed_tpu.models import generate, import_gpt2
    from fluxdistributed_tpu.models.transformer_lm import TransformerLM

    torch.manual_seed(2)
    cfg = transformers.GPT2Config(
        vocab_size=64, n_positions=16, n_embd=32, n_layer=2, n_head=2,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )
    hm = transformers.GPT2LMHeadModel(cfg).eval()
    params, _ = import_gpt2(hm.state_dict(), num_heads=2, seqlen=16)
    kw = dict(vocab=64, depth=2, dim=32, num_heads=2, mlp_dim=128,
              dtype=jnp.float32, dropout=0.0, use_rope=False, norm_eps=1e-5,
              max_len=16)
    dm = TransformerLM(**kw, decode=True)

    prompt = np.asarray([[3, 1, 4]], np.int32)
    out = generate(dm, params, jnp.asarray(prompt), total_len=10,
                   temperature=0.0)
    assert out.shape == (1, 10)
    # greedy generate must equal HF greedy continuation
    with torch.no_grad():
        href = hm.generate(
            torch.from_numpy(prompt.astype(np.int64)), max_length=10,
            do_sample=False, pad_token_id=0,
        ).numpy()
    np.testing.assert_array_equal(np.asarray(out), href)

    with pytest.raises(ValueError, match="positional table"):
        generate(dm, params, jnp.asarray(prompt), total_len=32)
    dm_nolen = TransformerLM(**{**kw, "max_len": None}, decode=True)
    with pytest.raises(ValueError, match="max_len"):
        generate(dm_nolen, params, jnp.asarray(prompt), total_len=10)
