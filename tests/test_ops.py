"""Loss and metric tests (reference: topkaccuracy src/utils.jl:20-45,
logitcrossentropy usage src/ddp_tasks.jl:28)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fluxdistributed_tpu.ops import logitcrossentropy, onehot, topkaccuracy


def test_logitcrossentropy_matches_optax():
    logits = jax.random.normal(jax.random.PRNGKey(0), (16, 10))
    labels = jax.random.randint(jax.random.PRNGKey(1), (16,), 0, 10)
    ours = logitcrossentropy(logits, labels)
    ref = optax.softmax_cross_entropy_with_integer_labels(logits, labels).mean()
    assert np.isclose(float(ours), float(ref), rtol=1e-6)
    # one-hot labels give the same result
    ours_oh = logitcrossentropy(logits, onehot(labels, 10))
    assert np.isclose(float(ours_oh), float(ref), rtol=1e-6)


def test_label_smoothing_increases_loss_on_confident_preds():
    logits = jnp.eye(10) * 10.0
    labels = jnp.arange(10)
    plain = float(logitcrossentropy(logits, labels))
    smooth = float(logitcrossentropy(logits, labels, label_smoothing=0.1))
    assert smooth > plain


def test_topkaccuracy_known_case():
    # row 0: true class 0 ranked 1st; row 1: true class 0 ranked 3rd
    scores = jnp.array(
        [[5.0, 1.0, 0.0, 0.0], [1.0, 5.0, 2.0, 0.0]]
    )
    labels = jnp.array([0, 0])
    assert float(topkaccuracy(scores, labels, k=1)) == 0.5
    assert float(topkaccuracy(scores, labels, k=3)) == 1.0
    # one-hot labels accepted, as the reference passes onehotbatch labels
    assert float(topkaccuracy(scores, onehot(labels, 4), k=1)) == 0.5


def test_topkaccuracy_k_clamped_and_jittable():
    scores = jax.random.normal(jax.random.PRNGKey(0), (8, 3))
    labels = jnp.zeros((8,), jnp.int32)
    assert 0.0 <= float(jax.jit(lambda s, l: topkaccuracy(s, l, k=3))(scores, labels)) <= 1.0
    assert float(topkaccuracy(scores, labels, k=10)) == 1.0  # k>classes → all hit
