"""Collective-traffic ledger (obs/comms.py): exact per-variant counts
on the 8-virtual-device CPU mesh, both ledger layers, and the rollup
helpers.

The headline assertion is the arXiv:2004.13336 signature on the REAL
registered paths: the explicit ZeRO-1 step moves its parameter traffic
as reduce-scatter + all-gather where the DP step moves all-reduce ONLY
— and the fused ZeRO-1 step does it in exactly ONE collective of each
kind (the PR-8 claim, now measured instead of asserted in prose).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import pytest

from fluxdistributed_tpu.analysis.variants import build_variants
from fluxdistributed_tpu.obs.comms import (
    collective_signature,
    hlo_collectives,
    jaxpr_collectives,
    merge_entries,
    total_bytes,
)


def _by_key(entries):
    return {(e["kind"], tuple(e["axes"]) if e["axes"] else None):
            e["count"] for e in entries}


@pytest.fixture(scope="module")
def variants():
    """One build per variant name the module pins (builds trace
    nothing; the hlo tests compile their own few)."""
    names = ["dp", "dp_shardmap", "zero1_shardmap", "zero1_fused",
             "pp_1f1b", "context", "tp", "fsdp"]
    return {v.name: v for n in names for v in build_variants([n])}


# ---- jaxpr layer: explicit-collective schedules ---------------------------

def test_dp_shardmap_all_reduce_only(variants):
    """DP's semantic signature: gradient + loss traffic is all-reduce
    and NOTHING else — one pmean per param leaf (6) plus the loss."""
    v = variants["dp_shardmap"]
    entries = jaxpr_collectives(v.fn, v.args)
    assert _by_key(entries) == {("all_reduce", ("data",)): 7}


def test_zero1_shardmap_reduce_scatter_all_gather(variants):
    """THE ZeRO-1 signature (arXiv:2004.13336): parameter traffic is
    reduce-scatter (summed 1/N slice in) + all-gather (updated params
    out), one per param leaf; the only all-reduce left is the scalar
    loss.  Exact counts, exact axes, on the real prepare_training
    path."""
    v = variants["zero1_shardmap"]
    entries = jaxpr_collectives(v.fn, v.args)
    assert _by_key(entries) == {
        ("reduce_scatter", ("data",)): 6,
        ("all_gather", ("data",)): 6,
        ("all_reduce", ("data",)): 1,
    }
    # the parameter bytes ride the scatter/gather pair, not all-reduce:
    per_kind = {e["kind"]: e["bytes"] for e in entries}
    assert per_kind["reduce_scatter"] == per_kind["all_gather"]
    assert per_kind["all_reduce"] < per_kind["reduce_scatter"]


def test_zero1_fused_one_collective_each(variants):
    """The fused packed update's whole point, pinned: ONE
    reduce-scatter, ONE all-gather (the packed buffer), ONE all-reduce
    (the loss scalar) — not one per leaf."""
    v = variants["zero1_fused"]
    assert _by_key(jaxpr_collectives(v.fn, v.args)) == {
        ("reduce_scatter", ("data",)): 1,
        ("all_gather", ("data",)): 1,
        ("all_reduce", ("data",)): 1,
    }


def test_pp_1f1b_ppermute_signature(variants):
    """The pipeline's signature: activation/cotangent hops are
    ppermute on the pipe axis (scan-multiplied to the per-step count),
    plus the loss/grad psums on pipe and the DP mean on data."""
    v = variants["pp_1f1b"]
    assert _by_key(jaxpr_collectives(v.fn, v.args)) == {
        ("ppermute", ("pipe",)): 20,
        ("all_reduce", ("pipe",)): 2,
        ("all_reduce", ("data",)): 16,
    }


def test_context_ring_signature(variants):
    """Ring attention rotates KV shards with ppermute on the seq axis
    — the context-parallel signature (psums from the shard_map
    transpose carry no named axes on this tracer; their count is
    pinned, their axis honestly None)."""
    v = variants["context"]
    sig = _by_key(jaxpr_collectives(v.fn, v.args))
    assert sig[("ppermute", ("seq",))] == 16
    assert sig[("all_reduce", None)] == 6
    assert set(sig) == {("ppermute", ("seq",)), ("all_reduce", None)}


# ---- HLO layer: GSPMD-inserted collectives --------------------------------

def test_dp_gspmd_hlo_all_reduce_only(variants):
    """The GSPMD dp step's jaxpr carries NO collectives (XLA inserts
    them) — the compiled-HLO layer sees exactly the all-reduces the
    shard_map twin writes explicitly, attributed to the data axis via
    replica_groups."""
    v = variants["dp"]
    assert jaxpr_collectives(v.fn, v.args) == []
    compiled = v.fn.lower(*v.args).compile()
    assert _by_key(hlo_collectives(compiled, mesh=v.mesh)) == {
        ("all_reduce", ("data",)): 7}


def test_tp_hlo_axes_attribution(variants):
    """Tensor parallelism's signature: activation reductions on the
    model axis next to the gradient mean on data — the replica_groups
    → mesh-axis matcher must untangle BOTH axis communicators of the
    2x4 mesh (including XLA's iota/transposed group spellings)."""
    v = variants["tp"]
    compiled = v.fn.lower(*v.args).compile()
    sig = _by_key(hlo_collectives(compiled, mesh=v.mesh))
    assert sig == {("all_reduce", ("model",)): 10,
                   ("all_reduce", ("data",)): 17}


def test_layout_hlo_signatures():
    """The rule-derived 3-D layouts' compiled signatures, pinned like
    dp/zero1: the 2-D dp x fsdp image layout moves its gradient mean
    over the JOINT (data, fsdp) communicator (the batch shards over
    both, so the mean is one all-reduce spanning both axes); the
    tp-composed LM layouts split activation reductions onto the model
    axis next to the batch-communicator gradient mean — byte-identical
    structure to the hand-built tp variant's (17 data + 10 model) with
    the batch communicator renamed to the layout's axes.  The
    replica_groups matcher must untangle the multi-axis groups of the
    3-D mesh, including the joint (data, fsdp) combination."""
    cases = {
        "layout_dp_fsdp": {("all_reduce", ("data", "fsdp")): 7},
        "layout_fsdp_tp": {("all_reduce", ("fsdp",)): 17,
                           ("all_reduce", ("model",)): 10},
        "layout_dp_fsdp_tp": {("all_reduce", ("data", "fsdp")): 17,
                              ("all_reduce", ("model",)): 10},
    }
    for name, want in cases.items():
        (v,) = build_variants([name])
        # GSPMD variant: the jaxpr carries no collectives, the
        # compiled HLO carries the derived schedule
        assert jaxpr_collectives(v.fn, v.args) == []
        compiled = v.fn.lower(*v.args).compile()
        assert _by_key(hlo_collectives(compiled, mesh=v.mesh)) == want, name


def test_fsdp_hlo_signature(variants):
    """fsdp's compiled signature pinned as XLA emits it HERE: on this
    CPU build the tiny model's gather/scatter pairs fold into plain
    all-reduces (sharding propagation re-replicates small params) —
    the pinned count is the regression tripwire; a future XLA emitting
    all-gather+reduce-scatter instead is a deliberate baseline
    update."""
    v = variants["fsdp"]
    compiled = v.fn.lower(*v.args).compile()
    assert _by_key(hlo_collectives(compiled, mesh=v.mesh)) == {
        ("all_reduce", ("data",)): 7}


# ---- counting semantics ---------------------------------------------------

def test_scan_multiplies_and_cond_takes_max():
    def body_fn(x):
        def one(c, _):
            return jax.lax.ppermute(c, "data", [(0, 1), (1, 0)]), None

        out, _ = jax.lax.scan(one, x, None, length=5)
        return out

    from fluxdistributed_tpu import mesh as mesh_lib

    m = mesh_lib.data_mesh(2)
    f = jax.jit(jax.shard_map(
        body_fn, mesh=m,
        in_specs=jax.sharding.PartitionSpec("data"),
        out_specs=jax.sharding.PartitionSpec("data")))
    entries = jaxpr_collectives(f, (jnp.zeros((2, 4)),))
    # renamed axis inside shard_map is 'data'; scan body runs 5x
    assert _by_key(entries) == {("ppermute", ("data",)): 5}

    def cond_fn(x, flag):
        return jax.lax.cond(
            flag > 0,
            lambda c: jax.lax.psum(c, "data"),
            lambda c: jax.lax.psum(c * 2, "data"),
            x)

    g = jax.jit(jax.shard_map(
        cond_fn, mesh=m,
        in_specs=(jax.sharding.PartitionSpec("data"),
                  jax.sharding.PartitionSpec()),
        out_specs=jax.sharding.PartitionSpec("data")))
    entries = jaxpr_collectives(g, (jnp.zeros((2, 4)),
                                    jnp.zeros((), jnp.int32)))
    # ONE branch runs per invocation: merged at max, not summed to 2
    assert _by_key(entries) == {("all_reduce", ("data",)): 1}


def test_bytes_accounting():
    from fluxdistributed_tpu import mesh as mesh_lib

    m = mesh_lib.data_mesh(8)

    def fn(x):
        return jax.lax.psum(x, "data")

    f = jax.jit(jax.shard_map(
        fn, mesh=m, in_specs=jax.sharding.PartitionSpec(),
        out_specs=jax.sharding.PartitionSpec()))
    x = jnp.zeros((4, 8), jnp.float32)
    (entry,) = jaxpr_collectives(f, (x,))
    assert entry["bytes"] == entry["bytes_per_call"] == 4 * 8 * 4
    assert total_bytes([entry]) == 128


# ---- rollups --------------------------------------------------------------

def test_signature_and_merge():
    a = [{"kind": "all_reduce", "axes": ["data"], "count": 2,
          "bytes": 100, "bytes_per_call": 60}]
    b = [{"kind": "all_reduce", "axes": ["data"], "count": 3,
          "bytes": 50, "bytes_per_call": 50},
         {"kind": "ppermute", "axes": None, "count": 1,
          "bytes": 10, "bytes_per_call": 10}]
    merged = merge_entries(a, b)
    assert _by_key(merged) == {("all_reduce", ("data",)): 5,
                               ("ppermute", None): 1}
    assert collective_signature(merged) == {"all_reduce": 5,
                                            "ppermute": 1}
    ar = next(e for e in merged if e["kind"] == "all_reduce")
    assert ar["bytes"] == 150 and ar["bytes_per_call"] == 60
