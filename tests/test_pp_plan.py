"""Profile-guided pipeline planner + schedule-oracle property tests.

Planner invariants: on a skewed synthetic cost profile the planner's
boundaries give STRICTLY lower modeled bubble than uniform splits; on
flat costs it degrades to the uniform layout exactly (same boundaries,
same compiled program); memory budgets make placements infeasible
rather than silently over-budget; artifacts round-trip and reject
cross-topology reuse through the fingerprint check.

Oracle invariants (:func:`~fluxdistributed_tpu.parallel.pp_1f1b._verify_placement`):
every timetable the builder emits passes, over a randomized
(S, M, V, schedule) grid including "zb" — and deliberately corrupted
placements of every hazard class FAIL, because a proof that never
fires proves nothing.
"""

import json

import numpy as np
import pytest

from fluxdistributed_tpu.obs.profile import (
    Profile, ProfileMismatch, bubble_report, modeled_bubble,
    stage_costs_from_static,
)
from fluxdistributed_tpu.parallel.pp_1f1b import (
    _place, _verify_placement, build_schedule,
)
from fluxdistributed_tpu.parallel.pp_plan import (
    PipelinePlan, PlanError, plan_stages, stage_costs_for,
    uniform_boundaries,
)


# ---- partitioner ----

@pytest.mark.parametrize("depth,s", [(8, 4), (6, 4), (9, 4), (7, 3), (16, 8)])
def test_flat_costs_degrade_to_uniform(depth, s):
    plan = plan_stages([1.0] * depth, s, 8)
    assert plan.boundaries == uniform_boundaries(depth, s)
    assert plan.is_uniform
    assert plan.modeled_bubble == pytest.approx(plan.uniform_bubble)


def test_skewed_profile_beats_uniform_modeled_bubble():
    """The acceptance criterion: strictly lower modeled bubble than
    uniform splits on a skewed synthetic cost profile."""
    skews = [
        [4, 1, 1, 1, 1, 1, 1, 4],          # heavy ends
        [1, 1, 1, 1, 1, 1, 1, 9],          # one heavy tail block
        [5, 1, 2, 1, 3, 1, 1, 2, 1, 1],    # irregular
    ]
    for costs in skews:
        plan = plan_stages(costs, 4, 8)
        assert plan.modeled_bubble < plan.uniform_bubble, (costs, plan)
        # the planned max stage is never worse than uniform's
        uni = stage_costs_for(costs, uniform_boundaries(len(costs), 4))
        assert max(plan.stage_costs) <= max(uni)


def test_outer_costs_thin_the_end_stages():
    """Embed/head folded into the first/last stages is the reason the
    planner wins even on a homogeneous stack."""
    plan = plan_stages([1.0] * 8, 4, 8, outer=(2.0, 2.0))
    assert plan.counts[0] < plan.counts[1]
    assert plan.counts[-1] < plan.counts[-2]
    assert plan.modeled_bubble < plan.uniform_bubble


def test_planner_validation_and_memory_budget():
    with pytest.raises(PlanError, match="cannot fill"):
        plan_stages([1.0] * 3, 4, 8)
    with pytest.raises(PlanError, match="num_microbatches"):
        plan_stages([1.0] * 8, 4, 0)
    with pytest.raises(PlanError, match="non-negative"):
        plan_stages([1.0, -1.0, 1.0, 1.0], 2, 4)
    # an impossible per-device budget is infeasible, not silently over
    with pytest.raises(PlanError, match="memory budget"):
        plan_stages([1.0] * 8, 4, 8, block_bytes=[100.0] * 8,
                    memory_budget=10.0)
    # a budget that rules out piling blocks on one device reshapes the
    # partition instead of failing
    plan = plan_stages([1.0] * 8, 4, 8, block_bytes=[100.0] * 8,
                       memory_budget=300.0)
    assert max(plan.counts) <= 3
    assert all(b <= 300.0 for b in plan.stage_bytes)


def test_plan_artifact_roundtrip_and_fingerprint_gate(tmp_path):
    plan = plan_stages([2, 1, 1, 1, 1, 2], 3, 6, fingerprint="")
    path = str(tmp_path / "plan.json")
    plan.save(path)
    back = PipelinePlan.load(path)
    assert back.boundaries == plan.boundaries
    assert back.stage_costs == plan.stage_costs
    # no fingerprint -> topology-free, verify passes anywhere
    assert back.verify() is back
    assert back.verify_source_topology() is back
    # a wrong-schema file is rejected with guidance
    bad = str(tmp_path / "bad.json")
    with open(bad, "w") as f:
        json.dump({"schema": "something-else"}, f)
    with pytest.raises(ValueError, match="fdtpu-pp-plan/v1"):
        PipelinePlan.load(bad)
    # a fingerprint from ANOTHER topology is rejected
    alien = plan_stages([1.0] * 6, 3, 6, fingerprint="0" * 16)
    with pytest.raises(ProfileMismatch):
        alien.verify()


def test_plan_from_profile_uses_blocks_and_outer():
    from fluxdistributed_tpu.parallel.pp_plan import plan_from_profile

    prof = Profile(
        fingerprint="",
        topology={"mesh": {"pipe": 4}},
        static={"model": {
            "batch": 2, "seqlen": 8, "depth": 8,
            "block": {"flops": 1.0, "bytes": 10.0},
            "outer": {"flops": 4.0, "bytes": 40.0},
            "total": {"flops": 12.0, "bytes": 120.0},
        }},
    )
    plan = plan_from_profile(prof, 4, 8)
    assert plan.depth == 8 and plan.S == 4
    assert plan.counts[0] < plan.counts[1]  # outer thins stage 0
    assert plan.meta["topology_mesh"] == {"pipe": 4}
    # an explicit per-block skew list takes precedence
    prof.static["model"]["blocks"] = [
        {"flops": f, "bytes": 1.0}
        for f in (6.0, 1.0, 1.0, 1.0, 1.0, 1.0, 1.0, 6.0)]
    prof.static["model"]["outer"] = {"flops": 0.0, "bytes": 0.0}
    plan2 = plan_from_profile(prof, 4, 8)
    assert plan2.modeled_bubble < plan2.uniform_bubble
    assert plan2.counts[0] == 1 and plan2.counts[-1] == 1
    # no static model costs -> actionable failure
    with pytest.raises(PlanError, match="static.model"):
        plan_from_profile(Profile(fingerprint=""), 4, 8)


def test_resolve_plan_fails_fast_on_mismatch(tmp_path):
    """A saved plan for a different pipe axis / model depth dies at
    RESOLUTION with the actionable message — not later, inside the
    model wiring, after sweep time was already burned."""
    import types

    from fluxdistributed_tpu.parallel.pp_plan import resolve_plan

    path = str(tmp_path / "plan8.json")
    plan_stages([1.0] * 16, 8, 8).save(path)
    with pytest.raises(PlanError, match="re-plan for this mesh"):
        resolve_plan(path, 4, 8)
    path2 = str(tmp_path / "plan4.json")
    plan_stages([1.0] * 16, 4, 8).save(path2)
    with pytest.raises(PlanError, match="re-plan for this model"):
        resolve_plan(path2, 4, 8, model=types.SimpleNamespace(depth=12))
    # matching plan resolves fine
    got = resolve_plan(path2, 4, 8, model=types.SimpleNamespace(depth=16))
    assert got.boundaries == plan_stages([1.0] * 16, 4, 8).boundaries


# ---- schedule model (obs.profile) ----

def test_modeled_bubble_reduces_to_closed_forms():
    S, M = 4, 8
    assert modeled_bubble([1.0] * S, M) == pytest.approx(
        (S - 1) / (M + S - 1))
    assert modeled_bubble([1.0] * S, M, schedule="zb") == pytest.approx(
        (S - 1) / (3 * M + S - 1))
    assert modeled_bubble([1.0] * S, M, schedule="zb") < modeled_bubble(
        [1.0] * S, M)
    assert modeled_bubble([], 4) == 0.0
    assert modeled_bubble([0.0, 0.0], 4) == 0.0


def test_stage_costs_from_static_boundaries():
    mc = {"depth": 8, "block": {"flops": 1.0}, "outer": {"flops": 4.0}}
    uni = stage_costs_from_static(mc, 4)
    assert uni == [4.0, 2.0, 2.0, 4.0]
    planned = stage_costs_from_static(mc, 4, boundaries=(0, 1, 4, 7, 8))
    assert planned == [3.0, 3.0, 3.0, 3.0]


def test_bubble_report_groups_tagged_rows():
    """Planned-vs-uniform and 1f1b-vs-zb rows in ONE artifact fit per
    configuration, and each group gets its own schedule model."""
    mc = {"depth": 8, "block": {"flops": 1.0}, "outer": {"flops": 4.0}}
    rows = []
    for sched, a, b in (("1f1b", 4.0, 12.0), ("zb", 5.0, 4.0)):
        for bounds in (None, [0, 1, 4, 7, 8]):
            for M in (4, 8, 16):
                r = {"M": M, "S": 4, "step_ms": a * M + b,
                     "schedule": sched}
                if bounds:
                    r["boundaries"] = bounds
                rows.append(r)
    prof = Profile(fingerprint="", static={"model": mc},
                   measured={"pp_rows": rows})
    rep = bubble_report(prof)
    assert len(rep) == len(rows)
    by_key = {}
    for r in rep:
        by_key.setdefault(
            (r["schedule"], bool(r.get("boundaries")), r["M"]), r)
    # planted linear rows -> the fit recovers each group's own (a, b)
    for r in rep:
        want_a = 4.0 if r["schedule"] == "1f1b" else 5.0
        assert r["fit_ms_per_microbatch"] == pytest.approx(want_a)
    # planned boundaries change the MODELED column within a schedule
    assert (by_key[("1f1b", True, 8)]["modeled_bubble"]
            < by_key[("1f1b", False, 8)]["modeled_bubble"])
    # zb's drain term is a third of 1f1b's at the same stage costs
    assert (by_key[("zb", False, 8)]["modeled_bubble"]
            < by_key[("1f1b", False, 8)]["modeled_bubble"])
    # a one-row configuration cannot be fitted -> actionable error
    prof.measured["pp_rows"] = rows[:3] + [
        {"M": 4, "S": 4, "step_ms": 9.0, "schedule": "solo"}]
    with pytest.raises(ValueError, match="per configuration"):
        bubble_report(prof)


# ---- the dependency oracle, property-tested ----

@pytest.mark.parametrize("seed", range(6))
def test_oracle_grid_randomized(seed):
    """Every timetable the builder emits passes its own oracle (the
    builder calls it) AND satisfies the count/exclusivity invariants,
    over a randomized (S, M, V, schedule) grid."""
    rng = np.random.default_rng(seed)
    for _ in range(6):
        S = int(rng.integers(2, 7))
        M = int(rng.integers(1, 13))
        V = int(rng.integers(1, 4))
        schedule = ("1f1b", "zb")[int(rng.integers(0, 2))]
        sched = build_schedule(S, M, V, schedule=schedule)
        assert (sched.is_fwd.sum(axis=0) == V * M).all()
        assert (sched.is_bwd.sum(axis=0) == V * M).all()
        assert not (sched.is_fwd & sched.is_bwd).any()
        if schedule == "zb":
            assert (sched.is_w.sum(axis=0) == V * M).all()
            assert not (sched.is_w & (sched.is_fwd | sched.is_bwd)).any()
            busy = 3 * V * M
        else:
            assert not sched.is_w.any()
            busy = 2 * V * M
        assert (sched.busy_per_device() == busy).all()
        assert (sched.idle_ticks == sched.ticks - busy).all()
        assert 0.0 < sched.utilization <= 1.0


def _fresh(S, M, V, schedule):
    ring = min(S, M)
    placed = _place(S, M, V, ring, 1, "bfw" if schedule == "zb" else "bfirst",
                    zb=schedule == "zb")
    assert placed is not None
    fdone, bdone, wdone, _t, _mif = placed
    return ring, fdone, bdone, wdone


@pytest.mark.parametrize("schedule", ["1f1b", "zb"])
def test_oracle_fires_on_corrupted_placements(schedule):
    """Feed the oracle deliberately corrupted placements of every
    hazard class — each must raise, naming the violation."""
    S, M, V = 4, 6, 1

    def corrupt(mutate, match):
        ring, fdone, bdone, wdone = _fresh(S, M, V, schedule)
        mutate(fdone, bdone, wdone)
        with pytest.raises(RuntimeError, match=match):
            _verify_placement(S, M, V, ring, 1, fdone, bdone, wdone)

    # activation arriving after its consumer fired
    corrupt(lambda f, b, w: f[1].__setitem__(
        0, [f[2][0][m] + 1 for m in range(M)]), "act order|act latch")
    # backward placed before its own forward
    corrupt(lambda f, b, w: b[2][0].__setitem__(1, f[2][0][1] - 1),
            "before its own forward|cot order|cot latch")
    # ring slot reused while its occupant is still in flight
    def ring_violation(f, b, w):
        retire = w if schedule == "zb" else b
        f[0][0][min(S, M)] = retire[0][0][0] - 1
    corrupt(ring_violation, "ring slot|act")
    if schedule == "zb":
        # weight-grad before its input-grad
        corrupt(lambda f, b, w: w[1][0].__setitem__(2, b[1][0][2] - 1),
                "weight-grad before")
        # cot stash overwritten before its W consumed it
        def stash_violation(f, b, w):
            w[0][0][0] = b[0][0][min(S, M)] + 1
        corrupt(stash_violation, "cot stash|ring slot")


def test_oracle_passes_valid_placements_directly():
    for schedule in ("1f1b", "zb"):
        ring, fdone, bdone, wdone = _fresh(4, 6, 1, schedule)
        _verify_placement(4, 6, 1, ring, 1, fdone, bdone, wdone)


# ---- schedule rendering (per-device idle, zb cells, no truncation) ----

def test_render_idle_counts_and_zb_cells():
    s = build_schedule(4, 8)
    text = s.render()
    assert "idle=6" in text and "S=4 M=8 V=1 T=22" in text
    z = build_schedule(4, 8, schedule="zb")
    zt = z.render()
    assert zt.startswith("ZB schedule:")
    assert "W0" in zt and "idle=" in zt
    # V > 1 interleaved layouts render in FULL by default (no silent
    # truncation), chunk-qualified cells included
    wide = build_schedule(4, 16, 2, schedule="zb")
    full = wide.render()
    assert "more ticks" not in full
    assert "w1:" in full and "f1:" in full
    # explicit truncation still available
    assert "more ticks" in wide.render(max_ticks=10)


def test_zb_fills_the_drain():
    """The point of zb: strictly fewer idle ticks than 1f1b at the same
    shape, with the drain dominated by W work, not waiting."""
    for S, M in ((4, 8), (8, 8), (4, 16)):
        zb = build_schedule(S, M, schedule="zb")
        base = build_schedule(S, M)
        assert int(zb.idle_ticks.max()) < int(base.idle_ticks.max()), (S, M)
        assert zb.utilization > base.utilization
        # the final ticks of device 0 are W work in zb (the drain is
        # filled), where 1f1b leaves them idle
        last_rows = zb.is_w[-3:, :]
        assert last_rows.any()
