"""Request-scoped tracing for the serve stack: one Perfetto track per
request, lifecycle events in a bounded ring.

The scheduler's aggregate metrics say how the FLEET is doing; routing
and tail-latency work need per-REQUEST truth — where did *this*
request's 900 ms go: queue wait, chunked prefill behind someone else's
long prompt, or slow decode ticks?  :class:`RequestTracer` is the data
layer for that question:

* every request gets a **trace id** (the client's ``X-Request-Id``
  header when given, the scheduler's request id otherwise) that rides
  HTTP → :class:`~..serve.scheduler.Scheduler` → the engine's prefill
  state, so every event along the way lands on the same timeline row;
* the scheduler emits **lifecycle events** — enqueue, queue_wait,
  prefill / prefill_chunk k, first_token, per-token decode ticks,
  finish / cancel / drain — into a bounded ring (a days-long server
  must not grow host memory without bound);
* :meth:`RequestTracer.export_chrome_trace` renders the ring as
  Chrome/Perfetto trace-event JSON where **each request is its own
  track** (``pid`` = the serve process row, ``tid`` = a per-request
  lane named by metadata events), so ui.perfetto.dev shows request
  timelines stacked the way a waterfall view should read.

Clocking: events are stamped with the SAME ``time.monotonic`` clock the
scheduler's ``submitted_at`` / ``first_token_at`` fields use, so spans
can be emitted retroactively from those fields without skew.

Overhead: one dict append per event under a short lock; per-token
events only exist while a tracer is attached (the default scheduler has
none), and even then the deque is bounded.
"""

from __future__ import annotations

import json
import threading
import time
from collections import deque
from typing import List, Optional

__all__ = ["RequestTracer"]

#: pid of the request-track rows in the exported trace (one synthetic
#: "process" that holds one thread-lane per request)
_TRACE_PID = 1


class RequestTracer:
    """Bounded ring of per-request lifecycle events.

    Parameters
    ----------
    max_events: ring capacity — oldest events drop first (the count of
        dropped events is exported in the trace metadata, so a
        truncated timeline says so)
    max_lanes: cap on remembered ``trace id → lane`` entries — a
        days-long server sees millions of request ids, and the lane map
        must not outgrow the bounded event ring it annotates.  Eviction
        is least-recently-USED (every event refreshes its lane), so the
        constantly-active scheduler lane and long-running streams keep
        their track; an evicted lane's ring events keep their tid
        number, only the pretty track name is lost.  Evictions are
        counted in the trace metadata.
    """

    def __init__(self, max_events: int = 100_000, max_lanes: int = 4096):
        self._events: deque = deque(maxlen=max_events)
        self._lock = threading.Lock()
        self._tids: dict = {}  # trace id -> stable integer lane
        self._next_tid = 0  # monotonic: an evicted lane's tid never reuses
        self.max_lanes = max(int(max_lanes), 1)
        self._origin = time.monotonic()
        self._origin_unix = time.time()
        self.dropped = 0
        self.lanes_evicted = 0

    # -- producer side (scheduler / server threads) --------------------
    def _push(self, rid, ev: dict) -> None:
        """Assign the lane and append under ONE lock round-trip — this
        runs per decode token when a tracer is attached."""
        with self._lock:
            ev["tid"] = self._lane_locked(rid)
            if len(self._events) == self._events.maxlen:
                self.dropped += 1
            self._events.append(ev)

    def _us(self, t: Optional[float]) -> float:
        return ((t if t is not None else time.monotonic())
                - self._origin) * 1e6

    def event(self, rid, name: str, ts: Optional[float] = None,
              **args) -> None:
        """One instant event on ``rid``'s track (``ts`` in the
        scheduler's ``time.monotonic`` clock; default now)."""
        ev = {"name": name, "ph": "i", "s": "t", "ts": self._us(ts),
              "pid": _TRACE_PID, "cat": "fdtpu.request"}
        if args:
            ev["args"] = args
        self._push(rid, ev)

    def span(self, rid, name: str, t0: float, t1: float, **args) -> None:
        """One complete event (begin + duration) on ``rid``'s track —
        emitted retroactively from recorded monotonic timestamps."""
        ev = {"name": name, "ph": "X", "ts": self._us(t0),
              "dur": max(t1 - t0, 0.0) * 1e6,
              "pid": _TRACE_PID, "cat": "fdtpu.request"}
        if args:
            ev["args"] = args
        self._push(rid, ev)

    def _lane_locked(self, rid) -> int:
        tid = self._tids.pop(rid, None)
        if tid is None:
            if len(self._tids) >= self.max_lanes:
                # LRU eviction: every event re-inserts its lane at the
                # end, so next(iter(...)) is the least-recently-used —
                # the hot scheduler lane and long streams never lose
                # their track to a flood of one-shot request ids
                self._tids.pop(next(iter(self._tids)))
                self.lanes_evicted += 1
            self._next_tid += 1
            tid = self._next_tid
        self._tids[rid] = tid  # (re-)insert at the recency end
        return tid

    # -- consumer side -------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._events)

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._tids.clear()
            self.dropped = 0
            self.lanes_evicted = 0

    def trace_events(self) -> List[dict]:
        """The trace-event list: per-request track-naming metadata
        (``thread_name`` per lane, a ``process_name`` for the group)
        followed by the ring's events."""
        with self._lock:
            events = list(self._events)
            lanes = dict(self._tids)
        meta = [{
            "name": "process_name", "ph": "M", "pid": _TRACE_PID, "tid": 0,
            "args": {"name": "fdtpu.serve requests"},
        }]
        for rid, tid in lanes.items():
            # the scheduler's own lane (decode ticks, drain marks) keeps
            # its bare name; everything else is a request track
            label = rid if rid == "scheduler" else f"request {rid}"
            meta.append({
                "name": "thread_name", "ph": "M", "pid": _TRACE_PID,
                "tid": tid, "args": {"name": label},
            })
            meta.append({
                # lanes sort by arrival, not by hash of the name
                "name": "thread_sort_index", "ph": "M", "pid": _TRACE_PID,
                "tid": tid, "args": {"sort_index": tid},
            })
        return meta + events

    def trace_document(self) -> dict:
        """The full Chrome trace JSON object (what ``GET /trace``
        serves and :meth:`export_chrome_trace` writes)."""
        return {
            "traceEvents": self.trace_events(),
            "displayTimeUnit": "ms",
            "otherData": {
                "origin_unix_time": self._origin_unix,
                "dropped_events": self.dropped,
                "evicted_lanes": self.lanes_evicted,
                "producer": "fluxdistributed_tpu.obs.reqtrace",
            },
        }

    def export_chrome_trace(self, path: str) -> int:
        """Write the buffer as Chrome/Perfetto trace-event JSON; returns
        the number of (non-metadata) events written."""
        n = len(self)
        with open(path, "w") as f:
            json.dump(self.trace_document(), f)
        return n
