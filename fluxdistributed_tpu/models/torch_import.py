"""Import torchvision-layout ResNet weights into the framework's models.

The reference ships pretrained-weight helpers (``getweights``/``weights``,
src/preprocess.jl:9-24) that fetch Metalhead BSON weights, and its demo
loads a trained model for inference (bin/pluto.jl:124).  The TPU-native
analog: map a **torchvision-format ResNet state_dict** (the de-facto
public weight layout for ResNets — `conv1.weight`, `layer{1-4}.{i}.*`,
`fc.*`) onto this framework's flax parameter / batch-stats trees, so
``bin/infer.py`` can serve real predictions and the model definitions are
numerically validated against a known-good implementation
(tests/test_torch_import.py pins logit parity).

No torch dependency at import time: a state_dict is just a mapping of
names to arrays — anything array-like (torch tensors, numpy arrays) is
accepted.  Load .pt/.pth files with ``load_torch_file`` (requires torch).
"""

from __future__ import annotations

from typing import Any, Mapping, Optional

import numpy as np

__all__ = [
    "import_torch_resnet",
    "import_torch_vit",
    "import_torch_convnext",
    "import_gpt2",
    "load_torch_file",
]

# stage_sizes per depth, matching models/resnet.py factories
_STAGES = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
           101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
_BOTTLENECK = {50, 101, 152}


def _np(x) -> np.ndarray:
    """torch.Tensor | np.ndarray -> float32 numpy (no torch import)."""
    if hasattr(x, "detach"):
        x = x.detach().cpu().numpy()
    return np.asarray(x, np.float32)


def _conv(sd: Mapping, name: str) -> np.ndarray:
    # torch conv weight OIHW -> flax HWIO
    return _np(sd[f"{name}.weight"]).transpose(2, 3, 1, 0)


def _bn(sd: Mapping, name: str):
    params = {"scale": _np(sd[f"{name}.weight"]), "bias": _np(sd[f"{name}.bias"])}
    stats = {"mean": _np(sd[f"{name}.running_mean"]),
             "var": _np(sd[f"{name}.running_var"])}
    return params, stats


def _ln(sd: Mapping, name: str) -> dict:
    return {"scale": _np(sd[f"{name}.weight"]), "bias": _np(sd[f"{name}.bias"])}


def _linear(sd: Mapping, name: str) -> dict:
    return {"kernel": _np(sd[f"{name}.weight"]).T, "bias": _np(sd[f"{name}.bias"])}


def import_torch_resnet(
    state_dict: Mapping[str, Any], depth: int = 50, space_to_depth: bool = False
) -> tuple[dict, dict]:
    """Convert a torchvision-layout ResNet ``state_dict`` to
    ``(params, model_state)`` for ``models.resnet{depth}``.

    ``space_to_depth=True`` re-lays the 7x7 stem kernel into the exact
    4x4 equivalent (``resnet.s2d_stem_kernel``) for a model built with
    ``space_to_depth=True`` — pretrained weights keep working on the
    MXU-shaped stem.

    Returns trees ready for
    ``model.apply({"params": params, **model_state}, x, train=False)``.
    """
    if depth not in _STAGES:
        raise ValueError(f"unsupported depth {depth}; have {sorted(_STAGES)}")
    stages = _STAGES[depth]
    bottleneck = depth in _BOTTLENECK
    block_name = "BottleneckBlock" if bottleneck else "BasicBlock"
    nconvs = 3 if bottleneck else 2

    params: dict = {}
    stats: dict = {}

    stem = _conv(state_dict, "conv1")
    if space_to_depth:
        from .resnet import s2d_stem_kernel

        stem = s2d_stem_kernel(stem)
    params["stem_conv"] = {"kernel": stem}
    params["stem_bn"], stats["stem_bn"] = _bn(state_dict, "bn1")

    k = 0  # flat block index, matching the compact-module naming order
    for li, nblocks in enumerate(stages):
        for bi in range(nblocks):
            t = f"layer{li + 1}.{bi}"
            f = f"{block_name}_{k}"
            bp: dict = {}
            bs: dict = {}
            for ci in range(nconvs):
                bp[f"Conv_{ci}"] = {"kernel": _conv(state_dict, f"{t}.conv{ci + 1}")}
                bnp, bns = _bn(state_dict, f"{t}.bn{ci + 1}")
                bp[f"BatchNorm_{ci}"] = bnp
                bs[f"BatchNorm_{ci}"] = bns
            if f"{t}.downsample.0.weight" in state_dict:
                bp["downsample_conv"] = {"kernel": _conv(state_dict, f"{t}.downsample.0")}
                bp["downsample_bn"], bs["downsample_bn"] = _bn(
                    state_dict, f"{t}.downsample.1"
                )
            params[f] = bp
            stats[f] = bs
            k += 1

    params["Dense_0"] = {
        "kernel": _np(state_dict["fc.weight"]).T,
        "bias": _np(state_dict["fc.bias"]),
    }
    return params, {"batch_stats": stats}


def import_torch_vit(
    state_dict: Mapping[str, Any], num_heads: int
) -> tuple[dict, dict]:
    """Convert a torchvision-layout ``VisionTransformer`` state_dict
    (`conv_proj`, `class_token`, `encoder.layers.encoder_layer_{i}`,
    `heads.head`) to ``(params, model_state)`` for a ``ViT`` built with
    ``use_class_token=True, gelu_exact=True`` (the torchvision form; the
    framework default stays mean-pool + tanh GELU for SP shardability).

    ``model_state`` is ``{}`` — ViT has no mutable collections.
    """
    d = _np(state_dict["class_token"]).shape[-1]
    if d % num_heads:
        raise ValueError(f"embed dim {d} not divisible by num_heads {num_heads}")
    hd = d // num_heads

    params: dict = {
        "patch_embed": {
            "kernel": _conv(state_dict, "conv_proj"),
            "bias": _np(state_dict["conv_proj.bias"]),
        },
        "cls_token": _np(state_dict["class_token"]),
        "pos_embed": _np(state_dict["encoder.pos_embedding"]),
        "final_norm": _ln(state_dict, "encoder.ln"),
        "head": _linear(state_dict, "heads.head"),
    }

    i = 0
    while f"encoder.layers.encoder_layer_{i}.ln_1.weight" in state_dict:
        t = f"encoder.layers.encoder_layer_{i}"
        # torch in_proj packs [q; k; v] rows of an (3D, D) weight applied
        # as x @ W.T -> transpose to (D, 3D) then split into (D, 3, H, Dh)
        w_in = _np(state_dict[f"{t}.self_attention.in_proj_weight"]).T
        b_in = _np(state_dict[f"{t}.self_attention.in_proj_bias"])
        w_out = _np(state_dict[f"{t}.self_attention.out_proj.weight"]).T
        # mlp keys: torchvision >=0.13 exports mlp.0/mlp.3 (Sequential);
        # the published .pth checkpoint FILES carry the pre-0.13
        # mlp.linear_1/linear_2 names (torchvision renames them in a
        # load_state_dict pre-hook) — accept both
        mlp1, mlp2 = f"{t}.mlp.0", f"{t}.mlp.3"
        if f"{t}.mlp.linear_1.weight" in state_dict:
            mlp1, mlp2 = f"{t}.mlp.linear_1", f"{t}.mlp.linear_2"
        params[f"block{i}"] = {
            "LayerNorm_0": _ln(state_dict, f"{t}.ln_1"),
            "MultiHeadAttention_0": {
                "qkv": {
                    "kernel": w_in.reshape(d, 3, num_heads, hd),
                    "bias": b_in.reshape(3, num_heads, hd),
                },
                "out": {
                    "kernel": w_out.reshape(num_heads, hd, d),
                    "bias": _np(state_dict[f"{t}.self_attention.out_proj.bias"]),
                },
            },
            "LayerNorm_1": _ln(state_dict, f"{t}.ln_2"),
            "MlpBlock_0": {
                "Dense_0": _linear(state_dict, mlp1),
                "Dense_1": _linear(state_dict, mlp2),
            },
        }
        i += 1
    if i == 0:
        raise ValueError("no encoder layers found — not a torchvision ViT state_dict")
    return params, {}


def gpt2_config(state_dict: Mapping[str, Any]) -> dict:
    """Infer a GPT-2 checkpoint's architecture from its weights alone:
    ``{vocab, dim, depth, mlp_dim, n_positions}`` (head count is NOT in
    the state_dict — the GPT-2 family convention is ``dim // 64``).
    Single source of the key-layout knowledge shared with
    :func:`import_gpt2` and ``bin/generate.py --gpt2-weights``."""
    pre = "transformer." if "transformer.wte.weight" in state_dict else ""
    if f"{pre}wte.weight" not in state_dict:
        raise ValueError("not a GPT-2 state_dict (no wte.weight)")
    vocab, d = _np(state_dict[f"{pre}wte.weight"]).shape
    depth = 0
    while f"{pre}h.{depth}.ln_1.weight" in state_dict:
        depth += 1
    if depth == 0:
        raise ValueError("no transformer blocks found — not a GPT-2 state_dict")
    return {
        "vocab": int(vocab),
        "dim": int(d),
        "depth": depth,
        "mlp_dim": int(_np(state_dict[f"{pre}h.0.mlp.c_fc.weight"]).shape[1]),
        "n_positions": int(_np(state_dict[f"{pre}wpe.weight"]).shape[0]),
    }


def import_gpt2(
    state_dict: Mapping[str, Any], num_heads: int, seqlen: Optional[int] = None
) -> tuple[dict, dict]:
    """Convert a HuggingFace ``GPT2LMHeadModel`` state_dict to
    ``(params, model_state)`` for a :class:`TransformerLM` built with
    ``use_rope=False, tie_embeddings=True, dtype=float32`` and matching
    ``depth/dim/num_heads/mlp_dim`` (GPT-2 is pre-LN, tanh-GELU, tied
    embeddings — exactly the framework LM with learned positions).

    HF ``Conv1D`` stores weights as ``[in, out]`` (already the flax
    orientation — no transpose, unlike ``nn.Linear``); ``c_attn`` packs
    ``[q|k|v]`` along the output dim.  ``seqlen`` slices the positional
    table (``wpe``) to the target context length (default: full table —
    the model must then be applied at exactly that length).

    ``model_state`` is ``{}`` — the LM has no mutable collections.
    """
    # accept both GPT2LMHeadModel ("transformer.h...") and bare
    # GPT2Model ("h...") key layouts
    pre = "transformer." if "transformer.wte.weight" in state_dict else ""
    if f"{pre}wte.weight" not in state_dict:
        raise ValueError("not a GPT-2 state_dict (no wte.weight)")
    wte = _np(state_dict[f"{pre}wte.weight"])
    wpe = _np(state_dict[f"{pre}wpe.weight"])
    d = wte.shape[1]
    if d % num_heads:
        raise ValueError(f"embed dim {d} not divisible by num_heads {num_heads}")
    hd = d // num_heads
    if seqlen is not None:
        if seqlen > wpe.shape[0]:
            raise ValueError(
                f"seqlen {seqlen} exceeds the checkpoint's positional "
                f"table ({wpe.shape[0]})")
        wpe = wpe[:seqlen]

    params: dict = {
        "embed": {"embedding": wte},
        "pos_embedding": wpe,
        "final_ln": _ln(state_dict, f"{pre}ln_f"),
    }
    i = 0
    while f"{pre}h.{i}.ln_1.weight" in state_dict:
        t = f"{pre}h.{i}"
        w_qkv = _np(state_dict[f"{t}.attn.c_attn.weight"])  # [d, 3d]
        b_qkv = _np(state_dict[f"{t}.attn.c_attn.bias"])  # [3d]
        w_out = _np(state_dict[f"{t}.attn.c_proj.weight"])  # [d, d]
        params[f"block{i}"] = {
            "LayerNorm_0": _ln(state_dict, f"{t}.ln_1"),
            "CausalSelfAttention_0": {
                "qkv": {
                    "kernel": w_qkv.reshape(d, 3, num_heads, hd),
                    "bias": b_qkv.reshape(3, num_heads, hd),
                },
                "out": {
                    "kernel": w_out.reshape(num_heads, hd, d),
                    "bias": _np(state_dict[f"{t}.attn.c_proj.bias"]),
                },
            },
            "LayerNorm_1": _ln(state_dict, f"{t}.ln_2"),
            "Dense_0": {
                "kernel": _np(state_dict[f"{t}.mlp.c_fc.weight"]),
                "bias": _np(state_dict[f"{t}.mlp.c_fc.bias"]),
            },
            "Dense_1": {
                "kernel": _np(state_dict[f"{t}.mlp.c_proj.weight"]),
                "bias": _np(state_dict[f"{t}.mlp.c_proj.bias"]),
            },
        }
        i += 1
    if i == 0:
        raise ValueError("no transformer blocks found — not a GPT-2 state_dict")
    return params, {}


def import_torch_convnext(state_dict: Mapping[str, Any]) -> tuple[dict, dict]:
    """Convert an official-layout ConvNeXt state_dict
    (facebookresearch/ConvNeXt, also what timm exports:
    ``downsample_layers.{s}``, ``stages.{s}.{b}.{dwconv,norm,pwconv1,
    pwconv2,gamma}``, ``norm``, ``head``) to ``(params, model_state)``
    for ``models.ConvNeXt``.  ``model_state`` is ``{}``.
    """
    params: dict = {
        # downsample_layers.0 = stem: [conv4x4/4, LN]
        "stem": {
            "kernel": _conv(state_dict, "downsample_layers.0.0"),
            "bias": _np(state_dict["downsample_layers.0.0.bias"]),
        },
        "stem_norm": _ln(state_dict, "downsample_layers.0.1"),
        "head_norm": _ln(state_dict, "norm"),
        "head": _linear(state_dict, "head"),
    }
    # downsample_layers.1..3 = [LN, conv2x2/2]
    s = 1
    while f"downsample_layers.{s}.1.weight" in state_dict:
        params[f"down{s}"] = {
            "norm": _ln(state_dict, f"downsample_layers.{s}.0"),
            "conv": {
                "kernel": _conv(state_dict, f"downsample_layers.{s}.1"),
                "bias": _np(state_dict[f"downsample_layers.{s}.1.bias"]),
            },
        }
        s += 1

    k = 0  # flat block index across stages, matching the flax naming
    stage = 0
    while f"stages.{stage}.0.dwconv.weight" in state_dict:
        b = 0
        while f"stages.{stage}.{b}.dwconv.weight" in state_dict:
            t = f"stages.{stage}.{b}"
            params[f"block{k}"] = {
                "dwconv": {
                    "kernel": _conv(state_dict, f"{t}.dwconv"),
                    "bias": _np(state_dict[f"{t}.dwconv.bias"]),
                },
                "norm": _ln(state_dict, f"{t}.norm"),
                "pwconv1": _linear(state_dict, f"{t}.pwconv1"),
                "pwconv2": _linear(state_dict, f"{t}.pwconv2"),
                "layer_scale": _np(state_dict[f"{t}.gamma"]),
            }
            k += 1
            b += 1
        stage += 1
    if k == 0:
        raise ValueError("no stages found — not an official-layout ConvNeXt state_dict")
    return params, {}


def load_torch_file(
    path: str,
    depth: int = 50,
    arch: str = "resnet",
    num_heads: int = 12,
) -> tuple[dict, dict]:
    """Load a .pt/.pth checkpoint file and convert (requires torch).

    ``arch``: ``"resnet"`` (uses ``depth``), ``"vit"`` (uses
    ``num_heads``), or ``"convnext"``.
    """
    import torch

    obj = torch.load(path, map_location="cpu", weights_only=True)
    if isinstance(obj, dict) and "state_dict" in obj:
        obj = obj["state_dict"]
    if arch == "resnet":
        return import_torch_resnet(obj, depth=depth)
    if arch == "vit":
        return import_torch_vit(obj, num_heads=num_heads)
    if arch == "convnext":
        return import_torch_convnext(obj)
    raise ValueError(
        f"unknown arch {arch!r}; expected 'resnet', 'vit', or 'convnext'"
    )


def load_torch_weights_for(model_name: str, num_classes: int, path: str):
    """One-call CLI path: build the torch-compatible model for a factory
    name (``resnet50``/``vit_b16``/``convnext_base``/…) and load the
    matching .pt/.pth weights.

    Returns ``(model, variables)`` ready for
    ``model.apply(variables, x, train=False)``.  ViT/ConvNeXt models are
    constructed in their torch-compat form (class-token readout / exact
    GELU) so imported weights are numerically faithful.
    """
    from fluxdistributed_tpu import models as m

    factory = getattr(m, model_name, None)
    if factory is None:
        raise ValueError(f"unknown model {model_name!r}")
    if model_name.startswith("resnet") and model_name[6:].isdigit():
        model = factory(num_classes=num_classes)
        params, mstate = load_torch_file(path, depth=int(model_name[6:]))
    elif model_name.startswith("vit_"):
        model = factory(num_classes=num_classes, use_class_token=True,
                        gelu_exact=True)
        params, mstate = load_torch_file(path, arch="vit",
                                         num_heads=model.num_heads)
    elif model_name.startswith("convnext_"):
        model = factory(num_classes=num_classes, gelu_exact=True)
        params, mstate = load_torch_file(path, arch="convnext")
    else:
        raise ValueError(
            f"--torch-weights supports resnet*/vit_*/convnext_* models, "
            f"got {model_name!r}"
        )
    return model, {"params": params, **mstate}
